"""Benchmark harness: TPU SPMD solve vs the reference's per-rank hot loop.

Prints ONE JSON line on stdout: {"metric", "value", "unit", "vs_baseline"}.
A provisional copy of the line (computed with the pre-validated baseline
constant) is written to stderr and to ``bench_provisional.json`` IMMEDIATELY
after the timed solve, so the perf number survives even if the process dies
before the final emit; stdout stays single-line for the driver's parser.

Metric: sustained PCG iteration throughput (dof-iterations / second) of the
full jitted solve on the available accelerator, measured on a converged
quasi-static step with compile excluded (the solve is re-run from a zeroed
state after a warm-up solve).

Baseline: the REAL 8-rank mpi4py reference cannot run in this image —
mpi4py, OpenMPI and mgmetis are absent and installs are unavailable
(verified: ``import mpi4py`` and ``mpiexec`` both missing).  The stand-in is
measured, not guessed: ``NumpyRefSolver`` re-implements the reference's
per-rank hot loop (type-grouped gather -> Ke@(ck*u) -> bincount scatter,
pcg_solver.py:277-300) in plain numpy; its per-(dof*iteration) cost is
measured on this host (on a capped-size model when the bench model is huge;
small models have BETTER cache behavior, so scaling per-dof favors the
baseline) and divided by 8 for idealized perfect 8-rank scaling — also
favoring the baseline, since the real 8-rank demo spent 1.0 of 12.6 s in
comm-wait (BASELINE.md, notebook cell 12).

The stand-in is VALIDATED against the reference's own code: the full
reference pipeline runs under tools/mpi_shim (tools/run_reference_baseline.py).
Measured 2026-07-30 on this host at 823,875 dofs: reference 232.8
ns/dof-iter vs NumpyRefSolver 235.2 ns/dof-iter (within 1%), with EXACT
PCG iteration parity between the reference and this framework on the same
MDF model (see docs/BENCH_LOG.md and tests/test_reference_parity.py).

Default model: 150^3 cells ~= 10.3M dofs — the BASELINE.json north-star
scale ("=>20x vs 8-rank mpi4py at 10M dofs").

Resilience posture (the round's BENCH artifact is captured by an external
driver exactly once, in whatever infrastructure weather prevails; the
r03 post-mortem — probe retries consumed the driver's whole ~1800 s
window and rc=124 landed with NOTHING on stdout — sets the design rule:
*fallback first, upgrade second*):

- a small, clearly-labeled CPU PROVISIONAL solve is launched in a
  subprocess IMMEDIATELY at startup (cube 24^3, validated-constant
  baseline — minutes, not tens of minutes), concurrently with the probe,
  so a printable line exists early no matter what the tunnel does;
- a deadline WATCHDOG daemon thread guarantees stdout gets exactly one
  JSON line before BENCH_WALL_BUDGET_S (default 1680 s, under the
  observed ~1800 s driver timeout) even if the accelerator path hangs in
  an uninterruptible native call — it emits the best line available
  (TPU > CPU-provisional > explicit zero-value error line) and exits;
- the accelerator probe RETRIES with backoff for BENCH_PROBE_BUDGET_S
  (default 600 s — capped well below the driver window) instead of
  giving up after one 3-minute attempt;
- a size LADDER retries the solve at smaller models if the flagship size
  fails to build/compile/converge (cube: BENCH_LADDER nx rungs, default
  "150,128,96"; octree: BENCH_OT_LADDER n0 rungs, default "22,18,12"),
  skipping rungs the remaining wall budget cannot fit;
- the live numpy baseline runs in a crash-isolated SUBPROCESS with a
  timeout; if it fails, the pre-validated constant is used instead.

- when the accelerator is unreachable, the remaining wall budget is NOT
  wasted on the tiny provisional: a mid-size CPU measurement
  (BENCH_UPGRADE_NX^3 cells, default 48 ~= 353k dofs, f64 direct —
  VERDICT r04 weak #1) upgrades the emitted line when it completes in
  budget (disable: BENCH_CPU_UPGRADE=0);
- every successful live accelerator line is recorded in
  ``bench_salvage.json``; a later invocation that finds the tunnel dead
  re-emits the best fresh one (<= BENCH_SALVAGE_MAX_AGE_S, default 12 h)
  clearly re-labeled as salvaged-from-an-earlier-session — a TPU number
  measured earlier in the round (e.g. by the tools/hw_session queue)
  beats any CPU fallback as the round artifact (disable reading:
  BENCH_SALVAGE=0; the hardware queues do, so a dead-tunnel wave step
  cannot masquerade as a fresh measurement in the session log).

Env knobs: BENCH_NX/NY/NZ (cells), BENCH_TOL, BENCH_PARTS, BENCH_DTYPE,
BENCH_MODE (mixed|direct), BENCH_BACKEND (auto|structured|general),
BENCH_REF_ITERS, BENCH_REF_MAX_DOFS, BENCH_MODEL (cube|octree),
BENCH_OT_N, BENCH_OT_LEVEL, BENCH_PROBE_BUDGET_S, BENCH_LADDER,
BENCH_OT_LADDER, BENCH_CPU_FALLBACK, BENCH_REF_TIMEOUT_S,
BENCH_WALL_BUDGET_S, BENCH_PROV_NX, BENCH_PROVISIONAL (internal:
marks the fast-fallback subprocess), BENCH_CPU_UPGRADE,
BENCH_UPGRADE_NX/BENCH_UPGRADE_MODE/BENCH_UPGRADE_DTYPE, BENCH_SALVAGE,
BENCH_SALVAGE_MAX_AGE_S, BENCH_NRHS (batched multi-RHS block width: the
timed leg solves an nrhs-wide block of the reference load via
Solver.solve_many and the line carries detail.nrhs +
detail.dof_iter_rhs_per_s — the nrhs ∈ {1, 4, 16} A/B for a hardware
window), BENCH_PLATEAU (mixed-mode inner
plateau-exit window, 0=off), BENCH_PRECOND (jacobi|block3|mg — the
ISSUE-10 preconditioner A/B; detail.precond + detail.time_to_tol_s /
detail.iters make it a time-to-solution comparison),
BENCH_PCG_VARIANT (classic|fused|pipelined PCG loop
formulation — the 3-way ms/iteration A/B knob: classic's 3 serialized
reductions vs fused's single psum vs pipelined's stencil-overlapped
psum; the engaged variant is reported in detail.pcg_variant on EVERY
line, insurance/salvage included, and schema-validated against the
canonical name set — obs/schema.BENCH_PCG_VARIANT_VALUES),
BENCH_FLIGHT (crash-durable flight-recorder JSONL, default
bench_flight.jsonl, 0 = off: fsync-per-event begin/end brackets around
every rung and every solve dispatch, so a tunnel death mid-timed-solve
leaves a parseable artifact — a previous run's artifact is ingested
mechanically at startup, verdict logged, file rotated to .prev; every
line also carries detail.predicted_ms_per_iter / detail.model_ratio,
the obs/perf.py analytic cost model's verdict), BENCH_PROFILE=1
(ISSUE 15: one PROFILED warm rung per leg after the timed solve —
jax.profiler trace captured into BENCH_PROFILE_DIR, default
bench_profile/, parsed back by obs/profview.py; the final line gains
detail.measured_ms_per_iter_matvec + detail.overlap_frac and the
artifact stays on disk for `pcg-tpu prof-report`); plus the solver-level performance knobs
PCG_TPU_MATVEC_FORM / PCG_TPU_PALLAS_V / PCG_TPU_PALLAS_PLANES /
PCG_TPU_HYBRID_BLOCK (docs/RUNBOOK.md knob table) — the engaged form is
reported in detail.matvec_form.
"""

import json
import os
import subprocess
import sys
import threading
import time

# obs/ is import-light by contract (no jax/numpy): safe before the
# accelerator env is configured.
from pcg_mpi_solver_tpu.obs.metrics import MetricsRecorder, StderrSink
from pcg_mpi_solver_tpu.obs.schema import BENCH_SCHEMA

# docs/BENCH_LOG.md 2026-07-30: the reference's OWN hot loop measured at
# 232.8 ns/dof-iter on this host at 823,875 dofs; the NumpyRefSolver
# stand-in at 235.2 (within 1%).  Used for the provisional line and
# whenever the live baseline measurement fails.
VALIDATED_REF_NS_PER_DOF_ITER = 235.2176
_VALIDATED_NOTE = ("pre-validated constant (docs/BENCH_LOG.md: reference's "
                   "own hot loop 232.8 ns/dof-iter at 823,875 dofs; "
                   "stand-in within 1%)")


# The bench's metrics registry: ONE logging path for the harness and the
# Solver it drives (the Solver is constructed with recorder=_REC).  The
# historical "# ..." note bodies are kept; the stderr sink adds the
# [pcg-tpu HH:MM:SS] timestamp prefix every line — the _vlog contract that
# localizes a hung remote dispatch from the driver's captured stderr.
# Phase timings accumulate as spans (emitted as bench_phase events) and
# land in the final line's detail.phases.
_REC = MetricsRecorder(sinks=[StderrSink()])


def _log(msg):
    _REC.note(msg)


def _cpu_only_env():
    """Env for CPU-only subprocesses that must NEVER touch the accelerator
    tunnel: with a wedged tunnel, the PJRT plugin's sitecustomize blocks
    even CPU work at interpreter start (docs/RUNBOOK.md) — so the plugin's
    site dir is dropped from PYTHONPATH, not just overridden by
    JAX_PLATFORMS."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    pp = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
          if p and "axon" not in p]
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in pp:
        pp.insert(0, repo)
    env["PYTHONPATH"] = os.pathsep.join(pp)
    return env


def _probe_with_retry(budget_s=None, probe_timeout_s=180.0):
    """Retry the backend probe with backoff across a wall budget.

    r02 post-mortem: one 180 s probe attempt died on a transiently dead
    tunnel and the whole round's perf artifact was lost.  The driver
    gives the bench far more wall than 3 minutes — spend it.  Also the
    ONE probe-retry policy for tools/hw_session.py (pass budget_s /
    probe_timeout_s explicitly there)."""
    from pcg_mpi_solver_tpu.utils.backend_probe import probe_backend

    # default 10 min: far past the fatal one-shot 180 s of r02, but capped
    # WELL below the observed ~1800 s driver window — r03's 30-min default
    # let the probe eat the entire window and the round artifact died
    # rc=124 with nothing emitted (the provisional-first orchestrator in
    # main() is the other half of that fix)
    budget = (float(os.environ.get("BENCH_PROBE_BUDGET_S", 600))
              if budget_s is None else float(budget_s))
    t0 = time.monotonic()
    attempt = 0
    hard_fails = 0
    while True:
        attempt += 1
        ok, detail = probe_backend(timeout_s=probe_timeout_s)
        if ok:
            if attempt > 1:
                _log(f"# backend probe ok on attempt {attempt} "
                     f"({time.monotonic() - t0:.0f}s in)")
            return True, detail
        elapsed = time.monotonic() - t0
        _log(f"# backend probe attempt {attempt} failed "
             f"({elapsed:.0f}/{budget:.0f}s): {detail}")
        # a timeout or connection error is transient tunnel weather worth
        # waiting out; a missing/broken plugin is deterministic — two
        # strikes and move on to the fallback instead of burning the
        # whole budget on it
        deterministic = any(sig in detail for sig in (
            "ModuleNotFoundError", "ImportError",
            "not in the list of known backends"))
        if deterministic:
            hard_fails += 1
            if hard_fails >= 2:
                return False, detail
        if elapsed >= budget:
            return False, detail
        # short sleeps early (transient relay restarts recover fast),
        # longer later (wedged-session reaping takes minutes)
        time.sleep(min(30.0 + 15.0 * attempt, 120.0))


def _model_cache_key(kind, gen_kwargs):
    """Cache key = the caller's FULL generator kwargs + a hash of the
    model-source files — so neither a generator code change, an edited
    call-site kwarg, nor a changed GENERATOR DEFAULT (callers may pass
    partial kwarg sets) can serve a stale model."""
    import hashlib

    import pcg_mpi_solver_tpu.models as m

    h = hashlib.sha256()
    pkg = os.path.dirname(m.__file__)
    for fn in sorted(os.listdir(pkg)):
        if fn.endswith(".py"):
            with open(os.path.join(pkg, fn), "rb") as f:
                h.update(f.read())
    h.update(repr((kind, sorted(gen_kwargs.items()))).encode())
    return h.hexdigest()[:16]


def cached_model(kind, **gen_kwargs):
    """Build (or load from the on-disk cache) a model.  Octree generation
    costs minutes at flagship scale on the 1-core bench host; caching cuts
    per-hardware-step latency and step-timeout pressure for the bench AND
    the examples/bench_*.py microbenchmarks (same cache, keyed on the full
    kwargs + a models-source hash).  Disable with BENCH_MODEL_CACHE=0."""
    import pickle

    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             os.pardir, ".bench_cache")
    use_cache = os.environ.get("BENCH_MODEL_CACHE", "1") == "1"
    path = os.path.join(
        cache_dir, f"model_{_model_cache_key(kind, gen_kwargs)}.pkl")
    if use_cache:
        # sweep SIGKILL-orphaned .tmp files on the read path too: if cache
        # WRITES keep failing (e.g. disk full — exactly when
        # multi-hundred-MB orphans matter) the write-side sweep never runs
        _sweep_stale_tmps(cache_dir)
    if use_cache and os.path.exists(path):
        try:
            with open(path, "rb") as f:
                model = pickle.load(f)
        except Exception as e:                          # noqa: BLE001
            _log(f"# model cache read failed ({type(e).__name__}); rebuilding")
        else:
            try:
                os.utime(path)                          # LRU touch
            except OSError:
                pass            # best-effort metadata; the load succeeded
            return model

    if kind == "octree":
        from pcg_mpi_solver_tpu.models.octree import make_octree_model

        model = make_octree_model(**gen_kwargs)
    else:
        from pcg_mpi_solver_tpu.models import make_cube_model

        model = make_cube_model(**gen_kwargs)
    if use_cache:
        try:
            from pcg_mpi_solver_tpu.utils.io import write_atomic

            os.makedirs(cache_dir, exist_ok=True)
            # streamed: the flagship pickle is multi-hundred-MB and must
            # not be materialized on top of the live model
            write_atomic(path, lambda f: pickle.dump(
                model, f, protocol=pickle.HIGHEST_PROTOCOL))
            _evict_model_cache(cache_dir, keep=path)
        except Exception as e:                          # noqa: BLE001
            _log(f"# model cache write failed ({type(e).__name__}); continuing")
    return model


def _build_model(kind, nx, ny, nz, ot_n, ot_level):
    if kind == "octree":
        return cached_model(kind, nx0=ot_n, ny0=ot_n, nz0=ot_n,
                            max_level=ot_level, n_incl=6, seed=2,
                            E=30e9, nu=0.2, load="traction",
                            load_value=1e6)
    return cached_model(kind, nx=nx, ny=ny, nz=nz, E=30e9, nu=0.2,
                        load="traction", load_value=1e6,
                        heterogeneous=True)


def _sweep_stale_tmps(cache_dir):
    """Remove SIGKILL-orphaned model_*.tmp files older than an hour (a
    killed writer — run_step timeout — leaves a multi-hundred-MB orphan
    the size cap would never see).  Called from both the cache-read and
    eviction paths; best-effort."""
    try:
        for fn in os.listdir(cache_dir):
            if fn.startswith("model_") and fn.endswith(".tmp"):
                p = os.path.join(cache_dir, fn)
                if time.time() - os.stat(p).st_mtime > 3600:
                    os.remove(p)
    except OSError:
        pass


def _evict_model_cache(cache_dir, keep, cap_bytes=None):
    """LRU-evict model_*.pkl until the cache fits the size cap
    (BENCH_MODEL_CACHE_GB, default 8).  Source-file edits re-key every
    entry, permanently orphaning the old generation — without eviction
    the multi-hundred-MB flagship pickles accumulate unboundedly.
    One eviction protocol repo-wide: cache/partition_cache.evict_lru
    (jax-free, safe to import before the accelerator env is set)."""
    from pcg_mpi_solver_tpu.cache.partition_cache import evict_lru

    if cap_bytes is None:
        cap_bytes = float(os.environ.get("BENCH_MODEL_CACHE_GB", 8)) * 2**30
    _sweep_stale_tmps(cache_dir)
    evict_lru(cache_dir, keep=keep, cap_bytes=cap_bytes,
              suffix=".pkl", prefix="model_")


def measure_ref_ns(kind, n_dof, ref_max_dofs, n_ref_iters,
                   nx, ny, nz, ot_n, ot_level):
    """Measure the numpy reference hot-loop cost; prints ONE line
    ``REF_NS <ns> <note>`` on stdout.  Runs in a subprocess so an OOM or
    hang here cannot take down the bench after its timed solve."""
    from pcg_mpi_solver_tpu.solver.numpy_ref import NumpyRefSolver

    if n_dof <= ref_max_dofs:
        ref_model = _build_model(kind, nx, ny, nz, ot_n, ot_level)
        note = "same model"
    elif kind == "octree":
        ref_model = _build_model(kind, 0, 0, 0, 8, 3)
        note = f"scaled per-dof from a {ref_model.n_dof}-dof octree"
    else:
        rn = max(8, int(round((ref_max_dofs / 3.1) ** (1 / 3))) - 1)
        ref_model = _build_model("cube", rn, rn, rn, 0, 0)
        note = f"scaled per-dof from {ref_model.n_dof} dofs"
    ref_per_iter = NumpyRefSolver(ref_model).time_per_iter(n_iters=n_ref_iters)
    print(f"REF_NS {ref_per_iter / ref_model.n_dof * 1e9:.4f} {note}",
          flush=True)


def _live_baseline(kind, n_dof, nx, ny, nz, ot_n, ot_level, deadline=None):
    """Subprocess-isolated live baseline; (ref_ns, note) or None."""
    ref_max_dofs = int(os.environ.get("BENCH_REF_MAX_DOFS", 800_000))
    n_ref_iters = int(os.environ.get("BENCH_REF_ITERS", 10))
    # the timeout covers model REGENERATION in the subprocess too (crash
    # isolation means the in-memory model cannot be reused), hence roomy —
    # but never past the orchestrator's wall budget
    timeout_s = float(os.environ.get("BENCH_REF_TIMEOUT_S", 900))
    if deadline is not None:
        timeout_s = min(timeout_s, max(30.0, deadline - time.monotonic()
                                       - 60.0))
    code = (
        "from pcg_mpi_solver_tpu.bench import measure_ref_ns\n"
        f"measure_ref_ns({kind!r}, {n_dof}, {ref_max_dofs}, {n_ref_iters}, "
        f"{nx}, {ny}, {nz}, {ot_n}, {ot_level})\n")
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              env=_cpu_only_env(),
                              capture_output=True, text=True,
                              timeout=timeout_s)
    except subprocess.TimeoutExpired:
        _log(f"# live baseline timed out after {timeout_s:.0f}s")
        return None
    for line in (proc.stdout or "").splitlines():
        if line.startswith("REF_NS "):
            _, ns, note = line.split(" ", 2)
            return float(ns), note
    tail = (proc.stderr or "").strip().splitlines()[-4:]
    _log(f"# live baseline failed (rc={proc.returncode}): "
         + " | ".join(tail))
    return None


def _accel_platform():
    """Platform label of device 0 (separate function so tests can fake a
    non-CPU platform without touching the real jax device list)."""
    import jax

    return jax.devices()[0].platform


def _run_config_extra(solver, dtype, mode, pallas_on, n_parts, t_part,
                      platform, setup=None):
    """The run-configuration detail keys shared by the warm-insurance
    line and the final emitted line (one place, so the two cannot
    drift).  ``setup`` carries the warm-path attribution fields
    (setup_s / setup_cache / time_to_first_iter_s — schema-validated,
    obs/schema.py BENCH_DETAIL_NUMERIC)."""
    out = {
        "dtype": dtype,
        "mode": mode,
        "backend": solver.backend,
        # classic-vs-fused A/B field: the engaged PCG loop formulation,
        # so hardware-window lines are directly comparable across
        # BENCH_PCG_VARIANT settings
        "pcg_variant": getattr(
            getattr(getattr(solver, "config", None), "solver", None),
            "pcg_variant", "classic"),
        # jacobi-vs-mg A/B field (BENCH_PRECOND): the engaged
        # preconditioner, so time_to_tol_s / iters read as a
        # time-to-solution A/B across rounds (ROADMAP item 4)
        "precond": getattr(
            getattr(getattr(solver, "config", None), "solver", None),
            "precond", "jacobi"),
        "pallas": bool(pallas_on),
        # ops without a form attribute (general backend) never read the
        # form knob; the stencil ops PIN it at construction
        "matvec_form": getattr(solver.ops, "form", "n/a"),
        "combine": getattr(solver.ops, "combine", "n/a"),
        # batched multi-RHS A/B field: the SolverConfig.nrhs block width
        # this round solved (BENCH_NRHS sets it at cfg build) —
        # schema-validated (obs/schema.BENCH_DETAIL_NUMERIC) and present
        # on the insurance/salvage lines too, so an interrupted window
        # still records which width it was measuring
        "nrhs": int(getattr(getattr(getattr(solver, "config", None),
                                    "solver", None), "nrhs", 1) or 1),
        "n_parts": n_parts,
        "partition_s": round(t_part, 2),
        "platform": platform,
    }
    out.update(setup or {})
    return out


class _FirstDispatchSink:
    """Metrics sink that records the wall-clock END of the first device
    dispatch it sees — the bench's ``time_to_first_iter_s`` anchor (the
    dispatch event is emitted when the span closes, so ``t`` is the
    moment the first jitted program — compile included — returned)."""

    def __init__(self):
        self.t_end = None

    def emit(self, ev):
        if self.t_end is None and ev.get("kind") == "dispatch":
            self.t_end = ev.get("t")

    def close(self):
        pass


def _predict_ms_per_iter(detail):
    """Roofline-predicted ms/iter (obs/perf.py) for a bench line, derived
    from the line's OWN detail fields so it works on every leg — final,
    warm insurance, failed-salvage — without a live solver in hand.
    Returns None (-> null) when the model cannot be built; an UNKNOWN
    variant/precond name still raises (the single-source-table loudness
    contract — a mislabeled line must not get a fabricated prediction)."""
    from pcg_mpi_solver_tpu.obs import perf as _perf

    try:
        shape = _perf.shape_from_detail(detail)
        if shape is None:
            return None
        cm = _perf.cost_model(
            shape,
            str(detail.get("pcg_variant", "classic")),
            str(detail.get("precond", "jacobi")),
            int(detail.get("nrhs", 1) or 1),
            _perf.resolve_profile(str(detail.get("platform", "cpu"))))
        return cm["predicted_ms_per_iter"] or None
    except KeyError:
        raise
    except Exception as e:                              # noqa: BLE001
        _log(f"# cost model unavailable for this line "
             f"({type(e).__name__}: {e}); predicted_ms_per_iter=null")
        return None


def _result_json(model, kind, r1, iters, ref_ns, ref_note, extra):
    dof_iters_per_sec = model.n_dof * iters / r1.wall_s
    # idealized 8-rank reference: perfect 8x scaling of the measured hot loop
    baseline = 8.0 / (ref_ns * 1e-9)
    detail = {
        "n_dof": model.n_dof,
        "model": kind,
        "iters": int(iters),
        "flag": int(r1.flag),
        "relres": float(r1.relres),
        "solve_wall_s": round(r1.wall_s, 4),
        # wall to CONVERGED-at-tol; null when the solve did not converge
        "time_to_tol_s": round(r1.wall_s, 4) if r1.flag == 0 else None,
        "tpu_ms_per_iter": round(r1.wall_s / iters * 1e3, 4),
        "numpy_ref_ns_per_dof_iter": round(ref_ns, 4),
        "baseline_model": (
            "measured numpy re-impl of the reference per-rank hot loop "
            "/ 8 (ideal scaling; real mpi4py+OpenMPI not installable in "
            "this image)"),
        "ref_measured_on": ref_note,
    }
    detail.update(extra)
    # batched-throughput field: dof*iter*rhs/s — equals the primary value
    # at nrhs=1, and shows the batched-matvec amortization at nrhs>1 (the
    # primary metric stays the per-column rate for cross-round
    # comparability).  Emitted on EVERY line (incl. salvage/insurance,
    # which share this function) so the next hardware window can A/B
    # nrhs in one queue entry.
    nrhs = int(detail.get("nrhs", 1) or 1)
    detail["nrhs"] = nrhs
    detail["dof_iter_rhs_per_s"] = round(dof_iters_per_sec * nrhs, 1)
    # Analytic cost-model verdict (ISSUE 12, obs/perf.py): the roofline-
    # predicted ms/iter for THIS line's engaged (variant, precond, nrhs,
    # platform) and measured/predicted — stamped on EVERY leg through
    # this one shared function (final, insurance, failed-salvage), so an
    # interrupted window still records how far off the model was.  Built
    # from the line's own detail fields (a salvage line must be
    # self-describing without a live solver); null when the model cannot
    # be derived — never a fabricated number.
    predicted = _predict_ms_per_iter(detail)
    detail["predicted_ms_per_iter"] = predicted
    detail["model_ratio"] = (
        round(detail["tpu_ms_per_iter"] / predicted, 3)
        if predicted else None)
    detail["phases"] = {k: round(v["total_s"], 3)
                       for k, v in _REC.span_stats().items()}
    return json.dumps({
        "schema": BENCH_SCHEMA,
        "metric": "pcg_dof_iterations_per_second",
        "value": round(dof_iters_per_sec, 1),
        "unit": "dof*iter/s",
        "vs_baseline": round(dof_iters_per_sec / baseline, 3),
        "detail": detail,
    })


def _solve_once(kind, nx, ny, nz, ot_n, ot_level, backend, n_parts, tol,
                mode, dtype, emitter=None):
    """Build the model/solver, warm-solve (compile), timed solve.

    Returns (model, solver, r1, iters, t_part, pallas_on, setup_info)
    where pallas_on reports whether the fused Pallas matvec path stayed
    engaged and setup_info carries the warm-path attribution fields."""
    import jax

    from pcg_mpi_solver_tpu import RunConfig, SolverConfig, TimeHistoryConfig
    from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
    from pcg_mpi_solver_tpu.solver import Solver

    n_dev = len(jax.devices())
    t_gen0 = time.perf_counter()
    with _REC.span("model_gen", emit=True):
        model = _build_model(kind, nx, ny, nz, ot_n, ot_level)
    _log(f"# model: {model.n_elem} elems / {model.n_dof} dofs "
         f"(gen {time.perf_counter()-t_gen0:.1f}s); devices={n_dev} "
         f"parts={n_parts} dtype={dtype} mode={mode} backend={backend}")

    solver_kw = {}
    if "BENCH_PROGRESS" in os.environ:   # override the SolverConfig default
        solver_kw["mixed_progress_window"] = int(os.environ["BENCH_PROGRESS"])
    cfg = RunConfig(
        solver=SolverConfig(tol=tol, max_iter=20000, dtype=dtype,
                            dot_dtype="float64", precision_mode=mode,
                            pallas=os.environ.get("BENCH_PALLAS", "auto"),
                            # classic|fused|pipelined A/B knob for the
                            # hardware windows (fused = one collective/
                            # iteration; pipelined = that collective
                            # overlapped with the stencil).  An unknown
                            # value fails HERE, loudly, at config build
                            # (SolverConfig validates against
                            # config.PCG_VARIANTS) — never as a silent
                            # classic fallback mislabeling a round.
                            pcg_variant=os.environ.get(
                                "BENCH_PCG_VARIANT", "classic"),
                            # batched multi-RHS block width: the timed
                            # leg solves this many load cases at once
                            # (Solver.solve_many)
                            nrhs=int(os.environ.get("BENCH_NRHS", "1")
                                     or 1),
                            # jacobi|block3|mg preconditioner A/B knob
                            # (mg = the ISSUE-10 geometric V-cycle:
                            # time_to_tol_s is the number to read)
                            precond=(os.environ.get("BENCH_PRECOND",
                                                    "jacobi")
                                     or "jacobi"),
                            mixed_plateau_window=int(
                                os.environ.get("BENCH_PLATEAU", 0)),
                            **solver_kw),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]),
    )
    # Warm-path cache (cache/): BENCH_CACHE_DIR routes partitions through
    # the content-addressed on-disk cache and AOT-exports the step — the
    # re-run after a tunnel drop (the r05 failure mode) then pays
    # near-zero setup.  Off by default: the flagship cold number must
    # stay an honest cold number unless the driver asks for warm.
    cfg.cache_dir = os.environ.get("BENCH_CACHE_DIR", "")
    # Preflight gate (validate/): a generator bug (NaN loads, degenerate
    # octree cell) must fail HERE, in milliseconds, not after the
    # minutes-scale partition+compile of a flagship round.  Run it once
    # explicitly so the round log carries the verdict, then mark the
    # config validated so Solver.__init__ does not re-scan the model.
    from pcg_mpi_solver_tpu.validate import run_preflight

    with _REC.span("preflight", emit=True):
        checks = run_preflight(model, cfg, recorder=_REC,
                               context={"kind": "quasi_static"})
    if checks:
        warned = sum(1 for c in checks if c.status == "warn")
        _log(f"# preflight: {len(checks)} checks ok"
             + (f" ({warned} warning(s))" if warned else ""))
        cfg.preflight = "off"       # already validated this model/config
    t_part0 = time.perf_counter()
    # time_to_first_iter_s anchor: solver-construction start -> end of
    # the FIRST device dispatch (compile included), via a one-shot
    # dispatch-event sink.  This is the bench-schema field that makes
    # cold vs warm setup visible end to end, not just per phase.
    fd_sink = _FirstDispatchSink()
    t_fd0 = time.time()                 # dispatch events carry time.time()
    _REC.add_sink(fd_sink)
    try:
        with _REC.span("partition_upload", emit=True):
            s = Solver(model, cfg, mesh=make_mesh(), n_parts=n_parts,
                       backend=backend, recorder=_REC)
        t_part = time.perf_counter() - t_part0
        _log(f"# partition+upload: {t_part:.2f}s (backend={s.backend}, "
             f"dispatch_cap={s._dispatch_cap}, "
             f"pallas={getattr(s.ops, 'use_pallas', False)})")

        # Warm-up: compile + first solve.  If the Pallas kernel fails at
        # bench scale (the init probe only validates lowering, not
        # runtime), fall back to the XLA matvec rather than losing the
        # round's perf number.
        def pallas_fallback(why):
            nonlocal s
            _log(f"# pallas path {why}; retrying with pallas=off")
            cfg.solver.pallas = "off"
            del s   # free the failed solver's buffers before re-upload
            # the rebuilt solver's programs recompile: reset cold/warm
            # keying so the new compiles are booked as cold, not warm
            _REC.reset_dispatch_attribution()
            s = Solver(model, cfg, mesh=make_mesh(), n_parts=n_parts,
                       backend=backend, recorder=_REC)
            return s.step(1.0)

        pallas_on = getattr(s.ops, "use_pallas", False)
        try:
            with _REC.span("warm_solve", emit=True):
                r0 = s.step(1.0)
        except Exception as e:                      # noqa: BLE001
            if not pallas_on:
                raise
            r0 = pallas_fallback(
                f"failed at scale ({type(e).__name__}: {e})")
            pallas_on = False
        else:
            if r0.flag != 0 and pallas_on:
                # a mis-lowered kernel cannot fake convergence (the f64
                # true residual is computed on the XLA path) — a failed
                # solve with pallas on warrants one XLA retry before
                # reporting failure
                r0 = pallas_fallback(f"solve flag={r0.flag}")
                pallas_on = False
    finally:
        # first dispatch seen (or never will be): detach the one-shot
        # sink on EVERY exit path — a leaked sink would latch a LATER
        # ladder rung's first dispatch
        _REC.remove_sink(fd_sink)
    _log(f"# warm solve: flag={r0.flag} iters={r0.iters} "
         f"relres={r0.relres:.3e} wall={r0.wall_s:.2f}s (incl. compile)")
    # Warm-path attribution for the bench line.  A pallas fallback
    # rebuilt the solver, so read setup_s/setup_cache from the solver
    # that SURVIVED; the first-dispatch anchor spans the whole attempt
    # either way.
    setup_info = {
        "setup_s": round(s.setup_s, 3),
        "setup_cache": s.setup_cache,
        "time_to_first_iter_s": (round(fd_sink.t_end - t_fd0, 3)
                                 if fd_sink.t_end is not None else None),
    }
    _log(f"# setup: {setup_info['setup_s']}s "
         f"({setup_info['setup_cache']} partition), first iter at "
         f"{setup_info['time_to_first_iter_s']}s")
    plat = _accel_platform() if emitter is not None else "cpu"
    # ONE run-config detail dict for the insurance line, the
    # failed-timed-solve salvage line, and (via the caller) the final
    # line — three consumers that must not drift in attribution.
    run_extra = _run_config_extra(s, dtype, mode, pallas_on, n_parts,
                                  t_part, plat, setup=setup_info)
    if emitter is not None and r0.flag == 0 and plat != "cpu":
        # Insurance against a device death DURING the timed solve: on
        # 2026-08-01 the tunnel died mid-timed-dispatch 29 SECONDS after
        # a COMPLETED warm solve (flag=0, 3334 iters, 83.3 s at 10.33M
        # dofs) and the round artifact fell back to a CPU provisional.
        # A converged warm solve is a real accelerator measurement —
        # conservative (wall includes compile + start overhead) and
        # labeled as such; the timed line displaces it at equal rank.
        warm_extra = dict(
            run_extra,
            # the warm solve is the SCALAR step: its line must report
            # the measured width (1), never fabricate nrhs-x batched
            # throughput that was never run; the configured sweep width
            # stays visible as nrhs_planned
            nrhs=1,
            nrhs_planned=run_extra.get("nrhs", 1),
            timing="warm (first solve; wall incl. compile/start "
                   "overhead — conservative)",
            baseline_source="validated-constant",
        )
        wline = _result_json(model, kind, r0, max(r0.iters, 1),
                             VALIDATED_REF_NS_PER_DOF_ITER,
                             _VALIDATED_NOTE, warm_extra)
        _log("# warm-solve accelerator line (insurance): " + wline)
        emitter.offer(wline, rank=4)

    # Measured solve from scratch state (compile cached).  A solver
    # exception HERE (the r05 failure mode: the device died mid-timed-
    # dispatch, 29 s after a completed warm solve) must not abort the
    # round silently: the warm solve is a real accelerator measurement,
    # so a salvage line carrying failed=true + the reason is offered at
    # accelerator rank before the exception continues up to the ladder /
    # fallback chain — the round artifact then records both the number
    # and WHY the timed leg is missing.
    s.reset_state()
    nrhs = int(getattr(cfg.solver, "nrhs", 1) or 1)
    try:
        if nrhs > 1:
            # Batched multi-RHS leg (BENCH_NRHS -> SolverConfig.nrhs):
            # solve an nrhs-wide
            # block of the reference load against the SAME warm
            # operator (Solver.solve_many — one lockstep Krylov loop,
            # collective count independent of nrhs).  A warm blocked
            # solve first so the timed one pays no blocked-program
            # compile, mirroring the scalar warm/timed split.
            from pcg_mpi_solver_tpu.solver.driver import StepResult

            fblk = np.repeat(np.asarray(model.F)[:, None], nrhs, axis=1)
            with _REC.span("warm_solve_many", emit=True):
                s.solve_many(fblk)
            with _REC.span("timed_solve", emit=True):
                mres = s.solve_many(fblk)
            # solve_wall_s excludes the per-call host rhs staging
            # (validate + global->local map + upload): the scalar
            # baseline's step() derives fext in-graph from device data,
            # so the blocked A/B number must not absorb PCIe/host cost
            # the classic leg never pays
            r1 = StepResult(flag=int(mres.flags.max(initial=0)),
                            relres=float(mres.relres.max(initial=0.0)),
                            iters=int(mres.iters.max(initial=0)),
                            wall_s=mres.solve_wall_s)
            # per-column resilience attribution (ISSUE 9): a blocked
            # throughput number that silently absorbed recovery
            # restarts or reported a quarantined column as healthy
            # would benchmark a lie — stamp the counts on the line
            run_extra["nrhs_quarantined"] = len(mres.quarantined)
            run_extra["nrhs_recoveries"] = int(mres.recoveries)
            _log(f"# timed blocked solve: nrhs={nrhs} "
                 f"flags={mres.flags.tolist()} "
                 f"iters={mres.iters.tolist()} wall={r1.wall_s:.3f}s "
                 f"(+{mres.wall_s - mres.solve_wall_s:.3f}s rhs staging, "
                 "excluded; quarantined="
                 f"{list(mres.quarantined)} recoveries={mres.recoveries})")
        else:
            with _REC.span("timed_solve", emit=True):
                r1 = s.step(1.0)
    except Exception as e:                              # noqa: BLE001
        _offer_failed_salvage(
            emitter, model, kind, r0, run_extra,
            f"timed solve died: {type(e).__name__}: {e}")
        raise
    iters = max(r1.iters, 1)
    _log(f"# timed solve: flag={r1.flag} iters={iters} "
         f"relres={r1.relres:.3e} wall={r1.wall_s:.3f}s "
         f"-> {r1.wall_s/iters*1e3:.3f} ms/iter")
    # BENCH_PROFILE=1: one profiled warm rung AFTER the timed solve
    # (the timed number is never perturbed); the measured fields ride
    # setup_info into the final line's detail (the earlier insurance/
    # salvage offers predate the capture and stay unstamped — absent,
    # not null, per obs/schema.py)
    setup_info.update(_capture_bench_profile(s, nrhs))
    return model, s, r1, iters, t_part, pallas_on, setup_info


def _capture_bench_profile(solver, nrhs):
    """BENCH_PROFILE=1 (ISSUE 15): capture + parse ONE profiled warm
    solve on the already-warm solver (obs/profview.py), AFTER the timed
    solve so the timed number is never perturbed.  Returns the
    schema-typed detail fields for the final line —
    ``measured_ms_per_iter_matvec`` / ``overlap_frac`` — when the
    capture actually measured them (absent otherwise: a line must never
    carry a measurement that was not taken).  Best-effort end to end: a
    failed capture/parse logs and returns {} — profiling trouble must
    never cost the round its perf number."""
    if os.environ.get("BENCH_PROFILE") != "1":
        return {}
    from pcg_mpi_solver_tpu.obs import profview

    out = {}
    pdir = os.environ.get("BENCH_PROFILE_DIR", "bench_profile")
    try:
        with _REC.span("profile_capture", emit=True):
            cap = profview.capture_solve_profile(
                solver, pdir, nrhs=max(1, int(nrhs or 1)), recorder=_REC)
        rep = profview.profile_report(cap["artifact"])
        profview.emit_prof_report(_REC, rep)
        mv = (rep["phases"].get("matvec") or {}).get("ms_per_iter")
        if mv is not None:
            out["measured_ms_per_iter_matvec"] = mv
        if rep.get("overlap_frac") is not None:
            out["overlap_frac"] = round(rep["overlap_frac"], 6)
        _log(f"# profiled warm rung: artifact={cap['artifact']} "
             f"verdict={rep['verdict']} matvec_ms_per_iter={mv} "
             f"overlap_frac={rep.get('overlap_frac')} "
             "(read back: pcg-tpu prof-report)")
        # Multi-controller capture (p<idx>/ subdirs): fold the fleet
        # skew verdict into the line — skew_frac / straggler_rank are
        # stamped ONLY when the report measured cross-process skew
        # (bench_detail_fields returns {} otherwise, same
        # never-fabricate contract as the fields above)
        import jax

        from pcg_mpi_solver_tpu.obs import fleet

        frep = fleet.fleet_report(pdir)
        fdet = fleet.bench_detail_fields(frep, jax.process_index())
        if fdet:
            fleet.emit_fleet_report(_REC, frep)
            out.update(fdet)
            _log(f"# fleet skew: skew_frac={fdet['skew_frac']} "
                 f"straggler_rank={fdet['straggler_rank']} "
                 f"straggler=p{frep['straggler']} "
                 "(read back: pcg-tpu fleet-report)")
    except Exception as e:                              # noqa: BLE001
        _log(f"# profile capture failed ({type(e).__name__}: {e}); "
             "continuing unprofiled")
    return out


def _offer_failed_salvage(emitter, model, kind, r0, extra, reason):
    """Salvage line for a solver exception mid-measurement: the WARM
    solve's numbers (a completed accelerator measurement) stamped with
    ``failed``/``fail_reason`` so the round continues with an honest
    artifact instead of aborting (round-5 post-mortem: the device death
    mid-timed-solve aborted the timed line entirely).  No-op when there
    is no emitter or no converged warm solve to salvage."""
    if emitter is None or r0 is None or r0.flag != 0:
        return None
    if str(extra.get("platform", "cpu")).startswith("cpu"):
        return None     # only accelerator measurements rank/salvage at 4
    line = _result_json(
        model, kind, r0, max(r0.iters, 1), VALIDATED_REF_NS_PER_DOF_ITER,
        _VALIDATED_NOTE,
        dict(extra, failed=True, fail_reason=reason,
             # the salvaged numbers come from the SCALAR warm solve —
             # report the measured width (1), keep the planned sweep
             # width visible instead of fabricating batched throughput
             nrhs=1, nrhs_planned=extra.get("nrhs", 1),
             timing="warm (timed solve failed; wall incl. compile/start "
                    "overhead — conservative)",
             baseline_source="validated-constant"))
    _log("# timed solve failed; salvage line (failed=true): " + line)
    emitter.offer(line, rank=4)
    return line


def _ladder(kind, cpu_fallback, provisional=False):
    """Rungs of (nx, ny, nz, ot_n, ot_level), flagship first."""
    def ints(s):
        vals = [int(t) for t in (x.strip() for x in s.split(",")) if t]
        if not vals:
            raise ValueError(f"no sizes in ladder spec {s!r}")
        return vals

    if provisional:
        # the fast-fallback line: must land in MINUTES on the 1-core CPU
        # host (48^3 CPU takes ~tens of minutes — too slow for this job)
        n = int(os.environ.get("BENCH_PROV_NX", 24))
        return [(n, n, n, 0, 0)]
    ot_level = int(os.environ.get("BENCH_OT_LEVEL", 4))
    if kind == "octree":
        if cpu_fallback:
            rungs = os.environ.get("BENCH_CPU_OT_N", "6")
        elif "BENCH_OT_N" in os.environ:     # explicit pin wins, like BENCH_NX
            rungs = os.environ["BENCH_OT_N"]
        else:
            # flagship 22^3 base at level 4 ~= 6M dofs (>= the VERDICT's
            # 5M-dof octree scale target; n=20 measured 4.66M)
            rungs = os.environ.get("BENCH_OT_LADDER", "22,18,12")
        return [(0, 0, 0, n, ot_level) for n in ints(rungs)]
    if cpu_fallback:
        n = int(os.environ.get("BENCH_CPU_NX", 48))
        return [(n, n, n, 0, 0)]
    if any(k in os.environ for k in ("BENCH_NX", "BENCH_NY", "BENCH_NZ")):
        n = int(os.environ.get("BENCH_NX", 150))
        return [(n, int(os.environ.get("BENCH_NY", n)),
                 int(os.environ.get("BENCH_NZ", n)), 0, 0)]
    return [(n, n, n, 0, 0)
            for n in ints(os.environ.get("BENCH_LADDER", "150,128,96"))]


class _Emitter:
    """Exactly-once stdout emitter shared by the main flow and the
    deadline watchdog.  ``best`` always holds the most valuable line
    computed so far, so a watchdog firing mid-upgrade still lands a
    real number (r03 lesson: rc=124 with an empty stdout is the one
    unacceptable outcome).  Offers carry a rank (0 = error sentinel,
    1 = tiny CPU provisional, 2 = mid-size CPU fallback upgrade,
    3 = salvaged earlier-session accelerator line, 4 = live accelerator
    measurement) so a late low-value line can never displace a better
    one."""

    def __init__(self, initial_line):
        self._lock = threading.Lock()
        self.done = False
        self.best = initial_line
        self._rank = 0

    @property
    def rank(self):
        with self._lock:
            return self._rank

    def offer(self, line, rank=1):
        """Record a better line for the watchdog to fall back on; kept
        only if at least as valuable as the current best."""
        with self._lock:
            if not self.done and rank >= self._rank:
                self.best = line
                self._rank = rank
        if rank >= 4:
            # persist a LIVE accelerator line the moment it exists: the
            # watchdog's os._exit(0) raced out main's end-of-run
            # _write_salvage on 2026-08-01 (flagship TPU line emitted to
            # stdout, salvage file never written — deadline-45s fired
            # 2 s before the step ended)
            _write_salvage(line)

    def emit(self, line=None):
        """Print line (or the best recorded one) once; False if already
        emitted.  Salvage-worthy lines are persisted as part of the
        emit so NO exit path can print a live accelerator number
        without recording it for later invocations (dedup in
        _write_salvage makes the double write from main's explicit
        call harmless)."""
        # An EXPLICIT line is the main flow's fresh measured-live result:
        # persist it BEFORE the done check, so a watchdog that emitted
        # first (its os._exit raced out main's end-of-run write on
        # 2026-08-01) cannot drop it from the salvage file — the in-file
        # dedup keeps the double write harmless when we also emit below.
        if line is not None:
            _write_salvage(line)
        with self._lock:
            if self.done:
                return False
            self.done = True
            out = line if line is not None else self.best
            rank = self._rank
            print(out, flush=True)
        # a best-recorded line is only persisted at rank 4 (a rank-3
        # re-labeled salvage must not be re-written — see
        # _salvage_worthy, which also rejects it by content)
        if line is None and rank >= 4:
            _write_salvage(out)
        return True


_SALVAGE_PATH = "bench_salvage.json"
_SALVAGE_LOCK = threading.Lock()


def _git_head():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    try:
        out = subprocess.run(["git", "rev-parse", "HEAD"], cwd=repo,
                             capture_output=True, text=True, timeout=10)
        return out.stdout.strip()[:12] or "unknown"
    except Exception:                                   # noqa: BLE001
        return "unknown"


def _salvage_worthy(line):
    """Only real accelerator measurements are worth keeping: a positive
    value whose platform label is not a CPU fallback/provisional, and
    that is not itself a RE-LABELED salvage from an earlier run (else a
    dead-tunnel round would refresh the entry's timestamp every run and
    the max-age guard could never expire it)."""
    try:
        d = json.loads(line)
        det = d.get("detail", {})
        plat = str(det.get("platform", ""))
        return float(d.get("value", 0)) > 0 and bool(plat) \
            and not plat.startswith("cpu") \
            and not det.get("salvaged_from_earlier_session")
    except Exception:                                   # noqa: BLE001
        return False


def _write_salvage(line):
    """Record a live accelerator line for LATER invocations (cwd file):
    if the round-end driver run hits a dead tunnel, a TPU number captured
    earlier in the round (e.g. by a tools/hw_session queue step running
    this same bench) is a far better artifact than any CPU fallback.
    Re-labeled unmistakably on the read side."""
    if not _salvage_worthy(line):
        return
    # offer() (any thread), emit() (watchdog thread) and the main flow
    # may all try to record the same run's line — serialize the whole
    # read-modify-replace and dedup BEFORE the expensive entry build
    # (git rev-parse subprocess)
    with _SALVAGE_LOCK:
        data = {}
        try:
            with open(_SALVAGE_PATH) as f:
                data = json.load(f)
        except (OSError, ValueError):
            pass
        lines = [e for e in data.get("lines", []) if isinstance(e, dict)]
        if any(e.get("line") == line for e in lines):
            return                          # already recorded this run
        entry = {"line": line, "unix_time": time.time(),
                 "measured_at_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                                  time.gmtime()),
                 "git_head": _git_head()}

        # evict AGE-EXPIRED entries first: _read_salvage can never use a
        # line older than BENCH_SALVAGE_MAX_AGE_S, so a stale
        # high-vs_baseline line must not permanently occupy a slot that a
        # fresher (usable) line needs
        max_age = float(os.environ.get("BENCH_SALVAGE_MAX_AGE_S", 43200))
        now = time.time()

        def _fresh(e):
            try:
                return now - float(e["unix_time"]) <= max_age
            except (KeyError, TypeError, ValueError):
                return False        # unreadable timestamp = unusable entry

        lines = [e for e in lines if _fresh(e)]

        # then trim by VALUE, not recency: a fully live wave writes ~3
        # entries per bench step (warm insurance, const-baseline, final
        # line), and dropping the oldest would evict the flagship line
        # the round-end driver exists to re-emit
        def _vsb(e):
            try:
                return float(json.loads(e["line"]).get("vs_baseline", 0.0))
            except Exception:               # noqa: BLE001
                return -1.0

        while len(lines) > 7:
            lines.remove(min(lines, key=_vsb))
        lines.append(entry)
        try:
            from pcg_mpi_solver_tpu.utils.io import write_atomic

            # per-process+thread tmp (write_atomic): the watchdog thread
            # and main — or two bench processes in one cwd — may salvage
            # concurrently
            write_atomic(_SALVAGE_PATH,
                         json.dumps({"lines": lines}, indent=1).encode())
            _log(f"# accelerator line recorded in {_SALVAGE_PATH} "
                 "for salvage by later invocations")
        except OSError as e:
            _log(f"# salvage write failed ({e}); continuing")


def _read_salvage():
    """Best fresh accelerator line from a previous invocation, re-labeled
    so it cannot be mistaken for a live measurement; None if absent,
    stale, or disabled (BENCH_SALVAGE=0 — the hardware queues disable it
    so a dead-tunnel wave step cannot look like a fresh success)."""
    if os.environ.get("BENCH_SALVAGE", "1") != "1":
        return None
    max_age = float(os.environ.get("BENCH_SALVAGE_MAX_AGE_S", 43200))
    try:
        with open(_SALVAGE_PATH) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    # prefer entries matching THIS invocation's configuration (a
    # BENCH_MODE=direct run must not re-emit a mixed-mode line as its
    # anchor); fall back to the best any-config accelerator line — still
    # better round evidence than any CPU fallback, and self-describing
    want = (os.environ.get("BENCH_MODEL", "cube"),
            os.environ.get("BENCH_MODE", "mixed"),
            os.environ.get("BENCH_DTYPE", "float32"))
    best = None
    best_key = (-1, -1.0)       # (config_match, vs_baseline)
    now = time.time()
    for e in data.get("lines", []):
        try:
            age = now - float(e["unix_time"])
            if age > max_age or not _salvage_worthy(e["line"]):
                continue
            d = json.loads(e["line"])
            det = d.get("detail", {})
            match = int((det.get("model"), det.get("mode"),
                         det.get("dtype")) == want)
            key = (match, float(d.get("vs_baseline", 0)))
            if key > best_key:
                best_key = key
                best = (d, e, age)
        except (KeyError, TypeError, ValueError):
            continue
    if best is None:
        return None
    d, e, age = best
    det = d.setdefault("detail", {})
    det["salvaged_from_earlier_session"] = True
    det["salvage_measured_at_utc"] = e.get("measured_at_utc")
    det["salvage_age_s"] = round(age)
    det["salvage_git_head"] = e.get("git_head")
    det["salvage_note"] = (
        "accelerator measurement captured earlier this round by an "
        "invocation of this same bench (see docs/HW_SESSION.log); the "
        "tunnel was unreachable when THIS invocation ran — not measured "
        "live by this process")
    return json.dumps(d)


def _attach_flight():
    """Crash-durable flight recorder around the bench run (obs/flight.py,
    ISSUE 12).  Every Solver dispatch is bracketed by fsync'd begin/end
    records (the Solver shares ``_REC``) and each ladder rung gets its
    own bracket, so a tunnel death / SIGKILL mid-timed-dispatch — the
    round-5 failure a human reconstructed from HW_SESSION.log by hand —
    leaves a parseable artifact naming the in-flight program.

    A LEFTOVER artifact from a previous invocation is ingested
    MECHANICALLY first: its verdict (clean / failed / died + what was in
    flight) is logged, then the file rotates to ``.prev`` so this run's
    verdict cannot inherit the dead run's unclosed brackets.  Disable
    with BENCH_FLIGHT=0 (the provisional/upgrade subprocesses do — they
    share the parent's cwd and must not interleave with its stream)."""
    path = os.environ.get("BENCH_FLIGHT", "bench_flight.jsonl")
    if not path or path == "0":
        return None
    from pcg_mpi_solver_tpu.obs.flight import (
        FlightRecorder, ingest_and_rotate)

    path = ingest_and_rotate(path, _log,
                             label="# previous bench flight record")
    try:
        _REC.flight = FlightRecorder(path, meta={
            "component": "bench",
            "model": os.environ.get("BENCH_MODEL", "cube"),
            "pcg_variant": os.environ.get("BENCH_PCG_VARIANT", "classic"),
            "precond": os.environ.get("BENCH_PRECOND", "jacobi"),
            "nrhs": os.environ.get("BENCH_NRHS", "1")})
    except (OSError, ValueError) as e:
        _log(f"# flight recorder unavailable ({e}); continuing without")
        _REC.flight = None
    return _REC.flight


def _error_line(why):
    """Last-ditch zero-value line: clearly labeled, parseable, and
    impossible to mistake for a measurement."""
    return json.dumps({
        "schema": BENCH_SCHEMA,
        "metric": "pcg_dof_iterations_per_second",
        "value": 0.0,
        "unit": "dof*iter/s",
        "vs_baseline": 0.0,
        "detail": {"error": why,
                   "note": "no solve completed inside the wall budget; "
                           "this is a sentinel, not a measurement"},
    })


class _ProvisionalRun:
    """A CPU fallback solve in a subprocess.  Default configuration is the
    t=0 fast provisional (small cube even for BENCH_MODEL=octree: the
    hybrid octree program's multi-minute CPU compile would defeat the
    purpose); the probe-failure path reuses it with ``provisional=False``
    + env overrides for the mid-size budget-filling upgrade run."""

    def __init__(self, env_extra=None, logname="bench_fallback.log",
                 provisional=True):
        env = _cpu_only_env()
        env["BENCH_FORCE_CPU"] = "1"
        env["BENCH_MODEL"] = "cube"
        # the fallback subprocess shares the parent's cwd: its flight
        # records must not interleave with (or rotate) the parent's —
        # neither through the bench recorder nor through a Solver
        # picking up the operator's PCG_TPU_FLIGHT default
        env["BENCH_FLIGHT"] = "0"
        env["PCG_TPU_FLIGHT"] = ""
        if provisional:
            env["BENCH_PROVISIONAL"] = "1"
        else:
            env.pop("BENCH_PROVISIONAL", None)
        env.update(env_extra or {})
        self._line = None
        self._got = threading.Event()
        try:
            logf = open(logname, "w")
        except OSError:
            logf = subprocess.DEVNULL
        try:
            self._proc = subprocess.Popen(
                [sys.executable, "-m", "pcg_mpi_solver_tpu.bench"],
                env=env, stdout=subprocess.PIPE, stderr=logf, text=True)
        except OSError as e:
            _log(f"# provisional launch failed ({e}); no fast fallback")
            self._proc = None
            self._got.set()
            return
        finally:
            if logf is not subprocess.DEVNULL:
                logf.close()    # the child holds its own descriptor
        threading.Thread(target=self._reader, daemon=True).start()

    def _reader(self):
        out, _ = self._proc.communicate()
        for ln in (out or "").splitlines():
            ln = ln.strip()
            if ln.startswith("{"):
                self._line = ln
        if self._line is None:
            _log(f"# provisional subprocess produced no line "
                 f"(rc={self._proc.returncode}; see bench_fallback.log)")
        self._got.set()

    def line(self, timeout_s=0.0):
        self._got.wait(timeout=timeout_s)
        return self._line

    def kill(self):
        if self._proc is not None and self._proc.poll() is None:
            self._proc.kill()


def _fallback_chain(emitter, prov, deadline, why):
    """Accelerator-less endgame (probe exhausted or bench crashed): offer
    every fallback in value order, then emit the best available line.

    1. the t=0 tiny CPU provisional (rank 1 — liveness floor);
    2. a mid-size CPU measurement filling the remaining wall budget
       (rank 2 — VERDICT r04 weak #1: the 46,875-dof provisional left
       ~1,000 s of budget unspent; a >=350k-dof f64 line is evidence,
       not just liveness);
    3. a salvaged accelerator line from an earlier invocation this round
       (rank 3 — outranks any CPU number and skips the upgrade burn).

    Rank 4 (live accelerator) may already sit in the emitter if the crash
    happened after a timed solve; nothing here can displace it."""
    ln = prov.line(timeout_s=max(5.0, min(
        300.0, deadline - time.monotonic() - 60.0)))
    if ln is not None:
        emitter.offer(ln, rank=1)
    salv = _read_salvage()
    if salv is not None:
        _log("# salvaging the accelerator line measured earlier this "
             "round (re-labeled in detail.salvage_note)")
        emitter.offer(salv, rank=3)
    elif (os.environ.get("BENCH_CPU_UPGRADE", "1") == "1"
          and emitter.rank < 2):
        left = deadline - time.monotonic() - 120.0
        if left >= 240.0:
            _log(f"# upgrading the CPU fallback with the remaining wall "
                 f"budget ({left:.0f}s, "
                 f"{os.environ.get('BENCH_UPGRADE_NX', '48')}^3 "
                 f"{os.environ.get('BENCH_UPGRADE_DTYPE', 'float64')} "
                 f"{os.environ.get('BENCH_UPGRADE_MODE', 'direct')})")
            up = _ProvisionalRun(
                env_extra={
                    "BENCH_MODE": os.environ.get("BENCH_UPGRADE_MODE",
                                                 "direct"),
                    "BENCH_DTYPE": os.environ.get("BENCH_UPGRADE_DTYPE",
                                                  "float64"),
                    "BENCH_CPU_NX": os.environ.get("BENCH_UPGRADE_NX",
                                                   "48"),
                },
                logname="bench_upgrade.log", provisional=False)
            ln2 = up.line(timeout_s=left)
            up.kill()
            if ln2 is not None:
                emitter.offer(ln2, rank=2)
            else:
                _log("# CPU upgrade produced no line in budget "
                     "(see bench_upgrade.log); keeping the provisional")
    if emitter.rank == 0:
        emitter.emit(_error_line(why))
    else:
        emitter.emit()


def main():
    t0 = time.monotonic()
    if os.environ.get("BENCH_SETUP_LADDER"):
        # ISSUE 14: the weak-scaling SETUP ladder leg — CPU-only by
        # design (it measures partition build / ingest / warm-cache
        # walls across jax.distributed process counts, never the
        # accelerator), so it runs before any probe/orchestration
        from pcg_mpi_solver_tpu.setup_ladder import main as ladder_main

        sys.exit(ladder_main())
    if os.environ.get("BENCH_SERVE"):
        # ISSUE 19: sustained solve-service throughput — saturated
        # queue with nrhs packing vs one-at-a-time dispatch, in one
        # process (no orchestration; the leg times dispatch, not the
        # probe ladder)
        from pcg_mpi_solver_tpu.serve.bench import main as serve_main

        sys.exit(serve_main())
    # a stale provisional file from a previous crashed run must not be
    # salvageable as THIS run's number
    try:
        os.remove("bench_provisional.json")
    except OSError:
        pass
    provisional = os.environ.get("BENCH_PROVISIONAL") == "1"
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        # subprocess / debug mode: run the bench body directly on CPU and
        # print its one line (no orchestration — the parent handles that)
        os.environ["JAX_PLATFORMS"] = "cpu"   # must hold before import jax
        print(_run_bench(cpu_fallback=True, provisional=provisional),
              flush=True)
        return

    # --- top-level orchestrator: fallback first, upgrade second ---
    wall = float(os.environ.get("BENCH_WALL_BUDGET_S", 1680))
    deadline = t0 + wall
    emitter = _Emitter(_error_line("bench still starting up"))
    _attach_flight()
    prov = _ProvisionalRun()

    def watchdog():
        # fire with enough margin to flush stdout before the driver kills
        while not emitter.done:
            left = deadline - 45.0 - time.monotonic()
            if left <= 0:
                break
            time.sleep(min(left, 5.0))
        if emitter.done:
            return
        ln = prov.line(timeout_s=0.0)
        if ln is not None:
            emitter.offer(ln, rank=1)   # never displaces a TPU line (rank 4)
        try:
            # a hung accelerator path (e.g. a cold remote compile
            # overrunning the budget) must not downgrade the artifact to
            # the provisional while a salvaged TPU line sits on disk
            salv = _read_salvage()
            if salv is not None:
                emitter.offer(salv, rank=3)
        except Exception:                               # noqa: BLE001
            pass                # the watchdog must never die pre-emit
        _log("# WALL BUDGET EXHAUSTED — watchdog emitting best available "
             "line and exiting")
        emitter.emit()
        sys.stdout.flush()
        prov.kill()
        os._exit(0)

    threading.Thread(target=watchdog, daemon=True).start()

    # every exit path below must reap the provisional child: an orphaned
    # 24^3 solve would keep burning the 1-core host's only CPU under
    # whatever the external driver runs next
    try:
        probe_budget = min(
            float(os.environ.get("BENCH_PROBE_BUDGET_S", 600)),
            max(0.0, deadline - time.monotonic() - 360.0))
        ok, detail = _probe_with_retry(budget_s=probe_budget)
        if not ok:
            if os.environ.get("BENCH_CPU_FALLBACK", "1") != "1":
                _log(f"# FATAL: {detail}\n# No perf number can be produced "
                     "from this host.")
                sys.exit(3)
            _log(f"# accelerator unreachable after probe budget: {detail}\n"
                 "# falling back (salvage / CPU upgrade / provisional — "
                 "clearly labeled; NOT the TPU north-star number)")
            _fallback_chain(emitter, prov, deadline,
                            f"accelerator unreachable ({detail}) "
                            "and every CPU fallback failed")
            return

        try:
            line = _run_bench(cpu_fallback=False, deadline=deadline,
                              emitter=emitter)
        except SystemExit:
            raise
        except Exception as e:                          # noqa: BLE001
            _log(f"# accelerator bench failed ({type(e).__name__}: {e}); "
                 "falling back (salvage / CPU upgrade / provisional)")
            _fallback_chain(emitter, prov, deadline,
                            f"accelerator bench failed "
                            f"({type(e).__name__}: {e}) and every CPU "
                            "fallback failed")
            return
        emitter.emit(line)
    finally:
        prov.kill()
        fl = getattr(_REC, "flight", None)
        if fl is not None:
            fl.close()


def _run_bench(cpu_fallback, provisional=False, deadline=None, emitter=None):
    """The bench body: probe already done (or CPU pinned).  Returns the
    final JSON line; registers intermediate lines on ``emitter`` so the
    watchdog always has the best available number."""
    import jax

    from pcg_mpi_solver_tpu.utils.backend_probe import (
        pin_cpu_backend_if_requested)

    # honor an explicit CPU request even where a sitecustomize
    # force-registers the accelerator plugin ahead of the env var
    # (docs/RUNBOOK.md) — enables CPU smoke runs of the bench
    pin_cpu_backend_if_requested()

    # Dispatch breadcrumbs on by default: a wedged remote compile/execute
    # must be localizable from the driver's captured stderr.
    os.environ.setdefault("PCG_TPU_VERBOSE", "1")
    # Persistent compilation cache: flagship programs compile in minutes
    # (hybrid octree ~20 min, chipless-measured 2026-07-31) — a retry
    # after a mid-solve tunnel drop must not pay the remote compile
    # again.  jax binds the env var at import time, which has already
    # happened — apply via config.update (authoritative either way).
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cache_dir = os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                                      os.path.join(repo, ".jax_cache"))
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    kind = os.environ.get("BENCH_MODEL", "cube")   # cube | octree
    tol = float(os.environ.get("BENCH_TOL", 1e-7))
    mode = os.environ.get("BENCH_MODE", "mixed")   # mixed | direct
    backend = os.environ.get("BENCH_BACKEND", "auto")
    dtype = os.environ.get("BENCH_DTYPE", "float32")
    n_parts = int(os.environ.get("BENCH_PARTS", len(jax.devices())))

    ladder = _ladder(kind, cpu_fallback, provisional)
    # loop invariant: reaching the emit below implies the LAST iteration
    # assigned all of these (every failure path raises)
    for rung_i, (nx, ny, nz, ot_n, ot_level) in enumerate(ladder):
        last = rung_i == len(ladder) - 1
        rung = ladder[rung_i]
        failed = None
        # flight bracket per rung (on top of the Solver's per-dispatch
        # brackets): a killed run's artifact names which ladder size was
        # in flight, not just which program
        fl = getattr(_REC, "flight", None)
        fl_seq = (fl.begin(f"rung:{rung_i}", nx=nx, ot_n=ot_n)
                  if fl is not None else None)
        try:
            model, solver, r1, iters, t_part, pallas_on, setup_info = \
                _solve_once(
                    kind, nx, ny, nz, ot_n, ot_level, backend, n_parts,
                    tol, mode, dtype, emitter=emitter)
            if fl is not None:
                fl.end(fl_seq, f"rung:{rung_i}", ok=True)
        except Exception as e:                      # noqa: BLE001
            if fl is not None:
                # descending to a smaller rung is the ladder working BY
                # DESIGN — only the last rung's failure fails the run,
                # so only that one may make the artifact read "failed"
                fl.end(fl_seq, f"rung:{rung_i}", ok=False,
                       error=f"{type(e).__name__}: {e}",
                       expected=not last)
            if last:
                raise
            failed = f"{type(e).__name__}: {e}"
            model = solver = r1 = None
        # a non-converged timed solve is also a failed rung (a smaller
        # model that converges beats a flagship number at flag!=0)
        if failed is None and r1.flag != 0 and not last:
            failed = f"flag={r1.flag} after {iters} iters"
            model = solver = r1 = None
        if failed is None:
            break
        _log(f"# ladder rung {rung_i} failed ({failed}); stepping down")
        if deadline is not None and time.monotonic() > deadline - 240.0:
            raise RuntimeError(
                f"ladder rung {rung_i} failed ({failed}) and the remaining "
                "wall budget cannot fit another rung")
        import gc

        gc.collect()                                # free device buffers

    extra = _run_config_extra(
        solver, dtype, mode, pallas_on, n_parts, t_part, _accel_platform() + (
            " (CPU PROVISIONAL — fast fallback so the round artifact "
            "cannot be empty; not the TPU north-star number)"
            if provisional else
            " (CPU FALLBACK — accelerator unreachable; not the TPU "
            "north-star number)" if cpu_fallback else ""),
        setup=setup_info)
    if provisional:
        extra["provisional"] = True

    # Validated-constant record FIRST (stderr + file, NOT stdout — the
    # driver parses stdout and must see exactly one JSON line): the perf
    # number must survive anything that follows.
    const_line = _result_json(
        model, kind, r1, iters, VALIDATED_REF_NS_PER_DOF_ITER,
        _VALIDATED_NOTE, dict(extra, baseline_source="validated-constant"))
    _log("# provisional (validated-constant baseline): " + const_line)
    if emitter is not None:
        emitter.offer(const_line, rank=4)   # the watchdog's fallback is
        #                                     now a REAL accelerator line
    if not provisional:
        # the fast-fallback SUBPROCESS must not write the crash-insurance
        # file: it shares the parent's cwd, and its tiny CPU line landing
        # late would overwrite the parent's accelerator line (stdout is
        # the subprocess's only channel)
        try:
            with open("bench_provisional.json", "w") as f:
                f.write(const_line + "\n")
        except OSError:
            pass
        # cross-run salvage happens at offer(rank=4) above (self-gated
        # on the platform label, so CPU fallback/upgrade lines never
        # land there)

    if provisional:
        # the fast-fallback subprocess: the validated constant IS the
        # baseline (a live numpy measurement would double its runtime)
        return const_line

    # Live baseline in a crash-isolated subprocess (numpy-only, CPU),
    # bounded by the remaining wall budget.
    if deadline is not None and time.monotonic() > deadline - 90.0:
        _log("# skipping live baseline (wall budget); "
             "returning validated-constant line")
        return const_line
    with _REC.span("live_baseline", emit=True):
        live = _live_baseline(kind, model.n_dof, rung[0], rung[1], rung[2],
                              rung[3], rung[4], deadline=deadline)
    if live is not None:
        ref_ns, ref_note = live
        _log(f"# numpy ref ({ref_note}): {ref_ns:.3f} ns/dof-iter")
        return _result_json(model, kind, r1, iters, ref_ns, ref_note,
                            dict(extra, baseline_source="measured-live"))
    _log("# live baseline unavailable; returning validated-constant line")
    return const_line


if __name__ == "__main__":
    main()
