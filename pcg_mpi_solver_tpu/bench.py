"""Benchmark harness: TPU SPMD solve vs the reference's per-rank hot loop.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: sustained PCG iteration throughput (dof-iterations / second) of the
full jitted solve on the available accelerator, measured on a converged
quasi-static step with compile excluded (the solve is re-run from a zeroed
state after a warm-up solve).

Baseline: the REAL 8-rank mpi4py reference cannot run in this image —
mpi4py, OpenMPI and mgmetis are absent and installs are unavailable
(verified: ``import mpi4py`` and ``mpiexec`` both missing).  The stand-in is
measured, not guessed: ``NumpyRefSolver`` re-implements the reference's
per-rank hot loop (type-grouped gather -> Ke@(ck*u) -> bincount scatter,
pcg_solver.py:277-300) in plain numpy; its per-(dof*iteration) cost is
measured on this host (on a capped-size model when the bench model is huge;
small models have BETTER cache behavior, so scaling per-dof favors the
baseline) and divided by 8 for idealized perfect 8-rank scaling — also
favoring the baseline, since the real 8-rank demo spent 1.0 of 12.6 s in
comm-wait (BASELINE.md, notebook cell 12).

The stand-in is VALIDATED against the reference's own code: the full
reference pipeline runs single-rank under tools/mpi_shim
(tools/run_reference_baseline.py).  Measured 2026-07-30 on this host at
823,875 dofs: reference 232.8 ns/dof-iter vs NumpyRefSolver 235.2
ns/dof-iter (within 1%), with EXACT PCG iteration parity between the
reference and this framework on the same MDF model (see
docs/BENCH_LOG.md and tests/test_reference_parity.py).

Default model: 150^3 cells ~= 10.3M dofs — the BASELINE.json north-star
scale ("=>20x vs 8-rank mpi4py at 10M dofs").

Env knobs: BENCH_NX/NY/NZ (cells), BENCH_TOL, BENCH_PARTS, BENCH_DTYPE,
BENCH_MODE (mixed|direct), BENCH_BACKEND (auto|structured|general),
BENCH_REF_ITERS, BENCH_REF_MAX_DOFS.
"""

import json
import os
import sys
import time

import numpy as np


def main():
    from pcg_mpi_solver_tpu.utils.backend_probe import probe_backend

    ok, detail = probe_backend()
    if not ok:
        print(f"# FATAL: {detail}\n# No perf number can be produced from "
              "this host.", file=sys.stderr, flush=True)
        sys.exit(3)

    import jax

    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        # honor an explicit CPU request even where a sitecustomize
        # force-registers the accelerator plugin ahead of the env var
        # (docs/RUNBOOK.md) — enables CPU smoke runs of the bench
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from pcg_mpi_solver_tpu import RunConfig, SolverConfig, TimeHistoryConfig
    from pcg_mpi_solver_tpu.models import make_cube_model
    from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
    from pcg_mpi_solver_tpu.solver import Solver
    from pcg_mpi_solver_tpu.solver.numpy_ref import NumpyRefSolver

    # Dispatch breadcrumbs on by default: a wedged remote compile/execute
    # must be localizable from the driver's captured stderr.
    os.environ.setdefault("PCG_TPU_VERBOSE", "1")
    kind = os.environ.get("BENCH_MODEL", "cube")   # cube | octree
    nx = int(os.environ.get("BENCH_NX", 150))
    ny = int(os.environ.get("BENCH_NY", 150))
    nz = int(os.environ.get("BENCH_NZ", 150))
    tol = float(os.environ.get("BENCH_TOL", 1e-7))
    mode = os.environ.get("BENCH_MODE", "mixed")   # mixed | direct
    backend = os.environ.get("BENCH_BACKEND", "auto")
    dtype = os.environ.get("BENCH_DTYPE", "float32")
    n_dev = len(jax.devices())
    n_parts = int(os.environ.get("BENCH_PARTS", n_dev))

    def gen_octree(n, level):
        from pcg_mpi_solver_tpu.models.octree import make_octree_model

        return make_octree_model(n, n, n, max_level=level, n_incl=6,
                                 seed=2, E=30e9, nu=0.2,
                                 load="traction", load_value=1e6)

    t_gen0 = time.perf_counter()
    if kind == "octree":
        # graded octree with real transition pattern types: the reference's
        # problem class, solved on the hybrid level-grid backend
        model = gen_octree(int(os.environ.get("BENCH_OT_N", 12)),
                           int(os.environ.get("BENCH_OT_LEVEL", 4)))
    else:
        model = make_cube_model(nx, ny, nz, E=30e9, nu=0.2, load="traction",
                                load_value=1e6, heterogeneous=True)
    print(f"# model: {model.n_elem} elems / {model.n_dof} dofs "
          f"(gen {time.perf_counter()-t_gen0:.1f}s); devices={n_dev} "
          f"parts={n_parts} dtype={dtype} mode={mode} backend={backend}",
          file=sys.stderr, flush=True)

    cfg = RunConfig(
        solver=SolverConfig(tol=tol, max_iter=20000, dtype=dtype,
                            dot_dtype="float64", precision_mode=mode,
                            pallas=os.environ.get("BENCH_PALLAS", "auto")),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]),
    )
    t_part0 = time.perf_counter()
    s = Solver(model, cfg, mesh=make_mesh(), n_parts=n_parts, backend=backend)
    t_part = time.perf_counter() - t_part0
    print(f"# partition+upload: {t_part:.2f}s (backend={s.backend}, "
          f"dispatch_cap={s._dispatch_cap})", file=sys.stderr, flush=True)

    # Warm-up: compile + first solve.  If the Pallas kernel fails at bench
    # scale (the init probe only validates a tiny compile), fall back to
    # the XLA matvec rather than losing the round's perf number.
    def pallas_fallback(why):
        nonlocal s
        print(f"# pallas path {why}; retrying with pallas=off",
              file=sys.stderr, flush=True)
        cfg.solver.pallas = "off"
        del s   # free the failed solver's device buffers before re-upload
        s = Solver(model, cfg, mesh=make_mesh(), n_parts=n_parts,
                   backend=backend)
        return s.step(1.0)

    pallas_on = getattr(s.ops, "use_pallas", False)
    try:
        r0 = s.step(1.0)
    except Exception as e:                          # noqa: BLE001
        if not pallas_on:
            raise
        r0 = pallas_fallback(f"failed at scale ({type(e).__name__}: {e})")
    else:
        if r0.flag != 0 and pallas_on:
            # a mis-lowered kernel cannot fake convergence (the f64 true
            # residual is computed on the XLA path) — a failed solve with
            # pallas on warrants one XLA retry before reporting failure
            r0 = pallas_fallback(f"solve flag={r0.flag}")
    print(f"# warm solve: flag={r0.flag} iters={r0.iters} "
          f"relres={r0.relres:.3e} wall={r0.wall_s:.2f}s (incl. compile)",
          file=sys.stderr, flush=True)

    # Measured solve from scratch state (compile cached).
    s.reset_state()
    r1 = s.step(1.0)
    iters = max(r1.iters, 1)
    tpu_per_iter = r1.wall_s / iters
    print(f"# timed solve: flag={r1.flag} iters={iters} "
          f"relres={r1.relres:.3e} wall={r1.wall_s:.3f}s "
          f"-> {tpu_per_iter*1e3:.3f} ms/iter", file=sys.stderr, flush=True)

    # Baseline: the reference's hot loop in numpy, measured on this host.
    # For huge bench models, measure on a capped-size model and scale
    # per-dof (conservative: small models cache better).
    ref_max_dofs = int(os.environ.get("BENCH_REF_MAX_DOFS", 800_000))
    if model.n_dof <= ref_max_dofs:
        ref_model, ref_note = model, "same model"
    elif kind == "octree":
        ref_model = gen_octree(8, 3)
        ref_note = f"scaled per-dof from a {ref_model.n_dof}-dof octree"
    else:
        rn = max(8, int(round((ref_max_dofs / 3.1) ** (1 / 3))) - 1)
        ref_model = make_cube_model(rn, rn, rn, E=30e9, nu=0.2,
                                    load="traction", load_value=1e6,
                                    heterogeneous=True)
        ref_note = f"scaled per-dof from {ref_model.n_dof} dofs"
    ref = NumpyRefSolver(ref_model)
    n_ref_iters = int(os.environ.get("BENCH_REF_ITERS", 10))
    ref_per_iter = ref.time_per_iter(n_iters=n_ref_iters)
    ref_per_dof_iter = ref_per_iter / ref_model.n_dof
    print(f"# numpy ref ({ref_note}): {ref_per_iter*1e3:.3f} ms/iter "
          f"({ref_per_dof_iter*1e9:.3f} ns/dof-iter)",
          file=sys.stderr, flush=True)

    dof_iters_per_sec = model.n_dof * iters / r1.wall_s
    # idealized 8-rank reference: perfect 8x scaling of the measured hot loop
    baseline_dof_iters_per_sec = 8.0 / ref_per_dof_iter
    vs_baseline = dof_iters_per_sec / baseline_dof_iters_per_sec

    print(json.dumps({
        "metric": "pcg_dof_iterations_per_second",
        "value": round(dof_iters_per_sec, 1),
        "unit": "dof*iter/s",
        "vs_baseline": round(vs_baseline, 3),
        "detail": {
            "n_dof": model.n_dof,
            "model": kind,
            "iters": int(iters),
            "flag": int(r1.flag),
            "relres": float(r1.relres),
            "solve_wall_s": round(r1.wall_s, 4),
            "tpu_ms_per_iter": round(tpu_per_iter * 1e3, 4),
            "numpy_ref_ns_per_dof_iter": round(ref_per_dof_iter * 1e9, 4),
            "baseline_model": (
                "measured numpy re-impl of the reference per-rank hot loop "
                "/ 8 (ideal scaling; real mpi4py+OpenMPI not installable in "
                "this image)"),
            "ref_measured_on": ref_note,
            "dtype": dtype,
            "mode": mode,
            "backend": s.backend,
            "n_parts": n_parts,
            "partition_s": round(t_part, 2),
        },
    }), flush=True)


if __name__ == "__main__":
    main()
