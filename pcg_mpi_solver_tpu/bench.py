"""Benchmark harness: TPU SPMD solve vs the single-process numpy reference.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: sustained PCG iteration throughput (dof-iterations / second) of the
full jitted solve on the available accelerator, measured on a converged
quasi-static step (compile excluded).  ``vs_baseline`` compares against an
idealized 8-rank run of the reference implementation: the numpy backend's
measured per-iteration time divided by 8 (perfect scaling — conservative,
the real mpi4py reference scales sublinearly; its 8-rank demo spent 1.0 of
12.6 s in comm-wait, BASELINE.md).

Env knobs: BENCH_NX/NY/NZ (mesh size), BENCH_TOL, BENCH_PARTS, BENCH_DTYPE.
"""

import json
import os
import sys
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    from pcg_mpi_solver_tpu import RunConfig, SolverConfig, TimeHistoryConfig
    from pcg_mpi_solver_tpu.models import make_cube_model
    from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
    from pcg_mpi_solver_tpu.solver import Solver
    from pcg_mpi_solver_tpu.solver.numpy_ref import NumpyRefSolver

    nx = int(os.environ.get("BENCH_NX", 48))
    ny = int(os.environ.get("BENCH_NY", 32))
    nz = int(os.environ.get("BENCH_NZ", 32))
    tol = float(os.environ.get("BENCH_TOL", 1e-7))
    mode = os.environ.get("BENCH_MODE", "mixed")   # mixed | direct
    dtype = os.environ.get("BENCH_DTYPE", "float32")
    n_dev = len(jax.devices())
    n_parts = int(os.environ.get("BENCH_PARTS", n_dev))

    model = make_cube_model(nx, ny, nz, E=30e9, nu=0.2, load="traction",
                            load_value=1e6, heterogeneous=True)
    print(f"# model: {model.n_elem} elems / {model.n_dof} dofs; "
          f"devices={n_dev} parts={n_parts} dtype={dtype}", file=sys.stderr)

    cfg = RunConfig(
        solver=SolverConfig(tol=tol, max_iter=20000, dtype=dtype,
                            dot_dtype="float64", precision_mode=mode),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]),
    )
    t_part0 = time.perf_counter()
    s = Solver(model, cfg, mesh=make_mesh(), n_parts=n_parts)
    t_part = time.perf_counter() - t_part0

    # Warm-up: compile + first solve.
    r0 = s.step(1.0)
    print(f"# warm solve: flag={r0.flag} iters={r0.iters} "
          f"relres={r0.relres:.3e} wall={r0.wall_s:.2f}s (incl. compile); "
          f"partition {t_part:.2f}s", file=sys.stderr)

    # Measured solve from scratch state (compile cached).
    s.reset_state()
    r1 = s.step(1.0)
    iters = max(r1.iters, 1)
    tpu_per_iter = r1.wall_s / iters
    print(f"# timed solve: flag={r1.flag} iters={iters} "
          f"relres={r1.relres:.3e} wall={r1.wall_s:.3f}s "
          f"-> {tpu_per_iter*1e3:.3f} ms/iter", file=sys.stderr)

    # Baseline: numpy reference per-iteration cost on this host.
    ref = NumpyRefSolver(model)
    ref_per_iter = ref.time_per_iter(n_iters=int(os.environ.get("BENCH_REF_ITERS", 20)))
    print(f"# numpy ref: {ref_per_iter*1e3:.3f} ms/iter "
          f"(x{ref_per_iter/tpu_per_iter:.1f} slower than accelerator)",
          file=sys.stderr)

    dof_iters_per_sec = model.n_dof * iters / r1.wall_s
    # idealized 8-rank reference: perfect 8x scaling of the numpy backend
    baseline_dof_iters_per_sec = model.n_dof / (ref_per_iter / 8.0)
    vs_baseline = dof_iters_per_sec / baseline_dof_iters_per_sec

    print(json.dumps({
        "metric": "pcg_dof_iterations_per_second",
        "value": round(dof_iters_per_sec, 1),
        "unit": "dof*iter/s",
        "vs_baseline": round(vs_baseline, 3),
        "detail": {
            "n_dof": model.n_dof,
            "iters": int(iters),
            "flag": int(r1.flag),
            "relres": float(r1.relres),
            "solve_wall_s": round(r1.wall_s, 4),
            "tpu_ms_per_iter": round(tpu_per_iter * 1e3, 4),
            "numpy_ref_ms_per_iter": round(ref_per_iter * 1e3, 4),
            "baseline_model": "numpy backend / 8 (ideal 8-rank mpi4py stand-in)",
            "dtype": dtype,
            "n_parts": n_parts,
        },
    }))


if __name__ == "__main__":
    main()
