"""AST-level source rules.

``recovery-paths``: no silently-swallowed broad exception handlers in
the solve/cache/recovery/ops/parallel/obs layers.  The resilience
posture only works if every broad ``except`` either **re-raises**
(possibly after cleanup), **records** what happened (a metrics call —
``.event``/``.inc``/``.note``/``.gauge`` — a ``warnings.warn``, or the
bench's ``_log``), or carries an explicit ``# noqa: BLE001``
justification on the handler line (the repo convention for best-effort
cache/IO paths where a failure legitimately degrades to a miss).

This is the engine-native home of the logic ``tools/
check_recovery_paths.py`` exposes as a standalone CLI (that script is
now a thin shim over this module).  Scope extension (ISSUE 7): ``ops/``,
``parallel/`` and ``obs/`` joined the historical
solver/cache/resilience/validate set — a swallowed matvec/mesh/telemetry
failure hides a wrong answer exactly as effectively as a swallowed
recovery failure.
"""

from __future__ import annotations

import ast
import os
from typing import List

from pcg_mpi_solver_tpu.analysis.engine import REPO, Finding, rule

PKG = os.path.join(REPO, "pcg_mpi_solver_tpu")

#: scanned packages: the historical recovery scope + the ISSUE-7
#: extension (ops/parallel/obs).
DEFAULT_SCOPE = (
    os.path.join(PKG, "solver"),
    os.path.join(PKG, "cache"),
    os.path.join(PKG, "resilience"),
    os.path.join(PKG, "validate"),
    os.path.join(PKG, "ops"),
    os.path.join(PKG, "parallel"),
    os.path.join(PKG, "obs"),
)

# Exception names considered "broad" when caught: anything narrower
# (OSError, ValueError, ...) expresses an expectation and is exempt.
_BROAD = {"Exception", "BaseException"}

# A call to any of these names (bare or attribute) inside the handler
# counts as recording the failure.
_LOG_CALLS = {"event", "inc", "note", "gauge", "warn", "warning",
              "exception", "_log"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:                            # bare `except:`
        return True
    names = []
    if isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    elif isinstance(t, ast.Name):
        names = [t.id]
    return any(n in _BROAD for n in names)


def _handler_ok(handler: ast.ExceptHandler, lines: List[str]) -> bool:
    # explicit justification on the `except` line (repo convention)
    line = lines[handler.lineno - 1]
    if "noqa" in line and "BLE001" in line:
        return True
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            f = node.func
            name = (f.attr if isinstance(f, ast.Attribute)
                    else getattr(f, "id", ""))
            if name in _LOG_CALLS:
                return True
    return False


def check_source(source: str, path: str = "<source>") -> List[str]:
    """Violations in one python source blob (path used for labels)."""
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [f"{path}: unparseable ({e})"]
    lines = source.splitlines()
    errs = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and _is_broad(node) \
                and not _handler_ok(node, lines):
            errs.append(
                f"{path}:{node.lineno}: broad `except` neither re-raises, "
                "logs a metrics/warning event, nor carries a "
                "`# noqa: BLE001` justification")
    return errs


def check_file(path: str) -> List[str]:
    try:
        with open(path, encoding="utf-8") as f:
            source = f.read()
    except OSError as e:
        return [f"{path}: unreadable ({e})"]
    return check_source(source, path)


def iter_py_files(paths) -> List[str]:
    out = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, files in os.walk(p):
                if "__pycache__" in root:
                    continue
                out.extend(os.path.join(root, fn) for fn in sorted(files)
                           if fn.endswith(".py"))
        elif p.endswith(".py"):
            out.append(p)
    return out


@rule("recovery-paths", kind="ast", fast=True,
      doc="every broad `except` in solver/cache/resilience/validate/ops/"
          "parallel/obs re-raises, records, or carries # noqa: BLE001")
def recovery_paths_rule(ctx) -> List[Finding]:
    findings = []
    for f in iter_py_files(DEFAULT_SCOPE):
        for err in check_file(f):
            # err is "path:line: message" — split the anchor off for the
            # baseline-stable loc
            loc, _, msg = err.partition(": ")
            findings.append(Finding(
                rule="recovery-paths",
                loc=os.path.relpath(loc, REPO),
                message=msg))
    return findings
