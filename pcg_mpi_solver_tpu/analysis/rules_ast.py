"""AST-level source rules.

``recovery-paths``: no silently-swallowed broad exception handlers in
the solve/cache/recovery/ops/parallel/obs layers.  The resilience
posture only works if every broad ``except`` either **re-raises**
(possibly after cleanup), **records** what happened (a metrics call —
``.event``/``.inc``/``.note``/``.gauge`` — a ``warnings.warn``, or the
bench's ``_log``), or carries an explicit ``# noqa: BLE001``
justification on the handler line (the repo convention for best-effort
cache/IO paths where a failure legitimately degrades to a miss).

This is the engine-native home of the logic ``tools/
check_recovery_paths.py`` exposes as a standalone CLI (that script is
now a thin shim over this module).  Scope extension (ISSUE 7): ``ops/``,
``parallel/`` and ``obs/`` joined the historical
solver/cache/resilience/validate set — a swallowed matvec/mesh/telemetry
failure hides a wrong answer exactly as effectively as a swallowed
recovery failure.
"""

from __future__ import annotations

import ast
import os
from typing import List

from pcg_mpi_solver_tpu.analysis.engine import REPO, Finding, rule

PKG = os.path.join(REPO, "pcg_mpi_solver_tpu")

#: scanned packages: the historical recovery scope + the ISSUE-7
#: extension (ops/parallel/obs) + the solve service (ISSUE 19 — a
#: swallowed daemon failure silently loses a tenant's job).
DEFAULT_SCOPE = (
    os.path.join(PKG, "solver"),
    os.path.join(PKG, "cache"),
    os.path.join(PKG, "resilience"),
    os.path.join(PKG, "validate"),
    os.path.join(PKG, "ops"),
    os.path.join(PKG, "parallel"),
    os.path.join(PKG, "obs"),
    os.path.join(PKG, "serve"),
)

# Exception names considered "broad" when caught: anything narrower
# (OSError, ValueError, ...) expresses an expectation and is exempt.
_BROAD = {"Exception", "BaseException"}

# A call to any of these names (bare or attribute) inside the handler
# counts as recording the failure.
_LOG_CALLS = {"event", "inc", "note", "gauge", "warn", "warning",
              "exception", "_log"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:                            # bare `except:`
        return True
    names = []
    if isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    elif isinstance(t, ast.Name):
        names = [t.id]
    return any(n in _BROAD for n in names)


def _handler_ok(handler: ast.ExceptHandler, lines: List[str]) -> bool:
    # explicit justification on the `except` line (repo convention)
    line = lines[handler.lineno - 1]
    if "noqa" in line and "BLE001" in line:
        return True
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            f = node.func
            name = (f.attr if isinstance(f, ast.Attribute)
                    else getattr(f, "id", ""))
            if name in _LOG_CALLS:
                return True
    return False


def check_source(source: str, path: str = "<source>") -> List[str]:
    """Violations in one python source blob (path used for labels)."""
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [f"{path}: unparseable ({e})"]
    lines = source.splitlines()
    errs = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and _is_broad(node) \
                and not _handler_ok(node, lines):
            errs.append(
                f"{path}:{node.lineno}: broad `except` neither re-raises, "
                "logs a metrics/warning event, nor carries a "
                "`# noqa: BLE001` justification")
    return errs


def check_file(path: str) -> List[str]:
    try:
        with open(path, encoding="utf-8") as f:
            source = f.read()
    except OSError as e:
        return [f"{path}: unreadable ({e})"]
    return check_source(source, path)


def iter_py_files(paths) -> List[str]:
    out = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, files in os.walk(p):
                if "__pycache__" in root:
                    continue
                out.extend(os.path.join(root, fn) for fn in sorted(files)
                           if fn.endswith(".py"))
        elif p.endswith(".py"):
            out.append(p)
    return out


@rule("recovery-paths", kind="ast", fast=True,
      doc="every broad `except` in solver/cache/resilience/validate/ops/"
          "parallel/obs re-raises, records, or carries # noqa: BLE001")
def recovery_paths_rule(ctx) -> List[Finding]:
    findings = []
    for f in iter_py_files(DEFAULT_SCOPE):
        for err in check_file(f):
            # err is "path:line: message" — split the anchor off for the
            # baseline-stable loc
            loc, _, msg = err.partition(": ")
            findings.append(Finding(
                rule="recovery-paths",
                loc=os.path.relpath(loc, REPO),
                message=msg))
    return findings


# ----------------------------------------------------------------------
# recovery-coverage: every Krylov dispatch surface of the drivers is
# wrapped by the resilience harness or carries a documented exemption
# (ISSUE 9).
# ----------------------------------------------------------------------

#: Files whose top-level functions/methods are swept for dispatch
#: surfaces.  ``solver/chunked.py`` (ChunkedEngine) and
#: ``resilience/engine.py`` are harness-INTERNAL — their dispatches are
#: only ever reached through a wrapped caller below.
COVERAGE_FILES = ("pcg_mpi_solver_tpu/solver/driver.py",
                  "pcg_mpi_solver_tpu/solver/newmark.py",
                  "pcg_mpi_solver_tpu/serve/daemon.py")

#: Krylov-TERMINAL dispatch-span names: a swept function whose subtree
#: opens ``<recorder>.dispatch("<one of these>")`` — or calls the
#: one-shot ``_step_fn`` program — runs a solve to (partial)
#: termination and is therefore a dispatch surface.  Setup/finalize
#: spans (start, restart, many_start, many_final, fallback_prec, ...)
#: are not surfaces: they hold no Krylov iterations to lose.
SOLVE_DISPATCH_NAMES = frozenset(
    {"step", "solve_many", "cycle", "inner_cycle", "many_cycle"})

#: (file, function) -> coverage requirement.  ``calls:<name>`` — the
#: function must invoke that recovery-harness entry (the positive proof
#: that the surface is wrapped); ``exempt`` — the function must carry a
#: ``recovery-exempt:`` comment documenting WHY no harness applies
#: (e.g. a donated one-shot operand that must never be re-dispatched).
#: A swept surface missing from this registry is itself a finding, so a
#: new dispatch path cannot ship silently unprotected.
RECOVERY_SURFACES = {
    ("pcg_mpi_solver_tpu/solver/driver.py", "_step_chunked"):
        "calls:run_with_recovery",
    ("pcg_mpi_solver_tpu/solver/driver.py", "_solve_many_chunked"):
        "calls:run_many_with_recovery",
    ("pcg_mpi_solver_tpu/solver/driver.py", "solve_many"):
        "calls:_dispatch_with_retry",
    ("pcg_mpi_solver_tpu/solver/driver.py", "step"): "exempt",
    ("pcg_mpi_solver_tpu/solver/newmark.py", "_step_chunked"):
        "calls:run_with_recovery",
    ("pcg_mpi_solver_tpu/solver/newmark.py", "step"): "exempt",
    # solve-service dispatch (ISSUE 19): jobs reach the solver ONLY
    # through Solver.solve_many — the per-column recovery/quarantine
    # path — so a poisoned tenant cannot fail its co-batched block
    ("pcg_mpi_solver_tpu/serve/daemon.py", "_dispatch_block"):
        "calls:solve_many",
}


def _top_level_functions(tree: ast.Module):
    """Module-level functions and class methods (nested closures belong
    to — and are walked with — their enclosing definition)."""
    out = []
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            out.extend(n for n in node.body
                       if isinstance(n, ast.FunctionDef))
        elif isinstance(node, ast.FunctionDef):
            out.append(node)
    return out


def _is_dispatch_surface(fn: ast.FunctionDef) -> bool:
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "dispatch" \
                and node.args:
            a = node.args[0]
            if isinstance(a, ast.Constant) \
                    and a.value in SOLVE_DISPATCH_NAMES:
                return True
        if isinstance(f, ast.Attribute) and f.attr == "_step_fn":
            return True
    return False


def _calls_name(fn: ast.FunctionDef, name: str) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            got = (f.attr if isinstance(f, ast.Attribute)
                   else getattr(f, "id", ""))
            if got == name:
                return True
    return False


def check_recovery_coverage(sources) -> List[str]:
    """Coverage violations for ``{relpath: source}`` (the rule feeds the
    real files; tests feed seeded-violation sources)."""
    errs: List[str] = []
    for rel, source in sources.items():
        try:
            tree = ast.parse(source)
        except SyntaxError as e:
            errs.append(f"{rel}:0: unparseable ({e})")
            continue
        lines = source.splitlines()
        seen = set()
        for fn in _top_level_functions(tree):
            key = (rel, fn.name)
            req = RECOVERY_SURFACES.get(key)
            if _is_dispatch_surface(fn):
                seen.add(key)
                if req is None:
                    errs.append(
                        f"{rel}:{fn.lineno}: `{fn.name}` opens a "
                        "Krylov-terminal dispatch but is not registered "
                        "in RECOVERY_SURFACES — wrap it in the recovery "
                        "harness (run_with_recovery / "
                        "run_many_with_recovery / _dispatch_with_retry) "
                        "and register it, or register a documented "
                        "exemption")
                    continue
            if req is None:
                continue
            if req.startswith("calls:"):
                want = req.split(":", 1)[1]
                if not _calls_name(fn, want):
                    errs.append(
                        f"{rel}:{fn.lineno}: dispatch surface "
                        f"`{fn.name}` no longer calls its registered "
                        f"recovery harness `{want}` — the surface runs "
                        "unprotected")
            elif req == "exempt":
                seg = "\n".join(
                    lines[fn.lineno - 1:fn.end_lineno or fn.lineno])
                if "recovery-exempt:" not in seg:
                    errs.append(
                        f"{rel}:{fn.lineno}: dispatch surface "
                        f"`{fn.name}` is registered exempt but carries "
                        "no `recovery-exempt:` comment — document why "
                        "no recovery harness applies, or wrap it")
        # stale registry entries: the function moved/renamed, so the
        # registry would silently vouch for nothing
        names = {fn.name for fn in _top_level_functions(tree)}
        for (f, name), _req in RECOVERY_SURFACES.items():
            if f == rel and name not in names:
                errs.append(
                    f"{rel}:0: RECOVERY_SURFACES registers "
                    f"`{name}` but no such function exists — update "
                    "the registry")
    return errs


@rule("recovery-coverage", kind="ast", fast=True,
      doc="every Krylov dispatch surface in driver.py/newmark.py "
          "(one-shot, chunked scalar, chunked blocked, mixed inner) is "
          "wrapped by the recovery harness or carries a documented "
          "`recovery-exempt:` justification")
def recovery_coverage_rule(ctx) -> List[Finding]:
    sources = {}
    for rel in COVERAGE_FILES:
        path = os.path.join(REPO, rel)
        try:
            with open(path, encoding="utf-8") as f:
                sources[rel] = f.read()
        except OSError as e:
            return [Finding(rule="recovery-coverage", loc=rel,
                            message=f"unreadable ({e})")]
    findings = []
    for err in check_recovery_coverage(sources):
        loc, _, msg = err.partition(": ")
        findings.append(Finding(rule="recovery-coverage", loc=loc,
                                message=msg))
    return findings


# ----------------------------------------------------------------------
# consensus-coverage: every host-side collective on the dispatch path
# routes its verdict through parallel/consensus or carries a documented
# exemption (ISSUE 18).
# ----------------------------------------------------------------------

#: Files swept for host-side collective call sites.  The dispatch path
#: only: setup-layer collectives (``parallel/partition.py`` glue
#: exchanges, ``cache/partition_cache.py``) run once before any Krylov
#: loop and already route their gate verdicts through the consensus
#: module by construction.
CONSENSUS_COVERAGE_FILES = (
    "pcg_mpi_solver_tpu/solver/driver.py",
    "pcg_mpi_solver_tpu/solver/chunked.py",
    "pcg_mpi_solver_tpu/resilience/engine.py",
)

#: Host-collective call names that pair blocking rounds across
#: processes: a divergent branch around ANY of these wedges the fleet.
#: Deliberately NOT ``warmup`` — ``ChunkedEngine.warmup`` is the
#: unrelated compile-warmup method and would shadow every sweep.
COLLECTIVE_CALL_NAMES = frozenset(
    {"allreduce", "allreduce_many", "allreduce_groups",
     "process_allgather", "sync_global_devices"})

#: (file, function) -> coverage requirement, the RECOVERY_SURFACES
#: shape: ``calls:<name>`` — the function must invoke that
#: ``parallel/consensus`` primitive (or the chunk-boundary liveness
#: sync), the positive proof its group verdict cannot diverge;
#: ``exempt`` — the function must carry a ``consensus-exempt:`` comment
#: documenting why no verdict needs agreement (an unconditional data
#: gather or plain barrier that every process reaches).
CONSENSUS_SITES = {
    # engage decision gates collective code paths -> agree_flag
    ("pcg_mpi_solver_tpu/solver/driver.py", "__init__"):
        "calls:agree_flag",
    # pallas-probe allgather: unconditional, AND-reduced on every rank
    ("pcg_mpi_solver_tpu/solver/driver.py", "_pallas_enabled"): "exempt",
    # export-glue layout exchange: unconditional data movement
    ("pcg_mpi_solver_tpu/solver/driver.py", "_exchange_export_glue"):
        "exempt",
    # runstore-prepared barrier: no verdict, every process reaches it
    ("pcg_mpi_solver_tpu/solver/driver.py", "solve"): "exempt",
    # chunk loops open every iteration with the guarded liveness sync
    ("pcg_mpi_solver_tpu/solver/chunked.py", "run"):
        "calls:sync_boundary",
    # scalar ladder triggers are group-agreed before branching
    ("pcg_mpi_solver_tpu/resilience/engine.py", "run_with_recovery"):
        "calls:agree_trigger",
    # per-column triggers (quarantine/ladder masks) likewise
    ("pcg_mpi_solver_tpu/resilience/engine.py",
     "run_many_with_recovery"): "calls:agree_triggers",
}


def _has_collective_call(fn: ast.FunctionDef) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            got = (f.attr if isinstance(f, ast.Attribute)
                   else getattr(f, "id", ""))
            if got in COLLECTIVE_CALL_NAMES:
                return True
    return False


def check_consensus_coverage(sources) -> List[str]:
    """Coverage violations for ``{relpath: source}`` (the rule feeds the
    real files; tests feed seeded-violation sources)."""
    errs: List[str] = []
    for rel, source in sources.items():
        try:
            tree = ast.parse(source)
        except SyntaxError as e:
            errs.append(f"{rel}:0: unparseable ({e})")
            continue
        lines = source.splitlines()
        for fn in _top_level_functions(tree):
            key = (rel, fn.name)
            req = CONSENSUS_SITES.get(key)
            if _has_collective_call(fn):
                if req is None:
                    errs.append(
                        f"{rel}:{fn.lineno}: `{fn.name}` calls a "
                        "host-side collective but is not registered in "
                        "CONSENSUS_SITES — route its verdict through "
                        "parallel/consensus (agree / agree_flag / "
                        "agree_trigger / agree_triggers) and register "
                        "it, or register a documented exemption")
                    continue
            if req is None:
                continue
            if req.startswith("calls:"):
                want = req.split(":", 1)[1]
                if not _calls_name(fn, want):
                    errs.append(
                        f"{rel}:{fn.lineno}: collective site "
                        f"`{fn.name}` no longer calls its registered "
                        f"consensus primitive `{want}` — a divergent "
                        "group verdict wedges the fleet")
            elif req == "exempt":
                seg = "\n".join(
                    lines[fn.lineno - 1:fn.end_lineno or fn.lineno])
                if "consensus-exempt:" not in seg:
                    errs.append(
                        f"{rel}:{fn.lineno}: collective site "
                        f"`{fn.name}` is registered exempt but carries "
                        "no `consensus-exempt:` comment — document why "
                        "the verdict needs no agreement, or route it "
                        "through parallel/consensus")
        names = {fn.name for fn in _top_level_functions(tree)}
        for (f, name), _req in CONSENSUS_SITES.items():
            if f == rel and name not in names:
                errs.append(
                    f"{rel}:0: CONSENSUS_SITES registers `{name}` but "
                    "no such function exists — update the registry")
    return errs


@rule("consensus-coverage", kind="ast", fast=True,
      doc="every host-side collective call site on the dispatch path "
          "(driver.py / chunked.py / resilience engine) routes its "
          "group verdict through parallel/consensus or carries a "
          "documented `consensus-exempt:` justification")
def consensus_coverage_rule(ctx) -> List[Finding]:
    sources = {}
    for rel in CONSENSUS_COVERAGE_FILES:
        path = os.path.join(REPO, rel)
        try:
            with open(path, encoding="utf-8") as f:
                sources[rel] = f.read()
        except OSError as e:
            return [Finding(rule="consensus-coverage", loc=rel,
                            message=f"unreadable ({e})")]
    findings = []
    for err in check_consensus_coverage(sources):
        loc, _, msg = err.partition(": ")
        findings.append(Finding(rule="consensus-coverage", loc=loc,
                                message=msg))
    return findings


# ----------------------------------------------------------------------
# serve-admission-events: every admission-decision outcome of the solve
# service emits its schema-versioned telemetry event (ISSUE 19) — the
# no-silent-drops contract, proven statically.
# ----------------------------------------------------------------------

#: Files swept for admission/lifecycle decision sites.
ADMISSION_COVERAGE_FILES = (
    "pcg_mpi_solver_tpu/serve/admission.py",
    "pcg_mpi_solver_tpu/serve/daemon.py",
)

#: (file, function) -> the event kinds the function MUST emit via a
#: constant-first-arg ``.event("<kind>", ...)`` call.  Each kind must
#: also exist in obs/schema.EVENT_KINDS (a registered typo would vouch
#: for an event no consumer can validate).  A registered function that
#: disappears is itself a finding — the registry cannot go stale
#: silently.
ADMISSION_EVENT_SITES = {
    ("pcg_mpi_solver_tpu/serve/admission.py", "admit"):
        ("job_admit",),
    ("pcg_mpi_solver_tpu/serve/admission.py", "_reject"):
        ("job_reject",),
    ("pcg_mpi_solver_tpu/serve/admission.py", "shed_past_deadline"):
        ("job_shed",),
    ("pcg_mpi_solver_tpu/serve/daemon.py", "_dispatch_block"):
        ("job_done", "job_quarantine"),
    ("pcg_mpi_solver_tpu/serve/daemon.py", "_finish_failed"):
        ("job_done",),
    ("pcg_mpi_solver_tpu/serve/daemon.py", "run"):
        ("serve_drain",),
}


def _emits_event(fn: ast.FunctionDef, kind: str) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and node.args:
            f = node.func
            name = (f.attr if isinstance(f, ast.Attribute)
                    else getattr(f, "id", ""))
            a = node.args[0]
            if name == "event" and isinstance(a, ast.Constant) \
                    and a.value == kind:
                return True
    return False


def check_admission_events(sources) -> List[str]:
    """Violations for ``{relpath: source}`` (the rule feeds the real
    files; tests feed seeded-violation sources)."""
    from pcg_mpi_solver_tpu.obs.schema import EVENT_KINDS

    errs: List[str] = []
    for rel, source in sources.items():
        try:
            tree = ast.parse(source)
        except SyntaxError as e:
            errs.append(f"{rel}:0: unparseable ({e})")
            continue
        fns = {fn.name: fn for fn in _top_level_functions(tree)}
        for (f, name), kinds in sorted(ADMISSION_EVENT_SITES.items()):
            if f != rel:
                continue
            fn = fns.get(name)
            if fn is None:
                errs.append(
                    f"{rel}:0: ADMISSION_EVENT_SITES registers "
                    f"`{name}` but no such function exists — update "
                    "the registry")
                continue
            for kind in kinds:
                if kind not in EVENT_KINDS:
                    errs.append(
                        f"{rel}:{fn.lineno}: ADMISSION_EVENT_SITES "
                        f"requires `{name}` to emit `{kind}`, which is "
                        "not a schema EVENT_KINDS kind — fix the "
                        "registry or add the kind to obs/schema.py")
                    continue
                if not _emits_event(fn, kind):
                    errs.append(
                        f"{rel}:{fn.lineno}: admission-decision site "
                        f"`{name}` no longer emits the "
                        f"schema-versioned `{kind}` event — a service "
                        "outcome would go silent")
    return errs


@rule("serve-admission-events", kind="ast", fast=True,
      doc="every solve-service admission/lifecycle outcome (admit, "
          "reject, shed, done, quarantine, drain) emits its "
          "schema-versioned telemetry event — decisions are never "
          "silent")
def serve_admission_events_rule(ctx) -> List[Finding]:
    sources = {}
    for rel in ADMISSION_COVERAGE_FILES:
        path = os.path.join(REPO, rel)
        try:
            with open(path, encoding="utf-8") as f:
                sources[rel] = f.read()
        except OSError as e:
            return [Finding(rule="serve-admission-events", loc=rel,
                            message=f"unreadable ({e})")]
    findings = []
    for err in check_admission_events(sources):
        loc, _, msg = err.partition(": ")
        findings.append(Finding(rule="serve-admission-events", loc=loc,
                                message=msg))
    return findings
