"""fingerprint-completeness: config reflection vs cache keys & snapshots.

The two worst recent bug classes (ISSUE 7) were trace-affecting config
fields missing from a completeness surface: ``pcg_variant`` absent from
the snapshot ``_fingerprint`` until PR-5 review, ``nrhs``/``rhs_hash``
until PR-6 review.  This rule makes that class MECHANICAL: it reflects
over every ``SolverConfig``/``RunConfig`` field, perturbs it on a real
small solver, and checks that any field that changes the traced step
program (jaxpr text + folded-constant bytes) also changes BOTH

* ``cache/keys.step_cache_key`` — else a warm run could deserialize an
  AOT program compiled for a different config, and
* ``utils/checkpoint._fingerprint`` — else a resume could continue a
  Krylov/time history under different numerics without a mismatch error.

A new config field is forced through classification: bool/int/float
fields get an auto-derived perturbation; string fields need a row in
``STRING_ALTERNATIVES``; fields that cannot be probed must be declared
(with the reason encoded in this module) or the rule fails.  Probe
injection (``key_fn``/``fp_fn``/``fields``) exists so the seeded-
violation tests can prove the rule fires on a deliberately-omitted
field without patching the real cache layer.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional

from pcg_mpi_solver_tpu.analysis.engine import Finding, rule
from pcg_mpi_solver_tpu.config import RunConfig, SolverConfig

#: alternatives for string-typed SolverConfig fields (auto-derivation
#: would be guesswork).  A NEW string field without a row here is an
#: unclassified-field finding — classification is the point.
STRING_ALTERNATIVES = {
    "precision_mode": "mixed",
    "dtype": "float32",
    "dot_dtype": "float32",
    "precond": "block3",
    "pcg_variant": "fused",
    "pallas": "off",
}

#: fields probed on the MIXED base solver (they only reach the traced
#: program through the f32/f64 refinement engine).
MIXED_SCOPE_FIELDS = ("inner_tol", "mixed_plateau_window",
                      "mixed_progress_window", "mixed_progress_ratio",
                      "mixed_progress_min_gain")

#: trace-affecting fields exempt from the SNAPSHOT fingerprint only
#: (they must still key the AOT cache).  Each entry carries its why.
RESUME_NEUTRAL = {
    "donate_carry": (
        "changes only the pjit donation metadata, not the computation — "
        "bit-identical on/off (asserted in tests/test_cache.py), so a "
        "resume across the toggle is safe; it keys the AOT cache via the "
        "explicit donate= component"),
}

#: RunConfig fields that never shape the traced step program: paths,
#: host-side policies, dispatch cadence.  ``solver`` is the SolverConfig
#: (swept field-by-field above); ``time_history`` carries runtime
#: schedule values that enter the program as ARGUMENTS (delta) and are
#: independently fingerprinted for resume-counter integrity
#: (checkpoint._fingerprint deltas/export/plot entries).  A NEW RunConfig
#: field must either join this set (with thought) or be handled like a
#: solver knob — unclassified fields fail the rule.
TRACE_NEUTRAL_RUNCONFIG = frozenset({
    "scratch_path", "model_name", "run_id", "n_parts",
    "partition_method", "speed_test", "checkpoint_every",
    "snapshot_every", "preflight", "cache_dir", "telemetry_path",
    "flight_path", "telemetry_profile", "profile_dir", "comm_probe_iters",
    "solver", "time_history",
    # ISSUE 14: sharded setup builds the SAME partition rows this
    # process would otherwise slice out of a full build — device data,
    # traced programs and cache keys are unchanged (bit-identity
    # asserted in tests/test_setup_shard.py)
    "setup_shard",
})


def _auto_alternative(value):
    """Perturbation for bool/int/float values; None if underivable."""
    if isinstance(value, bool):
        return not value
    if isinstance(value, int):
        return value + 7
    if isinstance(value, float):
        return value * 3.0 if value else 0.5
    return None


def perturbation_for(field: dataclasses.Field, value):
    if field.name in STRING_ALTERNATIVES:
        alt = STRING_ALTERNATIVES[field.name]
        return alt if alt != value else None
    return _auto_alternative(value)


def _default_key_fn():
    from pcg_mpi_solver_tpu.cache.keys import step_cache_key

    return step_cache_key


def _default_fp_fn():
    from pcg_mpi_solver_tpu.utils.checkpoint import _fingerprint

    return _fingerprint


def _key_digest(scfg: SolverConfig, key_fn) -> str:
    """The AOT step key exactly as the driver assembles it, with the
    non-config components held fixed so only the config can move it."""
    return key_fn(
        abstract="<sig>", mesh=[["parts", 2], "cpu"], backend="general",
        solver=dataclasses.asdict(scfg),
        pcg_variant=scfg.pcg_variant,
        precond=getattr(scfg, "precond", "jacobi"),
        nrhs=int(getattr(scfg, "nrhs", 1)),
        trace_len=0, glob_n_dof_eff=100,
        donate=bool(scfg.donate_carry),
        jax_version="<held>", extra={})


def check_structural_key_components(key_fn=None) -> List[Finding]:
    """The documented STRUCTURAL key components must move the digest on
    their own (they exist so the key survives a solver-dict/signature
    serialization refactor): pcg_variant, precond, nrhs, trace_len,
    donate."""
    key_fn = key_fn or _default_key_fn()

    def k(**over):
        kw = dict(abstract="a", mesh="m", backend="b", solver={},
                  trace_len=0, glob_n_dof_eff=1, donate=True,
                  jax_version="j", pcg_variant="classic",
                  precond="jacobi", nrhs=1)
        kw.update(over)
        return key_fn(**kw)

    base = k()
    out = []
    for name, over in (("pcg_variant", {"pcg_variant": "fused"}),
                       ("precond", {"precond": "mg"}),
                       ("nrhs", {"nrhs": 8}),
                       ("trace_len", {"trace_len": 16}),
                       ("donate", {"donate": False})):
        if k(**over) == base:
            out.append(Finding(
                rule="fingerprint-completeness",
                loc=f"field:step_cache_key.{name}",
                message=f"structural key component {name!r} does not "
                        "change the AOT step cache key — programs of "
                        "different shape would collide in the cache"))
    return out


def check_fingerprint_completeness(fields: Optional[List[str]] = None,
                                   key_fn: Optional[Callable] = None,
                                   fp_fn: Optional[Callable] = None,
                                   ) -> List[Finding]:
    """The perturbation sweep (see module docstring).  ``fields``
    restricts to named SolverConfig fields (test hook); ``key_fn`` /
    ``fp_fn`` override the probed surfaces (seeded-violation tests)."""
    from pcg_mpi_solver_tpu.analysis import programs as ap

    key_fn = key_fn or _default_key_fn()
    fp_fn = fp_fn or _default_fp_fn()
    out: List[Finding] = []

    bases = {}

    def base(mode: str):
        if mode not in bases:
            s = (ap.build_solver("general", precision_mode="mixed")
                 if mode == "mixed" else ap.build_solver("general"))
            bases[mode] = (s, ap.program_signature(s), fp_fn(s))
        return bases[mode]

    for f in dataclasses.fields(SolverConfig):
        if fields is not None and f.name not in fields:
            continue
        loc = f"field:SolverConfig.{f.name}"
        mode = "mixed" if f.name in MIXED_SCOPE_FIELDS else "direct"
        base_s, base_sig, base_fp = base(mode)
        value = getattr(base_s.config.solver, f.name)
        alt = perturbation_for(f, value)
        if alt is None:
            out.append(Finding(
                rule="fingerprint-completeness", loc=loc,
                message=f"no perturbation known for SolverConfig."
                        f"{f.name} (= {value!r}): add a "
                        "STRING_ALTERNATIVES row (or make it auto-"
                        "derivable) so new config knobs stay provably "
                        "keyed"))
            continue
        over = {f.name: alt}
        if mode == "mixed":
            over["precision_mode"] = "mixed"
        pert = ap.build_solver("general", **over)
        if ap.program_signature(pert) == base_sig:
            continue   # not trace-affecting: no coverage obligation
        scfg_b = base_s.config.solver
        scfg_p = pert.config.solver
        if _key_digest(scfg_b, key_fn) == _key_digest(scfg_p, key_fn):
            out.append(Finding(
                rule="fingerprint-completeness", loc=loc,
                message=f"SolverConfig.{f.name} changes the traced step "
                        f"program ({value!r} -> {alt!r}) but NOT "
                        "cache/keys.step_cache_key: a warm run could "
                        "deserialize an AOT program compiled for a "
                        "different config"))
        if fp_fn(pert) == base_fp:
            if f.name in RESUME_NEUTRAL:
                pass   # documented exemption (see RESUME_NEUTRAL)
            else:
                out.append(Finding(
                    rule="fingerprint-completeness", loc=loc,
                    message=f"SolverConfig.{f.name} changes the traced "
                            f"step program ({value!r} -> {alt!r}) but "
                            "NOT the snapshot _fingerprint "
                            "(utils/checkpoint.py): a resume would "
                            "continue under different numerics without "
                            "a mismatch error — the PR-5/PR-6 bug class"))
    return out


def check_runconfig_classified() -> List[Finding]:
    """Every RunConfig field must be classified: either declared
    trace-neutral (TRACE_NEUTRAL_RUNCONFIG, with thought) or handled
    like a solver knob.  A new field added without classification is a
    finding — the mechanical forcing function."""
    out = []
    for f in dataclasses.fields(RunConfig):
        if f.name not in TRACE_NEUTRAL_RUNCONFIG:
            out.append(Finding(
                rule="fingerprint-completeness",
                loc=f"field:RunConfig.{f.name}",
                message=f"RunConfig.{f.name} is unclassified: add it to "
                        "TRACE_NEUTRAL_RUNCONFIG (with thought) or wire "
                        "it through the sweep like a solver knob"))
    return out


# ----------------------------------------------------------------------
# cost-model-completeness (ISSUE 12): the analytic per-iteration cost
# model (obs/perf.py) must cover EVERY canonical combination — and stay
# loud about ones it does not know.
# ----------------------------------------------------------------------

#: the synthetic geometry the completeness sweep models: multi-part
#: (so collective terms engage) with a plausible iface payload.
_COST_MODEL_PROBE_SHAPE = dict(n_dof=30_000, n_parts=8, n_iface=2_000,
                               elem_groups=((24, 9_000),),
                               mg_coarse_dofs=4_000)


def check_cost_model_completeness(variants=None, preconds=None,
                                  model_fn=None, nrhs_set=(1, 8),
                                  ) -> List[Finding]:
    """Every ``config.PCG_VARIANTS`` x ``config.PRECONDS`` x nrhs
    combination must produce a finite positive prediction with all four
    attribution phases, and an UNKNOWN variant/precond must raise
    ``KeyError`` (the single-source-table loudness contract) — a combo
    the model silently defaults for would stamp fabricated
    ``predicted_ms_per_iter`` numbers on bench lines.  ``variants`` /
    ``preconds`` / ``model_fn`` are seeded-violation test hooks."""
    from pcg_mpi_solver_tpu import config as _cfg
    from pcg_mpi_solver_tpu.obs import perf as _perf

    shape = _perf.ProblemShape(**_COST_MODEL_PROBE_SHAPE)
    variants = tuple(variants if variants is not None
                     else _cfg.PCG_VARIANTS)
    preconds = tuple(preconds if preconds is not None else _cfg.PRECONDS)
    if model_fn is None:
        def model_fn(v, p, r):
            return _perf.cost_model(shape, v, p, r)
    out: List[Finding] = []
    for v in variants:
        for p in preconds:
            for r in nrhs_set:
                loc = f"combo:{v}/{p}/nrhs{r}"
                try:
                    cm = model_fn(v, p, r)
                except Exception as e:                  # noqa: BLE001
                    out.append(Finding(
                        rule="cost-model-completeness", loc=loc,
                        message=f"cost model has no entry for "
                                f"(pcg_variant={v!r}, precond={p!r}, "
                                f"nrhs={r}): {type(e).__name__}: {e} — "
                                "every canonical combination must "
                                "predict, or bench/telemetry lines for "
                                "it carry no model verdict"))
                    continue
                phases = (cm or {}).get("phases", {})
                missing = [ph for ph in _perf.PHASES if ph not in phases]
                pred = (cm or {}).get("predicted_ms_per_iter", 0)
                if missing or not (isinstance(pred, (int, float))
                                   and pred > 0):
                    out.append(Finding(
                        rule="cost-model-completeness", loc=loc,
                        message=f"cost model entry for ({v}, {p}, "
                                f"nrhs={r}) is degenerate: "
                                f"missing phases {missing}, "
                                f"predicted_ms_per_iter={pred!r} — a "
                                "zero/partial prediction reads as 'free' "
                                "on the measured-vs-model table"))
    # loudness probes: an unknown name must KeyError, never default
    for probe_kw, loc in ((("__no_such_variant__", preconds[0]),
                           "probe:unknown-variant"),
                          ((variants[0], "__no_such_precond__"),
                           "probe:unknown-precond")):
        try:
            model_fn(probe_kw[0], probe_kw[1], 1)
        except KeyError:
            continue
        except Exception as e:                          # noqa: BLE001
            out.append(Finding(
                rule="cost-model-completeness", loc=loc,
                message=f"unknown name raised {type(e).__name__} "
                        "instead of KeyError — consumers catch KeyError "
                        "as the 'table out of sync' signal and must not "
                        "confuse it with an internal failure"))
            continue
        out.append(Finding(
            rule="cost-model-completeness", loc=loc,
            message="cost model silently accepted an unknown "
                    f"{'variant' if 'variant' in loc else 'precond'} "
                    "name — an out-of-sync name table would stamp "
                    "fabricated predictions instead of failing loudly"))
    return out


# ----------------------------------------------------------------------
# partition-key-components (ISSUE 14): the shard-addressed partition
# cache's structural key components must each move the digest alone.
# ----------------------------------------------------------------------

def check_partition_key_components(shard_key_fn=None,
                                   glue_key_fn=None) -> List[Finding]:
    """Every structural component of the shard-addressed partition keys
    (cache/keys.py) must bite on its own — above all ``part_idx``: two
    parts of one partition colliding on one entry would hand a process
    another process's rows on warm start.  The glue key must differ
    from every part key (distinct ``kind``), and an out-of-range
    part_idx must KeyError (a key for a part that cannot exist would
    cache an unreachable entry).  ``shard_key_fn``/``glue_key_fn`` are
    seeded-violation test hooks."""
    from pcg_mpi_solver_tpu.cache import keys as ckeys

    shard_key_fn = shard_key_fn or ckeys.partition_shard_key
    glue_key_fn = glue_key_fn or ckeys.partition_glue_key

    def k(**over):
        kw = dict(n_parts=8, part_idx=0, backend="general",
                  dtype="float64", method="rcb", elem_part_hash=None,
                  pad_multiple=8, extra={})
        kw.update(over)
        return shard_key_fn("<model_fp>", **kw)

    base = k()
    out: List[Finding] = []
    for name, over in (("part_idx", {"part_idx": 3}),
                       ("n_parts", {"n_parts": 4, "part_idx": 0}),
                       ("backend", {"backend": "structured"}),
                       ("dtype", {"dtype": "float32"}),
                       ("method", {"method": "slab2"}),
                       ("elem_part_hash", {"elem_part_hash": "abc"}),
                       ("pad_multiple", {"pad_multiple": 16}),
                       ("extra", {"extra": {"slab2_slabs": 4}})):
        if k(**over) == base:
            out.append(Finding(
                rule="partition-key-components",
                loc=f"field:partition_shard_key.{name}",
                message=f"structural component {name!r} does not change "
                        "the partition shard key — entries of different "
                        "shape would collide; a warm start could hand a "
                        "process another shard's rows"))
    glue = glue_key_fn("<model_fp>", n_parts=8, backend="general",
                       dtype="float64", method="rcb")
    if glue == base:
        out.append(Finding(
            rule="partition-key-components",
            loc="field:partition_glue_key.kind",
            message="the glue key collides with a part entry key — the "
                    "glue must carry its own structural kind"))
    try:
        k(part_idx=99)
    except KeyError:
        pass
    else:
        out.append(Finding(
            rule="partition-key-components",
            loc="probe:part_idx-range",
            message="partition_shard_key accepted part_idx outside "
                    "[0, n_parts) — a key for a part that cannot exist "
                    "caches an unreachable entry instead of failing "
                    "loudly"))
    return out


@rule("partition-key-components", kind="config", fast=True,
      doc="every structural component of the shard-addressed partition "
          "cache keys (part_idx/n_parts/backend/dtype/method/"
          "elem_part_hash/pad_multiple/extra) moves the digest alone, "
          "the glue key is kind-distinct, and out-of-range part_idx "
          "raises KeyError")
def partition_key_components_rule(ctx) -> List[Finding]:
    return check_partition_key_components()


@rule("cost-model-completeness", kind="config", fast=True,
      doc="the analytic per-iteration cost model (obs/perf.py) covers "
          "every config.PCG_VARIANTS x config.PRECONDS x nrhs "
          "combination with a positive all-phase prediction, and "
          "unknown names raise KeyError (never a silent default row)")
def cost_model_completeness_rule(ctx) -> List[Finding]:
    return check_cost_model_completeness()


@rule("fingerprint-completeness", kind="config", fast=False,
      doc="every trace-affecting SolverConfig/RunConfig field appears in "
          "both cache/keys.step_cache_key and the snapshot _fingerprint "
          "(perturb-and-retrace proof; new fields must classify)")
def fingerprint_completeness_rule(ctx) -> List[Finding]:
    return (check_structural_key_components()
            + check_runconfig_classified()
            + check_fingerprint_completeness())
