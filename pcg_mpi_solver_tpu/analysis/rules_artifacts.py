"""Artifact rules: committed JSON artifacts validate against the
versioned contracts in ``obs/schema.py``.

``telemetry-schema`` covers the files ``tools/check_telemetry_schema.py``
(now a thin shim over this module) historically linted:

* ``*.jsonl``          — telemetry event streams (``--telemetry-out``)
* ``BENCH_*.json``     — bench round artifacts (raw line or round
                         wrapper; failed-round wrappers with
                         ``parsed: null`` pass)
* ``bench_*.json``     — provisional/salvage side files from bench.py

Import-light on purpose (obs/schema.py is jax/numpy-free): this runs in
the --fast gate.
"""

from __future__ import annotations

import glob
import json
import os
from typing import List

from pcg_mpi_solver_tpu.analysis.engine import REPO, Finding, rule


def default_paths() -> list:
    """The committed artifacts the tier-1 check covers."""
    return sorted(glob.glob(os.path.join(REPO, "BENCH_*.json")))


def check_file(path: str) -> list:
    """Validate one artifact; returns error strings prefixed with path."""
    from pcg_mpi_solver_tpu.obs.schema import (
        validate_bench_text, validate_jsonl_text)

    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError as e:
        return [f"{path}: unreadable ({e})"]
    name = os.path.basename(path)
    if name.endswith(".jsonl"):
        errs = validate_jsonl_text(text)
    elif name.endswith(".json"):
        if name.startswith("bench_salvage"):
            # salvage wrapper: {"lines": [{"line": <bench json str>}]}
            errs = []
            try:
                doc = json.loads(text)
            except ValueError as e:
                errs = [f"not JSON ({e})"]
            else:
                for i, entry in enumerate(doc.get("lines", [])):
                    errs.extend(
                        f"lines[{i}]: {e}"
                        for e in validate_bench_text(entry.get("line", "")))
        else:
            errs = validate_bench_text(text)
    else:
        errs = ["unrecognized artifact type (expected .json/.jsonl)"]
    return [f"{path}: {e}" for e in errs]


@rule("telemetry-schema", kind="artifact", fast=True,
      doc="committed BENCH_*.json artifacts (and any telemetry JSONL) "
          "validate against the versioned obs/schema.py contracts")
def telemetry_schema_rule(ctx) -> List[Finding]:
    findings = []
    for p in default_paths():
        for err in check_file(p):
            loc, _, msg = err.partition(": ")
            findings.append(Finding(
                rule="telemetry-schema",
                loc=os.path.relpath(loc, REPO),
                message=msg or err))
    return findings


# -- doc-schema sync (ISSUE 16) -----------------------------------------

EVENT_TABLE_DOC = os.path.join("docs", "OBSERVABILITY.md")


def documented_event_kinds(doc_text: str) -> set:
    """Event kinds documented in OBSERVABILITY.md's event table: the
    first backticked token of each table row (``| `kind` | ... |``)."""
    import re

    kinds = set()
    for line in doc_text.splitlines():
        m = re.match(r"^\|\s*`([a-z0-9_]+)`\s*\|", line)
        if m:
            kinds.add(m.group(1))
    return kinds


def check_doc_schema_sync(doc_text: str, kinds=None) -> List[str]:
    """Every event kind in obs/schema.py EVENT_KINDS must have a row in
    the doc's event table — an event a consumer cannot look up is
    undocumented telemetry.  Returns one error string per missing kind
    (testable directly on synthetic doc text)."""
    if kinds is None:
        from pcg_mpi_solver_tpu.obs.schema import EVENT_KINDS

        kinds = EVENT_KINDS
    documented = documented_event_kinds(doc_text)
    return [f"event kind `{k}` (obs/schema.py EVENT_KINDS) has no row "
            f"in the event table"
            for k in kinds if k not in documented]


@rule("doc-schema-sync", kind="artifact", fast=True,
      doc="every event kind in obs/schema.py EVENT_KINDS has a row in "
          "docs/OBSERVABILITY.md's event table (schema without doc is "
          "telemetry nobody can read back)")
def doc_schema_sync_rule(ctx) -> List[Finding]:
    path = os.path.join(REPO, EVENT_TABLE_DOC)
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError as e:
        return [Finding(rule="doc-schema-sync", loc=EVENT_TABLE_DOC,
                        message=f"unreadable ({e})")]
    return [Finding(rule="doc-schema-sync", loc=EVENT_TABLE_DOC,
                    message=msg)
            for msg in check_doc_schema_sync(text)]
