"""``python -m pcg_mpi_solver_tpu.analysis`` — the contract-lint CLI.

Exit codes: 0 = clean (baselined findings allowed), 1 = findings,
2 = a rule or the engine crashed.  ``pcg-tpu lint`` is the same runner
behind the package CLI.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def setup_cpu_env() -> None:
    """Pin the lint to the CPU backend BEFORE jax initializes: static
    analysis must never touch (or wait on) an accelerator grant, and the
    traced matrix needs a multi-device host platform.  No-ops when the
    operator already configured the env (or jax is loaded — pytest's
    conftest rig).  Also drops any inherited persistent-compile-cache
    dir: jax 0.4.x CPU executables crash on cache round-trips
    (cache/aot.py documents the same gate)."""
    from pcg_mpi_solver_tpu.utils.backend_probe import (
        backend_live, pin_cpu_backend_if_requested)

    if backend_live():
        return   # too late to (and no need to) reconfigure: test rig
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("JAX_ENABLE_X64", "1")
    if "cpu" in os.environ["JAX_PLATFORMS"]:
        os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    pin_cpu_backend_if_requested()
    if "jax" in sys.modules:
        # jax may already be imported (the package pin does so under
        # JAX_PLATFORMS=cpu); x64 and the compile-cache dir are config
        # flags, not import-frozen — jax binds the env vars at import,
        # so clearing the environment alone would not stick (the same
        # authoritative-config move bench.py makes, in reverse)
        import jax

        jax.config.update("jax_enable_x64", True)
        if "cpu" in os.environ["JAX_PLATFORMS"]:
            jax.config.update("jax_compilation_cache_dir", None)


def add_lint_args(ap) -> None:
    """The ONE definition of the lint option surface, shared by this
    module's parser and the ``pcg-tpu lint`` subcommand (cli.py) so the
    two documented-as-identical entry points cannot drift."""
    ap.add_argument("--fast", action="store_true",
                    help="pre-hardware-window gate: source/artifact rules "
                         "plus the collective/purity proofs on the "
                         "reduced program matrix (distributed backend; "
                         "skips donation + fingerprint sweeps)")
    ap.add_argument("--json", metavar="FILE", default=None,
                    help="write the machine-readable report here "
                         "('-' = stdout)")
    ap.add_argument("--baseline", metavar="FILE", default=None,
                    help="suppression file (default: the checked-in "
                         "analysis/baseline.json, which ships EMPTY); "
                         "entries need a documented reason")
    ap.add_argument("--rules", default=None, metavar="ID[,ID...]",
                    help="run only these rule ids")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")


def build_parser(prog: str = "pcg_mpi_solver_tpu.analysis"):
    ap = argparse.ArgumentParser(
        prog=prog,
        description="statically prove the solver's performance/resilience "
                    "invariants (collective budgets, hot-loop purity, "
                    "dtype discipline, donation aliasing, cache-key/"
                    "fingerprint completeness, source/artifact lints) — "
                    "see docs/ANALYSIS.md for the rule catalog")
    add_lint_args(ap)
    return ap


def run(args) -> int:
    from pcg_mpi_solver_tpu.analysis import engine

    if args.list_rules:
        for r in engine.list_rules():
            tag = "fast" if r.fast else "full"
            print(f"{r.id:26s} [{r.kind}/{tag}] {r.doc}")
        return 0
    baseline = args.baseline if args.baseline is not None \
        else engine.DEFAULT_BASELINE
    rule_ids = ([s for s in args.rules.split(",") if s]
                if args.rules else None)
    try:
        report = engine.run_lint(fast=args.fast, rule_ids=rule_ids,
                                 baseline_path=baseline)
    except ValueError as e:           # unknown rule id / bad baseline
        print(f"pcg-tpu lint: {e}", file=sys.stderr)
        return 2
    if args.json:
        blob = json.dumps(report.to_dict(), indent=2, sort_keys=True)
        if args.json == "-":
            print(blob)
        else:
            try:
                with open(args.json, "w", encoding="utf-8") as f:
                    f.write(blob + "\n")
            except OSError as e:
                # an unwritable report path is an ENGINE failure (exit
                # 2), not a lint verdict — exit 1 must keep meaning
                # "findings" for CI/hw_session wrappers
                print(report.render())
                print(f"pcg-tpu lint: cannot write --json {args.json}: "
                      f"{e}", file=sys.stderr)
                return 2
    if args.json != "-":
        print(report.render())
    return report.exit_code


def main(argv=None) -> int:
    setup_cpu_env()
    return run(build_parser().parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
