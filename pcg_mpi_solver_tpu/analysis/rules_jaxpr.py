"""Jaxpr-level rules over the canonical traced-program matrix.

Each rule's checker is exposed as a ``check_*`` function taking one
:class:`~pcg_mpi_solver_tpu.analysis.programs.Program` (or donation
surface), so the seeded-violation tests can feed deliberately-bad
synthetic programs through EXACTLY the code the registered rule runs.

This module stays import-light: jax (via analysis.programs) loads only
when a rule executes.
"""

from __future__ import annotations

from typing import List

from pcg_mpi_solver_tpu.analysis.engine import Finding, rule


# ---------------------------------------------------------------------------
# collective-budget: the loop body runs EXACTLY the declared collectives
# ---------------------------------------------------------------------------

def check_collective_budget(prog) -> List[Finding]:
    """The traced while-body collective histogram must EQUAL the budget
    the ops declared (Ops.body_collective_budget — the same table the
    comm.* telemetry gauges advertise).  Exactly one collective-bearing
    loop body per canonical program; extra primitives, extra counts AND
    under-counts all fail (an under-count means the declaration is stale
    — the gauges would be advertising collectives that do not exist)."""
    from pcg_mpi_solver_tpu.analysis import jaxpr_utils as ju

    hists = [h for h in ju.body_collective_histograms(prog.jaxpr) if h]
    loc = f"program:{prog.name}"
    if len(hists) != 1:
        return [Finding(
            rule="collective-budget", loc=loc,
            message=f"expected exactly one collective-bearing while body,"
                    f" found {len(hists)} (histograms: {hists}) — the "
                    "canonical program shape changed; re-derive the "
                    "budget declarations")]
    got = hists[0]
    want = {k: v for k, v in prog.collective_budget.items() if v}
    if got != want:
        return [Finding(
            rule="collective-budget", loc=loc,
            message=f"loop-body collectives {got} != declared budget "
                    f"{want} (Ops.body_collective_budget / comm.* "
                    "gauges): a re-serialized reduction or an undeclared "
                    "collective is in the hot body")]
    return []


@rule("collective-budget", kind="jaxpr", fast=True,
      doc="traced PCG loop-body psum/ppermute counts equal the budgets "
          "declared next to Ops.comm_estimate, for every variant x nrhs "
          "x backend program")
def collective_budget_rule(ctx) -> List[Finding]:
    out = []
    for prog in ctx.programs():
        out.extend(check_collective_budget(prog))
    return out


# ---------------------------------------------------------------------------
# psum-overlap: the pipelined body's reduction really is overlappable
# ---------------------------------------------------------------------------

def check_psum_overlap(prog) -> List[Finding]:
    """The latency-hiding claim of ``pcg_variant="pipelined"``
    (ISSUE 11), proven chipless: in the traced while-loop body, the
    variant's single fused scalar reduction must be data-INDEPENDENT of
    every other collective — it neither transitively consumes the
    stencil matvec's interface psum / halo ppermute outputs (the fused
    variant's serialization: mu = <z, A.z> reads the matvec) nor feeds
    them (the classic variant's serialization: beta -> p -> matvec) —
    so the lowered program's scheduler is free to run the reduction
    concurrently with the stencil.  XLA lowering never ADDS a data
    dependence, so jaxpr-level independence holds for the compiled
    executable (the runtime twin is the PR-1 profiler span overlap on
    hardware).

    Classic and fused programs are the rule's NEGATIVE CONTROLS: every
    collective in their bodies is serialized against at least one
    other, so a walker that lost dependency edges (and would vacuously
    "prove" overlap) fails loudly here first."""
    from pcg_mpi_solver_tpu.analysis import jaxpr_utils as ju

    loc = f"program:{prog.name}"
    bodies = [ju.while_body(e) for e in ju.while_eqns(prog.jaxpr.jaxpr)]
    bodies = [b for b in bodies if ju.collective_histogram(b)]
    if len(bodies) != 1:
        return [Finding(
            rule="psum-overlap", loc=loc,
            message=f"expected exactly one collective-bearing while "
                    f"body, found {len(bodies)} — the canonical program "
                    "shape changed; re-derive the overlap contract")]
    indep = [r for r in ju.independent_collectives(bodies[0])
             if r["primitive"] == "psum"]
    if prog.variant == "pipelined":
        if len(indep) != 1:
            got = [(r["primitive"], r["out_size"])
                   for r in ju.collective_dependencies(bodies[0])]
            return [Finding(
                rule="psum-overlap", loc=loc,
                message=f"the pipelined body must carry exactly ONE "
                        f"fully data-independent psum (its fused scalar "
                        f"reduction, overlappable with the stencil "
                        f"matvec); found {len(indep)} — the reduction "
                        "got serialized against another collective and "
                        "the latency-hiding claim no longer holds "
                        f"(body collectives: {got})")]
        # the independent psum must be the small stacked scalar
        # reduction (6 reduced scalars x nrhs), not a stencil payload
        # that accidentally lost its consumers
        limit = 16 * max(int(prog.nrhs), 1)
        if indep[0]["out_size"] > limit:
            return [Finding(
                rule="psum-overlap", loc=loc,
                message=f"the body's independent psum has payload size "
                        f"{indep[0]['out_size']} (> {limit}): that is a "
                        "vector collective, not the pipelined scalar "
                        "reduction — the dependency structure changed")]
    elif indep:
        return [Finding(
            rule="psum-overlap", loc=loc,
            message=f"{len(indep)} fully data-independent psum(s) in a "
                    f"{prog.variant} body — every classic/fused "
                    "collective is serialized against the stencil by "
                    "construction, so this means the dependency walker "
                    "lost edges (and the pipelined overlap proof would "
                    "be vacuous)")]
    return []


@rule("psum-overlap", kind="jaxpr", fast=False,
      doc="the pipelined variant's single fused psum is data-independent "
          "of the stencil matvec in BOTH directions in the traced loop "
          "body (latency-hiding proven chipless); classic/fused bodies "
          "prove fully serialized, as negative controls")
def psum_overlap_rule(ctx) -> List[Finding]:
    out = []
    for prog in ctx.programs():
        out.extend(check_psum_overlap(prog))
    return out


# ---------------------------------------------------------------------------
# scope-labels: the trace-attribution named scopes exist in every hot loop
# ---------------------------------------------------------------------------

def check_scope_labels(prog, phase_scopes=None) -> List[Finding]:
    """Every phase label ``obs/profview.py`` buckets trace events on
    (PHASE_SCOPES: pcg/matvec, pcg/precond, pcg/reduce, pcg/axpy) must
    appear in the traced program of EVERY variant, scalar AND blocked —
    a loop body that lost its ``jax.named_scope`` would silently move
    its device-op time into the report's 'other' bucket and the
    hardware attribution table would stop explaining the iteration.
    ``phase_scopes`` is the seeded-violation test hook."""
    from pcg_mpi_solver_tpu.analysis import jaxpr_utils as ju
    from pcg_mpi_solver_tpu.obs.profview import PHASE_SCOPES

    scopes = phase_scopes if phase_scopes is not None else PHASE_SCOPES
    found = ju.scope_labels(prog.jaxpr)
    out = []
    for label in scopes:
        if not found.get(label):
            out.append(Finding(
                rule="scope-labels", loc=f"program:{prog.name}",
                message=f"named-scope label {label!r} is absent from "
                        "the traced program: its phase's device-op "
                        "time would bucket as 'other' in every parsed "
                        "trace (obs/profview.py) — re-thread "
                        "jax.named_scope through the loop body "
                        f"(labels found: {sorted(found)})"))
    return out


def check_unknown_label_loudness(bucket_fn=None) -> List[Finding]:
    """The parser-side half of the contract: a device op matching NO
    phase must be COUNTED (other_events/other_ms), and a ``pcg/<x>``
    label outside the known four must land in ``unknown_scopes`` on
    BOTH arrival paths — TPU event-text metadata AND the CPU sidecar
    scope map — never silently dropped.  Probed on synthetic events
    through the REAL bucketing code (``bucket_fn`` is the
    seeded-violation hook)."""
    from pcg_mpi_solver_tpu.obs import profview

    fn = bucket_fn if bucket_fn is not None else profview.bucket_phases
    ops = [
        {"name": "mystery_fusion.9", "base": "mystery_fusion",
         "ts": 0.0, "dur": 5.0, "pid": 1, "tid": 1, "text": ""},
        {"name": "dot.1", "base": "dot", "ts": 10.0, "dur": 7.0,
         "pid": 1, "tid": 1, "text": "jit(f)/pcg/notaphase/dot_general"},
        # the CPU flavor: a bare instruction name whose ONLY route to a
        # label is the compiled-HLO sidecar map
        {"name": "ghost.1", "base": "ghost", "ts": 20.0, "dur": 3.0,
         "pid": 1, "tid": 1, "text": ""},
    ]
    smap = profview.scope_map_from_hlo_text(
        '%ghost.1 = f32[2]{0} add(...), '
        'metadata={op_name="jit(f)/pcg/ghostphase/add"}')
    out = []
    try:
        b = fn(list(ops), smap)
    except Exception as e:                              # noqa: BLE001
        return [Finding(
            rule="scope-labels", loc="probe:unknown-label",
            message=f"bucket_phases crashed on an unbucketable event "
                    f"({type(e).__name__}: {e}) — the tolerant-parse "
                    "contract demands counting, not crashing")]
    total_bucketed = sum(d["us"] for d in b["phases"].values()) \
        + b["other_us"]
    if b["other_events"] < 1 or total_bucketed < 15.0 - 1e-9:
        out.append(Finding(
            rule="scope-labels", loc="probe:unknown-label",
            message=f"bucket_phases DROPPED unbucketable device-op "
                    f"time (other_events={b['other_events']}, "
                    f"bucketed {total_bucketed} of 15.0 us): time that "
                    "matches no phase must be counted and reported, "
                    "never vanish from the attribution table"))
    if (b["unknown_scopes"].get("notaphase", 0) != 1
            or b["unknown_scopes"].get("ghostphase", 0) != 1):
        out.append(Finding(
            rule="scope-labels", loc="probe:unknown-label",
            message="a pcg/<x> label outside the known phase set was "
                    f"not counted into unknown_scopes (got "
                    f"{b['unknown_scopes']}; expected notaphase=1 via "
                    "event text AND ghostphase=1 via the sidecar scope "
                    "map) — a future phase label would silently "
                    "disappear from parsed traces instead of being "
                    "reported as unknown"))
    return out


@rule("scope-labels", kind="jaxpr", fast=True,
      doc="every pcg/* named-scope label the trace consumer "
          "(obs/profview.py) buckets on appears in the traced hot loop "
          "of every variant (scalar + blocked), and the parser counts "
          "+ reports unknown labels instead of dropping them")
def scope_labels_rule(ctx) -> List[Finding]:
    out = []
    for prog in ctx.programs():
        out.extend(check_scope_labels(prog))
    out.extend(check_unknown_label_loudness())
    return out


# ---------------------------------------------------------------------------
# hot-loop-purity: no host callbacks, no oversized folded constants
# ---------------------------------------------------------------------------

def check_hot_loop_purity(prog, threshold_elems=None) -> List[Finding]:
    from pcg_mpi_solver_tpu.analysis import jaxpr_utils as ju
    from pcg_mpi_solver_tpu.analysis.programs import (
        CALLBACK_PRIMITIVES, LOOP_CONST_THRESHOLD_ELEMS)

    if threshold_elems is None:
        threshold_elems = LOOP_CONST_THRESHOLD_ELEMS
    loc = f"program:{prog.name}"
    out = []
    hits = ju.loop_body_primitives(prog.jaxpr, CALLBACK_PRIMITIVES)
    if hits:
        out.append(Finding(
            rule="hot-loop-purity", loc=loc,
            message=f"callback primitive(s) {hits} inside a while-loop "
                    "body: every Krylov iteration would round-trip to "
                    "the host"))
    for c in ju.oversized_loop_consts(prog.jaxpr, threshold_elems):
        out.append(Finding(
            rule="hot-loop-purity", loc=loc,
            message=f"folded constant {c['dtype']}{list(c['shape'])} "
                    f"({c['size']} elems > {threshold_elems}) feeds the "
                    "while loop: a trace-time-captured operand array "
                    "bloats every AOT export (pass it as a program "
                    "argument instead)"))
    return out


@rule("hot-loop-purity", kind="jaxpr", fast=True,
      doc="no pure_callback/io_callback/debug_callback primitives and no "
          "folded constants above the size threshold inside any traced "
          "while-loop body")
def hot_loop_purity_rule(ctx) -> List[Finding]:
    out = []
    for prog in ctx.programs():
        out.extend(check_hot_loop_purity(prog))
    return out


# ---------------------------------------------------------------------------
# dtype-discipline: f32 programs stay f32
# ---------------------------------------------------------------------------

def check_dtype_discipline(prog) -> List[Finding]:
    """No f64 avals anywhere in an f32-role program (weak-typed scalar
    literals exempt — see jaxpr_utils.dtype_violations).  The mixed
    escalation engine's explicitly-f64 refinement programs are role
    'f64' and out of scope by construction."""
    from pcg_mpi_solver_tpu.analysis import jaxpr_utils as ju

    if prog.role != "f32":
        return []
    leaks = ju.dtype_violations(prog.jaxpr, "float64")
    if not leaks:
        return []
    prims = sorted({d["primitive"] for d in leaks})
    sample = leaks[0]
    return [Finding(
        rule="dtype-discipline", loc=f"program:{prog.name}",
        message=f"{len(leaks)} float64 operand(s)/result(s) in an f32 "
                f"step program (primitives {prims}; e.g. "
                f"{sample['primitive']} on {sample['aval']}): an f64 "
                "leak silently halves MXU throughput and doubles psum "
                "payloads")]


@rule("dtype-discipline", kind="jaxpr", fast=True,
      doc="no f64 avals leak into the all-f32 step programs (weak scalar "
          "literals exempt; the escalation engine's f64 programs are out "
          "of scope)")
def dtype_discipline_rule(ctx) -> List[Finding]:
    out = []
    for prog in ctx.programs():
        out.extend(check_dtype_discipline(prog))
    return out


# ---------------------------------------------------------------------------
# donation-integrity: donate_carry surfaces really alias
# ---------------------------------------------------------------------------

@rule("donation-integrity", kind="jaxpr", fast=False,
      doc="every donate_carry dispatch surface produces input/output "
          "buffer aliasing in the lowered+compiled executable (jax drops "
          "unusable donations SILENTLY — the copy shows up only as HBM "
          "and latency)")
def donation_integrity_rule(ctx) -> List[Finding]:
    from pcg_mpi_solver_tpu.analysis import programs as ap

    out = []
    for surface in ap.donation_surfaces():
        for err in ap.check_donation(surface):
            out.append(Finding(rule="donation-integrity",
                               loc=f"surface:{surface.name}",
                               message=err))
    return out
