"""Contract-lint rule engine: registry, findings, baseline, reports.

The analysis/ subsystem statically proves the framework's structural
claims — collective counts, hot-loop purity, dtype discipline, donation
aliasing, cache-key/fingerprint completeness — plus the source/artifact
lints that used to live as disconnected scripts under tools/.  This
module is the jax-free core: rules declare themselves into ``RULES`` via
the :func:`rule` decorator; jaxpr-level rules import jax lazily inside
their run function, so ``import pcg_mpi_solver_tpu.analysis`` configures
nothing and touches no accelerator (the same contract as the package
``__init__``).

Severity model: every violated invariant is an ``error`` (exit 1);
``warn`` findings are reported but do not fail the lint.  A checked-in
baseline file (``analysis/baseline.json``) suppresses known, documented
findings by exact (rule, loc) match — the shipped baseline is EMPTY and
should stay so; suppressions are for incident triage, not steady state.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
import traceback
from typing import Callable, Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: shipped (empty) baseline — the --baseline default.
DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")

BASELINE_SCHEMA = "pcg-tpu-lint-baseline/1"
REPORT_SCHEMA = "pcg-tpu-lint-report/1"


@dataclasses.dataclass
class Finding:
    """One rule violation.  ``loc`` is the stable anchor used for
    baseline matching: ``path:line`` for source rules, ``program:<name>``
    / ``surface:<name>`` / ``field:<name>`` for traced-program rules."""

    rule: str
    loc: str
    message: str
    severity: str = "error"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def __str__(self) -> str:
        return f"[{self.rule}] {self.loc}: {self.message}"


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    kind: str          # "ast" | "artifact" | "jaxpr" | "config"
    fast: bool         # included in --fast (pre-hardware-window gate)
    doc: str
    fn: Callable[["Context"], List[Finding]]


RULES: Dict[str, Rule] = {}


def rule(rule_id: str, *, kind: str, fast: bool, doc: str):
    """Register a rule.  ``fn(ctx) -> [Finding]``; raise nothing — an
    exception is converted into an engine-error finding by the runner."""
    def deco(fn):
        RULES[rule_id] = Rule(rule_id, kind, fast, doc, fn)
        return fn
    return deco


class Context:
    """Per-run context handed to every rule: mode flags plus the lazily
    built (and cached) canonical program matrix."""

    def __init__(self, fast: bool = False):
        self.fast = bool(fast)
        self.repo = REPO

    def programs(self):
        from pcg_mpi_solver_tpu.analysis import programs as _p

        return _p.build_programs(fast=self.fast)


@dataclasses.dataclass
class Report:
    findings: List[Finding]
    suppressed: List[Finding]
    rules_run: List[str]
    errors: List[str]
    fast: bool
    wall_s: float

    @property
    def clean(self) -> bool:
        return not self.errors and not any(
            f.severity == "error" for f in self.findings)

    @property
    def exit_code(self) -> int:
        if self.errors:
            return 2
        return 0 if self.clean else 1

    def to_dict(self) -> dict:
        return {
            "schema": REPORT_SCHEMA,
            "fast": self.fast,
            "clean": self.clean,
            "rules_run": list(self.rules_run),
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [f.to_dict() for f in self.suppressed],
            "errors": list(self.errors),
            "wall_s": round(self.wall_s, 3),
        }

    def render(self) -> str:
        lines = []
        for f in self.findings:
            lines.append(str(f))
        for f in self.suppressed:
            lines.append(f"(baselined) {f}")
        for e in self.errors:
            lines.append(f"[engine-error] {e}")
        n_err = sum(1 for f in self.findings if f.severity == "error")
        mode = "fast" if self.fast else "full"
        lines.append(
            f"pcg-tpu lint ({mode}): {len(self.rules_run)} rule(s), "
            f"{n_err} error(s), {len(self.suppressed)} baselined, "
            f"{len(self.errors)} engine error(s) "
            f"[{self.wall_s:.1f}s]")
        return "\n".join(lines)


def load_baseline(path: Optional[str]) -> List[dict]:
    """Suppression entries from a baseline file; missing file => empty.
    Entry shape: {"rule": id, "loc": anchor, "reason": why} — reason is
    mandatory, an undocumented suppression is itself a finding."""
    if not path or not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if doc.get("schema") != BASELINE_SCHEMA:
        raise ValueError(f"{path}: expected baseline schema "
                         f"{BASELINE_SCHEMA!r}, got {doc.get('schema')!r}")
    return list(doc.get("suppressions", []))


def apply_baseline(findings: List[Finding], entries: List[dict],
                   ) -> tuple:
    """(active, suppressed): exact (rule, loc) match suppresses; entries
    without a reason are converted into findings so the baseline cannot
    silently grow undocumented, and entries matching NO current finding
    surface as warn findings — a stale suppression would otherwise mask
    the same defect if it ever regressed at that anchor."""
    keys = {(e.get("rule"), e.get("loc")) for e in entries
            if e.get("reason")}
    active, suppressed, hit = [], [], set()
    for f in findings:
        if (f.rule, f.loc) in keys:
            suppressed.append(f)
            hit.add((f.rule, f.loc))
        else:
            active.append(f)
    for e in entries:
        if not e.get("reason"):
            active.append(Finding(
                rule="baseline", loc=str(e.get("loc")),
                message=f"baseline suppression for rule "
                        f"{e.get('rule')!r} carries no reason — document "
                        "it or delete it"))
        elif (e.get("rule"), e.get("loc")) not in hit:
            active.append(Finding(
                rule="baseline", loc=str(e.get("loc")), severity="warn",
                message=f"stale suppression: rule {e.get('rule')!r} no "
                        "longer reports here — delete the entry so a "
                        "future regression at this anchor is not "
                        "silently masked"))
    return active, suppressed


def _ensure_rules_registered() -> None:
    # import for the registration side effect; all four modules are
    # import-light (jax only inside rule bodies)
    from pcg_mpi_solver_tpu.analysis import (  # noqa: F401
        rules_artifacts, rules_ast, rules_config, rules_jaxpr)


def run_lint(fast: bool = False, rule_ids: Optional[List[str]] = None,
             baseline_path: Optional[str] = DEFAULT_BASELINE) -> Report:
    """Run the registered rules and return a :class:`Report`.

    ``fast`` runs the pre-hardware-window subset (source/artifact rules
    plus the collective/purity proofs on the reduced program matrix);
    ``rule_ids`` restricts to specific rules (unknown id => ValueError).
    """
    _ensure_rules_registered()
    t0 = time.monotonic()
    if rule_ids:
        unknown = [r for r in rule_ids if r not in RULES]
        if unknown:
            raise ValueError(f"unknown rule id(s) {unknown}; have "
                             f"{sorted(RULES)}")
        selected = [RULES[r] for r in rule_ids]
    else:
        selected = [r for r in RULES.values() if r.fast or not fast]
    ctx = Context(fast=fast)
    findings: List[Finding] = []
    errors: List[str] = []
    rules_run: List[str] = []
    for r in sorted(selected, key=lambda r: (r.kind, r.id)):
        try:
            findings.extend(r.fn(ctx))
            rules_run.append(r.id)
        except Exception:  # noqa: BLE001 - reported as an engine error
            errors.append(f"rule {r.id} crashed:\n"
                          f"{traceback.format_exc()}")
    entries = load_baseline(baseline_path)
    active, suppressed = apply_baseline(findings, entries)
    return Report(findings=active, suppressed=suppressed,
                  rules_run=rules_run, errors=errors, fast=fast,
                  wall_s=time.monotonic() - t0)


def list_rules() -> List[Rule]:
    _ensure_rules_registered()
    return sorted(RULES.values(), key=lambda r: (r.kind, r.id))
