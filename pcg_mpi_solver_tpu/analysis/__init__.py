"""analysis/ — jaxpr-level contract lint for the solver's structural
claims (ISSUE 7).

The framework's headline claims are *structural* facts about traced
programs — "one fused psum per iteration" (PR 5), "collective count
independent of nrhs" (PR 6), "zero retraces on warm runs" (PR 2) — and
its resilience posture depends on completeness facts about config
surfaces (cache keys, snapshot fingerprints).  This package proves them
statically, in seconds on CPU, instead of burning a hardware window:

* ``engine``          — rule registry, findings, baseline, reports
* ``rules_jaxpr``     — collective-budget, hot-loop-purity,
                        dtype-discipline, donation-integrity
* ``rules_config``    — fingerprint-completeness (perturb-and-retrace)
* ``rules_ast``       — recovery-paths (broad-except lint)
* ``rules_artifacts`` — telemetry-schema (committed artifact lint)
* ``programs``        — the canonical traced-program matrix
* ``collectives``     — back-compat tools/check_collectives.py API

Entry points: ``pcg-tpu lint`` and ``python -m
pcg_mpi_solver_tpu.analysis`` (``--fast``/``--json``/``--baseline``).

Import contract: importing this package (like the repo root package)
must NOT import jax — bench.py and the CLI configure the accelerator
environment after importing library modules, and the lint itself must be
constructible before deciding to pin the CPU backend.  jax loads lazily,
only when a jaxpr-level rule actually executes.
"""

from pcg_mpi_solver_tpu.analysis.engine import (
    DEFAULT_BASELINE, Finding, Report, Rule, RULES, list_rules, run_lint)

__all__ = [
    "DEFAULT_BASELINE",
    "Finding",
    "Report",
    "Rule",
    "RULES",
    "list_rules",
    "run_lint",
]
