"""Back-compat collective-count proof API (tools/check_collectives.py).

The full per-program proof now lives in the analysis/ collective-budget
rule (rules_jaxpr.py) over the canonical program matrix; this module
keeps the historical standalone API — ``EXPECTED_BODY_PSUMS``,
``iteration_psum_count``, ``run_checks`` — that tools/ and
tests/test_collectives.py consume, tracing the bare ``pcg``/``pcg_many``
loop directly on a 2-part mesh.  The documented counts are now DERIVED
from the declarations next to ``Ops.comm_estimate``
(ops/matvec.py PCG_SCALAR_PSUMS / PCG_DEFERRED_CHECK_PSUMS), so the
gauges, this check and the rule engine all read one table.

This module imports jax at load; callers own the backend env (the
tools/ shim pins CPU + an 8-device host platform before importing).
"""

from __future__ import annotations

from pcg_mpi_solver_tpu.analysis.jaxpr_utils import count_primitive
from pcg_mpi_solver_tpu.ops.matvec import (
    PCG_DEFERRED_CHECK_PSUMS, PCG_SCALAR_PSUMS)

# Documented while-body psum counts on a 2-part GENERAL partition (the
# interface-assembly psum is present; both conditional branches of the
# body, including the deferred mode-1 true-residual check, are part of
# the traced body jaxpr): classic 3+1+1 = 5, fused 1+1+1 = 3,
# pipelined 1+1+1 = 3 (same count as fused — its win is the psum's
# data-independence from the stencil, proven by the psum-overlap rule).
EXPECTED_BODY_PSUMS = {
    variant: scalar + 1 + PCG_DEFERRED_CHECK_PSUMS
    for variant, scalar in PCG_SCALAR_PSUMS.items()
}


def count_psums(jaxpr) -> int:
    """Recursive ``psum`` primitive count of a jaxpr (into conds etc.)."""
    return count_primitive(jaxpr, "psum")


def _while_bodies(jaxpr, out):
    from pcg_mpi_solver_tpu.analysis.jaxpr_utils import (
        sub_jaxprs, while_body)

    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "while":
            out.append(while_body(eqn))
        for j in sub_jaxprs(eqn):
            _while_bodies(j, out)
    return out


def iteration_psum_count(variant: str, nrhs: int = 1) -> int:
    """Psum count of the traced PCG while-loop body for ``variant`` on a
    2-part partition (so the interface-assembly psum exists).  With
    ``nrhs`` > 1 the BATCHED body (``pcg_many``) is traced instead —
    the documented counts must hold unchanged (payloads widen with the
    block, the collective count must not)."""
    import jax
    import jax.numpy as jnp

    from pcg_mpi_solver_tpu.models.synthetic import make_cube_model
    from pcg_mpi_solver_tpu.ops.matvec import Ops, device_data
    from pcg_mpi_solver_tpu.parallel.mesh import PARTS_AXIS, make_mesh
    from pcg_mpi_solver_tpu.parallel.partition import partition_model
    from pcg_mpi_solver_tpu.solver.driver import _data_specs
    from pcg_mpi_solver_tpu.solver.pcg import pcg, pcg_many

    model = make_cube_model(3, 3, 3)
    pm = partition_model(model, 2)
    if pm.n_iface == 0:
        raise RuntimeError("2-part partition produced no interface dofs; "
                           "the documented counts assume the iface psum")
    ops = Ops.from_model(pm, dot_dtype=jnp.float64, axis_name=PARTS_AXIS)
    data = device_data(pm, jnp.float64)
    mesh = make_mesh(2)
    P = jax.sharding.PartitionSpec(PARTS_AXIS)

    def step(data, fext, x0, inv_diag):
        solve = pcg_many if nrhs > 1 else pcg
        res = solve(ops, data, fext, x0, inv_diag, tol=1e-8, max_iter=50,
                    glob_n_dof_eff=pm.glob_n_dof_eff, variant=variant)
        return res.x

    fn = jax.shard_map(step, mesh=mesh,
                       in_specs=(_data_specs(data), P, P, P),
                       out_specs=P, check_vma=False)
    shape = ((pm.n_parts, pm.n_loc, nrhs) if nrhs > 1
             else (pm.n_parts, pm.n_loc))
    vec = jnp.zeros(shape, jnp.float64)
    inv = jnp.zeros((pm.n_parts, pm.n_loc), jnp.float64)
    jaxpr = jax.make_jaxpr(fn)(data, vec, vec, inv)
    bodies = _while_bodies(jaxpr.jaxpr, [])
    counts = [count_psums(b) for b in bodies]
    hits = [c for c in counts if c > 0]
    if len(hits) != 1:
        raise RuntimeError(
            f"expected exactly one psum-bearing while body for "
            f"variant={variant!r} nrhs={nrhs}, found counts {counts}")
    return hits[0]


def run_checks(nrhs_batched: int = 8) -> list:
    """Returns a list of error strings (empty = counts hold).  Checks
    both the single-RHS bodies and the batched bodies at
    ``nrhs_batched`` columns: the counts must be equal — psum count
    independent of the RHS-block width."""
    errs = []
    counts = {}
    for variant, want in EXPECTED_BODY_PSUMS.items():
        got = counts[variant] = iteration_psum_count(variant)
        if got != want:
            errs.append(f"{variant}: {got} psums in the loop body, "
                        f"documented count is {want}")
        got_b = iteration_psum_count(variant, nrhs=nrhs_batched)
        if got_b != want:
            errs.append(f"{variant} batched (nrhs={nrhs_batched}): "
                        f"{got_b} psums in the loop body, must equal the "
                        f"nrhs=1 count {want}")
    if not errs and counts["fused"] != counts["classic"] - 2:
        errs.append(f"fused must save exactly the two serialized scalar "
                    f"reductions: classic={counts['classic']} "
                    f"fused={counts['fused']}")
    return errs
