"""Canonical traced-program matrix for the jaxpr-level contract rules.

Builds REAL solvers (solver/driver.py) on a small 2-device virtual CPU
mesh and traces the programs they would dispatch — the same loop bodies,
carry pytrees and donation wiring the flagship runs, at toy scale — so
the lint proves invariants of the actual code paths rather than of a
hand-mirrored copy that could drift.

The matrix (ISSUE 7): every ``pcg_variant`` x nrhs in {1, 8} x
{distributed ("general"), structured} backend, all direct-f64 (the
reference-parity numerics), plus one all-f32 direct program per backend
for the dtype-discipline rule.  ``fast=True`` reduces to the distributed
backend (both variants, both widths, plus its f32 program) — the
structural headline claims — for the sub-minute pre-hardware-window
gate.

This module imports jax at module load; it must only be imported from
rule execution paths (the analysis package ``__init__`` stays jax-free).
Callers are responsible for the backend environment (the CLI entry
points pin JAX_PLATFORMS=cpu before any jax import; under pytest the
repo conftest does).
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Any, Dict, List, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from pcg_mpi_solver_tpu.config import RunConfig, SolverConfig

#: folded-constant size (elements) above which a while-loop operand is a
#: hot-loop-purity violation: a captured operand array this big bloats
#: every AOT export and defeats the donated-carry aliasing.
LOOP_CONST_THRESHOLD_ELEMS = 4096

#: callback primitives that must never appear inside a hot loop body —
#: each one forces a host round-trip per iteration.
CALLBACK_PRIMITIVES = ("pure_callback", "io_callback", "debug_callback",
                       "outside_call", "host_callback")


@dataclasses.dataclass
class Program:
    """One traced canonical program plus its declared contracts."""

    name: str                     # e.g. "step[general,fused,nrhs=8,f64]"
    backend: str                  # "general" | "structured"
    variant: str                  # SolverConfig.pcg_variant
    nrhs: int
    role: str                     # "f64" | "f32" (dtype-discipline scope)
    jaxpr: Any                    # ClosedJaxpr of the dispatched program
    collective_budget: Dict[str, int]   # declared while-body budget
    n_iface: int


@dataclasses.dataclass
class DonationSurface:
    """One donating dispatch surface: the jitted program, example
    (abstract) arguments, and the pytree donated to XLA."""

    name: str
    fn: Any                       # the jitted callable (donation baked in)
    args: Tuple[Any, ...]         # concrete or ShapeDtypeStruct args
    donated: Any                  # the donated argument's pytree

    @property
    def donated_leaves(self) -> List[Any]:
        return jax.tree.leaves(self.donated)

    @property
    def vector_leaves(self) -> int:
        """Donated leaves of rank >= 2 — the partitioned (P, n_loc[,
        nrhs]) Krylov vectors whose in-place aliasing IS the donation
        contract.  Rank-0/1 leaves (per-column stats, budget counters)
        are exempt: copying a handful of scalars per dispatch is free,
        and write-only counters like the carry's ``exec`` leaf have a
        legally-dead input that jax prunes from the executable."""
        return sum(1 for l in self.donated_leaves
                   if len(getattr(l, "shape", ())) >= 2)


_MODEL_CACHE: dict = {}
_MATRIX_CACHE: Dict[bool, List[Program]] = {}


def _model(backend: str, nx: int = 0):
    """Small synthetic cube per backend: the structured slab path needs
    grid[0] divisible by n_parts (driver.py can_structured); mg
    programs need even dims (one 2:1 coarsening) and pass ``nx=4``."""
    from pcg_mpi_solver_tpu.models.synthetic import make_cube_model

    nx = nx or (4 if backend == "structured" else 3)
    if (backend, nx) not in _MODEL_CACHE:
        _MODEL_CACHE[(backend, nx)] = make_cube_model(nx, nx, nx)
    return _MODEL_CACHE[(backend, nx)]


def _mesh2():
    from pcg_mpi_solver_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 2:
        raise RuntimeError(
            "the contract lint traces 2-part SPMD programs; run with "
            "JAX_PLATFORMS=cpu and "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8 "
            "(the CLI entry points set this up)")
    return make_mesh(2)


def build_solver(backend: str = "general", nx: int = 0,
                 **solver_overrides):
    """A real quasi-static Solver on the 2-device mesh.  One-shot
    dispatch (iters_per_dispatch=0) unless overridden, so ``_step_fn``
    is the single canonical program.  ``nx`` overrides the model size
    (mg programs need an even, coarsenable lattice)."""
    from pcg_mpi_solver_tpu.solver.driver import Solver

    kw = dict(iters_per_dispatch=0)
    kw.update(solver_overrides)
    cfg = RunConfig(solver=SolverConfig(**kw))
    return Solver(_model(backend, nx), cfg, mesh=_mesh2(), n_parts=2,
                  backend=backend)


def step_jaxpr(solver):
    """ClosedJaxpr of the one-shot quasi-static step program."""
    delta = jnp.asarray(1.0, solver.dtype)
    return jax.make_jaxpr(solver._step_fn)(solver.data, solver.un, delta)


def many_jaxpr(solver, nrhs: int):
    """ClosedJaxpr of the one-shot blocked (solve_many) program."""
    progs = solver._ensure_many_programs(nrhs)
    rdt = jnp.float64 if solver.mixed else solver.dtype
    fb = jax.ShapeDtypeStruct((solver.pm.n_parts, solver.pm.n_loc, nrhs),
                              rdt)
    data_abs = jax.eval_shape(lambda d: d, solver.data)
    return jax.make_jaxpr(progs["solve"])(data_abs, fb)


def program_signature(solver) -> str:
    """Content digest of the traced one-shot step: jaxpr text plus every
    folded constant's bytes (a config knob that only changes a baked
    array would not show in the pretty-printed text).  The
    fingerprint-completeness rule compares these across config
    perturbations."""
    jx = step_jaxpr(solver)
    h = hashlib.sha256(str(jx).encode())
    for c in jx.consts:
        a = np.asarray(c)
        h.update(f"{a.shape}:{a.dtype}".encode())
        h.update(a.tobytes())
    return h.hexdigest()


def build_programs(fast: bool = False) -> List[Program]:
    """The canonical matrix, cached per process (tracing only — nothing
    executes).  Full: 3 variants x nrhs {1,8} x 2 backends + one all-f32
    program per backend + the mg programs (20 traces, ~3 s).  Fast: the
    distributed backend, classic+fused only (incl. its f32 program, so
    every fast-tier rule has a non-vacuous surface)."""
    if fast in _MATRIX_CACHE:
        return _MATRIX_CACHE[fast]
    out: List[Program] = []
    backends = ("general",) if fast else ("general", "structured")
    # the full matrix carries all three loop formulations (the
    # psum-overlap rule needs the pipelined programs AND the
    # classic/fused negative controls); --fast keeps the pre-ISSUE-11
    # pair so the hardware-queue gate stays ~1s
    variants = (("classic", "fused") if fast
                else ("classic", "fused", "pipelined"))
    for backend in backends:
        for variant in variants:
            s = build_solver(backend, pcg_variant=variant)
            budget = s.ops.body_collective_budget(variant)
            for nrhs in (1, 8):
                jx = step_jaxpr(s) if nrhs == 1 else many_jaxpr(s, nrhs)
                out.append(Program(
                    name=(f"step[{backend},{variant},nrhs={nrhs},f64]"),
                    backend=backend, variant=variant, nrhs=nrhs,
                    role="f64", jaxpr=jx, collective_budget=budget,
                    n_iface=int(s.ops.n_iface)))
        s32 = build_solver(backend, dtype="float32", dot_dtype="float32")
        out.append(Program(
            name=f"step[{backend},classic,nrhs=1,f32]",
            backend=backend, variant="classic", nrhs=1, role="f32",
            jaxpr=step_jaxpr(s32),
            collective_budget=s32.ops.body_collective_budget("classic"),
            n_iface=int(s32.ops.n_iface)))
    if not fast:
        # MG-preconditioned programs (ISSUE 10): both variants x nrhs
        # {1, 8} on the general backend (the acceptance matrix — psum
        # budget gains 2*degree matvec assemblies + the restriction),
        # plus classic x {1, 8} on structured (ppermute accounting:
        # halo count x fine matvecs).  --fast stays general+jacobi
        # only: the mg traces add seconds the pre-window gate spends
        # elsewhere.
        mg_matrix = ([("general", v) for v in ("classic", "fused")]
                     + [("structured", "classic")])
        for backend, variant in mg_matrix:
            s = build_solver(backend, nx=4, precond="mg",
                             pcg_variant=variant)
            budget = s.ops.body_collective_budget(variant, precond="mg")
            for nrhs in (1, 8):
                jx = step_jaxpr(s) if nrhs == 1 else many_jaxpr(s, nrhs)
                out.append(Program(
                    name=f"step[{backend},{variant},mg,nrhs={nrhs},f64]",
                    backend=backend, variant=variant, nrhs=nrhs,
                    role="f64", jaxpr=jx, collective_budget=budget,
                    n_iface=int(s.ops.n_iface)))
    _MATRIX_CACHE[fast] = out
    return out


# ---------------------------------------------------------------------------
# Donation surfaces (donation-integrity rule): every donate_carry
# dispatch surface of the real drivers, with example abstract arguments
# derived by eval_shape-chaining the surface's own upstream programs —
# no hand-built carry pytrees that could drift from the real schema.
# ---------------------------------------------------------------------------

def donation_surfaces() -> List[DonationSurface]:
    surfaces: List[DonationSurface] = []
    budget = jax.ShapeDtypeStruct((), jnp.int64)

    # 1. one-shot step: donated previous-solution vector (driver.py)
    s1 = build_solver("general")
    delta = jnp.asarray(1.0, s1.dtype)
    surfaces.append(DonationSurface(
        "one-shot step (donated un_prev)", s1._step_fn,
        (s1.data, s1.un, delta), s1.un))

    # 2./3. chunked direct dispatch: donated resumable Krylov carry,
    # scalar and blocked (chunked.py _cycle / driver.py many "cycle")
    s2 = build_solver("general", iters_per_dispatch=5)
    d2 = jnp.asarray(1.0, s2.dtype)
    udi = jax.eval_shape(s2._start_pre_fn, s2.data, d2)
    kudi = jax.eval_shape(s2._amul64_fn, s2.data, udi)
    fext, x0 = jax.eval_shape(s2._start_mid_fn, s2.data, s2.un, d2, kudi)
    kx0 = jax.eval_shape(s2._amul64_fn, s2.data, x0)
    carry, _normr0, _n2b, prec = jax.eval_shape(
        s2._start_post_fn, s2.data, fext, x0, kx0)
    surfaces.append(DonationSurface(
        "chunked direct cycle (donated carry)", s2._engine._cycle_fn,
        (s2.data, fext, prec, carry, budget), carry))

    many = s2._ensure_many_programs(4)
    fb = jax.ShapeDtypeStruct((s2.pm.n_parts, s2.pm.n_loc, 4), s2.dtype)
    mfext, mcarry, _mn, mprec = jax.eval_shape(many["start"], s2.data, fb)
    surfaces.append(DonationSurface(
        "chunked blocked cycle (donated blocked carry)", many["cycle"],
        (s2.data, mfext, mprec, mcarry, budget), mcarry))

    # 4./5. mixed engine: donated f32 inner carry + donated f64 iterate
    # across the refine step (chunked.py)
    s3 = build_solver("general", precision_mode="mixed",
                      iters_per_dispatch=5)
    eng = s3._engine
    r = jax.ShapeDtypeStruct((s3.pm.n_parts, s3.pm.n_loc), jnp.float64)
    sc = jax.ShapeDtypeStruct((), jnp.float64)
    rhat32, tol_cycle, carry32 = jax.eval_shape(
        eng._inner_start_fn, s3.data, r, sc, sc)
    prec32 = jax.ShapeDtypeStruct((s3.pm.n_parts, s3.pm.n_loc),
                                  jnp.float32)
    surfaces.append(DonationSurface(
        "mixed inner cycle (donated f32 carry)", eng._inner_cycle_fn,
        (s3.data, rhat32, prec32, tol_cycle, carry32, budget), carry32))
    xinc32 = jax.ShapeDtypeStruct((s3.pm.n_parts, s3.pm.n_loc),
                                  jnp.float32)
    if getattr(eng, "_refine_pre_fn", None) is not None:
        surfaces.append(DonationSurface(
            "mixed refine (donated f64 iterate)", eng._refine_pre_fn,
            (r, xinc32, sc), r))
    else:
        surfaces.append(DonationSurface(
            "mixed refine (donated f64 iterate)", eng._refine_fn,
            (s3.data, r, r, xinc32, sc), r))
    return surfaces


import re as _re


def _donor_vector_marks(lowered_text: str) -> int:
    """Donor/alias-marked entry arguments of rank >= 2 in the lowered
    StableHLO signature (rank from the tensor<AxBx..> dims prefix)."""
    m = _re.search(r"func\.func public @main\((.*?)\)\s*->", lowered_text,
                   _re.S)
    if m is None:
        return 0
    n = 0
    for arg in m.group(1).split("%arg"):
        tm = _re.search(r"tensor<((?:\d+x)+)\d*[a-z]", arg)
        if tm is None:
            continue
        rank = tm.group(1).count("x")
        if rank >= 2 and ("jax.buffer_donor" in arg
                          or "tf.aliasing_output" in arg):
            n += 1
    return n


def check_donation(surface: DonationSurface) -> List[str]:
    """Errors for one surface: the lowering must donor-mark every
    rank>=2 donated buffer (jax drops an unusable donation SILENTLY —
    no matching output means the dispatch copies instead of aliasing),
    and the COMPILED executable must carry at least one input/output
    alias pair per donated vector leaf."""
    lowered = surface.fn.lower(*surface.args)
    marked = _donor_vector_marks(lowered.as_text())
    want = surface.vector_leaves
    errs = []
    if marked < want:
        errs.append(
            f"{surface.name}: lowering donor-marks only {marked} of "
            f"{want} donated vector (rank>=2) leaves — donation was "
            "dropped (no matching output: the dispatch copies instead "
            "of aliasing)")
        return errs
    hlo = lowered.compile().as_text()
    pairs = hlo.count("may-alias") + hlo.count("must-alias")
    if pairs < want:
        errs.append(
            f"{surface.name}: compiled executable aliases only {pairs} "
            f"buffer(s) for {want} donated vector leaves — XLA did not "
            "honor the donation (silent copy per dispatch)")
    return errs
