"""Jaxpr traversal helpers for the contract-lint rules (analysis/).

Pure structural walkers over already-traced jaxpr objects — duck-typed
(``eqn.primitive.name`` / ``eqn.params`` / ``aval.dtype``) so this module
imports neither jax nor the solver stack; the tracing itself lives in
:mod:`pcg_mpi_solver_tpu.analysis.programs`.  The one convention baked in
here: higher-order primitives carry their sub-programs as (Closed)Jaxpr
values inside ``eqn.params`` (``while`` -> cond/body, ``cond`` ->
branches, ``pjit``/``shard_map``/``custom_*`` -> the inner program), and
a ClosedJaxpr unwraps via its ``.jaxpr`` attribute.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Tuple

import numpy as np


def sub_jaxprs(eqn) -> List[Any]:
    """Nested (unwrapped) jaxprs of one equation's params."""
    out = []
    for v in eqn.params.values():
        for item in (v if isinstance(v, (list, tuple)) else [v]):
            j = getattr(item, "jaxpr", item)
            if hasattr(j, "eqns"):
                out.append(j)
    return out


def iter_eqns(jaxpr) -> Iterator[Any]:
    """All equations of ``jaxpr``, recursing into every nested program."""
    for eqn in jaxpr.eqns:
        yield eqn
        for j in sub_jaxprs(eqn):
            yield from iter_eqns(j)


def count_primitive(jaxpr, name: str) -> int:
    return sum(1 for eqn in iter_eqns(jaxpr) if eqn.primitive.name == name)


def collective_histogram(jaxpr, names=("psum", "ppermute", "all_gather",
                                       "all_to_all", "pmax", "pmin")) -> dict:
    """{primitive name: count} over ``jaxpr`` for the collective
    primitives in ``names`` (zero counts omitted)."""
    hist: Dict[str, int] = {}
    for eqn in iter_eqns(jaxpr):
        n = eqn.primitive.name
        if n in names:
            hist[n] = hist.get(n, 0) + 1
    return hist


def while_eqns(jaxpr) -> List[Any]:
    return [e for e in iter_eqns(jaxpr) if e.primitive.name == "while"]


def while_body(eqn):
    """The (unwrapped) body jaxpr of one ``while`` equation."""
    return eqn.params["body_jaxpr"].jaxpr


def body_collective_histograms(closed_jaxpr) -> List[dict]:
    """Collective histogram of every while-loop body in a traced program
    (the hot-loop contract surface), outermost-first."""
    return [collective_histogram(while_body(e))
            for e in while_eqns(closed_jaxpr.jaxpr)]


# ---------------------------------------------------------------------------
# Constant tracking: jax hoists trace-time (host-folded) array constants
# out of loop bodies — a big np array captured by a while body shows up as
# a constvar of some enclosing program, threaded positionally through
# pjit/shard_map call boundaries into the while equation's invars.  To
# prove "no folded constant above N elements feeds the hot loop", walk
# with an env mapping vars -> known constant values and resolve each
# while eqn's invars against it.
# ---------------------------------------------------------------------------

def _const_size(c) -> int:
    try:
        return int(np.asarray(c).size)
    except Exception:  # noqa: BLE001 - unsizeable const: treat as scalar
        return 1


def while_captured_consts(closed_jaxpr) -> List[Tuple[Any, Any]]:
    """(while_eqn, const_value) pairs for every while-equation operand
    that resolves to a trace-time constant, across all nesting levels."""
    found: List[Tuple[Any, Any]] = []

    def walk(jaxpr, env: Dict[int, Any]):
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "while":
                for v in eqn.invars:
                    if id(v) in env:
                        found.append((eqn, env[id(v)]))
            for item in eqn.params.values():
                for sub in (item if isinstance(item, (list, tuple))
                            else [item]):
                    inner = getattr(sub, "jaxpr", sub)
                    if not hasattr(inner, "eqns"):
                        continue
                    sub_env = dict(env)
                    # a ClosedJaxpr contributes its own consts
                    consts = getattr(sub, "consts", None)
                    if consts is not None:
                        for cv, c in zip(inner.constvars, consts):
                            sub_env[id(cv)] = c
                    # positional remap across the call boundary
                    # (pjit/shard_map-style: eqn invars <-> inner invars)
                    if len(inner.invars) == len(eqn.invars):
                        for outer, innerv in zip(eqn.invars, inner.invars):
                            if id(outer) in env:
                                sub_env[id(innerv)] = env[id(outer)]
                    walk(inner, sub_env)

    env0: Dict[int, Any] = {}
    for cv, c in zip(closed_jaxpr.jaxpr.constvars, closed_jaxpr.consts):
        env0[id(cv)] = c
    walk(closed_jaxpr.jaxpr, env0)
    return found


def oversized_loop_consts(closed_jaxpr, threshold_elems: int) -> List[dict]:
    """Folded constants above ``threshold_elems`` elements feeding a
    while loop: each entry carries the element count and dtype/shape
    labels for the finding message."""
    out = []
    for _eqn, c in while_captured_consts(closed_jaxpr):
        n = _const_size(c)
        if n > threshold_elems:
            arr = np.asarray(c)
            out.append({"size": n, "shape": tuple(arr.shape),
                        "dtype": str(arr.dtype)})
    return out


# ---------------------------------------------------------------------------
# Collective dependency analysis (the psum-overlap rule): flatten one
# while-loop body — inlining nested programs (cond branches, pjit calls)
# via positional operand mapping — and compute, per collective primitive
# occurrence, the set of OTHER collective occurrences whose outputs it
# transitively consumes.  Two collectives with no path between them in
# either direction are data-independent: the scheduler is free to run
# them (and the compute between them) concurrently, which is exactly the
# latency-hiding property the pipelined PCG body claims.  Lowering can
# only preserve or relax this structure (XLA never invents a data
# dependence), so independence proven on the jaxpr holds for the
# compiled program.
# ---------------------------------------------------------------------------

_EMPTY = frozenset()


def _sub_invar_deps(eqn, sub, in_deps):
    """Dependency sets for a nested jaxpr's invars, mapped positionally
    from the enclosing equation's operands: 1:1 for call-like primitives
    (pjit, custom_*), offset-1 for cond (invars = [index] + operands),
    conservative all-operands union otherwise."""
    n_outer, n_inner = len(eqn.invars), len(sub.invars)
    if n_inner == n_outer:
        pairs = list(zip(sub.invars, in_deps))
    elif n_inner == n_outer - 1 and eqn.primitive.name == "cond":
        pairs = list(zip(sub.invars, in_deps[1:]))
    else:
        union = frozenset().union(*in_deps) if in_deps else _EMPTY
        pairs = [(v, union) for v in sub.invars]
    env = {id(v): d for v, d in pairs}
    for cv in getattr(sub, "constvars", ()):
        # host constants carry no runtime dependency
        env.setdefault(id(cv), _EMPTY)
    return env


def collective_dependencies(jaxpr, names=("psum", "ppermute", "all_gather",
                                          "all_to_all", "pmax", "pmin")
                            ) -> List[dict]:
    """One record per collective occurrence in ``jaxpr`` (recursively,
    program order): ``{"id", "primitive", "out_size", "depends_on"}``
    where ``depends_on`` is the frozenset of earlier records' ids whose
    outputs this occurrence transitively consumes.  Loop-bearing nested
    programs (while/scan inside the analyzed body) are handled
    CONSERVATIVELY: their loop feedback can wire anything to anything
    across trips, so every collective found inside one is marked
    dependent on ITSELF (its own prior-trip occurrence) and on every
    other collective of the same nested loop — over-approximating
    dependence, never under-approximating it (the safe direction for
    an independence proof; a lone psum inside a nested loop must not
    read as overlappable)."""
    records: List[dict] = []

    def walk(jaxpr, env, loop_depth=0):
        def dep_of(v):
            return env.get(id(v), _EMPTY)

        for eqn in jaxpr.eqns:
            in_deps = [dep_of(v) for v in eqn.invars]
            base = frozenset().union(*in_deps) if in_deps else _EMPTY
            name = eqn.primitive.name
            subs = sub_jaxprs(eqn)
            if name in names:
                rid = len(records)
                aval = getattr(eqn.outvars[0], "aval", None)
                size = 1
                for d in getattr(aval, "shape", ()) or ():
                    size *= int(d)
                if loop_depth > 0:
                    # inside a nested while/scan: the collective's
                    # prior-trip occurrence can feed this one through
                    # loop carry, so it is SELF-dependent — even when
                    # it is the only collective in the nested loop
                    # (the `inner` mutual marking below is vacuous for
                    # a singleton)
                    base = base | {rid}
                records.append({"id": rid, "primitive": name,
                                "out_size": size, "depends_on": base})
                out_dep = base | {rid}
                for v in eqn.outvars:
                    env[id(v)] = out_dep
                continue
            if subs:
                looping = name in ("while", "scan")
                out_union = base
                per_pos = None
                inner_ids = []
                for sub in subs:
                    sub_env = _sub_invar_deps(eqn, sub, in_deps)
                    before = len(records)
                    walk(sub, sub_env, loop_depth + (1 if looping else 0))
                    inner_ids.extend(range(before, len(records)))
                    outs = [sub_env.get(id(v), _EMPTY)
                            for v in sub.outvars]
                    out_union = out_union.union(*outs) if outs \
                        else out_union
                    if (per_pos is not None
                            and len(outs) == len(per_pos)):
                        per_pos = [a | b for a, b in zip(per_pos, outs)]
                    elif per_pos is None:
                        per_pos = outs
                    else:
                        per_pos = None
                if looping and inner_ids:
                    # loop feedback: mark the nested collectives mutually
                    # dependent (conservative), and the loop outputs
                    # dependent on all of them
                    inner = frozenset(inner_ids)
                    for rid in inner_ids:
                        records[rid]["depends_on"] = (
                            records[rid]["depends_on"] | (inner - {rid}))
                    out_union = out_union | inner
                    per_pos = None
                if per_pos is not None and len(per_pos) == len(eqn.outvars):
                    for v, d in zip(eqn.outvars, per_pos):
                        env[id(v)] = base | d
                else:
                    for v in eqn.outvars:
                        env[id(v)] = out_union
                continue
            for v in eqn.outvars:
                env[id(v)] = base

    walk(jaxpr, {}, 0)
    return records


def independent_collectives(jaxpr, names=("psum", "ppermute", "all_gather",
                                          "all_to_all", "pmax", "pmin")
                            ) -> List[dict]:
    """Records from :func:`collective_dependencies` that neither consume
    any other collective's output NOR are consumed by any other
    collective — the fully-overlappable ones.  An empty result means
    every collective in the body is serialized against at least one
    other (the classic/fused shape); the pipelined body must show
    exactly its one scalar reduction here."""
    recs = collective_dependencies(jaxpr, names)
    fed = {}
    for r in recs:
        for d in r["depends_on"]:
            fed.setdefault(d, set()).add(r["id"])
    return [r for r in recs
            if not r["depends_on"] and not fed.get(r["id"])]


def dtype_violations(closed_jaxpr, forbidden: str = "float64") -> List[dict]:
    """Equations whose operands/results carry ``forbidden``-dtype avals.

    Weak-typed SCALARS are exempt: under x64, python float literals enter
    the trace as ``float64 weak_type=True`` and immediately convert to
    the storage dtype — a lowering artifact, not a precision leak."""
    seen = []
    for eqn in iter_eqns(closed_jaxpr.jaxpr):
        if eqn.primitive.name == "pjit":
            continue  # the inner jaxpr is walked on its own
        for v in list(eqn.invars) + list(eqn.outvars):
            aval = getattr(v, "aval", None)
            dt = getattr(aval, "dtype", None)
            if dt is None or str(dt) != forbidden:
                continue
            if getattr(aval, "weak_type", False) and \
                    not getattr(aval, "shape", ()):
                continue
            seen.append({"primitive": eqn.primitive.name,
                         "aval": str(aval)})
    return seen


def find_primitives(closed_jaxpr, names) -> List[str]:
    """Names from ``names`` that occur anywhere in the program."""
    names = set(names)
    return sorted({e.primitive.name for e in iter_eqns(closed_jaxpr.jaxpr)
                   if e.primitive.name in names})


def loop_body_primitives(closed_jaxpr, names) -> List[str]:
    """Names from ``names`` that occur inside any while-loop body."""
    names = set(names)
    hits = set()
    for eqn in while_eqns(closed_jaxpr.jaxpr):
        body = while_body(eqn)
        for e in iter_eqns(body):
            if e.primitive.name in names:
                hits.add(e.primitive.name)
    return sorted(hits)


def scope_labels(closed_jaxpr, prefix: str = "pcg/") -> dict:
    """{label: eqn count} of every ``<prefix><word>`` jax.named_scope
    label in the program's equation name stacks, recursing into every
    nested sub-jaxpr (while bodies, cond branches, pjit calls).

    The name stack is trace-time metadata (``eqn.source_info``) — the
    same string that lands in the compiled module's ``op_name`` HLO
    metadata and, from there, in profiler-trace events; reading it here
    proves the scope-labels the trace consumer (obs/profview.py)
    buckets on actually exist in the traced hot loop.  An equation with
    no readable name stack simply contributes nothing (the walker is
    tolerant of jax-internal representation changes — the RULE then
    fails on a missing label, loudly, rather than crashing here)."""
    import re as _re

    pat = _re.compile(_re.escape(prefix) + r"([A-Za-z0-9_]+)")
    out: dict = {}
    for eqn in iter_eqns(closed_jaxpr.jaxpr):
        try:
            stack = str(eqn.source_info.name_stack)
        except Exception:                               # noqa: BLE001
            continue
        for m in pat.finditer(stack):
            label = prefix + m.group(1)
            out[label] = out.get(label, 0) + 1
    return out
