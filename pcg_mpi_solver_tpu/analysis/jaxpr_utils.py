"""Jaxpr traversal helpers for the contract-lint rules (analysis/).

Pure structural walkers over already-traced jaxpr objects — duck-typed
(``eqn.primitive.name`` / ``eqn.params`` / ``aval.dtype``) so this module
imports neither jax nor the solver stack; the tracing itself lives in
:mod:`pcg_mpi_solver_tpu.analysis.programs`.  The one convention baked in
here: higher-order primitives carry their sub-programs as (Closed)Jaxpr
values inside ``eqn.params`` (``while`` -> cond/body, ``cond`` ->
branches, ``pjit``/``shard_map``/``custom_*`` -> the inner program), and
a ClosedJaxpr unwraps via its ``.jaxpr`` attribute.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Tuple

import numpy as np


def sub_jaxprs(eqn) -> List[Any]:
    """Nested (unwrapped) jaxprs of one equation's params."""
    out = []
    for v in eqn.params.values():
        for item in (v if isinstance(v, (list, tuple)) else [v]):
            j = getattr(item, "jaxpr", item)
            if hasattr(j, "eqns"):
                out.append(j)
    return out


def iter_eqns(jaxpr) -> Iterator[Any]:
    """All equations of ``jaxpr``, recursing into every nested program."""
    for eqn in jaxpr.eqns:
        yield eqn
        for j in sub_jaxprs(eqn):
            yield from iter_eqns(j)


def count_primitive(jaxpr, name: str) -> int:
    return sum(1 for eqn in iter_eqns(jaxpr) if eqn.primitive.name == name)


def collective_histogram(jaxpr, names=("psum", "ppermute", "all_gather",
                                       "all_to_all", "pmax", "pmin")) -> dict:
    """{primitive name: count} over ``jaxpr`` for the collective
    primitives in ``names`` (zero counts omitted)."""
    hist: Dict[str, int] = {}
    for eqn in iter_eqns(jaxpr):
        n = eqn.primitive.name
        if n in names:
            hist[n] = hist.get(n, 0) + 1
    return hist


def while_eqns(jaxpr) -> List[Any]:
    return [e for e in iter_eqns(jaxpr) if e.primitive.name == "while"]


def while_body(eqn):
    """The (unwrapped) body jaxpr of one ``while`` equation."""
    return eqn.params["body_jaxpr"].jaxpr


def body_collective_histograms(closed_jaxpr) -> List[dict]:
    """Collective histogram of every while-loop body in a traced program
    (the hot-loop contract surface), outermost-first."""
    return [collective_histogram(while_body(e))
            for e in while_eqns(closed_jaxpr.jaxpr)]


# ---------------------------------------------------------------------------
# Constant tracking: jax hoists trace-time (host-folded) array constants
# out of loop bodies — a big np array captured by a while body shows up as
# a constvar of some enclosing program, threaded positionally through
# pjit/shard_map call boundaries into the while equation's invars.  To
# prove "no folded constant above N elements feeds the hot loop", walk
# with an env mapping vars -> known constant values and resolve each
# while eqn's invars against it.
# ---------------------------------------------------------------------------

def _const_size(c) -> int:
    try:
        return int(np.asarray(c).size)
    except Exception:  # noqa: BLE001 - unsizeable const: treat as scalar
        return 1


def while_captured_consts(closed_jaxpr) -> List[Tuple[Any, Any]]:
    """(while_eqn, const_value) pairs for every while-equation operand
    that resolves to a trace-time constant, across all nesting levels."""
    found: List[Tuple[Any, Any]] = []

    def walk(jaxpr, env: Dict[int, Any]):
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "while":
                for v in eqn.invars:
                    if id(v) in env:
                        found.append((eqn, env[id(v)]))
            for item in eqn.params.values():
                for sub in (item if isinstance(item, (list, tuple))
                            else [item]):
                    inner = getattr(sub, "jaxpr", sub)
                    if not hasattr(inner, "eqns"):
                        continue
                    sub_env = dict(env)
                    # a ClosedJaxpr contributes its own consts
                    consts = getattr(sub, "consts", None)
                    if consts is not None:
                        for cv, c in zip(inner.constvars, consts):
                            sub_env[id(cv)] = c
                    # positional remap across the call boundary
                    # (pjit/shard_map-style: eqn invars <-> inner invars)
                    if len(inner.invars) == len(eqn.invars):
                        for outer, innerv in zip(eqn.invars, inner.invars):
                            if id(outer) in env:
                                sub_env[id(innerv)] = env[id(outer)]
                    walk(inner, sub_env)

    env0: Dict[int, Any] = {}
    for cv, c in zip(closed_jaxpr.jaxpr.constvars, closed_jaxpr.consts):
        env0[id(cv)] = c
    walk(closed_jaxpr.jaxpr, env0)
    return found


def oversized_loop_consts(closed_jaxpr, threshold_elems: int) -> List[dict]:
    """Folded constants above ``threshold_elems`` elements feeding a
    while loop: each entry carries the element count and dtype/shape
    labels for the finding message."""
    out = []
    for _eqn, c in while_captured_consts(closed_jaxpr):
        n = _const_size(c)
        if n > threshold_elems:
            arr = np.asarray(c)
            out.append({"size": n, "shape": tuple(arr.shape),
                        "dtype": str(arr.dtype)})
    return out


def dtype_violations(closed_jaxpr, forbidden: str = "float64") -> List[dict]:
    """Equations whose operands/results carry ``forbidden``-dtype avals.

    Weak-typed SCALARS are exempt: under x64, python float literals enter
    the trace as ``float64 weak_type=True`` and immediately convert to
    the storage dtype — a lowering artifact, not a precision leak."""
    seen = []
    for eqn in iter_eqns(closed_jaxpr.jaxpr):
        if eqn.primitive.name == "pjit":
            continue  # the inner jaxpr is walked on its own
        for v in list(eqn.invars) + list(eqn.outvars):
            aval = getattr(v, "aval", None)
            dt = getattr(aval, "dtype", None)
            if dt is None or str(dt) != forbidden:
                continue
            if getattr(aval, "weak_type", False) and \
                    not getattr(aval, "shape", ()):
                continue
            seen.append({"primitive": eqn.primitive.name,
                         "aval": str(aval)})
    return seen


def find_primitives(closed_jaxpr, names) -> List[str]:
    """Names from ``names`` that occur anywhere in the program."""
    names = set(names)
    return sorted({e.primitive.name for e in iter_eqns(closed_jaxpr.jaxpr)
                   if e.primitive.name in names})


def loop_body_primitives(closed_jaxpr, names) -> List[str]:
    """Names from ``names`` that occur inside any while-loop body."""
    names = set(names)
    hits = set()
    for eqn in while_eqns(closed_jaxpr.jaxpr):
        body = while_body(eqn)
        for e in iter_eqns(body):
            if e.primitive.name in names:
                hits.add(e.primitive.name)
    return sorted(hits)
