"""Device-side operators: matrix-free K.p, Jacobi diagonal, weighted dots.

THE hot kernel of the framework (reference: calcMatVecProd,
pcg_solver.py:242-336).  TPU-native formulation:

- per pattern-type group: gather -> sign-flip -> one dense
  ``Ke @ (ck * u)`` einsum on the MXU -> sign-flip back
  (reference does np.dot per rank, pcg_solver.py:277-280);
- scatter-add: all groups' element vectors concatenated, permuted into
  sorted-by-dof order (permutation precomputed on host), one
  ``segment_sum(indices_are_sorted=True)`` (reference: np.bincount 'outbin'
  mode, pcg_solver.py:294-300);
- cross-part assembly of shared ("interface") dofs: scatter partial sums into
  a small global interface vector, ONE ``lax.psum`` over the mesh axis, gather
  back (replaces the reference's tagged Isend/Recv halo exchange,
  pcg_solver.py:317-334 — deterministic, rides ICI);
- weighted dots with fp64 accumulation and the fused 3-norm reduction
  (reference: pcg_solver.py:462-507).

All functions run inside ``shard_map`` over a 1-D device mesh; arrays carry a
leading local-parts axis so multiple mesh partitions can be stacked per
device.  With ``axis_name=None`` the same code runs unsharded (single-device).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from pcg_mpi_solver_tpu.config import PCG_VARIANTS
from pcg_mpi_solver_tpu.parallel.partition import PartitionedModel
from pcg_mpi_solver_tpu.utils.compat import ensure_shard_map

# jax < 0.5 compat: every jax-importing root module of the package
# installs the jax.shard_map alias (the package __init__ must stay
# jax-free for bench.py's env-ordering contract).
ensure_shard_map()

# ---------------------------------------------------------------------------
# Declared per-iteration collective contract of the PCG loop formulations
# (SolverConfig.pcg_variant).  ONE source of truth consumed by BOTH the
# telemetry gauges (Ops.comm_estimate below) and the static proof
# (analysis/ collective-budget rule + tools/check_collectives.py), so the
# advertised counts and the jaxpr-level verification can never diverge.
#
# * classic — MATLAB-compatible loop: three serialized scalar/fused psums
#   per iteration (rho+inf-prec, p.q, the fused 3-norm).
# * fused   — Chronopoulos–Gear recurrence: ONE fused psum carries all six
#   reduced scalars plus the inf-prec flag.
# * pipelined — Ghysels–Vanroose depth-1 pipelining: still ONE fused
#   psum, but its operands are all previous-iteration recurrence state
#   (r/u/w/p/x carry leaves), so the psum is data-INDEPENDENT of the
#   body's stencil matvec in both directions and the scheduler may
#   overlap them — the analysis/ psum-overlap rule proves that
#   independence statically, on top of this count.
#
# Changing a loop body (adding a pcg_variant) REQUIRES a row here: an
# unknown variant is a KeyError in both the gauges and the budget — the
# lint fails loudly instead of silently re-serializing.  The key set is
# pinned to the canonical config.PCG_VARIANTS name table (the single
# source the CLI/config/cache layers validate against) by the assert
# below: a variant added to one surface but not the other cannot import.
PCG_SCALAR_PSUMS = {"classic": 3, "fused": 1, "pipelined": 1}

if tuple(PCG_SCALAR_PSUMS) != PCG_VARIANTS:
    # an explicit raise, not `assert` — the guard must survive -O
    raise ImportError(
        "ops/matvec.PCG_SCALAR_PSUMS keys must match config.PCG_VARIANTS "
        "(the single-source variant name set): "
        f"{tuple(PCG_SCALAR_PSUMS)} != {PCG_VARIANTS}")

# The deferred mode-1 true-residual check lives INSIDE the traced while
# body (both branches of the conditional are part of the body jaxpr), and
# its recomputed residual norm costs one more psum on the trace — a
# healthy mode-0 trip never executes it, so it is budgeted separately
# from the per-iteration gauges.
PCG_DEFERRED_CHECK_PSUMS = 1

# Full-length vector UPDATES per committed iteration of each loop
# formulation (solver/pcg.py bodies) — the memory-bound axpy side of the
# per-iteration cost, consumed by the analytic cost model (obs/perf.py)
# next to the collective table above.  Counted from the loop bodies:
#
# * classic   — p = z + beta*p, x += alpha*p, r -= alpha*q        -> 3
# * fused     — p/q recurrences + x/r updates                     -> 4
# * pipelined — GV p/s/q/z recurrences + x/r/u/w updates          -> 8
#
# Same key-set pin as PCG_SCALAR_PSUMS: a new variant cannot land in one
# table without the other.
PCG_VECTOR_AXPYS = {"classic": 3, "fused": 4, "pipelined": 8}

if tuple(PCG_VECTOR_AXPYS) != PCG_VARIANTS:
    raise ImportError(
        "ops/matvec.PCG_VECTOR_AXPYS keys must match config.PCG_VARIANTS "
        "(the single-source variant name set): "
        f"{tuple(PCG_VECTOR_AXPYS)} != {PCG_VARIANTS}")

# ---------------------------------------------------------------------------
# Declared per-APPLY collective contract of the preconditioners
# (SolverConfig.precond), the same one-table discipline as
# PCG_SCALAR_PSUMS above: consumed by the telemetry gauges
# (Ops.comm_estimate), the static proof (analysis/ collective-budget
# rule via Ops.body_collective_budget), and docs.
#
# * jacobi / block3 — elementwise / small-matmul applies: zero
#   collectives of their own.
# * mg — one geometric V-cycle (ops/mg.py): 2*degree assembled
#   fine-level matvecs (degree-d Chebyshev pre-smoothing from zero =
#   d-1, the defect = 1, post-smoothing = d), each carrying exactly the
#   matvec's own interface collective, plus MG_RESTRICT_PSUMS to
#   assemble the restricted defect into the replicated coarse
#   hierarchy.  The smoother itself contributes ZERO collectives (fixed
#   Chebyshev polynomial, eigenvalue bounds precomputed at setup; the
#   whole coarse hierarchy is replicated) — every collective in the
#   cycle is matvec assembly or THE restriction.
#
# An unknown precond is a KeyError in both the gauges and the budget —
# the lint fails loudly instead of silently under-declaring.
MG_RESTRICT_PSUMS = 1
PRECOND_CYCLE_MATVECS = {"jacobi": 0, "block3": 0}


def precond_cycle_cost(precond: str, mg_degree: int = 2):
    """(extra assembled matvecs, extra standalone psums) per
    preconditioner APPLY.  Unknown precond = loud KeyError."""
    if precond == "mg":
        return 2 * int(mg_degree), MG_RESTRICT_PSUMS
    return PRECOND_CYCLE_MATVECS[precond], 0


def device_data(pm: PartitionedModel, dtype=jnp.float64,
                flat: Optional[bool] = None, blocks: bool = True) -> dict:
    """Pack a PartitionedModel into the device pytree the ops consume.

    All leaves have a leading parts axis P (shard it over the mesh), except
    the small per-type constant matrices (Ke etc.), which are replicated.
    ``flat`` controls whether the flat-scatter arrays (dof/scat_perm/
    scat_ids) are included; by default they are uploaded only when the
    node-ELL fast path is unavailable (they are dead weight otherwise).
    ``blocks=False`` skips the per-type block arrays (for consumers that
    bring their own operator structure, e.g. the bucketed refresh amul,
    but still need the assembly/weight/load leaves).
    """
    if flat is None:
        flat = pm.ell is None and blocks

    def _blk(tb):
        b = {
            "Ke": jnp.asarray(tb.Ke, dtype),
            "diag_Ke": jnp.asarray(tb.diag_Ke, dtype),
            "Se": jnp.asarray(tb.Se, dtype) if tb.Se is not None else None,
            "sign": jnp.asarray(tb.sign),
            "node": jnp.asarray(tb.node, jnp.int32),
            "ck": jnp.asarray(tb.ck, dtype),
            "ce": jnp.asarray(tb.ce, dtype),
        }
        if flat:
            b["dof"] = jnp.asarray(tb.dof, jnp.int32)
        if pm.ell is not None:
            # node-component layouts for the node-ELL fast path: the element
            # matmul runs directly on gathered (node, elem, comp) rows, so
            # no runtime relayout of the (..., 3)-minor arrays is needed.
            nn = tb.d // 3
            b["Ke4"] = jnp.asarray(tb.Ke.reshape(nn, 3, nn, 3), dtype)
            b["diag_Ke4"] = jnp.asarray(tb.diag_Ke.reshape(nn, 3), dtype)
            b["sign_nc"] = jnp.asarray(
                np.ascontiguousarray(
                    tb.sign.reshape(tb.sign.shape[0], nn, 3, -1)
                    .transpose(0, 1, 3, 2)))
        return b

    d = {
        "blocks": [_blk(tb) for tb in pm.type_blocks] if blocks else [],
        # the ELL scatter map is only consumed by the blocks path
        # (_scatter_rows); without blocks it would be ~1e8 int32 of dead
        # HBM at flagship scale
        "ell": (jnp.asarray(pm.ell, jnp.int32)
                if pm.ell is not None and blocks else None),
        "iface_local": jnp.asarray(pm.iface_local, jnp.int32),
        "iface_slot": jnp.asarray(pm.iface_slot, jnp.int32),
        "niface_local": jnp.asarray(pm.niface_local, jnp.int32),
        "niface_slot": jnp.asarray(pm.niface_slot, jnp.int32),
        "weight": jnp.asarray(pm.weight, dtype),
        "node_weight": jnp.asarray(pm.node_weight, dtype),
        "eff": jnp.asarray(pm.eff, dtype),
        "F": jnp.asarray(pm.F, dtype),
        "Ud": jnp.asarray(pm.Ud, dtype),
    }
    if flat:
        d["scat_perm"] = jnp.asarray(pm.scat_perm, jnp.int32)
        d["scat_ids"] = jnp.asarray(pm.scat_ids, jnp.int32)
    if pm.spr_a is not None:
        # cohesive interface springs (PartitionedModel spr_*)
        d["spr_a"] = jnp.asarray(pm.spr_a, jnp.int32)
        d["spr_b"] = jnp.asarray(pm.spr_b, jnp.int32)
        d["spr_k"] = jnp.asarray(pm.spr_k, dtype)
    return d


@dataclasses.dataclass(frozen=True)
class Ops:
    """Static-shape metadata + the operator methods.

    Construct once per partitioned model; methods are pure and traceable.
    ``axis_name`` is the mesh axis inside shard_map (None = unsharded).
    """

    n_loc: int
    n_iface: int
    n_node_loc: int = 0
    n_node_iface: int = 0
    dot_dtype: jnp.dtype = jnp.float64
    axis_name: Optional[str] = None
    # Node-ELL fast path: gather/scatter move (node, 3) ROWS instead of
    # scalar dofs — TPU row-gathers are ~an order of magnitude faster than
    # scalar gathers, and scatter-add becomes row-gather + row-sum over the
    # precomputed ELL map (PartitionedModel.ell).
    use_node_ell: bool = False
    # MXU precision for the element matmuls.  TPU 'default' runs f32 inputs
    # through low-precision bf16 passes, which caps the attainable PCG
    # residual far above tol; HIGHEST is fp32-true (6-pass bf16) and still
    # rides the MXU.
    precision: jax.lax.Precision = jax.lax.Precision.HIGHEST
    # Chebyshev smoothing degree of the MG V-cycle preconditioner
    # (SolverConfig.mg_smooth_degree, pinned here at solver construction
    # because it shapes the traced cycle: 2*degree fine matvecs per
    # apply — precond_cycle_cost above).  Unused unless the prec operand
    # is the mg dict (ops/mg.py).
    mg_degree: int = 2
    # Replicated first-coarse vector length (ops/mg.coarse_dofs — the
    # restriction psum's payload), pinned alongside mg_degree so
    # comm_estimate can report the V-cycle's full psum traffic.
    mg_coarse_dofs: int = 0

    @classmethod
    def from_model(cls, pm: PartitionedModel, dot_dtype=jnp.float64, axis_name=None,
                   precision=jax.lax.Precision.HIGHEST):
        return cls(n_loc=pm.n_loc, n_iface=pm.n_iface,
                   n_node_loc=pm.n_node_loc, n_node_iface=pm.n_node_iface,
                   dot_dtype=dot_dtype, axis_name=axis_name, precision=precision,
                   use_node_ell=pm.ell is not None)

    # -- collectives ----------------------------------------------------
    def _psum(self, x):
        if self.axis_name is None:
            return x
        return jax.lax.psum(x, self.axis_name)

    # -- interface assembly --------------------------------------------
    def _assemble_shared(self, y, local, slot, n_glob):
        """Sum partial values of ids shared by several parts: scatter into a
        global shared-id vector, ONE psum, gather back.  y: (P, n) or, with
        a trailing RHS-block axis, (P, n, R) — the psum payload widens to
        (n_glob, R) but the collective COUNT stays one either way (the
        batched-solve contract, tools/check_collectives.py)."""
        if y.ndim == 3:
            R = y.shape[-1]
            vals = jnp.take_along_axis(y, local[:, :, None], axis=1,
                                       mode="fill", fill_value=0)
            glob = jnp.zeros((n_glob, R), y.dtype)
            glob = glob.at[slot.reshape(-1)].add(
                vals.reshape(-1, R), mode="drop")
            glob = self._psum(glob)
            new = glob.at[slot].get(mode="fill", fill_value=0)
            return jax.vmap(
                lambda yp, loc, nv: yp.at[loc].set(nv, mode="drop"))(
                y, local, new)
        vals = jnp.take_along_axis(y, local, axis=1, mode="fill", fill_value=0)
        glob = jnp.zeros((n_glob,), y.dtype)
        glob = glob.at[slot.reshape(-1)].add(vals.reshape(-1), mode="drop")
        glob = self._psum(glob)
        new = glob.at[slot].get(mode="fill", fill_value=0)
        return jax.vmap(lambda yp, loc, nv: yp.at[loc].set(nv, mode="drop"))(
            y, local, new)

    def iface_assemble(self, data: dict, y: jnp.ndarray) -> jnp.ndarray:
        """Dof-space assembly: (P, n_loc) partial sums -> fully assembled."""
        if self.n_iface == 0:
            return y
        return self._assemble_shared(y, data["iface_local"],
                                     data["iface_slot"], self.n_iface)

    def niface_assemble(self, data: dict, y: jnp.ndarray) -> jnp.ndarray:
        """Node-space assembly for (P, k, n_node_loc) stacked channels
        (reference exchanges nodal sums+counts over neighbors,
        pcg_solver.py:689-723)."""
        if self.n_node_iface == 0:
            return y
        f = lambda yk: self._assemble_shared(
            yk, data["niface_local"], data["niface_slot"], self.n_node_iface)
        return jax.vmap(f, in_axes=1, out_axes=1)(y)

    # -- gather/scatter primitives (node-ELL fast path + flat fallback) --
    #
    # The parts axis is folded into the gather row index (ids + p*stride into
    # a (P*rows, 3) view) instead of vmap-ing per part: batched (vmap) TPU
    # gathers measured 4-5x slower than a single flat row gather.  A zero
    # pad row per part keeps all padded indices in bounds.

    def _gather_u3(self, x: jnp.ndarray, blk: dict) -> jnp.ndarray:
        """x (P, n_loc[, R]) -> gathered node rows (P, nn, N, 3[, R]).
        The RHS-block axis rides the gathered row as extra minor dims —
        same single flat row gather, wider rows."""
        node = blk["node"]                                   # (P, nn, N)
        Pn, nn, N = node.shape
        nr = self.n_node_loc + 1
        tail = x.shape[2:]                                   # () or (R,)
        x3 = x.reshape((Pn, self.n_node_loc, 3) + tail)
        x3p = jnp.concatenate(
            [x3, jnp.zeros((Pn, 1, 3) + tail, x3.dtype)],
            axis=1).reshape((Pn * nr, 3) + tail)
        offs = (jnp.arange(Pn, dtype=jnp.int32) * nr)[:, None, None]
        u3 = jnp.take(x3p, (node + offs).reshape(-1), axis=0, mode="clip")
        return u3.reshape((Pn, nn, N, 3) + tail)

    def _gather_u(self, data: dict, x: jnp.ndarray, blk: dict) -> jnp.ndarray:
        """x (P, n_loc[, R]) -> element dof values (P, d, N[, R])."""
        if self.use_node_ell:
            u3 = self._gather_u3(x, blk)
            Pn, nn, N = u3.shape[:3]
            # row (a, n, c) -> dof row 3a+c of column n
            if u3.ndim == 5:
                return u3.transpose(0, 1, 3, 2, 4).reshape(
                    Pn, 3 * nn, N, u3.shape[4])
            return u3.transpose(0, 1, 3, 2).reshape(Pn, 3 * nn, N)
        if x.ndim == 3:
            return jnp.take_along_axis(
                x[:, None, :, :], blk["dof"][:, :, :, None], axis=2,
                mode="fill", fill_value=0)
        return jnp.take_along_axis(x[:, None, :], blk["dof"], axis=2,
                                   mode="fill", fill_value=0)

    def _scatter_rows(self, data: dict, rows) -> jnp.ndarray:
        """Per-block (P, nn*N, 3[, R]) value rows -> local dof sums
        (P, n_loc[, R]) via the ELL map: one row gather + row-sum, no
        scatter-add."""
        flat3 = jnp.concatenate(rows, axis=1)                # (P, NCn, 3[, R])
        Pn, ncn = flat3.shape[:2]
        tail = flat3.shape[3:]
        flat3p = jnp.concatenate(
            [flat3, jnp.zeros((Pn, 1, 3) + tail, flat3.dtype)],
            axis=1).reshape((Pn * (ncn + 1), 3) + tail)
        ell = data["ell"]                                    # (P, n_node_loc, K)
        offs = (jnp.arange(Pn, dtype=jnp.int32) * (ncn + 1))[:, None, None]
        g = jnp.take(flat3p, (ell + offs).reshape(-1), axis=0, mode="clip")
        y3 = g.reshape((Pn, self.n_node_loc, -1, 3) + tail).sum(axis=2)
        return y3.reshape((Pn, self.n_loc) + tail)

    def _scatter_blocks(self, data: dict, per_block_v) -> jnp.ndarray:
        """Per-block element values [(P, d, N[, R])] -> local dof sums
        (P, n_loc[, R])."""
        if self.use_node_ell:
            rows = []
            for v in per_block_v:
                Pn, d, N = v.shape[:3]
                nn = d // 3
                # dof row 3a+c -> value row a*N+n, component c
                if v.ndim == 4:
                    rows.append(
                        v.reshape(Pn, nn, 3, N, v.shape[3])
                        .transpose(0, 1, 3, 2, 4)
                        .reshape(Pn, nn * N, 3, v.shape[3]))
                else:
                    rows.append(v.reshape(Pn, nn, 3, N)
                                .transpose(0, 1, 3, 2)
                                .reshape(Pn, nn * N, 3))
            return self._scatter_rows(data, rows)
        flat = jnp.concatenate(
            [v.reshape((v.shape[0], -1) + v.shape[3:]) for v in per_block_v],
            axis=1)
        return self._scatter(data, flat)

    # -- the matvec -----------------------------------------------------
    def matvec_local(self, data: dict, x: jnp.ndarray) -> jnp.ndarray:
        """Part-local K.x (no cross-part assembly).  x: (P, n_loc), or
        (P, n_loc, nrhs) for a RHS block — then every per-type matmul
        batches over the trailing axis ((d x d) @ (d x N x nrhs): higher
        MXU utilization at near-constant gather/scatter traffic, the
        ISSUE-6 batched-SpMV shape) and the result keeps the block axis."""
        blocked = x.ndim == 3
        if self.use_node_ell:
            rows = []
            for blk in data["blocks"]:
                u3 = self._gather_u3(x, blk)             # (P, a, n, c[, r])
                sgn = (blk["sign_nc"][..., None] if blocked
                       else blk["sign_nc"])
                u3 = jnp.where(sgn, -u3, u3)
                ck = blk["ck"][:, None, :, None]
                if blocked:
                    v = jnp.einsum("bdac,pancr->pbndr", blk["Ke4"],
                                   ck[..., None] * u3,
                                   precision=self.precision)
                else:
                    v = jnp.einsum("bdac,panc->pbnd", blk["Ke4"],
                                   ck * u3,
                                   precision=self.precision)  # (P, b, n, d)
                v = jnp.where(sgn, -v, v)
                Pn, nn, N = v.shape[:3]
                rows.append(v.reshape((Pn, nn * N, 3) + x.shape[2:]))
            y = self._scatter_rows(data, rows)
        else:
            per_block_v = []
            for blk in data["blocks"]:
                u = self._gather_u(data, x, blk)             # (P, d, N[, r])
                sgn = blk["sign"][..., None] if blocked else blk["sign"]
                u = jnp.where(sgn, -u, u)
                ck = blk["ck"][:, None, :]
                if blocked:
                    v = jnp.einsum("de,penr->pdnr", blk["Ke"],
                                   ck[..., None] * u,
                                   precision=self.precision)
                else:
                    v = jnp.einsum("de,pen->pdn", blk["Ke"],
                                   ck * u,
                                   precision=self.precision)
                v = jnp.where(sgn, -v, v)
                per_block_v.append(v)
            y = self._scatter_blocks(data, per_block_v)
        return self._apply_springs(data, x, y)

    def _apply_springs(self, data: dict, x, y):
        """Cohesive interface springs: f_a += k*(x_a - x_b), f_b -= same
        (a live capability where the reference has only scaffolding,
        partition_mesh.py:603-650); padded entries have k = 0 and
        out-of-bounds ids, so they gather 0 and drop on scatter."""
        if "spr_a" not in data:
            return y
        if x.ndim == 3:
            ia, ib = data["spr_a"][:, :, None], data["spr_b"][:, :, None]
            xa = jnp.take_along_axis(x, ia, axis=1, mode="fill",
                                     fill_value=0)
            xb = jnp.take_along_axis(x, ib, axis=1, mode="fill",
                                     fill_value=0)
            f = data["spr_k"][..., None] * (xa - xb)
        else:
            xa = jnp.take_along_axis(x, data["spr_a"], axis=1,
                                     mode="fill", fill_value=0)
            xb = jnp.take_along_axis(x, data["spr_b"], axis=1,
                                     mode="fill", fill_value=0)
            f = data["spr_k"] * (xa - xb)
        return jax.vmap(
            lambda yp, ia, ib, fp: yp.at[ia].add(fp, mode="drop")
                                     .at[ib].add(-fp, mode="drop")
        )(y, data["spr_a"], data["spr_b"], f)

    def diag_local(self, data: dict) -> jnp.ndarray:
        """Part-local diag(K) via the same scatter path
        (reference 'Preconditioner' mode, pcg_solver.py:282-287)."""
        if self.use_node_ell:
            rows = []
            for blk in data["blocks"]:
                ck = blk["ck"]                               # (P, N)
                v = (blk["diag_Ke4"][None, :, None, :]
                     * ck[:, None, :, None])                 # (P, nn, N, 3)
                rows.append(v.reshape(ck.shape[0], -1, 3))
            y = self._scatter_rows(data, rows)
        else:
            per_block_v = [
                jnp.broadcast_to(
                    blk["diag_Ke"][None, :, None] * blk["ck"][:, None, :],
                    (blk["ck"].shape[0], blk["diag_Ke"].shape[0],
                     blk["ck"].shape[1]))
                for blk in data["blocks"]
            ]
            y = self._scatter_blocks(data, per_block_v)
        return self._apply_springs_diag(data, y)

    def _apply_springs_diag(self, data: dict, y):
        if "spr_a" not in data:
            return y
        return jax.vmap(
            lambda yp, ia, ib, kp: yp.at[ia].add(kp, mode="drop")
                                     .at[ib].add(kp, mode="drop")
        )(y, data["spr_a"], data["spr_b"], data["spr_k"])

    # -- node-block (3x3) diagonal for block-Jacobi ---------------------
    def _node_block_local(self, data: dict) -> jnp.ndarray:
        """Part-local per-node 3x3 diagonal blocks of K, flattened to
        (P, n_node_loc, 9) row-major.  Same assembly path as diag_local but
        keeping the full within-node coupling K[3a+i, 3a+j]; mirrored
        patterns scale entry (i, j) by sign_i*sign_j (the diag's sign^2 == 1
        generalized off the diagonal)."""
        if not self.use_node_ell:
            raise ValueError(
                "block-Jacobi needs the node-contiguous dof layout "
                "(PartitionedModel.ell); this model/partition lacks it — "
                "use precond='jacobi'")
        Pl = data["weight"].shape[0]
        dt = data["weight"].dtype
        out = jnp.zeros((Pl, self.n_node_loc, 9), dt)
        for blk in data["blocks"]:
            node = blk["node"]                            # (P, nn, N)
            Pn, nn, N = node.shape
            Ke4 = blk["Ke4"]                              # (nn, 3, nn, 3)
            D = jnp.stack([Ke4[a, :, a, :] for a in range(nn)])  # (nn, 3, 3)
            sv = jnp.where(blk["sign"], -1.0, 1.0).astype(dt) \
                .reshape(Pn, nn, 3, N)
            contrib = jnp.einsum("aij,pn,pain,pajn->panij",
                                 D, blk["ck"], sv, sv,
                                 precision=self.precision)
            out = jax.vmap(
                lambda o, idx, r: o.at[idx].add(r, mode="drop")
            )(out, node.reshape(Pn, -1),
              contrib.reshape(Pn, nn * N, 9))
        return self._springs_into_blocks(data, out)

    def _springs_into_blocks(self, data: dict, out):
        """Cohesive-spring diagonal contributions into the (i, i) entries of
        the endpoint nodes' blocks (off-node coupling is dropped — the
        preconditioner is approximate there, like scalar Jacobi)."""
        if "spr_a" not in data:
            return out
        Pl = out.shape[0]
        flat = out.reshape(Pl, -1)

        def add(fp, dof, kp):
            idx = (dof // 3) * 9 + (dof % 3) * 4
            return fp.at[idx].add(kp, mode="drop")

        flat = jax.vmap(add)(flat, data["spr_a"], data["spr_k"])
        flat = jax.vmap(add)(flat, data["spr_b"], data["spr_k"])
        return flat.reshape(out.shape)

    def node_block_diag(self, data: dict) -> jnp.ndarray:
        """Fully assembled per-node 3x3 diagonal blocks (P, n_node_loc,
        3, 3): local blocks summed across parts sharing the node (same
        psum assembly as the scalar diag)."""
        y = self._node_block_local(data)                  # (P, n, 9)
        y = self.niface_assemble(data, y.transpose(0, 2, 1)).transpose(0, 2, 1)
        return y.reshape(y.shape[0], self.n_node_loc, 3, 3)

    def _as_node3(self, v: jnp.ndarray) -> jnp.ndarray:
        """(P, n_loc[, R]) dof vector -> (P, n_node_loc, 3[, R]) node rows
        (the node-contiguous layout; StructuredOps overrides for its
        component-major grid layout)."""
        return v.reshape((v.shape[0], self.n_node_loc, 3) + v.shape[2:])

    def _from_node3(self, z3: jnp.ndarray) -> jnp.ndarray:
        """Inverse of :meth:`_as_node3`: (P, n_node_loc, 3[, R]) ->
        (P, n_loc[, R])."""
        return z3.reshape((z3.shape[0], self.n_loc) + z3.shape[3:])

    def block_precond(self, data: dict) -> jnp.ndarray:
        """Inverted eff-masked node blocks, ready for ``apply_prec``."""
        from pcg_mpi_solver_tpu.ops.precond import invert_node_blocks

        return invert_node_blocks(self.node_block_diag(data),
                                  self._as_node3(data["eff"]))

    def apply_prec(self, m, r: jnp.ndarray, data: dict = None) -> jnp.ndarray:
        """z = M^-1 r: elementwise for the scalar Jacobi inverse (ndim 2),
        batched 3x3 block multiply for the block-Jacobi inverse (ndim 4),
        or one geometric multigrid V-cycle when ``m`` is the mg prec
        dict (ops/mg.py — then ``data`` must be the device data tree the
        hierarchy rides, which every PCG body has in scope); backend dof
        layouts differ only through _as_node3/_from_node3.
        ``r`` may carry a trailing RHS-block axis (P, n_loc, nrhs)."""
        if isinstance(m, dict):
            from pcg_mpi_solver_tpu.ops.mg import mg_apply

            return mg_apply(self, data, m, r)
        blocked = r.ndim == 3
        if m.ndim == 2:
            return m[..., None] * r if blocked else m * r
        if blocked:
            z3 = jnp.einsum("pnij,pnjr->pnir", m, self._as_node3(r),
                            precision=self.precision)
        else:
            z3 = jnp.einsum("pnij,pnj->pni", m, self._as_node3(r),
                            precision=self.precision)
        return self._from_node3(z3)

    def _scatter(self, data: dict, flat: jnp.ndarray) -> jnp.ndarray:
        """(P, NC[, R]) element-dof values -> (P, n_loc[, R]) via sorted
        segment_sum (the RHS block rides as a trailing segment dim)."""
        perm = (data["scat_perm"][:, :, None] if flat.ndim == 3
                else data["scat_perm"])
        svals = jnp.take_along_axis(flat, perm, axis=1)
        seg = jax.vmap(
            partial(jax.ops.segment_sum, num_segments=self.n_loc + 1,
                    indices_are_sorted=True)
        )(svals, data["scat_ids"])
        return seg[:, : self.n_loc]

    def matvec(self, data: dict, x: jnp.ndarray) -> jnp.ndarray:
        """Full assembled K.x across all parts (reference calcMPFint).
        ``x`` may carry a trailing RHS-block axis (P, n_loc, nrhs); the
        result keeps it, and the interface-assembly psum count stays ONE
        regardless of the block width."""
        return self.iface_assemble(data, self.matvec_local(data, x))

    def comm_estimate(self, storage_dtype=None,
                      variant: str = "classic",
                      precond: str = "jacobi") -> dict:
        """Static per-PCG-iteration collective estimate from the ops
        shapes, for the telemetry gauges (obs/metrics.py).  ``variant``
        is the PCG loop formulation (SolverConfig.pcg_variant): classic
        runs 3 serialized scalar/fused psums per iteration (rho+inf, pq,
        fused 3-norm — 6 reduced scalars total); the fused
        Chronopoulos–Gear variant folds all 6 scalars into ONE psum.
        Either way the interface-assembly psum inside the matvec adds
        one collective whose payload is the shared-dof vector.
        ``bytes_per_iter_est`` is the per-device psum payload, not link
        traffic (the actual wire cost depends on the all-reduce
        algorithm and topology).

        The per-iteration scalar-psum count comes from
        ``PCG_SCALAR_PSUMS`` (declared above) — the SAME table the
        collective-budget lint rule (analysis/) proves against the
        traced loop-body jaxpr, so these gauges can never advertise a
        count the static proof does not hold."""
        itemsize = jnp.dtype(storage_dtype if storage_dtype is not None
                             else self.dot_dtype).itemsize
        dot_bytes = jnp.dtype(self.dot_dtype).itemsize
        n_iface = int(self.n_iface)
        scalar_psums = PCG_SCALAR_PSUMS[variant]
        # preconditioner-apply collectives (precond_cycle_cost — the mg
        # V-cycle's fine matvec assemblies + restriction psum; jacobi/
        # block3 add zero): same table the collective-budget rule
        # proves.  The restriction psum's payload is the replicated
        # first-coarse vector (mg_coarse_dofs, pinned at construction)
        # — the largest single collective payload of the cycle, so the
        # bytes estimate must carry it.
        mv_extra, ps_extra = precond_cycle_cost(precond, self.mg_degree)
        return {
            "pcg_variant": variant,
            "precond": precond,
            "psums_per_iter": (scalar_psums
                               + ((1 + mv_extra) if n_iface else 0)
                               + ps_extra),
            "iface_dofs": n_iface,
            "reduce_scalars_per_iter": 6,
            "bytes_per_iter_est": (n_iface * itemsize * (1 + mv_extra)
                                   + ps_extra * int(self.mg_coarse_dofs)
                                   * itemsize
                                   + 6 * dot_bytes),
        }

    def body_collective_budget(self, variant: str = "classic",
                               precond: str = "jacobi") -> dict:
        """Per-primitive collective budget of the TRACED PCG while-loop
        body, the contract the analysis/ collective-budget rule proves
        against every canonical program's jaxpr (and the single source
        ``tools/check_collectives.py`` documents).  Differs from the
        healthy-iteration gauge above because the traced body carries
        BOTH conditional branches: the deferred mode-1 true-residual
        check contributes ``PCG_DEFERRED_CHECK_PSUMS`` extra norm
        psum(s) that a healthy (mode-0) trip never executes.  Keyed per
        primitive so a re-serialized reduction OR a new collective kind
        sneaking into the hot body both fail the lint.

        ``precond`` extends the budget with the preconditioner apply's
        declared collectives (``precond_cycle_cost``): the mg V-cycle
        adds ``2*mg_degree`` assembled fine matvecs (each one interface
        psum when the partition has shared dofs) plus the restriction
        psum; the smoother itself contributes zero.  Unknown precond =
        loud KeyError."""
        mv_extra, ps_extra = precond_cycle_cost(precond, self.mg_degree)
        psums = PCG_SCALAR_PSUMS[variant] + PCG_DEFERRED_CHECK_PSUMS
        if int(self.n_iface):
            psums += 1 + mv_extra
        if self.axis_name is not None:
            psums += ps_extra
        return {"psum": psums}

    def diag(self, data: dict) -> jnp.ndarray:
        return self.iface_assemble(data, self.diag_local(data))

    # -- element strain + nodal averaging (export path) -----------------
    def elem_strain(self, data: dict, x: jnp.ndarray):
        """Per-block center-point strain eps = Se @ (ce * S.u_e), in each
        pattern's local frame (reference updateElemStrain,
        pcg_solver.py:601-618).  Returns list of (P, 6, N)."""
        out = []
        for blk in data["blocks"]:
            u = self._gather_u(data, x, blk)
            u = jnp.where(blk["sign"], -u, u)
            eps = jnp.einsum("sd,pdn->psn", blk["Se"],
                             blk["ce"][:, None, :] * u, precision=self.precision)
            out.append(eps)
        return out

    def elem_scale(self, data: dict):
        """Per-block elastic modulus E = ck*ce (since ck=E*h, ce=1/h)."""
        return [blk["ck"] * blk["ce"] for blk in data["blocks"]]

    def nodal_average(self, data: dict, vals_list) -> jnp.ndarray:
        """Element values -> averaged nodal field.

        vals_list: per block (P, k, N) element-constant values.  Scatter
        sums + counts to element nodes, assemble shared nodes across parts,
        divide (reference getNodalScalarVar/getNodalPS,
        pcg_solver.py:655-814, incl. the +1e-15 guard :724)."""
        k = vals_list[0].shape[1]
        Pl = vals_list[0].shape[0]
        dt = vals_list[0].dtype
        sums = jnp.zeros((Pl, k, self.n_node_loc), dt)
        counts = jnp.zeros((Pl, 1, self.n_node_loc), dt)

        def scat(s, ids, c):
            return s.at[:, ids].add(c, mode="drop")

        for blk, vals in zip(data["blocks"], vals_list):
            node = blk["node"]                        # (P, nn, N)
            nn = node.shape[1]
            ids = node.reshape(Pl, -1)
            contrib = jnp.broadcast_to(vals[:, :, None, :],
                                       (Pl, k, nn, vals.shape[2])
                                       ).reshape(Pl, k, -1)
            # Every real element counts once per node (reference
            # pcg_solver.py:685-686); padded slots drop via their
            # out-of-bounds node ids, so no extra masking — identical
            # semantics on both backends.
            ones = jnp.ones((Pl, 1, nn * vals.shape[2]), dt)
            sums = jax.vmap(scat)(sums, ids, contrib)
            counts = jax.vmap(scat)(counts, ids, ones)

        both = jnp.concatenate([sums, counts], axis=1)
        both = self.niface_assemble(data, both)
        return both[:, :k] / (both[:, k:] + 1e-15)

    # -- reductions -----------------------------------------------------
    def _local_dot(self, w, a, b):
        # Cast operands BEFORE multiplying: products of two f32 values are
        # exact in f64, so f32-storage runs get true f64-accumulated dots.
        dd = self.dot_dtype
        return jnp.sum(a.astype(dd) * b.astype(dd) * w.astype(dd))

    def wdot(self, w: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        """Global weighted dot <a, b>_w: duplicated interface dofs counted
        once via the 0/1 owner weights (reference pcg_solver.py:381,462)."""
        return self._psum(self._local_dot(w, a, b))

    def wdots(self, w: jnp.ndarray, pairs, extra=()) -> jnp.ndarray:
        """Fused multi-dot: ONE psum for several dots, optionally carrying
        extra pre-reduced local scalars in the same collective
        (reference's fused 3-norm allreduce, pcg_solver.py:504-507)."""
        loc = jnp.stack([self._local_dot(w, a, b) for a, b in pairs]
                        + [jnp.asarray(e, self.dot_dtype) for e in extra])
        return self._psum(loc)

    # -- per-RHS reductions (the batched-solve contract) ----------------
    def _local_dot_many(self, w, a, b):
        """Per-column local weighted dots of an RHS block: a, b
        (P, n_loc, R) -> (R,).  vmapped over the trailing axis so each
        column's reduction is the SAME reduce the single-RHS
        :meth:`_local_dot` runs (bit-identical per column on CPU — the
        classic-parity contract of tests/test_pcg_many.py)."""
        return jax.vmap(lambda ac, bc: self._local_dot(w, ac, bc),
                        in_axes=(-1, -1))(a, b)

    def wdot_many(self, w: jnp.ndarray, a: jnp.ndarray,
                  b: jnp.ndarray) -> jnp.ndarray:
        """Per-RHS global weighted dots <a_j, b_j>_w: (P, n_loc, R) ->
        (R,) in ONE psum — the collective count is independent of the
        block width; only the payload widens."""
        return self._psum(self._local_dot_many(w, a, b))

    def wdots_many(self, w: jnp.ndarray, pairs, extra=()) -> jnp.ndarray:
        """Fused per-RHS multi-dot: pairs of (P, n_loc, R) blocks (plus
        optional pre-reduced (R,) local rows) -> (k + len(extra), R) in
        ONE psum.  The batched twin of :meth:`wdots`: every per-RHS
        scalar reduction of a PCG iteration folds into a single
        collective whose payload scales with nrhs but whose COUNT does
        not (tools/check_collectives.py proves this statically)."""
        loc = jnp.stack([self._local_dot_many(w, a, b) for a, b in pairs]
                        + [jnp.asarray(e, self.dot_dtype) for e in extra])
        return self._psum(loc)


# ---------------------------------------------------------------------------
# Bucketed matvec: a compile-cheap operator formulation for out-of-loop use.
#
# The per-type loop above emits one gather/einsum/scatter structure PER
# pattern type; at the reference's deep-graded octrees that is 200+ types
# (/root/reference/src/solver/partition_mesh.py:1074 allows <=144 per rank,
# multi-part models exceed it globally), and measured chipless compile cost
# tracks the emitted structure COUNT, not FLOPs (docs/BENCH_LOG.md
# 2026-08-01: 227 type blocks -> 1343 s f64).  Here types are STACKED into
# a few buckets by element-count SIZE CLASS only (power-of-16 boundaries;
# ~5 buckets at the flagship), with element arity (d, nn) zero-padded to
# each bucket's max: one batched einsum per bucket.  Element-count slots
# pad to the bucket max and arity padding can cost up to ~16x on the
# small transition types — irrelevant for the ~4 calls/solve refresh
# amul this exists for (the dominant brick type sits alone in the top
# size class and pays no padding).  The scatter is an unordered at[].add
# (bit-order differs from the type-loop path), so this formulation is for
# paths WITHOUT a bit-exact iteration contract (the mixed-mode f64
# refresh; never the direct/f64 parity path).

def build_bucketed_blocks(pm: PartitionedModel, dtype=jnp.float64):
    """Stack pm.type_blocks into padded same-shape buckets.

    Returns a list of dicts {"Ke": (T, d, d), "node": (P, T, nn, Nmax),
    "sign": (P, T, d, Nmax), "ck": (P, T, Nmax)} — parts axis LEADING on
    the per-part arrays (the driver's _data_specs shards leaf axis 0).
    Padded slots carry ck = 0 and node = n_node_loc (the gather's zero
    row / the scatter's dropped out-of-bounds row)."""
    if pm.ell is None:
        raise ValueError("bucketed matvec requires the 3-dof node layout "
                         "(PartitionedModel.ell)")
    groups: dict = {}
    for tb in pm.type_blocks:
        if tb.d != 3 * tb.n_nodes:
            raise ValueError(f"type {tb.type_id}: d={tb.d} is not "
                             f"3*n_nodes={tb.n_nodes} — not node layout")
        N = tb.node.shape[2]
        size_cls = 0
        # coarse power-of-16 classes: N <= 16, 256, 4096, 65k, 1M, ...
        # Grouping is by size class ONLY — element arity (d, nn) is
        # zero-PADDED to the bucket max instead of splitting buckets:
        # measured at the flagship, (d, nn, cls) grouping still left
        # 36-40 buckets (the reference's hanging-node transition types
        # span many arities) while compile cost tracks bucket COUNT
        # (general 227 structs 1343 s / 40 buckets 680 s / stencil 999 s
        # chipless).  The dominant brick type sits alone in the top size
        # class, so the arity padding wastes FLOPs only on the small
        # transition types — irrelevant for an out-of-loop operator.
        while 16 ** (size_cls + 1) < N:
            size_cls += 1
        groups.setdefault(size_cls, []).append(tb)
    buckets = []
    for _cls, tbs in sorted(groups.items()):
        P = tbs[0].node.shape[0]
        nmax = max(tb.node.shape[2] for tb in tbs)
        nn = max(tb.n_nodes for tb in tbs)
        d = 3 * nn
        T = len(tbs)
        Ke = np.zeros((T, d, d))
        node = np.full((P, T, nn, nmax), pm.n_node_loc, dtype=np.int32)
        sign = np.zeros((P, T, d, nmax), dtype=bool)
        ck = np.zeros((P, T, nmax))
        for t, tb in enumerate(tbs):
            n = tb.node.shape[2]
            Ke[t, :tb.d, :tb.d] = tb.Ke
            node[:, t, :tb.n_nodes, :n] = tb.node
            sign[:, t, :tb.d, :n] = tb.sign
            ck[:, t, :n] = tb.ck
        buckets.append({"Ke": jnp.asarray(Ke, dtype),
                        "node": jnp.asarray(node),
                        "sign": jnp.asarray(sign),
                        "ck": jnp.asarray(ck, dtype)})
    return buckets


def bucketed_matvec(ops: Ops, data: dict, x: jnp.ndarray) -> jnp.ndarray:
    """Assembled K.x via the bucketed blocks (data["buckets"] +
    device_data(..., blocks=False) leaves).  Same contract as
    Ops.matvec; summation order differs (see module note above)."""
    Pn = x.shape[0]
    nr = ops.n_node_loc + 1
    x3p = jnp.concatenate(
        [x.reshape(Pn, ops.n_node_loc, 3),
         jnp.zeros((Pn, 1, 3), x.dtype)], axis=1).reshape(Pn * nr, 3)
    offs = (jnp.arange(Pn, dtype=jnp.int32) * nr)[:, None, None, None]
    y3 = jnp.zeros((Pn, ops.n_node_loc, 3), x.dtype)
    for bkt in data["buckets"]:
        node = bkt["node"]                              # (P, T, nn, Nmax)
        _, T, nn, N = node.shape
        u3 = jnp.take(x3p, (node + offs).reshape(-1), axis=0,
                      mode="clip").reshape(Pn, T, nn, N, 3)
        # dof-row order d = 3a + c, matching TypeBlock.sign's layout
        u = u3.transpose(0, 1, 2, 4, 3).reshape(Pn, T, 3 * nn, N)
        u = jnp.where(bkt["sign"], -u, u)
        v = jnp.einsum("tde,pten->ptdn", bkt["Ke"],
                       bkt["ck"][:, :, None, :] * u,
                       precision=ops.precision)
        v = jnp.where(bkt["sign"], -v, v)
        rows = (v.reshape(Pn, T, nn, 3, N).transpose(0, 1, 2, 4, 3)
                .reshape(Pn, T * nn * N, 3))
        ids = node.reshape(Pn, T * nn * N)
        y3 = jax.vmap(
            lambda yp, ip, rp: yp.at[ip].add(rp, mode="drop"))(y3, ids, rows)
    y = y3.reshape(Pn, ops.n_loc)
    y = ops._apply_springs(data, x, y)
    return ops.iface_assemble(data, y)
