"""Matrix-free geometric multigrid V-cycle preconditioner (ISSUE 10).

The dominant remaining term in time-to-solution is iteration COUNT
(ROADMAP item 4: 3334 Jacobi-preconditioned iterations at 10.33M dofs),
and Jacobi's count grows with resolution.  HPCG's reference shape
(arXiv:2304.08232) is CG + a geometric multigrid preconditioner; the
matrix-free FEM data-locality work (arXiv:2205.08909) shows the level
operators can stay assembly-free — which everything in this codebase
already is per level by construction.  ``SolverConfig.precond = "mg"``
selects it; scalar Jacobi stays the bit-exact default.

Design (the communication shape is the point):

* **Level lattice** — the fine mesh's cell lattice (``models/octree.py``
  metadata, or ``ModelData.grid`` for the structured backend, where the
  levels derive analytically) is coarsened by 2 per level while every
  dim stays even, down to a small fixed coarse size.  Each coarse level
  is a full uniform brick grid with a per-cell ``ck`` field
  (volume-averaged fine stiffness — rediscretization, not Galerkin: the
  brick element's ``Ke`` scales linearly in h through ``ck = E*h``, so
  the level operator is the SAME matrix-free stencil at every level).

* **Replicated coarse levels** — every level below the fine one is
  REPLICATED across the mesh: each device runs the identical small
  dense-stencil work redundantly, so the entire coarse hierarchy —
  smoothing, level transfers, the coarse sweep — executes with ZERO
  collectives.  One psum per V-cycle assembles the restricted fine
  defect into the replicated first-coarse vector (``MG_RESTRICT_PSUMS``);
  prolongation back to the part-local fine layout is a pure local
  gather.

* **Chebyshev–Jacobi smoother** — a FIXED-degree Chebyshev polynomial
  in ``D^-1 A`` (SPD-preserving for ``b >= lambda_max``; the classical
  symmetric-V-cycle argument gives a symmetric PSD ``M^-1`` when pre-
  and post-smoothing use the same polynomial).  Chebyshev needs NO
  inner products — the eigenvalue bounds are estimated ONCE at setup by
  a few power-iteration matvecs (host numpy per coarse level; one small
  jitted program for the fine level, cached in the partition cache) —
  so the smoother contributes zero collectives: every collective in the
  traced V-cycle is a fine-level matvec's interface assembly or THE
  restriction psum, statically proven by the analysis/ collective-budget
  rule (``Ops.body_collective_budget(variant, precond="mg")``).

* **Fixed linear operator** — the cycle shape is static (no inner
  convergence tests, no adaptivity), so ``M^-1`` is one fixed symmetric
  PSD linear operator and plain (non-flexible) PCG remains valid: two
  applies to the same vector are bitwise identical.

Per-V-cycle fine-level work: ``2 * degree`` assembled matvecs (degree-d
pre-smoothing from zero costs d-1, the defect 1, post-smoothing d), each
carrying exactly the matvec's own interface collective (1 psum general /
``STENCIL_HALO_PPERMUTES`` structured) — see ``precond_cycle_cost`` in
``ops/matvec.py``, the single table the telemetry gauges, the static
proof and this module share.

The RHS-block axis (``pcg_many``) batches by vmapping the single-column
cycle over the trailing axis: psum/ppermute COUNTS are independent of
nrhs (payloads widen), proven at nrhs in {1, 8} by the lint.

Not supported: the hybrid level-grid backend (its stencil costs minutes
of compile per instantiation — 2*degree more instantiations per body is
a different engineering problem), scalar (Poisson-class) models, and
models without lattice metadata; ``validate/`` preflights all three.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import numpy as np

# ---------------------------------------------------------------------------
# Tuning constants (NOT SolverConfig knobs: they gate smoother quality, and
# the two that shape the traced program — level count and smoothing degree —
# ARE knobs, see SolverConfig.mg_levels / mg_smooth_degree).
# ---------------------------------------------------------------------------

#: safety factor on the power-iteration lambda_max estimate: Chebyshev
#: smoothing is SPD-preserving only for b >= true lambda_max, and power
#: iteration converges from below
MG_LAM_SAFETY = 1.2
#: smoother interval [lam/alpha, lam] — Chebyshev targets the upper part
#: of the spectrum; the coarse correction owns the rest
MG_SMOOTH_ALPHA = 4.0
#: coarsest-level "solve": one fixed Chebyshev sweep over the (nearly)
#: full interval [lam/alpha, lam]
MG_COARSE_ALPHA = 30.0
MG_COARSE_DEGREE = 10
#: power-iteration matvecs for the per-level lambda_max estimates
MG_POWER_ITERS = 16
#: auto-coarsening stops at this many cells per dim (or when a dim odd)
MG_MIN_COARSE_DIM = 2
MG_MAX_LEVELS = 8

# 8 hex corners in the element-dof order of models/element.py
# HEX_CORNERS (shared with the structured slab stencil)
_CORNERS = [(0, 0, 0), (1, 0, 0), (1, 1, 0), (0, 1, 0),
            (0, 0, 1), (1, 0, 1), (1, 1, 1), (0, 1, 1)]


class MGSetupError(ValueError):
    """The model/config cannot build an MG hierarchy (named reason)."""


# ---------------------------------------------------------------------------
# Host-side hierarchy construction
# ---------------------------------------------------------------------------

def fine_lattice(model) -> Tuple[Optional[Tuple[int, int, int]],
                                 Optional[np.ndarray]]:
    """The fine cell-lattice dims and per-node integer lattice coords of
    a lattice-structured model, or ``(None, None)``.

    Octree models carry exact lattice metadata (``model.octree``); plain
    structured-grid models (``model.grid``) recover coords from
    ``node_coords / h``.  This is the ONE eligibility probe shared by
    the preflight check and the hierarchy builder."""
    ot = getattr(model, "octree", None)
    if ot:
        X, Y, Z = (int(d) for d in ot["dims"])
        sy, sz = (int(s) for s in ot["strides"])
        keys = np.asarray(ot["node_keys"])
        lat = np.stack([keys % sy, (keys // sy) % (Y + 1), keys // sz],
                       axis=1).astype(np.int64)
        return (X, Y, Z), lat
    if getattr(model, "grid", None) is not None:
        nx, ny, nz, h = model.grid
        nc = np.asarray(model.node_coords, float)
        latf = (nc - nc.min(axis=0)) / float(h)
        lat = np.rint(latf).astype(np.int64)
        if np.abs(latf - lat).max() > 1e-6:
            return None, None
        return (int(nx), int(ny), int(nz)), lat
    return None, None


def plan_levels(dims, n_levels: int = 0) -> List[Tuple[int, int, int]]:
    """Coarse-level cell dims, finest-coarse first.  Coarsens by 2 while
    every dim stays even, down to ``MG_MIN_COARSE_DIM`` (auto) or for
    exactly ``n_levels`` levels.  Raises :class:`MGSetupError` (named
    reason) when the lattice cannot coarsen at least once — the
    preflight surfaces the same reason before any partition build."""
    d = np.asarray(dims, np.int64)
    out: List[Tuple[int, int, int]] = []
    while len(out) < (n_levels or MG_MAX_LEVELS):
        if np.any(d % 2):
            break
        d = d // 2
        out.append(tuple(int(v) for v in d))
        if not n_levels and int(d.max()) <= MG_MIN_COARSE_DIM:
            break
    if not out:
        raise MGSetupError(
            f"precond='mg' cannot coarsen the {tuple(int(v) for v in dims)}"
            " cell lattice: every dim must be even for at least one "
            "2:1 coarsening (fewer than 2 levels)")
    if n_levels and len(out) < n_levels:
        raise MGSetupError(
            f"SolverConfig.mg_levels={n_levels} but the "
            f"{tuple(int(v) for v in dims)} lattice only supports "
            f"{len(out)} coarsening(s)")
    return out


def _ravel(dims_c, pts) -> np.ndarray:
    """Flat node id on a (cx, cy, cz)-cell grid: C-order over (ix, iy,
    iz) — the SAME ordering ``_to_grid``/``_to_flat`` use at runtime."""
    cx, cy, cz = dims_c
    return (pts[..., 0] * (cy + 1) + pts[..., 1]) * (cz + 1) + pts[..., 2]


def trilinear_transfer(lat, dims_c, scale: int):
    """Trilinear prolongation stencil of nodes at integer lattice coords
    ``lat`` (units of the FINER lattice) from the coarse node grid of
    ``dims_c`` cells (coarse spacing = ``scale`` finer units).

    Returns ``(gidx, gw)``: (n, 8) flat coarse node ids and weights with
    ``fine = sum_k gw[:, k] * coarse[gidx[:, k]]``.  Restriction is the
    exact transpose (same arrays, scatter instead of gather), which is
    what keeps the V-cycle operator symmetric."""
    lat = np.asarray(lat, np.float64)
    dims_c = tuple(int(v) for v in dims_c)
    pos = lat / float(scale)
    cell = np.minimum(np.floor(pos).astype(np.int64),
                      np.asarray(dims_c, np.int64) - 1)
    cell = np.maximum(cell, 0)
    frac = pos - cell
    gidx = np.zeros((len(lat), 8), np.int64)
    gw = np.zeros((len(lat), 8), np.float64)
    for k, (dx, dy, dz) in enumerate(_CORNERS):
        w = (frac[:, 0] if dx else 1.0 - frac[:, 0]) \
            * (frac[:, 1] if dy else 1.0 - frac[:, 1]) \
            * (frac[:, 2] if dz else 1.0 - frac[:, 2])
        gidx[:, k] = _ravel(dims_c, cell + np.asarray((dx, dy, dz)))
        gw[:, k] = w
    return gidx.astype(np.int32), gw


def _level_diag_np(diag_Ke, ck) -> np.ndarray:
    """Assembled nodal diagonal of one replicated brick level:
    ``diag[c, node] = sum_adjacent-cells ck * diag_Ke[3a + c]`` via the
    8 pad-translates (the numpy twin of the structured backend's
    ``diag_local``)."""
    cx, cy, cz = ck.shape
    d = np.zeros((3, cx + 1, cy + 1, cz + 1))
    for a, (dx, dy, dz) in enumerate(_CORNERS):
        for c in range(3):
            d[c, dx:dx + cx, dy:dy + cy, dz:dz + cz] \
                += diag_Ke[3 * a + c] * ck
    return d


def _level_matvec_np(Ke, ck, effg, xg) -> np.ndarray:
    """Replicated-level stencil matvec in numpy (setup-time power
    iteration only; the traced twin is :func:`_level_matvec`)."""
    cx, cy, cz = ck.shape
    xg = xg * effg
    slots = [xg[:, dx:dx + cx, dy:dy + cy, dz:dz + cz]
             for dx, dy, dz in _CORNERS]
    u = np.concatenate(slots, axis=0).reshape(24, -1)
    v = (Ke @ (ck.reshape(-1)[None] * u)).reshape(24, cx, cy, cz)
    y = np.zeros_like(xg)
    for a, (dx, dy, dz) in enumerate(_CORNERS):
        y[:, dx:dx + cx, dy:dy + cy, dz:dz + cz] += v[3 * a:3 * a + 3]
    return y * effg


def _np_level_lam(Ke, ck, effg, idiag, iters: int = MG_POWER_ITERS) -> float:
    """Power-iteration lambda_max estimate of ``D^-1 A`` on one
    replicated level (host numpy — the level is small by construction)."""
    x = effg.copy()
    n = np.linalg.norm(x)
    if n == 0:
        return 1.0
    x /= n
    lam = 1.0
    for _ in range(iters):
        y = idiag * _level_matvec_np(Ke, ck, effg, x)
        lam = float(np.linalg.norm(y))
        if lam <= 0 or not np.isfinite(lam):
            return 1.0
        x = y / lam
    return lam


def _np_level_lam_min(Ke, ck, effg, idiag, lam_max: float,
                      iters: int = 2 * MG_POWER_ITERS) -> float:
    """Shifted power iteration for lambda_min of ``D^-1 A`` on the
    coarsest level: the degenerate-interval diagnostic the validate/
    satellite warns on (lam_max/lam_min < 1.05 means the level operator
    is numerically a multiple of its diagonal)."""
    x = effg.copy()
    n = np.linalg.norm(x)
    if n == 0:
        return lam_max
    x /= n
    mu = 0.0
    for _ in range(iters):
        y = lam_max * (effg * x) - idiag * _level_matvec_np(
            Ke, ck, effg, x)
        mu = float(np.linalg.norm(y))
        if mu <= 0 or not np.isfinite(mu):
            return lam_max
        x = y / mu
    return max(lam_max - mu, 0.0)


@dataclasses.dataclass
class MGSetup:
    """Host product of the hierarchy build: the ``data["mg"]`` subtree
    (numpy; uploaded with the rest of the device data), the structural
    meta that must key AOT caches and snapshot fingerprints, and the
    setup diagnostics."""

    tree: dict
    meta: dict              # {"levels", "degree", "dims"} — cache/fp keyed
    coarse_lams: List[float]
    lam_min_coarse: float


def level_replicated_dofs(level_dims) -> List[int]:
    """Per-coarse-level REPLICATED dof counts (3 dofs/node on a full
    node grid) — the memory-audit quantity behind
    ``SolverConfig.mg_max_replicated_dofs``: every level below the fine
    one is replicated on EVERY device, so at 1B fine dofs the first
    coarse level alone is ~125M dofs per device.  Shared by the builder
    cutoff and the validate/ preflight warning."""
    return [3 * (cx + 1) * (cy + 1) * (cz + 1)
            for cx, cy, cz in level_dims]


def apply_replication_cutoff(level_dims, n_levels: int,
                             max_replicated_dofs: int):
    """Truncate the planned hierarchy before the CUMULATIVE replicated
    coarse-level dofs exceed ``max_replicated_dofs`` (0 = no cutoff).
    Raises :class:`MGSetupError` (named reason) when not even the first
    coarse level fits — replication would become the memory ceiling —
    or when an EXPLICIT ``mg_levels`` request cannot be honored under
    the cutoff (truncating a stated request silently would change the
    traced program behind the user's back)."""
    if max_replicated_dofs <= 0:
        return level_dims
    sizes = level_replicated_dofs(level_dims)
    keep, cum = [], 0
    for dims, sz in zip(level_dims, sizes):
        if cum + sz > max_replicated_dofs:
            break
        cum += sz
        keep.append(dims)
    if not keep:
        raise MGSetupError(
            f"precond='mg': the first coarse level ({level_dims[0]} "
            f"cells, {sizes[0]} replicated dofs) already exceeds "
            f"SolverConfig.mg_max_replicated_dofs="
            f"{max_replicated_dofs} — every coarse level is replicated "
            "on every device, so this hierarchy would make replication "
            "the memory ceiling; raise the cutoff or use "
            "precond='jacobi'|'block3'")
    if n_levels and len(keep) < n_levels:
        raise MGSetupError(
            f"SolverConfig.mg_levels={n_levels} needs "
            f"{sum(sizes[:n_levels])} replicated coarse dofs, over the "
            f"mg_max_replicated_dofs={max_replicated_dofs} cutoff "
            f"(only {len(keep)} level(s) fit); lower mg_levels or raise "
            "the cutoff")
    return keep


def build_mg_host(model, pm, n_levels: int = 0,
                  degree: int = 2,
                  max_replicated_dofs: int = 0) -> MGSetup:
    """Build the whole MG hierarchy on host from the model lattice and
    the partition's node map.

    ``pm`` supplies ``node_gid`` (P, n_node_loc) — the fine-transfer
    arrays are laid out in the SAME node order as ``ops._as_node3``
    (asserted equal on both supported backends by tests/test_mg.py).
    The fine level's lambda_max slot in ``tree["lam"]`` is a placeholder
    until :func:`estimate_fine_lam` fills it (device matvec required).
    ``max_replicated_dofs`` (SolverConfig.mg_max_replicated_dofs) caps
    the cumulative replicated coarse-level size — the ISSUE-14 scale
    audit of PR 9's replicate-everything design; see
    :func:`apply_replication_cutoff`."""
    if int(model.n_dof) != 3 * int(model.n_node):
        raise MGSetupError(
            "precond='mg' needs the vector (3-dof/node) problem class; "
            f"this model has n_dof={model.n_dof}, n_node={model.n_node}")
    if not getattr(pm, "node_layout", True):
        raise MGSetupError(
            "precond='mg' needs the node-contiguous dof layout "
            "(PartitionedModel.node_layout); this partition broke it "
            "(e.g. node-less spring ghost dofs)")
    dims, node_lat = fine_lattice(model)
    if dims is None:
        raise MGSetupError(
            "precond='mg' needs lattice metadata (ModelData.grid or "
            ".octree); this model has neither — use precond='jacobi'")
    level_dims = apply_replication_cutoff(
        plan_levels(dims, n_levels), n_levels, max_replicated_dofs)

    # ---- unit-lattice stiffness-density field E(x) --------------------
    X, Y, Z = dims
    E = np.asarray(model.ck, float) * np.asarray(model.ce, float)
    if getattr(model, "octree", None):
        leaves = np.asarray(model.octree["leaves"])
        E_unit = np.zeros((X, Y, Z))
        for s in np.unique(leaves[:, 3]):
            sel = leaves[:, 3] == s
            lx, ly, lz = (leaves[sel, 0], leaves[sel, 1], leaves[sel, 2])
            for dx in range(int(s)):
                for dy in range(int(s)):
                    for dz in range(int(s)):
                        E_unit[lx + dx, ly + dy, lz + dz] = E[sel]
        hf = float(model.level.min() / leaves[:, 3].min())
    else:
        # structured grid: element id x-fastest (ex + nx*(ey + ny*ez)),
        # the same convention parallel/structured.py slices
        E_unit = E.reshape(Z, Y, X).transpose(2, 1, 0)
        hf = float(model.grid[3])

    # ---- per-node Dirichlet mask on the fine lattice ------------------
    fixed = np.zeros(model.n_dof, bool)
    fixed[np.asarray(model.fixed_dof)] = True
    fixed3 = fixed.reshape(model.n_node, 3)
    fine_keys = _ravel(dims, node_lat)
    order = np.argsort(fine_keys)
    keys_sorted = fine_keys[order]

    # ---- the brick unit stiffness shared by every level ---------------
    Ke = _brick_Ke(model)
    diag_Ke = np.diag(Ke).copy()

    # ---- coarse levels ------------------------------------------------
    levels = []
    coarse_lams: List[float] = []
    lam_min_coarse = 0.0
    for li, dc in enumerate(level_dims):
        s = 2 ** (li + 1)
        cx, cy, cz = dc
        ck_l = (E_unit.reshape(cx, s, cy, s, cz, s)
                .mean(axis=(1, 3, 5)) * (s * hf))
        # Dirichlet injection: a coarse node fixed iff a fine mesh node
        # exists at the same lattice position and is fixed there; absent
        # positions stay free (safe: the Chebyshev correction operator
        # is PSD even on a singular level operator — module docstring)
        eff_l = np.ones((3, cx + 1, cy + 1, cz + 1))
        cn = np.stack(np.meshgrid(np.arange(cx + 1), np.arange(cy + 1),
                                  np.arange(cz + 1), indexing="ij"),
                      axis=-1).reshape(-1, 3)
        ckeys = _ravel(dims, cn * s)
        pos = np.searchsorted(keys_sorted, ckeys)
        pos_c = np.minimum(pos, len(keys_sorted) - 1)
        present = keys_sorted[pos_c] == ckeys
        nid = order[pos_c]
        for c in range(3):
            fx = np.zeros(len(cn), bool)
            fx[present] = fixed3[nid[present], c]
            eff_l[c] = np.where(fx, 0.0, 1.0).reshape(cx + 1, cy + 1,
                                                      cz + 1)
        dg = _level_diag_np(diag_Ke, ck_l)
        idiag = np.where((dg > 0) & (eff_l > 0), 1.0 / np.where(dg > 0, dg, 1.0), 0.0)
        lam = MG_LAM_SAFETY * _np_level_lam(Ke, ck_l, eff_l, idiag)
        coarse_lams.append(lam)
        lev = {"ck": ck_l, "eff": eff_l,
               "idiag": idiag.reshape(3, -1).T.copy()}   # flat (n, 3)
        if li + 1 < len(level_dims):
            # down-transfer: this level's nodes interpolated from the
            # next coarser grid (spacing ratio 2)
            ln = np.stack(np.meshgrid(np.arange(cx + 1),
                                      np.arange(cy + 1),
                                      np.arange(cz + 1), indexing="ij"),
                          axis=-1).reshape(-1, 3)
            gidx, gw = trilinear_transfer(ln, level_dims[li + 1], 2)
            lev["gidx"], lev["gw"] = gidx, gw
        else:
            lam_min_coarse = _np_level_lam_min(
                Ke, ck_l, eff_l, idiag, lam / MG_LAM_SAFETY)
        levels.append(lev)

    # ---- fine -> first-coarse transfer (part-local layout) ------------
    gid = np.asarray(pm.node_gid)                     # (P, n_node_loc)
    P, nnl = gid.shape
    valid = gid >= 0
    lat_loc = np.zeros((P, nnl, 3), np.int64)
    lat_loc[valid] = node_lat[gid[valid]]
    gidx, gw = trilinear_transfer(lat_loc.reshape(-1, 3), level_dims[0], 2)
    gidx = gidx.reshape(P, nnl, 8)
    gw = gw.reshape(P, nnl, 8)
    gw[~valid] = 0.0                                  # padded local slots

    tree = {
        "fine": {"gidx": gidx, "gw": gw},
        "levels": levels,
        "Ke": Ke,
        # [fine, coarse_1, ..., coarse_L]; slot 0 is a placeholder until
        # estimate_fine_lam fills it post-upload
        "lam": np.asarray([0.0] + coarse_lams, np.float64),
    }
    meta = {"levels": len(level_dims), "degree": int(degree),
            "dims": [int(v) for v in dims]}
    return MGSetup(tree=tree, meta=meta, coarse_lams=coarse_lams,
                   lam_min_coarse=lam_min_coarse)


def _brick_Ke(model) -> np.ndarray:
    """The 24x24 unit (h=1, E=1) brick stiffness the coarse levels
    rediscretize with: the model's own 8-node brick type when one
    exists (bitwise the operator the fine mesh uses for its bricks),
    else the canonical hex element."""
    ot = getattr(model, "octree", None)
    bt = ot.get("brick_type") if ot else None
    if bt is not None and bt in model.elem_lib:
        return np.asarray(model.elem_lib[bt]["Ke"], float)
    for lib in model.elem_lib.values():
        if np.asarray(lib["Ke"]).shape == (24, 24):
            return np.asarray(lib["Ke"], float)
    from pcg_mpi_solver_tpu.models.element import hex_stiffness

    nu = float(model.mat_prop[0]["Pos"]) if model.mat_prop else 0.2
    return hex_stiffness(1.0, 1.0, nu)


# ---------------------------------------------------------------------------
# Traced V-cycle (jnp)
# ---------------------------------------------------------------------------

def _to_grid(flat, dims_c):
    """(n_nodes, 3[, R]) flat level vector -> (3, cx+1, cy+1, cz+1[, R])
    grid (node id = C-order over (ix, iy, iz), matching ``_ravel``)."""
    cx, cy, cz = dims_c
    tail = flat.shape[2:]
    g = flat.reshape((cx + 1, cy + 1, cz + 1, 3) + tail)
    return jnp_moveaxis(g, 3, 0)


def _to_flat(grid):
    """Inverse of :func:`_to_grid`."""
    g = jnp_moveaxis(grid, 0, 3)
    return g.reshape((-1, 3) + g.shape[4:])


def jnp_moveaxis(a, src, dst):
    import jax.numpy as jnp

    return jnp.moveaxis(a, src, dst)


def _level_matvec(Ke, ck, effg, x_flat):
    """Replicated-level assembled stencil matvec: flat (n, 3) -> (n, 3),
    eff-masked in and out.  8 contiguous slices -> one (24, 24) MXU
    einsum -> 8 pad-translate adds — the structured backend's ``gse``
    form on an unsharded grid; NO collectives (the level is replicated,
    every device does the identical work)."""
    import jax.numpy as jnp

    cx, cy, cz = ck.shape
    xg = _to_grid(x_flat, (cx, cy, cz)) * effg
    slots = [xg[:, dx:dx + cx, dy:dy + cy, dz:dz + cz]
             for dx, dy, dz in _CORNERS]
    u = jnp.concatenate(slots, axis=0)               # (24, cx, cy, cz)
    v = jnp.einsum("de,exyz->dxyz", Ke, ck[None] * u)
    y = None
    for a, (dx, dy, dz) in enumerate(_CORNERS):
        t = jnp.pad(v[3 * a:3 * a + 3],
                    ((0, 0), (dx, 1 - dx), (dy, 1 - dy), (dz, 1 - dz)))
        y = t if y is None else y + t
    return _to_flat(y * effg)


def _cheb_smooth(amul, idiag_mul, r, z0, lam, degree: int,
                 alpha: float):
    """Fixed-degree Chebyshev–Jacobi smoothing toward ``A z = r`` on the
    interval ``[lam/alpha, lam]`` (``lam`` already carries the setup
    safety factor).  ``z0=None`` declares a zero start, eliding the
    initial defect matvec (degree-d costs d-1 matvecs from zero, d
    warm).  The recurrence is a FIXED polynomial — no inner products,
    no convergence tests, zero collectives of its own — which is what
    keeps the V-cycle a fixed SPD operator under plain CG."""
    b = lam
    a = lam / alpha
    theta = 0.5 * (b + a)
    delta = 0.5 * (b - a)
    sigma = theta / delta
    rho = 1.0 / sigma
    if z0 is None:
        res = r
        z = None
    else:
        res = r - amul(z0)
        z = z0
    d = idiag_mul(res) / theta
    for _ in range(1, int(degree)):
        z = d if z is None else z + d
        res = r - amul(z)
        rho_new = 1.0 / (2.0 * sigma - rho)
        d = (rho_new * rho) * d + (2.0 * rho_new / delta) * idiag_mul(res)
        rho = rho_new
    return d if z is None else z + d


def _coarse_vcycle(mg, lidx: int, rc, degree: int):
    """Recursive V-cycle over the REPLICATED coarse levels: Chebyshev
    pre/post smoothing, trilinear down/up transfers, a fixed Chebyshev
    sweep on the coarsest level.  Entirely collective-free."""
    import jax.numpy as jnp

    lev = mg["levels"][lidx]
    lam = mg["lam"][lidx + 1]
    Ke = mg["Ke"]
    idiag = lev["idiag"]
    amul = lambda v: _level_matvec(Ke, lev["ck"], lev["eff"], v)
    idiag_mul = lambda v: idiag * v
    if lidx == len(mg["levels"]) - 1:
        return _cheb_smooth(amul, idiag_mul, rc, None, lam,
                            MG_COARSE_DEGREE, MG_COARSE_ALPHA)
    z = _cheb_smooth(amul, idiag_mul, rc, None, lam, degree,
                     MG_SMOOTH_ALPHA)
    s = rc - amul(z)
    gidx, gw = lev["gidx"], lev["gw"]
    n_next = mg["levels"][lidx + 1]["idiag"].shape[0]
    sc = jnp.zeros((n_next, 3), s.dtype).at[gidx.reshape(-1)].add(
        (gw[..., None] * s[:, None, :]).reshape(-1, 3), mode="drop")
    zc = _coarse_vcycle(mg, lidx + 1, sc, degree)
    z = z + (gw[..., None]
             * jnp.take(zc, gidx, axis=0, mode="clip")).sum(axis=1)
    return _cheb_smooth(amul, idiag_mul, rc, z, lam, degree,
                        MG_SMOOTH_ALPHA)


def _vcycle_single(ops, data, m, r):
    """One symmetric V-cycle on a single fine column (P, n_loc)."""
    import jax.numpy as jnp

    mg = data["mg"]
    eff = data["eff"]
    degree = int(ops.mg_degree)
    lam = mg["lam"][0]
    idiag = m["mg_diag"]                  # eff-masked fine inverse diag
    amul = lambda v: eff * ops.matvec(data, v)
    idiag_mul = lambda v: idiag * v

    # pre-smooth from zero: degree-1 matvecs
    z = _cheb_smooth(amul, idiag_mul, r, None, lam, degree,
                     MG_SMOOTH_ALPHA)
    # defect + owner-deduplicated restriction into the replicated
    # first-coarse vector: ONE psum for the whole cycle
    s = r - amul(z)
    f = mg["fine"]
    s3 = ops._as_node3(s) * data["node_weight"][..., None]
    contrib = (f["gw"][..., None] * s3[:, :, None, :])
    n_c0 = mg["levels"][0]["idiag"].shape[0]
    part = jnp.zeros((n_c0, 3), s.dtype).at[f["gidx"].reshape(-1)].add(
        contrib.reshape(-1, 3), mode="drop")
    sc = ops._psum(part)
    # the whole coarse hierarchy is replicated: zero collectives
    zc = _coarse_vcycle(mg, 0, sc, degree)
    # prolongation back to the part-local fine layout: pure local gather
    z3 = (f["gw"][..., None]
          * jnp.take(zc, f["gidx"], axis=0, mode="clip")).sum(axis=2)
    z = z + eff * ops._from_node3(z3)
    # post-smooth (same polynomial as pre — the symmetry requirement)
    return _cheb_smooth(amul, idiag_mul, r, z, lam, degree,
                        MG_SMOOTH_ALPHA)


def mg_apply(ops, data, m, r):
    """Apply the MG preconditioner: ``z = M^-1 r``.

    ``m`` is the prec operand ``make_prec(ops, data, "mg")`` built —
    ``{"mg_diag": eff-masked inverse diag of A, "fb": ()}`` — and the
    hierarchy rides ``data["mg"]``.  ``m["fb"] > 0`` is the recovery
    ladder's DEMOTION switch: the apply becomes a plain scalar-Jacobi
    multiply with whatever diagonal inverse the ladder installed
    (``fallback_prec`` rung; the V-cycle branch is skipped by the cond,
    while its collectives still appear — once — in the traced body, so
    the static collective budget is mode-independent).

    ``r`` may carry a trailing RHS block axis (P, n_loc, nrhs): the
    cycle vmaps over columns — collective COUNTS are independent of the
    block width (payloads widen), the batched-solve contract."""
    import jax
    import jax.numpy as jnp

    if r.ndim == 3:
        return jax.vmap(lambda col: mg_apply(ops, data, m, col),
                        in_axes=-1, out_axes=-1)(r)

    fb = m.get("fb")
    if fb is None:
        return _vcycle_single(ops, data, m, r)
    return jax.lax.cond(
        fb > 0,
        lambda rr: (m["mg_diag"] * rr).astype(rr.dtype),
        lambda rr: _vcycle_single(ops, data, m, rr).astype(rr.dtype),
        r)


def cast_tree(tree: dict, dtype) -> dict:
    """The ``data["mg"]`` subtree with float leaves at the STORAGE dtype
    (a direct-f32 solve must not promote the cycle to f64 through f64
    hierarchy operands); index arrays pass through.  Shared by the
    driver and Newmark constructors."""
    import jax

    dt = np.dtype(dtype)
    return jax.tree.map(
        lambda x: (np.asarray(x).astype(dt)
                   if np.issubdtype(np.asarray(x).dtype, np.floating)
                   else np.asarray(x)), tree)


def fallback_operand(inv):
    """The recovery ladder's DEMOTED prec operand for an mg-configured
    solver: the scalar-Jacobi inverse in the mg prec-operand SHAPE with
    the ``fb`` switch set, so the compiled cycle's apply takes the plain
    scalar branch without recompiling anything (mg_apply)."""
    import jax.numpy as jnp

    return {"mg_diag": inv, "fb": jnp.ones((), jnp.int32)}


def coarse_dofs(meta) -> int:
    """Replicated first-coarse vector length (nodes x 3) of a hierarchy
    with structural ``meta`` — the mg restriction psum's payload size,
    consumed by the comm gauges (Ops.comm_estimate)."""
    if not meta:
        return 0
    half = [d // 2 for d in meta["dims"]]
    return 3 * (half[0] + 1) * (half[1] + 1) * (half[2] + 1)


def install_lam_and_report(setup: MGSetup, lam_fine: float, *, trees,
                           mesh, rep_spec, recorder, wall_s: float,
                           cached: bool) -> None:
    """Post-estimation half of the MG setup, shared by driver and
    Newmark: install the per-level lambda vector into every device tree
    (f64 + the mixed f32 shadow), emit the ``mg_setup`` telemetry event
    + the ``mg.levels`` gauge, and surface the degenerate-Chebyshev-
    interval warning (validate/)."""
    import warnings

    from pcg_mpi_solver_tpu.parallel.distributed import put_sharded
    from pcg_mpi_solver_tpu.validate import check_mg_interval

    lam = np.asarray([lam_fine] + list(setup.coarse_lams), np.float64)
    for t in trees:
        dt = t["mg"]["lam"].dtype
        t["mg"]["lam"] = put_sharded(lam.astype(dt), mesh, rep_spec)
    chk = check_mg_interval(setup.lam_min_coarse,
                            setup.coarse_lams[-1] / MG_LAM_SAFETY)
    if chk.status == "warn":
        warnings.warn(f"[{chk.name}] {chk.detail}")
    recorder.event(
        "mg_setup", levels=int(setup.meta["levels"]),
        degree=int(setup.meta["degree"]),
        dims=list(setup.meta["dims"]),
        lam_fine=round(lam_fine, 6),
        lam_coarse=[round(v, 6) for v in setup.coarse_lams],
        interval=chk.status, cached=bool(cached),
        wall_s=round(wall_s, 6))
    recorder.gauge("mg.levels", int(setup.meta["levels"]))


# ---------------------------------------------------------------------------
# Fine-level eigenvalue bound (device; "a few power-iteration matvecs")
# ---------------------------------------------------------------------------

def estimate_fine_lam(ops, data, mesh, data_specs, part_spec,
                      iters: int = MG_POWER_ITERS) -> float:
    """lambda_max estimate of ``D^-1 A`` on the PARTITIONED fine level:
    a small jitted power-iteration program (one matvec + one norm psum
    per iteration, setup-only — cached in the partition cache by the
    driver so warm runs skip it entirely).  Returns the SAFETY-scaled
    bound ready for ``data["mg"]["lam"][0]``."""
    import jax
    import jax.numpy as jnp

    R = jax.sharding.PartitionSpec()

    def run(data):
        eff = data["eff"]
        w = data["weight"] * eff
        diag = ops.diag(data)
        idiag = jnp.where((eff > 0) & (diag != 0),
                          1.0 / jnp.where(diag != 0, diag, 1.0), 0.0)
        x0 = eff / jnp.maximum(jnp.sqrt(ops.wdot(w, eff, eff)), 1e-30)

        def body(_, c):
            x, _lam = c
            y = idiag * (eff * ops.matvec(data, x))
            nrm = jnp.sqrt(ops.wdot(w, y, y))
            safe = jnp.maximum(nrm, 1e-30)
            return (y / safe).astype(x.dtype), nrm

        _x, lam = jax.lax.fori_loop(
            0, iters, body, (x0.astype(data["eff"].dtype),
                             jnp.asarray(1.0, ops.dot_dtype)))
        return lam

    fn = jax.jit(jax.shard_map(run, mesh=mesh, in_specs=(data_specs,),
                               out_specs=R, check_vma=False))
    lam = float(fn(data))
    if not np.isfinite(lam) or lam <= 0:
        lam = 1.0
    return MG_LAM_SAFETY * lam
