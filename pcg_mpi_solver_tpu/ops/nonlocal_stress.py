"""Nonlocal stress subsystem: Gaussian element neighborhoods + smoothing.

Re-designs the reference's ``config_NonlocalNeighbours``
(partition_mesh.py:1000-1299), which builds — per mesh partition, via
Isend/Recv element-id exchanges and per-element python loops — a sparse
row-normalized weight matrix the dynamics/damage-era solver used to smooth
element stresses over a material length scale.  (The quasi-static reference
solver never consumes it; here the chain is wired end-to-end as the ``NS``
export variable.)

Semantics reproduced exactly (partition_mesh.py:1016-1204):

- cutoff window: a BOX of half-width ``RefLc = Ko * max_i Lc_i`` (Ko = 3.2)
  around each element centroid (Chebyshev metric, not a Euclidean ball);
- same-material filter: only neighbors with the element's own ``PolyMat``;
- weights ``w = exp(-r^2 / (2 Lc^2)) * cellVol`` with Euclidean r,
  ``Lc`` the element's own material length, ``cellVol = level^3``;
- row-normalized (``/= sum`` — removes the boundary effect, :1197).

TPU-native re-design: the neighbor search is one global cKDTree query per
material (no p2p exchanges, no per-element loops), the operator is a global
scipy CSR for host-side (export-path) application plus a padded
gather-multiply form for in-graph device application.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from pcg_mpi_solver_tpu.models.model_data import ModelData

KO_DEFAULT = 3.2   # cutoff multiple of Lc (partition_mesh.py:1017)


def material_lc(model: ModelData, default_factor: float = 2.0) -> np.ndarray:
    """Per-material nonlocal length Lc (MatProp NonLocStressParam, read at
    partition_mesh.py:515-520).  Materials without the parameter default to
    ``default_factor * median(level)``."""
    fallback = default_factor * float(np.median(model.level))
    return np.array([
        float(m.get("NonLocStressParam", {}).get("Lc", fallback))
        for m in model.mat_prop
    ])


@dataclasses.dataclass
class NonlocalWeights:
    """Row-normalized nonlocal smoothing operator W (n_elem x n_elem)."""

    csr: "scipy.sparse.csr_matrix"
    ref_lc: float                 # the box half-width used
    lc: np.ndarray                # per-material Lc

    def apply(self, elem_vals: np.ndarray) -> np.ndarray:
        """Smooth per-element values (n_elem,) or (n_elem, k) on host."""
        return self.csr @ elem_vals

    def padded_arrays(self, pad_multiple: int = 8):
        """(cols, w) padded to a common neighbor count K for device apply:
        ``out[i] = sum_k w[i, k] * vals[cols[i, k]]`` with zero-weight
        padding.  Shapes (n_elem, K)."""
        indptr, indices, data = self.csr.indptr, self.csr.indices, self.csr.data
        n = self.csr.shape[0]
        counts = np.diff(indptr)
        K = int(-(-max(int(counts.max()), 1) // pad_multiple) * pad_multiple)
        cols = np.zeros((n, K), dtype=np.int32)
        w = np.zeros((n, K), dtype=data.dtype)
        # vectorized ragged fill: position of each nnz within its row
        pos = np.arange(len(indices)) - np.repeat(indptr[:-1], counts)
        rows = np.repeat(np.arange(n), counts)
        cols[rows, pos] = indices
        w[rows, pos] = data
        return cols, w


def apply_padded(cols, w, elem_vals):
    """Device-side smoothing: jnp gather-multiply-sum (export path, so the
    gather cost is off the solve hot loop)."""
    import jax.numpy as jnp

    return jnp.sum(w * elem_vals[cols], axis=-1)


def build_nonlocal_weights(
    model: ModelData,
    ko: float = KO_DEFAULT,
    lc: Optional[np.ndarray] = None,
) -> NonlocalWeights:
    """Build W over the whole mesh (replaces the per-partition build +
    boundary-element exchanges, partition_mesh.py:1030-1204)."""
    from scipy.sparse import csr_matrix
    from scipy.spatial import cKDTree

    if lc is None:
        lc = material_lc(model)
    ref_lc = float(ko * np.max(lc))

    sctrs = model.sctrs
    vol = model.level.astype(np.float64) ** 3
    n = model.n_elem

    rows_l, cols_l, vals_l = [], [], []
    for m in range(len(model.mat_prop)):
        idx = np.where(model.poly_mat == m)[0]
        if len(idx) == 0:
            continue
        tree = cKDTree(sctrs[idx])
        # box window: Chebyshev (p=inf) ball of radius RefLc
        # (partition_mesh.py:1104-1130 box test)
        nbr_lists = tree.query_ball_point(sctrs[idx], ref_lc, p=np.inf)
        counts = np.fromiter((len(nb) for nb in nbr_lists), dtype=np.int64,
                             count=len(idx))
        cols_m = idx[np.concatenate([np.asarray(nb, dtype=np.int64)
                                     for nb in nbr_lists])]
        rows_m = np.repeat(idx, counts)
        r = np.linalg.norm(sctrs[rows_m] - sctrs[cols_m], axis=1)
        lc_m = lc[m]
        vals_m = np.exp(-0.5 * r * r / (lc_m * lc_m)) * vol[cols_m]
        rows_l.append(rows_m)
        cols_l.append(cols_m)
        vals_l.append(vals_m)

    rows = np.concatenate(rows_l)
    cols = np.concatenate(cols_l)
    vals = np.concatenate(vals_l)
    W = csr_matrix((vals, (rows, cols)), shape=(n, n))
    # row-normalize (partition_mesh.py:1197)
    rowsum = np.asarray(W.sum(axis=1)).ravel()
    inv = np.where(rowsum > 0, 1.0 / rowsum, 0.0)
    row_of_nnz = np.repeat(np.arange(n), np.diff(W.indptr))
    W = csr_matrix((W.data * inv[row_of_nnz], W.indices, W.indptr), shape=(n, n))
    return NonlocalWeights(csr=W, ref_lc=ref_lc, lc=lc)


# ----------------------------------------------------------------------
# Host-side element stress + nodal averaging (partition-agnostic export path)
# ----------------------------------------------------------------------

def elem_stress_host(model: ModelData, u: np.ndarray) -> np.ndarray:
    """Center-point element stress (n_elem, 6) Voigt from a global solution
    vector, on host: sigma = E * D(nu) . Se . (ce * S.u_e)
    (reference updateElemStrain, pcg_solver.py:601-618 + getNodalPS :755)."""
    from pcg_mpi_solver_tpu.models.element import elasticity_matrix

    E_by_mat = np.array([m["E"] for m in model.mat_prop])
    nu = float(model.mat_prop[0]["Pos"]) if model.mat_prop else 0.2
    D = elasticity_matrix(1.0, nu)
    out = np.zeros((model.n_elem, 6))
    for t, lib in model.elem_lib.items():
        e = np.where(model.elem_type == t)[0]
        if len(e) == 0:
            continue
        Se = lib.get("Se")
        if Se is None:
            raise ValueError(f"element type {t} has no strain-mode matrix Se")
        d = Se.shape[1]
        dofs = _csr_rows(model.elem_dofs_flat, model.elem_dofs_offset, e, d)
        signs = _csr_rows(model.elem_sign_flat, model.elem_dofs_offset, e, d)
        ue = u[dofs]
        ue = np.where(signs, -ue, ue)
        eps = (model.ce[e][:, None] * ue) @ Se.T          # (ne, 6)
        sig = (E_by_mat[model.poly_mat[e]][:, None]) * (eps @ D.T)
        out[e] = sig
    return out


def nodal_average_host(model: ModelData, elem_vals: np.ndarray) -> np.ndarray:
    """Element-constant values -> averaged nodal field on host (the global
    counterpart of Ops.nodal_average; reference getNodalScalarVar,
    pcg_solver.py:655-727)."""
    sums = np.zeros(model.n_node)
    counts = np.zeros(model.n_node)
    reps = np.diff(model.elem_nodes_offset)
    np.add.at(sums, model.elem_nodes_flat, np.repeat(elem_vals, reps))
    np.add.at(counts, model.elem_nodes_flat, 1.0)
    return sums / (counts + 1e-15)


def von_mises_stress(sig: np.ndarray, axis: int = -1) -> np.ndarray:
    """Von Mises equivalent of Voigt stress (XX,YY,ZZ,YZ,XZ,XY)."""
    s = np.moveaxis(sig, axis, 0)
    s11, s22, s33, s23, s13, s12 = s[0], s[1], s[2], s[3], s[4], s[5]
    return np.sqrt(0.5 * ((s11 - s22) ** 2 + (s22 - s33) ** 2 + (s33 - s11) ** 2)
                   + 3.0 * (s23 ** 2 + s13 ** 2 + s12 ** 2))


def _csr_rows(flat, offset, elems, d):
    """(ne, d) rows of a CSR ragged array for constant-width elements."""
    starts = offset[elems]
    return flat[starts[:, None] + np.arange(d)[None, :]]
