"""Stress/strain export fields: principal values + nodal field assembly.

Completes a capability the reference left latent: its strain-mode matrices
(``Se.mat``) are commented out of the partitioner (partition_mesh.py:545,580),
so the documented 'ES'/'PS'/'PE' export variables would KeyError at
pcg_solver.py:875-889.  Here the strain modes are generated with the element
library (models/element.py:hex_strain_mode) and the full chain works:

    u -> eps = Se.(ce*S.u)  per element       (updateElemStrain :601-618)
      -> sigma = (1-omega)*E*D(nu).eps        (getNodalPS :755)
      -> principal values (trig invariant method, descending)
                                              (file_operations.py:251-301)
      -> node-averaged fields with halo-assembled sums/counts
                                              (getNodalScalarVar :655-727)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from pcg_mpi_solver_tpu.models.element import elasticity_matrix


def principal_values(voigt: jnp.ndarray, axis: int = 1) -> jnp.ndarray:
    """Principal values of symmetric 3x3 tensors in Voigt form
    (XX,YY,ZZ,YZ,XZ,XY) along ``axis``; returns 3 values, descending.

    Closed-form trigonometric (Cardano) method — branch-free and batched,
    the same algorithm as the reference (file_operations.py:274-301)."""
    v = jnp.moveaxis(voigt, axis, 0)
    s11, s22, s33, s23, s13, s12 = v[0], v[1], v[2], v[3], v[4], v[5]
    I1 = s11 + s22 + s33
    I2 = s11 * s22 + s22 * s33 + s33 * s11 - s12**2 - s23**2 - s13**2
    I3 = (s11 * s22 * s33 - s11 * s23**2 - s22 * s13**2 - s33 * s12**2
          + 2 * s12 * s23 * s13)
    scale = jnp.max(jnp.abs(v), axis=0)
    J2 = I1 * I1 - 3 * I2 + 1e-24 * scale  # guard (reference :283)
    J2 = jnp.maximum(J2, 0.0)
    # Clamp AFTER the 1.5-power with a dtype-aware tiny: J2**1.5 underflows
    # to 0 for near-degenerate tensors and 0/0 would NaN-poison the all-equal
    # eigenvalue case (e.g. the exactly-zero state of the always-exported
    # initial frame).  With denom clamped, phi_arg -> 0 and f -> 0, giving
    # the correct p_i = I1/3.
    tiny = np.finfo(np.dtype(v.dtype)).tiny
    denom = jnp.maximum(J2**1.5, tiny)
    phi_arg = jnp.clip(0.5 * (2 * I1**3 - 9 * I1 * I2 + 27 * I3) / denom,
                       -1.0, 1.0)
    phi = jnp.arccos(phi_arg) / 3.0
    f = (2.0 / 3.0) * jnp.sqrt(J2)
    p0 = I1 / 3.0 + f * jnp.cos(phi)
    p1 = I1 / 3.0 + f * jnp.cos(phi + 2.0 * jnp.pi / 3.0)
    p2 = I1 / 3.0 + f * jnp.cos(phi + 4.0 * jnp.pi / 3.0)
    stacked = jnp.stack([p0, p1, p2])
    pmax = jnp.max(stacked, axis=0)
    pmin = jnp.min(stacked, axis=0)
    pmid = I1 - pmax - pmin
    return jnp.moveaxis(jnp.stack([pmax, pmid, pmin]), 0, axis)


def eqv_strain(eps: jnp.ndarray, axis: int = 1) -> jnp.ndarray:
    """Von Mises equivalent strain from a Voigt strain vector (engineering
    shear).  The reference's 'ES' comes from its damage model (vestigial
    here); von Mises is the standard scalar equivalent."""
    e = jnp.moveaxis(eps, axis, 0)
    e11, e22, e33, g23, g13, g12 = e[0], e[1], e[2], e[3], e[4], e[5]
    dev = ((e11 - e22)**2 + (e22 - e33)**2 + (e33 - e11)**2) / 2.0
    shear = 3.0 / 4.0 * (g23**2 + g13**2 + g12**2)
    return (2.0 / 3.0) * jnp.sqrt(dev + shear)


def nodal_export_fields(ops, data: dict, un: jnp.ndarray, export_vars, nu: float,
                        omega_list=None) -> dict:
    """Compute every requested nodal export field from the solution.

    Returns {var: (P, n_node_loc)} for var in D, ES, PS1-3, PE1-3
    (reference exportContourData, pcg_solver.py:861-889)."""
    want_pe = any(v.startswith("PE") for v in export_vars)
    want_ps = any(v.startswith("PS") for v in export_vars)
    want_es = "ES" in export_vars
    want_d = "D" in export_vars
    out = {}

    eps_list = None
    if want_pe or want_ps or want_es:
        eps_list = ops.elem_strain(data, un)

    requests = []   # (name, per-block list of (P, k, N))
    if want_d:
        if omega_list is None:
            # damage scaffold: Omega = 0 (reference config_TypeGroupList
            # initializes it so, partition_mesh.py:482)
            omega_list = [jnp.zeros_like(c)[:, None, :]
                          for c in ops.elem_scale(data)]
        requests.append(("D", omega_list))
    if want_es:
        requests.append(("ES", [eqv_strain(e)[:, None] for e in eps_list]))
    if want_pe:
        requests.append(("PE", [principal_values(e) for e in eps_list]))
    if want_ps:
        D = jnp.asarray(elasticity_matrix(1.0, nu), eps_list[0].dtype)
        emods = ops.elem_scale(data)
        sig_list = [E[:, None] * jnp.einsum("st,ptn->psn", D, e)
                    for E, e in zip(emods, eps_list)]
        requests.append(("PS", [principal_values(s) for s in sig_list]))

    for name, vals in requests:
        avg = ops.nodal_average(data, vals)     # (P, k, n_node_loc)
        if name in ("D", "ES"):
            out[name] = avg[:, 0]
        else:
            for i in range(3):
                out[f"{name}{i+1}"] = avg[:, i]
    return out