from pcg_mpi_solver_tpu.ops.matvec import Ops, device_data

__all__ = ["Ops", "device_data"]
