"""Pallas TPU kernel: fused structured-slab matvec (plane-march stencil).

The XLA formulation of the slab matvec (parallel/structured.py) materializes
the gathered corner array ``u`` and the per-cell product ``v`` — two
(24, n_cells) HBM round-trips (~650 MB each way at 10M dofs) plus an 8-step
read-modify-write scatter chain.  The operator itself is a 27-point
block-stencil; its arithmetic intensity is tiny, so HBM traffic is the whole
cost (reference hot loop: one dense matmul + bincount scatter per type,
pcg_solver.py:277-300 — same physics, same bound).

This kernel marches along the x axis one NODE PLANE at a time:

  step i reads  x[:, i:i+2]  (two (3, ny+1, nz+1) node planes, VMEM)
                ck[i]        (one (ny, nz) cell plane)
  computes the cell-plane product  v = Ke @ (ck * u)  as 24x24 unrolled
  VPU plane-FMAs (no (24, cells) array ever exists), and splits it into
  the corner-0 part (finishing output plane i) and the corner-1 part
  (carried in VMEM scratch to finish plane i+1 at the next step).

Every x plane is read exactly twice, ck once, y written once:
~140 MB total at 10M dofs vs ~1.7 GB for the unfused XLA path.

Layout note: planes are (ny+1, nz+1) 2-D VMEM blocks (sublane x lane), all
slice offsets are static (corner shifts in {0,1}), and the only dynamic
index is the leading-axis plane DMA — Mosaic-friendly by construction.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from pcg_mpi_solver_tpu.models.element import HEX_CORNERS

_CORNERS = HEX_CORNERS.astype(np.int64)  # (8, 3) offsets in {0,1}^3


def _matvec_kernel(ke_ref, x_hbm, ck_hbm, y_ref,
                   xv, ckv, carry, dma_sem, ck_sem, *, nx, ny, nz):
    """One grid step = one finished output node plane.

    ke_ref: (24, 24) VMEM (replicated element stiffness)
    x_hbm:  (3, nx+1, ny+1, nz+1) ANY/HBM input grid
    ck_hbm: (nx, ny, nz) ANY/HBM cell stiffness scales
    y_ref:  (3, 1, ny+1, nz+1) VMEM output block (plane i)
    xv:     (3, 2, ny+1, nz+1) VMEM scratch (planes i, i+1)
    ckv:    (1, ny, nz) VMEM scratch
    carry:  (3, ny+1, nz+1) VMEM scratch — corner-1 partial sums for plane i+1
    """
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        carry[...] = jnp.zeros_like(carry)

    @pl.when(i < nx)
    def _work():
        cp_x = pltpu.make_async_copy(
            x_hbm.at[:, pl.ds(i, 2)], xv, dma_sem)
        cp_ck = pltpu.make_async_copy(
            ck_hbm.at[pl.ds(i, 1)], ckv, ck_sem)
        cp_x.start()
        cp_ck.start()
        cp_x.wait()
        cp_ck.wait()

        ck = ckv[0]                                    # (ny, nz)
        # t[e] = ck * gathered corner value, e = 3*corner + comp
        # (models/element.py dof ordering).
        t = [None] * 24
        for a, (dx, dy, dz) in enumerate(_CORNERS):
            for c in range(3):
                t[3 * a + c] = ck * xv[c, dx, dy:dy + ny, dz:dz + nz]
        # v[d] = sum_e Ke[d, e] * t[e]  — unrolled plane-FMAs on the VPU;
        # split by target corner as we go.  Corner placement is a zero-pad
        # (pure concatenate — Mosaic has no scatter-add lowering).
        lo = [jnp.zeros((ny + 1, nz + 1), xv.dtype) for _ in range(3)]
        hi = [jnp.zeros((ny + 1, nz + 1), xv.dtype) for _ in range(3)]
        for b, (ex, ey, ez) in enumerate(_CORNERS):
            for c in range(3):
                d = 3 * b + c
                v = ke_ref[d, 0] * t[0]
                for e in range(1, 24):
                    v = v + ke_ref[d, e] * t[e]
                tgt = lo if ex == 0 else hi
                tgt[c] = tgt[c] + jnp.pad(v, ((ey, 1 - ey), (ez, 1 - ez)))
        for c in range(3):
            y_ref[c, 0] = carry[c] + lo[c]
            carry[c] = hi[c]

    @pl.when(i == nx)
    def _last():
        for c in range(3):
            y_ref[c, 0] = carry[c]


def batched_structured_matvec(xg, ck, Ke, interpret=False):
    """Batched dispatch over the leading parts axis: one kernel launch per
    local part.  The structured backend always has exactly one local slab
    (n_parts == n_devices); the hybrid backend may carry several local
    parts and a few levels — the launches are sequential but share one
    compile cache entry, so the overhead is launch latency only (~us per
    part per level, negligible against a PCG iteration).

    PCG_TPU_PALLAS_V selects the variant (1 = per-plane VPU-FMA, 2 =
    per-plane MXU, 3 = chunked double-buffered MXU, 4 = reshape-free
    chunked — fails Mosaic concat-offset checks on its corner pads,
    5 = layout-legal chunked — fails Mosaic DMA slicing (size-1 sublane
    plane copies), default 6 = v5 compute + slab-aligned DMA,
    docs/RUNBOOK.md).  ``interpret`` runs the kernel through the Pallas
    interpreter (SolverConfig.pallas='interpret') so CI exercises this
    exact dispatch on CPU."""
    fn = selected_variant()[1]
    return jnp.stack([fn(xg[p], ck[p], Ke, interpret=interpret)
                      for p in range(xg.shape[0])])


def _planes_env(fn):
    """Wrap a chunked variant so it reads its chunk size from
    PCG_TPU_PALLAS_PLANES (default 8 — the smallest Mosaic-legal
    block), and trace it with x64 DISABLED: Pallas canonicalizes
    dynamic slice starts to the default int dtype, so under jax x64
    every dynamic memref_slice carries i64 indices — which Mosaic
    rejects — no matter what dtype the kernel passes (chipless x64
    check 2026-07-31).  The kernels are f32-only, so 32-bit tracing
    inside is always correct."""

    def wrapped(xg, ck, Ke, *, interpret=False):
        with jax.enable_x64(False):
            return fn(xg, ck, Ke, interpret=interpret,
                      planes=pallas_planes())

    return wrapped


def pallas_planes() -> int:
    """Resolved PCG_TPU_PALLAS_PLANES (cell planes per output block) —
    the ONE place the default lives.  Cache keys consume this function
    (solver/driver.py AOT step key) rather than copying the default, so
    a default change here re-keys cached step programs instead of
    silently serving a program built with the old block shape."""
    import os

    planes = int(os.environ.get("PCG_TPU_PALLAS_PLANES", "8"))
    if planes % 8 != 0:
        # a typo'd knob would otherwise fail Mosaic lowering and
        # silently degrade pallas='auto' to the XLA path
        raise ValueError(
            f"PCG_TPU_PALLAS_PLANES must be a multiple of 8, "
            f"got {planes}")
    return planes


def selected_variant():
    """(name, fn) of the kernel variant the PCG_TPU_PALLAS_V env knob
    selects — the single source of truth for dispatch AND probing.  Read
    at trace time: toggling the knob after a solver compiled does not
    retrace (build a new Solver to switch).

    PROVISIONAL DEFAULT: v6 passes the build-host chipless compile at
    the 150^3 flagship but the DEPLOYED terminal Mosaic rejects its u
    stack (concat of lane-offset-mismatched rows, HW_SESSION.log
    2026-08-01) — under pallas='auto' the probe burns one failed remote
    compile (~70 s) and degrades to the XLA path.  v9 removes the
    rejected construct class entirely and is the engage candidate; the
    default flips only after a hardware-measured v9 win (a mid-queue
    flip would confound the wave A/B arms)."""
    import os

    v = os.environ.get("PCG_TPU_PALLAS_V", "6")
    if v == "1":
        return "v1", structured_matvec_pallas
    if v == "2":
        return "v2", structured_matvec_pallas_v2
    if v == "3":
        return "v3", _planes_env(structured_matvec_pallas_v3)
    if v == "4":
        return "v4", _planes_env(structured_matvec_pallas_v4)
    if v == "5":
        return "v5", _planes_env(structured_matvec_pallas_v5)
    if v == "7":
        return "v7", _planes_env(structured_matvec_pallas_v7)
    if v == "6":
        return "v6", _planes_env(structured_matvec_pallas_v6)
    if v == "8":
        return "v8", _planes_env(structured_matvec_pallas_v8)
    if v != "9":
        raise ValueError(
            f"PCG_TPU_PALLAS_V must be 1|2|3|4|5|6|7|8|9, got {v!r}")
    return "v9", _planes_env(structured_matvec_pallas_v9)


def probe_shapes(shapes, dtype=jnp.float32) -> None:
    """AOT-compile the kernel for each (node-grid, cell-grid) shape pair;
    raises if any fails.  Used by the driver's pallas='auto' resolution so
    a shape-dependent Mosaic lowering failure degrades to the XLA path at
    init instead of crashing the first jitted step.  Probes the SAME
    variant batched_structured_matvec dispatches to."""
    fn = selected_variant()[1]
    fn = fn if hasattr(fn, "lower") else jax.jit(fn)
    for xg_shape, ck_shape in shapes:
        fn.lower(
            jax.ShapeDtypeStruct(xg_shape, dtype),
            jax.ShapeDtypeStruct(ck_shape, dtype),
            jax.ShapeDtypeStruct((24, 24), dtype)).compile()


@functools.partial(jax.jit, static_argnames=("interpret",))
def structured_matvec_pallas(xg, ck, Ke, *, interpret=False):
    """y = scatter(Ke @ (ck * gather(x))) on one structured slab.

    xg: (3, nx+1, ny+1, nz+1) f32 node grid
    ck: (nx, ny, nz) f32 cell scales
    Ke: (24, 24) f32
    returns y with xg's shape.  Matches StructuredOps.matvec_local (f32).
    """
    _, nxn, nyn, nzn = xg.shape
    nx, ny, nz = nxn - 1, nyn - 1, nzn - 1
    kernel = functools.partial(_matvec_kernel, nx=nx, ny=ny, nz=nz)
    return pl.pallas_call(
        kernel,
        grid=(nx + 1,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),     # Ke
            pl.BlockSpec(memory_space=pl.ANY),         # x (manual DMA)
            pl.BlockSpec(memory_space=pl.ANY),         # ck (manual DMA)
        ],
        out_specs=pl.BlockSpec((3, 1, nyn, nzn), lambda i: (0, i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((3, nxn, nyn, nzn), xg.dtype),
        scratch_shapes=[
            pltpu.VMEM((3, 2, nyn, nzn), xg.dtype),
            pltpu.VMEM((1, ny, nz), ck.dtype),
            pltpu.VMEM((3, nyn, nzn), xg.dtype),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
        interpret=interpret,
    )(Ke, xg, ck)


# ----------------------------------------------------------------------
# v2: flat-lane plane march with a REAL MXU matmul per plane.
#
# v1 computes Ke @ (ck*u) as 576 unrolled VPU plane-FMAs — memory-optimal
# but VPU-compute-bound.  v2 flattens each (ny+1, nz+1) plane into one lane
# axis: with the cell grid padded to NODE-plane strides and ck = 0 in the
# padding (a zero-stiffness cell contributes nothing), every corner gather
# is a contiguous lane slice at a static offset {0, 1, nz+1, nz+2}, the
# element product is one (24,24) @ (24, M) dot_general on the MXU per
# plane, and the scatter is eight shifted lane-slice adds.  Same HBM
# traffic as v1, MXU instead of VPU for the FLOPs.
# ----------------------------------------------------------------------


def _matvec_kernel_v2(ke_ref, x_hbm, ck_hbm, y_ref,
                      xv, ckv, carry, dma_sem, ck_sem, *, nx, m, sy):
    """One grid step = one finished output node plane (flat lanes).

    ke_ref: (24, 24) VMEM
    x_hbm:  (3, nx+1, m) ANY/HBM — node planes, flat (ny+1)*(nz+1) lanes
    ck_hbm: (nx, m) ANY/HBM — cell planes PADDED to node strides, ck=0 pad
    y_ref:  (3, 1, m) VMEM output block (plane i)
    xv:     (3, 2, m + sy + 2) VMEM (planes i, i+1; zero tail for the
            padded-cell gather overhang)
    ckv:    (1, m) VMEM
    carry:  (3, m + sy + 2) VMEM — upper-corner partials for plane i+1
    """
    i = pl.program_id(0)
    mp = m + sy + 2

    @pl.when(i == 0)
    def _init():
        carry[...] = jnp.zeros_like(carry)
        xv[...] = jnp.zeros_like(xv)       # zero gather-overhang tails

    @pl.when(i < nx)
    def _work():
        cp_x = pltpu.make_async_copy(
            x_hbm.at[:, pl.ds(i, 2)], xv.at[:, :, :m], dma_sem)
        cp_ck = pltpu.make_async_copy(
            ck_hbm.at[pl.ds(i, 1)], ckv, ck_sem)
        cp_x.start()
        cp_ck.start()
        cp_x.wait()
        cp_ck.wait()

        ck = ckv[0]                                     # (m,)
        rows = []
        for a, (dx, dy, dz) in enumerate(_CORNERS):
            off = dy * sy + dz
            for c in range(3):
                rows.append(ck * xv[c, dx, off:off + m])
        u = jnp.stack(rows)                             # (24, m)
        v = jax.lax.dot_general(
            ke_ref[...], u, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)         # (24, m) on the MXU
        # corner placement as zero-padded adds (pads with static widths
        # are pure concatenates — Mosaic has no scatter-add lowering)
        lo = jnp.zeros((3, mp), u.dtype)
        hi = jnp.zeros((3, mp), u.dtype)
        for a, (dx, dy, dz) in enumerate(_CORNERS):
            off = dy * sy + dz
            pad = jnp.pad(v[3 * a:3 * a + 3], ((0, 0), (off, mp - off - m)))
            if dx == 0:
                lo = lo + pad
            else:
                hi = hi + pad
        for c in range(3):
            y_ref[c, 0] = (carry[c] + lo[c])[:m]
            carry[c] = hi[c]

    @pl.when(i == nx)
    def _last():
        for c in range(3):
            y_ref[c, 0] = carry[c][:m]


@functools.partial(jax.jit, static_argnames=("interpret",))
def structured_matvec_pallas_v2(xg, ck, Ke, *, interpret=False):
    """Flat-lane MXU variant of :func:`structured_matvec_pallas`.

    Same signature/semantics: xg (3, nx+1, ny+1, nz+1), ck (nx, ny, nz),
    Ke (24, 24), all f32."""
    _, nxn, nyn, nzn = xg.shape
    nx, ny, nz = nxn - 1, nyn - 1, nzn - 1
    m = nyn * nzn
    x_flat = xg.reshape(3, nxn, m)
    ck_pad = jnp.pad(ck, ((0, 0), (0, 1), (0, 1))).reshape(nx, m)
    kernel = functools.partial(_matvec_kernel_v2, nx=nx, m=m, sy=nzn)
    y = pl.pallas_call(
        kernel,
        grid=(nx + 1,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),     # Ke
            pl.BlockSpec(memory_space=pl.ANY),         # x (manual DMA)
            pl.BlockSpec(memory_space=pl.ANY),         # ck (manual DMA)
        ],
        out_specs=pl.BlockSpec((3, 1, m), lambda i: (0, i, 0)),
        out_shape=jax.ShapeDtypeStruct((3, nxn, m), xg.dtype),
        scratch_shapes=[
            pltpu.VMEM((3, 2, m + nzn + 2), xg.dtype),
            pltpu.VMEM((1, m), ck.dtype),
            pltpu.VMEM((3, m + nzn + 2), xg.dtype),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
        interpret=interpret,
    )(Ke, x_flat, ck_pad)
    return y.reshape(3, nxn, nyn, nzn)


# ----------------------------------------------------------------------
# v3: C-plane chunks + double-buffered DMA.
#
# v2 marches one plane per grid step: ~microseconds of work per step, so
# fixed per-step costs (DMA issue/wait latency, loop overhead) dominate.
# v3 processes C cell planes per step.  The flat-lane trick extends to the
# x axis: within a chunk buffer of C+1 node planes, corner (dx,dy,dz) is
# the contiguous lane offset dx*M + dy*(nz+1) + dz, so the whole chunk is
# gathered by 24 slices and multiplied by 8 (24,3)@(3,C*M) MXU dots
# accumulated in VMEM (no (24, C*M) u buffer).  DMA for chunk j+1 is
# issued before chunk j's compute (double buffering).
# ----------------------------------------------------------------------


def _matvec_kernel_v3(ke_ref, x_hbm, ck_hbm, y_ref,
                      xv, ckv, acc, sems, ck_sems, *, g, cpp, nxn, m, sy):
    """One grid step = C finished output node planes (flat lanes).

    ke_ref: (24, 24) VMEM
    x_hbm:  (3, nxn, m) ANY/HBM — NOT padded; tail-chunk plane copies
            beyond nxn are skipped and the stale slot lanes they leave
            behind only ever multiply ck = 0 (ck IS zero-padded)
    ck_hbm: (g*cpp, m) ANY/HBM (zero-padded)
    y_ref:  (3, cpp, m) VMEM output block (planes j*cpp ..< (j+1)*cpp)
    xv:     (2, 3, (cpp+1)*m + sy + 2) VMEM — double-buffered chunk +
            one overlap plane + gather-overhang tail (zeroed once)
    ckv:    (2, cpp, m) VMEM
    acc:    (3, (cpp+1)*m + sy + 2) VMEM — chunk output accumulator;
            its tail plane [cpp*m:] is the carry into the next chunk
    """
    j = pl.program_id(0)
    cm = cpp * m

    def for_chunk(slot, chunk, act):
        """Start or wait the chunk's copies: cpp+1 node planes into flat
        lane offsets of the slot buffer + the ck plane block.  Descriptors
        are recreated identically at wait time (standard double-buffering
        pattern); out-of-range tail planes are skipped on BOTH sides."""
        for k in range(cpp + 1):
            # i32 ALWAYS: the static _init path (chunk = python 0)
            # otherwise traces plane as i64 under jax x64, and
            # Mosaic rejects i64 memref_slice indices (observed
            # on-HW 2026-07-31 from the driver's f64-mode probe)
            plane = jnp.asarray(chunk * cpp + k, jnp.int32)

            @pl.when(plane < nxn)
            def _cp():
                getattr(pltpu.make_async_copy(
                    x_hbm.at[:, plane],
                    xv.at[slot, :, pl.ds(jnp.asarray(k * m, jnp.int32), m)],
                    sems.at[slot]), act)()
        getattr(pltpu.make_async_copy(
            ck_hbm.at[pl.ds(chunk * cpp, cpp)],
            ckv.at[slot], ck_sems.at[slot]), act)()

    @pl.when(j == 0)
    def _init():
        xv[...] = jnp.zeros_like(xv)       # zero overhang tails once
        acc[...] = jnp.zeros_like(acc)
        for_chunk(0, 0, "start")

    # wait for this chunk's data; prefetch the next chunk
    slot = jax.lax.rem(j, jnp.asarray(2, j.dtype))
    for_chunk(slot, j, "wait")

    @pl.when(j + 1 < g)
    def _prefetch():
        for_chunk(1 - slot, j + 1, "start")

    xb = xv[slot]                                       # (3, (cpp+1)m + tail)
    ck = ckv[slot].reshape(1, cm)                       # (1, cm)
    # v = sum_a Ke[:, 3a:3a+3] @ (ck * x_slice_a)  — 8 MXU dots, no
    # (24, cm) gather buffer.  All slice offsets are STATIC (Mosaic has no
    # dynamic_slice lowering; the only dynamic index is the slot read).
    v = None
    for a, (dx, dy, dz) in enumerate(_CORNERS):
        off = dx * m + dy * sy + dz
        t = ck * xb[:, off:off + cm]                    # (3, cm)
        pa = jax.lax.dot_general(
            ke_ref[:, 3 * a:3 * a + 3], t, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        v = pa if v is None else v + pa                 # (24, cm)
    # scatter: out[q + off_e] += v_e[q] as 8 zero-padded adds (Mosaic has
    # no scatter-add lowering; pads with static widths are pure
    # concatenates); the dx offset folds the carry to the next output
    # plane into the accumulator's overlap plane
    mp = (cpp + 1) * m + sy + 2
    out = acc[...]
    for a, (dx, dy, dz) in enumerate(_CORNERS):
        off = dx * m + dy * sy + dz
        out = out + jnp.pad(v[3 * a:3 * a + 3],
                            ((0, 0), (off, mp - off - cm)))
    y_ref[...] = out[:, :cm].reshape(3, cpp, m)
    # roll: overlap plane (+ tail zeros) becomes the next chunk's head
    acc[...] = jnp.pad(out[:, cm:cm + m + sy + 2],
                       ((0, 0), (0, mp - (m + sy + 2))))


@functools.partial(jax.jit, static_argnames=("interpret", "planes"))
def structured_matvec_pallas_v3(xg, ck, Ke, *, interpret=False, planes=8):
    """Chunked double-buffered variant of :func:`structured_matvec_pallas_v2`.

    Same signature/semantics; ``planes`` = cell planes per grid step.
    Default 8: the deployed Mosaic toolchain requires the last two dims
    of the output BlockSpec — (planes, m) here — to be (8, 128)-divisible
    or equal to the full array dims (docs/RUNBOOK.md "Mosaic lowering
    constraints"); m is the full lane axis, so planes must be a multiple
    of 8.  Override with PCG_TPU_PALLAS_PLANES (multiples of 8)."""
    _, nxn, nyn, nzn = xg.shape
    nx, ny, nz = nxn - 1, nyn - 1, nzn - 1
    m = nyn * nzn
    cpp = max(1, min(planes, nx + 1))
    g = -(-(nx + 1) // cpp)                 # ceil: covers all output planes
    x_flat = xg.reshape(3, nxn, m)          # free reshape, no copy
    # single pad; loop-invariant, so XLA hoists it out of the PCG loop
    ck_pad = jnp.pad(ck, ((0, g * cpp - nx), (0, 1), (0, 1))) \
        .reshape(g * cpp, m)
    kernel = functools.partial(_matvec_kernel_v3, g=g, cpp=cpp, nxn=nxn,
                               m=m, sy=nzn)
    y = pl.pallas_call(
        kernel,
        grid=(g,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),     # Ke
            pl.BlockSpec(memory_space=pl.ANY),         # x (manual DMA)
            pl.BlockSpec(memory_space=pl.ANY),         # ck (manual DMA)
        ],
        out_specs=pl.BlockSpec((3, cpp, m), lambda j: (0, j, 0)),
        out_shape=jax.ShapeDtypeStruct((3, g * cpp, m), xg.dtype),
        scratch_shapes=[
            pltpu.VMEM((2, 3, (cpp + 1) * m + nzn + 2), xg.dtype),
            pltpu.VMEM((2, cpp, m), ck.dtype),
            pltpu.VMEM((3, (cpp + 1) * m + nzn + 2), xg.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
    )(Ke, x_flat, ck_pad)
    return y[:, :nxn].reshape(3, nxn, nyn, nzn)


# ----------------------------------------------------------------------
# v4: v3's chunked double-buffered DMA, v2's per-plane compute — and NO
# lane-merging reshapes.
#
# The 2026-07-30 hardware session pinned v3's Mosaic failure to its
# (cpp, m) -> (cpp*m,) shape casts ("infer-vector-layout: unsupported
# shape cast" on tpu.reshape when m is not 128-divisible — m = nyn*nzn
# is 22801 at the flagship).  v4 keeps the plane axis as a real (sublane-
# tiled) array axis end to end: chunk buffers are (3, cpp+1, m+tail), a
# corner's dx offset selects a PLANE (static index) instead of a +dx*m
# lane offset, and each of the cpp planes in the chunk runs v2's flat-
# lane math ((24, m) stack -> one (24,24)@(24,m) MXU dot -> eight
# zero-padded lane adds).  Per-step cost stays chunk-sized (v3's fix for
# v2's per-plane grid overhead), the output BlockSpec is (3, cpp, m)
# with cpp % 8 == 0 and m the full lane axis — Mosaic-legal — and every
# slice offset is static.
# ----------------------------------------------------------------------


def _matvec_kernel_v4(ke_ref, x_hbm, ck_hbm, y_ref,
                      xv, ckv, acc, sems, ck_sems, *, g, cpp, nxn, m, sy):
    """One grid step = cpp finished output node planes.

    ke_ref: (24, 24) VMEM
    x_hbm:  (3, nxn, m) ANY/HBM — NOT padded; tail-chunk plane copies
            beyond nxn are skipped and the stale slot lanes they leave
            behind only ever multiply ck = 0 (ck IS zero-padded)
    ck_hbm: (g*cpp, m) ANY/HBM (zero-padded)
    y_ref:  (3, cpp, m) VMEM output block (planes j*cpp ..< (j+1)*cpp)
    xv:     (2, 3, cpp+1, m + sy + 2) VMEM — double-buffered node-plane
            chunk + one overlap plane; lane tail for the per-plane
            gather overhang (zeroed once, only ever multiplies ck = 0)
    ckv:    (2, cpp, m) VMEM
    acc:    (3, m + sy + 2) VMEM — carry: the chunk's last cell plane's
            upper-corner (dx=1) partials, finishing the NEXT chunk's
            first output plane
    """
    # i32 index arithmetic ALWAYS: under jax x64 (the solver's f64 dot
    # mode) program_id arithmetic otherwise promotes to i64, and Mosaic
    # rejects i64 memref_slice indices (observed on-HW 2026-07-30:
    # "tpu.memref_slice ... (i32, i64, i32)" VerificationError from the
    # driver's probe while the same kernel passed DMA under plain i32)
    j = jnp.asarray(pl.program_id(0), jnp.int32)
    mt = m + sy + 2

    def for_chunk(slot, chunk, act):
        """Start or wait the chunk's copies: cpp+1 node planes (each into
        the :m lanes of its own plane row) + the ck plane block.
        Descriptors are recreated identically at wait time (standard
        double-buffering pattern); out-of-range tail planes are skipped
        on BOTH sides."""
        for k in range(cpp + 1):
            # i32 ALWAYS: the static _init path (chunk = python 0)
            # otherwise traces plane as i64 under jax x64, and
            # Mosaic rejects i64 memref_slice indices (observed
            # on-HW 2026-07-31 from the driver's f64-mode probe)
            plane = jnp.asarray(chunk * cpp + k, jnp.int32)

            @pl.when(plane < nxn)
            def _cp():
                getattr(pltpu.make_async_copy(
                    x_hbm.at[:, plane],
                    xv.at[slot, :, jnp.asarray(k, jnp.int32),
                          pl.ds(jnp.asarray(0, jnp.int32), m)],
                    sems.at[slot]), act)()
        getattr(pltpu.make_async_copy(
            ck_hbm.at[pl.ds(chunk * cpp, cpp)],
            ckv.at[slot], ck_sems.at[slot]), act)()

    @pl.when(j == 0)
    def _init():
        xv[...] = jnp.zeros_like(xv)       # zero overhang tails once
        acc[...] = jnp.zeros_like(acc)
        for_chunk(0, 0, "start")

    # wait for this chunk's data; prefetch the next chunk
    slot = jax.lax.rem(j, jnp.asarray(2, j.dtype))
    for_chunk(slot, j, "wait")

    @pl.when(j + 1 < g)
    def _prefetch():
        for_chunk(1 - slot, j + 1, "start")

    xb = xv[slot]                                       # (3, cpp+1, mt)
    ckb = ckv[slot]                                     # (cpp, m)
    carry = acc[...]                                    # (3, mt)
    for k in range(cpp):
        ck = ckb[k]                                     # (m,)
        # u[e] = ck * corner value; dx picks the PLANE (static), dy/dz a
        # static lane offset — no flattened-x layout, hence no reshape
        rows = []
        for a, (dx, dy, dz) in enumerate(_CORNERS):
            off = dy * sy + dz
            for c in range(3):
                rows.append(ck * xb[c, k + dx, off:off + m])
        u = jnp.stack(rows)                             # (24, m)
        v = jax.lax.dot_general(
            ke_ref[...], u, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)         # (24, m) on the MXU
        # corner placement as zero-padded lane adds (Mosaic has no
        # scatter-add lowering); dx routes to this output plane (lo) or
        # the next one (hi -> carry)
        lo = jnp.zeros((3, mt), u.dtype)
        hi = jnp.zeros((3, mt), u.dtype)
        for a, (dx, dy, dz) in enumerate(_CORNERS):
            off = dy * sy + dz
            pad = jnp.pad(v[3 * a:3 * a + 3], ((0, 0), (off, mt - off - m)))
            if dx == 0:
                lo = lo + pad
            else:
                hi = hi + pad
        out = carry + lo
        for c in range(3):
            y_ref[c, k] = out[c, :m]
        carry = hi
    acc[...] = carry


# ----------------------------------------------------------------------
# v5: v4 minus every Mosaic-illegal layout op.  The 2026-07-31 hardware
# session pinned v4's failure to its corner-placement pads:
#
#   tpu.concatenate (3x22801)+(3x153) -> (3x22954), in_layouts
#   {3,0} / {0,17} — "result/input offset mismatch on non-concat
#   dimension"
#
# i.e. (a) v[3a:3a+3] — a slice of a LOADED vector — carries sublane
# offset 3 while the pad's zeros are offset 0, and (b) the pad boundary
# m = 22801 = 17 (mod 128) puts the zeros at a misaligned lane offset.
# Three surgical fixes, same dataflow as v4 otherwise:
#
#   1. the per-corner product block is produced by its OWN small dot
#      ke[3a:3a+3] @ u — a fresh dot result gets a canonical {0,0}
#      layout, unlike v4's v[3a:3a+3] vector slice (sublane offset 3).
#      8 M=3 dots cost ~2.7x the one M=24 dot in MXU time, but the MXU
#      is ~0.3 us/plane against an HBM-bound kernel — irrelevant.
#   2. the lane axis is padded to m128 (a 128-multiple) on the host, so
#      the only remaining concatenate — the right-pad to mt128 — joins
#      at an aligned lane boundary with both inputs at {0,0}.
#   3. corner lane placement is pltpu.roll (tpu rotate primitive), not
#      an offset pad; the cyclic wrap only ever carries the zeroed lane
#      tail (mt128 - off >= m128 for every corner offset).
# ----------------------------------------------------------------------


def _matvec_kernel_v5(ke_ref, x_hbm, ck_hbm, y_ref,
                      xv, ckv, acc, sems, ck_sems,
                      *, g, cpp, nxn, m128, mt128, sy):
    """One grid step = cpp finished output node planes.

    ke_ref: (24, 24) VMEM
    x_hbm:  (3, nxn, m) ANY/HBM — NOT lane-padded (padding x would cost
            a full extra HBM round trip of the grid per matvec); VMEM
            rows are m128-wide, lanes [m:m128] stay zero from _init and
            only ever multiply ck = 0 (ck_hbm IS lane-padded — that pad
            is loop-invariant, so XLA hoists it out of the PCG loop)
    ck_hbm: (g*cpp, m128) ANY/HBM (zero-padded both axes)
    y_ref:  (3, cpp, m128) VMEM output block
    xv:     (2, 3, cpp+1, mt128) VMEM double-buffered chunk + overlap
            plane; zeroed lane tail holds the corner-read overhang
    ckv:    (2, cpp, m128) VMEM
    acc:    (3, mt128) VMEM — dx=1 partials carried to the next plane
    """
    j = jnp.asarray(pl.program_id(0), jnp.int32)  # i32 ALWAYS (see v4)
    m = x_hbm.shape[-1]

    def for_chunk(slot, chunk, act):
        for k in range(cpp + 1):
            # i32 ALWAYS: the static _init path (chunk = python 0)
            # otherwise traces plane as i64 under jax x64, and
            # Mosaic rejects i64 memref_slice indices (observed
            # on-HW 2026-07-31 from the driver's f64-mode probe)
            plane = jnp.asarray(chunk * cpp + k, jnp.int32)

            @pl.when(plane < nxn)
            def _cp():
                getattr(pltpu.make_async_copy(
                    x_hbm.at[:, plane],
                    xv.at[slot, :, jnp.asarray(k, jnp.int32),
                          pl.ds(jnp.asarray(0, jnp.int32), m)],
                    sems.at[slot]), act)()
        getattr(pltpu.make_async_copy(
            ck_hbm.at[pl.ds(chunk * cpp, cpp)],
            ckv.at[slot], ck_sems.at[slot]), act)()

    @pl.when(j == 0)
    def _init():
        xv[...] = jnp.zeros_like(xv)       # zero overhang tails once
        acc[...] = jnp.zeros_like(acc)
        for_chunk(0, 0, "start")

    slot = jax.lax.rem(j, jnp.asarray(2, j.dtype))
    for_chunk(slot, j, "wait")

    @pl.when(j + 1 < g)
    def _prefetch():
        for_chunk(1 - slot, j + 1, "start")

    ke = ke_ref[...]                                    # (24, 24)
    xb = xv[slot]                                       # (3, cpp+1, mt128)
    ckb = ckv[slot]                                     # (cpp, m128)
    carry = acc[...]                                    # (3, mt128)
    for k in range(cpp):
        ck = ckb[k]                                     # (m128,)
        rows = []
        for a, (dx, dy, dz) in enumerate(_CORNERS):
            off = dy * sy + dz
            for c in range(3):
                rows.append(ck * xb[c, k + dx, off:off + m128])
        u = jnp.stack(rows)                             # (24, m128)
        lo = jnp.zeros((3, mt128), u.dtype)
        hi = jnp.zeros((3, mt128), u.dtype)
        for b, (dx, dy, dz) in enumerate(_CORNERS):
            off = dy * sy + dz
            blk = jax.lax.dot_general(
                ke[3 * b:3 * b + 3], u, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)     # (3, m128), {0,0}
            vp = jnp.pad(blk, ((0, 0), (0, mt128 - m128)))  # aligned concat
            if off:
                vp = pltpu.roll(vp, off, 1)             # lane rotate
            if dx == 0:
                lo = lo + vp
            else:
                hi = hi + vp
        out = carry + lo
        for c in range(3):
            y_ref[c, k] = out[c, :m128]
        carry = hi
    acc[...] = carry


@functools.partial(jax.jit, static_argnames=("interpret", "planes"))
def structured_matvec_pallas_v5(xg, ck, Ke, *, interpret=False, planes=8):
    """Layout-legal variant of :func:`structured_matvec_pallas_v4`.

    Same signature/semantics: xg (3, nx+1, ny+1, nz+1), ck (nx, ny, nz),
    Ke (24, 24), all f32; ``planes`` = cell planes per grid step
    (multiple of 8 — the output BlockSpec's sublane axis)."""
    _, nxn, nyn, nzn = xg.shape
    nx = nxn - 1
    m = nyn * nzn
    m128 = -(-m // 128) * 128
    sy = nzn
    mt128 = m128 + (-(-(sy + 2) // 128)) * 128
    cpp = max(1, min(planes, ((nx + 1 + 7) // 8) * 8))
    g = -(-(nx + 1) // cpp)                 # ceil: covers all output planes
    x_flat = xg.reshape(3, nxn, m)          # free reshape, no copy
    # ck pads are loop-invariant, so XLA hoists them out of the PCG loop
    ck_pad = jnp.pad(ck, ((0, g * cpp - nx), (0, 1), (0, 1))) \
        .reshape(g * cpp, m)
    ck_pad = jnp.pad(ck_pad, ((0, 0), (0, m128 - m)))
    kernel = functools.partial(_matvec_kernel_v5, g=g, cpp=cpp, nxn=nxn,
                               m128=m128, mt128=mt128, sy=sy)
    y = pl.pallas_call(
        kernel,
        grid=(g,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),     # Ke
            pl.BlockSpec(memory_space=pl.ANY),         # x (manual DMA)
            pl.BlockSpec(memory_space=pl.ANY),         # ck (manual DMA)
        ],
        out_specs=pl.BlockSpec((3, cpp, m128), lambda j: (0, j, 0)),
        out_shape=jax.ShapeDtypeStruct((3, g * cpp, m128), xg.dtype),
        scratch_shapes=[
            pltpu.VMEM((2, 3, cpp + 1, mt128), xg.dtype),
            pltpu.VMEM((2, cpp, m128), ck.dtype),
            pltpu.VMEM((3, mt128), xg.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
    )(Ke, x_flat, ck_pad)
    return y[:, :nxn, :m].reshape(3, nxn, nyn, nzn)


@functools.partial(jax.jit, static_argnames=("interpret", "planes"))
def structured_matvec_pallas_v4(xg, ck, Ke, *, interpret=False, planes=8):
    """Reshape-free chunked variant of :func:`structured_matvec_pallas_v3`.

    Same signature/semantics: xg (3, nx+1, ny+1, nz+1), ck (nx, ny, nz),
    Ke (24, 24), all f32; ``planes`` = cell planes per grid step
    (multiple of 8 — the output BlockSpec's sublane axis)."""
    _, nxn, nyn, nzn = xg.shape
    nx = nxn - 1
    m = nyn * nzn
    cpp = max(1, min(planes, ((nx + 1 + 7) // 8) * 8))
    g = -(-(nx + 1) // cpp)                 # ceil: covers all output planes
    x_flat = xg.reshape(3, nxn, m)          # free reshape, no copy
    # single pad; loop-invariant, so XLA hoists it out of the PCG loop
    ck_pad = jnp.pad(ck, ((0, g * cpp - nx), (0, 1), (0, 1))) \
        .reshape(g * cpp, m)
    kernel = functools.partial(_matvec_kernel_v4, g=g, cpp=cpp, nxn=nxn,
                               m=m, sy=nzn)
    y = pl.pallas_call(
        kernel,
        grid=(g,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),     # Ke
            pl.BlockSpec(memory_space=pl.ANY),         # x (manual DMA)
            pl.BlockSpec(memory_space=pl.ANY),         # ck (manual DMA)
        ],
        out_specs=pl.BlockSpec((3, cpp, m), lambda j: (0, j, 0)),
        out_shape=jax.ShapeDtypeStruct((3, g * cpp, m), xg.dtype),
        scratch_shapes=[
            pltpu.VMEM((2, 3, cpp + 1, m + nzn + 2), xg.dtype),
            pltpu.VMEM((2, cpp, m), ck.dtype),
            pltpu.VMEM((3, m + nzn + 2), xg.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
    )(Ke, x_flat, ck_pad)
    return y[:, :nxn].reshape(3, nxn, nyn, nzn)


# ----------------------------------------------------------------------
# v6: v5's compute, slab-aligned DMA.
#
# The 2026-07-31 wave-3 A/B showed v5 lowering PAST v4's concat error
# into the DMA legality check v1 hit from the start:
#
#   tpu.memref_slice (3,152,22912) -> (3,1,22801): "Slice shape along
#   dimension 1 must be aligned to tiling (8), but is 1"
#
# i.e. on this toolchain a DMA may slice a TILED dimension only in
# multiples of the tile (8 sublanes / 128 lanes) at tile-aligned
# offsets; the per-plane x copies (one node plane = a size-1 sublane
# slice) that every variant v1-v5 used are categorically illegal —
# v3/v4 just died in earlier layout passes before reaching this check.
# v6 keeps v5's compute body (fresh per-corner dots, m128-aligned pads,
# pltpu.roll placement — everything v4/v5 already fixed) and makes every
# DMA slab-aligned:
#
#   1. x is host-padded to (3, g*cpp + 8, m128) — lanes to a
#      128-multiple, planes so every slab read is in bounds.  The pad is
#      one extra HBM round-trip of x per matvec (~0.1 ms at the 10M-dof
#      flagship) — acceptable until the structured backend keeps x in
#      padded layout natively.
#   2. each grid step DMAs ONE slab of cpp+8 planes (cpp % 8 == 0, so
#      both the chunk offset j*cpp and the slice shape cpp+8 are
#      8-aligned) at FULL m128 lane width into rows [0, cpp+8) of the
#      mt128-wide chunk buffer (lane slice offset 0, shape m128 — a
#      128-multiple).  The 8 extra planes per chunk cover the +dx=1
#      corner overlap (only 1 is needed; 8 is the smallest legal slab),
#      costing 2x x reads at cpp=8 — ~84 MB/matvec at the flagship
#      against the unfused path's ~1.7 GB.
#   3. ck was already slab-copied (cpp planes, m128 lanes) — unchanged.
# ----------------------------------------------------------------------


def _matvec_kernel_v6(ke_ref, x_hbm, ck_hbm, y_ref,
                      xv, ckv, acc, sems, ck_sems,
                      *, g, cpp, m128, mt128, sy):
    """One grid step = cpp finished output node planes.

    ke_ref: (24, 24) VMEM
    x_hbm:  (3, g*cpp + 8, m128) ANY/HBM — lane- AND plane-padded on the
            host (see v6 header note); pad lanes/planes are zero, and
            out-of-range corner reads contribute nothing because the
            OUTPUT block is scaled by ck = 0 there
    ck_hbm: (g*cpp, m128) ANY/HBM (zero-padded both axes)
    y_ref:  (3, cpp, m128) VMEM output block
    xv:     (2, 3, cpp+8, mt128) VMEM double-buffered slab; lanes
            [m128, mt128) stay zero from _init and hold the corner-read
            overhang
    ckv:    (2, cpp, m128) VMEM
    acc:    (3, mt128) VMEM — dx=1 partials carried to the next plane
    """
    j = jnp.asarray(pl.program_id(0), jnp.int32)  # i32 ALWAYS (see v4)

    def for_chunk(slot, chunk, act):
        # NOTE on index dtypes: Pallas canonicalizes indices to the
        # DEFAULT int dtype, so under jax x64 every dynamic memref_slice
        # would carry i64 indices — which Mosaic rejects — regardless of
        # what dtype is passed here.  The fix is structural: _planes_env
        # traces every kernel under jax.enable_x64(False) (verified
        # sufficient by the chipless x64 checks, 2026-07-31).
        c0 = jnp.asarray(chunk * cpp, jnp.int32)
        z = jnp.asarray(0, jnp.int32)
        getattr(pltpu.make_async_copy(
            x_hbm.at[:, pl.ds(c0, cpp + 8), :],
            xv.at[slot, :, :, pl.ds(z, m128)], sems.at[slot]), act)()
        getattr(pltpu.make_async_copy(
            ck_hbm.at[pl.ds(c0, cpp)],
            ckv.at[slot], ck_sems.at[slot]), act)()

    @pl.when(j == 0)
    def _init():
        xv[...] = jnp.zeros_like(xv)       # zero overhang tails once
        acc[...] = jnp.zeros_like(acc)
        for_chunk(0, 0, "start")

    slot = jax.lax.rem(j, jnp.asarray(2, j.dtype))
    for_chunk(slot, j, "wait")

    @pl.when(j + 1 < g)
    def _prefetch():
        for_chunk(1 - slot, j + 1, "start")

    # ---- compute: v5's corner dots and roll placement, with ck HOISTED
    # OUT of the contraction: ck[l] is per CELL (lane l), identical for
    # all 24 gathered rows, so  sum_e Ke[d,e]*(ck*x_e) == ck*sum_e(...)
    # — the output block is scaled ONCE instead of 24 input rows.  The
    # 24 scaled input vectors were the kernel's Mosaic scoped-vmem hot
    # spot: the unrolled plane loop's live arena overflowed VMEM at any
    # m (chipless-compile bisection 2026-07-31); raw xb slices are views
    # and cost nothing.
    ke = ke_ref[...]                                    # (24, 24)
    xb = xv[slot]                                       # (3, cpp+8, mt128)
    ckb = ckv[slot]                                     # (cpp, m128)
    carry = acc[...]                                    # (3, mt128)
    for k in range(cpp):
        ck = ckb[k]                                     # (m128,)
        rows = []
        for a, (dx, dy, dz) in enumerate(_CORNERS):
            off = dy * sy + dz
            for c in range(3):
                rows.append(xb[c, k + dx, off:off + m128])
        u = jnp.stack(rows)                             # (24, m128)
        lo = jnp.zeros((3, mt128), u.dtype)
        hi = jnp.zeros((3, mt128), u.dtype)
        for b, (dx, dy, dz) in enumerate(_CORNERS):
            off = dy * sy + dz
            blk = jax.lax.dot_general(
                ke[3 * b:3 * b + 3], u, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)     # (3, m128), {0,0}
            blk = ck * blk                              # hoisted ck scale
            vp = jnp.pad(blk, ((0, 0), (0, mt128 - m128)))  # aligned concat
            if off:
                vp = pltpu.roll(vp, off, 1)             # lane rotate
            if dx == 0:
                lo = lo + vp
            else:
                hi = hi + vp
        out = carry + lo
        for c in range(3):
            y_ref[c, k] = out[c, :m128]
        carry = hi
    acc[...] = carry


@functools.partial(jax.jit, static_argnames=("interpret", "planes"))
def structured_matvec_pallas_v6(xg, ck, Ke, *, interpret=False, planes=8):
    """Slab-DMA variant of :func:`structured_matvec_pallas_v5`.

    Same signature/semantics: xg (3, nx+1, ny+1, nz+1), ck (nx, ny, nz),
    Ke (24, 24), all f32; ``planes`` = cell planes per grid step
    (multiple of 8 — the output BlockSpec's sublane axis AND the DMA
    slab alignment).  VMEM budget caps planes at 8 for flagship m."""
    _, nxn, nyn, nzn = xg.shape
    nx = nxn - 1
    m = nyn * nzn
    m128 = -(-m // 128) * 128
    sy = nzn
    mt128 = m128 + (-(-(sy + 2) // 128)) * 128
    cpp = max(1, min(planes, ((nx + 1 + 7) // 8) * 8))
    g = -(-(nx + 1) // cpp)                 # ceil: covers all output planes
    x_flat = xg.reshape(3, nxn, m)          # free reshape, no copy
    # x pad: ONE fused pad to (planes, lanes) the slab DMA can read
    # whole; costs an extra HBM round trip of x per matvec (v6 header).
    x_pad = jnp.pad(x_flat, ((0, 0), (0, g * cpp + 8 - nxn), (0, m128 - m)))
    # ck pads are loop-invariant, so XLA hoists them out of the PCG loop
    ck_pad = jnp.pad(ck, ((0, g * cpp - nx), (0, 1), (0, 1))) \
        .reshape(g * cpp, m)
    ck_pad = jnp.pad(ck_pad, ((0, 0), (0, m128 - m)))
    kernel = functools.partial(_matvec_kernel_v6, g=g, cpp=cpp,
                               m128=m128, mt128=mt128, sy=sy)
    y = pl.pallas_call(
        kernel,
        grid=(g,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),     # Ke
            pl.BlockSpec(memory_space=pl.ANY),         # x (manual DMA)
            pl.BlockSpec(memory_space=pl.ANY),         # ck (manual DMA)
        ],
        out_specs=pl.BlockSpec((3, cpp, m128), lambda j: (0, j, 0)),
        out_shape=jax.ShapeDtypeStruct((3, g * cpp, m128), xg.dtype),
        scratch_shapes=[
            pltpu.VMEM((2, 3, cpp + 8, mt128), xg.dtype),
            pltpu.VMEM((2, cpp, m128), ck.dtype),
            pltpu.VMEM((3, mt128), xg.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        # the unrolled plane loop's live arena exceeds the 16 MB default
        # scoped limit at >=128^3 (chipless bisection 2026-07-31); v5e
        # VMEM is far larger — raise the per-kernel cap
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=64 * 1024 * 1024),
        interpret=interpret,
    )(Ke, x_pad, ck_pad)
    return y[:, :nxn, :m].reshape(3, nxn, nyn, nzn)


# ----------------------------------------------------------------------
# v7: v6's slab DMA, roll-only compute.
#
# v6 still contains two op classes Mosaic has never been observed to
# lower in this kernel family (every variant so far died at its first
# unproven op, serially): (a) VALUE lane-slices at unaligned offsets —
# the u build reads xb[c, k+dx, off:off+m128] at off = dy*sy+dz, whose
# result carries a non-canonical lane-offset layout into an elementwise
# multiply and a 24-row stack (v4's concat rejection came from exactly
# such offset layouts); (b) the output pad-concat at the m128 boundary.
# v7 removes both: every lane placement — input gather AND output
# placement — is a pltpu.roll (tpu rotate, canonical {0,0} result) of a
# full mt128-wide row, and the zero tail of the ck mask kills the
# cyclic wrap:
#
#   input:  u_row = ck_mt * roll(x_row, mt128 - off)   # u[l] = ck*x[l+off]
#           (wrap lanes l >= mt128-off carry head values, but ck_mt is
#           zero for l >= m, and l+off never wraps for l < m)
#   dot:    ke[3b:3b+3] @ u  -> (3, mt128), {0,0}, no pad needed
#   output: roll(blk, +off) accumulated into mt128-wide lo/hi
#
# ck is host-padded to mt128 (not m128) so its DMA stays full-width and
# no in-kernel pad exists at all.
# ----------------------------------------------------------------------


def _matvec_kernel_v7(ke_ref, x_hbm, ck_hbm, y_ref,
                      xv, ckv, acc, sems, ck_sems,
                      *, g, cpp, m128, mt128, sy):
    """One grid step = cpp finished output node planes.

    ke_ref: (24, 24) VMEM
    x_hbm:  (3, g*cpp + 8, m128) ANY/HBM (lane- and plane-padded, zeros)
    ck_hbm: (g*cpp, mt128) ANY/HBM (zero-padded both axes, FULL mt width)
    y_ref:  (3, cpp, m128) VMEM output block
    xv:     (2, 3, cpp+8, mt128) VMEM double-buffered slab; lanes
            [m128, mt128) stay zero from _init
    ckv:    (2, cpp, mt128) VMEM
    acc:    (3, mt128) VMEM — dx=1 partials carried to the next plane
    """
    j = jnp.asarray(pl.program_id(0), jnp.int32)  # i32 ALWAYS (see v4)

    def for_chunk(slot, chunk, act):
        # i32 ALWAYS, including literal zeros (index promotion, see v6)
        c0 = jnp.asarray(chunk * cpp, jnp.int32)
        z = jnp.asarray(0, jnp.int32)
        getattr(pltpu.make_async_copy(
            x_hbm.at[:, pl.ds(c0, cpp + 8), :],
            xv.at[slot, :, :, pl.ds(z, m128)], sems.at[slot]), act)()
        getattr(pltpu.make_async_copy(
            ck_hbm.at[pl.ds(c0, cpp)],
            ckv.at[slot], ck_sems.at[slot]), act)()

    @pl.when(j == 0)
    def _init():
        xv[...] = jnp.zeros_like(xv)       # zero overhang tails once
        acc[...] = jnp.zeros_like(acc)
        for_chunk(0, 0, "start")

    slot = jax.lax.rem(j, jnp.asarray(2, j.dtype))
    for_chunk(slot, j, "wait")

    @pl.when(j + 1 < g)
    def _prefetch():
        for_chunk(1 - slot, j + 1, "start")

    ke = ke_ref[...]                                    # (24, 24)
    xb = xv[slot]                                       # (3, cpp+8, mt128)
    ckb = ckv[slot]                                     # (cpp, mt128)
    carry = acc[...]                                    # (3, mt128)
    for k in range(cpp):
        ck = ckb[k]                                     # (mt128,), 0 tail
        rows = []
        for a, (dx, dy, dz) in enumerate(_CORNERS):
            off = int(dy * sy + dz)
            for c in range(3):
                base = xb[c, k + dx]                    # (mt128,) full row
                if off:
                    base = pltpu.roll(base, mt128 - off, 0)
                rows.append(ck * base)
        u = jnp.stack(rows)                             # (24, mt128), {0,0}
        lo = jnp.zeros((3, mt128), u.dtype)
        hi = jnp.zeros((3, mt128), u.dtype)
        for b, (dx, dy, dz) in enumerate(_CORNERS):
            off = int(dy * sy + dz)
            blk = jax.lax.dot_general(
                ke[3 * b:3 * b + 3], u, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)     # (3, mt128), {0,0}
            if off:
                blk = pltpu.roll(blk, off, 1)           # lane placement
            if dx == 0:
                lo = lo + blk
            else:
                hi = hi + blk
        out = carry + lo
        for c in range(3):
            y_ref[c, k] = out[c, :m128]
        carry = hi
    acc[...] = carry


@functools.partial(jax.jit, static_argnames=("interpret", "planes"))
def structured_matvec_pallas_v7(xg, ck, Ke, *, interpret=False, planes=8):
    """Roll-only variant of :func:`structured_matvec_pallas_v6`.

    Same signature/semantics: xg (3, nx+1, ny+1, nz+1), ck (nx, ny, nz),
    Ke (24, 24), all f32; ``planes`` = cell planes per grid step
    (multiple of 8)."""
    _, nxn, nyn, nzn = xg.shape
    nx = nxn - 1
    m = nyn * nzn
    m128 = -(-m // 128) * 128
    sy = nzn
    mt128 = m128 + (-(-(sy + 2) // 128)) * 128
    cpp = max(1, min(planes, ((nx + 1 + 7) // 8) * 8))
    g = -(-(nx + 1) // cpp)                 # ceil: covers all output planes
    x_flat = xg.reshape(3, nxn, m)          # free reshape, no copy
    x_pad = jnp.pad(x_flat, ((0, 0), (0, g * cpp + 8 - nxn), (0, m128 - m)))
    # ck pads are loop-invariant, so XLA hoists them out of the PCG loop;
    # FULL mt128 lane width so no pad op exists inside the kernel
    ck_pad = jnp.pad(ck, ((0, g * cpp - nx), (0, 1), (0, 1))) \
        .reshape(g * cpp, m)
    ck_pad = jnp.pad(ck_pad, ((0, 0), (0, mt128 - m)))
    kernel = functools.partial(_matvec_kernel_v7, g=g, cpp=cpp,
                               m128=m128, mt128=mt128, sy=sy)
    y = pl.pallas_call(
        kernel,
        grid=(g,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),     # Ke
            pl.BlockSpec(memory_space=pl.ANY),         # x (manual DMA)
            pl.BlockSpec(memory_space=pl.ANY),         # ck (manual DMA)
        ],
        out_specs=pl.BlockSpec((3, cpp, m128), lambda j: (0, j, 0)),
        out_shape=jax.ShapeDtypeStruct((3, g * cpp, m128), xg.dtype),
        scratch_shapes=[
            pltpu.VMEM((2, 3, cpp + 8, mt128), xg.dtype),
            pltpu.VMEM((2, cpp, mt128), ck.dtype),
            pltpu.VMEM((3, mt128), xg.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
    )(Ke, x_pad, ck_pad)
    return y[:, :nxn, :m].reshape(3, nxn, nyn, nzn)


# ----------------------------------------------------------------------
# v8: v6 with the plane loop GRID-IZED — grid (g, cpp), one cell plane
# per step.
#
# The chipless-compile bisection (2026-07-31, tools/aot_compile_check.py)
# pinned v6's RESOURCE_EXHAUSTED at >=128^3 to Mosaic's scoped-vmem
# arena: the python-unrolled cpp-plane loop keeps every iteration's
# temporaries live simultaneously (u alone is 24 x m128 x 4 B = 2.2 MB
# at the flagship m — eight live copies blow the ~16 MB budget together
# with the slab buffers).  Making the plane index a GRID dimension
# bounds the arena to ONE plane's temporaries; the output block is
# revisited across the cpp inner steps (index_map ignores the inner
# dim — Mosaic keeps the block resident until j changes), rows are
# written at the DYNAMIC sublane index kk and read at dynamic kk+dx —
# both verified to lower on the v5e toolchain by the chipless probes.
# Everything else (slab DMA, i32 indices, ck hoisted out of the
# contraction, roll placement) is v6's.
# ----------------------------------------------------------------------


def _matvec_kernel_v8(ke_ref, x_hbm, ck_hbm, y_ref,
                      xv, ckv, acc, sem, ck_sem,
                      *, g, cpp, m128, mt128, sy):
    """One grid step = ONE cell plane; cpp steps finish an output block.

    Shapes as _matvec_kernel_v6 except the slab is SINGLE-buffered
    ((3, cpp+8, mt128), no prefetch): the saved 4.4 MB keeps the scoped
    request inside VMEM at flagship m, and removing the dynamic ``slot``
    index leaves the row reads with ONE dynamic index (kk+dx) — Mosaic
    rejects dynamic loads with two ("dynamic load with unaligned
    indices", chipless probe 2026-07-31).  The lost copy/compute overlap
    is one slab DMA (~5 us at flagship) per cpp planes of compute.
    ``acc`` carries dx=1 partials from every plane to the next."""
    j = jnp.asarray(pl.program_id(0), jnp.int32)   # chunk
    kk = jnp.asarray(pl.program_id(1), jnp.int32)  # plane within chunk

    def for_chunk(chunk, act):
        # index dtypes: see _matvec_kernel_v6.for_chunk (the x64 story
        # is handled structurally by _planes_env's enable_x64(False))
        c0 = jnp.asarray(chunk * cpp, jnp.int32)
        z = jnp.asarray(0, jnp.int32)
        getattr(pltpu.make_async_copy(
            x_hbm.at[:, pl.ds(c0, cpp + 8), :],
            xv.at[:, :, pl.ds(z, m128)], sem), act)()
        getattr(pltpu.make_async_copy(
            ck_hbm.at[pl.ds(c0, cpp)],
            ckv, ck_sem), act)()

    @pl.when((j == 0) & (kk == 0))
    def _init():
        xv[...] = jnp.zeros_like(xv)       # zero overhang tails once
        acc[...] = jnp.zeros_like(acc)

    @pl.when(kk == 0)
    def _arrive():
        for_chunk(j, "start")
        for_chunk(j, "wait")

    ke = ke_ref[...]                                    # (24, 24)
    ck = ckv[kk]                                        # (m128,)
    rows = []
    for a, (dx, dy, dz) in enumerate(_CORNERS):
        off = dy * sy + dz
        for c in range(3):
            rows.append(xv[c, kk + dx, off:off + m128])
    u = jnp.stack(rows)                                 # (24, m128)
    lo = jnp.zeros((3, mt128), u.dtype)
    hi = jnp.zeros((3, mt128), u.dtype)
    for b, (dx, dy, dz) in enumerate(_CORNERS):
        off = dy * sy + dz
        blk = jax.lax.dot_general(
            ke[3 * b:3 * b + 3], u, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)         # (3, m128), {0,0}
        blk = ck * blk                                  # hoisted ck scale
        vp = jnp.pad(blk, ((0, 0), (0, mt128 - m128)))  # aligned concat
        if off:
            vp = pltpu.roll(vp, off, 1)                 # lane rotate
        if dx == 0:
            lo = lo + vp
        else:
            hi = hi + vp
    out = acc[...] + lo
    for c in range(3):
        y_ref[c, kk] = out[c, :m128]
    acc[...] = hi


@functools.partial(jax.jit, static_argnames=("interpret", "planes"))
def structured_matvec_pallas_v8(xg, ck, Ke, *, interpret=False, planes=8):
    """Plane-per-grid-step variant of :func:`structured_matvec_pallas_v6`.

    Same signature/semantics: xg (3, nx+1, ny+1, nz+1), ck (nx, ny, nz),
    Ke (24, 24), all f32; ``planes`` = cell planes per output block
    (multiple of 8 — the output block's sublane axis)."""
    _, nxn, nyn, nzn = xg.shape
    nx = nxn - 1
    m = nyn * nzn
    m128 = -(-m // 128) * 128
    sy = nzn
    mt128 = m128 + (-(-(sy + 2) // 128)) * 128
    cpp = max(1, min(planes, ((nx + 1 + 7) // 8) * 8))
    g = -(-(nx + 1) // cpp)                 # ceil: covers all output planes
    x_flat = xg.reshape(3, nxn, m)          # free reshape, no copy
    x_pad = jnp.pad(x_flat, ((0, 0), (0, g * cpp + 8 - nxn), (0, m128 - m)))
    ck_pad = jnp.pad(ck, ((0, g * cpp - nx), (0, 1), (0, 1))) \
        .reshape(g * cpp, m)
    ck_pad = jnp.pad(ck_pad, ((0, 0), (0, m128 - m)))
    kernel = functools.partial(_matvec_kernel_v8, g=g, cpp=cpp,
                               m128=m128, mt128=mt128, sy=sy)
    y = pl.pallas_call(
        kernel,
        grid=(g, cpp),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),     # Ke
            pl.BlockSpec(memory_space=pl.ANY),         # x (manual DMA)
            pl.BlockSpec(memory_space=pl.ANY),         # ck (manual DMA)
        ],
        out_specs=pl.BlockSpec((3, cpp, m128), lambda j, k: (0, j, 0)),
        out_shape=jax.ShapeDtypeStruct((3, g * cpp, m128), xg.dtype),
        scratch_shapes=[
            pltpu.VMEM((3, cpp + 8, mt128), xg.dtype),
            pltpu.VMEM((cpp, m128), ck.dtype),
            pltpu.VMEM((3, mt128), xg.dtype),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
        # the scoped request at flagship m is 16.54 MB against the 16 MB
        # default limit (chipless compile 2026-07-31); v5e VMEM is far
        # larger — raise the per-kernel cap instead of shaving buffers
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=64 * 1024 * 1024),
        interpret=interpret,
    )(Ke, x_pad, ck_pad)
    return y[:, :nxn, :m].reshape(3, nxn, nyn, nzn)


# ----------------------------------------------------------------------
# v9: dot-built gather — NO concat/stack/pad of offset vectors anywhere.
#
# The first HARDWARE compiles of v6/v8 (2026-08-01, terminal
# tpu_compile_helper) rejected the u stack with a Mosaic error the
# build-host chipless pipeline accepts: "tpu.concatenate: result/input
# offset mismatch on non-concat dimension" — the 24 row slices
# xb[c, k+dx, off:off+m128] carry lane-offset layouts off%128 in
# {0, 1, 23, 24} and the DEPLOYED Mosaic has no relayout for
# lane-offset-mismatched concat inputs (the local toolchain does).
# The two toolchains differ; chipless-verified is necessary, not
# sufficient.
#
# v9 therefore never materializes a misaligned vector at all:
#
#   * the slab is PLANE-MAJOR — x_hbm (g*cpp+8, 3, m128) — so a corner
#     read is xb[k+dx]: a majormost-index memref slice yielding a
#     (3, mt128) block at canonical {0,0} layout (same op class as
#     xv[slot], lowered by every variant since v3);
#   * the dy/dz lane shift is a 2-D static pltpu.roll of that block
#     (the op every variant's OUTPUT path already lowers), applied to
#     the full mt128 width: xroll_a[c, l] = x[c, k+dx, l+off_a] for
#     all real l, and the cyclic wrap only touches lanes the ck mask
#     zeroes (ck is zero for pad cells, and real cells never read past
#     m — see the v7 header for the same argument);
#   * the (24, m) gathered array u is never BUILT: the product
#     v = Ke @ u is accumulated directly as eight MXU dots
#         v += keT[a] . xroll_a,   keT[a] = Ke[:, 3a:3a+3]  as (3, 24)
#     (contraction over the 3 components; every operand and result
#     lives at {0,0});
#   * output corner blocks are EXTRACTED BY DOT, not by row slicing:
#         blk_b = sel[b] . (ck * v),   sel[b] (3, 24) one-hot rows
#     so the placement roll and the lo/hi accumulation only ever see
#     {0,0} (3, mt128) blocks.  No jnp.pad exists in the kernel (ck is
#     host-padded to full mt128 width, as in v7).
#
# Cost vs v6: the 8 (3,24)@(24,m) output dots are replaced by
# 8 gather dots + 8 sel dots of the same MAC count — ~2x the (already
# tiny) FLOPs — plus 8 input rolls; the kernel stays DMA/HBM-bound by
# design.  The slab reads cpp+1 planes per chunk (v6 read cpp+8: its
# plane axis was tiled second-minor and DMA extents had to be 8-tile
# multiples; plane-major has no such constraint).  The host-side
# pad/transpose to plane-major costs one extra x round-trip per
# matvec, same class as v6's x_pad (header note 1).
# ----------------------------------------------------------------------


def _matvec_kernel_v9(ket_ref, sel_ref, x_hbm, ck_hbm, y_ref,
                      xv, ckv, acc, sems, ck_sems,
                      *, g, cpp, m128, mt128, sy):
    """One grid step = cpp finished output node planes.

    The component axis is physically FOUR everywhere (3 dof + one zero
    row): Mosaic tiles the second-minor axis at 4 and requires every
    memref-slice extent along it to be tile-aligned (chipless probe
    2026-08-01) — so x planes, ket/sel operands, the accumulators and
    the output block all carry the dead 4th row (zero in, zero out).

    ket_ref: (8, 4, 24) VMEM — ket[a,:3] = Ke[:, 3a:3a+3].T, row 3 zero
    sel_ref: (8, 4, 24) VMEM — sel[b, c, 3b+c] = 1 (c < 3), row 3 zero
    x_hbm:   (g*cpp + 1, 4, m128) ANY/HBM, plane-major, zero-padded
    ck_hbm:  (g*cpp, mt128) ANY/HBM (zero-padded, FULL mt width)
    y_ref:   (cpp, 4, m128) VMEM output block (plane-major)
    xv:      (2, cpp+1, 4, mt128) VMEM double-buffered slab (the plane
             axis is MAJORMOST, so the DMA extent cpp+1 needs no 8-tile
             alignment — v6's +8 overhang is gone); lanes [m128, mt128)
             stay zero from _init
    ckv:     (2, cpp, mt128) VMEM
    acc:     (4, mt128) VMEM — dx=1 partials carried to the next plane
    """
    j = jnp.asarray(pl.program_id(0), jnp.int32)  # i32 ALWAYS (see v4)

    def for_chunk(slot, chunk, act):
        # i32 ALWAYS, including literal zeros (index promotion, see v6)
        c0 = jnp.asarray(chunk * cpp, jnp.int32)
        z = jnp.asarray(0, jnp.int32)
        getattr(pltpu.make_async_copy(
            x_hbm.at[pl.ds(c0, cpp + 1)],
            xv.at[slot, :, :, pl.ds(z, m128)], sems.at[slot]), act)()
        getattr(pltpu.make_async_copy(
            ck_hbm.at[pl.ds(c0, cpp)],
            ckv.at[slot], ck_sems.at[slot]), act)()

    @pl.when(j == 0)
    def _init():
        xv[...] = jnp.zeros_like(xv)       # zero overhang tails once
        acc[...] = jnp.zeros_like(acc)
        for_chunk(0, 0, "start")

    slot = jax.lax.rem(j, jnp.asarray(2, j.dtype))
    for_chunk(slot, j, "wait")

    @pl.when(j + 1 < g)
    def _prefetch():
        for_chunk(1 - slot, j + 1, "start")

    xb = xv[slot]                                       # (cpp+1, 4, mt128)
    ckb = ckv[slot]                                     # (cpp, mt128)
    carry = acc[...]                                    # (4, mt128)
    for k in range(cpp):
        ck = ckb[k]                                     # (mt128,), 0 tail
        planes = (xb[k], xb[k + 1])                     # (4, mt128) {0,0}
        v = None
        for a, (dx, dy, dz) in enumerate(_CORNERS):
            off = int(dy * sy + dz)
            xr = planes[dx]
            if off:
                xr = pltpu.roll(xr, mt128 - off, 1)     # xr[l] = x[l+off]
            ket = ket_ref[a]                            # (4, 24) {0,0}
            d = jax.lax.dot_general(
                ket, xr, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)     # (24, mt128) {0,0}
            v = d if v is None else v + d
        w = ck * v                                      # hoisted ck scale
        lo = jnp.zeros((4, mt128), w.dtype)
        hi = jnp.zeros((4, mt128), w.dtype)
        for b, (dx, dy, dz) in enumerate(_CORNERS):
            off = int(dy * sy + dz)
            sel = sel_ref[b]                            # (4, 24) one-hot
            blk = jax.lax.dot_general(
                sel, w, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)     # (4, mt128) {0,0}
            if off:
                blk = pltpu.roll(blk, off, 1)           # lane placement
            if dx == 0:
                lo = lo + blk
            else:
                hi = hi + blk
        out = carry + lo
        y_ref[k] = out[:, :m128]
        carry = hi
    acc[...] = carry


@functools.partial(jax.jit, static_argnames=("interpret", "planes"))
def structured_matvec_pallas_v9(xg, ck, Ke, *, interpret=False, planes=8):
    """Dot-built-gather variant of :func:`structured_matvec_pallas_v6`.

    Same signature/semantics: xg (3, nx+1, ny+1, nz+1), ck (nx, ny, nz),
    Ke (24, 24), all f32; ``planes`` = cell planes per grid step
    (multiple of 8)."""
    _, nxn, nyn, nzn = xg.shape
    nx = nxn - 1
    m = nyn * nzn
    m128 = -(-m // 128) * 128
    sy = nzn
    mt128 = m128 + (-(-(sy + 2) // 128)) * 128
    cpp = max(1, min(planes, ((nx + 1 + 7) // 8) * 8))
    g = -(-(nx + 1) // cpp)                 # ceil: covers all output planes
    x_flat = xg.reshape(3, nxn, m)          # free reshape, no copy
    # plane-major with a zero 4th component row (tiling alignment, see
    # kernel docstring): a corner read inside the kernel is then a
    # majormost-index (4, mt128) block slice at {0,0}
    x_pad = jnp.pad(x_flat, ((0, 1), (0, g * cpp + 1 - nxn),
                             (0, m128 - m))).transpose(1, 0, 2)
    # ck pads are loop-invariant, so XLA hoists them out of the PCG
    # loop; FULL mt128 lane width so no pad op exists inside the kernel
    ck_pad = jnp.pad(ck, ((0, g * cpp - nx), (0, 1), (0, 1))) \
        .reshape(g * cpp, m)
    ck_pad = jnp.pad(ck_pad, ((0, 0), (0, mt128 - m)))
    ket = jnp.stack([
        jnp.concatenate([Ke[:, 3 * a:3 * a + 3].T,
                         jnp.zeros((1, 24), Ke.dtype)]) for a in range(8)])
    sel_np = np.zeros((8, 4, 24), np.float32)
    for b in range(8):
        for c in range(3):
            sel_np[b, c, 3 * b + c] = 1.0
    sel = jnp.asarray(sel_np)
    kernel = functools.partial(_matvec_kernel_v9, g=g, cpp=cpp,
                               m128=m128, mt128=mt128, sy=sy)
    y = pl.pallas_call(
        kernel,
        grid=(g,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),     # ket
            pl.BlockSpec(memory_space=pltpu.VMEM),     # sel
            pl.BlockSpec(memory_space=pl.ANY),         # x (manual DMA)
            pl.BlockSpec(memory_space=pl.ANY),         # ck (manual DMA)
        ],
        out_specs=pl.BlockSpec((cpp, 4, m128), lambda j: (j, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((g * cpp, 4, m128), xg.dtype),
        scratch_shapes=[
            pltpu.VMEM((2, cpp + 1, 4, mt128), xg.dtype),
            pltpu.VMEM((2, cpp, mt128), ck.dtype),
            pltpu.VMEM((4, mt128), xg.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        # plane-major slab: each (4, mt128) plane occupies a 4-sublane
        # tile -> ~7 MB both slots at flagship m; raise the per-kernel
        # cap as for v6/v8
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=64 * 1024 * 1024),
        interpret=interpret,
    )(ket, sel, x_pad, ck_pad)
    return y[:nxn, :3, :m].transpose(1, 0, 2).reshape(3, nxn, nyn, nzn)
