"""Node-block (3x3) Jacobi preconditioning.

The reference has only the scalar Jacobi preconditioner (diag(K) assembled
via the scatter path, pcg_solver.py:282-287,346-352).  For vector-valued
elasticity the natural strengthening is BLOCK Jacobi over the 3 dofs of
each node: M = blkdiag(K_aa) with K_aa the assembled 3x3 node-diagonal
block.  It costs one extra small batched 3x3 inverse per preconditioner
rebuild and a batched (n,3,3)@(n,3) matmul per PCG iteration — both
MXU/VPU-friendly — and typically cuts iteration counts 10-30% on
heterogeneous elastic models (BASELINE.json config 4: "block-Jacobi").

This module holds the backend-agnostic piece: masked batched inversion.
Assembling the blocks is an Ops-protocol method (``node_block_diag``),
implemented per backend (general ELL, hybrid level-grid, structured slab).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def invert_node_blocks(B: jnp.ndarray, eff3: jnp.ndarray) -> jnp.ndarray:
    """Invert per-node 3x3 blocks restricted to effective (free) dofs.

    B:    (..., n, 3, 3) assembled node-diagonal blocks of K (SPD on the
          free dofs).
    eff3: (..., n, 3) 0/1 mask of effective dofs (0 = Dirichlet-fixed or
          padding).

    Fixed components are decoupled by masking row+column and placing 1 on
    the diagonal, so the inverse acts as the identity there — applied to an
    eff-masked residual those components stay exactly 0 (same contract as
    the scalar path's ``where(eff > 0, 1/diag, 0)``).

    Inversion is by explicit adjugate on blocks normalized by their diagonal
    max (keeps determinants O(1): raw stiffness entries are ~E*h, whose
    cube would overflow f32).  Blocks with a vanishing determinant fall
    back to their scalar-Jacobi diagonal inverse.
    """
    import jax

    out_dt = B.dtype
    # Compute the whole inversion in f64 when available: the adjugate det
    # of an ill-conditioned block is pure cancellation in f32 (absolute
    # noise ~eps32 on O(1) normalized entries, i.e. any det below ~1e-7
    # is unmeasurable — it can even come out sign-flipped), while this
    # runs once per preconditioner rebuild, far off the hot loop.  In f64
    # the det of the STORED block is exact to ~1e-16, so the fallback
    # cutoff below is a genuine conditioning policy, not a noise guard.
    dt = jnp.dtype(jnp.float64) if jax.config.jax_enable_x64 else out_dt
    e = eff3.astype(dt)
    eye = jnp.eye(3, dtype=dt)
    B = B.astype(dt)
    Bm = B * e[..., :, None] * e[..., None, :] + (1.0 - e)[..., :, None] * eye

    # normalize: s ~ the block's diagonal scale (>= 1 on fixed/padded rows)
    d = jnp.diagonal(Bm, axis1=-2, axis2=-1)
    s = jnp.max(jnp.abs(d), axis=-1)
    s = jnp.where(s > 0, s, 1.0)
    a = Bm / s[..., None, None]

    c00 = a[..., 1, 1] * a[..., 2, 2] - a[..., 1, 2] * a[..., 2, 1]
    c01 = a[..., 1, 2] * a[..., 2, 0] - a[..., 1, 0] * a[..., 2, 2]
    c02 = a[..., 1, 0] * a[..., 2, 1] - a[..., 1, 1] * a[..., 2, 0]
    det = (a[..., 0, 0] * c00 + a[..., 0, 1] * c01 + a[..., 0, 2] * c02)

    # adj[i, j] = cofactor(j, i)
    adj = jnp.stack([
        jnp.stack([c00,
                   a[..., 0, 2] * a[..., 2, 1] - a[..., 0, 1] * a[..., 2, 2],
                   a[..., 0, 1] * a[..., 1, 2] - a[..., 0, 2] * a[..., 1, 1]],
                  axis=-1),
        jnp.stack([c01,
                   a[..., 0, 0] * a[..., 2, 2] - a[..., 0, 2] * a[..., 2, 0],
                   a[..., 0, 2] * a[..., 1, 0] - a[..., 0, 0] * a[..., 1, 2]],
                  axis=-1),
        jnp.stack([c02,
                   a[..., 0, 1] * a[..., 2, 0] - a[..., 0, 0] * a[..., 2, 1],
                   a[..., 0, 0] * a[..., 1, 1] - a[..., 0, 1] * a[..., 1, 0]],
                  axis=-1),
    ], axis=-2)

    # a is diagonal-normalized so det = prod of its eigenvalue ratios in
    # (0, 1].  The adjugate inverse degrades gracefully as det shrinks,
    # and an ill-conditioned but valid SPD block (e.g. two stiffness
    # ratios of ~3e-4: det ~1e-7, the stiff heterogeneous cases block3
    # targets) must NOT silently fall back to scalar Jacobi.  With f64
    # compute the det is trustworthy far below f32 eps, so the cutoff
    # drops to eps32^1.5 (~4e-11); without x64 the f32 arithmetic noise
    # floor (~eps32 of cancelling O(1) cofactor terms) forces the old
    # conservative cutoff.
    if out_dt == jnp.dtype(jnp.float32) and dt == jnp.dtype(jnp.float64):
        cutoff = float(np.finfo(np.float32).eps) ** 1.5   # ~4e-11
    else:
        cutoff = float(np.finfo(np.dtype(dt)).eps)        # old behavior
    tiny = jnp.asarray(cutoff, dt)
    ok = jnp.abs(det) > tiny
    dinv = jnp.where(ok, 1.0 / jnp.where(ok, det, 1.0), 0.0)
    inv = adj * (dinv / s)[..., None, None]

    # Degenerate block: scalar Jacobi on its diagonal.  A zero diagonal on
    # an EFFECTIVE dof (for SPD K: a fully disconnected dof) maps to inf,
    # preserving pcg's flag-2 inf-preconditioner contract exactly like the
    # scalar path's 1/0 (fixed/padded rows were masked to diagonal 1 above,
    # so they never produce inf).
    dsafe = jnp.where(d != 0, d, 1.0)
    dvals = jnp.where(d != 0, 1.0 / dsafe, jnp.inf)
    # embed on the diagonal by select, not multiply (inf * 0 would NaN)
    scalar = jnp.where(eye > 0, dvals[..., :, None], jnp.zeros((), dt))
    return jnp.where(ok[..., None, None], inv, scalar).astype(out_dt)


from pcg_mpi_solver_tpu.config import PRECONDS as VALID_PRECONDS

if VALID_PRECONDS != ("jacobi", "block3", "mg"):
    # an explicit raise, not `assert` — the guard must survive -O.  The
    # builders below dispatch on exactly these three names; a name added
    # to the canonical config.PRECONDS table without a builder here (or
    # vice versa) must fail at import, loudly, before any layer can
    # disagree about the valid set.
    raise ImportError(
        "ops/precond builders cover ('jacobi', 'block3', 'mg') but the "
        f"canonical config.PRECONDS table says {VALID_PRECONDS}: add the "
        "builder (make_prec/fallback_kind) alongside the table row")


def fallback_kind(kind: str) -> "str | None":
    """The next-weaker-but-safer preconditioner for the recovery ladder
    (resilience/): a flag-2/4 breakdown under block-Jacobi OR under the
    geometric multigrid V-cycle retries under scalar Jacobi — the
    reference's only preconditioner, whose inverse is finite wherever
    the assembled diagonal is nonzero, so it cannot itself re-introduce
    the Inf a near-singular 3x3 block inverse produced, nor depend on a
    level hierarchy that may itself be the broken ingredient (a bad mg
    hierarchy DEGRADES to scalar Jacobi instead of failing the solve —
    the demotion rung of ISSUE 10).  Scalar Jacobi has nothing weaker
    that is still a preconditioner (identity would change iteration
    counts far more than it saves), so it returns None and the ladder
    skips to its next rung."""
    return "jacobi" if kind in ("block3", "mg") else None


def corner_block_field(Ke: jnp.ndarray, ck: jnp.ndarray,
                       corners) -> jnp.ndarray:
    """Brick-grid node-block assembly: every cell adds ``ck * Ke[3a:3a+3,
    3a:3a+3]`` to its corner-``a`` node, as 8 pad-translated 9-channel
    terms.  ck: (P, cx, cy, cz) cell grid -> (P, 9, cx+1, cy+1, cz+1) node
    grid.  Shared by the structured slab and hybrid level-grid backends."""
    Ke4 = Ke.reshape(8, 3, 8, 3)
    D9 = jnp.stack([Ke4[a, :, a, :].reshape(9) for a in range(8)])
    terms = []
    for a, (dx, dy, dz) in enumerate(corners):
        contrib = D9[a][None, :, None, None, None] * ck[:, None]
        terms.append(jnp.pad(
            contrib,
            ((0, 0), (0, 0), (dx, 1 - dx), (dy, 1 - dy), (dz, 1 - dz))))
    g = terms[0]
    for t in terms[1:]:
        g = g + t
    return g


def make_fallback_prec(ops, data: dict, kind: str):
    """The recovery ladder's fallback preconditioner inverse for a solve
    configured with ``kind``, or None when no weaker-but-safer inverse
    exists (:func:`fallback_kind`).  The blocked multi-RHS cycle wires
    this as ``pcg_many``'s ``inv_diag_fb`` so the per-column ladder can
    flip ONE broken column to the safe inverse (carry ``prec_sel``)
    while every other column keeps the configured preconditioner
    bit-identically."""
    fb = fallback_kind(kind)
    return None if fb is None else make_prec(ops, data, fb)


def make_prec(ops, data: dict, kind: str):
    """The preconditioner inverse for ``kind`` ("jacobi" | "block3" |
    "mg"), ready for ``ops.apply_prec`` inside the PCG body — the one
    shared builder for every solver (quasi-static driver, implicit
    Newmark).

    "mg" returns the prec DICT the V-cycle consumes (ops/mg.py): the
    eff-masked scalar inverse diagonal (the Chebyshev smoother's D^-1 —
    bitwise the jacobi inverse) plus the ``fb`` demotion switch the
    recovery ladder flips to 1 to degrade the apply to plain scalar
    Jacobi without recompiling the cycle; the hierarchy itself rides
    ``data["mg"]``."""
    if kind == "block3":
        return ops.block_precond(data)
    if kind not in ("jacobi", "mg"):
        raise ValueError(
            f"precond must be one of {VALID_PRECONDS}, got {kind!r}")
    diag_k = ops.diag(data)
    inv = jnp.where(data["eff"] > 0, 1.0 / diag_k, 0.0)
    if kind == "mg":
        return {"mg_diag": inv, "fb": jnp.zeros((), jnp.int32)}
    return inv
