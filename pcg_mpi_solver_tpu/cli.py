"""Command-line interface: the reference's five entry-point programs
(read_input_model / run_metis / partition_mesh / pcg_solver / export_vtk,
orchestrated by examples/run_basic_script.bash) as one typed CLI.

    pcg-tpu ingest    <archive.zip> <scratch>          # unpack MDF bundle
    pcg-tpu partition <scratch> <n_parts>              # element->part map
    pcg-tpu validate  <scratch> [--preflight=]         # preflight checks only
    pcg-tpu solve     <scratch> <run_id> [options]     # SPMD PCG solve
    pcg-tpu solve-many <scratch> <run_id> [options]    # batched multi-RHS solve
    pcg-tpu dynamics  <scratch> <run_id> [options]     # explicit time history
    pcg-tpu newmark   <scratch> <run_id> [options]     # implicit time history
    pcg-tpu export    <scratch> <run_id> <vars> <mode> # frames -> .vtu
    pcg-tpu demo      [--nx ...]                       # synthetic end-to-end
    pcg-tpu bench                                      # benchmark harness
    pcg-tpu warmup    <scratch> [options]              # pre-bake caches
    pcg-tpu cache-stats [--cache-dir D]                # warm-path cache table
    pcg-tpu lint      [--fast] [--json F]              # contract lint (analysis/)
    pcg-tpu perf-report [--nx N | scratch]             # measured-vs-model phases
    pcg-tpu prof-report <trace-artifact>               # parse a captured device trace
    pcg-tpu fleet-report <capture-root>                # cross-process collective skew
    pcg-tpu trend     [BENCH_r*.json ...]              # bench-trend regression sentinel
    pcg-tpu summary   <run.jsonl> [...]                # offline telemetry summary
    pcg-tpu watch     <run.jsonl> [--once]             # live monitor + stall alarm
    pcg-tpu telemetry-merge <run.jsonl> --out M.jsonl  # merge per-process shards
    pcg-tpu serve     --spool DIR [model opts]         # multi-tenant solve daemon
    pcg-tpu submit    --spool DIR --scale S            # drop a job into the spool
    pcg-tpu jobs      --spool DIR                      # job table from the journal

Settings come from ``--settings settings.json`` (same shape as the
reference's GlobSettings: TimeHistoryParam/SolverParam,
run_basic_script.bash:30-49) or per-flag overrides.
"""

from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np


def _load_settings(path, args) -> "RunConfig":
    from pcg_mpi_solver_tpu.config import RunConfig, SolverConfig, TimeHistoryConfig

    th, sp = {}, {}
    if path and os.path.exists(path):
        with open(path) as f:
            raw = json.load(f)
        th = raw.get("TimeHistoryParam", {})
        sp = raw.get("SolverParam", {})
    # default precision is "direct" (f64, reference parity) — a reference-
    # shaped settings file without PrecisionMode must not change numerics;
    # pass --precision mixed (or PrecisionMode) for the TPU performance path.
    solver = SolverConfig(
        tol=float(getattr(args, "tol", None) or sp.get("Tol", 1e-7)),
        max_iter=int(getattr(args, "max_iter", None) or sp.get("MaxIter", 10000)),
        precision_mode=getattr(args, "precision", None) or sp.get("PrecisionMode", "direct"),
        precond=getattr(args, "precond", None) or sp.get("Precond", "jacobi"),
        # classic stays the bit-exact reference-parity default; "fused"
        # opts into the single-reduction Chronopoulos–Gear loop
        pcg_variant=(getattr(args, "pcg_variant", None)
                     or sp.get("PcgVariant", "classic")),
        # dispatch cap override (settings-only; -1 = auto): tests and
        # small chaos drills force the chunked/resumable path below the
        # auto-engage size, where snapshots/recovery actually exist
        iters_per_dispatch=int(sp.get("ItersPerDispatch", -1)),
    )
    time_history = TimeHistoryConfig(
        time_step_delta=th.get("TimeStepDelta", [0.0, 1.0]),
        export_flag=bool(th.get("ExportFlag", True)),
        export_frame_rate=int(th.get("ExportFrmRate", 1)),
        export_frames=th.get("ExportFrms", []),
        plot_flag=bool(th.get("PlotFlag", False)),
        export_vars=th.get("ExportVars", "U"),
    )
    cfg = RunConfig(solver=solver, time_history=time_history)
    _apply_telemetry_flags(cfg, args)
    return cfg


def _apply_telemetry_flags(cfg, args) -> None:
    """Wire the shared per-run flags into the RunConfig: --telemetry-out
    (JSONL sink), --trace-resid (in-graph convergence ring),
    --profile-spans (jax.profiler annotations), --cache-dir, and the
    validate/ --preflight policy override."""
    cfg.telemetry_path = getattr(args, "telemetry_out", None) or ""
    cfg.flight_path = getattr(args, "flight_out", None) or ""
    cfg.solver.trace_resid = int(getattr(args, "trace_resid", None) or 0)
    if getattr(args, "profile_spans", False):
        cfg.telemetry_profile = True
    cfg.cache_dir = _resolve_cache_dir(args)
    cfg.preflight = getattr(args, "preflight", None) or ""


def _resolve_cache_dir(args) -> str:
    """One resolution rule for every subcommand (warmup MUST land in the
    same dir the later solve reads, so they cannot have different
    defaults): the --cache-dir flag, else the PCG_TPU_CACHE_DIR env var,
    else off."""
    return getattr(args, "cache_dir", None) or \
        os.environ.get("PCG_TPU_CACHE_DIR", "")


def _resolve_partition_mesh(n_parts_arg, scratch):
    """(n_parts, elem_part, n_dev, n_dev_used): the n_parts default, the
    scratch MeshPart_<n>.npy element->part map, and the device count
    that divides n_parts — ONE resolution shared by solve and warmup,
    because warmup's entire value depends on baking caches for the
    IDENTICAL mesh/partition inputs the later solve resolves."""
    import jax

    n_dev = len(jax.devices())
    n_parts = n_parts_arg or n_dev
    elem_part = None
    if scratch:
        part_file = os.path.join(scratch, "ModelData",
                                 f"MeshPart_{n_parts}.npy")
        if os.path.exists(part_file):
            elem_part = np.load(part_file)
    # use as many devices as divide n_parts
    n_dev_used = n_dev if n_parts % n_dev == 0 else max(
        d for d in range(1, min(n_dev, n_parts) + 1) if n_parts % d == 0)
    return n_parts, elem_part, n_dev, n_dev_used


def _add_cache_flag(p) -> None:
    p.add_argument("--cache-dir", default=None, metavar="DIR",
                   help="warm-path cache directory (cache/): partitions "
                        "are served from a content-addressed on-disk "
                        "cache, the PCG step program is AOT-exported, and "
                        "jax's persistent XLA compilation cache lives "
                        "under DIR/xla — the second solve of the same "
                        "model/n_parts/backend pays near-zero setup "
                        "(pre-bake with `pcg-tpu warmup`; env default: "
                        "PCG_TPU_CACHE_DIR)")


def _finish_telemetry(solver, args) -> None:
    """End-of-run telemetry surfaces: the --summary table and the
    recorder's sink shutdown (flushes/closes the JSONL file)."""
    if getattr(args, "summary", False):
        print(solver.recorder.summary())
    if getattr(args, "telemetry_out", None):
        print(f">telemetry: {args.telemetry_out}")
    solver.recorder.close()


def _precond_choices():
    # derived from the canonical table (config.PRECONDS) like the
    # variant flag below: a precond added to the table must be
    # selectable from every CLI surface without six hand-edits
    from pcg_mpi_solver_tpu.config import PRECONDS

    return list(PRECONDS)


def _add_variant_flag(p) -> None:
    from pcg_mpi_solver_tpu.config import PCG_VARIANTS

    p.add_argument("--pcg-variant", choices=list(PCG_VARIANTS),
                   default=None, dest="pcg_variant",
                   help="PCG loop formulation: classic = MATLAB-"
                        "compatible 3-reduction loop (bit-exact "
                        "reference parity; default), fused = "
                        "Chronopoulos-Gear single-reduction recurrence "
                        "(ONE collective per iteration — cuts the "
                        "between-matvec latency at scale), pipelined = "
                        "Ghysels-Vanroose depth-1 pipelining (the one "
                        "collective overlaps the stencil matvec "
                        "entirely; 4 extra carry vectors, tighter "
                        "drift guard).  Iteration counts of the non-"
                        "classic variants differ by O(1); see "
                        "docs/RUNBOOK.md 'Choosing pcg_variant'")


def _add_preflight_flag(p) -> None:
    p.add_argument("--preflight", choices=["fail", "warn", "off"],
                   default=None,
                   help="model/config preflight gate (validate/): fail "
                        "= reject pathological inputs before any "
                        "partition/compile work (default), warn = "
                        "report and proceed, off = skip the checks "
                        "(env default: PCG_TPU_PREFLIGHT)")


def _add_resilience_flags(p, granularity: str,
                          elastic: bool = False) -> None:
    """--snapshot-every / --max-recoveries / --resume, shared by the
    solve, dynamics and newmark subcommands; ``granularity`` names what
    one snapshot interval means on that path.  ``elastic`` additionally
    exposes --resume-elastic (the quasi-static driver only — the path
    ``Solver.resume_elastic`` serves)."""
    p.add_argument("--snapshot-every", type=int, default=0,
                   help=f"resumable snapshots (resilience/): persist "
                        f"state every N {granularity} so a "
                        "killed/preempted run loses at most N and "
                        "--resume continues where it left off (0 = off; "
                        "on-disk retention: PCG_TPU_SNAP_KEEP, "
                        "default 2)")
    p.add_argument("--max-recoveries", type=int, default=None,
                   help="recovery budget for breakdowns, NaN/Inf "
                        "corruption and device-loss failures (default "
                        "2; 0 = report-and-stop)")
    p.add_argument("--resume", action="store_true",
                   help=f"continue from the latest snapshot/checkpoint "
                        f"of this run ({granularity} granularity)")
    if not elastic:
        return
    p.add_argument("--resume-elastic", default=None, metavar="DIR",
                   nargs="?", const="",
                   help="resume a MULTI-PROCESS run's committed snapshot "
                        "epochs / checkpoints on THIS (typically smaller) "
                        "process count (resilience/distributed, ISSUE "
                        "18): re-joins the group-consistent shards and "
                        "accepts the n_procs fingerprint mismatch as a "
                        "named elastic_resume event.  DIR = the dead "
                        "fleet's checkpoint dir (default: this config's "
                        "checkpoint path)")


def _add_telemetry_flags(p) -> None:
    p.add_argument("--telemetry-out", default=None, metavar="FILE.jsonl",
                   help="append schema-versioned telemetry events (one "
                        "JSON object per line: step metrics, dispatch "
                        "timings, residual traces, run summary) here")
    p.add_argument("--trace-resid", type=int, default=0, metavar="N",
                   help="record the last N per-iteration (normr, rho, "
                        "stag, flag) samples on device and surface them "
                        "once per solve (0 = off; clamped to max_iter)")
    p.add_argument("--flight-out", default=None, metavar="FILE.jsonl",
                   help="crash-durable flight recorder (obs/flight.py): "
                        "fsync-per-event begin/end brackets + heartbeats "
                        "around every solve dispatch, so a tunnel death "
                        "or SIGKILL mid-solve leaves a parseable artifact "
                        "(read it back with `pcg-tpu summary`; env "
                        "default: PCG_TPU_FLIGHT)")
    p.add_argument("--summary", action="store_true",
                   help="print the per-step / per-dispatch telemetry "
                        "table after the run")
    p.add_argument("--profile-spans", action="store_true",
                   help="wrap each device dispatch in a named "
                        "jax.profiler.TraceAnnotation (also "
                        "PCG_TPU_PROFILE_SPANS=1)")


def cmd_ingest(args):
    from pcg_mpi_solver_tpu.models.mdf import ingest_archive, read_mdf

    mdf = ingest_archive(args.archive, args.scratch)
    model = read_mdf(mdf)
    print(f">extracted to {mdf}")
    print(f">elements:  {model.n_elem}")
    print(f">nodes:     {model.n_node}")
    print(f">dofs:      {model.n_dof}")


def cmd_partition(args):
    from pcg_mpi_solver_tpu.models.mdf import read_mdf
    from pcg_mpi_solver_tpu.parallel.partition import make_elem_part

    model = read_mdf(os.path.join(args.scratch, "ModelData", "MDF"))
    print(f">partitioning {model.n_elem} elements into {args.n_parts} parts "
          f"({args.method})..")
    part = make_elem_part(model, args.n_parts, method=args.method)
    out = os.path.join(args.scratch, "ModelData", f"MeshPart_{args.n_parts}.npy")
    np.save(out, part)
    print(f">saved {out}")


def cmd_solve(args):
    from pcg_mpi_solver_tpu.models.mdf import read_mdf
    from pcg_mpi_solver_tpu.solver.driver import Solver
    from pcg_mpi_solver_tpu.utils.io import RunStore

    cfg = _load_settings(args.settings, args)
    cfg.scratch_path = args.scratch
    cfg.run_id = args.run_id
    cfg.speed_test = bool(args.speed_test)
    cfg.checkpoint_every = int(args.checkpoint_every or 0)
    cfg.snapshot_every = int(args.snapshot_every or 0)
    if args.max_recoveries is not None:
        cfg.solver.max_recoveries = int(args.max_recoveries)
    cfg.profile_dir = args.profile_dir or ""
    model = read_mdf(os.path.join(args.scratch, "ModelData", "MDF"))
    cfg.time_history.dt = model.dt   # frame timestamps follow the model's dt
    n_parts, elem_part, n_dev, n_dev_used = _resolve_partition_mesh(
        args.n_parts, args.scratch)

    from pcg_mpi_solver_tpu.parallel.mesh import make_mesh

    print(f">solving on {n_dev_used}/{n_dev} device(s), {n_parts} parts "
          f"({cfg.solver.precision_mode} precision)..")
    s = Solver(model, cfg, mesh=make_mesh(n_dev_used), n_parts=n_parts,
               elem_part=elem_part, backend=args.backend)
    print(f">backend: {s.backend}")
    store = RunStore(cfg.result_path, cfg.model_name)
    out_store = None if cfg.speed_test else store
    if getattr(args, "resume_elastic", None) is not None:
        res = s.resume_elastic(args.resume_elastic or None,
                               store=out_store)
    else:
        res = s.solve(store=out_store, resume=bool(args.resume))
    # With --resume, earlier steps were restored: label only the ones run.
    t_first = len(s.flags) - len(res) + 1
    for t, r in enumerate(res, t_first):
        print(f">step {t}: flag={r.flag} iters={r.iters} relres={r.relres:.3e} "
              f"wall={r.wall_s:.2f}s")
    td = s.time_data()
    print(f">calculation time: {td['Mean_CalcTime']:.2f} sec")
    _finish_telemetry(s, args)
    print(">success!")


def cmd_solve_many(args):
    """Batched multi-RHS solve: a LIST of load cases against one shared
    partitioned operator (Solver.solve_many — the multi-tenant solve
    path).  The block comes from ``--rhs loads.npy`` ((n_dof, nrhs) or
    (nrhs, n_dof)) or ``--scales "1.0,0.5,2.0"`` (columns = scale *
    model reference load F); each column is validated per request
    (validate.check_rhs_block names the offending column) on top of the
    construction-time preflight.  One Krylov loop solves all columns
    lockstep — converged columns freeze, per-iteration collective count
    independent of the block width — and per-RHS flags/relres/iters are
    printed and emitted as `rhs_solve` telemetry events.

    Resilience rides the blocked path for real: --snapshot-every /
    --resume persist and continue the blocked carry mid-solve
    (``many_*.npz``), and --max-recoveries bounds the PER-COLUMN
    recovery ladder — a flag-2/4 breakdown or NaN/Inf poison in one
    column restarts/escalates that column alone while the others keep
    iterating bit-identically; an unrecoverable column is QUARANTINED
    (flag 5 + `rhs_quarantine` telemetry) instead of failing the block
    (docs/RUNBOOK.md "Blocked solve failure modes & quarantine")."""
    from pcg_mpi_solver_tpu.models.mdf import read_mdf
    from pcg_mpi_solver_tpu.solver.driver import Solver, normalize_rhs_block

    cfg = _load_settings(args.settings, args)
    cfg.scratch_path = args.scratch
    cfg.run_id = args.run_id
    cfg.snapshot_every = int(args.snapshot_every or 0)
    if args.max_recoveries is not None:
        cfg.solver.max_recoveries = int(args.max_recoveries)
    model = read_mdf(os.path.join(args.scratch, "ModelData", "MDF"))
    if args.rhs:
        # the ONE shape heuristic lives in normalize_rhs_block (shared
        # with Solver.solve_many) — the CLI only needs the width early
        # for the config/telemetry stamp, so this is the shape-only pass
        # (no dtype: the transpose is a view, no full-block copy;
        # solve_many converts once to the solve dtype)
        fb = normalize_rhs_block(np.load(args.rhs), model.n_dof)
    elif args.scales:
        try:
            scales = [float(v) for v in args.scales.split(",")
                      if v.strip()]
        except ValueError:
            raise SystemExit(f"solve-many: --scales {args.scales!r} is "
                             "not a comma-separated list of numbers")
        if not scales:
            raise SystemExit("solve-many: --scales parsed to zero load "
                             "cases; pass e.g. --scales \"1.0,0.5\"")
        fb = np.stack([np.asarray(model.F) * sc for sc in scales], axis=-1)
    else:
        raise SystemExit("solve-many: pass --rhs FILE.npy (columns = load "
                         "cases) or --scales \"1.0,0.5,...\"")
    cfg.solver.nrhs = fb.shape[1]
    n_parts, elem_part, n_dev, n_dev_used = _resolve_partition_mesh(
        args.n_parts, args.scratch)

    from pcg_mpi_solver_tpu.parallel.mesh import make_mesh

    print(f">solving {fb.shape[1]} load cases on {n_dev_used}/{n_dev} "
          f"device(s), {n_parts} parts "
          f"({cfg.solver.precision_mode} precision, "
          f"{cfg.solver.pcg_variant} variant)..")
    s = Solver(model, cfg, mesh=make_mesh(n_dev_used), n_parts=n_parts,
               elem_part=elem_part, backend=args.backend)
    print(f">backend: {s.backend}  setup: {s.setup_s:.2f}s "
          f"({s.setup_cache} partition)")
    res = s.solve_many(fb, resume=bool(args.resume))
    for j in range(res.nrhs):
        tag = "  [QUARANTINED]" if j in res.quarantined else ""
        print(f">rhs {j}: flag={int(res.flags[j])} "
              f"iters={int(res.iters[j])} relres={res.relres[j]:.3e}{tag}")
    print(f">block wall: {res.wall_s:.2f}s ({res.nrhs} load cases, "
          f"one operator)")
    if res.recoveries:
        print(f">recoveries: {res.recoveries} per-column ladder "
              f"attempt(s) consumed")
    if res.quarantined:
        print(f">quarantined columns: {list(res.quarantined)} — "
              "returned their min-residual iterate (flag 5); see "
              "docs/RUNBOOK.md 'Blocked solve failure modes'")
    out = os.path.join(cfg.result_path, "u_many")
    os.makedirs(cfg.result_path, exist_ok=True)
    np.save(out, s.displacement_global_many(res.x))
    print(f">solutions (n_dof, nrhs) -> {out}.npy")
    _finish_telemetry(s, args)
    print(">success!")


def cmd_serve(args):
    """Run the multi-tenant solve service (serve/, ISSUE 19): one warm
    partitioned operator serving filesystem-submitted jobs exactly once.

    The daemon polls ``--spool``/incoming for specs (``pcg-tpu
    submit``), prices each admission with the analytic cost model
    against the job's deadline, packs compatible jobs into standard
    nrhs blocks and dispatches them through ``Solver.solve_many`` — a
    poisoned tenant's column quarantines alone (PR 8) while co-batched
    tenants finish.  Every lifecycle transition is an fsync'd record in
    ``spool/journal.jsonl``; restarting the daemon over the same spool
    replays the journal (no job lost, none solved twice).  SIGTERM
    drains gracefully; watch the journal live with ``pcg-tpu watch
    spool/journal.jsonl``."""
    from pcg_mpi_solver_tpu.serve.daemon import ServeDaemon
    from pcg_mpi_solver_tpu.solver.driver import Solver

    cfg = _load_settings(args.settings, args)
    if args.synthetic:
        from pcg_mpi_solver_tpu.models.synthetic import make_cube_model

        try:
            dims = [int(v) for v in args.synthetic.split(",")]
        except ValueError:
            raise SystemExit(f"serve: --synthetic {args.synthetic!r} is "
                             "not NX[,NY,NZ]")
        dims += [0] * (3 - len(dims))
        model = make_cube_model(dims[0], dims[1], dims[2], E=30e9,
                                nu=0.2, load="traction", load_value=1e6,
                                heterogeneous=True)
    elif args.scratch:
        from pcg_mpi_solver_tpu.models.mdf import read_mdf

        cfg.scratch_path = args.scratch
        model = read_mdf(os.path.join(args.scratch, "ModelData", "MDF"))
    else:
        raise SystemExit("serve: pass a <scratch> dir or --synthetic NX")
    try:
        widths = sorted({int(v) for v in args.widths.split(",")})
    except ValueError:
        raise SystemExit(f"serve: --widths {args.widths!r} is not a "
                         "comma-separated list of ints")
    n_parts, elem_part, n_dev, n_dev_used = _resolve_partition_mesh(
        args.n_parts, args.scratch)

    from pcg_mpi_solver_tpu.parallel.mesh import make_mesh

    print(f">serve: warming {model.n_dof} dofs on {n_dev_used}/{n_dev} "
          f"device(s), {n_parts} parts..")
    s = Solver(model, cfg, mesh=make_mesh(n_dev_used), n_parts=n_parts,
               elem_part=elem_part, backend=args.backend)
    daemon = ServeDaemon(
        s, args.spool, queue_max=args.queue_max, widths=widths,
        expected_iters=args.expected_iters, poll_s=args.poll_s)
    print(f">serve: spool={args.spool} queue_max={args.queue_max} "
          f"widths={daemon.widths} (SIGTERM drains; journal="
          f"{daemon.journal.path})")
    reason = daemon.run(max_blocks=args.max_blocks,
                        idle_exit_s=args.idle_exit_s)
    print(f">serve: drained ({reason}) — {daemon.jobs_done} done, "
          f"{daemon.jobs_failed} failed, "
          f"{daemon.admission.shed_count} shed, "
          f"{daemon.blocks} block(s)")
    _finish_telemetry(s, args)
    print(">success!")


def cmd_submit(args):
    """Submit one job to a solve-service spool (import-light: works
    from a login node without the accelerator environment).  Prints the
    job id; poll ``spool/results/<job>.json`` — every submitted job
    eventually gets a result with a named verdict."""
    from pcg_mpi_solver_tpu.serve import jobs as sjobs

    spec = {"deadline_s": args.deadline_s}
    if args.job_id:
        spec["job"] = args.job_id
    if args.rhs is not None:
        spec["rhs"] = args.rhs
    if args.scale is not None:
        spec["scale"] = args.scale
    try:
        job = sjobs.submit(args.spool, spec)
    except ValueError as e:
        raise SystemExit(f"submit: {e}")
    print(f">submitted {job} -> "
          f"{sjobs.result_path(args.spool, job)}")


def cmd_jobs(args):
    """Job table of a solve-service spool, folded from the journal —
    works on a live daemon's spool (the journal is append-only and
    torn-tail tolerant) and on a crashed one (what WOULD replay)."""
    from pcg_mpi_solver_tpu.serve import jobs as sjobs
    from pcg_mpi_solver_tpu.serve.journal import read_journal, replay_jobs

    path = sjobs.journal_path(args.spool)
    if not os.path.exists(path):
        raise SystemExit(f"jobs: no journal at {path}")
    events, truncated = read_journal(path)
    states = replay_jobs(events)
    if truncated:
        print(f">warning: {truncated} torn journal line(s) skipped")
    print(f">{'job':12s} {'ordinal':>7s} {'state':12s} verdict")
    for st in sorted(states.values(),
                     key=lambda s: (s["ordinal"] is None,
                                    s["ordinal"] or 0)):
        o = "-" if st["ordinal"] is None else str(st["ordinal"])
        print(f">{st['job']:12s} {o:>7s} {st['op'] or '?':12s} "
              f"{st['verdict'] or ''}")
    n_term = sum(st["terminal"] for st in states.values())
    print(f">{len(states)} job(s), {n_term} terminal, "
          f"{len(states) - n_term} in flight")


def cmd_validate(args):
    """Run the validate/ preflight checks against a scratch model and
    report every one — the dry-run twin of the gate that solve/dynamics/
    newmark apply at construction.  The --preflight policy drives the
    exit code exactly as it would drive the gate: fail (default) exits
    non-zero on any failed check, warn reports and exits zero, off skips
    the scans entirely."""
    from pcg_mpi_solver_tpu.models.mdf import read_mdf
    from pcg_mpi_solver_tpu.validate import preflight_checks, resolve_policy

    pol = resolve_policy(getattr(args, "preflight", None))
    if pol == "off":
        print(">validate: preflight policy is off; nothing checked")
        return
    cfg = _load_settings(args.settings, args)
    model = read_mdf(os.path.join(args.scratch, "ModelData", "MDF"))
    print(f">preflight: {model.n_elem} elems / {model.n_dof} dofs")
    results = preflight_checks(model, cfg, context={"kind": "validate"})
    n_fail = 0
    for r in results:
        tag = {"ok": "  ok ", "warn": " WARN", "fail": " FAIL"}[r.status]
        n_fail += r.status == "fail"
        print(f">[{tag}] {r.name}" + (f": {r.detail}" if r.detail else ""))
    if n_fail and pol == "fail":
        raise SystemExit(f"validate: {n_fail} failed check(s)")
    if n_fail:
        print(f">validate: {n_fail} failed check(s) (policy={pol}; "
              "exit 0)")
    else:
        print(">validate: all checks passed")


def _print_dyn_summary(store_dir, name, u, extra=""):
    os.makedirs(store_dir, exist_ok=True)
    out = os.path.join(store_dir, name)
    np.save(out, u)
    print(f">final displacement -> {out}.npy{extra}")


def cmd_dynamics(args):
    """Explicit central-difference time history (solver/dynamics.py),
    preemption-safe: --snapshot-every N checkpoints the full state every
    N TIMESTEPS, --resume continues mid-history bit-identically."""
    from pcg_mpi_solver_tpu.models.mdf import read_mdf
    from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
    from pcg_mpi_solver_tpu.solver.dynamics import DynamicsSolver

    cfg = _load_settings(args.settings, args)
    cfg.scratch_path = args.scratch
    cfg.run_id = args.run_id
    cfg.snapshot_every = int(args.snapshot_every or 0)
    if args.max_recoveries is not None:
        cfg.solver.max_recoveries = int(args.max_recoveries)
    model = read_mdf(os.path.join(args.scratch, "ModelData", "MDF"))
    n_parts, _elem_part, n_dev, n_dev_used = _resolve_partition_mesh(
        args.n_parts, args.scratch)
    probe = tuple(int(d) for d in (args.probe_dofs or "").split(",") if d)
    print(f">explicit dynamics on {n_dev_used}/{n_dev} device(s), "
          f"{n_parts} parts, {args.n_steps} steps..")
    dyn = DynamicsSolver(model, cfg, mesh=make_mesh(n_dev_used),
                         n_parts=n_parts, dt=args.dt,
                         damping=args.damping, probe_dofs=probe,
                         backend=args.backend)
    print(f">backend: {dyn.backend}  dt={dyn.dt:.4e}")
    res = dyn.run(args.n_steps, export_every=args.export_every,
                  resume=bool(args.resume))
    print(f">integrated {args.n_steps} steps "
          f"({len(res.frames)} frames, {res.probe_u.shape[0]} probes)")
    _print_dyn_summary(cfg.result_path, "u_dynamics", res.u)
    if len(probe):
        np.save(os.path.join(cfg.result_path, "probe_dynamics"),
                res.probe_u)
        print(f">probe series -> {cfg.result_path}/probe_dynamics.npy")
    _finish_telemetry(dyn, args)
    print(">success!")


def cmd_newmark(args):
    """Implicit Newmark-beta time history (solver/newmark.py), one PCG
    solve per step, preemption-safe: --snapshot-every N checkpoints the
    kinematic state every N TIMESTEPS, --resume continues mid-history
    bit-identically (including the per-step trace ring)."""
    from pcg_mpi_solver_tpu.models.mdf import read_mdf
    from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
    from pcg_mpi_solver_tpu.solver.newmark import NewmarkSolver

    cfg = _load_settings(args.settings, args)
    cfg.scratch_path = args.scratch
    cfg.run_id = args.run_id
    cfg.snapshot_every = int(args.snapshot_every or 0)
    if args.max_recoveries is not None:
        cfg.solver.max_recoveries = int(args.max_recoveries)
    model = read_mdf(os.path.join(args.scratch, "ModelData", "MDF"))
    n_parts, _elem_part, n_dev, n_dev_used = _resolve_partition_mesh(
        args.n_parts, args.scratch)
    dt = args.dt if args.dt else (model.dt if model.dt > 0 else 1.0)
    print(f">Newmark dynamics on {n_dev_used}/{n_dev} device(s), "
          f"{n_parts} parts, {args.n_steps} steps, dt={dt:.4e}..")
    s = NewmarkSolver(model, cfg, mesh=make_mesh(n_dev_used),
                      n_parts=n_parts, dt=dt, beta=args.beta,
                      gamma=args.gamma, damping=args.damping,
                      backend=args.backend)
    print(f">backend: {s.backend}")
    res = s.run([1.0] * args.n_steps, resume=bool(args.resume))
    t_first = len(s.flags) - len(res) + 1
    for t, r in enumerate(res, t_first):
        print(f">step {t}: flag={r.flag} iters={r.iters} "
              f"relres={r.relres:.3e} wall={r.wall_s:.2f}s")
    _print_dyn_summary(cfg.result_path, "u_newmark",
                       s.displacement_global())
    _finish_telemetry(s, args)
    print(">success!")


def cmd_export(args):
    from pcg_mpi_solver_tpu.models.mdf import read_mdf
    from pcg_mpi_solver_tpu.utils.io import RunStore
    from pcg_mpi_solver_tpu.vtk.export import export_vtk

    from pcg_mpi_solver_tpu.config import RunConfig

    model = read_mdf(os.path.join(args.scratch, "ModelData", "MDF"))
    cfg = RunConfig(scratch_path=args.scratch, run_id=args.run_id)
    store = RunStore(cfg.result_path, "model")
    files = export_vtk(model, store, args.vars.split(), args.mode)
    print(f">wrote {len(files)} vtu files to {store.vtk_path}")


def cmd_demo(args):
    from pcg_mpi_solver_tpu.models.synthetic import make_cube_model
    from pcg_mpi_solver_tpu.solver.driver import Solver
    from pcg_mpi_solver_tpu.utils.io import RunStore
    from pcg_mpi_solver_tpu.vtk.export import export_vtk

    cfg = _load_settings(args.settings, args)
    cfg.scratch_path = args.scratch
    cfg.time_history.export_vars = "U D ES PS PE"
    vtk_vars, vtk_mode = ["U", "PS1", "PS3", "ES"], "Full"
    if getattr(args, "poisson", False):
        from pcg_mpi_solver_tpu.models.synthetic import make_poisson_model

        cfg.model_name = "demo_poisson"
        cfg.time_history.export_vars = "U"      # scalar class: U only
        vtk_vars, vtk_mode = ["U"], "Boundary"
        model = make_poisson_model(args.nx, args.ny or 0, args.nz or 0,
                                   heterogeneous=True, seed=1)
        print(f">demo poisson: {model.n_elem} elems / {model.n_dof} dofs "
              "(scalar diffusion)")
    elif args.octree:
        from pcg_mpi_solver_tpu.models.octree import make_octree_model

        cfg.model_name = "demo_octree"
        model = make_octree_model(
            args.nx, args.ny or args.nx, args.nz or args.nx,
            max_level=args.max_level, n_incl=3, seed=1,
            E=30e9, nu=0.2, load="traction", load_value=1e6)
        print(f">demo octree: {model.n_elem} elems / {model.n_dof} dofs / "
              f"{len(model.elem_lib)} pattern types")
    else:
        cfg.model_name = "demo_cube"
        model = make_cube_model(args.nx, args.ny or 0, args.nz or 0,
                                E=30e9, nu=0.2, load="traction",
                                load_value=1e6, heterogeneous=True)
        print(f">demo model: {model.n_elem} elems / {model.n_dof} dofs")
    # the octree demo EXPLICITLY showcases the hybrid level-grid path
    # (auto-selection is deprecation-gated behind PCG_TPU_ENABLE_HYBRID,
    # ISSUE 14 — an explicit request stays honored)
    s = Solver(model, cfg, backend="hybrid" if args.octree else "auto")
    store = RunStore(cfg.result_path, cfg.model_name)
    res = s.solve(store=store)
    for t, r in enumerate(res, 1):
        print(f">step {t}: flag={r.flag} iters={r.iters} relres={r.relres:.3e} "
              f"wall={r.wall_s:.2f}s  [{s.backend} backend]")
    files = export_vtk(model, store, vtk_vars, vtk_mode)
    print(f">wrote {len(files)} vtu files to {store.vtk_path}")
    _finish_telemetry(s, args)
    print(">success!")


def cmd_warmup(args):
    """Pre-bake the warm-path caches for a model/config (docs/RUNBOOK.md
    "Warm path"): partition + AOT step + persistent XLA compile entries,
    so the solve inside a scarce hardware window pays no setup."""
    from pcg_mpi_solver_tpu.cache.partition_cache import format_stats
    from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
    from pcg_mpi_solver_tpu.solver.driver import Solver

    cfg = _load_settings(args.settings, args)
    cfg.cache_dir = _resolve_cache_dir(args)
    if not cfg.cache_dir:
        # refusing to invent a default: a warmup baked into a dir the
        # later solve does not read is worse than no warmup at all
        raise SystemExit(
            "warmup: pass --cache-dir DIR (or set PCG_TPU_CACHE_DIR) — "
            "and run the solve with the SAME dir to use the baked caches")
    if args.demo_nx:
        from pcg_mpi_solver_tpu.models.synthetic import make_cube_model

        model = make_cube_model(args.demo_nx, 0, 0, E=30e9, nu=0.2,
                                load="traction", load_value=1e6,
                                heterogeneous=True)
    elif args.scratch:
        from pcg_mpi_solver_tpu.models.mdf import read_mdf

        cfg.scratch_path = args.scratch
        model = read_mdf(os.path.join(args.scratch, "ModelData", "MDF"))
    else:
        raise SystemExit("warmup: pass a <scratch> dir or --demo-nx N")
    # the scratch MeshPart map belongs to the scratch MODEL — when
    # --demo-nx overrode the model above, pairing it with a synthetic
    # cube would index past the cube's element count
    n_parts, elem_part, n_dev, n_dev_used = _resolve_partition_mesh(
        args.n_parts, None if args.demo_nx else args.scratch)
    print(f">warming {model.n_dof} dofs on {n_dev_used}/{n_dev} device(s), "
          f"{n_parts} parts ({cfg.solver.precision_mode} precision) into "
          f"{cfg.cache_dir} ..")
    s = Solver(model, cfg, mesh=make_mesh(n_dev_used), n_parts=n_parts,
               elem_part=elem_part, backend=args.backend)
    print(f">backend: {s.backend}  setup: {s.setup_s:.2f}s "
          f"({s.setup_cache} partition)")
    s.warmup()
    _finish_telemetry(s, args)
    print(format_stats(cfg.cache_dir))
    print(">warm path ready")


def cmd_cache_stats(args):
    from pcg_mpi_solver_tpu.cache.partition_cache import format_stats

    d = _resolve_cache_dir(args)
    if not d:
        raise SystemExit("cache-stats: pass --cache-dir DIR (or set "
                         "PCG_TPU_CACHE_DIR)")
    print(format_stats(d))


def cmd_bench(args):
    from pcg_mpi_solver_tpu.bench import main as bench_main

    bench_main()


def cmd_lint(args):
    """Contract lint (analysis/): statically prove the solver's
    structural claims — loop-body collective budgets, hot-loop purity,
    f32 dtype discipline, donated-carry aliasing, cache-key/snapshot-
    fingerprint completeness, plus the recovery-path and telemetry-
    schema source/artifact lints.  Runs on CPU (the env is pinned before
    jax initializes); exit 0 = every invariant holds."""
    from pcg_mpi_solver_tpu.analysis.__main__ import run, setup_cpu_env

    setup_cpu_env()
    rc = run(args)
    if rc:
        raise SystemExit(rc)


def cmd_summary(args):
    """Offline summary of an on-disk telemetry/flight JSONL artifact —
    tolerant by design: the exact artifact a dead tunnel produces has a
    truncated trailing line, which is SKIPPED and counted
    (``truncated_lines``), never raised on.  Flight records present in
    the stream add the mechanical verdict (clean / failed / died) with
    the in-flight record names and last heartbeat.  A base path that a
    multi-process run sharded away (run.jsonl -> run.p<idx>.jsonl)
    falls back to its per-process shards, each summarized in turn."""
    from pcg_mpi_solver_tpu.obs.flight import find_shards
    from pcg_mpi_solver_tpu.obs.metrics import summarize_jsonl

    first = True
    for path in args.files:
        if os.path.exists(path):
            targets = [path]
        else:
            targets = find_shards(path)
            if not targets:
                raise SystemExit(f"summary: {path}: no such file (and "
                                 "no .p<N>.jsonl shard siblings)")
            if not first:
                print()
            print(f">summary: {path}: sharded by a multi-process run — "
                  f"{len(targets)} per-process shard(s)")
            first = False
        for t in targets:
            if not first:
                print()
            first = False
            if len(targets) > 1:
                print(f"--- {t}")
            print(summarize_jsonl(t))


def cmd_telemetry_merge(args):
    """Aggregate per-process telemetry/flight shards (multi-process
    jax.distributed writes run.p<idx>.jsonl per process) into ONE
    time-ordered JSONL stream, each event tagged with its source shard.
    Truncated lines — the dead-tunnel signature — are skipped and
    counted, never raised on."""
    from pcg_mpi_solver_tpu.obs.flight import find_shards, merge_shards

    paths = []
    for p in args.paths:
        shards = find_shards(p)
        for s in (shards or ([p] if os.path.exists(p) else [])):
            if s not in paths:
                paths.append(s)
    if not paths:
        raise SystemExit("telemetry-merge: no shards found for "
                         f"{args.paths} (expected FILE.jsonl and/or "
                         "FILE.p<N>.jsonl siblings)")
    align = None if args.align == "none" else args.align
    stats = merge_shards(paths, args.out, align=align)
    for name in sorted(stats["shards"]):
        st = stats["shards"][name]
        print(f">shard {name}: {st['events']} event(s), "
              f"{st['truncated']} truncated line(s) skipped")
    al = stats.get("align")
    if al is not None:
        if al["matched_anchors"]:
            offs = "  ".join(f"{n}={v:+.6f}s"
                             for n, v in sorted(al["offsets_s"].items()))
            print(f">clock alignment ({al['mode']}): "
                  f"{al['matched_anchors']} matched anchor(s); "
                  f"offsets vs first shard: {offs}")
        else:
            print(">clock alignment: no matched dispatch anchors across "
                  "shards — falling back to raw t ordering")
    print(f">merged {stats['events']} event(s) from "
          f"{len(stats['shards'])} shard(s) -> {args.out}"
          + (f" ({stats['truncated_lines']} truncated line(s) skipped)"
             if stats["truncated_lines"] else ""))


def cmd_perf_report(args):
    """Measured-vs-model phase attribution (ISSUE 12): time the matvec /
    precond / reduction / axpy sub-programs of a live solver individually
    (obs/phases.py — compiled from the solver's own ops/data) next to the
    analytic cost model's roofline prediction (obs/perf.py), anchored by
    a real whole-iteration measurement.  Runs chiplessly on CPU, so the
    attribution table exists BEFORE a hardware window opens."""
    from pcg_mpi_solver_tpu.obs import perf as _perf
    from pcg_mpi_solver_tpu.obs.phases import run_phase_probe
    from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
    from pcg_mpi_solver_tpu.solver.driver import Solver

    cfg = _load_settings(args.settings, args)
    if cfg.solver.precision_mode != "direct":
        raise SystemExit(
            "perf-report: phase probes need a direct-mode solver (one "
            "dtype, one loop) — drop --precision mixed")
    nrhs = max(1, int(args.nrhs))
    cfg.solver.nrhs = nrhs
    if args.scratch:
        from pcg_mpi_solver_tpu.models.mdf import read_mdf

        cfg.scratch_path = args.scratch
        model = read_mdf(os.path.join(args.scratch, "ModelData", "MDF"))
    else:
        from pcg_mpi_solver_tpu.models.synthetic import make_cube_model

        model = make_cube_model(args.nx, 0, 0, E=30e9, nu=0.2,
                                load="traction", load_value=1e6,
                                heterogeneous=True)
    n_parts, elem_part, n_dev, n_dev_used = _resolve_partition_mesh(
        args.n_parts, args.scratch)
    print(f">perf-report: {model.n_elem} elems / {model.n_dof} dofs on "
          f"{n_dev_used}/{n_dev} device(s), {n_parts} parts "
          f"({cfg.solver.pcg_variant} variant, {cfg.solver.precond} "
          f"precond, nrhs={nrhs})..")
    s = Solver(model, cfg, mesh=make_mesh(n_dev_used), n_parts=n_parts,
               elem_part=elem_part, backend=args.backend)
    print(f">backend: {s.backend}")
    cm = s._cost_model
    if cm is None:
        # The Solver degrades _cost_model to None (with a recorder note)
        # when the derivation raises on an exotic model; the measured
        # table must still print, so degrade the same way here.  Like
        # the Solver, only the cost_model() table lookup stays loud.
        try:
            shp = _perf.shape_from_solver(s)
            prof = _perf.resolve_profile(s.mesh.devices.flat[0].platform)
            cm = _perf.cost_model(shp, cfg.solver.pcg_variant,
                                  cfg.solver.precond, nrhs, prof)
        except Exception as e:                          # noqa: BLE001
            print(f">cost model unavailable ({type(e).__name__}: {e}) "
                  "— measured-only table")
            cm = None
    probe = run_phase_probe(s, reps=args.reps, nrhs=nrhs,
                            inner=args.inner)
    trace_rep = None
    if getattr(args, "profile_dir", None):
        # ISSUE 15: the MEASURED column — capture a device trace of one
        # warm solve on this same solver and parse it back
        # (obs/profview.py); capture trouble degrades to the
        # predicted|recorded table, never a crash.
        from pcg_mpi_solver_tpu.obs import profview

        try:
            cap = profview.capture_solve_profile(
                s, args.profile_dir, nrhs=nrhs, recorder=s.recorder)
            trace_rep = profview.profile_report(cap["artifact"])
            profview.emit_prof_report(s.recorder, trace_rep)
        except Exception as e:                          # noqa: BLE001
            print(f">device-trace capture failed ({type(e).__name__}: "
                  f"{e}) — the predicted|recorded table below stands")
    if trace_rep is not None:
        # predicted | recorded | measured: the cost model next to the
        # compiled phase probes next to the parsed-trace attribution
        print()
        print(profview.format_report(trace_rep, predicted=cm,
                                     recorded=probe["phases"]))
        _finish_telemetry(s, args)
        return
    print()
    print(f"{'phase':<10} {'model_ms':>10} {'measured_ms':>12} "
          f"{'share':>7}")
    sum_ms = probe["sum_ms_per_iter"] or 0.0
    model_sum = 0.0
    for ph in _perf.PHASES:
        mm = cm["phases"][ph]["model_ms"] if cm is not None else None
        model_sum += mm or 0.0
        meas = probe["phases"][ph]
        share = (meas / sum_ms) if sum_ms else 0.0
        mm_s = f"{mm:>10.4f}" if mm is not None else f"{'-':>10}"
        print(f"{ph:<10} {mm_s} {meas:>12.4f} {share:>6.0%}")
    msum_s = f"{model_sum:>10.4f}" if cm is not None else f"{'-':>10}"
    print(f"{'sum':<10} {msum_s} {sum_ms:>12.4f}")
    whole = probe.get("whole_ms_per_iter")
    if whole:
        print(f"\n>whole-iteration anchor: {whole:.4f} ms/iter "
              f"({probe.get('whole_iters', '?')} iters, real solve "
              "program)")
        print(f">attribution (phase sum / whole): "
              f"{probe['attribution']:.2f}")
        if cm is not None and cm["predicted_ms_per_iter"]:
            print(f">model ratio (measured whole / predicted): "
                  f"{whole / cm['predicted_ms_per_iter']:.2f} "
                  f"(predicted {cm['predicted_ms_per_iter']:.4f} ms/iter, "
                  f"profile={cm['profile']})")
    _finish_telemetry(s, args)


def cmd_prof_report(args):
    """Offline device-trace report (ISSUE 15, obs/profview.py): parse a
    captured profiler artifact — the trace-viewer JSON(.gz) itself, its
    run dir, or any capture root — into per-phase attribution, the
    measured collective-overlap fraction, and the tolerant reader's
    verdict.  Works on any artifact, chiplessly: truncated files and
    missing device lanes degrade to a NAMED verdict, never a crash.
    When the capture sidecar (profview_meta.json) is present, the
    obs/perf.py cost model is rebuilt from it for the predicted
    column.  jax is never imported — a dead-tunnel post-mortem must
    not wait on an accelerator runtime."""
    from pcg_mpi_solver_tpu.obs import profview

    # resolve the artifact and its sidecar ONCE, then hand both to the
    # parser (profile_report short-circuits on a direct file path)
    files = profview.find_trace_files(args.path)
    meta = profview.load_meta(files[0]) if files else None
    rep = profview.profile_report(files[0] if files else args.path,
                                  meta=meta, iters=args.iters)
    predicted = None
    try:
        predicted = profview.predicted_from_meta(meta or {})
    except KeyError as e:
        print(f">predicted column unavailable: unknown name {e} in the "
              "capture sidecar (name tables out of sync?)")
    if meta:
        print(f">profile: {meta.get('pcg_variant')} variant, "
              f"{meta.get('precond')} precond, nrhs={meta.get('nrhs')}, "
              f"{meta.get('backend')} backend, "
              f"{meta.get('n_dof')} dofs on "
              f"{meta.get('n_devices')} device(s) "
              f"[{meta.get('platform')}]")
    print(profview.format_report(rep, predicted=predicted))
    if args.telemetry_out:
        from pcg_mpi_solver_tpu.obs.metrics import (
            JsonlSink, MetricsRecorder)

        rec = MetricsRecorder(sinks=[JsonlSink(args.telemetry_out)])
        profview.emit_prof_report(rec, rep)
        rec.close()
        print(f">telemetry: {args.telemetry_out}")
    if not files:
        raise SystemExit(2)


def cmd_fleet_report(args):
    """Cross-process collective-skew attribution (ISSUE 16,
    obs/fleet.py): align the per-process capture subdirs
    (``p<idx>/…``) a multi-controller ``capture_solve_profile`` run
    writes on matched collective END anchors, split every matched
    collective into transport vs wait, and name the straggler per phase.
    Offline and jax-free like ``prof-report``; a single-process capture
    or a collective-free trace degrades to a NAMED verdict, never a
    crash."""
    from pcg_mpi_solver_tpu.obs import fleet

    rep = fleet.fleet_report(args.path)
    print(fleet.format_fleet_report(rep))
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(rep, f, indent=1, sort_keys=True)
        print(f">json: {args.json}")
    if args.telemetry_out:
        from pcg_mpi_solver_tpu.obs.metrics import (
            JsonlSink, MetricsRecorder)

        rec = MetricsRecorder(sinks=[JsonlSink(args.telemetry_out)])
        fleet.emit_fleet_report(rec, rep)
        rec.close()
        print(f">telemetry: {args.telemetry_out}")
    if rep["n_processes"] == 0:
        raise SystemExit(2)


def cmd_watch(args):
    """Live run monitor (ISSUE 16, obs/watch.py): tail the flight/
    telemetry JSONL shards of a running solve — per-dispatch progress,
    completed-step residuals, a stall alarm when ALL shards' heartbeats
    go silent past the threshold, and a cost-model x observed-rate ETA.
    ``--once`` prints one snapshot and exits (exit 3 when that snapshot
    is a stall — the scriptable probe); the default polls until the run
    is done or interrupted.  Read-only on the watched stream."""
    from pcg_mpi_solver_tpu.obs import watch

    rec = None
    if args.telemetry_out:
        from pcg_mpi_solver_tpu.obs.metrics import (
            JsonlSink, MetricsRecorder)

        rec = MetricsRecorder(sinks=[JsonlSink(args.telemetry_out)])
    stalled = False
    try:
        while True:
            snap = watch.watch_snapshot(args.path,
                                        stall_after_s=args.stall_after,
                                        tol=args.tol)
            print(watch.format_watch(snap), flush=True)
            if rec is not None:
                watch.emit_watch_events(rec, snap)
            stalled = snap["status"] == "stalled"
            if args.once or snap["status"] == "done":
                break
            try:
                time.sleep(args.interval)
            except KeyboardInterrupt:
                break
            print(flush=True)
    finally:
        if rec is not None:
            rec.close()
            print(f">telemetry: {args.telemetry_out}")
    if stalled and args.once:
        raise SystemExit(3)


def cmd_trend(args):
    """Bench-trend regression sentinel (ISSUE 15, obs/trend.py): match
    legs across the committed BENCH_r*.json round artifacts (plus an
    optional fresh artifact) by shape/variant/precond/nrhs and print
    per-leg deltas with threshold verdicts.  Exit 1 = at least one
    matched leg REGRESSED; exit 2 = nothing to compare."""
    from pcg_mpi_solver_tpu.obs import trend

    thr = (args.threshold if args.threshold is not None
           else trend.DEFAULT_THRESHOLD)
    rc = trend.main_cli(list(args.artifacts), fresh=args.fresh,
                        threshold=thr)
    if rc:
        raise SystemExit(rc)


def main(argv=None):
    ap = argparse.ArgumentParser(prog="pcg-tpu", description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("ingest", help="unpack a reference MDF model archive")
    p.add_argument("archive")
    p.add_argument("scratch")
    p.set_defaults(fn=cmd_ingest)

    p = sub.add_parser("partition", help="compute element->part map")
    p.add_argument("scratch")
    p.add_argument("n_parts", type=int)
    p.add_argument("--method", choices=["rcb", "graph", "auto"], default="auto",
                   help="rcb = coordinate bisection; graph = native "
                        "multilevel dual-graph partitioner (METIS-equivalent)")
    p.set_defaults(fn=cmd_partition)

    p = sub.add_parser("solve", help="run the SPMD PCG solve")
    p.add_argument("scratch")
    p.add_argument("run_id")
    p.add_argument("--settings", default=None)
    p.add_argument("--n-parts", type=int, default=None)
    p.add_argument("--tol", type=float, default=None)
    p.add_argument("--max-iter", type=int, default=None)
    p.add_argument("--precision", choices=["direct", "mixed"], default=None)
    p.add_argument("--precond", choices=_precond_choices(), default=None,
                   help="preconditioner: scalar Jacobi (reference "
                        "parity), 3x3 node-block Jacobi (stronger on "
                        "heterogeneous elasticity), or mg — geometric "
                        "multigrid V-cycle on the lattice hierarchy "
                        "(>=5x fewer iterations on lattice models; "
                        "docs/RUNBOOK.md 'Choosing a preconditioner')")
    _add_variant_flag(p)
    p.add_argument("--speed-test", action="store_true",
                   help="disable all exports for clean timing "
                        "(reference SpeedTestFlag)")
    p.add_argument("--checkpoint-every", type=int, default=0,
                   help="write a solver checkpoint every N time steps")
    _add_resilience_flags(p, "mid-Krylov chunk boundaries", elastic=True)
    p.add_argument("--backend",
                   choices=["auto", "structured", "hybrid", "general"],
                   default="auto",
                   help="matvec backend (auto: structured for uniform "
                        "grids, hybrid for octrees, else general)")
    p.add_argument("--profile-dir", default=None,
                   help="write a jax.profiler trace of the solve here "
                        "(open with TensorBoard; shows the per-op "
                        "compute/collective split; ignored with --speed-test)")
    _add_telemetry_flags(p)
    _add_cache_flag(p)
    _add_preflight_flag(p)
    p.set_defaults(fn=cmd_solve)

    p = sub.add_parser("solve-many",
                       help="batched multi-RHS solve: many load cases "
                            "against one shared partitioned operator "
                            "(per-RHS convergence masks; collective "
                            "count independent of the block width)")
    p.add_argument("scratch")
    p.add_argument("run_id")
    p.add_argument("--rhs", default=None, metavar="FILE.npy",
                   help="load-case block: (n_dof, nrhs) array, one "
                        "column per case ((nrhs, n_dof) is transposed)")
    p.add_argument("--scales", default=None, metavar="S0,S1,...",
                   help="alternative block: columns = scale * the "
                        "model's reference load F")
    p.add_argument("--settings", default=None)
    p.add_argument("--n-parts", type=int, default=None)
    p.add_argument("--tol", type=float, default=None)
    p.add_argument("--max-iter", type=int, default=None)
    p.add_argument("--precision", choices=["direct", "mixed"], default=None)
    p.add_argument("--precond", choices=_precond_choices(), default=None)
    _add_variant_flag(p)
    p.add_argument("--backend",
                   choices=["auto", "structured", "hybrid", "general"],
                   default="auto")
    _add_resilience_flags(p, "blocked-solve chunk boundaries")
    _add_telemetry_flags(p)
    _add_cache_flag(p)
    _add_preflight_flag(p)
    p.set_defaults(fn=cmd_solve_many)

    p = sub.add_parser("serve",
                       help="multi-tenant solve daemon: admit filesystem-"
                            "submitted jobs against one warm operator "
                            "(cost-model deadline pricing, bounded queue "
                            "with load shedding, nrhs packing, crash-"
                            "durable exactly-once journal)")
    p.add_argument("scratch", nargs="?", default=None,
                   help="scratch dir with an ingested model (or use "
                        "--synthetic)")
    p.add_argument("--spool", required=True, metavar="DIR",
                   help="service root: incoming/, results/, "
                        "journal.jsonl")
    p.add_argument("--synthetic", default=None, metavar="NX[,NY,NZ]",
                   help="serve a synthetic heterogeneous cube instead "
                        "of a scratch model")
    p.add_argument("--queue-max", type=int, default=16,
                   help="bounded admission queue depth (default 16); "
                        "arrivals beyond it shed past-deadline jobs or "
                        "are rejected queue_full")
    p.add_argument("--widths", default="1,2,4,8",
                   help="standard nrhs block widths jobs are packed "
                        "into (default 1,2,4,8; the AOT cache compiles "
                        "once per width)")
    p.add_argument("--expected-iters", type=int, default=None,
                   help="iteration count admission prices deadlines "
                        "against (default: the solver max_iter cap — "
                        "conservative)")
    p.add_argument("--poll-s", type=float, default=0.05,
                   help="incoming-directory poll interval (default 0.05)")
    p.add_argument("--idle-exit-s", type=float, default=None,
                   help="drain after this long idle (default: serve "
                        "forever until SIGTERM)")
    p.add_argument("--max-blocks", type=int, default=None,
                   help="drain after dispatching N blocks (bench/test "
                        "knob)")
    p.add_argument("--settings", default=None)
    p.add_argument("--n-parts", type=int, default=None)
    p.add_argument("--tol", type=float, default=None)
    p.add_argument("--max-iter", type=int, default=None)
    p.add_argument("--precision", choices=["direct", "mixed"], default=None)
    p.add_argument("--precond", choices=_precond_choices(), default=None)
    _add_variant_flag(p)
    p.add_argument("--backend",
                   choices=["auto", "structured", "hybrid", "general"],
                   default="auto")
    _add_telemetry_flags(p)
    _add_cache_flag(p)
    _add_preflight_flag(p)
    p.set_defaults(fn=cmd_serve)

    p = sub.add_parser("submit",
                       help="submit one job to a solve-service spool "
                            "(atomic drop; import-light — works from a "
                            "login node)")
    p.add_argument("--spool", required=True, metavar="DIR")
    p.add_argument("--scale", type=float, default=None,
                   help="load case = scale * the model's reference "
                        "load F")
    p.add_argument("--rhs", default=None, metavar="FILE.npy",
                   help="load case = an (n_dof,) .npy column (exactly "
                        "one of --scale / --rhs)")
    p.add_argument("--deadline-s", type=float, default=3600.0,
                   help="relative deadline admission prices against "
                        "(default 3600)")
    p.add_argument("--job-id", default=None,
                   help="explicit job id (default: generated); "
                        "resubmitting a consumed id is dropped — "
                        "exactly-once is per id")
    p.set_defaults(fn=cmd_submit)

    p = sub.add_parser("jobs",
                       help="job table of a solve-service spool, folded "
                            "from the crash-durable journal")
    p.add_argument("--spool", required=True, metavar="DIR")
    p.set_defaults(fn=cmd_jobs)

    p = sub.add_parser("validate",
                       help="run the validate/ preflight checks against "
                            "a scratch model (dry run; no partition, no "
                            "compile)")
    p.add_argument("scratch")
    p.add_argument("--settings", default=None)
    p.add_argument("--tol", type=float, default=None)
    p.add_argument("--max-iter", type=int, default=None)
    p.add_argument("--precision", choices=["direct", "mixed"], default=None)
    _add_preflight_flag(p)
    p.set_defaults(fn=cmd_validate)

    p = sub.add_parser("dynamics",
                       help="explicit central-difference time history "
                            "(preemption-safe: timestep-granular "
                            "snapshots + --resume)")
    p.add_argument("scratch")
    p.add_argument("run_id")
    p.add_argument("--n-steps", type=int, required=True,
                   help="number of explicit timesteps to integrate")
    p.add_argument("--dt", type=float, default=None,
                   help="timestep (default: the model's dt, else the "
                        "CFL estimate; an explicit value above the CFL "
                        "bound is rejected by preflight)")
    p.add_argument("--damping", type=float, default=0.0,
                   help="mass-proportional damping coefficient c_m")
    p.add_argument("--export-every", type=int, default=0,
                   help="displacement frames every k steps (0 = none)")
    p.add_argument("--probe-dofs", default="",
                   help="comma-separated dof ids sampled every step")
    p.add_argument("--settings", default=None)
    p.add_argument("--n-parts", type=int, default=None)
    p.add_argument("--backend", choices=["auto", "hybrid", "general"],
                   default="auto")
    _add_resilience_flags(p, "timesteps")
    _add_telemetry_flags(p)
    _add_preflight_flag(p)
    p.set_defaults(fn=cmd_dynamics)

    p = sub.add_parser("newmark",
                       help="implicit Newmark-beta time history, one "
                            "PCG solve per step (preemption-safe: "
                            "timestep-granular snapshots + --resume)")
    p.add_argument("scratch")
    p.add_argument("run_id")
    p.add_argument("--n-steps", type=int, required=True,
                   help="number of implicit timesteps to integrate")
    p.add_argument("--dt", type=float, default=None,
                   help="timestep (default: the model's dt; "
                        "unconditionally stable at beta=1/4 gamma=1/2, "
                        "so dt is a resolution choice, not a CFL bound)")
    p.add_argument("--beta", type=float, default=0.25)
    p.add_argument("--gamma", type=float, default=0.5)
    p.add_argument("--damping", type=float, default=0.0,
                   help="mass-proportional damping coefficient c_m")
    p.add_argument("--settings", default=None)
    p.add_argument("--n-parts", type=int, default=None)
    p.add_argument("--tol", type=float, default=None)
    p.add_argument("--max-iter", type=int, default=None)
    p.add_argument("--precision", choices=["direct", "mixed"], default=None)
    p.add_argument("--precond", choices=_precond_choices(), default=None)
    _add_variant_flag(p)
    p.add_argument("--backend", choices=["auto", "hybrid", "general"],
                   default="auto")
    _add_resilience_flags(p, "timesteps")
    _add_telemetry_flags(p)
    _add_preflight_flag(p)
    p.set_defaults(fn=cmd_newmark)

    p = sub.add_parser("export", help="export result frames to VTK")
    p.add_argument("scratch")
    p.add_argument("run_id")
    p.add_argument("vars", help='e.g. "U PS1 ES"')
    p.add_argument("mode", choices=["Full", "Boundary", "MidSlices", "Delaunay"])
    p.set_defaults(fn=cmd_export)

    p = sub.add_parser("demo", help="synthetic end-to-end demo")
    p.add_argument("--nx", type=int, default=16)
    p.add_argument("--ny", type=int, default=0)
    p.add_argument("--nz", type=int, default=0)
    p.add_argument("--scratch", default="./scratch")
    p.add_argument("--settings", default=None)
    p.add_argument("--tol", type=float, default=None)
    p.add_argument("--max-iter", type=int, default=None)
    p.add_argument("--precision", choices=["direct", "mixed"], default="mixed")
    p.add_argument("--precond", choices=_precond_choices(), default=None)
    _add_variant_flag(p)
    p.add_argument("--octree", action="store_true",
                   help="graded octree model with transition pattern types "
                        "(nx/ny/nz = base cells; solved on the hybrid "
                        "level-grid backend)")
    p.add_argument("--max-level", type=int, default=2,
                   help="octree refinement levels (with --octree)")
    p.add_argument("--poisson", action="store_true",
                   help="scalar Poisson/diffusion model (1 dof per node, "
                        "heterogeneous conductivity)")
    _add_telemetry_flags(p)
    _add_cache_flag(p)
    _add_preflight_flag(p)
    p.set_defaults(fn=cmd_demo)

    p = sub.add_parser("warmup", help="pre-bake the warm-path caches "
                                      "(partition + AOT step + XLA "
                                      "compile) before a hardware window")
    p.add_argument("scratch", nargs="?", default=None,
                   help="scratch dir with an ingested MDF model "
                        "(or use --demo-nx)")
    p.add_argument("--demo-nx", type=int, default=0,
                   help="warm a synthetic nx^3 cube instead of a scratch "
                        "model (smoke/testing)")
    p.add_argument("--settings", default=None)
    p.add_argument("--n-parts", type=int, default=None)
    p.add_argument("--tol", type=float, default=None)
    p.add_argument("--max-iter", type=int, default=None)
    p.add_argument("--precision", choices=["direct", "mixed"], default=None)
    p.add_argument("--precond", choices=_precond_choices(), default=None)
    _add_variant_flag(p)
    p.add_argument("--backend",
                   choices=["auto", "structured", "hybrid", "general"],
                   default="auto")
    _add_telemetry_flags(p)
    _add_cache_flag(p)
    _add_preflight_flag(p)
    p.set_defaults(fn=cmd_warmup)

    p = sub.add_parser("cache-stats", help="show the warm-path cache table")
    _add_cache_flag(p)
    p.set_defaults(fn=cmd_cache_stats)

    p = sub.add_parser("bench", help="benchmark harness (prints one JSON line)")
    p.set_defaults(fn=cmd_bench)

    p = sub.add_parser("lint",
                       help="contract lint (analysis/): statically prove "
                            "collective budgets, hot-loop purity, dtype "
                            "discipline, donation aliasing and cache-key/"
                            "fingerprint completeness on CPU (see "
                            "docs/ANALYSIS.md)")
    # ONE option surface shared with `python -m pcg_mpi_solver_tpu.analysis`
    # (the same runner) — defined once so the two entry points cannot
    # drift.  analysis/ imports are jax-free, so this is safe here.
    from pcg_mpi_solver_tpu.analysis.__main__ import add_lint_args

    add_lint_args(p)
    p.set_defaults(fn=cmd_lint)

    p = sub.add_parser("perf-report",
                       help="measured-vs-model phase attribution: time "
                            "the matvec/precond/reduction/axpy "
                            "sub-programs of a live solver against the "
                            "analytic cost model's prediction "
                            "(obs/perf.py + obs/phases.py; runs "
                            "chiplessly on CPU)")
    p.add_argument("scratch", nargs="?", default=None,
                   help="scratch dir with an ingested MDF model "
                        "(default: a synthetic --nx cube)")
    p.add_argument("--nx", type=int, default=12,
                   help="synthetic heterogeneous cube size when no "
                        "scratch dir is given (default 12 — below ~10 "
                        "the while-loop carry machinery the four phases "
                        "deliberately exclude dominates the anchor and "
                        "the attribution ratio goes soft)")
    p.add_argument("--settings", default=None)
    p.add_argument("--n-parts", type=int, default=None)
    p.add_argument("--tol", type=float, default=None)
    p.add_argument("--max-iter", type=int, default=None)
    p.add_argument("--precond", choices=_precond_choices(),
                   default=None)
    _add_variant_flag(p)
    p.add_argument("--nrhs", type=int, default=1,
                   help="probe the blocked (multi-RHS) programs at this "
                        "block width")
    p.add_argument("--inner", type=int, default=16,
                   help="inner applications per timed dispatch "
                        "(amortizes host dispatch overhead)")
    p.add_argument("--reps", type=int, default=5,
                   help="interleaved measurement rounds (each times "
                        "every phase plus one whole-iteration anchor; "
                        "per-quantity best-of across rounds)")
    p.add_argument("--backend",
                   choices=["auto", "structured", "hybrid", "general"],
                   default="general",
                   help="matvec backend for the probed solver (default "
                        "general — the probe works on any, general is "
                        "the portable reference)")
    p.add_argument("--profile-dir", default=None, metavar="DIR",
                   help="also capture a device trace of one warm solve "
                        "into DIR and parse it back (obs/profview.py): "
                        "the table gains the MEASURED column next to "
                        "predicted (cost model) and recorded (phase "
                        "probes), plus the collective-overlap verdict")
    _add_telemetry_flags(p)
    _add_cache_flag(p)
    _add_preflight_flag(p)
    p.set_defaults(fn=cmd_perf_report, precision=None)

    p = sub.add_parser("prof-report",
                       help="parse a captured jax.profiler trace "
                            "artifact into per-phase attribution + the "
                            "measured collective-overlap verdict "
                            "(offline, tolerant — a truncated artifact "
                            "degrades to a named verdict)")
    p.add_argument("path",
                   help="trace artifact: the *.trace.json(.gz) file, "
                        "its run dir, or any capture root (e.g. the "
                        "--profile-dir / BENCH_PROFILE_DIR directory)")
    p.add_argument("--iters", type=int, default=None,
                   help="iteration count override for per-iteration "
                        "normalization (default: the capture sidecar's)")
    p.add_argument("--telemetry-out", default=None, metavar="FILE.jsonl",
                   help="also emit the schema-versioned prof_report "
                        "event + prof.* gauges here")
    p.set_defaults(fn=cmd_prof_report)

    p = sub.add_parser("fleet-report",
                       help="cross-process collective-skew attribution "
                            "over a multi-controller capture root "
                            "(p<idx>/ subdirs): clock-align on matched "
                            "collective ends, split transport vs wait, "
                            "name the straggler per phase (offline, "
                            "jax-free, tolerant)")
    p.add_argument("path",
                   help="capture root holding the per-process p<idx>/ "
                        "subdirs (e.g. the --profile-dir / "
                        "BENCH_PROFILE_DIR directory)")
    p.add_argument("--json", default=None, metavar="FILE.json",
                   help="also write the full report as JSON")
    p.add_argument("--telemetry-out", default=None, metavar="FILE.jsonl",
                   help="also emit the schema-versioned fleet_report "
                        "event + fleet.* gauges here")
    p.set_defaults(fn=cmd_fleet_report)

    p = sub.add_parser("watch",
                       help="live run monitor: tail the flight/telemetry "
                            "JSONL shards of a running solve — progress, "
                            "stall alarm (all shards silent past the "
                            "threshold), and a cost-model x observed-"
                            "rate ETA")
    p.add_argument("path", metavar="FILE.jsonl",
                   help="base telemetry/flight path; on-disk .p<N> "
                        "shards are tailed together")
    p.add_argument("--once", action="store_true",
                   help="print one snapshot and exit (exit 3 when it is "
                        "a stall)")
    p.add_argument("--interval", type=float, default=5.0,
                   help="poll interval in seconds (default 5)")
    p.add_argument("--stall-after", type=float, default=None,
                   metavar="S",
                   help="flag a stall when ALL shards are silent this "
                        "long (default: 3x the flight heartbeat "
                        "cadence)")
    p.add_argument("--tol", type=float, default=1e-8,
                   help="convergence target the ETA aims the observed "
                        "rate at (the stream does not carry the run's "
                        "tol; default matches SolverConfig)")
    p.add_argument("--telemetry-out", default=None, metavar="FILE.jsonl",
                   help="emit watch/stall events here (never to the "
                        "watched stream)")
    p.set_defaults(fn=cmd_watch)

    p = sub.add_parser("trend",
                       help="bench-trend regression sentinel: match "
                            "legs across committed BENCH_r*.json round "
                            "artifacts (by shape/variant/precond/nrhs) "
                            "and print threshold-based regressed/"
                            "improved/flat verdicts; exit 1 on a "
                            "regression")
    p.add_argument("artifacts", nargs="*", metavar="BENCH_rNN.json",
                   help="round artifacts in round order (default: "
                        "./BENCH_r*.json sorted)")
    p.add_argument("--fresh", default=None, metavar="FILE.json",
                   help="a fresh artifact (raw bench line or round "
                        "wrapper) appended as the newest round — the "
                        "before/after answer for a live window")
    p.add_argument("--threshold", type=float, default=None,
                   help="relative change separating flat from "
                        "regressed/improved (default 0.10)")
    p.set_defaults(fn=cmd_trend)

    p = sub.add_parser("summary",
                       help="offline summary of a telemetry/flight JSONL "
                            "artifact — tolerant of the truncated "
                            "trailing line a dead tunnel produces "
                            "(skipped + counted, never raised on)")
    p.add_argument("files", nargs="+", metavar="FILE.jsonl")
    p.set_defaults(fn=cmd_summary)

    p = sub.add_parser("telemetry-merge",
                       help="aggregate per-process telemetry shards "
                            "(FILE.p<N>.jsonl, written under "
                            "multi-process jax.distributed) into one "
                            "time-ordered stream")
    p.add_argument("paths", nargs="+", metavar="FILE.jsonl",
                   help="base path(s); on-disk .p<N> siblings are "
                        "discovered automatically")
    p.add_argument("--out", required=True, metavar="MERGED.jsonl")
    p.add_argument("--align", choices=["none", "collectives"],
                   default="none",
                   help="'collectives': clock-align shards on matched "
                        "dispatch completions (the fleet-report anchor "
                        "model) before ordering, so skewed host clocks "
                        "interleave in true order; events gain "
                        "t_aligned, raw t is preserved")
    p.set_defaults(fn=cmd_telemetry_merge)

    args = ap.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
