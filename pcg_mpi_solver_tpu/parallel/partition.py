"""Host-side partitioner: global ModelData -> padded per-device shards.

Re-designs the reference's MPI partitioner (src/solver/partition_mesh.py, 1428
LoC of per-rank python loops + Isend/Recv neighbor discovery) as a single
vectorized numpy pass producing a ``PartitionedModel``: every per-partition
structure is a dense array with a leading parts axis ``P``, padded to common
shapes so the whole solve is one jitted SPMD program.

Key re-designs vs the reference:

- Element->part assignment: recursive coordinate bisection over element
  centroids by default (replaces METIS dual-graph partitioning,
  run_metis.py:88; a native graph partitioner can plug in via ``elem_part``).
- Local renumbering (config_ElemVectors, partition_mesh.py:208-297): done with
  np.unique/searchsorted over whole partitions at once — no per-element loops.
- Neighbor discovery + halo maps (identify_PotentialNeighbours /
  config_Neighbours, partition_mesh.py:674-921): replaced by an exact global
  computation — a dof is "interface" iff it lives in >=2 parts.  Each part
  gets scatter/gather maps into one global interface vector; at solve time
  partial sums are combined with a single ``lax.psum`` (no point-to-point
  messaging, bitwise deterministic).
- Duplicate-dof weighting (partition_mesh.py:867-887): owner = lowest part id
  containing the dof, weight 1 on owner / 0 elsewhere, so global dots count
  every dof exactly once.
"""

from __future__ import annotations

import dataclasses
import warnings
from typing import Dict, List, Optional

import numpy as np

from pcg_mpi_solver_tpu.models.model_data import ModelData
from pcg_mpi_solver_tpu import native


# Host-side build-work call counters, bumped at the top of each builder
# (here and in parallel/structured.py, parallel/hybrid.py).  The cache/
# warm path's contract — "a warm cache hit performs ZERO partitioning
# work" — is asserted against these in tests/test_cache.py.  Monotonic;
# never reset by library code.
BUILD_CALLS = {
    "make_elem_part": 0,
    "partition_model": 0,
    "partition_structured": 0,
    "partition_hybrid": 0,
}


# ----------------------------------------------------------------------
# Element -> part assignment
# ----------------------------------------------------------------------

def graph_partition(model: ModelData, n_parts: int, ncommon: int = 1,
                    seed: int = 0, strict: bool = True) -> np.ndarray:
    """Dual-graph element partition via the native multilevel partitioner —
    the METIS-equivalent path (reference run_metis.py:84-88 calls
    ``metis.part_mesh_dual``).  With ``strict`` (the default) an unavailable
    native library raises; with ``strict=False`` it falls back to RCB."""
    part = native.part_mesh_dual(
        np.asarray(model.elem_nodes_offset, dtype=np.int64),
        np.asarray(model.elem_nodes_flat, dtype=np.int64),
        model.n_node, n_parts, ncommon=ncommon, seed=seed)
    if part is None:
        if strict:
            raise RuntimeError(
                "partition method 'graph' requires the native library "
                "(native/src/partition.cpp); build failed or g++ missing — "
                "use method='auto' or 'rcb' for the numpy fallback")
        return rcb_partition(model.sctrs, n_parts)
    if len(np.unique(part)) != n_parts:
        # The solver needs every part non-empty.
        if strict:
            raise RuntimeError(
                f"partition method 'graph' produced an empty part "
                f"(n_parts={n_parts}); the explicitly requested graph "
                "partition cannot be honored — use method='auto' or 'rcb'")
        warnings.warn(
            f"graph partition produced an empty part (n_parts={n_parts}); "
            "falling back to RCB")
        return rcb_partition(model.sctrs, n_parts)
    return part


def make_elem_part(model: ModelData, n_parts: int, method: str = "rcb",
                   seed: int = 0) -> np.ndarray:
    """Element->part map by method: 'rcb' (coordinate bisection), 'graph'
    (native dual-graph, raises if the native lib is missing), or 'auto'
    (graph when the native lib is present, else RCB)."""
    BUILD_CALLS["make_elem_part"] += 1
    if n_parts <= 1:
        return np.zeros(model.n_elem, dtype=np.int32)
    if method == "rcb":
        return rcb_partition(model.sctrs, n_parts)
    if method == "graph":
        return graph_partition(model, n_parts, seed=seed, strict=True)
    if method == "auto":
        if native.available():
            return graph_partition(model, n_parts, seed=seed, strict=False)
        return rcb_partition(model.sctrs, n_parts)
    raise ValueError(f"unknown partition method {method!r}")

def rcb_partition(centroids: np.ndarray, n_parts: int) -> np.ndarray:
    """Recursive coordinate bisection on element centroids.

    Supports any n_parts >= 1 (splits proportionally when odd).  Produces
    contiguous, balanced spatial blocks — the same surface-minimizing goal the
    reference gets from METIS dual-graph partitioning (run_metis.py:84-88).
    """
    n = len(centroids)
    part = np.zeros(n, dtype=np.int32)

    def split(idx: np.ndarray, p0: int, np_: int):
        if np_ == 1:
            part[idx] = p0
            return
        n_left = np_ // 2
        frac = n_left / np_
        c = centroids[idx]
        axis = int(np.argmax(c.max(axis=0) - c.min(axis=0)))
        order = np.argsort(c[:, axis], kind="stable")
        k = int(round(len(idx) * frac))
        split(idx[order[:k]], p0, n_left)
        split(idx[order[k:]], p0 + n_left, np_ - n_left)

    split(np.arange(n), 0, n_parts)
    return part


# ----------------------------------------------------------------------
# Partitioned model container
# ----------------------------------------------------------------------

@dataclasses.dataclass
class TypeBlock:
    """One pattern-type group, padded across parts.

    The matvec for this block is (reference pcg_solver.py:271-280):
        u  = x[dof]            gather (P, d, N)
        u  = where(sign, -u, u)
        v  = Ke @ (ck * u)     one MXU matmul per part
        v  = where(sign, -v, v)
    Padded element slots have ck == 0 and dof == n_loc (out-of-bounds, so
    gathers fill 0 and scatters drop).
    """

    type_id: int
    d: int                 # dofs per element
    n_nodes: int
    Ke: np.ndarray         # (d, d) unit stiffness
    diag_Ke: np.ndarray    # (d,)
    Se: Optional[np.ndarray]  # (6, d) strain mode, if available
    Me: Optional[np.ndarray]
    dof: np.ndarray        # (P, d, N) int32 local dof ids
    sign: np.ndarray       # (P, d, N) bool
    node: np.ndarray       # (P, n_nodes, N) int32 local node ids
    ck: np.ndarray         # (P, N) stiffness scale, 0 for padding
    ce: np.ndarray         # (P, N) strain scale, 0 for padding
    e_mod: np.ndarray      # (P, N) elastic modulus (for stress export)
    valid: np.ndarray      # (P, N) bool
    n_elem: np.ndarray     # (P,) true element counts


@dataclasses.dataclass
class PartitionedModel:
    """Everything the SPMD solver needs, as (P, ...) padded numpy arrays."""

    n_parts: int
    n_loc: int                   # padded local dof count
    n_node_loc: int              # padded local node count
    n_iface: int                 # global interface dof count
    n_node_iface: int            # global interface node count
    glob_n_dof: int
    glob_n_dof_eff: int
    glob_n_node: int

    type_blocks: List[TypeBlock]

    # Scatter maps (per part): flat element-dof values (concatenated over type
    # blocks in order, each ravel'd (d*N)) -> local dof vector.  ``perm``
    # pre-sorts values so segment_sum sees sorted indices.
    scat_perm: np.ndarray        # (P, NC) int32
    scat_ids: np.ndarray         # (P, NC) int32 sorted local dof ids (n_loc for padding)

    # Node-ELL scatter map (the TPU fast path): every local node receives
    # <= K element-node contributions, each a contiguous 3-vector.  ``ell``
    # indexes rows of the flattened (NC/3, 3) element-node value array
    # (slot = block_base + node_slot*N_blk + elem), NC/3 = out-of-range fill.
    # TPU gathers rows of 3 ~an order of magnitude faster than scalars, so
    # scatter-add becomes row-gather + row-sum.  None when the model is not
    # 3-dof-per-node (then the sorted segment_sum path is used).
    ell: Optional[np.ndarray]    # (P, n_node_loc, K) int32
    node_layout: bool            # dof_gid == 3*node_gid+c everywhere

    # Interface assembly maps (dof space)
    iface_local: np.ndarray      # (P, NI) int32 local dof id, n_loc padded
    iface_slot: np.ndarray       # (P, NI) int32 slot in global iface vector, n_iface padded

    # Interface assembly maps (node space, for nodal averaging exports)
    niface_local: np.ndarray     # (P, NNI) int32
    niface_slot: np.ndarray      # (P, NNI) int32

    # Per-part nodal vectors, padded to n_loc
    weight: np.ndarray           # (P, n_loc) owner weights (0/1), 0 on padding
    node_weight: np.ndarray      # (P, n_node_loc)
    eff: np.ndarray              # (P, n_loc) 1.0 on effective (free) dofs
    F: np.ndarray                # (P, n_loc) reference load
    Ud: np.ndarray               # (P, n_loc) prescribed displacement
    inv_diag_M: np.ndarray       # (P, n_loc) — for the dynamics (Newmark) path;
                                 # unused by the quasi-static solve

    # Global id maps (for export); -1 padding
    dof_gid: np.ndarray          # (P, n_loc) int64
    node_gid: np.ndarray         # (P, n_node_loc) int64
    ndof_p: np.ndarray           # (P,) true local dof counts
    nnode_p: np.ndarray          # (P,) true local node counts

    elem_part: np.ndarray        # (n_elem,) the element->part map used

    # Cohesive interface springs (model.interface_springs), padded per part:
    # local dof ids (n_loc padding) + stiffness (0 padding); None if the
    # model has no interface elements.
    spr_a: Optional[np.ndarray] = None   # (P, NS) int32
    spr_b: Optional[np.ndarray] = None   # (P, NS) int32
    spr_k: Optional[np.ndarray] = None   # (P, NS) float


def _pad_to(x: np.ndarray, n: int, fill) -> np.ndarray:
    out = np.full((n,) + x.shape[1:], fill, dtype=x.dtype)
    out[: len(x)] = x
    return out


def partition_model(
    model: ModelData,
    n_parts: int,
    elem_part: Optional[np.ndarray] = None,
    pad_multiple: int = 8,
    method: str = "rcb",
    block_filter: Optional[np.ndarray] = None,
) -> PartitionedModel:
    """Partition ``model`` into ``n_parts`` padded shards.

    ``block_filter`` (bool, n_elem): elements with False still belong to
    their part (their nodes/dofs are in the local sets, weights, and
    interface maps) but are EXCLUDED from the type blocks and scatter maps
    — the hybrid level-grid backend (parallel/hybrid.py) applies their
    stiffness through dense per-level stencils instead."""
    BUILD_CALLS["partition_model"] += 1
    if elem_part is None:
        elem_part = make_elem_part(model, n_parts, method=method)

    P = n_parts
    type_ids = sorted(model.elem_lib.keys())
    # Per-part element id lists
    part_elems = [np.where(elem_part == p)[0] for p in range(P)]

    # ---- interface springs: assigned to the part of their anchor element --
    spr_ga, spr_gb, spr_gk, spr_adj = model.interface_springs()
    have_springs = len(spr_ga) > 0
    spr_part = elem_part[spr_adj] if have_springs else None

    # ---- local dof/node renumbering per part ------------------------------
    dof_gids: List[np.ndarray] = []
    node_gids: List[np.ndarray] = []
    for p in range(P):
        e = part_elems[p]
        # All models here have constant dofs-per-elem within a type; gather
        # ragged CSR slices via offsets.
        dof_idx = _csr_take(model.elem_dofs_flat, model.elem_dofs_offset, e)
        node_idx = _csr_take(model.elem_nodes_flat, model.elem_nodes_offset, e)
        if have_springs:
            # both sides of a part's springs must be locally addressable;
            # any cross-part sharing this creates is resolved by the normal
            # interface-dof assembly (a dof in >= 2 parts is psum-combined)
            m = spr_part == p
            dof_idx = np.concatenate([dof_idx, spr_ga[m], spr_gb[m]])
        dof_gids.append(_unique(dof_idx))
        node_gids.append(_unique(node_idx))

    ndof_p = np.array([len(g) for g in dof_gids])
    nnode_p = np.array([len(g) for g in node_gids])
    n_node_loc = int(-(-int(nnode_p.max()) // pad_multiple) * pad_multiple)
    # Keep n_loc = 3*n_node_loc so the dof vector reshapes to (n_node, 3)
    # rows for the node-wise gather/scatter fast path.  The ELL path assumes
    # node-interleaved dofs at BOTH levels: per element
    # (elem_dofs[e][3a+c] == 3*elem_nodes[e][a]+c, which Ke4/sign_nc rely
    # on) and per part (dof_gid == 3*node_gid+c, which the x3 reshape
    # relies on — springs can break it by pulling in node-less dofs).
    node_layout = (
        len(model.elem_dofs_flat) == 3 * len(model.elem_nodes_flat)
        and np.array_equal(np.asarray(model.elem_dofs_offset),
                           3 * np.asarray(model.elem_nodes_offset))
        and np.array_equal(
            np.asarray(model.elem_dofs_flat),
            (3 * np.asarray(model.elem_nodes_flat)[:, None]
             + np.arange(3)).ravel())
        and all(
            len(dg) == 3 * len(ng)
            and np.array_equal(dg, (3 * ng[:, None] + np.arange(3)).ravel())
            for dg, ng in zip(dof_gids, node_gids))
    )
    if node_layout:
        n_loc = 3 * n_node_loc
    else:
        n_loc = int(-(-int(ndof_p.max()) // pad_multiple) * pad_multiple)

    # ---- interface dofs/nodes (shared by >= 2 parts) ----------------------
    iface_gid, iface_owner = _shared_ids(dof_gids, model.n_dof)
    niface_gid, niface_owner = _shared_ids(node_gids, model.n_node)
    n_iface = len(iface_gid)
    n_node_iface = len(niface_gid)

    # ---- per-part padded nodal arrays -------------------------------------
    weight = np.zeros((P, n_loc))
    node_weight = np.zeros((P, n_node_loc))
    eff = np.zeros((P, n_loc))
    F = np.zeros((P, n_loc))
    Ud = np.zeros((P, n_loc))
    inv_diag_M = np.zeros((P, n_loc))
    dof_gid_arr = np.full((P, n_loc), -1, dtype=np.int64)
    node_gid_arr = np.full((P, n_node_loc), -1, dtype=np.int64)

    iface_local_l, iface_slot_l = [], []
    niface_local_l, niface_slot_l = [], []

    eff_mask_glob = np.zeros(model.n_dof, dtype=bool)
    eff_mask_glob[model.dof_eff] = True

    for p in range(P):
        g = dof_gids[p]
        n = len(g)
        dof_gid_arr[p, :n] = g
        node_gid_arr[p, : nnode_p[p]] = node_gids[p]
        F[p, :n] = model.F[g]
        Ud[p, :n] = model.Ud[g]
        with np.errstate(divide="ignore"):
            inv_diag_M[p, :n] = np.where(model.diag_M[g] > 0, 1.0 / model.diag_M[g], 0.0)
        eff[p, :n] = eff_mask_glob[g].astype(float)

        # weights: 1 iff this part owns the dof (owner = lowest part id).
        w = np.ones(n)
        if n_iface > 0:
            pos = np.searchsorted(iface_gid, g)
            is_if = (pos < n_iface) & (iface_gid[np.minimum(pos, n_iface - 1)] == g)
            w[is_if] = (iface_owner[pos[is_if]] == p).astype(float)
        else:
            pos = np.zeros(n, dtype=np.int64)
            is_if = np.zeros(n, dtype=bool)
        weight[p, :n] = w

        nw = np.ones(nnode_p[p])
        gn = node_gids[p]
        if n_node_iface > 0:
            npos = np.searchsorted(niface_gid, gn)
            nis_if = (npos < n_node_iface) & (niface_gid[np.minimum(npos, n_node_iface - 1)] == gn)
            nw[nis_if] = (niface_owner[npos[nis_if]] == p).astype(float)
        else:
            npos = np.zeros(len(gn), dtype=np.int64)
            nis_if = np.zeros(len(gn), dtype=bool)
        node_weight[p, : nnode_p[p]] = nw

        # interface maps for this part
        iface_local_l.append(np.where(is_if)[0].astype(np.int32))
        iface_slot_l.append(pos[is_if].astype(np.int32))
        niface_local_l.append(np.where(nis_if)[0].astype(np.int32))
        niface_slot_l.append(npos[nis_if].astype(np.int32))

    NI = int(max((len(a) for a in iface_local_l), default=0))
    NNI = int(max((len(a) for a in niface_local_l), default=0))
    NI = max(NI, 1)
    NNI = max(NNI, 1)
    iface_local = np.stack([_pad_to(a, NI, n_loc) for a in iface_local_l])
    iface_slot = np.stack([_pad_to(a, NI, n_iface) for a in iface_slot_l])
    niface_local = np.stack([_pad_to(a, NNI, n_node_loc) for a in niface_local_l])
    niface_slot = np.stack([_pad_to(a, NNI, n_node_iface) for a in niface_slot_l])

    # ---- type blocks ------------------------------------------------------
    type_blocks: List[TypeBlock] = []
    E_by_mat = np.array([m["E"] for m in model.mat_prop])
    for t in type_ids:
        lib = model.elem_lib[t]
        d = lib["Ke"].shape[0]
        nn = lib["n_nodes"]
        per_part = []
        for p in range(P):
            e = part_elems[p][model.elem_type[part_elems[p]] == t]
            if block_filter is not None:
                e = e[block_filter[e]]
            per_part.append(e)
        N_t = int(max((len(e) for e in per_part), default=0))
        if N_t == 0:
            continue
        N_t = int(-(-N_t // pad_multiple) * pad_multiple)

        dof = np.full((P, d, N_t), n_loc, dtype=np.int32)
        sign = np.zeros((P, d, N_t), dtype=bool)
        node = np.full((P, nn, N_t), n_node_loc, dtype=np.int32)
        ck = np.zeros((P, N_t))
        ce = np.zeros((P, N_t))
        e_mod = np.zeros((P, N_t))
        valid = np.zeros((P, N_t), dtype=bool)
        n_elem_t = np.zeros(P, dtype=np.int64)

        for p in range(P):
            e = per_part[p]
            ne = len(e)
            n_elem_t[p] = ne
            if ne == 0:
                continue
            gd = _csr_take(model.elem_dofs_flat, model.elem_dofs_offset, e).reshape(ne, d)
            gs = _csr_take(model.elem_sign_flat, model.elem_dofs_offset, e).reshape(ne, d)
            gn_ = _csr_take(model.elem_nodes_flat, model.elem_nodes_offset, e).reshape(ne, nn)
            dof[p, :, :ne] = np.searchsorted(dof_gids[p], gd).T
            sign[p, :, :ne] = gs.T
            node[p, :, :ne] = np.searchsorted(node_gids[p], gn_).T
            ck[p, :ne] = model.ck[e]
            ce[p, :ne] = model.ce[e]
            e_mod[p, :ne] = E_by_mat[model.poly_mat[e]]
            valid[p, :ne] = True

        type_blocks.append(
            TypeBlock(
                type_id=t, d=d, n_nodes=nn,
                Ke=np.asarray(lib["Ke"], dtype=np.float64),
                diag_Ke=np.asarray(lib["diagKe"], dtype=np.float64),
                Se=np.asarray(lib["Se"], dtype=np.float64) if lib.get("Se") is not None else None,
                Me=np.asarray(lib.get("Me"), dtype=np.float64) if lib.get("Me") is not None else None,
                dof=dof, sign=sign, node=node, ck=ck, ce=ce, e_mod=e_mod,
                valid=valid, n_elem=n_elem_t,
            )
        )

    # ---- flat scatter maps (concatenated type blocks, pre-sorted) ---------
    NC = sum(tb.d * tb.dof.shape[2] for tb in type_blocks)
    scat_perm = np.zeros((P, NC), dtype=np.int32)
    scat_ids = np.zeros((P, NC), dtype=np.int32)
    for p in range(P if type_blocks else 0):
        flat = np.concatenate([tb.dof[p].ravel() for tb in type_blocks])
        nat = native.sort_i32(flat.astype(np.int32))
        if nat is not None:
            scat_perm[p], scat_ids[p] = nat
        else:
            perm = np.argsort(flat, kind="stable")
            scat_perm[p] = perm
            scat_ids[p] = flat[perm]

    # ---- node-ELL scatter map (TPU fast path) -----------------------------
    ell = None
    if node_layout and type_blocks:
        n_slots = sum(tb.n_nodes * tb.node.shape[2] for tb in type_blocks)
        per_part_ell = []
        seg_data = []
        K = 1
        for p in range(P):
            # slot id = block_base + node_slot*N_blk + elem  (ravel of (nn, N))
            ids_n = np.concatenate([tb.node[p].reshape(-1) for tb in type_blocks])
            valid = ids_n < n_node_loc        # padded slots point out of range
            slots = np.where(valid)[0].astype(np.int64)
            ids_v = ids_n[valid].astype(np.int64)
            order = np.argsort(ids_v, kind="stable")
            ids_s, slots_s = ids_v[order], slots[order]
            counts = np.bincount(ids_s, minlength=n_node_loc)
            K = max(K, int(counts.max()) if len(counts) else 0)
            seg_data.append((ids_s, slots_s, counts))
        for p in range(P):
            ids_s, slots_s, counts = seg_data[p]
            ell_p = np.full((n_node_loc, K), n_slots, dtype=np.int32)
            off = np.concatenate([[0], np.cumsum(counts)])
            rank = np.arange(len(ids_s)) - off[ids_s]
            ell_p[ids_s, rank] = slots_s
            per_part_ell.append(ell_p)
        ell = np.stack(per_part_ell)

    # ---- padded interface-spring arrays -----------------------------------
    spr_a = spr_b = spr_k = None
    if have_springs:
        per_part = [np.where(spr_part == p)[0] for p in range(P)]
        NS = int(max((len(s) for s in per_part), default=0))
        NS = max(int(-(-NS // pad_multiple) * pad_multiple), 1)
        spr_a = np.full((P, NS), n_loc, dtype=np.int32)
        spr_b = np.full((P, NS), n_loc, dtype=np.int32)
        spr_k = np.zeros((P, NS))
        for p in range(P):
            s = per_part[p]
            ns = len(s)
            if ns == 0:
                continue
            spr_a[p, :ns] = np.searchsorted(dof_gids[p], spr_ga[s])
            spr_b[p, :ns] = np.searchsorted(dof_gids[p], spr_gb[s])
            spr_k[p, :ns] = spr_gk[s]

    return PartitionedModel(
        n_parts=P,
        n_loc=n_loc,
        n_node_loc=n_node_loc,
        n_iface=n_iface,
        n_node_iface=n_node_iface,
        glob_n_dof=model.n_dof,
        glob_n_dof_eff=len(model.dof_eff),
        glob_n_node=model.n_node,
        type_blocks=type_blocks,
        scat_perm=scat_perm,
        scat_ids=scat_ids,
        ell=ell,
        node_layout=node_layout,
        iface_local=iface_local,
        iface_slot=iface_slot,
        niface_local=niface_local,
        niface_slot=niface_slot,
        weight=weight,
        node_weight=node_weight,
        eff=eff,
        F=F,
        Ud=Ud,
        inv_diag_M=inv_diag_M,
        dof_gid=dof_gid_arr,
        node_gid=node_gid_arr,
        ndof_p=ndof_p,
        nnode_p=nnode_p,
        elem_part=elem_part,
        spr_a=spr_a,
        spr_b=spr_b,
        spr_k=spr_k,
    )


def _unique(ids: np.ndarray) -> np.ndarray:
    """Sorted unique, using the native prep kernel when available
    (the np.unique half of config_ElemVectors, partition_mesh.py:272-286)."""
    nat = native.unique_renumber(ids, renumber=False)
    if nat is not None:
        return nat[0]
    return np.unique(ids)


def _csr_take(flat: np.ndarray, offset: np.ndarray, elems: np.ndarray) -> np.ndarray:
    """Concatenate flat[offset[e]:offset[e+1]] for e in elems (vectorized;
    native kernel when available — the loop the reference marked
    TODO-Cython, partition_mesh.py:244-255)."""
    if len(elems) == 0:
        return flat[:0]
    nat = native.csr_take(flat, offset, elems)
    if nat is not None:
        return nat
    starts = offset[elems]
    ends = offset[elems + 1]
    lens = ends - starts
    # Vectorized ragged-range: cumsum of a step vector walks each CSR slice.
    total = int(lens.sum())
    out_idx = np.ones(total, dtype=np.int64)
    cum = np.cumsum(lens)[:-1]
    out_idx[0] = starts[0]
    if len(elems) > 1:
        out_idx[cum] = starts[1:] - (starts[:-1] + lens[:-1]) + 1
    return flat[np.cumsum(out_idx)]


def _shared_ids(gid_lists: List[np.ndarray], n_glob: int):
    """Global ids present in >= 2 lists; returns (sorted ids, owner part)."""
    count = np.zeros(n_glob, dtype=np.int32)
    owner = np.full(n_glob, np.iinfo(np.int32).max, dtype=np.int32)
    for p, g in enumerate(gid_lists):
        count[g] += 1
        owner[g] = np.minimum(owner[g], p)
    shared = np.where(count >= 2)[0]
    return shared, owner[shared]
