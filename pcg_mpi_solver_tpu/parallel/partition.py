"""Host-side partitioner: global ModelData -> padded per-device shards.

Re-designs the reference's MPI partitioner (src/solver/partition_mesh.py, 1428
LoC of per-rank python loops + Isend/Recv neighbor discovery) as a single
vectorized numpy pass producing a ``PartitionedModel``: every per-partition
structure is a dense array with a leading parts axis ``P``, padded to common
shapes so the whole solve is one jitted SPMD program.

Key re-designs vs the reference:

- Element->part assignment: recursive coordinate bisection over element
  centroids by default (replaces METIS dual-graph partitioning,
  run_metis.py:88; a native graph partitioner can plug in via ``elem_part``).
- Local renumbering (config_ElemVectors, partition_mesh.py:208-297): done with
  np.unique/searchsorted over whole partitions at once — no per-element loops.
- Neighbor discovery + halo maps (identify_PotentialNeighbours /
  config_Neighbours, partition_mesh.py:674-921): replaced by an exact global
  computation — a dof is "interface" iff it lives in >=2 parts.  Each part
  gets scatter/gather maps into one global interface vector; at solve time
  partial sums are combined with a single ``lax.psum`` (no point-to-point
  messaging, bitwise deterministic).
- Duplicate-dof weighting (partition_mesh.py:867-887): owner = lowest part id
  containing the dof, weight 1 on owner / 0 elsewhere, so global dots count
  every dof exactly once.
"""

from __future__ import annotations

import dataclasses
import warnings
from typing import Dict, List, Optional, Tuple

import numpy as np

from pcg_mpi_solver_tpu.models.model_data import ModelData
from pcg_mpi_solver_tpu import native


# Host-side build-work call counters, bumped at the top of each builder
# (here and in parallel/structured.py, parallel/hybrid.py).  The cache/
# warm path's contract — "a warm cache hit performs ZERO partitioning
# work" — is asserted against these in tests/test_cache.py.  Monotonic;
# never reset by library code.
BUILD_CALLS = {
    "make_elem_part": 0,
    "partition_model": 0,
    "partition_structured": 0,
    "partition_hybrid": 0,
}


# ----------------------------------------------------------------------
# Element -> part assignment
# ----------------------------------------------------------------------

def graph_partition(model: ModelData, n_parts: int, ncommon: int = 1,
                    seed: int = 0, strict: bool = True) -> np.ndarray:
    """Dual-graph element partition via the native multilevel partitioner —
    the METIS-equivalent path (reference run_metis.py:84-88 calls
    ``metis.part_mesh_dual``).  With ``strict`` (the default) an unavailable
    native library raises; with ``strict=False`` it falls back to RCB."""
    part = native.part_mesh_dual(
        np.asarray(model.elem_nodes_offset, dtype=np.int64),
        np.asarray(model.elem_nodes_flat, dtype=np.int64),
        model.n_node, n_parts, ncommon=ncommon, seed=seed)
    if part is None:
        if strict:
            raise RuntimeError(
                "partition method 'graph' requires the native library "
                "(native/src/partition.cpp); build failed or g++ missing — "
                "use method='auto' or 'rcb' for the numpy fallback")
        return rcb_partition(model.sctrs, n_parts)
    if len(np.unique(part)) != n_parts:
        # The solver needs every part non-empty.
        if strict:
            raise RuntimeError(
                f"partition method 'graph' produced an empty part "
                f"(n_parts={n_parts}); the explicitly requested graph "
                "partition cannot be honored — use method='auto' or 'rcb'")
        warnings.warn(
            f"graph partition produced an empty part (n_parts={n_parts}); "
            "falling back to RCB")
        return rcb_partition(model.sctrs, n_parts)
    return part


def make_elem_part(model: ModelData, n_parts: int, method: str = "rcb",
                   seed: int = 0, n_slabs: int = 1) -> np.ndarray:
    """Element->part map by method: 'rcb' (coordinate bisection), 'graph'
    (native dual-graph, raises if the native lib is missing), 'auto'
    (graph when the native lib is present, else RCB), or 'slab2' (the
    two-level split for sharded setup — see :func:`two_level_partition`;
    ``n_slabs`` is the coarse slab count, 1 == plain RCB)."""
    BUILD_CALLS["make_elem_part"] += 1
    if n_parts <= 1:
        return np.zeros(model.n_elem, dtype=np.int32)
    if method == "rcb":
        return rcb_partition(model.sctrs, n_parts)
    if method == "slab2":
        return two_level_partition(model.sctrs, n_parts, n_slabs)
    if method == "graph":
        return graph_partition(model, n_parts, seed=seed, strict=True)
    if method == "auto":
        if native.available():
            return graph_partition(model, n_parts, seed=seed, strict=False)
        return rcb_partition(model.sctrs, n_parts)
    raise ValueError(f"unknown partition method {method!r}")


def coarse_slab_cut(centroids: np.ndarray, n_slabs: int) -> np.ndarray:
    """The CHEAP coarse cut of the two-level split: one stable argsort of
    ONE coordinate axis (the longest global extent), cut into ``n_slabs``
    balanced contiguous chunks.  Returns the (n_elem,) slab id map.
    Deterministic — every process of a sharded build computes the same
    cut from the same centroids (or each process computes only its own
    slab membership from the global axis order during slab ingest)."""
    n = len(centroids)
    slab = np.zeros(n, dtype=np.int32)
    if n_slabs <= 1:
        return slab
    axis = int(np.argmax(centroids.max(axis=0) - centroids.min(axis=0)))
    order = np.argsort(centroids[:, axis], kind="stable")
    bounds = [int(round(n * s / n_slabs)) for s in range(n_slabs + 1)]
    for s in range(n_slabs):
        slab[order[bounds[s]:bounds[s + 1]]] = s
    return slab


def two_level_partition(centroids: np.ndarray, n_parts: int,
                        n_slabs: int = 1, refine=None) -> np.ndarray:
    """Two-level METIS-style element partition (the sharded-setup path,
    ISSUE 14): a cheap coarse slab cut (:func:`coarse_slab_cut`) into
    ``n_slabs`` contiguous slabs along the dominant axis, then an
    INDEPENDENT per-slab RCB refinement into ``n_parts // n_slabs``
    parts each — so under a multi-process build each process only has to
    refine (and renumber, and block-build) its own slab.  ``n_slabs=1``
    degenerates to plain RCB.  Deterministic for fixed inputs; the slab
    count is a cache-key component (the resulting partition differs
    between slab counts).

    ``refine`` (iterable of slab ids, None = all): slabs NOT listed keep
    their coarse label ``slab_id * parts_per_slab`` instead of the RCB
    refinement — the sharded-build fast path refines only its own
    slab(s); unrefined labels are exact at slab granularity, so any
    consumer restricted to the refined slabs' parts sees the identical
    map the full refinement would give."""
    if n_parts % max(n_slabs, 1) != 0:
        raise ValueError(
            f"two_level_partition: n_parts={n_parts} must be divisible "
            f"by n_slabs={n_slabs}")
    n_slabs = max(n_slabs, 1)
    pps = n_parts // n_slabs
    slab = coarse_slab_cut(centroids, n_slabs)
    refine_set = set(range(n_slabs)) if refine is None else set(refine)
    part = np.zeros(len(centroids), dtype=np.int32)
    for s in range(n_slabs):
        idx = np.where(slab == s)[0]
        if s in refine_set:
            part[idx] = s * pps + rcb_partition(centroids[idx], pps)
        else:
            part[idx] = s * pps
    return part


def slab_local_parts(slab_centroids: np.ndarray, n_parts: int,
                     n_slabs: int, slab_idx: int):
    """Per-slab refinement half of the two-level split, for a process
    that holds ONLY its slab (models/mdf.read_mdf_slab): returns the
    slab-positional element->part map and this slab's ``part_range``.
    Identical assignment to :func:`two_level_partition` run on the full
    model (the slab's elements arrive in ascending global id order from
    ``slab_elem_ids``, matching ``np.where(slab == s)`` order)."""
    if n_parts % max(n_slabs, 1) != 0:
        raise ValueError(
            f"slab_local_parts: n_parts={n_parts} not divisible by "
            f"n_slabs={n_slabs}")
    pps = n_parts // max(n_slabs, 1)
    part = slab_idx * pps + rcb_partition(slab_centroids, pps)
    return part.astype(np.int32), (slab_idx * pps, (slab_idx + 1) * pps)

def rcb_partition(centroids: np.ndarray, n_parts: int) -> np.ndarray:
    """Recursive coordinate bisection on element centroids.

    Supports any n_parts >= 1 (splits proportionally when odd).  Produces
    contiguous, balanced spatial blocks — the same surface-minimizing goal the
    reference gets from METIS dual-graph partitioning (run_metis.py:84-88).
    """
    n = len(centroids)
    part = np.zeros(n, dtype=np.int32)

    def split(idx: np.ndarray, p0: int, np_: int):
        if np_ == 1:
            part[idx] = p0
            return
        n_left = np_ // 2
        frac = n_left / np_
        c = centroids[idx]
        axis = int(np.argmax(c.max(axis=0) - c.min(axis=0)))
        order = np.argsort(c[:, axis], kind="stable")
        k = int(round(len(idx) * frac))
        split(idx[order[:k]], p0, n_left)
        split(idx[order[k:]], p0 + n_left, np_ - n_left)

    split(np.arange(n), 0, n_parts)
    return part


# ----------------------------------------------------------------------
# Partitioned model container
# ----------------------------------------------------------------------

@dataclasses.dataclass
class TypeBlock:
    """One pattern-type group, padded across parts.

    The matvec for this block is (reference pcg_solver.py:271-280):
        u  = x[dof]            gather (P, d, N)
        u  = where(sign, -u, u)
        v  = Ke @ (ck * u)     one MXU matmul per part
        v  = where(sign, -v, v)
    Padded element slots have ck == 0 and dof == n_loc (out-of-bounds, so
    gathers fill 0 and scatters drop).
    """

    type_id: int
    d: int                 # dofs per element
    n_nodes: int
    Ke: np.ndarray         # (d, d) unit stiffness
    diag_Ke: np.ndarray    # (d,)
    Se: Optional[np.ndarray]  # (6, d) strain mode, if available
    Me: Optional[np.ndarray]
    dof: np.ndarray        # (P, d, N) int32 local dof ids
    sign: np.ndarray       # (P, d, N) bool
    node: np.ndarray       # (P, n_nodes, N) int32 local node ids
    ck: np.ndarray         # (P, N) stiffness scale, 0 for padding
    ce: np.ndarray         # (P, N) strain scale, 0 for padding
    e_mod: np.ndarray      # (P, N) elastic modulus (for stress export)
    valid: np.ndarray      # (P, N) bool
    n_elem: np.ndarray     # (P,) true element counts


@dataclasses.dataclass
class PartitionedModel:
    """Everything the SPMD solver needs, as (P, ...) padded numpy arrays."""

    n_parts: int
    n_loc: int                   # padded local dof count
    n_node_loc: int              # padded local node count
    n_iface: int                 # global interface dof count
    n_node_iface: int            # global interface node count
    glob_n_dof: int
    glob_n_dof_eff: int
    glob_n_node: int

    type_blocks: List[TypeBlock]

    # Scatter maps (per part): flat element-dof values (concatenated over type
    # blocks in order, each ravel'd (d*N)) -> local dof vector.  ``perm``
    # pre-sorts values so segment_sum sees sorted indices.
    scat_perm: np.ndarray        # (P, NC) int32
    scat_ids: np.ndarray         # (P, NC) int32 sorted local dof ids (n_loc for padding)

    # Node-ELL scatter map (the TPU fast path): every local node receives
    # <= K element-node contributions, each a contiguous 3-vector.  ``ell``
    # indexes rows of the flattened (NC/3, 3) element-node value array
    # (slot = block_base + node_slot*N_blk + elem), NC/3 = out-of-range fill.
    # TPU gathers rows of 3 ~an order of magnitude faster than scalars, so
    # scatter-add becomes row-gather + row-sum.  None when the model is not
    # 3-dof-per-node (then the sorted segment_sum path is used).
    ell: Optional[np.ndarray]    # (P, n_node_loc, K) int32
    node_layout: bool            # dof_gid == 3*node_gid+c everywhere

    # Interface assembly maps (dof space)
    iface_local: np.ndarray      # (P, NI) int32 local dof id, n_loc padded
    iface_slot: np.ndarray       # (P, NI) int32 slot in global iface vector, n_iface padded

    # Interface assembly maps (node space, for nodal averaging exports)
    niface_local: np.ndarray     # (P, NNI) int32
    niface_slot: np.ndarray      # (P, NNI) int32

    # Per-part nodal vectors, padded to n_loc
    weight: np.ndarray           # (P, n_loc) owner weights (0/1), 0 on padding
    node_weight: np.ndarray      # (P, n_node_loc)
    eff: np.ndarray              # (P, n_loc) 1.0 on effective (free) dofs
    F: np.ndarray                # (P, n_loc) reference load
    Ud: np.ndarray               # (P, n_loc) prescribed displacement
    inv_diag_M: np.ndarray       # (P, n_loc) — for the dynamics (Newmark) path;
                                 # unused by the quasi-static solve

    # Global id maps (for export); -1 padding
    dof_gid: np.ndarray          # (P, n_loc) int64
    node_gid: np.ndarray         # (P, n_node_loc) int64
    ndof_p: np.ndarray           # (P,) true local dof counts
    nnode_p: np.ndarray          # (P,) true local node counts

    elem_part: np.ndarray        # (n_elem,) the element->part map used

    # Cohesive interface springs (model.interface_springs), padded per part:
    # local dof ids (n_loc padding) + stiffness (0 padding); None if the
    # model has no interface elements.
    spr_a: Optional[np.ndarray] = None   # (P, NS) int32
    spr_b: Optional[np.ndarray] = None   # (P, NS) int32
    spr_k: Optional[np.ndarray] = None   # (P, NS) float

    # Sharded setup (ISSUE 14): the global layout glue this partition was
    # built against (cache/shards.py persists it as the glue entry), and
    # the part range whose rows are actually populated — (0, n_parts) for
    # a full monolithic build.
    layout: Optional["PartitionLayout"] = None
    part_range: Optional[Tuple[int, int]] = None


def _pad_to(x: np.ndarray, n: int, fill) -> np.ndarray:
    out = np.full((n,) + x.shape[1:], fill, dtype=x.dtype)
    out[: len(x)] = x
    return out


class SerialComm:
    """No-op reduction group: the single-process degenerate of the
    sharded-build exchange protocol (every reduction input already IS
    the global value).  The multi-process twin is
    ``parallel/distributed.HostComm`` (jax.distributed allgather)."""

    n_procs = 1

    def allreduce(self, arr: np.ndarray, op: str) -> np.ndarray:
        return np.asarray(arr)

    def allreduce_many(self, arrs, op: str):
        """Reduce several same-op arrays in ONE exchange round (the
        multi-process impl packs them into a single collective — each
        round-trip costs a dispatch, so the layout exchange batches its
        sums/mins into one call each)."""
        return [self.allreduce(a, op) for a in arrs]

    def allreduce_groups(self, groups):
        """Several (arrays, op) groups in ONE exchange round: an
        allreduce is an allgather + a local reduce, so differently-
        reduced groups can still share a single collective payload (the
        multi-process impl packs everything into one int32 buffer).
        ``groups``: list of ``(list_of_arrays, op)``; returns the
        reduced array lists in order."""
        return [self.allreduce_many(arrs, op) for arrs, op in groups]


@dataclasses.dataclass
class PartitionLayout:
    """Global layout 'glue' of a partition build: everything a per-part
    build phase needs beyond its own parts — padded local sizes, the
    interface (shared-dof) set + owners, per-type padding, spring/ELL
    pad widths.  Under a sharded build this is the ONLY globally
    assembled state (counts/owners exchanged via ``SetupComm``
    reductions); the heavy per-part structures never leave their
    process.  Also the content of the shard cache's 'glue' entry
    (cache/shards.py), so a warm shard load skips the exchange too."""

    n_parts: int
    n_loc: int
    n_node_loc: int
    node_layout: bool
    ndof_p: np.ndarray             # (P,) true local dof counts
    nnode_p: np.ndarray            # (P,)
    iface_gid: np.ndarray          # global dof ids present in >= 2 parts
    iface_owner: np.ndarray
    niface_gid: np.ndarray
    niface_owner: np.ndarray
    type_N: Dict[int, int]         # type id -> padded per-part width (0=skip)
    NS: int                        # padded spring width (0 = no springs)
    have_springs: bool
    NI: Optional[int] = None       # padded iface map width (resolved lazily)
    NNI: Optional[int] = None
    K: Optional[int] = None        # ELL width (resolved lazily)


def _node_layout_local(model, dof_gids: dict, node_gids: dict,
                       elems_ok: bool) -> bool:
    """The node-interleaved-dof condition evaluated on THIS process's
    parts (see the comment at the n_loc computation); AND-reduced across
    processes under a sharded build.  ``elems_ok`` is the per-element
    interleave check, evaluated on the local parts' CSR slices during
    the renumbering loop (the parts of all processes tile every element,
    so the AND-reduction covers the model without any process paying an
    O(total-connectivity) pass)."""
    return bool(
        elems_ok
        and len(model.elem_dofs_flat) == 3 * len(model.elem_nodes_flat)
        and np.array_equal(np.asarray(model.elem_dofs_offset),
                           3 * np.asarray(model.elem_nodes_offset))
        and all(
            len(dof_gids[p]) == 3 * len(node_gids[p])
            and np.array_equal(
                dof_gids[p],
                (3 * node_gids[p][:, None] + np.arange(3)).ravel())
            for p in dof_gids)
    )


def layout_exchange_sizes(n_dof: int, n_node: int, n_types: int,
                          n_parts: int):
    """The DETERMINISTIC 1-D payload sizes of the sharded-build exchange
    rounds — the packed counts+layout-flag round (``_compute_layout``)
    and the 3-wide pad-width round in ``partition_model`` — so
    ``HostComm.warmup`` can pre-pay their per-shape setup (program
    compile, channel warmup) OUTSIDE the timed partition span.  The
    third round (sparse shared-dof owners, ``_compute_layout``) has a
    data-dependent payload unknowable before the counts reduce; its
    power-of-two padding bounds it to a handful of program shapes whose
    one-time compile amortizes across builds.  Must stay in sync with
    the exchange call sites."""
    P, T = int(n_parts), int(n_types)
    return (3 * P + T * P + int(n_dof) + int(n_node) + 1, 3)


def _compute_layout(model, P: int, local, type_elems, dof_gids, node_gids,
                    type_ids, spr_part, n_springs: int,
                    pad_multiple: int, comm,
                    nl_elems_ok: bool = True) -> PartitionLayout:
    """Phase-A merge: per-part counts + shared-dof counts/owners from the
    local parts, reduced across the group into the global layout."""
    I32MAX = np.iinfo(np.int32).max
    ndof_p = np.zeros(P, dtype=np.int64)
    nnode_p = np.zeros(P, dtype=np.int64)
    dof_count = np.zeros(model.n_dof, dtype=np.int32)
    dof_owner = np.full(model.n_dof, I32MAX, dtype=np.int32)
    node_count = np.zeros(model.n_node, dtype=np.int32)
    node_owner = np.full(model.n_node, I32MAX, dtype=np.int32)
    type_counts = np.zeros((len(type_ids), P), dtype=np.int64)
    spring_counts = np.zeros(P, dtype=np.int64)
    for p in local:
        g, gn = dof_gids[p], node_gids[p]
        ndof_p[p] = len(g)
        nnode_p[p] = len(gn)
        dof_count[g] += 1
        dof_owner[g] = np.minimum(dof_owner[g], p)
        node_count[gn] += 1
        node_owner[gn] = np.minimum(node_owner[gn], p)
        for ti, t in enumerate(type_ids):
            type_counts[ti, p] = len(type_elems[p][t])
        if spr_part is not None:
            spring_counts[p] = int(np.count_nonzero(spr_part == p))
    nl_local = _node_layout_local(model, dof_gids, node_gids, nl_elems_ok)

    sums, mins = comm.allreduce_groups([
        ([ndof_p, nnode_p, dof_count, node_count, type_counts,
          spring_counts], "sum"),
        ([np.asarray([int(nl_local)], dtype=np.int64)], "min"),
    ])
    (ndof_p, nnode_p, dof_count, node_count, type_counts,
     spring_counts) = sums
    node_layout = bool(int(mins[0][0]))
    # springs need no exchange: every process of a sharded FULL-model
    # build derives the identical spring list from the identical model,
    # and slab-ingested views reject interface elements outright
    have_springs = n_springs > 0

    n_node_loc = int(-(-int(nnode_p.max()) // pad_multiple) * pad_multiple)
    # Keep n_loc = 3*n_node_loc so the dof vector reshapes to (n_node, 3)
    # rows for the node-wise gather/scatter fast path.  The ELL path assumes
    # node-interleaved dofs at BOTH levels: per element
    # (elem_dofs[e][3a+c] == 3*elem_nodes[e][a]+c, which Ke4/sign_nc rely
    # on) and per part (dof_gid == 3*node_gid+c, which the x3 reshape
    # relies on — springs can break it by pulling in node-less dofs).
    if node_layout:
        n_loc = 3 * n_node_loc
    else:
        n_loc = int(-(-int(ndof_p.max()) // pad_multiple) * pad_multiple)

    iface_gid = np.where(dof_count >= 2)[0]
    niface_gid = np.where(node_count >= 2)[0]
    # Owners only matter on the SHARED (interface) ids — exchange them
    # sparsely (surface-scale, not O(n_dof)): every process derives the
    # identical iface sets from the reduced counts, so the min-reduce of
    # the restricted owner slices lines up position-for-position.
    # Padded to a power-of-two length so the data-dependent payload
    # shape reuses a handful of compiled exchange programs.
    n_if, n_nif = len(iface_gid), len(niface_gid)
    pad = max(1 << (max(n_if + n_nif, 1) - 1).bit_length(), 16)
    own = np.full(pad, np.iinfo(np.int32).max, dtype=np.int32)
    own[:n_if] = dof_owner[iface_gid]
    own[n_if:n_if + n_nif] = node_owner[niface_gid]
    (own,), = comm.allreduce_groups([([own], "min")])
    iface_owner = own[:n_if].copy()
    niface_owner = own[n_if:n_if + n_nif].copy()
    type_N = {}
    for ti, t in enumerate(type_ids):
        N_t = int(type_counts[ti].max()) if P else 0
        type_N[t] = (int(-(-N_t // pad_multiple) * pad_multiple)
                     if N_t > 0 else 0)
    NS = 0
    if have_springs:
        NS = int(spring_counts.max())
        NS = max(int(-(-NS // pad_multiple) * pad_multiple), 1)
    return PartitionLayout(
        n_parts=P, n_loc=n_loc, n_node_loc=n_node_loc,
        node_layout=node_layout, ndof_p=ndof_p, nnode_p=nnode_p,
        iface_gid=iface_gid, iface_owner=iface_owner,
        niface_gid=niface_gid, niface_owner=niface_owner,
        type_N=type_N, NS=NS, have_springs=have_springs)


def partition_model(
    model: ModelData,
    n_parts: int,
    elem_part: Optional[np.ndarray] = None,
    pad_multiple: int = 8,
    method: str = "rcb",
    block_filter: Optional[np.ndarray] = None,
    part_range: Optional[Tuple[int, int]] = None,
    comm=None,
    layout: Optional[PartitionLayout] = None,
    slab2_slabs: int = 1,
) -> PartitionedModel:
    """Partition ``model`` into ``n_parts`` padded shards.

    ``block_filter`` (bool, n_elem): elements with False still belong to
    their part (their nodes/dofs are in the local sets, weights, and
    interface maps) but are EXCLUDED from the type blocks and scatter maps
    — the hybrid level-grid backend (parallel/hybrid.py) applies their
    stiffness through dense per-level stencils instead.

    Sharded setup (ISSUE 14): with ``part_range=(lo, hi)`` only the heavy
    per-part structures of parts [lo, hi) are built — rows outside the
    range stay at their padding values (weight 0, dof_gid -1, index maps
    at their out-of-range sentinels) — so an N-process ``jax.distributed``
    run builds its own slab of parts in 1/N the time.  The global layout
    (padded sizes, the shared-dof interface set + owners) is the ONLY
    globally assembled state, merged from per-process count/owner
    reductions through ``comm`` (``SerialComm`` when None — correct for a
    single process covering the whole range; pass
    ``parallel/distributed.HostComm`` under jax.distributed).  A
    precomputed ``layout`` (e.g. from the shard cache's glue entry, or a
    prior full build's ``pm.layout``) skips every exchange.  The full
    default build (``part_range=None``) is bit-identical to the
    historical monolithic output.

    ``model`` may be a slab-ingested view (models/mdf.read_mdf_slab):
    per-element arrays then cover only the slab's elements (``elem_part``
    must be slab-positional) while nodal lookups resolve through the
    slab's sparse vectors — global dof/node ids and counts are unchanged,
    so the interface reduction still operates on global ids."""
    BUILD_CALLS["partition_model"] += 1
    if elem_part is None:
        if getattr(model, "elem_ids", None) is not None:
            raise ValueError(
                "partition_model: a slab-ingested model view needs an "
                "explicit slab-positional elem_part (use "
                "slab_local_parts) — a fresh global partition cannot be "
                "derived from one slab")
        if (method == "slab2" and slab2_slabs > 1
                and part_range is not None
                and not getattr(model, "intfc_elems", None)):
            # sharded fast path: refine ONLY the slabs overlapping this
            # process's parts (unrefined slabs keep slab-granular
            # labels, never queried for out-of-range parts).  Spring
            # models are excluded: spring->part anchoring reads labels
            # of arbitrary slabs.
            pps = n_parts // slab2_slabs
            BUILD_CALLS["make_elem_part"] += 1
            elem_part = two_level_partition(
                model.sctrs, n_parts, slab2_slabs,
                refine=range(part_range[0] // pps,
                             -(-part_range[1] // pps)))
        else:
            elem_part = make_elem_part(model, n_parts, method=method,
                                       n_slabs=slab2_slabs)

    P = n_parts
    if part_range is None:
        part_range = (0, P)
    lo, hi = int(part_range[0]), int(part_range[1])
    if not (0 <= lo < hi <= P):
        raise ValueError(f"part_range {part_range} outside [0, {P})")
    local = range(lo, hi)
    comm = comm or SerialComm()
    type_ids = sorted(model.elem_lib.keys())
    # Per-part element id lists (LOCAL parts only — under a sharded build
    # the other parts' elements are never touched; ids are positional in
    # the model's element arrays, which for a slab model cover only the
    # slab)
    part_elems = {p: np.where(elem_part == p)[0] for p in local}

    # ---- interface springs: assigned to the part of their anchor element --
    spr_ga, spr_gb, spr_gk, spr_adj = model.interface_springs()
    spr_part = elem_part[spr_adj] if len(spr_ga) > 0 else None

    # ---- local dof/node renumbering per part ------------------------------
    dof_gids: Dict[int, np.ndarray] = {}
    node_gids: Dict[int, np.ndarray] = {}
    nl_elems_ok = True
    r3 = np.arange(3)
    for p in local:
        e = part_elems[p]
        # All models here have constant dofs-per-elem within a type; gather
        # ragged CSR slices via offsets.
        dof_idx = _csr_take(model.elem_dofs_flat, model.elem_dofs_offset, e)
        node_idx = _csr_take(model.elem_nodes_flat, model.elem_nodes_offset, e)
        if nl_elems_ok:
            # per-element node-interleave condition, checked on the
            # local CSR slices (every process's parts together tile all
            # elements — _node_layout_local)
            nl_elems_ok = (
                len(dof_idx) == 3 * len(node_idx)
                and np.array_equal(
                    dof_idx, (3 * node_idx[:, None] + r3).ravel()))
        if spr_part is not None:
            # both sides of a part's springs must be locally addressable;
            # any cross-part sharing this creates is resolved by the normal
            # interface-dof assembly (a dof in >= 2 parts is psum-combined)
            m = spr_part == p
            dof_idx = np.concatenate([dof_idx, spr_ga[m], spr_gb[m]])
        dof_gids[p] = _unique(dof_idx)
        node_gids[p] = _unique(node_idx)

    # per-(part, type) element lists, computed ONCE and shared by the
    # layout counts and the type-block build (the elem_type gather per
    # part is O(local elements) — doing it twice would double-pay on
    # the timed cold path)
    type_elems: Dict[int, Dict[int, np.ndarray]] = {}
    for p in local:
        et = model.elem_type[part_elems[p]]
        per_t = {}
        for t in type_ids:
            e = part_elems[p][et == t]
            if block_filter is not None:
                e = e[block_filter[e]]
            per_t[t] = e
        type_elems[p] = per_t

    if layout is None:
        layout = _compute_layout(
            model, P, local, type_elems, dof_gids, node_gids, type_ids,
            spr_part, len(spr_ga), pad_multiple, comm,
            nl_elems_ok=nl_elems_ok)
    n_loc, n_node_loc = layout.n_loc, layout.n_node_loc
    node_layout = layout.node_layout
    ndof_p, nnode_p = layout.ndof_p, layout.nnode_p
    have_springs = layout.have_springs

    iface_gid, iface_owner = layout.iface_gid, layout.iface_owner
    niface_gid, niface_owner = layout.niface_gid, layout.niface_owner
    n_iface = len(iface_gid)
    n_node_iface = len(niface_gid)

    # ---- per-part padded nodal arrays -------------------------------------
    weight = np.zeros((P, n_loc))
    node_weight = np.zeros((P, n_node_loc))
    eff = np.zeros((P, n_loc))
    F = np.zeros((P, n_loc))
    Ud = np.zeros((P, n_loc))
    inv_diag_M = np.zeros((P, n_loc))
    dof_gid_arr = np.full((P, n_loc), -1, dtype=np.int64)
    node_gid_arr = np.full((P, n_node_loc), -1, dtype=np.int64)

    iface_local_l, iface_slot_l = {}, {}
    niface_local_l, niface_slot_l = {}, {}

    eff_mask_glob = np.zeros(model.n_dof, dtype=bool)
    eff_mask_glob[np.asarray(model.dof_eff)] = True

    for p in local:
        g = dof_gids[p]
        n = len(g)
        dof_gid_arr[p, :n] = g
        node_gid_arr[p, : nnode_p[p]] = node_gids[p]
        F[p, :n] = model.F[g]
        Ud[p, :n] = model.Ud[g]
        with np.errstate(divide="ignore"):
            inv_diag_M[p, :n] = np.where(model.diag_M[g] > 0, 1.0 / model.diag_M[g], 0.0)
        eff[p, :n] = eff_mask_glob[g].astype(float)

        # weights: 1 iff this part owns the dof (owner = lowest part id).
        w = np.ones(n)
        if n_iface > 0:
            pos = np.searchsorted(iface_gid, g)
            is_if = (pos < n_iface) & (iface_gid[np.minimum(pos, n_iface - 1)] == g)
            w[is_if] = (iface_owner[pos[is_if]] == p).astype(float)
        else:
            pos = np.zeros(n, dtype=np.int64)
            is_if = np.zeros(n, dtype=bool)
        weight[p, :n] = w

        nw = np.ones(nnode_p[p])
        gn = node_gids[p]
        if n_node_iface > 0:
            npos = np.searchsorted(niface_gid, gn)
            nis_if = (npos < n_node_iface) & (niface_gid[np.minimum(npos, n_node_iface - 1)] == gn)
            nw[nis_if] = (niface_owner[npos[nis_if]] == p).astype(float)
        else:
            npos = np.zeros(len(gn), dtype=np.int64)
            nis_if = np.zeros(len(gn), dtype=bool)
        node_weight[p, : nnode_p[p]] = nw

        # interface maps for this part
        iface_local_l[p] = np.where(is_if)[0].astype(np.int32)
        iface_slot_l[p] = pos[is_if].astype(np.int32)
        niface_local_l[p] = np.where(nis_if)[0].astype(np.int32)
        niface_slot_l[p] = npos[nis_if].astype(np.int32)

    # (iface maps padded below — the NI/NNI/K pad widths resolve in ONE
    # exchange round after the ELL multiplicities are known)

    # ---- type blocks ------------------------------------------------------
    type_blocks: List[TypeBlock] = []
    E_by_mat = np.array([m["E"] for m in model.mat_prop])
    for t in type_ids:
        lib = model.elem_lib[t]
        d = lib["Ke"].shape[0]
        nn = lib["n_nodes"]
        per_part = {p: type_elems[p][t] for p in local}
        N_t = layout.type_N[t]
        if N_t == 0:
            continue

        dof = np.full((P, d, N_t), n_loc, dtype=np.int32)
        sign = np.zeros((P, d, N_t), dtype=bool)
        node = np.full((P, nn, N_t), n_node_loc, dtype=np.int32)
        ck = np.zeros((P, N_t))
        ce = np.zeros((P, N_t))
        e_mod = np.zeros((P, N_t))
        valid = np.zeros((P, N_t), dtype=bool)
        n_elem_t = np.zeros(P, dtype=np.int64)

        for p in local:
            e = per_part[p]
            ne = len(e)
            n_elem_t[p] = ne
            if ne == 0:
                continue
            gd = _csr_take(model.elem_dofs_flat, model.elem_dofs_offset, e).reshape(ne, d)
            gs = _csr_take(model.elem_sign_flat, model.elem_dofs_offset, e).reshape(ne, d)
            gn_ = _csr_take(model.elem_nodes_flat, model.elem_nodes_offset, e).reshape(ne, nn)
            dof[p, :, :ne] = np.searchsorted(dof_gids[p], gd).T
            sign[p, :, :ne] = gs.T
            node[p, :, :ne] = np.searchsorted(node_gids[p], gn_).T
            ck[p, :ne] = model.ck[e]
            ce[p, :ne] = model.ce[e]
            e_mod[p, :ne] = E_by_mat[model.poly_mat[e]]
            valid[p, :ne] = True

        type_blocks.append(
            TypeBlock(
                type_id=t, d=d, n_nodes=nn,
                Ke=np.asarray(lib["Ke"], dtype=np.float64),
                diag_Ke=np.asarray(lib["diagKe"], dtype=np.float64),
                Se=np.asarray(lib["Se"], dtype=np.float64) if lib.get("Se") is not None else None,
                Me=np.asarray(lib.get("Me"), dtype=np.float64) if lib.get("Me") is not None else None,
                dof=dof, sign=sign, node=node, ck=ck, ce=ce, e_mod=e_mod,
                valid=valid, n_elem=n_elem_t,
            )
        )

    # ---- flat scatter maps (concatenated type blocks, pre-sorted) ---------
    NC = sum(tb.d * tb.dof.shape[2] for tb in type_blocks)
    scat_perm = np.zeros((P, NC), dtype=np.int32)
    scat_ids = np.zeros((P, NC), dtype=np.int32)
    for p in (local if type_blocks else ()):
        flat = np.concatenate([tb.dof[p].ravel() for tb in type_blocks])
        nat = native.sort_i32(flat.astype(np.int32))
        if nat is not None:
            scat_perm[p], scat_ids[p] = nat
        else:
            perm = np.argsort(flat, kind="stable")
            scat_perm[p] = perm
            scat_ids[p] = flat[perm]

    # ---- node-ELL multiplicities (TPU fast path, fill deferred) -----------
    want_ell = node_layout and bool(type_blocks)
    seg_data = {}
    K_loc = 1
    if want_ell:
        n_slots = sum(tb.n_nodes * tb.node.shape[2] for tb in type_blocks)
        for p in local:
            # slot id = block_base + node_slot*N_blk + elem  (ravel of (nn, N))
            ids_n = np.concatenate([tb.node[p].reshape(-1) for tb in type_blocks])
            valid = ids_n < n_node_loc        # padded slots point out of range
            slots = np.where(valid)[0].astype(np.int64)
            ids_v = ids_n[valid].astype(np.int64)
            order = np.argsort(ids_v, kind="stable")
            ids_s, slots_s = ids_v[order], slots[order]
            counts = np.bincount(ids_s, minlength=n_node_loc)
            K_loc = max(K_loc, int(counts.max()) if len(counts) else 0)
            seg_data[p] = (ids_s, slots_s, counts)

    # ---- the ONE pad-width exchange round (NI/NNI/K) ----------------------
    if layout.NI is None or (want_ell and layout.K is None):
        (dims,), = comm.allreduce_groups([([np.asarray(
            [max((len(a) for a in iface_local_l.values()), default=0),
             max((len(a) for a in niface_local_l.values()), default=0),
             K_loc], dtype=np.int64)], "max")])
        layout.NI = max(int(dims[0]), 1)
        layout.NNI = max(int(dims[1]), 1)
        layout.K = int(dims[2])
    NI, NNI = int(layout.NI), int(layout.NNI)
    iface_local = np.stack(
        [_pad_to(iface_local_l.get(p, np.zeros(0, np.int32)), NI,
                 n_loc) for p in range(P)])
    iface_slot = np.stack(
        [_pad_to(iface_slot_l.get(p, np.zeros(0, np.int32)), NI,
                 n_iface) for p in range(P)])
    niface_local = np.stack(
        [_pad_to(niface_local_l.get(p, np.zeros(0, np.int32)), NNI,
                 n_node_loc) for p in range(P)])
    niface_slot = np.stack(
        [_pad_to(niface_slot_l.get(p, np.zeros(0, np.int32)), NNI,
                 n_node_iface) for p in range(P)])

    # ---- node-ELL scatter map fill ----------------------------------------
    ell = None
    if want_ell:
        K = int(layout.K)
        ell = np.full((P, n_node_loc, K), n_slots, dtype=np.int32)
        for p in local:
            ids_s, slots_s, counts = seg_data[p]
            off = np.concatenate([[0], np.cumsum(counts)])
            rank = np.arange(len(ids_s)) - off[ids_s]
            ell[p][ids_s, rank] = slots_s

    # ---- padded interface-spring arrays -----------------------------------
    spr_a = spr_b = spr_k = None
    if have_springs:
        NS = layout.NS
        spr_a = np.full((P, NS), n_loc, dtype=np.int32)
        spr_b = np.full((P, NS), n_loc, dtype=np.int32)
        spr_k = np.zeros((P, NS))
        for p in local:
            s = np.where(spr_part == p)[0]
            ns = len(s)
            if ns == 0:
                continue
            spr_a[p, :ns] = np.searchsorted(dof_gids[p], spr_ga[s])
            spr_b[p, :ns] = np.searchsorted(dof_gids[p], spr_gb[s])
            spr_k[p, :ns] = spr_gk[s]

    return PartitionedModel(
        n_parts=P,
        n_loc=n_loc,
        n_node_loc=n_node_loc,
        n_iface=n_iface,
        n_node_iface=n_node_iface,
        glob_n_dof=model.n_dof,
        glob_n_dof_eff=len(model.dof_eff),
        glob_n_node=model.n_node,
        type_blocks=type_blocks,
        scat_perm=scat_perm,
        scat_ids=scat_ids,
        ell=ell,
        node_layout=node_layout,
        iface_local=iface_local,
        iface_slot=iface_slot,
        niface_local=niface_local,
        niface_slot=niface_slot,
        weight=weight,
        node_weight=node_weight,
        eff=eff,
        F=F,
        Ud=Ud,
        inv_diag_M=inv_diag_M,
        dof_gid=dof_gid_arr,
        node_gid=node_gid_arr,
        ndof_p=ndof_p,
        nnode_p=nnode_p,
        elem_part=elem_part,
        spr_a=spr_a,
        spr_b=spr_b,
        spr_k=spr_k,
        layout=layout,
        part_range=(lo, hi),
    )


def _unique(ids: np.ndarray) -> np.ndarray:
    """Sorted unique, using the native prep kernel when available
    (the np.unique half of config_ElemVectors, partition_mesh.py:272-286)."""
    nat = native.unique_renumber(ids, renumber=False)
    if nat is not None:
        return nat[0]
    return np.unique(ids)


def _csr_take(flat: np.ndarray, offset: np.ndarray, elems: np.ndarray) -> np.ndarray:
    """Concatenate flat[offset[e]:offset[e+1]] for e in elems (vectorized;
    native kernel when available — the loop the reference marked
    TODO-Cython, partition_mesh.py:244-255)."""
    if len(elems) == 0:
        return flat[:0]
    nat = native.csr_take(flat, offset, elems)
    if nat is not None:
        return nat
    starts = offset[elems]
    ends = offset[elems + 1]
    lens = ends - starts
    # Vectorized ragged-range: cumsum of a step vector walks each CSR slice.
    total = int(lens.sum())
    out_idx = np.ones(total, dtype=np.int64)
    cum = np.cumsum(lens)[:-1]
    out_idx[0] = starts[0]
    if len(elems) > 1:
        out_idx[cum] = starts[1:] - (starts[:-1] + lens[:-1]) + 1
    return flat[np.cumsum(out_idx)]


