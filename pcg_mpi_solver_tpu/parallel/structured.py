"""Structured-block fast path: gather/scatter-free matvec + ppermute halos.

TPU hardware has no vector gather/scatter — XLA lowers arbitrary indexed
reads/writes to near-serial code (measured ~28 ms/iter at 160k dofs vs
~0.4 ms for all dense work).  The TPU-native answer for the reference's
problem class: octree meshes are (collections of) structured blocks, and on a
structured block the element gather is EIGHT CONTIGUOUS SLICES of the
displacement grid and the scatter-add is eight shifted slice-adds — pure
dense memory traffic, with the per-cell ``ck`` heterogeneity kept as a cell
grid.  The element matmul stays the same (24x24) MXU einsum.

Domain decomposition: 1-D slabs along x, one slab per device.  Neighboring
slabs share one node plane; after the local matvec the two copies of a shared
plane hold partial sums which are combined by a single bidirectional
``lax.ppermute`` of boundary planes over the mesh axis — the direct analogue
of the reference's neighbor Isend/Recv halo exchange (pcg_solver.py:317-334)
riding ICI.

The vector/weight/eff/dot machinery and the whole PCG stack are shared with
the general unstructured path through the same ops protocol; only
matvec/diag/assembly differ.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from pcg_mpi_solver_tpu.models.element import HEX_CORNERS
from pcg_mpi_solver_tpu.models.model_data import ModelData
from pcg_mpi_solver_tpu.ops.matvec import Ops


@dataclasses.dataclass
class StructuredPartition:
    """Slab decomposition of a structured cube model (duck-compatible with
    the PartitionedModel fields the driver/export layer uses)."""

    n_parts: int
    n_loc: int                  # 3 * nxn_loc * nny * nnz
    n_iface: int                # unused (halo via ppermute); kept for protocol
    n_node_loc: int             # nxn_loc * nny * nnz
    glob_n_dof: int
    glob_n_dof_eff: int
    glob_n_node: int
    nxc: int                    # local cells along x (same for every part)
    ny: int
    nz: int

    ck: np.ndarray              # (P, nxc, ny, nz) cell stiffness scale
    ce: np.ndarray              # (P, nxc, ny, nz) cell strain scale (1/h)
    Ke: np.ndarray              # (24, 24)
    diag_Ke: np.ndarray         # (24,)
    Se: np.ndarray              # (6, 24)
    weight: np.ndarray          # (P, n_loc)
    node_weight: np.ndarray     # (P, n_node_loc)
    eff: np.ndarray             # (P, n_loc)
    F: np.ndarray               # (P, n_loc)
    Ud: np.ndarray              # (P, n_loc)
    dof_gid: np.ndarray         # (P, n_loc) int64
    node_gid: np.ndarray        # (P, n_node_loc) int64
    ndof_p: np.ndarray          # (P,)

    # Sharded setup (ISSUE 14): the slab range whose rows are populated;
    # (0, n_parts) for a full build.  The layout here is analytic (every
    # dimension derives from the grid), so there is no exchange — each
    # process just fills its own slab rows.
    part_range: Optional[tuple] = None


def partition_structured(model: ModelData, n_parts: int,
                         part_range=None) -> StructuredPartition:
    """Slab-partition a structured cube model (requires model.grid set and
    nx % n_parts == 0).  With ``part_range=(lo, hi)`` only those slabs'
    model-sized gathers (F/Ud/eff/ck/ce/gid maps) are materialized — the
    sharded-setup fast path; rows outside the range stay zero/-1."""
    from pcg_mpi_solver_tpu.parallel.partition import BUILD_CALLS

    BUILD_CALLS["partition_structured"] += 1
    if model.grid is None:
        raise ValueError("model has no structured-grid metadata")
    nx, ny, nz, _h = model.grid
    if nx % n_parts != 0:
        raise ValueError(f"nx={nx} not divisible by n_parts={n_parts}")
    if len(model.elem_lib) != 1 or 0 not in model.elem_lib:
        raise ValueError("structured path expects the single-type cube library")

    P = n_parts
    if part_range is None:
        part_range = (0, P)
    lo, hi = int(part_range[0]), int(part_range[1])
    if not (0 <= lo < hi <= P):
        raise ValueError(f"part_range {part_range} outside [0, {P})")
    local = range(lo, hi)
    nxc = nx // P
    nxn = nxc + 1
    nny, nnz = ny + 1, nz + 1
    n_loc = 3 * nxn * nny * nnz

    lib = model.elem_lib[0]

    # cell ck grid: global element id = ex + nx*(ey + ny*ez)  (x fastest)
    ck_glob = np.asarray(model.ck).reshape(nz, ny, nx).transpose(2, 1, 0)  # (nx,ny,nz)
    ck = np.zeros((P, nxc, ny, nz))
    ce = np.zeros((P, nxc, ny, nz))
    ce_glob = np.asarray(model.ce).reshape(nz, ny, nx).transpose(2, 1, 0)
    for p in local:
        ck[p] = ck_glob[p * nxc:(p + 1) * nxc]
        ce[p] = ce_glob[p * nxc:(p + 1) * nxc]

    # local node (ix,iy,iz) [x-major local layout] -> global dof ids
    nnx = nx + 1
    weight = np.zeros((P, n_loc))
    eff = np.zeros((P, n_loc))
    F = np.zeros((P, n_loc))
    Ud = np.zeros((P, n_loc))
    # -1 init so non-built rows of a sharded build read as padding for
    # the owner masks (a full build overwrites every row — bit-identical)
    dof_gid = np.full((P, n_loc), -1, dtype=np.int64)

    eff_mask_glob = np.zeros(model.n_dof, dtype=bool)
    eff_mask_glob[model.dof_eff] = True

    n_node_loc = nxn * nny * nnz
    node_gid = np.full((P, n_node_loc), -1, dtype=np.int64)
    ix = np.arange(nxn)
    iy = np.arange(nny)
    iz = np.arange(nnz)
    IX, IY, IZ = np.meshgrid(ix, iy, iz, indexing="ij")
    for p in local:
        gnode = (IX + p * nxc) + nnx * (IY + nny * IZ)          # (nxn,nny,nnz)
        node_gid[p] = gnode.reshape(-1)
        gdof = (3 * gnode[..., None] + np.arange(3)).transpose(3, 0, 1, 2)
        # local flat layout: (c, ix, iy, iz) row-major
        g = gdof.reshape(-1)
        dof_gid[p] = g
        F[p] = model.F[g]
        Ud[p] = model.Ud[g]
        eff[p] = eff_mask_glob[g].astype(float)
    # ownership: the lowest part containing a dof keeps weight 1 (same rule
    # as the unstructured path / reference partition_mesh.py:885-887) — a
    # shared plane belongs to the lower slab, so zero the lower plane of
    # every part except the first.
    weight = np.ones((P, 3, nxn, nny, nnz))
    weight[1:, :, 0] = 0.0
    weight = weight.reshape(P, n_loc)
    node_weight = np.ones((P, nxn, nny, nnz))
    node_weight[1:, 0] = 0.0
    node_weight = node_weight.reshape(P, n_node_loc)

    return StructuredPartition(
        n_parts=P,
        n_loc=n_loc,
        n_iface=0,
        n_node_loc=n_node_loc,
        glob_n_dof=model.n_dof,
        glob_n_dof_eff=len(model.dof_eff),
        glob_n_node=model.n_node,
        nxc=nxc, ny=ny, nz=nz,
        ck=ck,
        ce=ce,
        Ke=np.asarray(lib["Ke"], np.float64),
        diag_Ke=np.asarray(lib["diagKe"], np.float64),
        Se=np.asarray(lib["Se"], np.float64),
        weight=weight,
        node_weight=node_weight,
        eff=eff,
        F=F,
        Ud=Ud,
        dof_gid=dof_gid,
        node_gid=node_gid,
        ndof_p=np.full(P, n_loc),
        part_range=(lo, hi),
    )


def device_data_structured(sp: StructuredPartition, dtype=jnp.float64) -> dict:
    return {
        "blocks": [{
            "Ke": jnp.asarray(sp.Ke, dtype),
            "diag_Ke": jnp.asarray(sp.diag_Ke, dtype),
            "Se": jnp.asarray(sp.Se, dtype),
            "ck": jnp.asarray(sp.ck, dtype),
            "ce": jnp.asarray(sp.ce, dtype),
        }],
        "weight": jnp.asarray(sp.weight, dtype),
        "node_weight": jnp.asarray(sp.node_weight, dtype),
        "eff": jnp.asarray(sp.eff, dtype),
        "F": jnp.asarray(sp.F, dtype),
        "Ud": jnp.asarray(sp.Ud, dtype),
    }


# Corner offsets in the element-dof ordering of models/element.py
_CORNERS = HEX_CORNERS.astype(np.int64)  # (8, 3)


VALID_FORMS = ("gse", "gsplit", "corner")

# Declared collective cost of one _halo exchange on a sharded multi-part
# slab: one bidirectional plane swap = 2 ppermutes per matvec.  Part of
# StructuredOps.body_collective_budget — the contract the analysis/
# collective-budget rule proves against the traced PCG body jaxpr.
STENCIL_HALO_PPERMUTES = 2


def matvec_form() -> str:
    """The PCG_TPU_MATVEC_FORM knob, validated — the ONE place its
    name/default/valid values live (resolved once at stencil-ops
    construction; reported by bench.py and checkpoint fingerprints)."""
    import os

    form = os.environ.get("PCG_TPU_MATVEC_FORM", "gse")
    if form not in VALID_FORMS:
        raise ValueError(
            f"PCG_TPU_MATVEC_FORM must be one of {VALID_FORMS}, got {form!r}")
    return form


def corner_matvec_grid(Ke, ck, xg):
    """Fusion-friendly brick-grid matvec: no (24, cells) intermediates.

    y = sum_b pad_b(sum_a Ke[3b:3b+3, 3a:3a+3] @ (ck * x_a)) with each
    3x3 block unrolled to scalar-broadcast FMAs (static unroll — XLA
    fuses the whole thing into slice-read -> FMA -> pad-accumulate
    chains), landing on the node grid as zero-padded translates.  Shared
    by the structured slab backend (_gse corner form) and the hybrid
    level-grid stencil.

    Ke (24, 24); ck (P, cx, cy, cz); xg (P, 3, cx+1, cy+1, cz+1)."""
    cx, cy, cz = ck.shape[1], ck.shape[2], ck.shape[3]
    w = []
    for a in range(8):
        dx, dy, dz = _CORNERS[a]
        w.append(ck[:, None] * xg[:, :, dx:dx + cx, dy:dy + cy, dz:dz + cz])
    y = None
    for b in range(8):
        ex, ey, ez = _CORNERS[b]
        comps = []
        for d in range(3):
            acc = None
            for a in range(8):
                for c in range(3):
                    t = Ke[3 * b + d, 3 * a + c] * w[a][:, c]
                    acc = t if acc is None else acc + t
            comps.append(acc)
        vb = jnp.stack(comps, axis=1)                  # (P, 3, cells)
        term = jnp.pad(vb, ((0, 0), (0, 0), (ex, 1 - ex),
                            (ey, 1 - ey), (ez, 1 - ez)))
        y = term if y is None else y + term
    return y


def gsplit_matvec_grid(Ke, ck, xg, precision):
    """gse minus the gather CONCAT (PCG_TPU_MATVEC_FORM=gsplit):
    v = sum_a Ke[:, 3a:3a+3] @ (ck * x_a) accumulates eight
    (24,3)@(3,cells) einsums whose inputs are contiguous grid slices —
    the (24, cells) gathered array u never exists, saving one full HBM
    round-trip of it (~650 MB at 10M dofs) against gse.  Keeps gse's
    single (24, cells) product; the caller scatters it.  Shared by the
    structured slab backend and the hybrid level-grid stencil (like
    corner_matvec_grid).

    Ke (24, 24); ck (P, cx, cy, cz); xg (P, 3, cx+1, cy+1, cz+1);
    returns v (P, 24, cx, cy, cz) in 3*corner + comp dof order."""
    cx, cy, cz = ck.shape[1], ck.shape[2], ck.shape[3]
    v = None
    for a in range(8):
        dx, dy, dz = _CORNERS[a]
        xa = xg[:, :, dx:dx + cx, dy:dy + cy, dz:dz + cz]
        t = jnp.einsum("dc,pcxyz->pdxyz", Ke[:, 3 * a:3 * a + 3],
                       ck[:, None] * xa, precision=precision)
        v = t if v is None else v + t
    return v


@dataclasses.dataclass(frozen=True)
class StructuredOps(Ops):
    """Same operator protocol as Ops, slab-structured implementation."""

    nxc: int = 0
    ny: int = 0
    nz: int = 0
    n_parts: int = 1
    # cells above which f64 matvecs run x-slab-chunked (see _chunk_planes)
    chunk_threshold: int = 500_000
    # f32 matvecs through the fused Pallas plane-march kernel
    # (ops/pallas_matvec.py) instead of the XLA gather/einsum/scatter
    use_pallas: bool = False
    # run the kernel through the Pallas interpreter (CI on CPU exercises
    # the real solver->kernel dispatch; SolverConfig.pallas='interpret')
    pallas_interpret: bool = False
    # XLA stencil formulation, PINNED at construction (the checkpoint
    # fingerprint records it; an env flip after construction must not
    # silently change what a resume replays)
    form: str = "gse"

    def __post_init__(self):
        # explicit pins (incl. dataclasses.replace) must not bypass the
        # validation matvec_form() applies to the env path — a typo'd
        # form would silently run gse while being recorded as itself
        if self.form not in VALID_FORMS:
            raise ValueError(
                f"form must be one of {VALID_FORMS}, got {self.form!r}")

    @classmethod
    def from_partition(cls, sp: StructuredPartition, dot_dtype=jnp.float64,
                       axis_name=None, precision=jax.lax.Precision.HIGHEST,
                       use_pallas=False, form=None, pallas_interpret=False):
        return cls(n_loc=sp.n_loc, n_iface=0,
                   n_node_loc=sp.n_node_loc, n_node_iface=0,
                   dot_dtype=dot_dtype,
                   axis_name=axis_name, precision=precision,
                   nxc=sp.nxc, ny=sp.ny, nz=sp.nz, n_parts=sp.n_parts,
                   use_pallas=use_pallas, pallas_interpret=pallas_interpret,
                   form=form if form is not None else matvec_form())

    # -- grid helpers ---------------------------------------------------
    def _grid(self, x):
        Pl = x.shape[0]
        return x.reshape(Pl, 3, self.nxc + 1, self.ny + 1, self.nz + 1)

    def _gather_cells(self, xg):
        """(Pl,3,cx+1,cy+1,cz+1) -> (Pl,24,cx,cy,cz) via 8 contiguous
        slices (cell shape derived from the node grid, so x-slab chunks
        work through the same code)."""
        cx, cy, cz = xg.shape[2] - 1, xg.shape[3] - 1, xg.shape[4] - 1
        slots = []
        for a in range(8):
            dx, dy, dz = _CORNERS[a]
            s = xg[:, :, dx:dx + cx, dy:dy + cy, dz:dz + cz]
            slots.append(s)
        return jnp.concatenate(slots, axis=1)  # dof order: 3*corner + comp

    def _scatter_cells(self, v):
        """(Pl,24,cx,cy,cz) -> (Pl,3,cx+1,cy+1,cz+1) via a sum of 8
        zero-padded translates (one fused output pass; an .at[].add chain
        would serialize 8 read-modify-write sweeps of the node grid)."""
        terms = []
        for a in range(8):
            dx, dy, dz = _CORNERS[a]
            terms.append(jnp.pad(
                v[:, 3 * a:3 * a + 3],
                ((0, 0), (0, 0), (dx, 1 - dx), (dy, 1 - dy), (dz, 1 - dz))))
        y = terms[0]
        for t in terms[1:]:
            y = y + t
        return y

    def body_collective_budget(self, variant: str = "classic",
                               precond: str = "jacobi") -> dict:
        """Structured-slab collective contract of the PCG loop body: the
        scalar psums + deferred-check psum from the base table (no iface
        psum — n_iface is 0 by construction; boundary planes combine via
        _halo instead), plus the halo exchange's ``STENCIL_HALO_PPERMUTES``
        ppermutes per matvec.  Proven against the traced body jaxpr by the
        analysis/ collective-budget rule — a stencil change that adds
        shifts must update the declaration consciously.

        ``precond="mg"`` multiplies the halo count by the V-cycle's
        fine-level matvecs (1 body matvec + 2*mg_degree cycle matvecs,
        each = one halo exchange) and the base budget already carries
        the restriction psum (ops/matvec.precond_cycle_cost — one
        table for gauges, budget and proof)."""
        from pcg_mpi_solver_tpu.ops.matvec import precond_cycle_cost

        budget = dict(super().body_collective_budget(variant, precond))
        if self.n_parts > 1 and self.axis_name is not None:
            mv_extra, _ps = precond_cycle_cost(precond, self.mg_degree)
            budget["ppermute"] = STENCIL_HALO_PPERMUTES * (1 + mv_extra)
        return budget

    def _halo(self, yg):
        """Combine partial sums on shared slab-boundary planes: one
        bidirectional ppermute of (3,nny,nnz) planes over the mesh axis."""
        P = self.n_parts
        if P == 1:
            return yg
        if self.axis_name is None:
            # unsharded multi-part view (testing): roll over leading axis
            up = yg[:, :, -1]
            dn = yg[:, :, 0]
            from_left = jnp.roll(up, 1, axis=0).at[0].set(0.0)
            from_right = jnp.roll(dn, -1, axis=0).at[-1].set(0.0)
            yg = yg.at[:, :, 0].add(from_left)
            yg = yg.at[:, :, -1].add(from_right)
            return yg
        idx = jax.lax.axis_index(self.axis_name)
        up = yg[:, :, -1]
        dn = yg[:, :, 0]
        fwd = [(i, (i + 1) % P) for i in range(P)]
        bwd = [(i, (i - 1) % P) for i in range(P)]
        from_left = jax.lax.ppermute(up, self.axis_name, fwd)
        from_right = jax.lax.ppermute(dn, self.axis_name, bwd)
        from_left = jnp.where(idx == 0, 0.0, from_left)
        from_right = jnp.where(idx == P - 1, 0.0, from_right)
        yg = yg.at[:, :, 0].add(from_left)
        yg = yg.at[:, :, -1].add(from_right)
        return yg

    # -- operator protocol ---------------------------------------------
    def _chunk_planes(self, dtype) -> int:
        """x-slab chunk size for the sequential matvec, or 0 for one shot.

        f64 arithmetic on TPU is software-emulated (several f32 passes per
        op); unchunked at 10M dofs the f64 (24, cells) gather/product
        intermediates need multi-GB temp buffers.  f64 matvecs are rare
        (Dirichlet lifting + one true-residual per refinement cycle), so a
        fori_loop over x-slabs trades a little latency for bounded memory.
        The body is the same gather/einsum/scatter as the one-shot path —
        f64 conv lowerings proved pathological on real v5e (the remote
        compile never returned), while the f64 einsum path is routinely
        exercised; see bench history r01-r02."""
        cells = self.nxc * self.ny * self.nz
        if np.dtype(dtype) != np.float64 or cells < self.chunk_threshold:
            return 0
        target = max(1, int(self.chunk_threshold / max(self.ny * self.nz, 1)))
        # largest divisor of nxc that is <= target
        for c in range(min(target, self.nxc), 0, -1):
            if self.nxc % c == 0:
                return c if c < self.nxc else 0
        return 0

    def _gse(self, blk, xg, ck):
        """One slab matvec; two XLA formulations, env-selected.

        - ``gse`` (default): gather -> one (24,24)@(24,cells) MXU einsum
          -> scatter.  Materializes the gathered corner array and the
          product — two (24, cells) HBM round-trips (~650 MB each way at
          10M dofs).
        - ``corner`` (PCG_TPU_MATVEC_FORM=corner): per-output-corner
          accumulation y = sum_b pad_b(sum_a Ke[3b:3b+3, 3a:3a+3] @
          (ck * x_a)), with each 3x3 block unrolled to scalar
          multiply-adds so XLA fuses the whole thing into
          slice-read -> FMA -> pad-accumulate chains and NO (24, cells)
          intermediate ever exists.  Trades the single big MXU matmul
          (arithmetic intensity ~12 flops/byte — far below the MXU
          roofline anyway; the op is HBM-bound) for ~4x less HBM
          traffic.  The knob is resolved ONCE at ops construction
          (self.form) — toggling the env later does nothing.
        """
        if self.form == "corner":
            return self._gse_corner(blk, xg, ck)
        if self.form == "gsplit":
            return self._gse_split(blk, xg, ck)
        u = self._gather_cells(xg)                     # (P, 24, cells)
        v = jnp.einsum("de,pexyz->pdxyz", blk["Ke"], ck[:, None] * u,
                       precision=self.precision)
        return self._scatter_cells(v)

    def _gse_corner(self, blk, xg, ck):
        return corner_matvec_grid(blk["Ke"], ck, xg)

    def _gse_split(self, blk, xg, ck):
        return self._scatter_cells(
            gsplit_matvec_grid(blk["Ke"], ck, xg, self.precision))

    def matvec_local(self, data, x):
        if x.ndim == 3:
            # RHS-block axis (Ops.matvec contract): the stencil is built
            # around grid reshapes of one flat vector, so the block is
            # batched with vmap — XLA turns the slice/einsum/pad chain
            # into its batched twin; no per-column Python loop.
            return jax.vmap(lambda xc: self.matvec_local(data, xc),
                            in_axes=-1, out_axes=-1)(x)
        blk = data["blocks"][0]
        xg = self._grid(x)                             # (P, 3, nxn, nny, nnz)
        chunk = self._chunk_planes(x.dtype)
        if (self.use_pallas and chunk == 0
                and np.dtype(x.dtype) == np.float32):
            from pcg_mpi_solver_tpu.ops.pallas_matvec import (
                batched_structured_matvec)

            y = batched_structured_matvec(xg, blk["ck"], blk["Ke"],
                                          interpret=self.pallas_interpret)
            return y.reshape(x.shape)
        if chunk == 0:
            # slice-gather + einsum: contiguous slices, MXU matmul, shifted
            # slice-adds — no vector gather/scatter anywhere.
            return self._gse(blk, xg, blk["ck"]).reshape(x.shape)

        Pl = xg.shape[0]
        nxc, ny, nz = self.nxc, self.ny, self.nz
        n_chunks = nxc // chunk

        def body(i, y):
            a = i * chunk
            xs = jax.lax.dynamic_slice(
                xg, (0, 0, a, 0, 0), (Pl, 3, chunk + 1, ny + 1, nz + 1))
            cks = jax.lax.dynamic_slice(
                blk["ck"], (0, a, 0, 0), (Pl, chunk, ny, nz))
            ys = self._gse(blk, xs, cks)
            cur = jax.lax.dynamic_slice(y, (0, 0, a, 0, 0), ys.shape)
            return jax.lax.dynamic_update_slice(y, cur + ys, (0, 0, a, 0, 0))

        y = jax.lax.fori_loop(0, n_chunks, body, jnp.zeros_like(xg))
        return y.reshape(x.shape)

    def matvec(self, data, x):
        if x.ndim == 3:
            return jax.vmap(lambda xc: self.matvec(data, xc),
                            in_axes=-1, out_axes=-1)(x)
        yg = self._grid(self.matvec_local(data, x))
        return self._halo(yg).reshape(x.shape)

    def diag_local(self, data):
        blk = data["blocks"][0]
        Pl = blk["ck"].shape[0]
        v = blk["diag_Ke"][None, :, None, None, None] * blk["ck"][:, None]
        yg = self._scatter_cells(v)
        return yg.reshape(Pl, self.n_loc)

    def diag(self, data):
        yg = self._grid(self.diag_local(data))
        return self._halo(yg).reshape(-1, self.n_loc)

    # -- node-block (3x3) diagonal for block-Jacobi ---------------------
    def node_block_diag(self, data):
        """Per-node 3x3 blocks as 9 channels on the node grid: for corner
        ``a`` every cell adds ``ck * Ke[3a:3a+3, 3a:3a+3]`` to its corner
        node — the same 8 pad-translates as diag_local, 9-channel; slab-
        boundary planes assemble through the halo like any other field."""
        from pcg_mpi_solver_tpu.ops.precond import corner_block_field

        blk = data["blocks"][0]
        ck = blk["ck"]                                    # (P, cx, cy, cz)
        Pl = ck.shape[0]
        g = self._halo(corner_block_field(blk["Ke"], ck, _CORNERS))
        return g.reshape(Pl, 9, self.n_node_loc) \
            .transpose(0, 2, 1).reshape(Pl, self.n_node_loc, 3, 3)

    def _as_node3(self, v):
        # structured dof layout is component-major: (P, 3, nodes[, R])
        if v.ndim == 3:
            return v.reshape(v.shape[0], 3, self.n_node_loc,
                             v.shape[2]).transpose(0, 2, 1, 3)
        return v.reshape(v.shape[0], 3, self.n_node_loc).transpose(0, 2, 1)

    def _from_node3(self, z3):
        if z3.ndim == 4:
            return z3.transpose(0, 2, 1, 3).reshape(
                z3.shape[0], self.n_loc, z3.shape[3])
        return z3.transpose(0, 2, 1).reshape(z3.shape[0], self.n_loc)

    def iface_assemble(self, data, y):
        if y.ndim == 3:
            return jax.vmap(lambda yc: self.iface_assemble(data, yc),
                            in_axes=-1, out_axes=-1)(y)
        return self._halo(self._grid(y)).reshape(y.shape)

    # -- export path ----------------------------------------------------
    def _node_grid(self, y):
        Pl = y.shape[0]
        return y.reshape(Pl, -1, self.nxc + 1, self.ny + 1, self.nz + 1)

    def elem_strain(self, data, x):
        blk = data["blocks"][0]
        u = self._gather_cells(self._grid(x))                  # (P,24,cx,cy,cz)
        eps = jnp.einsum("sd,pdxyz->psxyz", blk["Se"],
                         blk["ce"][:, None] * u, precision=self.precision)
        Pl = eps.shape[0]
        return [eps.reshape(Pl, 6, -1)]

    def elem_scale(self, data):
        blk = data["blocks"][0]
        Pl = blk["ck"].shape[0]
        return [(blk["ck"] * blk["ce"]).reshape(Pl, -1)]

    def nodal_average(self, data, vals_list):
        """Cell values -> averaged nodal grid via 8 shifted slice-adds of
        sums and counts, halo'd as extra channels."""
        vals = vals_list[0]
        Pl, k = vals.shape[0], vals.shape[1]
        nxc, ny, nz = self.nxc, self.ny, self.nz
        vg = vals.reshape(Pl, k, nxc, ny, nz)
        cg = jnp.ones((Pl, 1, nxc, ny, nz), vals.dtype)
        both = jnp.concatenate([vg, cg], axis=1)               # (P, k+1, cells)
        y = None
        for a in range(8):
            dx, dy, dz = _CORNERS[a]
            t = jnp.pad(both, ((0, 0), (0, 0), (dx, 1 - dx),
                               (dy, 1 - dy), (dz, 1 - dz)))
            y = t if y is None else y + t
        y = self._halo(y)
        avg = y[:, :k] / (y[:, k:] + 1e-15)
        return avg.reshape(Pl, k, -1)
