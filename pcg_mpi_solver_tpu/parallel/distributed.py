"""Multi-host (DCN) support.

The reference scales across nodes with mpi4py over OpenMPI — tagged p2p
halo messages plus allreduces (reference: SURVEY.md §2d; pcg_solver.py:
317-334, 622-628).  The TPU-native equivalent has no user-level messaging:
``jax.distributed`` forms one multi-controller program, the device mesh
spans all hosts (ICI within a slice, DCN across), and the SAME compiled
solve program runs everywhere — XLA routes the psum/collectives.

What this module provides:

- :func:`init_distributed` — process bootstrap (coordinator discovery from
  standard env vars, explicit args, or single-process no-op).
- :func:`make_global_mesh` — 1-D parts mesh over every device of every host.
- :func:`put_sharded` / :func:`put_tree` — build sharded global device
  arrays from host numpy data; on multi-host each process materializes only
  its addressable shards (the analogue of the reference's per-rank partition
  pickles + shared-memory staging, file_operations.py:306-339).

Single-process semantics are identical to plain ``device_put``, so every
code path here is exercised by the single-host test suite; multi-host adds
only the bootstrap.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np

from pcg_mpi_solver_tpu.parallel.mesh import PARTS_AXIS


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> int:
    """Initialize jax.distributed for a multi-host run; returns process id.

    Resolution order: explicit args > env vars (``PCG_TPU_COORDINATOR`` /
    ``PCG_TPU_NUM_PROCS`` / ``PCG_TPU_PROC_ID``, mirroring the standard JAX
    ones) > single-process no-op.  Safe to call repeatedly.
    """
    coordinator_address = coordinator_address or os.environ.get("PCG_TPU_COORDINATOR")
    if num_processes is None and os.environ.get("PCG_TPU_NUM_PROCS"):
        num_processes = int(os.environ["PCG_TPU_NUM_PROCS"])
    if process_id is None and os.environ.get("PCG_TPU_PROC_ID"):
        process_id = int(os.environ["PCG_TPU_PROC_ID"])

    if coordinator_address is None and num_processes is None:
        return jax.process_index()          # single process / TPU pod auto-init
    global _initialized
    if not _initialized:
        # NOTE: must run before anything touches the XLA backend — do not
        # query jax.process_count() here.
        if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
            # Multi-process CPU groups (the weak-scaling setup ladder,
            # the 2/4-process tests) need a cross-process collectives
            # implementation — the default CPU client rejects
            # multiprocess computations outright ("Multiprocess
            # computations aren't implemented on the CPU backend").
            # Gloo ships with jaxlib; best-effort for jax versions
            # without the knob.
            try:
                jax.config.update("jax_cpu_collectives_implementation",
                                  "gloo")
            except Exception:               # noqa: BLE001
                pass
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        _initialized = True
    return jax.process_index()


_initialized = False


def make_global_mesh(n_devices: Optional[int] = None) -> jax.sharding.Mesh:
    """1-D ``(parts,)`` mesh over all devices of all processes (DCN-aware:
    jax.devices() enumerates host-local devices first, so contiguous part
    blocks land host-local and halo traffic prefers ICI)."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return jax.sharding.Mesh(np.asarray(devices), (PARTS_AXIS,))


def put_sharded(x: np.ndarray, mesh: jax.sharding.Mesh,
                spec: jax.sharding.PartitionSpec) -> jax.Array:
    """Host numpy -> sharded global device array.

    Single-process: plain device_put.  Multi-process: each process builds
    only its addressable shards via make_array_from_callback (every process
    must hold the rows its devices own; the part-major layout makes that a
    contiguous block of the leading axis)."""
    sharding = jax.sharding.NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    return jax.make_array_from_callback(
        np.shape(x), sharding, lambda idx: np.asarray(x[idx]))


def gather_owned_global(pm, x, mesh: Optional[jax.sharding.Mesh] = None,
                        dtype=None) -> np.ndarray:
    """(P, n_loc[, R]) part-padded dof vector/block -> (glob_n_dof[, R])
    global array via the owner mask (each dof written by exactly one
    part).  The one shared mask-and-scatter idiom for every solver's
    global views — a trailing RHS-block axis rides through unchanged
    (one fetch, one masked scatter for the whole block)."""
    tail = tuple(np.shape(x))[2:]
    out = np.zeros((pm.glob_n_dof,) + tail, dtype=dtype or np.float64)
    m = (pm.weight > 0) & (pm.dof_gid >= 0)
    out[pm.dof_gid[m]] = fetch_global(x, mesh)[m]
    return out


def fetch_global(x, mesh: Optional[jax.sharding.Mesh] = None) -> np.ndarray:
    """Fetch a (possibly multi-host sharded) jax.Array as full host numpy.

    Single-process (or fully addressable) arrays are a plain device_get; a
    multi-host sharded array is first resharded to fully-replicated (an XLA
    all-gather over DCN) so every process can read the whole value — the
    analogue of the reference's Comm.gather-to-rank-0 exports
    (pcg_solver.py:910-911)."""
    if not isinstance(x, jax.Array) or x.is_fully_addressable:
        return np.asarray(x)
    if mesh is None:
        mesh = jax.sharding.Mesh(
            np.asarray(x.sharding.mesh.devices), x.sharding.mesh.axis_names)
    rep = jax.jit(lambda a: a,
                  out_shardings=jax.sharding.NamedSharding(
                      mesh, jax.sharding.PartitionSpec()))(x)
    return np.asarray(rep)


def fetch_addressable(x) -> tuple:
    """Fetch only this process's addressable rows of a parts-sharded array.

    Returns ``(rows, p0, p1)`` with ``rows == x[p0:p1]``.  The collective-free
    counterpart of :func:`fetch_global` — the basis of parallel result
    writes (each process persists its own contiguous part block, the
    analogue of the reference's MPI-IO shared-file writes at computed
    offsets, file_operations.py:348-396)."""
    if not isinstance(x, jax.Array) or x.is_fully_addressable:
        a = np.asarray(x)
        return a, 0, a.shape[0]
    shards = sorted(x.addressable_shards,
                    key=lambda s: s.index[0].start or 0)
    rows = np.concatenate([np.asarray(s.data) for s in shards], axis=0)
    p0 = shards[0].index[0].start or 0
    p1 = p0 + rows.shape[0]
    # The part-range labeling is only valid if this process's shards tile
    # [p0, p1) contiguously — true for make_global_mesh's device order,
    # not necessarily for an arbitrary (e.g. torus-reordered) mesh.
    ends = [s.index[0] for s in shards]
    cov = sorted((sl.start or 0, sl.stop) for sl in ends)
    pos = p0
    for a, b in cov:
        if a != pos:
            raise ValueError(
                f"addressable shards are not part-contiguous: {cov} "
                "(use make_global_mesh, or export via fetch_global)")
        pos = b
    return rows, p0, p1


class HostComm:
    """Host-side reduction group over the processes of a jax.distributed
    run — the multi-process implementation of the sharded-setup exchange
    protocol (``parallel/partition.SerialComm`` is the 1-process twin).
    Built on ``multihost_utils.process_allgather`` + a numpy reduce, so
    arbitrary host arrays (the partition layout's count/owner vectors)
    ride the existing collective fabric; every process must call
    ``allreduce`` in the same order with same-shaped arrays."""

    _OPS = {"sum": np.sum, "min": np.min, "max": np.max}

    def __init__(self):
        self.n_procs = jax.process_count()

    def allreduce(self, arr: np.ndarray, op: str) -> np.ndarray:
        arr = np.asarray(arr)
        if self.n_procs == 1:
            return arr
        from jax.experimental import multihost_utils

        gathered = multihost_utils.process_allgather(arr)
        return self._OPS[op](np.asarray(gathered), axis=0).astype(arr.dtype)

    def warmup(self, sizes=(1,)) -> None:
        """Pay the one-time collective-fabric costs (gloo/ICI channel
        setup, the per-shape allgather program compile) BEFORE any timed
        partition span — connection establishment and program compile
        are not partition work and must not pollute
        ``partition_build_s``.  ``sizes``: the exact 1-D payload sizes
        the exchange will use (``parallel/partition.
        layout_exchange_sizes``); every process must call with the same
        sequence (each warmup is itself a collective).  Routed through
        ``allreduce_groups`` so the warmed program matches the packed
        (int32) path the real exchange takes."""
        for n in sizes:
            self.allreduce_groups([([np.zeros(int(n), np.int64)], "max")])

    def allreduce_many(self, arrs, op: str):
        """Several same-op reductions in ONE collective (see
        ``allreduce_groups`` — this is the single-group case)."""
        return self.allreduce_groups([(arrs, op)])[0]

    def allreduce_groups(self, groups):
        """Differently-reduced array groups in ONE collective: an
        allreduce is an allgather + a local reduce, so every group
        shares a single packed buffer — one dispatch, one per-shape
        program, one gloo/DCN round for the whole layout exchange.
        ALWAYS packed as int32 (halves the wire payload; every
        layout-exchange value — counts, owners, per-part sizes — fits
        by design): the dtype choice must be identical on every process
        (a per-process int64 fallback would enter the collective with
        mismatched byte-widths), so an out-of-range value raises LOUDLY
        here instead."""
        from jax.experimental import multihost_utils

        groups = [([np.asarray(a) for a in arrs], op)
                  for arrs, op in groups]
        if self.n_procs == 1:
            return [arrs for arrs, _ in groups]
        flats = [a.astype(np.int64).ravel()
                 for arrs, _ in groups for a in arrs]
        flat = (np.concatenate(flats) if flats
                else np.zeros(0, np.int64))
        if flat.size and (int(flat.max()) > 2 ** 31 - 1
                          or int(flat.min()) < -(2 ** 31)):
            raise OverflowError(
                "HostComm.allreduce_groups: a layout-exchange value "
                "exceeds int32 — the packed exchange protocol assumes "
                "counts/owners/per-part sizes below 2^31 (a single part "
                "beyond that is outside the design envelope); widen the "
                "protocol deliberately rather than per-process")
        flat = flat.astype(np.int32)
        if flat.size <= self.CHUNK:
            gathered = np.asarray(
                multihost_utils.process_allgather(flat)).astype(np.int64)
            red_flat = None
        else:
            # Chunked gather-reduce: one (n_procs, N) copy of an
            # O(n_dof) payload would multiply the very memory bound the
            # sharded setup exists to hold — reduce chunk by chunk so
            # the transient stays n_procs * CHUNK regardless of model
            # size.  Every chunk is padded to the SAME length, so the
            # whole loop reuses one compiled allgather program (padding
            # is sliced off before the reduce; all processes iterate
            # the identical chunk sequence).
            red_flat = np.empty(flat.size, np.int64)
            pos_c = 0
            while pos_c < flat.size:
                n = min(self.CHUNK, flat.size - pos_c)
                buf = np.zeros(self.CHUNK, np.int32)
                buf[:n] = flat[pos_c:pos_c + n]
                g = np.asarray(multihost_utils.process_allgather(buf))
                # per-position op: resolve below per group segment —
                # store BOTH reductions? No: segments are contiguous,
                # so reduce lazily per segment from the gathered chunk.
                # To keep one pass, stash the raw chunk reductions for
                # both ops only when a segment boundary crosses the
                # chunk; simpler and still bounded: keep the gathered
                # chunk and reduce the overlapping segments now.
                for seg_pos, seg_n, op in self._segments(groups):
                    lo = max(seg_pos, pos_c)
                    hi = min(seg_pos + seg_n, pos_c + n)
                    if lo < hi:
                        red_flat[lo:hi] = self._OPS[op](
                            g[:, lo - pos_c:hi - pos_c], axis=0)
                pos_c += n
        out, pos = [], 0
        for arrs, op in groups:
            red_arrs = []
            for a in arrs:
                n = int(a.size)
                if red_flat is not None:
                    red = red_flat[pos:pos + n]
                else:
                    red = self._OPS[op](gathered[:, pos:pos + n], axis=0)
                red_arrs.append(red.reshape(a.shape).astype(a.dtype))
                pos += n
            out.append(red_arrs)
        return out

    #: chunk length (int32 entries) of the chunked gather-reduce path:
    #: 4M entries = 16 MB per process-copy per chunk
    CHUNK = 1 << 22

    @staticmethod
    def _segments(groups):
        """(pos, size, op) spans of the packed buffer, one per array."""
        pos = 0
        for arrs, op in groups:
            for a in arrs:
                n = int(np.asarray(a).size)
                yield pos, n, op
                pos += n


def local_part_range(mesh: jax.sharding.Mesh, n_parts: int):
    """The contiguous [lo, hi) part range whose rows are addressable by
    THIS process on a parts-sharded (P, ...) array over ``mesh``, or
    None when this process's parts are not one contiguous block (an
    exotic device order — the sharded setup path then falls back to the
    full build).  Single process: the full range."""
    if jax.process_count() == 1:
        return (0, n_parts)
    devices = list(mesh.devices.flat)
    if n_parts % len(devices) != 0:
        return None
    ppd = n_parts // len(devices)
    pid = jax.process_index()
    mine = [p for p, d in enumerate(devices) if d.process_index == pid]
    if not mine or mine != list(range(mine[0], mine[-1] + 1)):
        return None
    return (mine[0] * ppd, (mine[-1] + 1) * ppd)


def put_tree(tree, mesh: jax.sharding.Mesh, specs):
    """put_sharded over a pytree of arrays with a matching pytree of specs
    (None leaves pass through, as with device_put)."""
    if jax.process_count() == 1:
        shardings = jax.tree.map(
            lambda s: jax.sharding.NamedSharding(mesh, s), specs,
            is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec))
        return jax.device_put(tree, shardings)

    def rec(t, s):
        if t is None:
            return None
        if isinstance(t, dict):
            return {k: rec(v, s[k]) for k, v in t.items()}
        if isinstance(t, (list, tuple)):
            return type(t)(rec(v, s[i]) for i, v in enumerate(t))
        return put_sharded(np.asarray(t), mesh, s)

    return rec(tree, specs)
