"""Multi-host (DCN) support.

The reference scales across nodes with mpi4py over OpenMPI — tagged p2p
halo messages plus allreduces (reference: SURVEY.md §2d; pcg_solver.py:
317-334, 622-628).  The TPU-native equivalent has no user-level messaging:
``jax.distributed`` forms one multi-controller program, the device mesh
spans all hosts (ICI within a slice, DCN across), and the SAME compiled
solve program runs everywhere — XLA routes the psum/collectives.

What this module provides:

- :func:`init_distributed` — process bootstrap (coordinator discovery from
  standard env vars, explicit args, or single-process no-op).
- :func:`make_global_mesh` — 1-D parts mesh over every device of every host.
- :func:`put_sharded` / :func:`put_tree` — build sharded global device
  arrays from host numpy data; on multi-host each process materializes only
  its addressable shards (the analogue of the reference's per-rank partition
  pickles + shared-memory staging, file_operations.py:306-339).

Single-process semantics are identical to plain ``device_put``, so every
code path here is exercised by the single-host test suite; multi-host adds
only the bootstrap.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np

from pcg_mpi_solver_tpu.parallel.mesh import PARTS_AXIS


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> int:
    """Initialize jax.distributed for a multi-host run; returns process id.

    Resolution order: explicit args > env vars (``PCG_TPU_COORDINATOR`` /
    ``PCG_TPU_NUM_PROCS`` / ``PCG_TPU_PROC_ID``, mirroring the standard JAX
    ones) > single-process no-op.  Safe to call repeatedly.
    """
    coordinator_address = coordinator_address or os.environ.get("PCG_TPU_COORDINATOR")
    if num_processes is None and os.environ.get("PCG_TPU_NUM_PROCS"):
        num_processes = int(os.environ["PCG_TPU_NUM_PROCS"])
    if process_id is None and os.environ.get("PCG_TPU_PROC_ID"):
        process_id = int(os.environ["PCG_TPU_PROC_ID"])

    if coordinator_address is None and num_processes is None:
        return jax.process_index()          # single process / TPU pod auto-init
    global _initialized
    if not _initialized:
        # NOTE: must run before anything touches the XLA backend — do not
        # query jax.process_count() here.
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        _initialized = True
    return jax.process_index()


_initialized = False


def make_global_mesh(n_devices: Optional[int] = None) -> jax.sharding.Mesh:
    """1-D ``(parts,)`` mesh over all devices of all processes (DCN-aware:
    jax.devices() enumerates host-local devices first, so contiguous part
    blocks land host-local and halo traffic prefers ICI)."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return jax.sharding.Mesh(np.asarray(devices), (PARTS_AXIS,))


def put_sharded(x: np.ndarray, mesh: jax.sharding.Mesh,
                spec: jax.sharding.PartitionSpec) -> jax.Array:
    """Host numpy -> sharded global device array.

    Single-process: plain device_put.  Multi-process: each process builds
    only its addressable shards via make_array_from_callback (every process
    must hold the rows its devices own; the part-major layout makes that a
    contiguous block of the leading axis)."""
    sharding = jax.sharding.NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    return jax.make_array_from_callback(
        np.shape(x), sharding, lambda idx: np.asarray(x[idx]))


def gather_owned_global(pm, x, mesh: Optional[jax.sharding.Mesh] = None,
                        dtype=None) -> np.ndarray:
    """(P, n_loc[, R]) part-padded dof vector/block -> (glob_n_dof[, R])
    global array via the owner mask (each dof written by exactly one
    part).  The one shared mask-and-scatter idiom for every solver's
    global views — a trailing RHS-block axis rides through unchanged
    (one fetch, one masked scatter for the whole block)."""
    tail = tuple(np.shape(x))[2:]
    out = np.zeros((pm.glob_n_dof,) + tail, dtype=dtype or np.float64)
    m = (pm.weight > 0) & (pm.dof_gid >= 0)
    out[pm.dof_gid[m]] = fetch_global(x, mesh)[m]
    return out


def fetch_global(x, mesh: Optional[jax.sharding.Mesh] = None) -> np.ndarray:
    """Fetch a (possibly multi-host sharded) jax.Array as full host numpy.

    Single-process (or fully addressable) arrays are a plain device_get; a
    multi-host sharded array is first resharded to fully-replicated (an XLA
    all-gather over DCN) so every process can read the whole value — the
    analogue of the reference's Comm.gather-to-rank-0 exports
    (pcg_solver.py:910-911)."""
    if not isinstance(x, jax.Array) or x.is_fully_addressable:
        return np.asarray(x)
    if mesh is None:
        mesh = jax.sharding.Mesh(
            np.asarray(x.sharding.mesh.devices), x.sharding.mesh.axis_names)
    rep = jax.jit(lambda a: a,
                  out_shardings=jax.sharding.NamedSharding(
                      mesh, jax.sharding.PartitionSpec()))(x)
    return np.asarray(rep)


def fetch_addressable(x) -> tuple:
    """Fetch only this process's addressable rows of a parts-sharded array.

    Returns ``(rows, p0, p1)`` with ``rows == x[p0:p1]``.  The collective-free
    counterpart of :func:`fetch_global` — the basis of parallel result
    writes (each process persists its own contiguous part block, the
    analogue of the reference's MPI-IO shared-file writes at computed
    offsets, file_operations.py:348-396)."""
    if not isinstance(x, jax.Array) or x.is_fully_addressable:
        a = np.asarray(x)
        return a, 0, a.shape[0]
    shards = sorted(x.addressable_shards,
                    key=lambda s: s.index[0].start or 0)
    rows = np.concatenate([np.asarray(s.data) for s in shards], axis=0)
    p0 = shards[0].index[0].start or 0
    p1 = p0 + rows.shape[0]
    # The part-range labeling is only valid if this process's shards tile
    # [p0, p1) contiguously — true for make_global_mesh's device order,
    # not necessarily for an arbitrary (e.g. torus-reordered) mesh.
    ends = [s.index[0] for s in shards]
    cov = sorted((sl.start or 0, sl.stop) for sl in ends)
    pos = p0
    for a, b in cov:
        if a != pos:
            raise ValueError(
                f"addressable shards are not part-contiguous: {cov} "
                "(use make_global_mesh, or export via fetch_global)")
        pos = b
    return rows, p0, p1


def put_tree(tree, mesh: jax.sharding.Mesh, specs):
    """put_sharded over a pytree of arrays with a matching pytree of specs
    (None leaves pass through, as with device_put)."""
    if jax.process_count() == 1:
        shardings = jax.tree.map(
            lambda s: jax.sharding.NamedSharding(mesh, s), specs,
            is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec))
        return jax.device_put(tree, shardings)

    def rec(t, s):
        if t is None:
            return None
        if isinstance(t, dict):
            return {k: rec(v, s[k]) for k, v in t.items()}
        if isinstance(t, (list, tuple)):
            return type(t)(rec(v, s[i]) for i, v in enumerate(t))
        return put_sharded(np.asarray(t), mesh, s)

    return rec(tree, specs)
