"""Device-mesh helpers.

The reference's distributed runtime is mpi4py over OpenMPI (1 rank = 1 mesh
partition, pcg_solver.py:91,968-970).  Here the runtime is a 1-D
``jax.sharding.Mesh`` over TPU devices: one device = one (or more, stacked)
mesh partition(s); collectives ride ICI inside the jitted program.  Multi-host
extends the same mesh over DCN via ``jax.distributed`` without code changes.
"""

from __future__ import annotations

import jax
import numpy as np

from pcg_mpi_solver_tpu.utils.backend_probe import pin_cpu_backend_if_requested
from pcg_mpi_solver_tpu.utils.compat import ensure_shard_map

# jax < 0.5 compat: alias jax.shard_map before any call site runs (the
# package __init__ must stay jax-free; see ops/matvec.py).
ensure_shard_map()

PARTS_AXIS = "parts"


def make_mesh(n_devices: int | None = None, devices=None) -> jax.sharding.Mesh:
    """1-D mesh over the parts axis."""
    if devices is None:
        # a JAX_PLATFORMS=cpu env request must become an in-process pin
        # BEFORE the jax.devices() touch (wedged-tunnel hang otherwise —
        # see the helper's docstring)
        pin_cpu_backend_if_requested()
        devices = jax.devices()
        if n_devices is not None:
            if len(devices) < n_devices:
                raise ValueError(
                    f"requested {n_devices} devices, only {len(devices)} available")
            devices = devices[:n_devices]
    return jax.sharding.Mesh(np.array(devices), (PARTS_AXIS,))


def part_spec() -> jax.sharding.PartitionSpec:
    """Leading-axis sharding: arrays are (P, ...) with P split over devices."""
    return jax.sharding.PartitionSpec(PARTS_AXIS)
