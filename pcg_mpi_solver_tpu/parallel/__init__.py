from pcg_mpi_solver_tpu.parallel.partition import PartitionedModel, partition_model
from pcg_mpi_solver_tpu.parallel.mesh import make_mesh, PARTS_AXIS

__all__ = ["PartitionedModel", "partition_model", "make_mesh", "PARTS_AXIS"]
