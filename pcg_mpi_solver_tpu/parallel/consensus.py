"""Group-agreed verdicts for multi-process control flow.

Every host-side branch that sits next to a collective is a divergence
hazard under multi-controller JAX: if rank 0 decides "retry" while
rank 1 decides "give up", the next psum pairs a live program against a
missing one and the whole fleet wedges.  PR 14 grew two ad-hoc copies
of the fix (the engage agreement in ``solver/driver.py`` and the
warm/cold agreement in ``cache/partition_cache.py``); this module is
the generalization both now route through, and the one the recovery
ladder / quarantine logic of ``resilience/engine.py`` uses so no rank
ever takes a divergent recovery branch across a collective.

The mechanics are deliberately tiny: each rank encodes its local
verdict as a small int64 vector, one packed allreduce (HostComm packs
into a single int32 gather buffer) reduces it with ``min`` or ``max``,
and every rank decodes the SAME agreed vector.  ``min`` expresses
"all ranks must be able" (warm cache, shard write landed); ``max``
expresses "any rank's alarm wins" (breakdown triggers, where the
highest-priority local trigger must drive every rank's ladder).
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

__all__ = ["agree", "agree_flag", "agree_trigger", "agree_triggers",
           "encode_trigger", "decode_trigger"]

# Trigger encoding for ladder consensus: 0 = no trigger; breakdown
# flags outrank the carry/device classes under a max-reduce because a
# flagged breakdown carries more diagnostic information than the
# generic classes — every rank recovers, and the agreed event names
# the most specific cause any rank observed.
_TRIGGER_CODES = {"device_loss": 1, "nan_carry": 2}
_FLAG_BASE = 10


def _has_group(comm) -> bool:
    return comm is not None and getattr(comm, "n_procs", 1) > 1


def agree(comm, local, op: str = "min") -> np.ndarray:
    """Reduce each rank's local int verdict vector into the group-agreed
    vector (every rank returns the identical array).  ``comm`` is any
    HostComm-shaped object (``allreduce_groups`` + ``n_procs``); a None
    comm or a single-process group is the identity — callers never need
    a serial special case."""
    arr = np.asarray(local, dtype=np.int64).reshape(-1)
    if not _has_group(comm):
        return arr.copy()
    (agreed,), = comm.allreduce_groups([([arr], op)])
    return np.asarray(agreed, dtype=np.int64).reshape(arr.shape)


def agree_flag(comm, ok) -> bool:
    """All-ranks-agree boolean (min-reduce): True only when EVERY rank's
    local verdict is True — the engage/warm-cache agreement shape."""
    return bool(int(agree(comm, [1 if ok else 0], "min")[0]))


def encode_trigger(trigger: Optional[str]) -> int:
    """Ladder trigger -> consensus code (None = 0 = no recovery)."""
    if trigger is None:
        return 0
    if trigger in _TRIGGER_CODES:
        return _TRIGGER_CODES[trigger]
    if trigger.startswith("flag"):
        return _FLAG_BASE + int(trigger[len("flag"):])
    raise ValueError(f"unknown ladder trigger {trigger!r}")


def decode_trigger(code) -> Optional[str]:
    """Consensus code -> ladder trigger (inverse of encode_trigger)."""
    code = int(code)
    if code == 0:
        return None
    for name, c in _TRIGGER_CODES.items():
        if c == code:
            return name
    if code >= _FLAG_BASE:
        return f"flag{code - _FLAG_BASE}"
    raise ValueError(f"unknown trigger code {code}")


def agree_trigger(comm, trigger: Optional[str]) -> Optional[str]:
    """Group-agreed scalar ladder trigger: max-reduce of the encoded
    local triggers, so one rank's breakdown drives every rank's ladder
    in lockstep (and the agreed trigger is the most specific one any
    rank observed)."""
    return decode_trigger(agree(comm, [encode_trigger(trigger)], "max")[0])


def agree_triggers(comm, triggers: Dict[int, Optional[str]],
                   width: int) -> Dict[int, str]:
    """Group-agreed per-column triggers of a blocked multi-RHS solve:
    one packed max-reduce over all ``width`` columns, returning only the
    columns with an agreed trigger (the shape
    ``run_many_with_recovery`` consumes)."""
    vec = np.zeros(int(width), dtype=np.int64)
    for k, trig in triggers.items():
        vec[int(k)] = encode_trigger(trig)
    agreed = agree(comm, vec, "max")
    return {k: decode_trigger(c) for k, c in enumerate(agreed) if c}
