"""Hybrid level-grid backend: octree meshes as sparse unions of structured
grids.

The reference's problem class is octree meshes (2:1-graded hexahedral cells,
<=144 geometric pattern types; partition_mesh.py:420-493, 1074).  On TPU the
pain point is the per-element gather/scatter: every vector gather costs an
order of magnitude more than the dense math it feeds.  But in any graded
octree the overwhelming majority of cells are pure 8-node "bricks" of some
refinement level — only the level-interface transition cells carry hanging
nodes.  This backend:

- places each level's brick cells on a DENSE per-level cell grid over the
  part's bounding box, with ``ck = 0`` holes wherever this level has no
  brick (a zero-stiffness cell contributes exactly nothing, so holes are
  free);
- gathers each level's NODE lattice once per matvec (one (n,3)-row gather
  per level — ~8x less gather traffic than per-element corner gathers),
  runs the same slice-gather -> MXU einsum -> padded-translate-scatter
  stencil as the structured backend (parallel/structured.py), and
  row-scatters the result back into the local dof vector;
- keeps ONLY transition cells on the general node-ELL gather path
  (ops/matvec.py) — they are excluded from the type blocks via
  ``partition_model(block_filter=...)``;
- shares everything else (interface psum assembly, weighted dots, PCG,
  exports) with the general backend through the same Ops protocol.

Correctness note: a lattice point of a level grid that is NOT a node of the
mesh (or not local to the part) maps to the pad index — its gathered value
(0) only ever multiplies into cells with ck = 0, and its scattered output
row is dropped, because every corner of a ck > 0 brick cell IS a local mesh
node by construction.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from pcg_mpi_solver_tpu.models.model_data import ModelData
from pcg_mpi_solver_tpu.ops.matvec import Ops, device_data
from pcg_mpi_solver_tpu.parallel.partition import (
    PartitionedModel, make_elem_part, partition_model)


@dataclasses.dataclass
class LevelGrid:
    """One refinement level's brick cells on a dense per-part grid."""

    size: int                   # cell edge length in finest lattice units
    bx: int                     # cell-grid dims (common, padded over parts)
    by: int
    bz: int
    origin: np.ndarray          # (P, 3) lattice origin in LEVEL units
    ck: np.ndarray              # (P, bx, by, bz); 0 = hole
    ce: np.ndarray              # (P, bx, by, bz)
    nidx: np.ndarray            # (P, (bx+1)*(by+1)*(bz+1)) int32 local node
                                # ids, n_node_loc = pad
    n_cells: np.ndarray         # (P,) true brick count per part


@dataclasses.dataclass
class HybridPartition:
    """PartitionedModel (transition cells only in its type blocks) plus the
    per-level brick grids.  Duck-compatible with the driver's pm usage."""

    pm: PartitionedModel
    levels: List[LevelGrid]
    brick_Ke: np.ndarray        # (24, 24) unit brick stiffness
    brick_diag: np.ndarray      # (24,)
    brick_Se: Optional[np.ndarray]  # (6, 24)

    def __getattr__(self, name):
        # Guard 'pm' and dunders: during unpickling/deepcopy the object
        # exists before __dict__ is populated, and delegating those lookups
        # would recurse.
        if name == "pm" or name.startswith("__"):
            raise AttributeError(name)
        return getattr(self.pm, name)


def can_hybrid(model: ModelData) -> bool:
    """Single source of truth for hybrid-backend eligibility (used by the
    quasi-static driver, the dynamics solver, and partition_hybrid)."""
    return (model.octree is not None
            and model.octree.get("brick_type") is not None)


def hybrid_pallas_enabled(hp: "HybridPartition", pallas_mode: str,
                          mesh) -> bool:
    """Resolve the pallas knob with THIS partition's level-grid shapes —
    the one shared probe call for every hybrid consumer (quasi-static
    driver, dynamics)."""
    from pcg_mpi_solver_tpu.solver.driver import _pallas_enabled

    return _pallas_enabled(
        pallas_mode, mesh,
        shapes=tuple(((3, lv.bx + 1, lv.by + 1, lv.bz + 1),
                      (lv.bx, lv.by, lv.bz)) for lv in hp.levels))


def partition_hybrid(model: ModelData, n_parts: int,
                     elem_part: Optional[np.ndarray] = None,
                     method: str = "rcb") -> HybridPartition:
    if not can_hybrid(model):
        raise ValueError("model has no octree/brick metadata for the "
                         "hybrid backend")
    meta = model.octree
    bt = meta["brick_type"]
    leaves = np.asarray(meta["leaves"])
    node_keys = np.asarray(meta["node_keys"])
    sy, sz = meta["strides"]
    corners = np.asarray(meta["brick_corners"], dtype=np.int64)   # (8, 3)
    if not np.array_equal(corners, _CORNERS):
        raise ValueError("brick corner order does not match the level-grid "
                         "stencil's corner order")

    brick = model.elem_type == bt
    if elem_part is None:
        elem_part = make_elem_part(model, n_parts, method=method)
    pm = partition_model(model, n_parts, elem_part=elem_part,
                         block_filter=~brick)

    P = n_parts
    lib = model.elem_lib[bt]
    levels: List[LevelGrid] = []
    for s in sorted(int(v) for v in np.unique(leaves[brick, 3])):
        sel_lvl = brick & (leaves[:, 3] == s)
        per_part = [np.where(sel_lvl & (elem_part == p))[0] for p in range(P)]
        # level-unit cell coords (octree cells of size s are s-aligned)
        lat = [leaves[e, :3] // s for e in per_part]
        lo = np.zeros((P, 3), dtype=np.int64)
        dims = np.zeros((P, 3), dtype=np.int64)
        for p in range(P):
            if len(per_part[p]):
                lo[p] = lat[p].min(axis=0)
                dims[p] = lat[p].max(axis=0) + 1 - lo[p]
        bx, by, bz = (int(d) for d in dims.max(axis=0))
        if bx == 0:
            continue
        ck = np.zeros((P, bx, by, bz))
        ce = np.zeros((P, bx, by, bz))
        nn = (bx + 1) * (by + 1) * (bz + 1)
        nidx = np.full((P, nn), pm.n_node_loc, dtype=np.int32)
        n_cells = np.zeros(P, dtype=np.int64)
        II, JJ, KK = np.meshgrid(np.arange(bx + 1), np.arange(by + 1),
                                 np.arange(bz + 1), indexing="ij")
        for p in range(P):
            e = per_part[p]
            n_cells[p] = len(e)
            if not len(e):
                continue
            c = lat[p] - lo[p]
            ck[p, c[:, 0], c[:, 1], c[:, 2]] = model.ck[e]
            ce[p, c[:, 0], c[:, 1], c[:, 2]] = model.ce[e]
            # node lattice -> local node ids (missing / non-local -> pad)
            gx = (II + lo[p, 0]) * s
            gy = (JJ + lo[p, 1]) * s
            gz = (KK + lo[p, 2]) * s
            keys = (gx + sy * gy + sz * gz).reshape(-1)
            kpos = np.searchsorted(node_keys, keys)
            kpos_c = np.minimum(kpos, len(node_keys) - 1)
            is_node = node_keys[kpos_c] == keys
            gnid = np.where(is_node, kpos_c, -1)       # global node id or -1
            loc_gids = pm.node_gid[p, : pm.nnode_p[p]]  # sorted
            lpos = np.searchsorted(loc_gids, np.where(gnid < 0, 0, gnid))
            lpos_c = np.minimum(lpos, len(loc_gids) - 1)
            is_loc = is_node & (loc_gids[lpos_c] == gnid)
            nidx[p] = np.where(is_loc, lpos_c, pm.n_node_loc).astype(np.int32)
        levels.append(LevelGrid(size=s, bx=bx, by=by, bz=bz,
                                origin=lo, ck=ck, ce=ce,
                                nidx=nidx, n_cells=n_cells))

    return HybridPartition(
        pm=pm,
        levels=levels,
        brick_Ke=np.asarray(lib["Ke"], np.float64),
        brick_diag=np.asarray(lib["diagKe"], np.float64),
        brick_Se=(np.asarray(lib["Se"], np.float64)
                  if lib.get("Se") is not None else None),
    )


def device_data_hybrid(hp: HybridPartition, dtype=jnp.float64) -> dict:
    d = device_data(hp.pm, dtype)
    d["levels"] = [{
        "ck": jnp.asarray(lv.ck, dtype),
        "ce": jnp.asarray(lv.ce, dtype),
        "nidx": jnp.asarray(lv.nidx, jnp.int32),
    } for lv in hp.levels]
    d["brick_Ke"] = jnp.asarray(hp.brick_Ke, dtype)
    d["brick_diag"] = jnp.asarray(hp.brick_diag, dtype)
    if hp.brick_Se is not None:
        d["brick_Se"] = jnp.asarray(hp.brick_Se, dtype)
    return d


# corner offsets in the brick type's node order (== models/element.py
# HEX_CORNERS == _slot_layout(0)'s corner order, asserted in tests)
_CORNERS = np.array([[0, 0, 0], [1, 0, 0], [1, 1, 0], [0, 1, 0],
                     [0, 0, 1], [1, 0, 1], [1, 1, 1], [0, 1, 1]],
                    dtype=np.int64)


@dataclasses.dataclass(frozen=True)
class HybridOps(Ops):
    """General Ops over the transition blocks + dense level-grid stencils
    for the brick cells of each refinement level."""

    # static (bx, by, bz) per level — shapes must be trace-constants
    level_dims: tuple = ()
    # run the f32 level stencils through the fused Pallas plane-march
    # kernel (ops/pallas_matvec.py) — same kernel as the structured backend
    use_pallas: bool = False

    @classmethod
    def from_hybrid(cls, hp: HybridPartition, dot_dtype=jnp.float64,
                    axis_name=None,
                    precision=jax.lax.Precision.HIGHEST,
                    use_pallas=False):
        pm = hp.pm
        return cls(n_loc=pm.n_loc, n_iface=pm.n_iface,
                   n_node_loc=pm.n_node_loc, n_node_iface=pm.n_node_iface,
                   dot_dtype=dot_dtype, axis_name=axis_name,
                   precision=precision,
                   use_node_ell=pm.ell is not None,
                   level_dims=tuple((lv.bx, lv.by, lv.bz)
                                    for lv in hp.levels),
                   use_pallas=use_pallas)

    # -- level-grid primitives -----------------------------------------
    def _rows_pad(self, x):
        """x (P, n_loc) -> zero-padded node rows (P*(n_node_loc+1), 3)."""
        Pn = x.shape[0]
        x3 = x.reshape(Pn, self.n_node_loc, 3)
        return jnp.concatenate(
            [x3, jnp.zeros((Pn, 1, 3), x3.dtype)], axis=1
        ).reshape(Pn * (self.n_node_loc + 1), 3)

    def _level_gather(self, x3p, lv, dims, Pn):
        """Node-lattice gather: (P, 3, bx+1, by+1, bz+1) grid."""
        bx, by, bz = dims
        nr = self.n_node_loc + 1
        offs = (jnp.arange(Pn, dtype=jnp.int32) * nr)[:, None]
        g = jnp.take(x3p, (lv["nidx"] + offs).reshape(-1), axis=0,
                     mode="clip")
        g = g.reshape(Pn, bx + 1, by + 1, bz + 1, 3)
        return g.transpose(0, 4, 1, 2, 3)

    def _level_scatter_add(self, y, grid, lv, dims, Pn):
        """Adds (P, 3, bx+1, by+1, bz+1) node-grid values into y (P, n_loc)."""
        rows = grid.transpose(0, 2, 3, 4, 1).reshape(Pn, -1, 3)
        y3 = y.reshape(Pn, self.n_node_loc, 3)
        y3 = jax.vmap(
            lambda yp, idx, r: yp.at[idx].add(r, mode="drop")
        )(y3, lv["nidx"], rows)
        return y3.reshape(Pn, self.n_loc)

    def _stencil(self, Ke, ck, xg):
        """Structured brick matvec on one level grid (same formulations
        as parallel/structured.py: slice gather -> einsum -> sum of
        padded translates, the fusion-friendly corner form under
        PCG_TPU_MATVEC_FORM=corner, or the fused Pallas kernel when
        enabled)."""
        if self.use_pallas and np.dtype(xg.dtype) == np.float32:
            from pcg_mpi_solver_tpu.ops.pallas_matvec import (
                batched_structured_matvec)

            return batched_structured_matvec(xg, ck, Ke)
        from pcg_mpi_solver_tpu.parallel.structured import (
            corner_matvec_grid, matvec_form)

        if matvec_form() == "corner":
            return corner_matvec_grid(Ke, ck, xg)
        bx, by, bz = ck.shape[1], ck.shape[2], ck.shape[3]
        slots = [xg[:, :, dx:dx + bx, dy:dy + by, dz:dz + bz]
                 for dx, dy, dz in _CORNERS]
        u = jnp.concatenate(slots, axis=1)             # (P, 24, cells)
        v = jnp.einsum("de,pexyz->pdxyz", Ke, ck[:, None] * u,
                       precision=self.precision)
        terms = []
        for a, (dx, dy, dz) in enumerate(_CORNERS):
            terms.append(jnp.pad(
                v[:, 3 * a:3 * a + 3],
                ((0, 0), (0, 0), (dx, 1 - dx), (dy, 1 - dy), (dz, 1 - dz))))
        y = terms[0]
        for t in terms[1:]:
            y = y + t
        return y

    # -- operator protocol ---------------------------------------------
    def matvec_local(self, data, x):
        Pn = x.shape[0]
        if data["blocks"]:
            y = Ops.matvec_local(self, data, x)
        else:
            y = self._apply_springs(data, x, jnp.zeros_like(x))
        if data["levels"]:
            x3p = self._rows_pad(x)
            for lv, dims in zip(data["levels"], self.level_dims):
                xg = self._level_gather(x3p, lv, dims, Pn)
                yg = self._stencil(data["brick_Ke"], lv["ck"], xg)
                y = self._level_scatter_add(y, yg, lv, dims, Pn)
        return y

    def diag_local(self, data):
        Pn = data["weight"].shape[0]
        if data["blocks"]:
            y = Ops.diag_local(self, data)
        else:
            y = self._apply_springs_diag(
                data, jnp.zeros((Pn, self.n_loc), data["weight"].dtype))
        for lv, dims in zip(data["levels"], self.level_dims):
            ck = lv["ck"]
            dk = data["brick_diag"]
            terms = []
            for a, (dx, dy, dz) in enumerate(_CORNERS):
                contrib = dk[3 * a:3 * a + 3][None, :, None, None, None] \
                    * ck[:, None]
                terms.append(jnp.pad(
                    contrib,
                    ((0, 0), (0, 0), (dx, 1 - dx), (dy, 1 - dy),
                     (dz, 1 - dz))))
            g = terms[0]
            for t in terms[1:]:
                g = g + t
            y = self._level_scatter_add(y, g, lv, dims, Pn)
        return y

    def _node_block_local(self, data):
        """Transition-block node blocks (general path) + brick-cell corner
        blocks pad-translated onto each level's node grid."""
        if data["blocks"]:
            y = Ops._node_block_local(self, data)
        else:
            Pl = data["weight"].shape[0]
            y = self._springs_into_blocks(
                data, jnp.zeros((Pl, self.n_node_loc, 9),
                                data["weight"].dtype))
        from pcg_mpi_solver_tpu.ops.precond import corner_block_field

        for lv, dims in zip(data["levels"], self.level_dims):
            ck = lv["ck"]
            Pn = ck.shape[0]
            g = corner_block_field(data["brick_Ke"], ck, _CORNERS)
            rows = g.transpose(0, 2, 3, 4, 1).reshape(Pn, -1, 9)
            y = jax.vmap(
                lambda yp, idx, r: yp.at[idx].add(r, mode="drop")
            )(y, lv["nidx"], rows)
        return y

    # -- export protocol (strain + nodal averaging over blocks + levels) --
    def elem_strain(self, data, x):
        out = Ops.elem_strain(self, data, x) if data["blocks"] else []
        Pn = x.shape[0]
        if data["levels"]:
            if "brick_Se" not in data:
                raise ValueError("strain export unavailable: the brick "
                                 "element library has no Se strain mode")
            x3p = self._rows_pad(x)
            for lv, dims in zip(data["levels"], self.level_dims):
                xg = self._level_gather(x3p, lv, dims, Pn)
                bx, by, bz = dims
                slots = [xg[:, :, dx:dx + bx, dy:dy + by, dz:dz + bz]
                         for dx, dy, dz in _CORNERS]
                u = jnp.concatenate(slots, axis=1)
                eps = jnp.einsum("sd,pdxyz->psxyz", data["brick_Se"],
                                 lv["ce"][:, None] * u,
                                 precision=self.precision)
                out.append(eps.reshape(Pn, 6, -1))
        return out

    def elem_scale(self, data):
        out = Ops.elem_scale(self, data) if data["blocks"] else []
        for lv in data["levels"]:
            Pn = lv["ck"].shape[0]
            out.append((lv["ck"] * lv["ce"]).reshape(Pn, -1))
        return out

    def nodal_average(self, data, vals_list):
        """Blocks + levels -> averaged nodal field.  vals_list aligns with
        elem_strain/elem_scale output order: blocks first, then levels."""
        nb = len(data["blocks"])
        k = vals_list[0].shape[1]
        Pl = vals_list[0].shape[0]
        dt = vals_list[0].dtype
        sums = jnp.zeros((Pl, k, self.n_node_loc), dt)
        counts = jnp.zeros((Pl, 1, self.n_node_loc), dt)

        def scat(s, ids, c):
            return s.at[:, ids].add(c, mode="drop")

        for blk, vals in zip(data["blocks"], vals_list[:nb]):
            node = blk["node"]
            nn = node.shape[1]
            ids = node.reshape(Pl, -1)
            contrib = jnp.broadcast_to(
                vals[:, :, None, :], (Pl, k, nn, vals.shape[2])
            ).reshape(Pl, k, -1)
            ones = jnp.ones((Pl, 1, nn * vals.shape[2]), dt)
            sums = jax.vmap(scat)(sums, ids, contrib)
            counts = jax.vmap(scat)(counts, ids, ones)

        for lv, dims, vals in zip(data["levels"], self.level_dims,
                                  vals_list[nb:]):
            bx, by, bz = dims
            vg = vals.reshape(Pl, k, bx, by, bz)
            # valid-cell mask: holes (ck == 0) must not count
            valid = (lv["ck"] != 0).astype(dt)[:, None]
            both = jnp.concatenate([vg * valid, valid], axis=1)
            terms = []
            for dx, dy, dz in _CORNERS:
                terms.append(jnp.pad(
                    both, ((0, 0), (0, 0), (dx, 1 - dx), (dy, 1 - dy),
                           (dz, 1 - dz))))
            g = terms[0]
            for t in terms[1:]:
                g = g + t                       # (P, k+1, node grid)
            rows = g.transpose(0, 2, 3, 4, 1).reshape(Pl, -1, k + 1)
            joined = jnp.concatenate([sums, counts], axis=1) \
                .transpose(0, 2, 1)             # (P, n_node_loc, k+1)
            joined = jax.vmap(
                lambda jp, idx, r: jp.at[idx].add(r, mode="drop")
            )(joined, lv["nidx"], rows)
            joined = joined.transpose(0, 2, 1)
            sums, counts = joined[:, :k], joined[:, k:]

        both = jnp.concatenate([sums, counts], axis=1)
        both = self.niface_assemble(data, both)
        return both[:, :k] / (both[:, k:] + 1e-15)
