"""Hybrid level-grid backend: octree meshes as sparse unions of structured
grids.

The reference's problem class is octree meshes (2:1-graded hexahedral cells,
<=144 geometric pattern types; partition_mesh.py:420-493, 1074).  On TPU the
pain point is the per-element gather/scatter: every vector gather costs an
order of magnitude more than the dense math it feeds.  But in any graded
octree the overwhelming majority of cells are pure 8-node "bricks" of some
refinement level — only the level-interface transition cells carry hanging
nodes.  This backend:

- places each level's brick cells on a DENSE per-level cell grid over the
  part's bounding box, with ``ck = 0`` holes wherever this level has no
  brick (a zero-stiffness cell contributes exactly nothing, so holes are
  free);
- gathers each level's NODE lattice once per matvec (one (n,3)-row gather
  per level — ~8x less gather traffic than per-element corner gathers),
  runs the same slice-gather -> MXU einsum -> padded-translate-scatter
  stencil as the structured backend (parallel/structured.py), and
  row-scatters the result back into the local dof vector;
- keeps ONLY transition cells on the general node-ELL gather path
  (ops/matvec.py) — they are excluded from the type blocks via
  ``partition_model(block_filter=...)``;
- shares everything else (interface psum assembly, weighted dots, PCG,
  exports) with the general backend through the same Ops protocol.

Correctness note: a lattice point of a level grid that is NOT a node of the
mesh (or not local to the part) maps to the pad index — its gathered value
(0) only ever multiplies into cells with ck = 0, and its scattered output
row is dropped, because every corner of a ck > 0 brick cell IS a local mesh
node by construction.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from pcg_mpi_solver_tpu.models.model_data import ModelData
from pcg_mpi_solver_tpu.ops.matvec import Ops, device_data
from pcg_mpi_solver_tpu.parallel.partition import (
    PartitionedModel, make_elem_part, partition_model)


@dataclasses.dataclass
class LevelGrid:
    """One refinement level's brick cells as a BATCH of dense blocks.

    A graded octree's per-level bounding box is mostly holes at scale
    (measured 3.7% fill on the 5.67M-dof flagship's finest level — 96%
    of a dense-bbox stencil would be wasted compute), so each level is
    tiled into bs^3-cell blocks and only blocks containing at least one
    brick are kept (5.8x total-cell reduction on that flagship at
    bs=8).  Small or well-filled levels keep a single dense-bbox block
    (nb == 1, dims == bbox) — the tiled and dense layouts are the same
    code path with different dims.

    Parts are padded to a common block count nb; padding blocks have
    ck = 0 and nidx = pad, so they compute and scatter exactly nothing.
    """

    size: int                   # cell edge length in finest lattice units
                                # (0 = merged multi-size batch, see
                                # PCG_TPU_HYBRID_MERGE in partition_hybrid)
    nb: int                     # blocks per part (common, padded)
    bx: int                     # per-BLOCK cell dims
    by: int
    bz: int
    origin: np.ndarray          # (P, nb, 3) block origin in LEVEL units
    ck: np.ndarray              # (P, nb, bx, by, bz); 0 = hole
    ce: np.ndarray              # (P, nb, bx, by, bz)
    nidx: np.ndarray            # (P, nb, (bx+1)*(by+1)*(bz+1)) int32 local
                                # node ids, n_node_loc = pad
    n_cells: np.ndarray         # (P,) true brick count per part


@dataclasses.dataclass
class CombineMaps:
    """Slot->node gather-combine maps (the scatter-free level combine).

    The 2026-07-30 hardware session measured the duplicate-row scatter at
    88.7 ns/row against 5.9 ns/row gathers (docs/BENCH_LOG.md "hybrid row
    traffic") — so the combine is transposed: all levels' lattice slots
    are sorted by target node at PARTITION time and composed into direct
    per-node source-slot indices.  At solve time the element->node
    accumulation (reference pcg_solver.py:300's bincount) becomes KD
    row gathers (+ a small scatter for the rare heavy nodes), never a
    7M-row scatter.

    Slot numbering: levels in list order, each level flat over
    (block, lattice pos) exactly as its ``nidx`` — runtime row arrays are
    concatenated in the same order, with ONE trailing zero row at index
    ``n_slots`` serving as the universal pad target.
    """

    n_slots: int                # total slots across levels (zero row = pad)
    gidx: np.ndarray            # (P, n_node_loc, KD) int32 slot ids
    hnode: np.ndarray           # (P, H) int32 heavy node ids (pad=n_node_loc)
    hgidx: np.ndarray           # (P, H, KE) int32 slot ids


@dataclasses.dataclass
class HybridPartition:
    """PartitionedModel (transition cells only in its type blocks) plus the
    per-level brick grids.  Duck-compatible with the driver's pm usage."""

    pm: PartitionedModel
    levels: List[LevelGrid]
    brick_Ke: np.ndarray        # (24, 24) unit brick stiffness
    brick_diag: np.ndarray      # (24,)
    brick_Se: Optional[np.ndarray]  # (6, 24)
    combine: Optional[CombineMaps] = None

    def __getattr__(self, name):
        # Guard 'pm' and dunders: during unpickling/deepcopy the object
        # exists before __dict__ is populated, and delegating those lookups
        # would recurse.
        if name == "pm" or name.startswith("__"):
            raise AttributeError(name)
        return getattr(self.pm, name)


def can_hybrid(model: ModelData) -> bool:
    """Single source of truth for hybrid-backend eligibility (used by the
    quasi-static driver, the dynamics solver, and partition_hybrid)."""
    return (model.octree is not None
            and model.octree.get("brick_type") is not None)


# batched_structured_matvec launches the kernel once per leading-batch
# entry (part*block); beyond this many launches per level the XLA
# stencil wins on dispatch overhead alone.  ONE constant shared by the
# probe/enable decision and the per-level trace-time dispatch.
PALLAS_BATCH_CAP = 16


def local_parts(n_parts: int, mesh) -> int:
    """Parts resident per device (the stencil's leading batch is
    local_parts * blocks under shard_map)."""
    n_dev = int(mesh.devices.size) if mesh is not None else 1
    return max(1, -(-int(n_parts) // n_dev))


def hybrid_pallas_enabled(hp: "HybridPartition", pallas_mode: str,
                          mesh) -> bool:
    """Resolve the pallas knob with THIS partition's level-grid shapes —
    the one shared probe call for every hybrid consumer (quasi-static
    driver, dynamics).  Only levels whose part*block batch fits the
    per-launch cap are probed (the others always run the XLA stencil);
    if no level qualifies the kernel is declined outright."""
    from pcg_mpi_solver_tpu.solver.driver import _pallas_enabled

    lp = local_parts(hp.pm.n_parts, mesh)
    shapes = tuple(sorted(set(
        ((3, lv.bx + 1, lv.by + 1, lv.bz + 1), (lv.bx, lv.by, lv.bz))
        for lv in hp.levels if lp * lv.nb <= PALLAS_BATCH_CAP)))
    if not shapes:
        if pallas_mode in ("on", "interpret"):
            import warnings

            warnings.warn(
                f"pallas={pallas_mode!r} but every hybrid level's "
                f"part*block batch exceeds the {PALLAS_BATCH_CAP}-launch "
                "cap; using the XLA stencils")
        return False
    return _pallas_enabled(pallas_mode, mesh, shapes=shapes)


def partition_hybrid(model: ModelData, n_parts: int,
                     elem_part: Optional[np.ndarray] = None,
                     method: str = "rcb") -> HybridPartition:
    from pcg_mpi_solver_tpu.parallel.partition import BUILD_CALLS

    BUILD_CALLS["partition_hybrid"] += 1
    if not can_hybrid(model):
        raise ValueError("model has no octree/brick metadata for the "
                         "hybrid backend")
    meta = model.octree
    bt = meta["brick_type"]
    leaves = np.asarray(meta["leaves"])
    # node_keys[i] = lattice key of node id i.  Generator-built models
    # number nodes in sorted-key order, but RECONSTRUCTED metadata
    # (models/octree.py reconstruct_lattice_meta) follows the bundle's
    # own numbering — sort once and keep the id permutation.
    raw_keys = np.asarray(meta["node_keys"])
    key_order = np.argsort(raw_keys)
    node_keys = raw_keys[key_order]
    sy, sz = meta["strides"]
    corners = np.asarray(meta["brick_corners"], dtype=np.int64)   # (8, 3)
    if not np.array_equal(corners, _CORNERS):
        raise ValueError("brick corner order does not match the level-grid "
                         "stencil's corner order")

    brick = model.elem_type == bt
    if elem_part is None:
        elem_part = make_elem_part(model, n_parts, method=method)
    pm = partition_model(model, n_parts, elem_part=elem_part,
                         block_filter=~brick)

    P = n_parts
    lib = model.elem_lib[bt]
    knobs = partition_env_knobs()   # one owner for the defaults
    bs_knob = knobs["block"]
    # PCG_TPU_HYBRID_MERGE (default OFF): give EVERY level the same tile
    # dims and merge all levels into ONE block batch after the loop —
    # legal because the stencil math is size-independent (level size
    # enters only through nidx and ck), and slot numbering is the same
    # level-order concatenation CombineMaps already uses.  Measured
    # chiplessly at the 5.67M-dof flagship (2026-07-31): the merge makes
    # COMPILE WORSE, not better (inner-cycle 473 -> 551 s, f64 amul
    # 999 -> 1328 s — the larger uniform batch outweighs the removed
    # per-level unroll), so it stays an off-by-default runtime A/B
    # candidate (1 launch vs 5 per matvec; parity-asserted in
    # tests/test_hybrid.py::test_merged_levels_match_unmerged).
    merge = knobs["merge"]
    sizes = sorted(int(v) for v in np.unique(leaves[brick, 3]))
    level_sel = []
    for s in sizes:
        sel_lvl = brick & (leaves[:, 3] == s)
        per_part = [np.where(sel_lvl & (elem_part == p))[0]
                    for p in range(P)]
        # level-unit cell coords (octree cells of size s are s-aligned)
        lat = [leaves[e, :3] // s for e in per_part]
        level_sel.append((s, per_part, lat))
    bs_eff = bs_knob
    if merge:
        # shared tile edge: cap the knob by the largest per-part level
        # extent so a force-dense setting (e.g. 10^6) cannot allocate an
        # astronomically-sized tile
        max_ext = 1
        for s, per_part, lat in level_sel:
            for p in range(P):
                if len(per_part[p]):
                    e = lat[p].max(axis=0) - lat[p].min(axis=0) + 1
                    max_ext = max(max_ext, int(e.max()))
        bs_eff = min(bs_knob, max_ext)
    levels: List[LevelGrid] = []
    for s, per_part, lat in level_sel:
        # choose this level's block dims: a single dense bbox block when
        # that is no larger than the bs^3 tiling would be, else bs^3
        # tiles of only the occupied blocks (absolute bs-aligned ids, so
        # dims stay common across parts).  One key-sort per part serves
        # both the decision and the fill below.  Under merge, EVERY
        # level tiles at the shared bs_eff edge.
        bs_lvl = bs_eff if merge else bs_knob
        ext = np.zeros(3, dtype=np.int64)
        bmax = 1
        blocks = [None] * P      # (uniq_block_keys, binv) per part
        for p in range(P):
            if not len(per_part[p]):
                continue
            lo_p = lat[p].min(axis=0)
            ext = np.maximum(ext, lat[p].max(axis=0) + 1 - lo_p)
            bid = lat[p] // bs_lvl
            uniq, binv = np.unique(
                (bid[:, 0] << 42) + (bid[:, 1] << 21) + bid[:, 2],
                return_inverse=True)
            blocks[p] = (uniq, binv)
            bmax = max(bmax, len(uniq))
        if not ext.any():
            continue
        # the dense layout allocates prod(ext) of the COMMON (padded)
        # extents for every part — that, not any single part's bbox, is
        # what tiling competes against
        if not merge and int(np.prod(ext)) <= bmax * bs_knob ** 3:
            nb, (bx, by, bz) = 1, (int(ext[0]), int(ext[1]), int(ext[2]))
            tiled = False
        else:
            nb, (bx, by, bz) = bmax, (bs_lvl,) * 3
            tiled = True

        ck = np.zeros((P, nb, bx, by, bz))
        ce = np.zeros((P, nb, bx, by, bz))
        nn = (bx + 1) * (by + 1) * (bz + 1)
        nidx = np.full((P, nb, nn), pm.n_node_loc, dtype=np.int32)
        origin = np.zeros((P, nb, 3), dtype=np.int64)
        n_cells = np.zeros(P, dtype=np.int64)
        II, JJ, KK = np.meshgrid(np.arange(bx + 1), np.arange(by + 1),
                                 np.arange(bz + 1), indexing="ij")
        lat_nodes = np.stack([II, JJ, KK], axis=-1).reshape(-1, 3)  # (nn, 3)
        for p in range(P):
            e = per_part[p]
            n_cells[p] = len(e)
            if not len(e):
                continue
            if tiled:
                uniq, binv = blocks[p]
                blk_origin = np.stack([uniq >> 42, (uniq >> 21) & ((1 << 21) - 1),
                                       uniq & ((1 << 21) - 1)],
                                      axis=-1) * bs_lvl       # (B_p, 3)
                c = lat[p] - blk_origin[binv]
            else:
                blk_origin = lat[p].min(axis=0)[None]          # (1, 3)
                binv = np.zeros(len(e), dtype=np.int64)
                c = lat[p] - blk_origin[0]
            B_p = len(blk_origin)
            origin[p, :B_p] = blk_origin
            ck[p, binv, c[:, 0], c[:, 1], c[:, 2]] = model.ck[e]
            ce[p, binv, c[:, 0], c[:, 1], c[:, 2]] = model.ce[e]
            # node lattice -> local node ids (missing / non-local -> pad),
            # vectorized over this part's blocks
            g = (blk_origin[:, None, :] + lat_nodes[None]) * s   # (B_p, nn, 3)
            keys = (g[..., 0] + sy * g[..., 1] + sz * g[..., 2]).reshape(-1)
            kpos = np.searchsorted(node_keys, keys)
            kpos_c = np.minimum(kpos, len(node_keys) - 1)
            is_node = node_keys[kpos_c] == keys
            # global node id or -1 (key_order maps sorted pos -> node id)
            gnid = np.where(is_node, key_order[kpos_c], -1)
            loc_gids = pm.node_gid[p, : pm.nnode_p[p]]  # sorted
            lpos = np.searchsorted(loc_gids, np.where(gnid < 0, 0, gnid))
            lpos_c = np.minimum(lpos, len(loc_gids) - 1)
            is_loc = is_node & (loc_gids[lpos_c] == gnid)
            nidx[p, :B_p] = np.where(is_loc, lpos_c, pm.n_node_loc) \
                .astype(np.int32).reshape(B_p, nn)
        levels.append(LevelGrid(size=s, nb=nb, bx=bx, by=by, bz=bz,
                                origin=origin, ck=ck, ce=ce,
                                nidx=nidx, n_cells=n_cells))

    if merge and len(levels) > 1:
        # one block batch for the whole octree (size=0 marks the merged
        # multi-size batch; per-cell sizes live on in nidx/ck).  Slot
        # order after concatenation equals the level-order flattening
        # CombineMaps uses, so the maps below see identical numbering.
        cat = lambda attr: np.concatenate(
            [getattr(lv, attr) for lv in levels], axis=1)
        levels = [LevelGrid(
            size=0, nb=sum(lv.nb for lv in levels),
            bx=levels[0].bx, by=levels[0].by, bz=levels[0].bz,
            origin=cat("origin"), ck=cat("ck"), ce=cat("ce"),
            nidx=cat("nidx"),
            n_cells=np.sum([lv.n_cells for lv in levels], axis=0))]

    return HybridPartition(
        pm=pm,
        levels=levels,
        brick_Ke=np.asarray(lib["Ke"], np.float64),
        brick_diag=np.asarray(lib["diagKe"], np.float64),
        brick_Se=(np.asarray(lib["Se"], np.float64)
                  if lib.get("Se") is not None else None),
        combine=build_combine_maps(levels, pm.n_node_loc, P),
    )


def partition_env_knobs() -> Dict[str, object]:
    """Every env knob ``partition_hybrid`` consumes at PARTITION time,
    resolved by the module that owns the defaults.  Cache keys
    (solver/driver.py ``_partition_cached``) must consume THIS dict, not
    copy the defaults: a default change here must re-key cached
    partitions, never silently serve the old layout."""
    return {
        "block": int(os.environ.get("PCG_TPU_HYBRID_BLOCK", "8")),
        "merge": os.environ.get("PCG_TPU_HYBRID_MERGE", "0") == "1",
        "kd": combine_kd(),
        "combine": hybrid_combine_mode(),
    }


def combine_kd() -> int:
    """Dense width of the gather-combine (slots gathered for EVERY node
    before falling to the heavy-node residual): PCG_TPU_HYBRID_KD."""
    kd = int(os.environ.get("PCG_TPU_HYBRID_KD", "2"))
    if kd < 1:
        raise ValueError(f"PCG_TPU_HYBRID_KD must be >= 1, got {kd}")
    return kd


def hybrid_combine_mode() -> str:
    """The PCG_TPU_HYBRID_COMBINE knob, validated — ``gather`` (default:
    partition-time-composed per-node source indices, scatter-free) or
    ``scatter`` (the vmap'd at[].add row scatter)."""
    mode = os.environ.get("PCG_TPU_HYBRID_COMBINE", "gather")
    if mode not in ("gather", "scatter"):
        raise ValueError("PCG_TPU_HYBRID_COMBINE must be gather|scatter, "
                         f"got {mode!r}")
    return mode


def build_combine_maps(levels: List[LevelGrid], n_node_loc: int,
                       P: int) -> Optional[CombineMaps]:
    """Sort every level's lattice slots by target node and compose direct
    per-node source indices (see CombineMaps).  All host-side numpy, one
    argsort over the concatenated slot count per part."""
    if not levels:
        return None
    KD = combine_kd()
    nslot = [lv.nb * (lv.bx + 1) * (lv.by + 1) * (lv.bz + 1)
             for lv in levels]
    Ns = int(np.sum(nslot))
    # slot id = position in the level-order concatenation = plain range
    slots_all = np.arange(Ns, dtype=np.int64)
    gidx = np.full((P, n_node_loc, KD), Ns, dtype=np.int64)
    starts_l, lens_l, ss_l = [], [], []
    ke_max = 0
    h_max = 0
    for p in range(P):
        tgt = np.concatenate([lv.nidx[p].reshape(-1) for lv in levels]) \
            .astype(np.int64)
        real = tgt < n_node_loc
        order = np.argsort(tgt[real], kind="stable")
        t_s = tgt[real][order]
        s_s = slots_all[real][order]
        starts = np.searchsorted(t_s, np.arange(n_node_loc, dtype=np.int64))
        lens = np.diff(np.append(starts, len(t_s)))
        for k in range(KD):
            sel = lens > k
            gidx[p, sel, k] = s_s[starts[sel] + k]
        starts_l.append(starts)
        lens_l.append(lens)
        ss_l.append(s_s)
        ke_max = max(ke_max, int(lens.max(initial=0)) - KD)
        h_max = max(h_max, int((lens > KD).sum()))
    KE = max(ke_max, 0)
    hnode = np.full((P, h_max), n_node_loc, dtype=np.int64)
    hgidx = np.full((P, h_max, KE), Ns, dtype=np.int64)
    for p in range(P):
        heavy = np.where(lens_l[p] > KD)[0]
        hnode[p, :len(heavy)] = heavy
        for k in range(KE):
            sel = lens_l[p][heavy] > KD + k
            hgidx[p, :len(heavy), k][sel] = \
                ss_l[p][starts_l[p][heavy[sel]] + KD + k]
    return CombineMaps(n_slots=Ns, gidx=gidx.astype(np.int32),
                       hnode=hnode.astype(np.int32),
                       hgidx=hgidx.astype(np.int32))


def device_data_hybrid(hp: HybridPartition, dtype=jnp.float64) -> dict:
    d = device_data(hp.pm, dtype)
    d["levels"] = [{
        "ck": jnp.asarray(lv.ck, dtype),
        "ce": jnp.asarray(lv.ce, dtype),
        "nidx": jnp.asarray(lv.nidx, jnp.int32),
    } for lv in hp.levels]
    d["brick_Ke"] = jnp.asarray(hp.brick_Ke, dtype)
    d["brick_diag"] = jnp.asarray(hp.brick_diag, dtype)
    if hp.brick_Se is not None:
        d["brick_Se"] = jnp.asarray(hp.brick_Se, dtype)
    if hp.combine is not None:
        d["combine"] = {
            "gidx": jnp.asarray(hp.combine.gidx),
            "hnode": jnp.asarray(hp.combine.hnode),
            "hgidx": jnp.asarray(hp.combine.hgidx),
        }
    return d


# corner offsets in the brick type's node order (== models/element.py
# HEX_CORNERS == _slot_layout(0)'s corner order, asserted in tests)
_CORNERS = np.array([[0, 0, 0], [1, 0, 0], [1, 1, 0], [0, 1, 0],
                     [0, 0, 1], [1, 0, 1], [1, 1, 1], [0, 1, 1]],
                    dtype=np.int64)


@dataclasses.dataclass(frozen=True)
class HybridOps(Ops):
    """General Ops over the transition blocks + dense level-grid stencils
    for the brick cells of each refinement level."""

    # static (nb, bx, by, bz) per level — shapes must be trace-constants
    level_dims: tuple = ()
    # run the f32 level stencils through the fused Pallas plane-march
    # kernel (ops/pallas_matvec.py) — same kernel as the structured backend
    use_pallas: bool = False
    # per-level kernel eligibility (part*block batch within the launch
    # cap), resolved at construction so the trace-time dispatch agrees
    # with hybrid_pallas_enabled's probe
    pallas_levels: tuple = ()
    # run the kernel through the Pallas interpreter (CI on CPU exercises
    # the real solver->kernel dispatch; SolverConfig.pallas='interpret')
    pallas_interpret: bool = False
    # XLA stencil formulation, PINNED at construction (checkpoint
    # fingerprints record it — see parallel/structured.py)
    form: str = "gse"
    # level-combine strategy, PINNED at construction: "gather" (composed
    # per-node source indices, scatter-free) or "scatter" (row scatter)
    combine: str = "gather"

    def __post_init__(self):
        from pcg_mpi_solver_tpu.parallel.structured import VALID_FORMS

        if self.form not in VALID_FORMS:
            raise ValueError(
                f"form must be one of {VALID_FORMS}, got {self.form!r}")
        if self.combine not in ("gather", "scatter"):
            raise ValueError("combine must be gather|scatter, "
                             f"got {self.combine!r}")

    @classmethod
    def from_hybrid(cls, hp: HybridPartition, dot_dtype=jnp.float64,
                    axis_name=None,
                    precision=jax.lax.Precision.HIGHEST,
                    use_pallas=False, n_local_parts=1, form=None,
                    combine=None, pallas_interpret=False):
        from pcg_mpi_solver_tpu.parallel.structured import matvec_form

        pm = hp.pm
        if combine is None:
            combine = hybrid_combine_mode()
        if hp.combine is None:
            combine = "scatter"     # no maps built (no levels)
        return cls(n_loc=pm.n_loc, n_iface=pm.n_iface,
                   n_node_loc=pm.n_node_loc, n_node_iface=pm.n_node_iface,
                   dot_dtype=dot_dtype, axis_name=axis_name,
                   precision=precision,
                   use_node_ell=pm.ell is not None,
                   level_dims=tuple((lv.nb, lv.bx, lv.by, lv.bz)
                                    for lv in hp.levels),
                   use_pallas=use_pallas, pallas_interpret=pallas_interpret,
                   pallas_levels=tuple(
                       use_pallas
                       and n_local_parts * lv.nb <= PALLAS_BATCH_CAP
                       for lv in hp.levels),
                   form=form if form is not None else matvec_form(),
                   combine=combine)

    # -- level-grid primitives -----------------------------------------
    def _rows_pad(self, x):
        """x (P, n_loc) -> zero-padded node rows (P*(n_node_loc+1), 3)."""
        Pn = x.shape[0]
        x3 = x.reshape(Pn, self.n_node_loc, 3)
        return jnp.concatenate(
            [x3, jnp.zeros((Pn, 1, 3), x3.dtype)], axis=1
        ).reshape(Pn * (self.n_node_loc + 1), 3)

    def _level_gather(self, x3p, lv, dims, Pn):
        """Node-lattice gather: (P*nb, 3, bx+1, by+1, bz+1) block batch."""
        nb, bx, by, bz = dims
        nr = self.n_node_loc + 1
        offs = (jnp.arange(Pn, dtype=jnp.int32) * nr)[:, None, None]
        g = jnp.take(x3p, (lv["nidx"] + offs).reshape(-1), axis=0,
                     mode="clip")
        g = g.reshape(Pn * nb, bx + 1, by + 1, bz + 1, 3)
        return g.transpose(0, 4, 1, 2, 3)

    def _level_scatter_add(self, y, grid, lv, dims, Pn):
        """Adds (P*nb, 3, bx+1, by+1, bz+1) block-batch node-grid values
        into y (P, n_loc).  Block-boundary lattice nodes appear in every
        adjacent block's lattice; the row scatter accumulates them."""
        rows = self._grid_rows(grid, Pn)
        y3 = y.reshape(Pn, self.n_node_loc, 3)
        y3 = jax.vmap(
            lambda yp, idx, r: yp.at[idx].add(r, mode="drop")
        )(y3, lv["nidx"].reshape(Pn, -1), rows)
        return y3.reshape(Pn, self.n_loc)

    def _combined_gather_add(self, y, rows_levels, data, Pn):
        """Scatter-free combine: add every level's lattice-slot rows into
        y (P, n_loc) through the partition-composed slot->node maps
        (CombineMaps; measured rationale in docs/BENCH_LOG.md "hybrid row
        traffic").  ``rows_levels``: per-level (P, n_slots_l, w) arrays in
        level order; w is the row width (3 for matvec/diag)."""
        cm = data["combine"]
        w = rows_levels[0].shape[-1]
        rows = jnp.concatenate(rows_levels, axis=1)
        rows = jnp.concatenate(
            [rows, jnp.zeros((Pn, 1, w), rows.dtype)], axis=1)  # pad row
        take = jax.vmap(lambda rp, gi: jnp.take(rp, gi, axis=0))
        acc = None
        for k in range(cm["gidx"].shape[-1]):
            t = take(rows, cm["gidx"][:, :, k])
            acc = t if acc is None else acc + t
        y3 = y.reshape(Pn, self.n_node_loc, w) + acc
        if cm["hnode"].shape[1]:
            hacc = None
            for k in range(cm["hgidx"].shape[-1]):
                t = take(rows, cm["hgidx"][:, :, k])
                hacc = t if hacc is None else hacc + t
            y3 = jax.vmap(
                lambda yp, idx, r: yp.at[idx].add(r, mode="drop")
            )(y3, cm["hnode"], hacc)
        return y3.reshape(Pn, -1)

    def _use_gather(self, data) -> bool:
        """ONE eligibility rule for the gather-combine across every
        consumer method (matvec, diag, node blocks, nodal averaging)."""
        return (self.combine == "gather" and "combine" in data
                and bool(data["levels"]))

    @staticmethod
    def _grid_rows(grid, Pn):
        """(P*nb, w, bx+1, by+1, bz+1) block-batch grid -> (P, slots, w)
        rows in the CombineMaps slot order."""
        w = grid.shape[1]
        return grid.transpose(0, 2, 3, 4, 1).reshape(Pn, -1, w)

    def _stencil(self, Ke, ck, xg, pallas_ok=False):
        """Structured brick matvec on one level grid (same formulations
        as parallel/structured.py: slice gather -> einsum -> sum of
        padded translates, the fusion-friendly corner form when
        ``self.form == "corner"`` — pinned at construction, the env knob
        is not re-read — or the fused Pallas kernel when this level is
        flagged eligible in ``pallas_levels``)."""
        if pallas_ok and np.dtype(xg.dtype) == np.float32:
            from pcg_mpi_solver_tpu.ops.pallas_matvec import (
                batched_structured_matvec)

            return batched_structured_matvec(
                xg, ck, Ke, interpret=self.pallas_interpret)
        if self.form == "corner":
            from pcg_mpi_solver_tpu.parallel.structured import (
                corner_matvec_grid)

            return corner_matvec_grid(Ke, ck, xg)
        bx, by, bz = ck.shape[1], ck.shape[2], ck.shape[3]
        if self.form == "gsplit":
            from pcg_mpi_solver_tpu.parallel.structured import (
                gsplit_matvec_grid)

            v = gsplit_matvec_grid(Ke, ck, xg, self.precision)
        else:
            slots = [xg[:, :, dx:dx + bx, dy:dy + by, dz:dz + bz]
                     for dx, dy, dz in _CORNERS]
            u = jnp.concatenate(slots, axis=1)         # (P, 24, cells)
            v = jnp.einsum("de,pexyz->pdxyz", Ke, ck[:, None] * u,
                           precision=self.precision)
        terms = []
        for a, (dx, dy, dz) in enumerate(_CORNERS):
            terms.append(jnp.pad(
                v[:, 3 * a:3 * a + 3],
                ((0, 0), (0, 0), (dx, 1 - dx), (dy, 1 - dy), (dz, 1 - dz))))
        y = terms[0]
        for t in terms[1:]:
            y = y + t
        return y

    # -- operator protocol ---------------------------------------------
    def matvec_local(self, data, x):
        if x.ndim == 3:
            # RHS-block axis (Ops.matvec contract): the level-grid
            # gather/stencil/scatter machinery runs on flat vectors, so
            # the block batches with vmap (the inherited iface_assemble
            # handles the 3-D case natively — still ONE psum).
            return jax.vmap(lambda xc: self.matvec_local(data, xc),
                            in_axes=-1, out_axes=-1)(x)
        Pn = x.shape[0]
        if data["blocks"]:
            y = Ops.matvec_local(self, data, x)
        else:
            y = self._apply_springs(data, x, jnp.zeros_like(x))
        if data["levels"]:
            x3p = self._rows_pad(x)
            pal = self.pallas_levels or (False,) * len(data["levels"])
            use_gather = self._use_gather(data)
            rows_levels = []
            for lv, dims, pok in zip(data["levels"], self.level_dims, pal):
                xg = self._level_gather(x3p, lv, dims, Pn)
                ck = lv["ck"].reshape((Pn * dims[0],) + lv["ck"].shape[2:])
                yg = self._stencil(data["brick_Ke"], ck, xg, pallas_ok=pok)
                if use_gather:
                    rows_levels.append(self._grid_rows(yg, Pn))
                else:
                    y = self._level_scatter_add(y, yg, lv, dims, Pn)
            if use_gather:
                y = self._combined_gather_add(y, rows_levels, data, Pn)
        return y

    def diag_local(self, data):
        Pn = data["weight"].shape[0]
        if data["blocks"]:
            y = Ops.diag_local(self, data)
        else:
            y = self._apply_springs_diag(
                data, jnp.zeros((Pn, self.n_loc), data["weight"].dtype))
        use_gather = self._use_gather(data)
        rows_levels = []
        for lv, dims in zip(data["levels"], self.level_dims):
            ck = lv["ck"].reshape((Pn * dims[0],) + lv["ck"].shape[2:])
            dk = data["brick_diag"]
            terms = []
            for a, (dx, dy, dz) in enumerate(_CORNERS):
                contrib = dk[3 * a:3 * a + 3][None, :, None, None, None] \
                    * ck[:, None]
                terms.append(jnp.pad(
                    contrib,
                    ((0, 0), (0, 0), (dx, 1 - dx), (dy, 1 - dy),
                     (dz, 1 - dz))))
            g = terms[0]
            for t in terms[1:]:
                g = g + t
            if use_gather:
                rows_levels.append(self._grid_rows(g, Pn))
            else:
                y = self._level_scatter_add(y, g, lv, dims, Pn)
        if use_gather:
            y = self._combined_gather_add(y, rows_levels, data, Pn)
        return y

    def _node_block_local(self, data):
        """Transition-block node blocks (general path) + brick-cell corner
        blocks pad-translated onto each level's node grid."""
        if data["blocks"]:
            y = Ops._node_block_local(self, data)
        else:
            Pl = data["weight"].shape[0]
            y = self._springs_into_blocks(
                data, jnp.zeros((Pl, self.n_node_loc, 9),
                                data["weight"].dtype))
        from pcg_mpi_solver_tpu.ops.precond import corner_block_field

        use_gather = self._use_gather(data)
        rows_levels = []
        for lv, dims in zip(data["levels"], self.level_dims):
            Pn = lv["ck"].shape[0]
            ck = lv["ck"].reshape((Pn * dims[0],) + lv["ck"].shape[2:])
            g = corner_block_field(data["brick_Ke"], ck, _CORNERS)
            if use_gather:
                rows_levels.append(self._grid_rows(g, Pn))
            else:
                rows = self._grid_rows(g, Pn)
                y = jax.vmap(
                    lambda yp, idx, r: yp.at[idx].add(r, mode="drop")
                )(y, lv["nidx"].reshape(Pn, -1), rows)
        if use_gather:
            Pn = y.shape[0]
            y = self._combined_gather_add(
                y.reshape(Pn, -1), rows_levels, data, Pn
            ).reshape(Pn, self.n_node_loc, 9)
        return y

    # -- export protocol (strain + nodal averaging over blocks + levels) --
    def elem_strain(self, data, x):
        out = Ops.elem_strain(self, data, x) if data["blocks"] else []
        Pn = x.shape[0]
        if data["levels"]:
            if "brick_Se" not in data:
                raise ValueError("strain export unavailable: the brick "
                                 "element library has no Se strain mode")
            x3p = self._rows_pad(x)
            for lv, dims in zip(data["levels"], self.level_dims):
                xg = self._level_gather(x3p, lv, dims, Pn)
                nb, bx, by, bz = dims
                slots = [xg[:, :, dx:dx + bx, dy:dy + by, dz:dz + bz]
                         for dx, dy, dz in _CORNERS]
                u = jnp.concatenate(slots, axis=1)
                ce = lv["ce"].reshape((Pn * nb,) + lv["ce"].shape[2:])
                eps = jnp.einsum("sd,pdxyz->psxyz", data["brick_Se"],
                                 ce[:, None] * u,
                                 precision=self.precision)
                # (P*nb, 6, cells) -> (P, 6, nb*cells): per-part cell
                # order stays aligned with elem_scale/nodal_average
                eps = eps.reshape(Pn, nb, 6, -1).transpose(0, 2, 1, 3)
                out.append(eps.reshape(Pn, 6, -1))
        return out

    def elem_scale(self, data):
        out = Ops.elem_scale(self, data) if data["blocks"] else []
        for lv in data["levels"]:
            Pn = lv["ck"].shape[0]
            out.append((lv["ck"] * lv["ce"]).reshape(Pn, -1))
        return out

    def nodal_average(self, data, vals_list):
        """Blocks + levels -> averaged nodal field.  vals_list aligns with
        elem_strain/elem_scale output order: blocks first, then levels."""
        nb = len(data["blocks"])
        k = vals_list[0].shape[1]
        Pl = vals_list[0].shape[0]
        dt = vals_list[0].dtype
        sums = jnp.zeros((Pl, k, self.n_node_loc), dt)
        counts = jnp.zeros((Pl, 1, self.n_node_loc), dt)

        def scat(s, ids, c):
            return s.at[:, ids].add(c, mode="drop")

        for blk, vals in zip(data["blocks"], vals_list[:nb]):
            node = blk["node"]
            nn = node.shape[1]
            ids = node.reshape(Pl, -1)
            contrib = jnp.broadcast_to(
                vals[:, :, None, :], (Pl, k, nn, vals.shape[2])
            ).reshape(Pl, k, -1)
            ones = jnp.ones((Pl, 1, nn * vals.shape[2]), dt)
            sums = jax.vmap(scat)(sums, ids, contrib)
            counts = jax.vmap(scat)(counts, ids, ones)

        # ONE pack/unpack for the (sums, counts) <-> (P, n_node_loc, k+1)
        # row layout, shared by the gather and scatter combine branches
        def pack():
            return jnp.concatenate([sums, counts], axis=1).transpose(0, 2, 1)

        def unpack(joined):
            j = joined.transpose(0, 2, 1)
            return j[:, :k], j[:, k:]

        use_gather = self._use_gather(data)
        rows_levels = []
        for lv, dims, vals in zip(data["levels"], self.level_dims,
                                  vals_list[nb:]):
            lnb, bx, by, bz = dims
            vg = vals.reshape(Pl, k, lnb, bx, by, bz) \
                .transpose(0, 2, 1, 3, 4, 5).reshape(Pl * lnb, k, bx, by, bz)
            # valid-cell mask: holes (ck == 0) must not count
            valid = (lv["ck"].reshape(Pl * lnb, bx, by, bz) != 0) \
                .astype(dt)[:, None]
            both = jnp.concatenate([vg * valid, valid], axis=1)
            terms = []
            for dx, dy, dz in _CORNERS:
                terms.append(jnp.pad(
                    both, ((0, 0), (0, 0), (dx, 1 - dx), (dy, 1 - dy),
                           (dz, 1 - dz))))
            g = terms[0]
            for t in terms[1:]:
                g = g + t                       # (P*nb, k+1, node grid)
            rows = self._grid_rows(g, Pl)
            if use_gather:
                rows_levels.append(rows)
                continue
            joined = jax.vmap(
                lambda jp, idx, r: jp.at[idx].add(r, mode="drop")
            )(pack(), lv["nidx"].reshape(Pl, -1), rows)
            sums, counts = unpack(joined)
        if use_gather and rows_levels:
            joined = self._combined_gather_add(
                pack().reshape(Pl, -1), rows_levels, data, Pl
            ).reshape(Pl, self.n_node_loc, k + 1)
            sums, counts = unpack(joined)

        both = jnp.concatenate([sums, counts], axis=1)
        both = self.niface_assemble(data, both)
        return both[:, :k] / (both[:, k:] + 1e-15)
