"""Benchmark entry point (driver-invoked): prints ONE JSON line
{"metric", "value", "unit", "vs_baseline"}.  Implementation lives in the
package so the installed `pcg-tpu bench` subcommand shares it."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from pcg_mpi_solver_tpu.bench import main

if __name__ == "__main__":
    main()
