"""Post-wave v9 hardware A/B: measure the dot-built-gather kernel on the
deployed toolchain and, if it beats the engaged XLA gse form, capture a
v9-engaged flagship bench line.

Written 2026-08-01 after the first live window showed the DEPLOYED
terminal Mosaic rejects v6/v8 (concat lane-offset mismatch) while the
build-host chipless pipeline accepts them; v9 removes the rejected
construct class (docs/BENCH_LOG.md).  This queue runs AFTER
tools/hw_wave5.py so the two cannot contend for the device grant.

Steps:
  1. matvec A/B, v9 only, at the 150^3 flagship — the first hardware
     compile AND first hardware execution of any kernel in the family.
  2. ONLY IF v9 compiled and beat gse: flagship bench with the v9
     kernel engaged (PCG_TPU_PALLAS_V=9, pallas=auto probes it) so the
     salvage file carries the better line for the round-end driver.

Usage: python tools/hw_v9_ab.py [--deadline-min 240]
"""

from __future__ import annotations

import argparse
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.hw_session import log_line, run_step, start_queue  # noqa: E402


def _parse_ab(path, marker):
    """(gse_ms, v9_ms or None) from the A/B step's log section.

    Never raises: an unreadable log or a missing marker (the step died
    before writing its section header) is an anomaly of ONE step, and it
    must not abort the remaining independent steps of a scarce hardware
    window — log it and report (None, None), which downstream treats as
    "v9 produced no number"."""
    try:
        text = open(path).read()
        sect = text[text.rindex(marker):]
    except (OSError, ValueError) as e:
        try:
            log_line(path, f"v9 A/B parse anomaly ({type(e).__name__}: "
                           f"{e}) — treating as no-measurement")
        except OSError:
            print(f"v9 A/B parse anomaly ({type(e).__name__}: {e})",
                  flush=True)         # the log file itself is the anomaly
        return None, None
    gse = re.search(r"xla \(gse\):\s+([0-9.]+) ms/matvec", sect)
    v9 = re.search(r"pallas v9 C=8:\s+([0-9.]+) ms/matvec", sect)
    return (float(gse.group(1)) if gse else None,
            float(v9.group(1)) if v9 else None)


_AB_STEP = "matvec A/B v9"


def run_v9_ab(path):
    """A/B step + parse; returns (gse_ms, v9_ms).  Shared with
    tools/hw_wave6.py so the scarce-window sequence exists once."""
    run_step(path, _AB_STEP, ["examples/bench_matvec.py", "150"],
             env_extra={"BENCH_MATVEC_VARIANTS": "v9"}, timeout=2400)
    # the trailing colon+space anchors the STEP line — run_step also
    # appends a "... done: rc=..." line a bare prefix would rindex
    gse_ms, v9_ms = _parse_ab(path, f"=== {_AB_STEP}: ")
    log_line(path, f"v9 A/B parse: gse={gse_ms} ms, v9={v9_ms} ms")
    return gse_ms, v9_ms


def maybe_engage_flagship(path, gse_ms, v9_ms):
    """Run the v9-engaged flagship bench only on a measured win; log a
    reason that distinguishes compile-rejection from a perf loss."""
    if v9_ms is None:
        log_line(path, "v9 did not produce a hardware number "
                       "(compile rejection or runtime failure) — "
                       "no engaged flagship run")
        return False
    if gse_ms is not None and v9_ms >= gse_ms:
        log_line(path, f"v9 measured {v9_ms} ms but does NOT beat gse "
                       f"({gse_ms} ms) — no engaged flagship run")
        return False
    # dead-tunnel steps must not re-emit salvage as fresh; a LIVE line
    # still WRITES salvage for the round-end driver (bench.py:_write_salvage
    # is unconditional)
    run_step(path, "flagship (v9 engaged)", ["bench.py"],
             env_extra={"BENCH_SALVAGE": "0", "BENCH_CPU_UPGRADE": "0",
                        "PCG_TPU_PALLAS_V": "9",
                        "BENCH_WALL_BUDGET_S": "3480"},
             timeout=3600, force_gate=True)
    return True


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--deadline-min", type=float, default=240)
    ap.add_argument("--log", default=os.path.join("docs", "HW_SESSION.log"))
    args = ap.parse_args()
    path = start_queue("hw_v9_ab", args.deadline_min, args.log)
    gse_ms, v9_ms = run_v9_ab(path)
    maybe_engage_flagship(path, gse_ms, v9_ms)
    log_line(path, "hw_v9_ab complete")


if __name__ == "__main__":
    main()


# smoke: python - <<'EOF'
# import tools.hw_v9_ab as m
# open('/tmp/ablog','w').write(
#     "x\n=== matvec A/B v9: ...\nxla (gse):      13.741 ms/matvec\n"
#     "pallas v9 C=8:    3.2 ms/matvec  (vs xla  4.29x, maxrelerr 1e-07)\n"
#     "=== matvec A/B v9 done: rc=0 (98s)\n")
# assert m._parse_ab('/tmp/ablog', '=== matvec A/B v9: ') == (13.741, 3.2)
# EOF
