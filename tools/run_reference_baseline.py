"""Run the REFERENCE implementation single-rank on a synthetic model and
measure its per-(dof x iteration) cost — the honest benchmark baseline.

OpenMPI/mpi4py cannot be installed here, so the reference cannot run
8-rank; instead its own code runs rank-0-of-1 under tools/mpi_shim (a
single-rank mpi4py stand-in) through its full pipeline:

    read_input_model.py -> run_metis.py 1 (N=1 shortcut, no METIS)
    -> partition_mesh.py 1 0 -> pcg_solver.py <run> <speedtest>

on an MDF archive written by this framework's write_mdf (the schema
round-trips both ways).  The reference repo is never written to: a
staging directory holds a `src` symlink and the `__pycache__` config
files its CWD-relative paths expect.

Prints ONE JSON line: the reference's iterations/relres/flag, wall-clock
calc time, and ns per dof-iteration — plus, when --compare is given,
this framework's CPU solve of the SAME MDF model at the same tolerance
(cross-implementation parity: iteration counts should agree to ~1).

Usage:
    python tools/run_reference_baseline.py [--model cube|octree] [--n 24]
        [--tol 1e-7] [--scratch DIR] [--speedtest 0|1] [--compare]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import shutil
import subprocess
import sys
import time
import zlib

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REFERENCE = os.environ.get("PCG_REFERENCE_PATH", "/root/reference")
SHIM = os.path.join(REPO, "tools", "mpi_shim")


def make_stage(scratch):
    """Create <scratch>/stage with a ``src`` symlink to the CURRENT
    reference checkout, unlinking a stale link left by an earlier run
    against a different PCG_REFERENCE_PATH (a reused --scratch must
    never silently run the wrong oracle)."""
    stage = os.path.join(scratch, "stage")
    os.makedirs(stage, exist_ok=True)
    link = os.path.join(stage, "src")
    target = os.path.join(REFERENCE, "src")
    if os.path.lexists(link):
        if os.path.islink(link) and os.readlink(link) != target:
            os.unlink(link)        # stale link from an earlier reference
    if not os.path.lexists(link):
        os.symlink(target, link)
    return stage


def _run(stage, argv, env, ranks=1):
    t0 = time.perf_counter()
    if ranks > 1:
        # real N-process run through the multi-rank shim's mpiexec
        tools_dir = os.path.normpath(os.path.join(SHIM, os.pardir))
        if tools_dir not in sys.path:
            sys.path.insert(0, tools_dir)
        from mpi_shim.mpiexec import launch

        rc, outputs = launch([sys.executable] + argv, ranks=ranks,
                             cwd=stage, env=env, timeout=3600)
        dt = time.perf_counter() - t0
        if rc != 0:
            tails = "\n".join(f"[rank {r}] {line}"
                              for r, out in enumerate(outputs)
                              for line in out.strip().splitlines()[-12:])
            raise RuntimeError(
                f"reference stage {argv[0]} failed at {ranks} ranks "
                f"(rc={rc}):\n{tails}")
        return dt, outputs[0]
    proc = subprocess.run([sys.executable] + argv, cwd=stage, env=env,
                          capture_output=True, text=True, timeout=3600)
    dt = time.perf_counter() - t0
    if proc.returncode != 0:
        raise RuntimeError(
            f"reference stage {argv[0]} failed (rc={proc.returncode}):\n"
            f"{proc.stdout}\n{proc.stderr}")
    return dt, proc.stdout


def _parse_vtu(path):
    """Generic VTK-XML appended-raw parser: both the reference's vendored
    evtk and this framework's writer use an 8-byte size prefix per block;
    attribute order differs, so attrs are matched individually."""
    import re

    with open(path, "rb") as f:
        raw = f.read()
    head, _, tail = raw.partition(b'<AppendedData encoding="raw">')
    data = tail.split(b"_", 1)[1]
    dtypes = {"Float64": np.float64, "Float32": np.float32,
              "Int64": np.int64, "Int32": np.int32, "UInt64": np.uint64,
              "UInt32": np.uint32, "UInt8": np.uint8, "Int8": np.int8}
    out = {}
    for m in re.finditer(rb"<DataArray\b[^>]*>", head):
        attrs = dict(re.findall(r'(\w+)="([^"]*)"', m.group(0).decode()))
        if attrs.get("format") != "appended":
            continue
        off = int(attrs["offset"])
        nbytes = int(np.frombuffer(data[off:off + 8], np.uint64)[0])
        arr = np.frombuffer(data[off + 8:off + 8 + nbytes],
                            dtypes[attrs["type"]])
        ncomp = int(attrs.get("NumberOfComponents", 1))
        out[attrs["Name"]] = arr.reshape(-1, ncomp) if ncomp > 1 else arr
    return out


def _compare_vtu_exports(stage, env, ref_scratch, model, store,
                         mode="Full"):
    """Run the reference's export_vtk AND this framework's exporter (on
    the already-written ``store`` of the --compare solve); compare the
    .vtu geometry and the U point field.  Returns a dict of diffs."""
    _run(stage, ["src/data/export_vtk.py", "1", "U", mode], env)
    pattern = os.path.join(ref_scratch, "Results_Run1", "VTKs", "*.vtu")
    ref_vtus = sorted(
        glob.glob(pattern),
        key=lambda p: int(p.rsplit("_", 1)[1][:-len(".vtu")]))
    if not ref_vtus:
        raise RuntimeError(f"reference export produced no .vtu at {pattern}")

    from pcg_mpi_solver_tpu.vtk.export import export_vtk

    our_vtus = export_vtk(model, store, ["U"], mode)

    ref_raw = _parse_vtu(ref_vtus[-1])
    our_raw = _parse_vtu(our_vtus[-1])
    ref = _canon_vtu(ref_raw)
    ours = _canon_vtu(our_raw)

    # face sets keyed by node COORDINATES (the reference's Boundary mode
    # renumbers points to the used subset; ours keeps all points — the
    # geometry, not the numbering, must agree); raw cell counts catch
    # duplicated-cell regressions the set comparison alone would dedup away
    missing_pts = [p for p in ref["u_at"] if p not in ours["u_at"]]
    u_d = 0.0
    scale = max((abs(v) for rows in ref["u_at"].values()
                 for u in rows for v in u), default=0.0) or 1e-30
    for p, rows in ref["u_at"].items():
        if p in ours["u_at"]:
            # coincident duplicate nodes (cohesive interfaces) compare as
            # sorted multisets of displacement rows
            for a, b in zip(sorted(rows), sorted(ours["u_at"][p])):
                u_d = max(u_d, max(abs(x - y) for x, y in zip(a, b)))
    out = {
        "ref_file": os.path.basename(ref_vtus[-1]),
        "n_cells_ref": len(np.asarray(ref_raw["offsets"])),
        "n_cells_ours": len(np.asarray(our_raw["offsets"])),
        "n_faces_ref": len(ref["faces"]),
        "n_faces_ours": len(ours["faces"]),
        "faces_match": ref["faces"] == ours["faces"],
        "points_missing_in_ours": len(missing_pts),
        "u_max_rel_diff": u_d / scale,
    }
    if mode in ("Full", "Delaunay"):
        # Full/Delaunay renumber nothing on either side (and Delaunay is
        # the same deterministic qhull run on the same coordinates): the
        # arrays must be BYTE-identical, not just geometry-equal
        our_pts = our_raw.get("points", our_raw.get("Points"))
        out["points_max_abs_diff"] = float(
            np.abs(np.asarray(ref_raw["points"], float)
                   - np.asarray(our_pts, float)).max())
        out["connectivity_max_diff"] = int(
            np.abs(np.asarray(ref_raw["connectivity"], np.int64)
                   - np.asarray(our_raw["connectivity"], np.int64)).max())
        out["offsets_max_diff"] = int(
            np.abs(np.asarray(ref_raw["offsets"], np.int64)
                   - np.asarray(our_raw["offsets"], np.int64)).max())
    return out


def _canon_vtu(arrays):
    """Geometry-canonical view of a parsed VTU: faces as frozensets of
    node-coordinate tuples, and the U field keyed by coordinates (a LIST
    of rows per coordinate: cohesive-interface models carry coincident
    duplicate nodes with distinct displacements)."""
    pts = np.asarray(arrays.get("points", arrays.get("Points")), float)
    conn = np.asarray(arrays["connectivity"], np.int64)
    offs = np.asarray(arrays["offsets"], np.int64)
    u = np.asarray(arrays["U"], float)
    faces = set()
    start = 0
    for end in offs:
        faces.add(frozenset(map(tuple, pts[conn[start:int(end)]])))
        start = int(end)
    u_at = {}
    for p, row in zip(pts, u):
        u_at.setdefault(tuple(p), []).append(tuple(row))
    return {"faces": faces, "u_at": u_at}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=24,
                    help="cells per edge of the cube model (base cells for "
                         "--model octree)")
    ap.add_argument("--model", choices=["cube", "octree"], default="cube",
                    help="octree: 2:1-graded mesh with multiple pattern "
                         "types and sign vectors — the reference's actual "
                         "problem class")
    ap.add_argument("--level", type=int, default=2,
                    help="octree max refinement level (deeper grading -> "
                         "more simultaneous edge+face hanging-node pattern "
                         "types; level 4 with --incl 8 produces 170+ "
                         "distinct types, the reference's <=144-type "
                         "regime, partition_mesh.py:1074)")
    ap.add_argument("--incl", type=int, default=2,
                    help="octree inclusion count (refinement seeds)")
    ap.add_argument("--tol", type=float, default=1e-7)
    ap.add_argument("--scratch", default=None)
    ap.add_argument("--speedtest", type=int, default=1,
                    help="reference SpeedTestFlag (1 disables its exports "
                         "for clean timing — the reference's own method)")
    ap.add_argument("--ranks", type=int, default=1,
                    help="run the reference MULTI-RANK: run_metis builds a "
                         "real k-way dual-graph partition (mgmetis stand-in "
                         "backed by the framework's C++ partitioner), "
                         "partition_mesh runs at min(4, ranks) workers and "
                         "pcg_solver at RANKS workers (1 rank = 1 partition) "
                         "through the multi-rank mpi_shim — exercising the "
                         "reference's neighbor discovery, halo exchange and "
                         "shared-memory windows as an oracle")
    ap.add_argument("--compare", action="store_true",
                    help="also solve the same MDF with this framework "
                         "(CPU) and report iteration parity")
    ap.add_argument("--export-compare", action="store_true",
                    help="additionally run the reference's export_vtk AND "
                         "this framework's VTK exporter on their own solve "
                         "results and compare the .vtu content (implies "
                         "--compare; requires --speedtest 0)")
    ap.add_argument("--export-mode", nargs="+",
                    choices=["Full", "Boundary", "MidSlices", "Delaunay"],
                    default=["Full"],
                    help="export mode(s) for --export-compare, all served "
                         "from the ONE solve (Boundary exercises the "
                         "reference's PolysFlat incidence selection, "
                         "MidSlices its per-face plane loop, Delaunay its "
                         "point-cloud tetrahedralization — export_vtk.py:"
                         "178-215, NO geometric filtering on either side — "
                         "vs this framework's vectorized selections)")
    args = ap.parse_args()
    if args.export_compare:
        args.compare = True
        if args.speedtest == 1:
            ap.error("--export-compare needs --speedtest 0 (exports on)")

    import tempfile

    scratch = args.scratch or tempfile.mkdtemp(prefix="refbase_")
    stage = make_stage(scratch)

    sys.path.insert(0, REPO)
    from pcg_mpi_solver_tpu.models import make_cube_model
    from pcg_mpi_solver_tpu.models.mdf import write_mdf

    n = args.n
    t0 = time.perf_counter()
    if args.model == "octree":
        from pcg_mpi_solver_tpu.models.octree import make_octree_model

        model = make_octree_model(n, n, n, max_level=args.level,
                                  n_incl=args.incl, seed=3,
                                  E=30e9, nu=0.2, load="traction",
                                  load_value=1e6)
    else:
        model = make_cube_model(n, n, n, E=30e9, nu=0.2, load="traction",
                                load_value=1e6, heterogeneous=True)
    mdf_dir = os.path.join(scratch, "mdf")
    write_mdf(model, mdf_dir)
    archive = shutil.make_archive(os.path.join(scratch, "cube"), "zip",
                                  mdf_dir)
    print(f"# model: {model.n_elem} elems / {model.n_dof} dofs "
          f"(gen+mdf {time.perf_counter()-t0:.1f}s)", file=sys.stderr,
          flush=True)

    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [SHIM, stage] + env.get("PYTHONPATH", "").split(os.pathsep))
    env.pop("JAX_PLATFORMS", None)   # reference is numpy-only
    ref_scratch = os.path.join(scratch, "ref_scratch")

    ranks = args.ranks
    if ranks > 1 and ranks % 4 != 0:
        # the reference hardcodes 4 loading ranks (partition_mesh.py:1409
        # asserts multi-rank worker counts are multiples of 4)
        ap.error(f"--ranks must be 1 or a multiple of 4, got {ranks}")
    part_workers = 1 if ranks == 1 else min(4, ranks)

    stages = {}
    stages["ingest"], _ = _run(stage, [
        "src/data/read_input_model.py", stage, "cube", ref_scratch,
        archive], env)
    stages["metis"], _ = _run(stage, ["src/solver/run_metis.py",
                                      str(ranks)], env)
    stages["partition"], _ = _run(stage, [
        "src/solver/partition_mesh.py", str(ranks), "0"], env,
        ranks=part_workers)

    # GlobSettings in the reference's schema (run_basic_script.bash:30-49)
    import pickle

    settings = {
        "TimeHistoryParam": {"ExportFlag": True, "ExportFrmRate": 1,
                             "ExportFrms": [], "PlotFlag": False,
                             "TimeStepDelta": [0, 1], "ExportVars": "U"},
        "SolverParam": {"Tol": args.tol, "MaxIter": 10000},
    }
    with open(os.path.join(stage, "__pycache__", "GlobSettings.zpkl"),
              "wb") as f:
        f.write(zlib.compress(pickle.dumps(settings)))

    stages["solve"], out = _run(stage, [
        "src/solver/pcg_solver.py", "1", str(args.speedtest)], env,
        ranks=ranks)
    print("# reference solver output tail:", file=sys.stderr)
    for line in out.strip().splitlines()[-8:]:
        print(f"#   {line}", file=sys.stderr)

    # the reference appends _SpeedTest only for flag EXACTLY 1
    # (pcg_solver.py:62 `if SpeedTestFlag == 1`)
    suffix = "_SpeedTest" if args.speedtest == 1 else ""
    pattern = os.path.join(ref_scratch, f"Results_Run1{suffix}",
                           "PlotData", "*_TimeData.npz")
    td_files = glob.glob(pattern)
    if not td_files:
        raise RuntimeError(f"reference produced no TimeData at {pattern}")
    td = np.load(td_files[0], allow_pickle=True)["TimeData"].item()
    iters = int(np.asarray(td["Iter"]).ravel()[-1])
    relres = float(np.asarray(td["RelRes"]).ravel()[-1])
    flag = int(np.asarray(td["Flag"]).ravel()[-1])
    calc_s = float(td["Mean_CalcTime"])
    ns_per_dof_iter = calc_s / (model.n_dof * max(iters, 1)) * 1e9

    result = {
        "reference": {
            "n_dof": model.n_dof, "iters": iters, "relres": relres,
            "flag": flag, "calc_s": round(calc_s, 3),
            "comm_wait_s": round(float(td["Mean_CommWaitTime"]), 3),
            "ns_per_dof_iter": round(ns_per_dof_iter, 3),
            "stage_s": {k: round(v, 2) for k, v in stages.items()},
            "ranks": ranks,
            "how": (f"reference code, {ranks} real processes via the "
                    "multi-rank tools/mpi_shim" if ranks > 1 else
                    "reference code, single rank via tools/mpi_shim"),
        },
    }

    if args.compare:
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
        from pcg_mpi_solver_tpu import (RunConfig, SolverConfig,
                                        TimeHistoryConfig)
        from pcg_mpi_solver_tpu.models.mdf import read_mdf
        from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
        from pcg_mpi_solver_tpu.solver import Solver

        m2 = read_mdf(os.path.join(ref_scratch, "ModelData", "MDF"))
        cfg = RunConfig(scratch_path=os.path.join(scratch, "ours"),
                        solver=SolverConfig(tol=args.tol, max_iter=10000),
                        time_history=TimeHistoryConfig(
                            time_step_delta=[0.0, 1.0]))
        s = Solver(m2, cfg, mesh=make_mesh(1), n_parts=1)
        store = None
        if args.export_compare:
            # solve WITH frame exports so the VTU comparison reuses this
            # solve instead of paying a second one
            from pcg_mpi_solver_tpu.utils.io import RunStore

            store = RunStore(cfg.result_path, cfg.model_name)
            r = s.solve(store=store)[-1]
        else:
            r = s.step(1.0)
        result["this_framework_cpu"] = {
            "iters": r.iters, "relres": r.relres, "flag": r.flag,
            "backend": s.backend,
            "iters_delta_vs_reference": r.iters - iters,
        }

        if args.speedtest != 1:
            # Solution-vector parity via the reference's OWN export:
            # global u from its final U frame + Dof map
            # (pcg_solver.py:869,201).
            rv = os.path.join(ref_scratch, "Results_Run1", "ResVecData")

            def read_mpidat(name):
                md = np.load(os.path.join(rv, name + "_metadat.npy"),
                             allow_pickle=True).item()
                # slice to the recorded element count (the shim's File.Open
                # keeps MPI no-truncate semantics, so a reused scratch may
                # leave stale tail bytes from a larger earlier run)
                n = int(np.sum(md["NfData"]))
                return np.fromfile(os.path.join(rv, name + ".mpidat"),
                                   dtype=md["DTypeData"][0])[:n]

            frames = sorted(
                glob.glob(os.path.join(rv, "U_*.mpidat")),
                key=lambda p: int(
                    os.path.basename(p)[2:-len(".mpidat")]))
            if not frames:
                raise RuntimeError(f"reference exported no U frames in {rv}")
            u_ref = np.zeros(m2.n_dof)
            u_ref[read_mpidat("Dof")] = read_mpidat(
                os.path.basename(frames[-1])[:-len(".mpidat")])
            # elementwise relative difference, with a 1e-6*max floor so
            # near-zero dofs can't divide the metric to infinity
            scale = np.maximum(np.abs(u_ref), 1e-6 * np.abs(u_ref).max())
            rel = np.abs(s.displacement_global() - u_ref) / scale
            result["this_framework_cpu"]["solution_max_rel_diff"] = float(
                rel.max())

        if args.export_compare:
            result["vtu_parity"] = {
                mode: _compare_vtu_exports(stage, env, ref_scratch, m2,
                                           store, mode)
                for mode in args.export_mode}

    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
