"""Nonlocal-stress ORACLE: the reference's own ``config_NonlocalNeighbours``
(partition_mesh.py:1000-1299) vs this framework's ``ops/nonlocal_stress.py``
on the same model.

The reference's nonlocal path is latently broken in this snapshot (the
``NonLocStressParam`` MatProp parsing is commented out,
partition_mesh.py:515-523 — see tools/ref_nonlocal_wrapper.py), so the
wrapper injects exactly what that parser would have produced and otherwise
runs the reference's unmodified main sequence with ``ExportNonLocalStress=1``
under the multi-rank mpi_shim — exercising its nonlocal AABB broadcast,
element-id Isend/Recv exchanges, per-element box search, Gaussian weight
build and per-partition csr assembly as an oracle.

Comparison: the reference's per-partition ``NLSpWeightMatrix`` rows are
composed into a GLOBAL (n_elem x n_elem) csr via each partition's
``ElemIdVector`` (rows) and ``NL_ElemIdVec`` (columns) and compared against
this framework's global row-normalized operator — same sparsity pattern,
values to float tolerance.

Prints ONE JSON line; exits nonzero on mismatch.

Usage: python tools/run_reference_nonlocal.py [--n 8] [--ranks 4]
"""

import argparse
import json
import os
import pickle
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.run_reference_baseline import (  # noqa: E402
    REFERENCE, REPO, SHIM, _run, make_stage)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=8, help="cube cells per edge")
    ap.add_argument("--ranks", type=int, default=4,
                    help="partition workers (1 or a multiple of 4); >1 "
                         "exercises the reference's nonlocal Isend/Recv "
                         "element-id exchanges across real processes")
    ap.add_argument("--parts", type=int, default=4,
                    help="mesh partitions (N_parts)")
    ap.add_argument("--lc", type=float, nargs=2, default=[2.3, 1.7],
                    help="per-material nonlocal length Lc (defaults picked "
                         "so Ko*max(Lc) is not an exact centroid distance — "
                         "boundary-tie behavior at the box surface is not "
                         "part of the parity contract)")
    ap.add_argument("--scratch", default=None)
    args = ap.parse_args()
    if args.ranks != 1 and (args.ranks % 4 != 0
                            or args.parts % args.ranks != 0):
        # the reference hardcodes 4 loading ranks and requires workers to
        # divide N_parts (partition_mesh.py:39-40,1409) — fail at argparse
        # instead of deep inside an N-process shim run
        ap.error(f"--ranks must be 1, or a multiple of 4 dividing --parts "
                 f"(got ranks={args.ranks}, parts={args.parts})")

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from pcg_mpi_solver_tpu.models import make_cube_model
    from pcg_mpi_solver_tpu.models.mdf import read_mdf, write_mdf
    from pcg_mpi_solver_tpu.ops.nonlocal_stress import build_nonlocal_weights

    scratch = args.scratch or tempfile.mkdtemp(prefix="refnl_")
    stage = make_stage(scratch)

    t0 = time.perf_counter()
    model = make_cube_model(args.n, args.n, args.n, E=30e9, nu=0.2,
                            load="traction", load_value=1e6,
                            heterogeneous=True, seed=7)
    for mp, lc in zip(model.mat_prop, args.lc):
        mp["NonLocStressParam"] = {"Lc": float(lc)}
    mdf_dir = os.path.join(scratch, "mdf")
    write_mdf(model, mdf_dir)
    archive = shutil.make_archive(os.path.join(scratch, "cube"), "zip",
                                  mdf_dir)
    print(f"# model: {model.n_elem} elems, Lc={args.lc} "
          f"({time.perf_counter()-t0:.1f}s)", file=sys.stderr, flush=True)

    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [SHIM, stage] + env.get("PYTHONPATH", "").split(os.pathsep))
    env.pop("JAX_PLATFORMS", None)        # reference is numpy-only
    ref_scratch = os.path.join(scratch, "ref_scratch")

    _run(stage, ["src/data/read_input_model.py", stage, "cube",
                 ref_scratch, archive], env)
    _run(stage, ["src/solver/run_metis.py", str(args.parts)], env)
    dump = os.path.join(scratch, "nonlocal_ref.pkl")
    wrapper = os.path.join(REPO, "tools", "ref_nonlocal_wrapper.py")
    dt, _ = _run(stage, [wrapper, str(args.parts), dump], env,
                 ranks=args.ranks)
    print(f"# reference partition+nonlocal: {dt:.1f}s at {args.ranks} "
          f"ranks", file=sys.stderr, flush=True)

    # ---- compose the reference's global operator
    with open(dump, "rb") as f:
        parts = pickle.load(f)
    import scipy.sparse as sp

    n_elem = model.n_elem
    rows, cols, vals = [], [], []
    for p in parts:
        W = p["NLSpWeightMatrix"].tocoo()
        rows.append(np.asarray(p["ElemIdVector"])[W.row])
        cols.append(np.asarray(p["NL_ElemIdVec"])[W.col])
        vals.append(W.data)
    W_ref = sp.csr_matrix(
        (np.concatenate(vals),
         (np.concatenate(rows), np.concatenate(cols))),
        shape=(n_elem, n_elem))

    # ---- this framework's operator on the same model (MDF round-trip,
    # exactly what the reference's partitioner consumed)
    ours = build_nonlocal_weights(read_mdf(mdf_dir))
    W_our = ours.csr

    # ---- compare: sparsity pattern + values
    d = (W_ref - W_our).tocoo()
    max_abs = float(np.abs(d.data).max()) if d.nnz else 0.0
    pat_ref = set(zip(*W_ref.nonzero()))
    pat_our = set(zip(*W_our.nonzero()))
    only_ref = len(pat_ref - pat_our)
    only_our = len(pat_our - pat_ref)
    row_sums = np.asarray(W_our.sum(axis=1)).ravel()
    result = {
        "n_elem": n_elem, "ranks": args.ranks, "parts": args.parts,
        "nnz_ref": int(W_ref.nnz), "nnz_ours": int(W_our.nnz),
        "pattern_only_ref": only_ref, "pattern_only_ours": only_our,
        "max_abs_diff": max_abs,
        "row_normalized": bool(np.allclose(row_sums, 1.0, atol=1e-12)),
    }
    ok = (only_ref == 0 and only_our == 0 and max_abs < 1e-12
          and result["row_normalized"])
    result["parity"] = "PASS" if ok else "FAIL"
    print(json.dumps(result))
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
