#!/usr/bin/env python
"""Telemetry/bench artifact schema lint.

Validates JSON artifacts against the versioned contracts in
``pcg_mpi_solver_tpu/obs/schema.py``:

* ``*.jsonl``          — telemetry event streams (``--telemetry-out``)
* ``BENCH_*.json``     — bench round artifacts (raw line or round wrapper;
                         failed-round wrappers with ``parsed: null`` pass)
* ``bench_*.json``     — provisional/salvage side files written by bench.py

Bench-line ``detail`` carries the warm-path attribution fields
(``setup_s`` / ``time_to_first_iter_s`` numeric-or-null, ``setup_cache``
off/cold/warm — obs/schema.py BENCH_DETAIL_NUMERIC): typed when present,
optional so pre-warm-path committed artifacts stay valid.

Usage::

    python tools/check_telemetry_schema.py [PATH ...]

With no PATH arguments, scans the repository root for committed
``BENCH_*.json`` artifacts (the tier-1 fast check,
tests/test_telemetry_schema.py).  Exits non-zero if any file fails;
prints one line per error.  Import-light on purpose (no jax/numpy): this
runs as a fast lint.
"""

from __future__ import annotations

import glob
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from pcg_mpi_solver_tpu.obs.schema import (          # noqa: E402
    validate_bench_text, validate_jsonl_text)


def default_paths() -> list:
    """The committed artifacts the tier-1 check covers."""
    return sorted(glob.glob(os.path.join(REPO, "BENCH_*.json")))


def check_file(path: str) -> list:
    """Validate one artifact; returns error strings prefixed with path."""
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError as e:
        return [f"{path}: unreadable ({e})"]
    name = os.path.basename(path)
    if name.endswith(".jsonl"):
        errs = validate_jsonl_text(text)
    elif name.endswith(".json"):
        if name.startswith("bench_salvage"):
            # salvage wrapper: {"lines": [{"line": <bench json str>}]}
            errs = []
            try:
                doc = json.loads(text)
            except ValueError as e:
                errs = [f"not JSON ({e})"]
            else:
                for i, entry in enumerate(doc.get("lines", [])):
                    errs.extend(
                        f"lines[{i}]: {e}"
                        for e in validate_bench_text(entry.get("line", "")))
        else:
            errs = validate_bench_text(text)
    else:
        errs = [f"unrecognized artifact type (expected .json/.jsonl)"]
    return [f"{path}: {e}" for e in errs]


def main(argv=None) -> int:
    paths = list(argv if argv is not None else sys.argv[1:]) or \
        default_paths()
    if not paths:
        print("check_telemetry_schema: no artifacts to check")
        return 0
    errors = []
    for p in paths:
        errors.extend(check_file(p))
    for e in errors:
        print(e, file=sys.stderr)
    n = len(paths)
    if errors:
        print(f"check_telemetry_schema: {len(errors)} error(s) in {n} "
              f"file(s)", file=sys.stderr)
        return 1
    print(f"check_telemetry_schema: {n} file(s) OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
