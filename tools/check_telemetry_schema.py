#!/usr/bin/env python
"""Telemetry/bench artifact schema lint — thin shim over the analysis/
``telemetry-schema`` rule (same CLI, same exit codes).

Validates JSON artifacts against the versioned contracts in
``pcg_mpi_solver_tpu/obs/schema.py``:

* ``*.jsonl``          — telemetry event streams (``--telemetry-out``)
* ``BENCH_*.json``     — bench round artifacts (raw line or round wrapper;
                         failed-round wrappers with ``parsed: null`` pass)
* ``bench_*.json``     — provisional/salvage side files written by bench.py

Implementation: ``pcg_mpi_solver_tpu/analysis/rules_artifacts.py``.

Usage::

    python tools/check_telemetry_schema.py [PATH ...]

With no PATH arguments, scans the repository root for committed
``BENCH_*.json`` artifacts (the tier-1 fast check,
tests/test_telemetry_schema.py).  Exits non-zero if any file fails;
prints one line per error.  Import-light on purpose (no jax/numpy): this
runs as a fast lint.
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from pcg_mpi_solver_tpu.analysis.rules_artifacts import (  # noqa: E402,F401
    check_file, default_paths)


def main(argv=None) -> int:
    paths = list(argv if argv is not None else sys.argv[1:]) or \
        default_paths()
    if not paths:
        print("check_telemetry_schema: no artifacts to check")
        return 0
    errors = []
    for p in paths:
        errors.extend(check_file(p))
    for e in errors:
        print(e, file=sys.stderr)
    n = len(paths)
    if errors:
        print(f"check_telemetry_schema: {len(errors)} error(s) in {n} "
              f"file(s)", file=sys.stderr)
        return 1
    print(f"check_telemetry_schema: {n} file(s) OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
