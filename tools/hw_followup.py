"""Second-wave hardware queue for the 2026-07-30 session (round 3).

Runs the measurements the first wave could not: the v4 Pallas A/B (i32
fix landed mid-session), the true f64-direct flagship anchor, the
combine-variant row microbench, and the octree flagship on the NEW
gather-combine path.  Same probe/retry + step isolation as
tools/hw_session.py.

Usage: python tools/hw_followup.py [--deadline-min 120]
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.hw_session import log_line, run_step  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--deadline-min", type=float, default=120)
    ap.add_argument("--log", default=os.path.join("docs", "HW_SESSION.log"))
    args = ap.parse_args()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(repo, args.log)

    from pcg_mpi_solver_tpu.bench import _probe_with_retry

    log_line(path, f"hw_followup start (deadline {args.deadline_min:.0f} min)")
    ok, detail = _probe_with_retry(budget_s=args.deadline_min * 60,
                                   probe_timeout_s=600)
    if not ok:
        log_line(path, f"deadline reached; no followup session ({detail})")
        sys.exit(3)
    log_line(path, f"accelerator ANSWERED: {detail}")

    run_step(path, "matvec A/B v4", ["examples/bench_matvec.py", "150"],
             timeout=2400)
    run_step(path, "f64 direct anchor", ["bench.py"],
             env_extra={"BENCH_MODE": "direct", "BENCH_DTYPE": "float64"},
             timeout=3600)
    run_step(path, "combine variants", ["examples/bench_gather.py"],
             timeout=1800)
    run_step(path, "octree flagship (gather combine)", ["bench.py"],
             env_extra={"BENCH_MODEL": "octree"}, timeout=5400)
    log_line(path, "hw_followup complete")


if __name__ == "__main__":
    main()
