"""Static per-iteration collective-count check for the PCG loop body —
thin shim over the analysis/ subsystem (same CLI, same exit codes).

The fused variant's entire value claim (ISSUE 5) is "ONE scalar-
reduction psum per iteration", and the batched multi-RHS claim (ISSUE 6)
is "psum count independent of the block width"; the proof traces the
loop bodies to jaxprs on a 2-part CPU mesh and counts the ``psum``
primitives.  The implementation (and the documented counts, now DERIVED
from the budget table next to ``Ops.comm_estimate``) lives in
``pcg_mpi_solver_tpu/analysis/collectives.py``; the wider per-program
proof — every variant x nrhs x backend, plus ppermute budgets — is the
analysis/ ``collective-budget`` rule (``pcg-tpu lint``).

Usage: python tools/check_collectives.py     (exit 0 = counts hold)
Tier-1: tests/test_collectives.py runs the same checks in-process.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Standalone runs mirror the test rig (tests/conftest.py): CPU backend,
# virtual multi-device mesh so psums are real collectives.  Must be set
# before jax initializes; a no-op when pytest's conftest already did it.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_ENABLE_X64", "1")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

from pcg_mpi_solver_tpu.analysis.collectives import (  # noqa: E402,F401
    EXPECTED_BODY_PSUMS, count_psums, iteration_psum_count, run_checks)


def main() -> int:
    errs = run_checks()
    for variant, want in EXPECTED_BODY_PSUMS.items():
        print(f"{variant}: {want} psum(s) in the while-loop body "
              f"(single-RHS and batched) {'OK' if not errs else ''}")
    if errs:
        for e in errs:
            print(f"FAIL: {e}")
        return 1
    print("collective counts hold (fused saves 2 psums/iteration; "
          "batched bodies match nrhs=1)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
