"""Static per-iteration collective-count check for the PCG loop body.

The fused variant's entire value claim (ISSUE 5) is "ONE scalar-reduction
psum per iteration"; this check traces both variants' ``lax.while_loop``
bodies to a jaxpr on a 2-part CPU mesh and counts the ``psum``
primitives, so a collective regression — an accidentally serialized
extra reduction sneaking back into the hot body — fails CI instead of a
scarce hardware window.

Documented counts (2 parts => the matvec's interface-assembly psum is
present; both conditional branches of the body, including the deferred
mode-1 true-residual check, are part of the traced body jaxpr):

* classic: 5 — interface assembly + the rho/inf-prec fused psum + p.q
  + the fused 3-norm + the deferred check's true-residual norm
* fused:   3 — interface assembly + THE single fused reduction (rho,
  mu, ||r||, ||p||, ||x||, inf flag in one psum) + the deferred check's
  true-residual norm

Per healthy iteration (mode-0 trip) that is 3+1 collectives classic vs
1+1 fused — the claim ``Ops.comm_estimate`` gauges advertise.

The same proof extends to the batched multi-RHS body (solver/pcg.py
``pcg_many``): its psum count must be INDEPENDENT of the RHS-block
width — widening the block widens psum payloads, never the collective
count (the ISSUE-6 headline claim).  ``iteration_psum_count(variant,
nrhs=8)`` traces the blocked body and must equal the nrhs=1 count for
both variants.

Usage: python tools/check_collectives.py     (exit 0 = counts hold)
Tier-1: tests/test_collectives.py runs the same checks in-process.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Standalone runs mirror the test rig (tests/conftest.py): CPU backend,
# virtual multi-device mesh so psums are real collectives.  Must be set
# before jax initializes; a no-op when pytest's conftest already did it.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_ENABLE_X64", "1")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

EXPECTED_BODY_PSUMS = {"classic": 5, "fused": 3}


def _sub_jaxprs(eqn):
    """Nested jaxprs of one equation (while/cond/pjit/custom_* params),
    unwrapping ClosedJaxpr."""
    out = []
    for v in eqn.params.values():
        for item in (v if isinstance(v, (list, tuple)) else [v]):
            j = getattr(item, "jaxpr", item)
            if hasattr(j, "eqns"):
                out.append(j)
    return out


def count_psums(jaxpr) -> int:
    """Recursive ``psum`` primitive count of a jaxpr (into conds etc.)."""
    n = 0
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "psum":
            n += 1
        for j in _sub_jaxprs(eqn):
            n += count_psums(j)
    return n


def _while_bodies(jaxpr, out):
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "while":
            out.append(eqn.params["body_jaxpr"].jaxpr)
        for j in _sub_jaxprs(eqn):
            _while_bodies(j, out)
    return out


def iteration_psum_count(variant: str, nrhs: int = 1) -> int:
    """Psum count of the traced PCG while-loop body for ``variant`` on a
    2-part partition (so the interface-assembly psum exists).  With
    ``nrhs`` > 1 the BATCHED body (``pcg_many``) is traced instead —
    the documented counts must hold unchanged (payloads widen with the
    block, the collective count must not)."""
    import jax
    import jax.numpy as jnp

    from pcg_mpi_solver_tpu.models.synthetic import make_cube_model
    from pcg_mpi_solver_tpu.ops.matvec import Ops, device_data
    from pcg_mpi_solver_tpu.parallel.mesh import PARTS_AXIS, make_mesh
    from pcg_mpi_solver_tpu.parallel.partition import partition_model
    from pcg_mpi_solver_tpu.solver.driver import _data_specs
    from pcg_mpi_solver_tpu.solver.pcg import pcg, pcg_many

    model = make_cube_model(3, 3, 3)
    pm = partition_model(model, 2)
    if pm.n_iface == 0:
        raise RuntimeError("2-part partition produced no interface dofs; "
                           "the documented counts assume the iface psum")
    ops = Ops.from_model(pm, dot_dtype=jnp.float64, axis_name=PARTS_AXIS)
    data = device_data(pm, jnp.float64)
    mesh = make_mesh(2)
    P = jax.sharding.PartitionSpec(PARTS_AXIS)

    def step(data, fext, x0, inv_diag):
        solve = pcg_many if nrhs > 1 else pcg
        res = solve(ops, data, fext, x0, inv_diag, tol=1e-8, max_iter=50,
                    glob_n_dof_eff=pm.glob_n_dof_eff, variant=variant)
        return res.x

    fn = jax.shard_map(step, mesh=mesh,
                       in_specs=(_data_specs(data), P, P, P),
                       out_specs=P, check_vma=False)
    shape = ((pm.n_parts, pm.n_loc, nrhs) if nrhs > 1
             else (pm.n_parts, pm.n_loc))
    vec = jnp.zeros(shape, jnp.float64)
    inv = jnp.zeros((pm.n_parts, pm.n_loc), jnp.float64)
    jaxpr = jax.make_jaxpr(fn)(data, vec, vec, inv)
    bodies = _while_bodies(jaxpr.jaxpr, [])
    counts = [count_psums(b) for b in bodies]
    hits = [c for c in counts if c > 0]
    if len(hits) != 1:
        raise RuntimeError(
            f"expected exactly one psum-bearing while body for "
            f"variant={variant!r} nrhs={nrhs}, found counts {counts}")
    return hits[0]


def run_checks(nrhs_batched: int = 8) -> list:
    """Returns a list of error strings (empty = counts hold).  Checks
    both the single-RHS bodies and the batched bodies at
    ``nrhs_batched`` columns: the counts must be equal — psum count
    independent of the RHS-block width."""
    errs = []
    counts = {}
    for variant, want in EXPECTED_BODY_PSUMS.items():
        got = counts[variant] = iteration_psum_count(variant)
        if got != want:
            errs.append(f"{variant}: {got} psums in the loop body, "
                        f"documented count is {want}")
        got_b = iteration_psum_count(variant, nrhs=nrhs_batched)
        if got_b != want:
            errs.append(f"{variant} batched (nrhs={nrhs_batched}): "
                        f"{got_b} psums in the loop body, must equal the "
                        f"nrhs=1 count {want}")
    if not errs and counts["fused"] != counts["classic"] - 2:
        errs.append(f"fused must save exactly the two serialized scalar "
                    f"reductions: classic={counts['classic']} "
                    f"fused={counts['fused']}")
    return errs


def main() -> int:
    errs = run_checks()
    for variant, want in EXPECTED_BODY_PSUMS.items():
        print(f"{variant}: {want} psum(s) in the while-loop body "
              f"(single-RHS and batched) {'OK' if not errs else ''}")
    if errs:
        for e in errs:
            print(f"FAIL: {e}")
        return 1
    print("collective counts hold (fused saves 2 psums/iteration; "
          "batched bodies match nrhs=1)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
