"""Persistent-compile-cache KEY-IDENTITY check: chipless vs remote.

The whole pre-warmed ``.jax_cache`` story (docs/BENCH_LOG.md 2026-07-31)
assumes the remote backend computes the SAME cache key for a program as
the local chipless topology path (``tools/aot_compile_check.py``).  If
the keys differ, every flagship attempt still pays the full remote
compile and the pre-warming was theater — VERDICT r04 weak #4 makes
verifying this the FIRST step of the next hardware session.

Two modes, one marker program (a fixed 64-step tanh-matmul scan — small,
a few seconds to compile, structurally unlike any solver program so it
cannot collide with real entries):

  python tools/cache_key_check.py --seed     # chipless: compile the
        marker via the v5e topology path into .jax_cache and record the
        cache-dir manifest (run on the build host, no tunnel needed)
  python tools/cache_key_check.py            # live session: compile the
        SAME marker on the real backend and report
        CACHE_KEY_MATCH    — no new cache entry appeared (+ fast compile)
        CACHE_KEY_MISMATCH — the remote backend wrote a NEW entry (its
                             key differs; pre-warmed entries are useless
                             remotely — rely on same-session retry
                             caching only and budget flagship steps for
                             cold compiles)

Exit code 0 = match, 4 = mismatch, 1 = error (probe/compile failed).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

CACHE_DIR = os.path.join(REPO, ".jax_cache")
MANIFEST = os.path.join(REPO, ".jax_cache_manifest.json")


def _enable_cache():
    """Returns the EFFECTIVE cache dir (a pre-exported
    JAX_COMPILATION_CACHE_DIR wins — _listing must watch the dir entries
    actually land in, not the default)."""
    import jax

    eff = os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", CACHE_DIR)
    jax.config.update("jax_compilation_cache_dir", eff)
    # the marker compiles in ~1-3 s; without this it may fall under the
    # default 1 s persistence threshold and never be written at all
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    return eff


def _marker_fn(salt):
    """``salt`` (a float folded into the program as a constant) makes each
    SEED's marker a distinct program: a remote compile from an earlier
    seed generation can never be hit by the current check, so a stale
    remotely-keyed entry cannot fake a CACHE_KEY_MATCH."""
    import jax
    import jax.numpy as jnp

    def step(x, _):
        return jnp.tanh(x @ x.T @ x * 0.01 + salt), None

    def fn(x):
        y, _ = jax.lax.scan(step, x, None, length=64)
        return y.sum()

    return fn, (256, 256)


def _listing(cache_dir):
    try:
        return sorted(os.listdir(cache_dir))
    except OSError:
        return []


def seed():
    """Chipless-compile the marker into the persistent cache."""
    cache_dir = _enable_cache()
    import numpy as np
    import jax
    from jax.experimental import topologies
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    before = _listing(cache_dir)
    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name="v5e:2x2")
    mesh = Mesh(np.array(topo.devices)[:1], ("x",))
    s = NamedSharding(mesh, PartitionSpec())
    # fresh salt per seed: derived from the wall clock, recorded in the
    # manifest so check() rebuilds the IDENTICAL program
    salt = round(0.1 + (time.time() % 1000.0) / 8000.0, 9)
    fn, shape = _marker_fn(salt)
    t0 = time.perf_counter()
    jax.jit(fn).lower(
        jax.ShapeDtypeStruct(shape, "float32", sharding=s)).compile()
    wall = time.perf_counter() - t0
    after = _listing(cache_dir)
    new = sorted(set(after) - set(before))
    with open(MANIFEST, "w") as f:
        json.dump({"seeded_at_utc": time.strftime(
                       "%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                   "salt": salt, "marker_entries": new,
                   "all_entries": after, "compile_s": round(wall, 1)}, f,
                  indent=1)
    print(f"seeded: {len(new)} new cache entr{'y' if len(new)==1 else 'ies'} "
          f"in {wall:.1f}s -> {MANIFEST}", flush=True)
    if not new:
        print("WARNING: the fresh-salted marker produced no cache entry — "
              "the persistent cache is not writing; seeding is not "
              "verifiable", flush=True)
        return 1
    return 0


def check():
    """Live session: compile the marker remotely, compare cache entries."""
    cache_dir = _enable_cache()
    from pcg_mpi_solver_tpu.bench import _probe_with_retry

    ok, detail = _probe_with_retry(budget_s=float(
        os.environ.get("BENCH_PROBE_BUDGET_S", 300)), probe_timeout_s=180)
    if not ok:
        print(f"ERROR: accelerator unreachable ({detail})", flush=True)
        return 1
    import jax

    dev = jax.devices()[0]
    print(f"# backend: {dev.platform} {dev.device_kind}", flush=True)
    try:
        with open(MANIFEST) as f:
            man = json.load(f)
    except (OSError, ValueError):
        print("ERROR: no seed manifest — run "
              "`python tools/cache_key_check.py --seed` on the build host "
              "first", flush=True)
        return 1
    missing = [e for e in man.get("marker_entries", [])
               if e not in _listing(cache_dir)]
    if missing:
        print(f"ERROR: seeded marker entries missing from the cache dir "
              f"({missing}) — .jax_cache was cleared since the seed; "
              "re-seed before checking", flush=True)
        return 1
    before = _listing(cache_dir)
    fn, shape = _marker_fn(man["salt"])
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    # mirror the seed's lowering EXACTLY (ShapeDtypeStruct + 1-device
    # NamedSharding): any difference here would test our own call-site
    # divergence, not the backend's key computation
    s = NamedSharding(Mesh(np.array([dev]), ("x",)), PartitionSpec())
    t0 = time.perf_counter()
    jax.jit(fn).lower(
        jax.ShapeDtypeStruct(shape, "float32", sharding=s)).compile()
    wall = time.perf_counter() - t0
    new = sorted(set(_listing(cache_dir)) - set(before))
    print(f"# marker compile {wall:.1f}s; new cache entries: {new}; "
          f"seeded marker entries: {man.get('marker_entries')}", flush=True)
    if new:
        # drop the remotely-keyed marker entries so a re-run of this
        # check (the queues re-run on session recovery) cannot hit them
        # and report a false MATCH
        for e in new:
            try:
                os.remove(os.path.join(cache_dir, e))
            except OSError:
                pass
        print("CACHE_KEY_MISMATCH: the remote backend keyed the marker "
              "differently from the chipless seed — pre-warmed .jax_cache "
              "entries will NOT be hit; budget flagship steps for cold "
              "compiles (same-session retries still hit the entries this "
              "session writes)", flush=True)
        return 4
    # 'no new entry' only means MATCH if this backend's cache WRITES are
    # actually landing where we look — prove it with a second,
    # never-seeded probe program (salt+1).  A silently-failing write
    # (full disk, unwritable dir, redirected path) would otherwise fake
    # the exact 'pre-warming works' verdict this tool exists to refute.
    probe_fn, shape = _marker_fn(man["salt"] + 1.0)
    before2 = _listing(cache_dir)
    jax.jit(probe_fn).lower(
        jax.ShapeDtypeStruct(shape, "float32", sharding=s)).compile()
    probe_new = sorted(set(_listing(cache_dir)) - set(before2))
    for e in probe_new:
        try:
            os.remove(os.path.join(cache_dir, e))
        except OSError:
            pass
    if not probe_new:
        print("CACHE_WRITE_BROKEN: the unseeded probe compile produced no "
              "cache entry — writes are not landing in "
              f"{cache_dir}; the marker's apparent hit proves nothing. "
              "Treat pre-warmed entries as absent.", flush=True)
        return 1
    print("CACHE_KEY_MATCH: remote compile hit the chipless-seeded entry "
          "(and cache writes verified live) — pre-warmed flagship "
          "programs should load in seconds", flush=True)
    return 0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seed", action="store_true")
    args = ap.parse_args()
    sys.exit(seed() if args.seed else check())


if __name__ == "__main__":
    main()
