"""CHIPLESS TPU compile checks: run the full Mosaic/XLA v5e compile
locally, no tunnel, no device.

``jax.experimental.topologies`` + the locally installed libtpu give the
exact compile pipeline the remote terminal uses ("TpuAotCompiler
(chipless)" in its logs) — so Pallas lowering rejections and XLA
buffer-assignment failures that previously burned hardware-session steps
reproduce here in seconds.  Discovered 2026-07-31 after five kernel
variants each died at their first Mosaic-unproven op ON HARDWARE.

Usage:
    python tools/aot_compile_check.py kernel [--variants 6,7] [--nx 150]
    python tools/aot_compile_check.py f64matvec [--nx 150]

``kernel``    — Pallas matvec variants at small + given shape.
``f64matvec`` — the XLA chunked f64 matvec at the given shape (the
                remote-compile failure mode of the f64-direct anchor).
``pcg``       — the FULL f64 PCG while_loop program at the given shape.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _topo_sharding():
    import numpy as np
    import jax
    from jax.experimental import topologies
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name="v5e:2x2")
    mesh = Mesh(np.array(topo.devices)[:1], ("x",))
    return NamedSharding(mesh, PartitionSpec())


def _compile_structs(fn, args, label):
    """Lower+compile against prebuilt ShapeDtypeStruct pytrees."""
    import jax

    t0 = time.perf_counter()
    try:
        jax.jit(fn).lower(*args).compile()
    except Exception as e:                              # noqa: BLE001
        msg = " ".join(str(e).split())[:400]
        print(f"{label}: FAIL {type(e).__name__}: {msg}", flush=True)
        return False
    print(f"{label}: OK ({time.perf_counter()-t0:.1f}s)", flush=True)
    return True


def _compile(fn, shapes_dtypes, sharding, label):
    import jax

    return _compile_structs(
        fn, [jax.ShapeDtypeStruct(s, d, sharding=sharding)
             for s, d in shapes_dtypes], label)


def check_kernel(args):
    import jax.numpy as jnp

    from pcg_mpi_solver_tpu.ops import pallas_matvec as pm

    s = _topo_sharding()
    nx = args.nx
    ok = True
    for v in [int(x) for x in args.variants.split(",")]:
        fn = getattr(pm, "structured_matvec_pallas_v%d" % v
                     if v > 1 else "structured_matvec_pallas")
        for dims in [(8, 6, 5), (nx, nx, nx)]:
            nxn = tuple(d + 1 for d in dims)
            ok &= _compile(
                lambda xg, ck, Ke, f=fn: f(xg, ck, Ke),
                [((3,) + nxn, jnp.float32),
                 (dims, jnp.float32), ((24, 24), jnp.float32)],
                s, f"v{v} {dims}")
    return ok


def check_f64matvec(args):
    import jax.numpy as jnp

    from pcg_mpi_solver_tpu.models import make_cube_model
    from pcg_mpi_solver_tpu.parallel.structured import (
        StructuredOps, partition_structured)

    s = _topo_sharding()
    n = args.nx
    # tiny model just to build ops with the right dims; the compile input
    # shapes are what matter, and they depend only on (nx, ny, nz)
    model = make_cube_model(4, 4, 4)
    sp = partition_structured(model, 1)
    import dataclasses

    ops = dataclasses.replace(
        StructuredOps.from_partition(sp, dot_dtype=jnp.float64),
        nxc=n, ny=n, nz=n)
    nn = n + 1

    def fn(xg_flat, ck, Ke, diag_ke):
        data = {"blocks": [{"ck": ck, "Ke": Ke, "diag_Ke": diag_ke}]}
        return ops.matvec_local(data, xg_flat)

    return _compile(
        fn,
        [((1, 3 * nn * nn * nn), jnp.float64),
         ((1, n, n, n), jnp.float64), ((24, 24), jnp.float64),
         ((24,), jnp.float64)],
        s, f"f64 chunked matvec {n}^3")


def _hybrid_setup(args):
    """Shared setup for the hybrid checks: topology sharding, flagship
    octree partition (cached model), ops + f32 data structs."""
    import jax
    import jax.numpy as jnp

    # topology FIRST (needs the tpu plugin visible), THEN pin the CPU
    # backend so conversions below cannot touch the tunnel
    s = _topo_sharding()
    jax.config.update("jax_platforms", "cpu")

    from pcg_mpi_solver_tpu.bench import cached_model
    from pcg_mpi_solver_tpu.parallel.hybrid import (
        HybridOps, device_data_hybrid, partition_hybrid)

    n0 = args.nx if args.nx is not None else 22   # flagship octree
    model = cached_model("octree", nx0=n0, ny0=n0, nz0=n0,
                         max_level=4, n_incl=6, seed=2, E=30e9, nu=0.2,
                         load="traction", load_value=1e6)
    t0 = time.perf_counter()
    hp = partition_hybrid(model, 1)
    ops = HybridOps.from_hybrid(hp, dot_dtype=jnp.float64,
                                use_pallas=args.pallas == "on")
    data = device_data_hybrid(hp, jnp.float32)
    print(f"# octree {model.n_dof} dofs, {len(hp.levels)} levels "
          f"(partition {time.perf_counter()-t0:.0f}s)", flush=True)
    structs = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s), data)
    return s, hp, ops, structs, n0


def check_hybridcycle(args):
    """Compile the CHUNKED inner-cycle program — the program the bench
    actually compiles at flagship scale (hybrid force-engages the
    chunked path; solver/chunked.py _inner_cycle): warm resumable pcg,
    ONE stencil instantiation in the loop body after the round-4
    restructure."""
    import jax
    import jax.numpy as jnp

    from pcg_mpi_solver_tpu.solver.pcg import cold_carry, pcg

    s, hp, ops, structs, n0 = _hybrid_setup(args)
    n_loc = ops.n_loc

    def fn(data, rhat32, prec32, tol_cycle, carry32, budget):
        res, carry2 = pcg(
            ops, data, rhat32, carry32["x"], prec32,
            tol=tol_cycle, max_iter=jnp.minimum(500, budget),
            glob_n_dof_eff=n_loc, max_iter_nominal=20000,
            carry_in=carry32, return_carry=True, progress_window=0)
        return res.x, carry2, res.flag

    vec = jax.ShapeDtypeStruct((1, n_loc), jnp.float32, sharding=s)
    carry = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
        cold_carry(jnp.zeros((1, n_loc), jnp.float32),
                   jnp.zeros((1, n_loc), jnp.float32),
                   jnp.asarray(1.0, ops.dot_dtype), ops.dot_dtype))
    scal32 = jax.ShapeDtypeStruct((), jnp.float32, sharding=s)
    bud = jax.ShapeDtypeStruct((), jnp.int32, sharding=s)
    return _compile_structs(
        fn, [structs, vec, vec, scal32, carry, bud],
        f"hybrid CHUNKED inner-cycle octree {n0}^3/L4")


def check_hybridamul64(args):
    """Compile the shared out-of-loop f64 hybrid matvec program (driver
    _amul64_fn) — the ONE f64 stencil instantiation the chunked driver
    now pays (was 3: lifting + r0 in _start, plus _refine)."""
    import jax
    import jax.numpy as jnp

    from pcg_mpi_solver_tpu.parallel.hybrid import device_data_hybrid

    s, hp, ops64, _structs32, n0 = _hybrid_setup(args)
    data64 = device_data_hybrid(hp, jnp.float64)
    structs = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
        data64)
    n_loc = ops64.n_loc

    def fn(data, v):
        return data["eff"] * ops64.matvec(data, v)

    vec = jax.ShapeDtypeStruct((1, n_loc), jnp.float64, sharding=s)
    return _compile_structs(fn, [structs, vec],
                            f"hybrid f64 amul octree {n0}^3/L4")


def check_bktamul64(args):
    """Compile the BUCKETED f64 amul at the flagship octree partition
    (PCG_TPU_HYBRID_F64_REFRESH=bucketed): same operator as genamul64
    but with the 200+ per-type structures stacked into a few padded
    buckets — the compile-cost hypothesis is that cost tracks structure
    count (general 1343 s vs stencil 999 s, BENCH_LOG 2026-08-01)."""
    import jax
    import jax.numpy as jnp

    s = _topo_sharding()
    jax.config.update("jax_platforms", "cpu")

    from pcg_mpi_solver_tpu.bench import cached_model
    from pcg_mpi_solver_tpu.ops.matvec import (
        Ops, bucketed_matvec, build_bucketed_blocks, device_data)
    from pcg_mpi_solver_tpu.parallel.partition import partition_model

    n0 = args.nx if args.nx is not None else 22
    model = cached_model("octree", nx0=n0, ny0=n0, nz0=n0,
                         max_level=4, n_incl=6, seed=2, E=30e9, nu=0.2,
                         load="traction", load_value=1e6)
    t0 = time.perf_counter()
    pm = partition_model(model, 1)
    ops = Ops.from_model(pm, dot_dtype=jnp.float64)
    data = device_data(pm, jnp.float64, blocks=False)
    data["buckets"] = build_bucketed_blocks(pm, jnp.float64)
    print(f"# octree {model.n_dof} dofs, {len(pm.type_blocks)} types -> "
          f"{len(data['buckets'])} buckets of T="
          f"{[b['Ke'].shape[0] for b in data['buckets']]} "
          f"(partition {time.perf_counter()-t0:.0f}s)", flush=True)
    structs = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s), data)

    def fn(data, v):
        return data["eff"] * bucketed_matvec(ops, data, v)

    vec = jax.ShapeDtypeStruct((1, pm.n_loc), jnp.float64, sharding=s)
    return _compile_structs(fn, [structs, vec],
                            f"BUCKETED f64 amul octree {n0}^3/L4")


def check_genamul64(args):
    """Compile the GENERAL-form f64 amul at the flagship octree partition
    (PCG_TPU_HYBRID_F64_REFRESH=general, driver _amul64g) — the
    compile-cost alternative to the 999 s stencil amul above (VERDICT
    r04 next #8).  Same elem_part/numbering as the hybrid partition."""
    import jax
    import jax.numpy as jnp

    s = _topo_sharding()
    jax.config.update("jax_platforms", "cpu")

    from pcg_mpi_solver_tpu.bench import cached_model
    from pcg_mpi_solver_tpu.ops.matvec import Ops, device_data
    from pcg_mpi_solver_tpu.parallel.partition import partition_model

    n0 = args.nx if args.nx is not None else 22
    model = cached_model("octree", nx0=n0, ny0=n0, nz0=n0,
                         max_level=4, n_incl=6, seed=2, E=30e9, nu=0.2,
                         load="traction", load_value=1e6)
    t0 = time.perf_counter()
    pm = partition_model(model, 1)
    ops = Ops.from_model(pm, dot_dtype=jnp.float64)
    data = device_data(pm, jnp.float64)
    print(f"# octree {model.n_dof} dofs, {len(pm.type_blocks)} type "
          f"blocks (partition {time.perf_counter()-t0:.0f}s)", flush=True)
    structs = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s), data)

    def fn(data, v):
        return data["eff"] * ops.matvec(data, v)

    vec = jax.ShapeDtypeStruct((1, pm.n_loc), jnp.float64, sharding=s)
    return _compile_structs(fn, [structs, vec],
                            f"GENERAL f64 amul octree {n0}^3/L4")


def check_cubecycle(args):
    """Chunked inner-cycle program for the STRUCTURED (cube) flagship —
    the program bench.py compiles at 150^3 (10.33M dofs > 4M engages the
    chunked path): warm resumable pcg over the slab stencil.  With
    ``--dtype float32 --pallas on`` this is the v6-FUSED chunked cycle,
    which has never been compiled anywhere (round 3 verified the fused
    ONE-SHOT program only)."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from pcg_mpi_solver_tpu.models import make_cube_model
    from pcg_mpi_solver_tpu.parallel.structured import (
        StructuredOps, partition_structured)
    from pcg_mpi_solver_tpu.solver.pcg import cold_carry, pcg

    # topology FIRST, then pin the CPU backend: the cold_carry template
    # below materializes REAL arrays, and an unpinned first array touch
    # initializes the tunneled backend — hanging forever on a dead tunnel
    s = _topo_sharding()
    jax.config.update("jax_platforms", "cpu")
    n = args.nx
    dt = jnp.dtype(args.dtype)
    model = make_cube_model(4, 4, 4)
    sp = partition_structured(model, 1)
    ops = dataclasses.replace(
        StructuredOps.from_partition(sp, dot_dtype=jnp.float64,
                                     use_pallas=args.pallas == "on"),
        nxc=n, ny=n, nz=n)
    nn = n + 1
    n_loc = 3 * nn * nn * nn

    def fn(x, ck, Ke, diag_ke, eff, weight, fext, inv_diag, carry, budget):
        data = {"blocks": [{"ck": ck, "Ke": Ke, "diag_Ke": diag_ke}],
                "eff": eff, "weight": weight}
        res, c2 = pcg(ops, data, fext=fext, x0=carry["x"],
                      inv_diag=inv_diag,
                      tol=1e-5, max_iter=jnp.minimum(500, budget),
                      glob_n_dof_eff=n_loc, max_iter_nominal=20000,
                      carry_in=carry, return_carry=True,
                      progress_window=0)
        return res.x, c2, res.flag

    sds = lambda shape, d: jax.ShapeDtypeStruct(shape, d, sharding=s)
    carry = jax.tree_util.tree_map(
        lambda a: sds(a.shape, a.dtype),
        cold_carry(jnp.zeros((1, n_loc), dt), jnp.zeros((1, n_loc), dt),
                   jnp.asarray(1.0, ops.dot_dtype), ops.dot_dtype))
    shapes = [sds((1, n_loc), dt), sds((1, n, n, n), dt), sds((24, 24), dt),
              sds((24,), dt), sds((1, n_loc), dt), sds((1, n_loc), dt),
              sds((1, n_loc), dt), sds((1, n_loc), dt), carry,
              sds((), jnp.int32)]
    label = (f"{args.dtype} CHUNKED cycle"
             + (" +pallas" if args.pallas == "on" else "") + f" {n}^3")
    return _compile_structs(fn, shapes, label)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("what", choices=["kernel", "f64matvec", "pcg",
                                     "hybridpcg", "hybridcycle",
                                     "hybridamul64", "genamul64",
                                     "bktamul64", "cubecycle"])
    ap.add_argument("--variants", default="6,7")
    ap.add_argument("--nx", type=int, default=None,
                    help="cells per edge (default: 150; hybridpcg: 22 "
                         "octree base cells)")
    ap.add_argument("--dtype", default="float64",
                    help="f64matvec/pcg input dtype")
    ap.add_argument("--pallas", default="off", choices=["off", "on"],
                    help="pcg mode: engage the fused Pallas matvec")
    args = ap.parse_args()
    if args.what in ("pcg", "cubecycle") and args.pallas == "on" \
            and args.dtype != "float32":
        # the pallas dispatch is f32-gated (structured.matvec_local);
        # with f64 inputs the flag would silently validate the XLA path
        ap.error("--pallas on requires --dtype float32")
    if args.nx is None and args.what not in ("hybridpcg", "hybridcycle",
                                             "hybridamul64", "genamul64",
                                             "bktamul64"):
        args.nx = 150
    # never touch the real backend: the topology API needs no client, and
    # an accidental device touch would hang on a wedged tunnel
    os.environ.pop("JAX_PLATFORMS", None)
    if args.what in ("f64matvec", "pcg", "hybridpcg", "hybridcycle",
                     "hybridamul64", "genamul64", "bktamul64", "cubecycle"):
        # without x64, the float64 ShapeDtypeStructs canonicalize to f32
        # and the chunked-path gate (dtype == float64) never engages —
        # the check would silently validate a different program
        import jax

        jax.config.update("jax_enable_x64", True)
    ok = {"kernel": check_kernel, "f64matvec": check_f64matvec,
          "pcg": check_pcg, "hybridpcg": check_hybridpcg,
          "hybridcycle": check_hybridcycle,
          "hybridamul64": check_hybridamul64,
          "genamul64": check_genamul64,
          "bktamul64": check_bktamul64,
          "cubecycle": check_cubecycle}[args.what](args)
    sys.exit(0 if ok else 1)




def check_pcg(args):
    """Compile the FULL PCG while_loop program (matvec + fused dots +
    preconditioner + convergence control) at the given size — the actual
    program whose REMOTE compile failed UNAVAILABLE at 150^3/128^3 f64
    in waves 2-3.  With --dtype float32 --pallas on this is the HEADLINE
    mixed-mode inner program with the fused v6 kernel engaged."""
    import jax.numpy as jnp

    from pcg_mpi_solver_tpu.models import make_cube_model
    from pcg_mpi_solver_tpu.parallel.structured import (
        StructuredOps, partition_structured)
    from pcg_mpi_solver_tpu.solver.pcg import pcg

    s = _topo_sharding()
    n = args.nx
    dt = jnp.dtype(args.dtype)
    model = make_cube_model(4, 4, 4)
    sp = partition_structured(model, 1)
    import dataclasses

    ops = dataclasses.replace(
        StructuredOps.from_partition(sp, dot_dtype=jnp.float64,
                                     use_pallas=args.pallas == "on"),
        nxc=n, ny=n, nz=n)
    nn = n + 1
    n_loc = 3 * nn * nn * nn

    def fn(x, ck, Ke, diag_ke, eff, weight, fext, inv_diag):
        data = {"blocks": [{"ck": ck, "Ke": Ke, "diag_Ke": diag_ke}],
                "eff": eff, "weight": weight}
        r = pcg(ops, data, fext=fext, x0=x, inv_diag=inv_diag,
                tol=1e-7, max_iter=2000, glob_n_dof_eff=n_loc)
        return r.x, r.flag, r.relres, r.iters

    shapes = [((1, n_loc), dt), ((1, n, n, n), dt), ((24, 24), dt),
              ((24,), dt), ((1, n_loc), dt), ((1, n_loc), dt),
              ((1, n_loc), dt), ((1, n_loc), dt)]
    label = (f"{args.dtype} PCG program"
             + (" +pallas" if args.pallas == "on" else "") + f" {n}^3")
    return _compile(fn, shapes, s, label)




def check_hybridpcg(args):
    """Compile the hybrid (octree) f32 PCG program at a REAL graded-octree
    flagship partition — the program whose REMOTE compile failed
    UNAVAILABLE in wave 1 (then under the scatter combine; the gather
    combine is now default).  Builds the real partition (cached model),
    converts the device-data pytree to ShapeDtypeStructs, compiles
    chiplessly."""
    import jax
    import jax.numpy as jnp

    # topology FIRST (needs the tpu plugin visible), THEN pin the CPU
    # backend so the numpy->jnp conversions below cannot touch the
    # tunnel; lowering uses the topology shardings only
    s = _topo_sharding()
    jax.config.update("jax_platforms", "cpu")

    from pcg_mpi_solver_tpu.bench import cached_model
    from pcg_mpi_solver_tpu.parallel.hybrid import (
        HybridOps, device_data_hybrid, partition_hybrid)
    from pcg_mpi_solver_tpu.solver.pcg import pcg

    n0 = args.nx if args.nx is not None else 22   # flagship octree
    model = cached_model("octree", nx0=n0, ny0=n0, nz0=n0,
                         max_level=4, n_incl=6, seed=2, E=30e9, nu=0.2,
                         load="traction", load_value=1e6)
    t0 = time.perf_counter()
    hp = partition_hybrid(model, 1)
    ops = HybridOps.from_hybrid(hp, dot_dtype=jnp.float64,
                                use_pallas=args.pallas == "on")
    data = device_data_hybrid(hp, jnp.float32)
    print(f"# octree {model.n_dof} dofs, {len(hp.levels)} levels "
          f"(partition {time.perf_counter()-t0:.0f}s)", flush=True)

    structs = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s), data)
    n_loc = ops.n_loc

    def fn(data, fext, x0, inv_diag):
        r = pcg(ops, data, fext=fext, x0=x0, inv_diag=inv_diag,
                tol=1e-7, max_iter=2000, glob_n_dof_eff=n_loc)
        return r.x, r.flag, r.relres, r.iters

    vec = jax.ShapeDtypeStruct((1, n_loc), jnp.float32, sharding=s)
    label = (f"hybrid f32 PCG octree {n0}^3/L4"
             + (" +pallas" if args.pallas == "on" else ""))
    return _compile_structs(fn, [structs, vec, vec, vec], label)


if __name__ == "__main__":
    main()
