"""Fourth-wave hardware queue (round 3).

Runs what waves 1-3 still owe:

  1. v6 Pallas A/B — wave 3 pinned v5's failure to DMA slice legality
     (size-1 sublane plane copies); v6 DMAs tile-aligned slabs.  If v6
     lowers, the fused-path headline finally exists.
  2. OCTREE FLAGSHIP retry — wave 1's 5.67M/3.76M-dof octree rungs
     failed REMOTE COMPILE under the then-default scatter combine; the
     gather-combine level assembly (afc29e3) is now the default and is
     both cheaper (no duplicate-row scatter, the measured 88.7 ns/row
     hot spot) and structurally simpler for the compiler.  VERDICT r2
     item 5 ("octree at >=5M dofs") is open until this lands.
  3. Flagship bench with the v6 probe live — if the probe lowers, this
     is the first fused-path headline number.
  4. PLATEAU A/B at 10.33M dofs — the mixed flagship's refinement trace
     burns ~670 stagnation iterations in its first f32 cycle; the
     plateau window (off by default, BENCH_PLATEAU) could cut 15-20%.
     Small-scale A/Bs were null/negative (docs/BENCH_LOG.md 2026-07-31);
     only the at-scale run decides.
  5. Gather/scatter combine variants at flagship fill — the candidate
     scatter replacements added to examples/bench_gather.py after the
     row-traffic isolation.

Same probe/retry + wedged-grant step isolation as tools/hw_session.py.

Usage: python tools/hw_wave4.py [--deadline-min 240]
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.hw_session import log_line, run_step, start_queue  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--deadline-min", type=float, default=240)
    ap.add_argument("--log", default=os.path.join("docs", "HW_SESSION.log"))
    args = ap.parse_args()
    path = start_queue("hw_wave4", args.deadline_min, args.log)

    # 1. v6 Pallas A/B + the gsplit XLA form, both first-time on HW.
    run_step(path, "matvec A/B v6+gsplit",
             ["examples/bench_matvec.py", "150"], timeout=2400)
    # 2. Flagship cube with the v6 probe live (pallas=auto probes v6 now;
    # models come from .bench_cache, saving ~17 s/rung).
    run_step(path, "flagship (v6 probe live)", ["bench.py"], timeout=3600,
             force_gate=True)   # the A/B exits 0 even when every Mosaic
    #                             probe failed and wedged the grant
    # 3. Octree flagship: ladder 22 -> 18 -> 12 (5.67M / 3.76M / 1.3M dofs
    # at level 4) under the gather combine (wave-1 compile fail was under
    # scatter).  VERDICT r2 item 5 is open until this lands.
    run_step(path, "octree flagship (gather combine)", ["bench.py"],
             env_extra={"BENCH_MODEL": "octree"}, timeout=4800,
             force_gate=True)
    # 4. f64-direct TPU anchor (wave 3's ran as CPU fallback: tunnel down).
    run_step(path, "f64 direct anchor 96", ["bench.py"],
             env_extra={"BENCH_MODE": "direct", "BENCH_DTYPE": "float64",
                        "BENCH_NX": "96"},
             timeout=3600, force_gate=True)
    # 5. Per-iteration split at flagship scale (owed since wave 1).
    run_step(path, "iteration breakdown",
             ["examples/bench_iter_breakdown.py", "150"], timeout=2400)
    # 6. Plateau A/B: same flagship cube as the rc=0 headline, window 120
    # (the only setting that was lossless at small scale).  Compare
    # iters/time against the window-0 runs already in the log.
    run_step(path, "flagship plateau=120", ["bench.py"],
             env_extra={"BENCH_PLATEAU": "120"}, timeout=3600)
    # 7. Hybrid per-level split (owed since wave 1).
    run_step(path, "hybrid breakdown",
             ["examples/bench_hybrid_breakdown.py"], timeout=2400)
    # 8. Scatter-replacement candidates at flagship fill.
    run_step(path, "gather/scatter variants", ["examples/bench_gather.py"],
             timeout=2400)
    log_line(path, "hw_wave4 complete")


if __name__ == "__main__":
    main()
