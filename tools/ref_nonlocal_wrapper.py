"""Run the REFERENCE partitioner with its nonlocal-stress path enabled,
instrumented to dump the nonlocal weight structures it computes.

The reference has a LATENT DEFECT here (same class as its never-loaded
``Se.mat`` strain path, SURVEY.md §2c): ``config_ElemMaterial`` has the
``NonLocStressParam`` MatProp parsing commented out
(/root/reference/src/solver/partition_mesh.py:515-523), so running
``partition_mesh.py N 1`` crashes with a KeyError at
``config_NonlocalNeighbours``'s first Lc access (:1018-1019).  This
wrapper executes the reference's OWN main sequence verbatim
(partition_mesh.py:1389-1428) with exactly ONE injection between
``config_ElemMaterial`` and ``config_NonlocalNeighbours``: the
``NonLocStressParam`` dicts read from the model's own ``MatProp.mat`` —
precisely what the commented-out parser would have produced.  Everything
else — neighbor discovery, element-id exchanges, the Gaussian weight
build, the csr assembly — is the reference's unmodified code.

After ``exportMP`` it dumps, per partition, the in-memory
``{ElemIdVector, NL_ElemIdVec, NLSpWeightMatrix}`` (the global column-id
vector ``NL_ElemIdVec`` is NOT in the reference's own export, which only
ships solver-facing local maps) to ``<scratch>/nonlocal_ref.pkl`` for
the parity harness.

Usage (under tools/mpi_shim, cwd = the stage dir with ``src`` symlink):
    python ref_nonlocal_wrapper.py <N_parts> <out_pickle>
"""

import pickle
import sys

import numpy as np
import scipy.io


def main():
    n_parts, out_path = sys.argv[1], sys.argv[2]
    # the reference parses argv itself (initModelData): [prog, N, ExportNL]
    sys.argv = ["partition_mesh.py", n_parts, "1"]

    import importlib.util

    from mpi4py import MPI

    spec = importlib.util.spec_from_file_location(
        "ref_partition_mesh", "src/solver/partition_mesh.py")
    pm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pm)      # __main__-guarded: defs only

    # the reference's main block binds these as module globals
    pm.Comm = MPI.COMM_WORLD
    pm.Rank = pm.Comm.Get_rank()
    pm.N_Workers = pm.Comm.Get_size()

    # ---- the reference's own main sequence (partition_mesh.py:1389-1428)
    GlobData = pm.initModelData()
    pm.Comm.barrier()
    MPGData = {"GlobData": GlobData, "PotentialNbrDataFlag": False}
    pm.extract_Elepart(MPGData)
    pm.extract_PlotSettings(MPGData)
    if pm.N_Workers > 1 and not GlobData["N_MPGs"] % 4 == 0:
        raise Exception("N_Workers must be a multiple of 4")
    pm.extract_ElemMeshData(MPGData)
    pm.Comm.barrier()
    pm.config_ElemVectors(MPGData)
    pm.extract_NodalVectors(MPGData)
    pm.config_TypeGroupList(MPGData)
    pm.config_ElemMaterial(MPGData)

    # ---- the ONE injection: what partition_mesh.py:515-523 would parse
    mat_raw = scipy.io.loadmat(GlobData["MDF_Path"] + "MatProp.mat",
                               struct_as_record=False)["Data"][0]
    for i, mp in enumerate(MPGData["MatProp"]):
        d = mat_raw[i].__dict__
        raw = d["NonLocStressParam"][0]
        nl = {}
        for io in range(len(raw) // 2):
            nl[str(raw[2 * io][0])] = float(raw[2 * io + 1][0][0])
        mp["NonLocStressParam"] = nl
    # (MeshPart['MatProp'] entries are the same dict objects — shared)

    pm.config_ElemLib(MPGData)
    pm.config_IntfcElem(MPGData)
    pm.identify_PotentialNeighbours(MPGData)
    pm.config_Neighbours(MPGData)
    pm.config_NonlocalNeighbours(MPGData)
    pm.exportMP(MPGData)

    # ---- dump the reference-computed nonlocal structures for the harness
    local = [{
        "Id": int(mpart["Id"]),
        "ElemIdVector": np.asarray(mpart["ElemIdVector"]),
        "NL_ElemIdVec": np.asarray(mpart["NL_ElemIdVec"]),
        "NLSpWeightMatrix": mpart["NLSpWeightMatrix"],
    } for mpart in MPGData["MeshPartList"]]
    gathered = pm.Comm.gather(local, root=0)
    if pm.Rank == 0:
        parts = [p for worker in gathered for p in worker]
        with open(out_path, "wb") as f:
            pickle.dump(parts, f)
        print(f">nonlocal wrapper: dumped {len(parts)} partitions")


if __name__ == "__main__":
    main()
