"""``mgmetis.metis`` stand-in: part_mesh_dual with mgmetis's call shape.

mgmetis signature (what the reference calls, run_metis.py:88):

    objval, epart, npart = metis.part_mesh_dual(nparts, cells, vwgt=...)

where ``cells`` is a list of per-element node-id arrays.  Backed by the
framework's C++ multilevel HEM/FM dual-graph partitioner
(pcg_mpi_solver_tpu/native.py part_mesh_dual); falls back to the
pure-numpy dual-graph build + greedy BFS growth if the native library
cannot build.  Not METIS — but a real k-way dual-graph partition with
the same contract (contiguous-ish balanced parts, epart in [0, nparts)).
"""

from __future__ import annotations

import os
import sys

import numpy as np

# tools/mpi_shim/mgmetis -> repo root is three levels up
_REPO = os.path.abspath(os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..", "..", ".."))


def part_mesh_dual(nparts, cells, vwgt=None, ncommon=1, **_kw):
    """Returns (objval, epart, npart) like mgmetis.metis.part_mesh_dual."""
    if _REPO not in sys.path:
        sys.path.insert(0, _REPO)
    from pcg_mpi_solver_tpu import native

    eptr = np.zeros(len(cells) + 1, dtype=np.int64)
    eptr[1:] = np.cumsum([len(c) for c in cells])
    eind = np.concatenate([np.asarray(c, dtype=np.int64) for c in cells])
    n_node = int(eind.max()) + 1 if eind.size else 0

    # one numpy dual-graph build serves both the fallback partition and
    # the edge-cut objval (it is the dominant cost of this function)
    xadj, adjncy = native.build_dual_graph_np(eptr, eind, n_node,
                                              ncommon=int(ncommon))
    epart = native.part_mesh_dual(eptr, eind, n_node, int(nparts),
                                  ncommon=int(ncommon))
    if epart is None:
        epart = _greedy_parts(xadj, adjncy, int(nparts))
    epart = np.asarray(epart, dtype=np.int64)

    # npart (node part map): owner = part of the lowest-id incident element
    npart = np.zeros(n_node, dtype=np.int64)
    seen = np.zeros(n_node, dtype=bool)
    for e in range(len(cells) - 1, -1, -1):
        nodes = eind[eptr[e]:eptr[e + 1]]
        npart[nodes] = epart[e]
        seen[nodes] = True
    npart[~seen] = 0

    # objval: dual-graph edge cut of the produced partition
    objval = int(native.edge_cut(xadj, adjncy, epart))
    return objval, epart, npart


def _greedy_parts(xadj, adjncy, nparts):
    """Balanced BFS region growth over the dual graph (fallback path)."""
    n = len(xadj) - 1
    part = np.full(n, -1, dtype=np.int64)
    target = -(-n // nparts)
    from collections import deque

    next_seed = 0
    for p in range(nparts):
        while next_seed < n and part[next_seed] >= 0:
            next_seed += 1
        if next_seed >= n:
            break
        q = deque([next_seed])
        grown = 0
        while q and grown < target:
            e = q.popleft()
            if part[e] >= 0:
                continue
            part[e] = p
            grown += 1
            for nb in adjncy[xadj[e]:xadj[e + 1]]:
                if part[nb] < 0:
                    q.append(int(nb))
    part[part < 0] = nparts - 1
    return part
