"""mgmetis stand-in for the reference oracle (see metis.py).

The real mgmetis (a METIS binding) is not installable in this image;
this package exposes the one call the reference makes
(``mgmetis.metis.part_mesh_dual``, run_metis.py:88) backed by this
framework's own first-party C++ multilevel dual-graph partitioner
(native/src/partition.cpp) — so the reference's unmodified run_metis.py
produces a genuine k-way dual-graph partition at N > 1.
"""
