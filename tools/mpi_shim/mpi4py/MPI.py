"""The MPI module of the mpi4py shim (see package docstring).

API surface (everything the reference calls):
COMM_WORLD/COMM_SELF with Get_rank/Get_size/barrier/allreduce/gather/
scatter/bcast/Allgather/Split_type/Isend/Recv/isend/recv; SUM;
LONG/DOUBLE/BOOL datatypes; Request.Waitall; Win.Allocate_shared +
Shared_query; File.Open with MODE_* + Write_at/Read_at/Read/Close.

Two transports behind the same surface:

- default: rank 0 of 1 — collectives are identities, point-to-point is
  an in-process mailbox (any send at 1 rank is a self-send);
- MPI_SHIM_SIZE > 1 in the environment (set by tools/mpi_shim/mpiexec.py
  for each spawned rank): real N-process semantics through the router —
  see _multirank.py.
"""

from __future__ import annotations

import copy
import os

import numpy as np

COMM_TYPE_SHARED = 1
MODE_WRONLY = 1
MODE_CREATE = 2
MODE_RDONLY = 4
SUM = "MPI_SUM"


class _Datatype:
    def __init__(self, size):
        self._size = size

    def Get_size(self):
        return self._size


LONG = _Datatype(8)
DOUBLE = _Datatype(8)
BOOL = _Datatype(1)


class _Request:
    def Wait(self):
        return None


class Request:
    @staticmethod
    def Waitall(requests):
        return None


class _Win:
    def __init__(self, nbytes, itemsize):
        self._buf = bytearray(nbytes)
        self._itemsize = itemsize

    def Shared_query(self, rank):
        return self._buf, self._itemsize


class Win:
    @staticmethod
    def Allocate_shared(nbytes, itemsize, comm=None):
        if _MULTI and isinstance(comm, _multirank.MultiComm):
            return _multirank.MultiWin.allocate(int(nbytes), int(itemsize),
                                                comm)
        return _Win(int(nbytes), int(itemsize))


class File:
    def __init__(self, fh):
        self._fh = fh

    @staticmethod
    def Open(comm, name, amode):
        if amode & MODE_WRONLY:
            # MPI semantics: create if needed, do NOT truncate existing.
            # O_CREAT without O_TRUNC is race-free under concurrent Opens
            # from N ranks (an exists()-then-"w+b" check would truncate a
            # file another rank is already writing).
            fh = os.fdopen(os.open(name, os.O_RDWR | os.O_CREAT), "r+b")
        else:
            fh = open(name, "rb")
        return File(fh)

    def Write_at(self, offset, buf):
        self._fh.seek(int(offset))
        self._fh.write(np.ascontiguousarray(buf).tobytes())

    def Read_at(self, offset, buf):
        self._fh.seek(int(offset))
        raw = self._fh.read(buf.nbytes)
        buf[...] = np.frombuffer(raw, dtype=buf.dtype).reshape(buf.shape)

    def Write(self, buf):
        self._fh.write(np.ascontiguousarray(buf).tobytes())

    def Read(self, buf):
        raw = self._fh.read(buf.nbytes)
        buf[...] = np.frombuffer(raw, dtype=buf.dtype).reshape(buf.shape)

    def Close(self):
        self._fh.close()


class _Comm:
    """Rank 0 of 1.  Collectives are identities; point-to-point is a
    tag-keyed in-process mailbox (any send at 1 rank is a self-send)."""

    def __init__(self):
        self._mail = {}

    # -- topology ------------------------------------------------------
    def Get_rank(self):
        return 0

    def Get_size(self):
        return 1

    def Split_type(self, split_type, key=0):
        return self

    # -- sync / collectives -------------------------------------------
    def barrier(self):
        return None

    Barrier = barrier

    def allreduce(self, x, op=None):
        return np.copy(x) if isinstance(x, np.ndarray) else x

    def gather(self, x, root=0):
        return [x]

    def scatter(self, xs, root=0):
        return xs[0]

    def bcast(self, x, root=0):
        return x

    def Allgather(self, sendbuf, recvbuf):
        a = np.ascontiguousarray(sendbuf).ravel()
        np.asarray(recvbuf).ravel()[: a.size] = a

    # -- point-to-point (self-sends only at 1 rank) --------------------
    def Isend(self, buf, dest=0, tag=0):
        self._mail.setdefault(tag, []).append(np.array(buf, copy=True))
        return _Request()

    def Recv(self, buf, source=0, tag=0):
        data = self._mail[tag].pop(0)
        b = np.asarray(buf)
        b[...] = data.reshape(b.shape)

    def isend(self, obj, dest=0, tag=0):
        self._mail.setdefault(tag, []).append(copy.deepcopy(obj))
        return _Request()

    def recv(self, source=0, tag=0):
        return self._mail[tag].pop(0)


_MULTI = int(os.environ.get("MPI_SHIM_SIZE", "1")) > 1
if _MULTI:
    from . import _multirank

    _rank = int(os.environ["MPI_SHIM_RANK"])
    _size = int(os.environ["MPI_SHIM_SIZE"])
    COMM_WORLD = _multirank.MultiComm(_rank, _size)
    COMM_SELF = _Comm()
else:
    COMM_WORLD = _Comm()
    COMM_SELF = _Comm()
