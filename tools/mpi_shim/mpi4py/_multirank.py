"""Multi-rank transport for the mpi4py shim: N real processes, a router.

The reference is an mpiexec-launched SPMD program; this module gives its
unmodified code real N-process semantics without OpenMPI:

- every rank holds one persistent unix-socket connection to a ROUTER
  (a thread in the launcher, tools/mpi_shim/mpiexec.py);
- point-to-point (Isend/Recv/isend/recv) routes pickled payloads through
  per-(comm, dst, src, tag) mailboxes on the router — tagged, FIFO,
  source-explicit, exactly the discipline the reference uses
  (pcg_solver.py:317-334: Isend tag=Rank, Recv tag=NbrMP_Id);
- collectives (barrier/bcast/gather/scatter/allreduce/Allgather) are
  built client-side over p2p on a separate channel keyed by a per-comm
  collective sequence number (all ranks issue collectives in the same
  order — SPMD — so the sequence agrees without negotiation);
- MPI.Win.Allocate_shared maps one mmap'd file per window (created by
  comm-rank 0, fully truncated to the summed per-rank sizes); like real
  MPI shared windows the memory is CONTIGUOUS in rank order, so
  Shared_query(r) returns the window from rank r's offset to the end —
  both idioms in the reference (query(0) at partition_mesh.py:101,
  query(LoadingRank) at file_operations.py:322) resolve to the loading
  rank's bytes because all other ranks allocate 0;
- MPI.File keeps plain POSIX pread/pwrite-at-offset semantics (the
  reference writes disjoint offset ranges per rank).

Wire format: 8-byte big-endian length + pickle.  Performance is a non-
goal — this is a parity ORACLE for test-scale models, not a runtime.
"""

from __future__ import annotations

import mmap
import os
import pickle
import socket
import struct
import threading
from collections import deque

import numpy as np

_LEN = struct.Struct(">Q")


def send_frame(sock, obj):
    data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(data)) + data)


def recv_frame(sock):
    hdr = _recv_exact(sock, _LEN.size)
    if hdr is None:
        return None
    (n,) = _LEN.unpack(hdr)
    body = _recv_exact(sock, n)
    if body is None:
        return None
    return pickle.loads(body)


def _recv_exact(sock, n):
    chunks = []
    got = 0
    while got < n:
        b = sock.recv(min(n - got, 1 << 20))
        if not b:
            return None
        chunks.append(b)
        got += len(b)
    return b"".join(chunks)


# ----------------------------------------------------------------------
# Router (runs in the LAUNCHER, one thread per rank connection)
# ----------------------------------------------------------------------


class Router:
    """Tag-keyed mailboxes + barrier counting for N ranks.

    One handler thread per rank connection (threads, not select: a
    handler blocks only on ITS rank's socket; shared state is behind one
    lock; parked Recv/barrier replies are delivered by whichever handler
    completes the match)."""

    def __init__(self, n_ranks: int, sock_path: str):
        self.n = n_ranks
        self.path = sock_path
        self._lock = threading.Lock()
        self._mail = {}          # key -> deque of payloads
        self._waiting = {}       # key -> conn of the blocked receiver
        self._bar = {}           # comm_id -> [count, [conns]]
        self._conns = []
        self._srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        if os.path.exists(sock_path):
            os.unlink(sock_path)
        self._srv.bind(sock_path)
        self._srv.listen(n_ranks)
        self._threads = []
        self._accept_thread = threading.Thread(target=self._accept,
                                               daemon=True)
        self._accept_thread.start()

    def _accept(self):
        for _ in range(self.n):
            conn, _ = self._srv.accept()
            with self._lock:
                self._conns.append(conn)
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _serve(self, conn):
        try:
            while True:
                msg = recv_frame(conn)
                if msg is None:
                    return
                kind = msg[0]
                if kind == "snd":
                    _, key, payload = msg
                    with self._lock:
                        waiter = self._waiting.pop(key, None)
                        if waiter is None:
                            self._mail.setdefault(
                                key, deque()).append(payload)
                    if waiter is not None:
                        send_frame(waiter, payload)
                elif kind == "rcv":
                    _, key = msg
                    with self._lock:
                        box = self._mail.get(key)
                        if box:
                            payload = box.popleft()
                            have = True
                        else:
                            self._waiting[key] = conn
                            have = False
                    if have:
                        send_frame(conn, payload)
                elif kind == "bar":
                    _, cid = msg
                    with self._lock:
                        count, conns = self._bar.setdefault(cid, [0, []])
                        self._bar[cid][0] += 1
                        conns.append(conn)
                        done = self._bar[cid][0] == self.n
                        if done:
                            release = list(conns)
                            self._bar[cid] = [0, []]
                    if done:
                        for c in release:
                            send_frame(c, ("ok",))
        except (OSError, EOFError):
            return

    def close(self):
        for c in self._conns:
            try:
                c.close()
            except OSError:
                pass
        try:
            self._srv.close()
        finally:
            if os.path.exists(self.path):
                os.unlink(self.path)


# ----------------------------------------------------------------------
# Client side
# ----------------------------------------------------------------------


class _Request:
    def Wait(self):
        return None


class MultiComm:
    """An N-rank communicator backed by the router connection.

    Each comm has a stable id agreed WITHOUT negotiation: comms are only
    created collectively (COMM_WORLD at import; Split_type calls in
    program order), so a per-process creation counter matches across
    ranks."""

    _next_cid = [0]
    _sock = None
    _sock_lock = threading.Lock()

    def __init__(self, rank: int, size: int):
        self.rank = rank
        self.size = size
        self.cid = MultiComm._next_cid[0]
        MultiComm._next_cid[0] += 1
        self._coll_seq = 0
        self._win_seq = 0
        if MultiComm._sock is None:
            path = os.environ["MPI_SHIM_SOCK"]
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.connect(path)
            MultiComm._sock = s

    # -- plumbing ------------------------------------------------------
    def _snd(self, chan, dst, src, tag, payload):
        key = (self.cid, chan, dst, src, tag)
        with MultiComm._sock_lock:
            send_frame(MultiComm._sock, ("snd", key, payload))

    def _rcv(self, chan, src, tag):
        key = (self.cid, chan, self.rank, src, tag)
        with MultiComm._sock_lock:
            send_frame(MultiComm._sock, ("rcv", key))
            return recv_frame(MultiComm._sock)

    def _coll(self):
        self._coll_seq += 1
        return self._coll_seq

    # -- topology ------------------------------------------------------
    def Get_rank(self):
        return self.rank

    def Get_size(self):
        return self.size

    def Split_type(self, split_type, key=0):
        # single host: the shared-memory comm spans all ranks.  Creation
        # is collective, so cids stay aligned.
        return MultiComm(self.rank, self.size)

    # -- sync / collectives -------------------------------------------
    def barrier(self):
        with MultiComm._sock_lock:
            send_frame(MultiComm._sock, ("bar", self.cid))
            recv_frame(MultiComm._sock)

    Barrier = barrier

    def bcast(self, x, root=0):
        seq = self._coll()
        if self.rank == root:
            for r in range(self.size):
                if r != root:
                    self._snd("c", r, root, seq, x)
            return x
        return self._rcv("c", root, seq)

    def gather(self, x, root=0):
        seq = self._coll()
        if self.rank == root:
            out = [None] * self.size
            out[root] = x
            for r in range(self.size):
                if r != root:
                    out[r] = self._rcv("c", r, seq)
            return out
        self._snd("c", root, self.rank, seq, x)
        return None

    def scatter(self, xs, root=0):
        seq = self._coll()
        if self.rank == root:
            for r in range(self.size):
                if r != root:
                    self._snd("c", r, root, seq, xs[r])
            return xs[root]
        return self._rcv("c", root, seq)

    def allreduce(self, x, op=None):
        if op is not None and op != "MPI_SUM":    # MPI.SUM sentinel
            raise NotImplementedError(f"shim allreduce supports SUM, got {op}")
        parts = self.gather(x, root=0)
        if self.rank == 0:
            total = parts[0]
            for p in parts[1:]:
                total = total + p
        else:
            total = None
        return self.bcast(total, root=0)

    def Allgather(self, sendbuf, recvbuf):
        parts = self.gather(np.ascontiguousarray(sendbuf), root=0)
        parts = self.bcast(parts, root=0)
        r = np.asarray(recvbuf)
        # assign through r itself (reshape of a non-contiguous recvbuf
        # would be a throwaway copy and silently discard the result)
        r[...] = np.stack([np.asarray(p).ravel() for p in parts]) \
            .reshape(r.shape)

    # -- point-to-point ------------------------------------------------
    def Isend(self, buf, dest=0, tag=0):
        # no defensive copy needed: _snd pickles synchronously, so the
        # payload is fully snapshotted before Isend returns
        self._snd("u", dest, self.rank, tag, np.asarray(buf))
        return _Request()

    def Recv(self, buf, source=0, tag=0):
        data = self._rcv("u", source, tag)
        b = np.asarray(buf)
        b[...] = np.asarray(data).reshape(b.shape)

    def isend(self, obj, dest=0, tag=0):
        self._snd("u", dest, self.rank, tag, obj)
        return _Request()

    def recv(self, source=0, tag=0):
        return self._rcv("u", source, tag)


class MultiWin:
    """Shared window over an mmap'd file, contiguous in rank order."""

    def __init__(self, mm, sizes, itemsize):
        self._mm = mm
        self._sizes = sizes
        self._itemsize = itemsize

    def Shared_query(self, rank):
        off = int(sum(self._sizes[:rank]))
        return memoryview(self._mm)[off:], self._itemsize

    @staticmethod
    def allocate(nbytes, itemsize, comm: MultiComm):
        sizes = comm.gather(int(nbytes), root=0)
        sizes = comm.bcast(sizes, root=0)
        comm._win_seq += 1
        jobdir = os.environ["MPI_SHIM_JOBDIR"]
        path = os.path.join(jobdir, f"win_{comm.cid}_{comm._win_seq}")
        total = max(sum(sizes), 1)
        if comm.rank == 0:
            with open(path, "wb") as f:
                f.truncate(total)
        comm.barrier()
        f = open(path, "r+b")
        mm = mmap.mmap(f.fileno(), total)
        f.close()
        return MultiWin(mm, sizes, itemsize)
