"""Stand-in for the slice of the mpi4py API the reference exercises,
with TWO transports behind one surface.

Purpose: OpenMPI/mpi4py cannot be installed in this image, so the
reference cannot run under a real MPI — but its unmodified code CAN if
`import mpi4py` resolves to this package:

- single-rank (default): rank 0 of 1, in-process "collectives"
  (identity), a bytes-backed shared-memory window, plain-file MPI-IO,
  and a tag-keyed mailbox for the (self-)send paths — used to measure
  the reference's per-rank hot loop for an honest `vs_baseline`;
- multi-rank (MPI_SHIM_SIZE > 1, set by tools/mpi_shim/mpiexec.py):
  N real processes with router-backed tagged point-to-point and
  collectives, mmap'd contiguous shared-memory windows, and concurrent
  POSIX MPI-IO — see _multirank.py — used to run the reference's
  multi-rank partitioning/halo-exchange/parallel-IO code paths as a
  parity ORACLE (tests/test_reference_parity.py).

Used ONLY by tools/run_reference_baseline.py and its tests; the
framework itself never imports it.

This is original code written against mpi4py's public API signatures as
called by the reference (pcg_solver.py, partition_mesh.py,
file_operations.py) — no mpi4py source is used.
"""

from . import MPI  # noqa: F401  (`from mpi4py import MPI` support)
