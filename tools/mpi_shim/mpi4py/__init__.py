"""Single-rank in-process stand-in for the slice of the mpi4py API the
reference implementation exercises.

Purpose: OpenMPI/mpi4py cannot be installed in this image, so the
reference cannot run multi-rank — but its per-rank hot loop (the thing
the benchmark baseline models) CAN run single-rank if `import mpi4py`
resolves.  This package provides exactly that: rank 0 of 1, in-process
"collectives" (identity), a bytes-backed shared-memory window, plain-file
MPI-IO, and a tag-keyed mailbox for the (self-)send paths.  It is used
ONLY by tools/run_reference_baseline.py to measure the reference's own
code for an honest `vs_baseline`; the framework itself never imports it.

This is original code written against mpi4py's public API signatures as
called by the reference (pcg_solver.py, partition_mesh.py,
file_operations.py) — no mpi4py source is used.
"""

from . import MPI  # noqa: F401  (`from mpi4py import MPI` support)
