"""Launcher for the multi-rank mpi4py shim: a minimal ``mpiexec``.

Spawns N copies of a Python program with MPI_SHIM_RANK/SIZE set and a
router thread (mpi4py/_multirank.Router) serving their unix-socket
rendezvous, so the REFERENCE's unmodified mpiexec-launched programs
(partition_mesh.py, pcg_solver.py, export_vtk.py) run with real
N-process semantics in an image without OpenMPI.

Usage (CLI):         python tools/mpi_shim/mpiexec.py -np 8 script.py args...
Usage (programmatic) from tools/run_reference_baseline.py:

    rc, outs = launch([sys.executable, "script.py", ...], ranks=8,
                      cwd=stage, env=env)

Per-rank stdout/stderr are captured to files in the job dir and returned.
A rank failing (nonzero exit) terminates the others after a grace period.
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import time


def launch(argv, ranks: int, cwd=None, env=None, timeout=3600):
    """Run ``argv`` as ``ranks`` SPMD processes.  Returns (rc, outputs)
    where rc is 0 iff every rank exited 0 and outputs is a list of
    per-rank captured stdout+stderr strings."""
    shim_dir = os.path.dirname(os.path.abspath(__file__))
    if shim_dir not in sys.path:
        sys.path.insert(0, shim_dir)
    from mpi4py._multirank import Router

    env = dict(env if env is not None else os.environ)
    jobdir = tempfile.mkdtemp(prefix="mpishim_")
    sock = os.path.join(jobdir, "router.sock")
    router = Router(ranks, sock)
    env["MPI_SHIM_SIZE"] = str(ranks)
    env["MPI_SHIM_SOCK"] = sock
    env["MPI_SHIM_JOBDIR"] = jobdir
    # the ranks must resolve `import mpi4py` to THIS shim regardless of
    # how the launcher was invoked (mpi4py is not installed in the image)
    pp = env.get("PYTHONPATH", "")
    if shim_dir not in pp.split(os.pathsep):
        env["PYTHONPATH"] = (shim_dir + os.pathsep + pp) if pp else shim_dir

    procs = []
    logs = []
    try:
        for r in range(ranks):
            renv = dict(env, MPI_SHIM_RANK=str(r))
            log = open(os.path.join(jobdir, f"rank{r}.log"), "w+")
            logs.append(log)
            procs.append(subprocess.Popen(
                argv, cwd=cwd, env=renv, stdout=log, stderr=log))
        deadline = time.monotonic() + timeout
        rcs = [None] * ranks
        while any(rc is None for rc in rcs):
            for i, p in enumerate(procs):
                if rcs[i] is None:
                    rcs[i] = p.poll()
            # fail fast: one dead rank means the job cannot complete
            if any(rc not in (None, 0) for rc in rcs):
                time.sleep(2.0)          # let siblings flush/finish
                for p in procs:
                    if p.poll() is None:
                        p.terminate()
                for p in procs:
                    try:
                        p.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        p.kill()
                rcs = [p.poll() for p in procs]
                break
            if time.monotonic() > deadline:
                for p in procs:
                    if p.poll() is None:
                        p.kill()
                raise TimeoutError(
                    f"mpi_shim job exceeded {timeout}s: {argv}")
            time.sleep(0.05)
    finally:
        router.close()
        outputs = []
        for log in logs:
            log.flush()
            log.seek(0)
            outputs.append(log.read())
            log.close()
        # the job dir holds the full mmap'd shared windows (the whole
        # partitioned mesh) — leaking one per launch would grow /tmp
        # without bound across parity-test runs
        import shutil

        shutil.rmtree(jobdir, ignore_errors=True)
    rc = 0 if all(c == 0 for c in rcs) else next(
        c for c in rcs if c not in (0, None))
    return rc, outputs


def main():
    args = sys.argv[1:]
    ranks = 1
    if args and args[0] in ("-np", "-n"):
        ranks = int(args[1])
        args = args[2:]
    if not args:
        print("usage: mpiexec.py -np N script.py [args...]", file=sys.stderr)
        sys.exit(2)
    rc, outputs = launch([sys.executable] + args, ranks)
    for r, out in enumerate(outputs):
        for line in out.splitlines():
            print(f"[rank {r}] {line}")
    sys.exit(rc)


if __name__ == "__main__":
    main()
