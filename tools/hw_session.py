"""Unattended on-hardware measurement session (RUNBOOK checklist).

Probes the accelerator until it answers (or a deadline passes), then
runs the whole RUNBOOK "on-hardware measurement checklist" as
subprocesses with per-step timeouts, appending everything to a log file
inside the repo — so a tunnel recovery at any hour turns into captured
measurements without an operator in the loop.

Usage:
    python tools/hw_session.py [--deadline-min 360] [--log docs/HW_SESSION.log]
        [--quick]            # small sizes (smoke/CPU test of the harness)
        [--preset full|priority]

Presets:

* ``full`` (default) — the historical RUNBOOK checklist:
  1. bench_matvec         — XLA gse vs corner vs Pallas v3 at flagship scale
  2. bench_gather         — hybrid row-traffic isolation
  3. bench.py             — cube flagship (mixed)
  4. bench.py direct      — f64-direct anchor at the same scale
  5. bench.py octree      — graded-octree flagship on the blocked hybrid
  6. bench_iter_breakdown — structured per-iteration split
  7. bench_hybrid_breakdown — per-level gather/stencil/scatter split

* ``priority`` — the highest-value unanswered questions FIRST (every
  prior window died before the full queue finished; ROADMAP #3):
  1. flagship classic     — the 10.33M-dof ms/iter anchor (mixed)
  2. flagship fused       — PR-5's single-reduction loop, FIRST hardware
                            measurement (BENCH_PCG_VARIANT=fused)
  3. flagship pipelined   — ISSUE-11's stencil-overlapped psum
                            (BENCH_PCG_VARIANT=pipelined), directly
                            after the fused leg so the 3-way
                            classic/fused/pipelined ms/iter A/B reads
                            off three adjacent lines (the overlap claim
                            is lint-proven by step 0.2; this leg only
                            has to confirm the ms/iter number)
  3.5 profiled flagship   — ISSUE 15: one BENCH_PROFILE=1 rung on the
                            same warm cache/size (pipelined when the
                            overlap lint passed, else classic); the
                            captured device trace is parsed back and
                            the MEASURED overlap verdict + the
                            bench-trend verdict (obs/trend.py over the
                            committed BENCH_r*.json series) are logged
                            into this session log
  4. MG A/B               — classic+jacobi vs classic+mg at a
                            multi-level-coarsenable size (BENCH_NX=144;
                            BENCH_PRECOND=mg): iters + ms/iter +
                            detail.time_to_tol_s — the ISSUE-10
                            iteration-count lever, first hardware
                            measurement
  5. nrhs sweep 4, 16     — batched multi-RHS throughput A/B
                            (BENCH_NRHS; detail.dof_iter_rhs_per_s)
  6. Pallas v9 A/B        — first-ever hardware execution of the kernel
                            family (the hw_v9_ab.py step)
  Step 0.2 (after the fast lint, still on CPU) is the overlap lint:
  the full-tier ``psum-overlap`` rule alone (~15 s — the fast tier
  stays ~1 s and deliberately excludes it), proving the pipelined
  psum really is data-independent of the stencil before the hardware
  leg that measures the claim; a FAIL SKIPS the pipelined leg only
  (classic/fused measurements do not depend on the overlap claim).
  Step 0.5 (between the lints and the flagship) is the
  blocked-resilience smoke: a tiny solve_many with an injected
  per-column fault, proving the ISSUE-9 per-column recovery ladder +
  fault isolation live on the accelerator for seconds of window time.
  Steps 2-5 reuse step 1's warm caches (shared BENCH_CACHE_DIR), so a
  window that dies mid-queue still leaves each completed step's salvage
  line.
"""

from __future__ import annotations

import argparse
import atexit
import datetime
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Blocked-resilience smoke (priority preset step 0.5): tiny solve_many
# with a per-column NaN fault injected at the first blocked chunk
# boundary.  Asserts the poisoned column RECOVERS (per-column ladder)
# and the healthy column matches a fault-free block bit-identically —
# the ISSUE-9 fault-isolation contract, proven live on the accelerator.
_MANY_SMOKE = """
import numpy as np
from pcg_mpi_solver_tpu.config import RunConfig, SolverConfig, \
    TimeHistoryConfig
from pcg_mpi_solver_tpu.models.synthetic import make_cube_model
from pcg_mpi_solver_tpu.resilience import FaultPlan
from pcg_mpi_solver_tpu.solver.driver import Solver

m = make_cube_model(6, 5, 5, heterogeneous=True)
def mk():
    cfg = RunConfig(solver=SolverConfig(
        tol=1e-8, max_iter=2000, iters_per_dispatch=25,
        max_recoveries=2))
    cfg.time_history = TimeHistoryConfig(time_step_delta=[0.0, 1.0])
    return Solver(m, cfg, backend="general")
F = np.asarray(m.F)
fb = np.stack([F, 0.5 * F], axis=-1)
ref = mk().solve_many(fb)
s = mk()
s.fault_plan = FaultPlan("nan@col:1", recorder=s.recorder)
res = s.solve_many(fb)
assert list(res.flags) == [0, 0], (res.flags, res.quarantined)
assert res.recoveries >= 1, "column fault never engaged the ladder"
np.testing.assert_array_equal(np.asarray(res.x)[..., 0],
                              np.asarray(ref.x)[..., 0])
print("blocked-resilience smoke OK: poisoned column recovered "
      f"(recoveries={res.recoveries}), healthy column bit-identical")
"""


# Solve-service smoke (priority preset step 0.7, ISSUE 19): a tiny
# daemon over a temp spool serves 3 submitted jobs, one with an
# injected service-boundary fault (`exc@job:1`).  Asserts 2 done + 1
# failed WITH the named verdict, and that every job got a result file —
# the admission/journal/dispatch loop proven live in seconds, on CPU
# (the service layer is accelerator-agnostic; the flagship legs own the
# device grant).
_SERVE_SMOKE = """
import tempfile
from pcg_mpi_solver_tpu.config import RunConfig, SolverConfig
from pcg_mpi_solver_tpu.models.synthetic import make_cube_model
from pcg_mpi_solver_tpu.resilience import FaultPlan
from pcg_mpi_solver_tpu.serve import jobs as sjobs
from pcg_mpi_solver_tpu.serve.daemon import ServeDaemon
from pcg_mpi_solver_tpu.solver.driver import Solver

m = make_cube_model(6, 5, 5, heterogeneous=True)
s = Solver(m, RunConfig(solver=SolverConfig(tol=1e-8, max_iter=2000)),
           backend="general")
spool = tempfile.mkdtemp(prefix="pcg_serve_smoke_")
ids = [sjobs.submit(spool, {"scale": sc, "deadline_s": 3600.0},
                    submit_t=float(i))
       for i, sc in enumerate([1.0, 0.5, 2.0])]
d = ServeDaemon(s, spool, queue_max=8, widths=(1, 2, 4),
                fault_plan=FaultPlan("exc@job:1", recorder=s.recorder))
reason = d.run(idle_exit_s=0.0, install_signals=False)
results = [sjobs.read_result(spool, j) for j in ids]
assert all(r is not None for r in results), results
n_ok = sum(r["ok"] for r in results)
failed = [r for r in results if not r["ok"]]
assert n_ok == 2 and len(failed) == 1, results
assert failed[0]["verdict"].startswith("injected:"), failed
print("serve smoke OK: 2 done + 1 failed with named verdict "
      f"({failed[0]['verdict']!r}), drain={reason}")
"""


def log_line(path, msg):
    stamp = datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%d %H:%M:%SZ")
    line = f"[{stamp}] {msg}"
    print(line, flush=True)
    with open(path, "a") as f:
        f.write(line + "\n")


_last_step_ok = True

# one flight recorder per session log (lazy): the crash-durable twin of
# the log itself.  The HW_SESSION.log stream dies with the tunnel; the
# flight file's fsync-per-event begin/end brackets + heartbeats survive
# it, so "which step was in flight when the window died, and when did it
# last breathe" is a mechanical read (pcg-tpu summary <log>.flight.jsonl)
# instead of log archaeology (the BENCH_r05 provenance mode).
_FLIGHTS = {}


@atexit.register
def _close_flights():
    # clean interpreter exit only — a SIGKILL skips this, which is the
    # point: every record is already fsync'd, close is bookkeeping
    for fl in _FLIGHTS.values():
        fl.close()


def _flight(path):
    if path not in _FLIGHTS:
        try:
            from pcg_mpi_solver_tpu.obs.flight import (
                FlightRecorder, ingest_and_rotate)
        except ImportError:
            sys.path.insert(0, REPO)
            from pcg_mpi_solver_tpu.obs.flight import (
                FlightRecorder, ingest_and_rotate)
        fpath = path + ".flight.jsonl"
        # a leftover artifact from a previous session on the same log is
        # ingested + rotated first (the shared startup discipline —
        # obs/flight.ingest_and_rotate documents why)
        fpath = ingest_and_rotate(fpath, lambda msg: log_line(path, msg))
        _FLIGHTS[path] = FlightRecorder(
            fpath, meta={"component": "hw_session"})
    return _FLIGHTS[path]


def run_step(path, name, argv, env_extra=None, timeout=3600, gate_s=900,
             force_gate=False, ok_rcs=(0,)):
    """Run one checklist step.  If the PREVIOUS step failed or timed out,
    first re-probe the accelerator (bounded by ``gate_s``): a SIGKILLed
    step wedges the device grant for minutes (docs/RUNBOOK.md), and the
    example scripts — unlike bench.py — have no probe/retry of their own,
    so without this gate they die instantly at the first device touch
    (observed: second-wave combine-variants step, rc=1 after the f64
    step's timeout kill).  ``force_gate`` probes even after an rc=0 step:
    the matvec A/B exits 0 while its per-variant try/except swallows
    Mosaic failures that wedge the grant all the same (observed wave 3:
    the flagship's XLA compile died UNAVAILABLE right after the rc=0
    A/B's ten failed probe compiles)."""
    global _last_step_ok
    if (not _last_step_ok or force_gate) and gate_s:
        from pcg_mpi_solver_tpu.bench import _probe_with_retry

        why = "previous step failed" if not _last_step_ok else "force_gate"
        log_line(path, f"gate: {why}; re-probing before "
                       f"{name} (wedged-grant guard, {gate_s:.0f}s budget)")
        ok, detail = _probe_with_retry(budget_s=gate_s, probe_timeout_s=300)
        log_line(path, f"gate: {'accelerator ok' if ok else 'STILL DOWN'} "
                       f"({detail}); launching step regardless")
    env = dict(os.environ)
    env.setdefault("PCG_TPU_VERBOSE", "1")
    # persistent compile cache shared across steps/waves (see bench.py)
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   os.path.join(REPO, ".jax_cache"))
    # examples/*.py run with sys.path[0]=examples/, and the package is
    # not pip-installed — the repo root must come from PYTHONPATH
    env["PYTHONPATH"] = REPO + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    env.update(env_extra or {})
    log_line(path, f"=== {name}: {' '.join(argv)} "
                   + (f"env={env_extra} " if env_extra else ""))
    # crash-durable flight bracket around the whole step (begin fsync'd
    # BEFORE the subprocess launches; heartbeats while it runs): a
    # tunnel death mid-step leaves "step:<name> in flight" on disk even
    # when the log stream itself is lost.  Best-effort — recorder
    # trouble must never cost a hardware window a step.
    fl = fl_seq = None
    try:
        fl = _flight(path)
        fl_seq = fl.begin(f"step:{name}", argv=list(argv))
    except Exception as e:                              # noqa: BLE001
        log_line(path, f"flight recorder unavailable ({e}); continuing")
        fl = None
    t0 = time.monotonic()
    # own process GROUP so a timeout kills the step's whole tree —
    # bench.py spawns its own subprocesses (reference baseline, CPU
    # fallback) which would otherwise survive as orphans competing with
    # the next step, unlogged, in an unattended session
    import signal

    # stream straight into the log (no PIPE): an external kill mid-step
    # must not lose the step's partial output — that is the exact
    # artifact-loss mode this harness exists to prevent
    with open(path, "a") as logf:
        proc = subprocess.Popen([sys.executable] + argv, cwd=REPO, env=env,
                                stdout=logf, stderr=subprocess.STDOUT,
                                text=True, start_new_session=True)
        try:
            proc.wait(timeout=timeout)
            status = f"rc={proc.returncode}"
        except subprocess.TimeoutExpired:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                pass    # a daemonized escapee; the group is dead, move on
            status = f"TIMEOUT after {timeout}s (process group killed)"
    wall = time.monotonic() - t0
    # ok_rcs: some steps use nonzero exits as VERDICTS, not failures
    # (cache_key_check exits 4 for a successfully-determined MISMATCH) —
    # those must not trip the next step's wedged-grant gate
    _last_step_ok = status in tuple(f"rc={rc}" for rc in ok_rcs)
    if fl is not None:
        try:
            fail_extra = {} if _last_step_ok else {"error": status}
            fl.end(fl_seq, f"step:{name}", ok=_last_step_ok,
                   status=status, wall_s=round(wall, 1), **fail_extra)
            if not _last_step_ok:
                # the mechanical post-mortem pointer, IN the session log:
                # where the durable artifact is and what it says
                from pcg_mpi_solver_tpu.obs.flight import (
                    flight_verdict_path)

                v = flight_verdict_path(fl.path)
                log_line(path, f"flight record: {fl.path} "
                               f"verdict={v['verdict']} "
                               f"({v['records']} record(s)"
                               + (", in flight: "
                                  + ", ".join(v["in_flight"])
                                  if v["in_flight"] else "")
                               + ")")
        except Exception as e:                          # noqa: BLE001
            log_line(path, f"flight record close failed ({e}); "
                           "continuing")
    log_line(path, f"=== {name} done: {status} ({wall:.0f}s)")
    return status


def log_profile_verdicts(path, prof_dir, since=None):
    """ISSUE 15: after the profiled flagship rung, put the two
    mechanical verdicts INTO the session log — the measured
    collective-overlap fraction parsed from the captured device trace
    (obs/profview.py) and the bench-trend verdict over the committed
    BENCH_r*.json series plus this queue's fresh line (obs/trend.py).
    ``since`` (unix seconds, the profiled step's start) guards against
    attributing a STALE artifact: bench swallows capture failures by
    design, and bench_profile/ persists across sessions — an earlier
    round's trace must not be logged as this round's measurement.
    Best-effort end to end: a broken trace parse or a missing artifact
    logs a named reason and must never cost the step (tested in
    tests/test_hw_queue.py)."""
    try:
        from pcg_mpi_solver_tpu.obs import profview

        files = profview.find_trace_files(prof_dir)
        if not files:
            raise FileNotFoundError(f"no trace artifact under "
                                    f"{prof_dir}")
        if since is not None and os.path.getmtime(files[0]) < since:
            raise FileNotFoundError(
                f"newest artifact predates this step (the capture "
                f"failed silently; stale: {files[0]})")
        rep = profview.profile_report(files[0])
        ov = rep.get("overlap_frac")
        mv = (rep.get("phases") or {}).get("matvec", {}).get(
            "ms_per_iter")
        log_line(path, "overlap verdict: "
                 + (f"{ov:.3f} of collective time hidden behind "
                    "concurrent compute" if ov is not None else
                    "n/a (no collective ops in trace)")
                 + f" (matvec {mv} ms/iter, parse verdict "
                   f"{rep.get('verdict')!r}, artifact "
                   f"{rep.get('source')})")
    except Exception as e:                              # noqa: BLE001
        log_line(path, f"overlap verdict unavailable "
                       f"({type(e).__name__}: {e}); continuing")
    # Fleet skew verdict (ISSUE 16): on a multi-controller capture the
    # p<idx>/ subdirs carry per-process traces — log the cross-process
    # transport-vs-wait attribution next to the overlap verdict.  On the
    # usual single-controller window this degrades to a named reason.
    try:
        from pcg_mpi_solver_tpu.obs import fleet

        frep = fleet.fleet_report(prof_dir)
        if frep.get("skew_frac") is not None:
            who = (f"p{frep['straggler']}" if frep.get("straggler")
                   is not None else "none (balanced)")
            log_line(path, f"fleet verdict: skew_frac="
                           f"{frep['skew_frac']:.4f} (wait "
                           f"{frep['wait_ms']:.1f} ms vs transport "
                           f"{frep['transport_ms']:.1f} ms over "
                           f"{frep['matched_collectives']} matched "
                           f"collectives), straggler {who} "
                           "(read back: pcg-tpu fleet-report)")
        else:
            log_line(path, f"fleet verdict: n/a ({frep['verdict']})")
    except Exception as e:                              # noqa: BLE001
        log_line(path, f"fleet verdict unavailable "
                       f"({type(e).__name__}: {e}); continuing")
    try:
        import glob as _glob

        from pcg_mpi_solver_tpu.obs import trend

        arts = sorted(_glob.glob(os.path.join(REPO, "BENCH_r*.json")))
        fresh = os.path.join(REPO, "bench_provisional.json")
        rep = trend.trend_report(
            arts, fresh=fresh if os.path.exists(fresh) else None)
        log_line(path, "trend verdict: " + trend.verdict_line(rep))
        for leg in rep["legs"]:
            if leg["verdict"] == "regressed":
                log_line(path, f"trend REGRESSION: {leg['leg']} "
                               f"{leg['old_value']:.3g} -> "
                               f"{leg['new_value']:.3g} "
                               f"({leg['delta_pct']:+.1f}%)")
    except Exception as e:                              # noqa: BLE001
        log_line(path, f"trend verdict unavailable "
                       f"({type(e).__name__}: {e}); continuing")


def start_queue(name, deadline_min, log):
    """Shared session-start policy for every hardware queue script: derive
    the log path, probe the accelerator with the ONE retry policy (incl.
    the deterministic-failure two-strike, pcg_mpi_solver_tpu/bench.py),
    exit(3) if the deadline passes.  Returns the log path."""
    path = os.path.join(REPO, log)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    sys.path.insert(0, REPO)
    from pcg_mpi_solver_tpu.bench import _probe_with_retry

    log_line(path, f"{name} start (deadline {deadline_min:.0f} min)")
    ok, detail = _probe_with_retry(budget_s=deadline_min * 60,
                                   probe_timeout_s=600)
    if not ok:
        log_line(path, f"deadline reached; no {name} session ({detail})")
        sys.exit(3)
    log_line(path, f"accelerator ANSWERED: {detail}")
    return path


def run_priority_queue(path, quick: bool):
    """The prioritized measurement queue (module docstring ``priority``
    preset): contract lint FIRST (step 0, on CPU — a broken structural
    claim means the measurements would benchmark a lie), then the
    3-way classic/fused/pipelined ms/iter A/B at the flagship, then the
    batched-RHS sweep, then the Pallas v9 A/B — ordered so the minutes
    a dying window DOES deliver answer the most valuable open
    questions.
    A shared warm-path cache dir makes the bench steps near-zero-setup."""
    # Step 0: `pcg-tpu lint --fast` (analysis/) — statically prove the
    # collective budgets / hot-loop purity the queue is about to measure.
    # Runs on the CPU backend (JAX_PLATFORMS=cpu: never touches, or
    # waits on, the accelerator grant; the lint entry point also drops
    # JAX_COMPILATION_CACHE_DIR — jax 0.4.x CPU + persistent compile
    # cache segfaults).  A FAIL aborts BEFORE the hardware queue starts:
    # measuring a claim the lint just disproved burns the window on
    # garbage.
    status = run_step(path, "contract lint (step 0)",
                      ["-m", "pcg_mpi_solver_tpu.analysis", "--fast"],
                      env_extra={"JAX_PLATFORMS": "cpu"}, timeout=900,
                      gate_s=0)
    verdict = "PASS" if status == "rc=0" else f"FAIL ({status})"
    log_line(path, f"lint verdict: {verdict}")
    if status != "rc=0":
        log_line(path, "structural contract lint FAILED — aborting the "
                       "priority queue before any hardware step (fix the "
                       "invariant or baseline it, then relaunch)")
        return
    # Step 0.2: the psum-overlap rule ALONE, full tier, still on CPU
    # (~15 s; registered fast=False and the pipelined programs are not
    # in the --fast matrix, so step 0 deliberately never checks the
    # overlap claim — this step does, right before the hardware leg
    # that measures it).  A FAIL skips ONLY the pipelined leg: the
    # classic/fused measurements do not depend on the overlap claim,
    # so the window still answers them.
    ov_status = run_step(path, "overlap lint (step 0.2)",
                         ["-m", "pcg_mpi_solver_tpu.analysis",
                          "--rules", "psum-overlap"],
                         env_extra={"JAX_PLATFORMS": "cpu"}, timeout=900,
                         gate_s=0)
    overlap_ok = ov_status == "rc=0"
    log_line(path, "overlap lint verdict: "
                   + ("PASS" if overlap_ok else f"FAIL ({ov_status})"))
    # Step 0.5: blocked-resilience smoke (ISSUE 9) — a tiny solve_many
    # with an injected per-column fault, ON THE ACCELERATOR: proves the
    # per-column recovery ladder + fault isolation live (tier-1 only
    # ever runs it on CPU) for ~seconds of window time.  The healthy
    # column must match a fault-free run bit-identically and the
    # poisoned column must recover (flag 0 after a ladder restart).
    run_step(path, "blocked-resilience smoke", ["-c", _MANY_SMOKE],
             env_extra={"PCG_TPU_RETRY_BACKOFF_S": "0.01"}, timeout=900)
    # Step 0.6: distributed-chaos smoke (ISSUE 18) — a 2-process CPU
    # gloo group with a rank-targeted kill (`kill@rank:1:2`): the
    # survivor must raise the NAMED DeadPeerError within the collective
    # deadline (not hang in gloo), and a same-count relaunch must resume
    # from the group-committed snapshot epoch bit-identically.
    # CPU-only (jax.distributed child processes; never touches the
    # accelerator grant) and BEFORE the setup ladder, so a broken
    # fault-tolerance path fails the window in minutes, not at 3 a.m.
    run_step(path, "distributed-chaos smoke",
             ["-m", "pytest", "-x", "-q",
              "tests/test_distributed_ft.py::"
              "test_dead_peer_named_and_resume_scalar"],
             env_extra={"JAX_PLATFORMS": "cpu"}, timeout=1200, gate_s=0)
    # Step 0.7: solve-service smoke (ISSUE 19) — a tiny serve daemon
    # over a temp spool: 3 submitted jobs, one injected service-
    # boundary fault (`exc@job:1`), asserting 2 done + 1 failed with
    # the NAMED verdict and a result file for every job.  CPU-only
    # (the service layer is accelerator-agnostic; never touches the
    # grant) and before the ladder — a broken admission/journal/
    # dispatch loop fails the window in seconds.
    run_step(path, "serve smoke", ["-c", _SERVE_SMOKE],
             env_extra={"JAX_PLATFORMS": "cpu"}, timeout=900, gate_s=0)
    # BENCH_NX exported unconditionally so the flagship size is pinned
    # HERE, not silently inherited from bench.py's default
    cache = {"BENCH_CACHE_DIR": os.path.join(REPO, ".pcg_cache")}
    size = {"BENCH_NX": "24" if quick else "150"}
    # Setup ladder (ISSUE 14): the weak-scaling cold-path measurement —
    # sharded partition build vs the monolithic serial build, streamed
    # slab-ingest peak memory, shard-cache warm/cold deltas — runs on
    # CPU (jax.distributed child groups; it never touches the
    # accelerator grant) BEFORE the variant A/Bs.  It scratches inside
    # BENCH_CACHE_DIR but in an isolated per-run subdir it deletes on
    # exit (its rungs must COLD-build to measure honestly), so it
    # neither pollutes nor pre-warms the later legs' entries.
    # Artifact: SETUP_LADDER.json in the repo (BENCH-schema rungs).
    run_step(path, "setup ladder", ["bench.py"],
             env_extra=dict(cache,
                            BENCH_SETUP_LADDER="1,2" if quick else "1,2,4",
                            BENCH_SETUP_NX="12" if quick else "40",
                            BENCH_SETUP_OUT=os.path.join(
                                REPO, "SETUP_LADDER.json"),
                            JAX_PLATFORMS="cpu"),
             timeout=1800, gate_s=0)
    run_step(path, "flagship classic", ["bench.py"],
             env_extra=dict(cache, **size), timeout=3600)
    run_step(path, "flagship fused", ["bench.py"],
             env_extra=dict(cache, BENCH_PCG_VARIANT="fused", **size),
             timeout=3600)
    # Pipelined leg (ISSUE 11): same size, same warm cache dir, directly
    # after fused — the psum-overlap lint (step 0.2) already proved the
    # reduction is concurrent with the stencil in the lowered program,
    # so this step only has to confirm ms/iter; three adjacent lines =
    # the 3-way variant A/B (detail.pcg_variant labels them).
    if overlap_ok:
        run_step(path, "flagship pipelined", ["bench.py"],
                 env_extra=dict(cache, BENCH_PCG_VARIANT="pipelined",
                                **size),
                 timeout=3600)
    else:
        log_line(path, "SKIPPING the flagship pipelined leg: the "
                       "psum-overlap lint (step 0.2) FAILED — measuring "
                       "the variant would benchmark a disproven "
                       "latency-hiding claim; the rest of the queue "
                       "does not depend on it")
    # Profiled flagship rung (ISSUE 15): one BENCH_PROFILE=1 leg
    # directly after the variant A/Bs, on the SAME warm cache dir and
    # size — the bench captures a jax.profiler trace of one warm solve
    # (after its timed solve; the A/B numbers above are never
    # perturbed), parses it back (obs/profview.py), and stamps
    # detail.measured_ms_per_iter_matvec + detail.overlap_frac on its
    # line.  The profiled variant is pipelined when the overlap lint
    # passed (the hardware twin of the step-0.2 static proof — the
    # MEASURED overlap fraction), else classic.  The overlap + trend
    # verdicts land in this session log right after the step; a broken
    # trace parse logs a reason and never costs the step.
    prof_dir = os.path.join(REPO, "bench_profile")
    prof_env = dict(cache, BENCH_PROFILE="1", BENCH_PROFILE_DIR=prof_dir,
                    **size)
    if overlap_ok:
        prof_env["BENCH_PCG_VARIANT"] = "pipelined"
    t_prof0 = time.time()
    run_step(path, "profiled flagship", ["bench.py"],
             env_extra=prof_env, timeout=3600)
    log_profile_verdicts(path, prof_dir, since=t_prof0)
    # Watch smoke (ISSUE 16): one `pcg-tpu watch --once` snapshot of
    # THIS session's own flight stream (the file run_step's brackets +
    # heartbeats write), logged into the session — so a wedged hardware
    # run is diagnosed from the session log (status/last-breath/in-
    # flight names) instead of a dead tunnel.  Healthy session: the
    # watch step itself is in flight, so the snapshot reads RUNNING.
    run_step(path, "watch smoke (--once)",
             ["-m", "pcg_mpi_solver_tpu.cli", "watch",
              path + ".flight.jsonl", "--once"],
             env_extra={"JAX_PLATFORMS": "cpu"}, timeout=600, gate_s=0)
    # MG A/B (ISSUE 10): classic+jacobi anchor vs classic+mg at an
    # even, multi-level-coarsenable size (150 halves once to 75 and
    # stops; 144 = 16*9 gives the 72/36/18/9 coarse chain), sharing the
    # warm cache dir — read iters + tpu_ms_per_iter + time_to_tol_s off
    # the two lines (detail.precond labels them).
    mg_size = {"BENCH_NX": "24" if quick else "144"}
    run_step(path, "mg A/B anchor (jacobi)", ["bench.py"],
             env_extra=dict(cache, **mg_size), timeout=3600)
    run_step(path, "mg A/B (mg)", ["bench.py"],
             env_extra=dict(cache, BENCH_PRECOND="mg", **mg_size),
             timeout=3600)
    for nrhs in ("4", "16"):
        run_step(path, f"nrhs sweep ({nrhs})", ["bench.py"],
                 env_extra=dict(cache, BENCH_NRHS=nrhs, **size),
                 timeout=3600)
    run_step(path, "matvec A/B v9",
             ["examples/bench_matvec.py", "48" if quick else "150"],
             env_extra={"BENCH_MATVEC_VARIANTS": "v9"}, timeout=2400)
    log_line(path, "priority queue complete")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--deadline-min", type=float, default=360,
                    help="give up probing after this many minutes")
    ap.add_argument("--log", default=os.path.join("docs", "HW_SESSION.log"))
    ap.add_argument("--quick", action="store_true",
                    help="small sizes (harness smoke; also used on CPU)")
    ap.add_argument("--preset", choices=["full", "priority"],
                    default="full",
                    help="full = historical RUNBOOK checklist; priority "
                         "= the classic/fused/pipelined ms/iter A/B, "
                         "then the BENCH_NRHS sweep, then Pallas v9 "
                         "(highest-value open questions first — see "
                         "module docstring)")
    args = ap.parse_args()
    path = start_queue(f"hw_session (quick={args.quick}, "
                       f"preset={args.preset})",
                       args.deadline_min, args.log)
    if args.preset == "priority":
        run_priority_queue(path, args.quick)
        return

    nx = "48" if args.quick else "150"
    ot = ({"BENCH_OT_N": "6", "BENCH_OT_LEVEL": "2"} if args.quick else {})
    run_step(path, "matvec A/B", ["examples/bench_matvec.py", nx],
             timeout=2400)
    run_step(path, "row traffic",
             ["examples/bench_gather.py"]
             + (["0.3", "1.0"] if args.quick else []), timeout=1200)
    run_step(path, "flagship cube (mixed)", ["bench.py"],
             env_extra=dict({"BENCH_NX": nx} if args.quick else {}),
             timeout=3600)
    # direct mode needs f64 STORAGE too — f32 direct stagnates at
    # relres ~1e-5*kappa (RUNBOOK) and only ladders down
    run_step(path, "flagship cube (f64 direct)", ["bench.py"],
             env_extra=dict({"BENCH_MODE": "direct",
                             "BENCH_DTYPE": "float64"},
                            **({"BENCH_NX": nx} if args.quick else {})),
             timeout=3600)
    # hybrid auto-selection is deprecation-gated (ISSUE 14; RUNBOOK
    # "Scaling the setup path") — this step measures it DELIBERATELY
    run_step(path, "octree flagship (hybrid)", ["bench.py"],
             env_extra=dict({"BENCH_MODEL": "octree",
                             "PCG_TPU_ENABLE_HYBRID": "1"}, **ot),
             timeout=4800)
    run_step(path, "iteration breakdown",
             ["examples/bench_iter_breakdown.py", nx], timeout=1800)
    run_step(path, "hybrid per-level breakdown",
             ["examples/bench_hybrid_breakdown.py"]
             + (["6", "2", "3"] if args.quick else ["16", "4", "6"]),
             timeout=1800)
    log_line(path, "hw_session complete")


if __name__ == "__main__":
    main()
