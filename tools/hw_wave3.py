"""Third-wave hardware queue for the 2026-07-31 session (round 3).

Runs what the second wave could not: the v5 Pallas A/B (layout-legal
kernel committed mid-session, eda25cd), a flagship bench with the v5
probe live (if v5 lowers, the fused path engages and the headline moves),
and the two breakdown runs wave 1 lost to the grant wedge.  Same
probe/retry + step isolation as tools/hw_session.py.

Usage: python tools/hw_wave3.py [--deadline-min 240]
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.hw_session import log_line, run_step  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--deadline-min", type=float, default=240)
    ap.add_argument("--log", default=os.path.join("docs", "HW_SESSION.log"))
    args = ap.parse_args()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(repo, args.log)

    from pcg_mpi_solver_tpu.bench import _probe_with_retry

    log_line(path, f"hw_wave3 start (deadline {args.deadline_min:.0f} min)")
    ok, detail = _probe_with_retry(budget_s=args.deadline_min * 60,
                                   probe_timeout_s=600)
    if not ok:
        log_line(path, f"deadline reached; no wave3 session ({detail})")
        sys.exit(3)
    log_line(path, f"accelerator ANSWERED: {detail}")

    run_step(path, "matvec A/B v5", ["examples/bench_matvec.py", "150"],
             timeout=2400)
    # default bench (mixed flagship): pallas='auto' now probes v5 — if it
    # lowers, this is the first fused-path headline number
    run_step(path, "flagship (v5 probe live)", ["bench.py"], timeout=3600)
    # f64-direct anchor: 150^3/128^3 f64 fail REMOTE COMPILE (UNAVAILABLE,
    # ~25 min each before erroring — the second-wave step burned its whole
    # budget on them); pin the largest size that can realistically compile
    run_step(path, "f64 direct anchor 96", ["bench.py"],
             env_extra={"BENCH_MODE": "direct", "BENCH_DTYPE": "float64",
                        "BENCH_NX": "96"},
             timeout=3600)
    run_step(path, "iteration breakdown", ["examples/bench_iter_breakdown.py",
                                           "150"], timeout=2400)
    run_step(path, "hybrid breakdown", ["examples/bench_hybrid_breakdown.py"],
             timeout=2400)
    log_line(path, "hw_wave3 complete")


if __name__ == "__main__":
    main()
