"""Fifth-wave hardware queue (round 4).

The round-3 tunnel outage (dead from 04:21Z to end of round) left every
wave-4 step owed.  This queue re-runs them with the round-4 changes in
(single-instantiation PCG body + x0_zero + refresh-at-top refinement —
roughly half the stencil instantiations per compiled program), ordered
so the highest-value measurements land first and nothing that can
wedge the grant precedes them:

  0. Cache-key identity check (decides whether the pre-warmed
     .jax_cache erases the flagship compiles).
  1. matvec A/B — ONLY v6 + v8 (chipless-compile-verified candidates;
     v1-v5/v7 are pinned Mosaic failures whose failed remote compiles
     wedge the grant) vs the XLA gse/gsplit/corner forms at 150^3.
  2. Per-iteration breakdown immediately after (third re-queue; VERDICT
     r03 item 7 says before anything that can wedge).
  3. Flagship cube bench (pallas auto probes v6; progress exit OFF —
     the default since the negative 96^3 A/B, BENCH_LOG 2026-08-01).
  4. Progress-exit A/B: same flagship with BENCH_PROGRESS=150 (the ON
     arm) — the 670-wasted-iteration claim decides at true scale.
  5. Octree flagship ladder 22/18/12 at L4 (compile cache warm from
     round-3 entries is INVALID after the PCG restructure; the 4800 s
     budget covers one cold ~10 min compile + solve — half the old
     ~20 min after the single-instantiation change).
  6. f64-direct anchor at 150 (chipless compile exonerated the program;
     ladder steps down 128/96 on failure).
  7. Hybrid per-level breakdown.
  8. Gather/scatter combine variants at flagship fill.

Same probe/retry + wedged-grant step isolation as tools/hw_session.py.

Usage: python tools/hw_wave5.py [--deadline-min 300]
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.hw_session import log_line, run_step, start_queue  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--deadline-min", type=float, default=300)
    ap.add_argument("--log", default=os.path.join("docs", "HW_SESSION.log"))
    args = ap.parse_args()
    path = start_queue("hw_wave5", args.deadline_min, args.log)

    # bench.py wave posture: a dead-tunnel step must NOT re-emit an
    # earlier session's salvaged line into the session log as if fresh,
    # nor burn the 1-core host on mid-size CPU upgrades between retries
    # (those two legs exist for the round-end DRIVER invocation).
    bench_env = {"BENCH_SALVAGE": "0", "BENCH_CPU_UPGRADE": "0"}

    # 0. Cache-key identity (VERDICT r04 weak #4): does the remote
    # backend hit the chipless-seeded .jax_cache entries?  Decides
    # whether the pre-warmed flagship programs load in seconds or pay
    # cold compiles — knowing which is worth 5 minutes up front.
    run_step(path, "cache-key identity check",
             ["tools/cache_key_check.py"], timeout=600)
    # 1. The fused-kernel A/B this repo's perf thesis rides on.
    run_step(path, "matvec A/B v6+v8 vs XLA forms",
             ["examples/bench_matvec.py", "150"],
             env_extra={"BENCH_MATVEC_VARIANTS": "v6,v8"}, timeout=2400)
    # 2. Per-op split while the grant is clean (owed since wave 1).
    run_step(path, "iteration breakdown",
             ["examples/bench_iter_breakdown.py", "150"], timeout=2400)
    # bench.py's internal wall budget (default 1680 s, sized for the
    # round-end driver's ~1800 s window) must be widened to each wave
    # step's ACTUAL timeout, or the watchdog would emit the provisional
    # line mid-step with half the budget unused.
    # 3. Flagship cube (v6 probe live; progress exit OFF — the default
    # since the negative 96^3 A/B, docs/BENCH_LOG.md 2026-08-01).
    run_step(path, "flagship (v6 probe, progress off)", ["bench.py"],
             env_extra=dict(bench_env, BENCH_WALL_BUDGET_S="3480"),
             timeout=3600, force_gate=True)
    # 4. Progress-exit A/B at the only scale where it can pay.  The CPU
    # A/B at 96^3 measured the exit NEGATIVE (+24% iterations) and the
    # default flipped OFF (docs/BENCH_LOG.md 2026-08-01) — this arm now
    # A/Bs the ON side at the true flagship.
    run_step(path, "flagship progress=150 A/B", ["bench.py"],
             env_extra=dict(bench_env, BENCH_PROGRESS="150",
                            BENCH_WALL_BUDGET_S="3480"), timeout=3600)
    # 5. Octree flagship (gather combine, halved compile after the
    # single-instantiation restructure).
    run_step(path, "octree flagship", ["bench.py"],
             env_extra=dict(bench_env, BENCH_MODEL="octree",
                            BENCH_WALL_BUDGET_S="4680"), timeout=4800,
             force_gate=True)
    # 6. f64-direct anchor at the full 150^3 (program exonerated
    # chiplessly at 106 s; earlier failures were service weather).
    run_step(path, "f64 direct anchor 150", ["bench.py"],
             env_extra=dict(bench_env, BENCH_MODE="direct",
                            BENCH_DTYPE="float64",
                            BENCH_WALL_BUDGET_S="4680"),
             timeout=4800, force_gate=True)
    # 7/8. Remaining owed microbenchmarks.
    run_step(path, "hybrid breakdown",
             ["examples/bench_hybrid_breakdown.py"], timeout=2400)
    run_step(path, "gather/scatter variants", ["examples/bench_gather.py"],
             timeout=2400)
    log_line(path, "hw_wave5 complete")


if __name__ == "__main__":
    main()
