#!/usr/bin/env python
"""Recovery-path lint: no silently-swallowed broad exception handlers.

The resilience posture only works if every broad ``except`` in the
solve/cache/recovery layers does one of three things:

* **re-raises** (possibly after cleanup — the one-shot dispatch path's
  donated-carry restore is the canonical example), or
* **records** what happened — a metrics call (``.event``/``.inc``/
  ``.note``/``.gauge``), a ``warnings.warn``, or the bench's ``_log`` —
  so the JSONL stream / stderr breadcrumbs show the swallow, or
* carries an explicit ``# noqa: BLE001`` justification on the handler
  line (the repo convention for best-effort cache/IO paths where a
  failure legitimately degrades to a miss).

A bare ``except:``/``except Exception:`` that silently ``pass``es in
``solver/``, ``cache/``, ``resilience/`` or ``validate/`` is exactly
how a breakdown or device loss turns into a wrong answer with no trail
— this lint makes that unrepresentable.

Usage::

    python tools/check_recovery_paths.py [PATH ...]

With no PATH arguments, scans the default scope (the four packages
above).  Exits non-zero listing each violation; wired into tier-1 via
``tests/test_recovery_paths.py`` like the telemetry-schema lint.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import List

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "pcg_mpi_solver_tpu")
DEFAULT_SCOPE = (
    os.path.join(PKG, "solver"),
    os.path.join(PKG, "cache"),
    os.path.join(PKG, "resilience"),
    os.path.join(PKG, "validate"),
)

# Exception names considered "broad" when caught: anything narrower
# (OSError, ValueError, ...) expresses an expectation and is exempt.
_BROAD = {"Exception", "BaseException"}

# A call to any of these names (bare or attribute) inside the handler
# counts as recording the failure.
_LOG_CALLS = {"event", "inc", "note", "gauge", "warn", "warning",
              "exception", "_log"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:                            # bare `except:`
        return True
    names = []
    if isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    elif isinstance(t, ast.Name):
        names = [t.id]
    return any(n in _BROAD for n in names)


def _handler_ok(handler: ast.ExceptHandler, lines: List[str]) -> bool:
    # explicit justification on the `except` line (repo convention)
    line = lines[handler.lineno - 1]
    if "noqa" in line and "BLE001" in line:
        return True
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            f = node.func
            name = (f.attr if isinstance(f, ast.Attribute)
                    else getattr(f, "id", ""))
            if name in _LOG_CALLS:
                return True
    return False


def check_source(source: str, path: str = "<source>") -> List[str]:
    """Violations in one python source blob (path used for labels)."""
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [f"{path}: unparseable ({e})"]
    lines = source.splitlines()
    errs = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and _is_broad(node) \
                and not _handler_ok(node, lines):
            errs.append(
                f"{path}:{node.lineno}: broad `except` neither re-raises, "
                "logs a metrics/warning event, nor carries a "
                "`# noqa: BLE001` justification")
    return errs


def check_file(path: str) -> List[str]:
    try:
        with open(path, encoding="utf-8") as f:
            source = f.read()
    except OSError as e:
        return [f"{path}: unreadable ({e})"]
    return check_source(source, path)


def iter_py_files(paths) -> List[str]:
    out = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, files in os.walk(p):
                if "__pycache__" in root:
                    continue
                out.extend(os.path.join(root, fn) for fn in sorted(files)
                           if fn.endswith(".py"))
        elif p.endswith(".py"):
            out.append(p)
    return out


def main(argv=None) -> int:
    paths = list(argv if argv is not None else sys.argv[1:]) or \
        list(DEFAULT_SCOPE)
    files = iter_py_files(paths)
    if not files:
        print("check_recovery_paths: no python files to check")
        return 0
    errors = []
    for f in files:
        errors.extend(check_file(f))
    for e in errors:
        print(e, file=sys.stderr)
    if errors:
        print(f"check_recovery_paths: {len(errors)} violation(s) in "
              f"{len(files)} file(s)", file=sys.stderr)
        return 1
    print(f"check_recovery_paths: {len(files)} file(s) OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
