#!/usr/bin/env python
"""Recovery-path lint: no silently-swallowed broad exception handlers —
thin shim over the analysis/ ``recovery-paths`` rule (same CLI, same
exit codes).

Every broad ``except`` in the scanned packages must **re-raise**
(possibly after cleanup), **record** what happened (a metrics
``.event``/``.inc``/``.note``/``.gauge`` call, ``warnings.warn``, or the
bench's ``_log``), or carry an explicit ``# noqa: BLE001`` justification
on the handler line.  The default scope now covers ``solver/``,
``cache/``, ``resilience/``, ``validate/`` AND (ISSUE 7) ``ops/``,
``parallel/``, ``obs/`` — see
``pcg_mpi_solver_tpu/analysis/rules_ast.py`` for the implementation and
rationale.

Usage::

    python tools/check_recovery_paths.py [PATH ...]

With no PATH arguments, scans the default scope.  Exits non-zero listing
each violation; wired into tier-1 via ``tests/test_recovery_paths.py``
and into ``pcg-tpu lint`` as the ``recovery-paths`` rule.
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from pcg_mpi_solver_tpu.analysis.rules_ast import (  # noqa: E402,F401
    DEFAULT_SCOPE, check_file, check_source, iter_py_files)


def main(argv=None) -> int:
    paths = list(argv if argv is not None else sys.argv[1:]) or \
        list(DEFAULT_SCOPE)
    files = iter_py_files(paths)
    if not files:
        print("check_recovery_paths: no python files to check")
        return 0
    errors = []
    for f in files:
        errors.extend(check_file(f))
    for e in errors:
        print(e, file=sys.stderr)
    if errors:
        print(f"check_recovery_paths: {len(errors)} violation(s) in "
              f"{len(files)} file(s)", file=sys.stderr)
        return 1
    print(f"check_recovery_paths: {len(files)} file(s) OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
