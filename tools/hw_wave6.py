"""Sixth-wave hardware queue (round 5): the measurements still owed
after the 2026-08-01 live window (63 min, 08:27-09:30Z) closed.

That window banked the north star — the 150^3 flagship at flag=0 /
743.8M dof-iter/s / vs_baseline 21.9 (persisted in bench_salvage.json)
— plus the matvec A/B and the per-op breakdown.  It also proved the
deployed terminal Mosaic rejects v6/v8, which is why this queue leads
with the v9 kernel written in response.  Owed and ordered by
value-per-minute-of-window (short windows die on big compiles, so the
cheap high-information step goes first and the compile-heavy octree
before the cheaper-but-lower-stakes f64 anchor):

  1. matvec A/B v9 — first hardware compile+execution of the kernel
     family (the perf thesis).  Minutes.
  2. octree flagship — the reference's real problem class; no octree
     model has ever SOLVED on the TPU (VERDICT r04 next #3).
  3. f64-direct anchor at 150^3, ladder 128/96 (VERDICT r04 next #4).
  4. flagship with v9 ENGAGED — only if step 1 measured v9 beating the
     13.74 ms/matvec gse form (upgrades the salvaged artifact line).
  5. progress=150 A/B, hybrid breakdown, gather variants (leftovers).

Usage: python tools/hw_wave6.py [--deadline-min 300]
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.hw_session import log_line, run_step, start_queue  # noqa: E402
from tools.hw_v9_ab import maybe_engage_flagship, run_v9_ab  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--deadline-min", type=float, default=300)
    ap.add_argument("--log", default=os.path.join("docs", "HW_SESSION.log"))
    args = ap.parse_args()
    path = start_queue("hw_wave6", args.deadline_min, args.log)

    bench_env = {"BENCH_SALVAGE": "0", "BENCH_CPU_UPGRADE": "0"}

    # 0. cache-key identity (VERDICT r04 weak #4) — the seed manifest
    # now exists (.jax_cache_manifest.json, generated 2026-08-01), so
    # this finally ANSWERS whether chipless pre-warming helps remotely.
    run_step(path, "cache-key identity check",
             ["tools/cache_key_check.py"], timeout=600,
             ok_rcs=(0, 4))      # 4 = determined MISMATCH, not a failure

    gse_ms, v9_ms = run_v9_ab(path)

    run_step(path, "octree flagship", ["bench.py"],
             env_extra=dict(bench_env, BENCH_MODEL="octree",
                            BENCH_WALL_BUDGET_S="4680"), timeout=4800,
             force_gate=True)
    run_step(path, "f64 direct anchor 150", ["bench.py"],
             env_extra=dict(bench_env, BENCH_MODE="direct",
                            BENCH_DTYPE="float64",
                            BENCH_WALL_BUDGET_S="4680"),
             timeout=4800, force_gate=True)

    maybe_engage_flagship(path, gse_ms, v9_ms)

    run_step(path, "flagship progress=150 A/B", ["bench.py"],
             env_extra=dict(bench_env, BENCH_PROGRESS="150",
                            BENCH_WALL_BUDGET_S="3480"), timeout=3600,
             force_gate=True)
    run_step(path, "hybrid breakdown",
             ["examples/bench_hybrid_breakdown.py"], timeout=2400)
    run_step(path, "gather/scatter variants", ["examples/bench_gather.py"],
             timeout=2400)
    log_line(path, "hw_wave6 complete")


if __name__ == "__main__":
    main()
