"""Scalar Poisson/diffusion problem class (BASELINE.json config 2): the
general matvec/PCG machinery at 1 dof per node, d=8 type blocks — proving
the framework is not hardwired to 3-dof elasticity."""

import jax.numpy as jnp
import numpy as np
import pytest

from pcg_mpi_solver_tpu.config import RunConfig, SolverConfig, TimeHistoryConfig
from pcg_mpi_solver_tpu.models.synthetic import make_poisson_model
from pcg_mpi_solver_tpu.ops.matvec import Ops, device_data
from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
from pcg_mpi_solver_tpu.parallel.partition import partition_model
from pcg_mpi_solver_tpu.solver.driver import Solver

from tests.test_matvec import global_to_parts, parts_to_global


def test_laplacian_element_matrix():
    """Rigid (constant) mode is in the kernel; row sums vanish; SPD on the
    complement."""
    from pcg_mpi_solver_tpu.models.element import hex_laplacian

    Ke = hex_laplacian(h=1.0, k=1.0)
    assert Ke.shape == (8, 8)
    np.testing.assert_allclose(Ke @ np.ones(8), 0.0, atol=1e-14)
    np.testing.assert_allclose(Ke, Ke.T, atol=1e-14)
    w = np.linalg.eigvalsh(Ke)
    assert w[0] > -1e-14 and w[1] > 1e-6      # one zero mode, rest positive


@pytest.mark.parametrize("n_parts,hetero", [(1, False), (4, True)])
def test_poisson_matvec_vs_dense(n_parts, hetero):
    model = make_poisson_model(4, 3, 3, h=0.5, heterogeneous=hetero, seed=2)
    pm = partition_model(model, n_parts)
    assert pm.ell is None                     # 1 dof/node -> flat path
    data = device_data(pm)
    ops = Ops.from_model(pm)
    x = np.random.default_rng(1).normal(size=model.n_dof)
    y = ops.matvec(data, jnp.asarray(global_to_parts(pm, x)))
    np.testing.assert_allclose(parts_to_global(pm, y),
                               model.assemble_csr() @ x,
                               rtol=1e-10, atol=1e-10)


def test_poisson_pcg_vs_scipy():
    import scipy.sparse.linalg as spla

    model = make_poisson_model(5, 4, 4, heterogeneous=True, seed=3)
    cfg = RunConfig(
        solver=SolverConfig(tol=1e-10, max_iter=2000),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]),
    )
    s = Solver(model, cfg, mesh=make_mesh(4), n_parts=4)
    assert s.backend == "general"
    res = s.step(1.0)
    assert res.flag == 0 and res.relres <= 1e-10
    u = s.displacement_global()

    K = model.assemble_csr().tocsc()
    free = model.dof_eff
    u_ref = np.zeros(model.n_dof)
    u_ref[free] = spla.spsolve(K[np.ix_(free, free)], model.F[free])
    np.testing.assert_allclose(u, u_ref, rtol=1e-7,
                               atol=1e-10 * np.abs(u_ref).max())


# Poisson 5x4x4 heterogeneous (seed 3), tol=1e-10, Jacobi, 4 parts on 4
# devices.  Pinned at round 2 (same role as the cube goldens,
# tests/test_goldens.py).
GOLDEN_POISSON = {"iters": 30, "checksum": 725.442452128879}


def test_poisson_golden():
    model = make_poisson_model(5, 4, 4, heterogeneous=True, seed=3)
    cfg = RunConfig(
        solver=SolverConfig(tol=1e-10, max_iter=2000),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]),
    )
    s = Solver(model, cfg, mesh=make_mesh(4), n_parts=4)
    res = s.step(1.0)
    assert res.flag == 0
    assert abs(res.iters - GOLDEN_POISSON["iters"]) <= 1, res.iters
    checksum = float(np.abs(s.displacement_global()).sum())
    assert np.isclose(checksum, GOLDEN_POISSON["checksum"], rtol=1e-8), checksum


def test_mdf_rejects_scalar_models(tmp_path):
    """The MDF schema is the reference's 3-dof elasticity format; writing
    a scalar model must fail loudly, not corrupt the 3-component layout."""
    from pcg_mpi_solver_tpu.models.mdf import write_mdf

    model = make_poisson_model(3, 3, 3)
    with pytest.raises(ValueError, match="3-dof-per-node"):
        write_mdf(model, str(tmp_path / "mdf"))


def test_poisson_partition_count_parity():
    model = make_poisson_model(4, 4, 4, heterogeneous=True, seed=1)
    runs = {}
    for n_parts in (1, 8):
        cfg = RunConfig(
            solver=SolverConfig(tol=1e-9, max_iter=2000),
            time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]),
        )
        s = Solver(model, cfg, mesh=make_mesh(n_parts), n_parts=n_parts)
        res = s.step(1.0)
        assert res.flag == 0
        runs[n_parts] = (res.iters, s.displacement_global())
    assert abs(runs[8][0] - runs[1][0]) <= 1
    np.testing.assert_allclose(runs[8][1], runs[1][1], rtol=1e-7,
                               atol=1e-10 * np.abs(runs[1][1]).max())


def test_poisson_dirichlet_physics():
    """k uniform, u(0)=0, u(L)=1, no source: the solution is the linear
    ramp u = x/L (exact for trilinear elements)."""
    model = make_poisson_model(5, 3, 3, h=1.0, load="dirichlet",
                               load_value=1.0, source=0.0)
    cfg = RunConfig(
        solver=SolverConfig(tol=1e-12, max_iter=2000),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]),
    )
    s = Solver(model, cfg, mesh=make_mesh(2), n_parts=2)
    res = s.step(1.0)
    assert res.flag == 0
    u = s.displacement_global()
    np.testing.assert_allclose(u, model.node_coords[:, 0] / 5.0, atol=1e-9)


def test_poisson_solve_and_vtk_export(tmp_path):
    """Full pipeline on the scalar class: solve with frame exports, then
    write .vtu files (U exported as a scalar point field)."""
    from pcg_mpi_solver_tpu.utils.io import RunStore
    from pcg_mpi_solver_tpu.vtk.export import export_vtk
    from pcg_mpi_solver_tpu.vtk.writer import read_vtu_arrays

    model = make_poisson_model(4, 3, 3, heterogeneous=True, seed=5)
    cfg = RunConfig(
        scratch_path=str(tmp_path), run_id="poisson",
        solver=SolverConfig(tol=1e-9, max_iter=2000),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]),
    )
    s = Solver(model, cfg, mesh=make_mesh(2), n_parts=2)
    store = RunStore(cfg.result_path, cfg.model_name)
    results = s.solve(store=store)
    assert results[0].flag == 0
    files = export_vtk(model, store, export_vars=("U",), mode="Boundary")
    assert files
    arrays = read_vtu_arrays(files[-1])
    assert arrays["U"].shape == (model.n_node,)
    np.testing.assert_allclose(
        np.sort(arrays["U"]), np.sort(s.displacement_global()), atol=1e-12)


def test_poisson_strain_export_rejected(tmp_path):
    """Strain/stress export vars statically unpack 6 Voigt components;
    the scalar class must fail loudly, like the block3 layout guard."""
    from pcg_mpi_solver_tpu.utils.io import RunStore

    model = make_poisson_model(3, 3, 3)
    for bad_vars in ("U ES", "U NS"):
        cfg = RunConfig(
            scratch_path=str(tmp_path), run_id="p2",
            solver=SolverConfig(tol=1e-8, max_iter=500),
            time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0],
                                           export_vars=bad_vars),
        )
        s = Solver(model, cfg, mesh=make_mesh(2), n_parts=2)
        store = RunStore(cfg.result_path, cfg.model_name)
        with pytest.raises(ValueError, match="scalar problem class"):
            s.solve(store=store)


def test_poisson_block3_rejected():
    """block3 needs the 3-dof node layout; the scalar class must fail
    loudly, not silently misapply a 3x3 block structure."""
    model = make_poisson_model(3, 3, 3)
    cfg = RunConfig(
        solver=SolverConfig(tol=1e-8, precond="block3"),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]),
    )
    s = Solver(model, cfg, mesh=make_mesh(2), n_parts=2)
    with pytest.raises(ValueError, match="node-contiguous"):
        s.step(1.0)
