"""Fleet observability (ISSUE 16, obs/fleet.py + obs/watch.py): the
matched-anchor clock alignment and transport-vs-wait split on synthetic
per-process captures, the straggler naming on a REAL 2-process CPU
capture with an injected boundary delay, the live run monitor's stall /
ETA semantics, the clock-aligned telemetry merge, the summary CLI's
salvaged-final-heartbeat readback, the faultinject ``sleep`` straggler
simulator, the doc-schema sync rule, and the bench skew-detail
stamping."""

import gzip
import json
import os
import socket
import subprocess
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pcg_mpi_solver_tpu.obs import fleet, watch  # noqa: E402
from pcg_mpi_solver_tpu.obs.flight import (  # noqa: E402
    FlightRecorder, dispatch_anchors, flight_verdict_path, merge_shards,
    salvage_truncated_tail)
from pcg_mpi_solver_tpu.obs.schema import (  # noqa: E402
    TELEMETRY_SCHEMA, validate_bench_text, validate_event)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class _CapturingRecorder:
    def __init__(self):
        self.events = []
        self.gauges = {}

    def event(self, kind, **fields):
        ev = {"schema": TELEMETRY_SCHEMA, "t": 0.0, "kind": kind}
        ev.update(fields)
        self.events.append(ev)
        return ev

    def gauge(self, name, value):
        self.gauges[name] = value


# ----------------------------------------------------------------------
# matched-anchor clock alignment (the shared helper)
# ----------------------------------------------------------------------

def test_align_offsets_median_and_degrades():
    # two streams, constant skew: the median recovers it exactly
    offs, n = fleet.align_offsets({
        0: {("a", 0): 10.0, ("a", 1): 20.0, ("b", 0): 30.0},
        1: {("a", 0): 110.0, ("a", 1): 120.0, ("b", 0): 130.0}})
    assert n == 3 and offs == {0: 0.0, 1: 100.0}
    # odd count with one outlier (a trace-boundary clip): median ignores it
    offs, _ = fleet.align_offsets({
        0: {("a", 0): 1.0, ("a", 1): 2.0, ("a", 2): 3.0},
        1: {("a", 0): 51.0, ("a", 1): 52.0, ("a", 2): 953.0}})
    assert offs[1] == 50.0
    # even count interpolates between the middle pair
    offs, _ = fleet.align_offsets({
        0: {("a", 0): 0.0, ("a", 1): 0.0},
        1: {("a", 0): 100.0, ("a", 1): 101.0}})
    assert offs[1] == pytest.approx(100.5)
    # anchors only match when present in ALL streams
    offs, n = fleet.align_offsets({
        0: {("a", 0): 1.0}, 1: {("b", 0): 2.0}})
    assert n == 0 and offs == {0: 0.0, 1: 0.0}
    # a single stream has nothing to align against
    offs, n = fleet.align_offsets({0: {("a", 0): 1.0}})
    assert n == 0 and offs == {0: 0.0}


def test_collective_occurrences_lane_aggregation():
    def op(name, ts, dur, pid=1, tid=1):
        return {"name": name, "base": name.rsplit(".", 1)[0], "ts": ts,
                "dur": dur, "pid": pid, "tid": tid, "text": ""}

    # two device lanes of ONE process see the same program collective:
    # the k-th per-lane occurrences aggregate (end=max, dur=max), and a
    # non-collective op contributes nothing
    reps = fleet.collective_occurrences([
        op("all-reduce.1", 1000, 300, pid=1, tid=1),
        op("all-reduce.5", 1010, 250, pid=2, tid=2),   # lane 2, k=0
        op("all-reduce.9", 2000, 100, pid=1, tid=1),   # lane 1, k=1
        op("fusion.2", 0, 9999)])
    assert set(reps) == {("all-reduce", 0), ("all-reduce", 1)}
    r0 = reps[("all-reduce", 0)]
    assert r0["dur"] == 300 and r0["end"] == 1300 and r0["lanes"] == 2
    assert r0["ts"] == 1000
    assert reps[("all-reduce", 1)]["lanes"] == 1


# ----------------------------------------------------------------------
# fleet_report over synthetic per-process captures
# ----------------------------------------------------------------------

def _write_capture(pdir, colls, meta=None):
    """One process's capture dir: a trace of collective events (name,
    ts, dur) plus the profview_meta.json sidecar."""
    os.makedirs(pdir, exist_ok=True)
    events = [{"ph": "X", "name": name, "ts": ts, "dur": dur,
               "pid": 1, "tid": 1, "args": {"hlo_op": name}}
              for name, ts, dur in colls]
    with gzip.open(os.path.join(pdir, "x.trace.json.gz"), "wb") as f:
        f.write(json.dumps({"traceEvents": events}).encode())
    if meta is not None:
        with open(os.path.join(pdir, "profview_meta.json"), "w",
                  encoding="utf-8") as f:
            json.dump(meta, f)


def _skewed_fleet_root(tmp_path):
    """p0 on the reference clock; p1's clock +100000us ahead and p1 the
    straggler (arrives last -> shortest durations) on the all-reduces."""
    meta = {"iters": 10, "scope_map": {"all-reduce.1": "reduce",
                                       "all-reduce.7": "reduce",
                                       "all-gather.3": "matvec"}}
    _write_capture(str(tmp_path / "p0"),
                   [("all-reduce.1", 1000, 300),
                    ("all-reduce.7", 2000, 400),
                    ("all-gather.3", 3000, 200)], meta=meta)
    _write_capture(str(tmp_path / "p1"),
                   [("all-reduce.1", 101200, 100),
                    ("all-reduce.7", 102250, 150),
                    ("all-gather.3", 103000, 200)], meta=meta)
    return str(tmp_path)


def test_fleet_report_synthetic_transport_wait_split(tmp_path):
    rep = fleet.fleet_report(_skewed_fleet_root(tmp_path))
    assert rep["verdict"] == "ok"
    assert rep["n_processes"] == 2 and rep["matched_collectives"] == 3
    # every matched end differs by exactly the baked-in clock skew
    assert rep["clock_offsets_ms"] == {"0": 0.0, "1": 100.0}
    # transport = per-collective min duration: 100 + 150 + 200 us
    assert rep["transport_ms"] == pytest.approx(0.45)
    # wait = p0's excess (200 + 250 + 0); p1 never waited
    assert rep["wait_ms"] == pytest.approx(0.45)
    p0, p1 = rep["processes"]["0"], rep["processes"]["1"]
    assert p0["wait_ms"] == pytest.approx(0.45)
    assert p1["wait_ms"] == pytest.approx(0.0)
    assert p0["skew_frac"] == pytest.approx(0.5)       # 450/900
    assert rep["skew_frac"] == pytest.approx(450 / 1350, abs=1e-4)
    # p1 arrived last and waited least: THE straggler, rank 0
    assert rep["straggler"] == "1"
    assert p1["straggler_rank"] == 0 and p0["straggler_rank"] == 1
    assert p1["caused_wait_ms"] == pytest.approx(0.45)
    # per-iteration normalization from the sidecar's iters
    assert p0["wait_ms_per_iter"] == pytest.approx(0.045)
    # phase attribution through the sidecar scope map: the skew lives in
    # the reduce-side collectives, the all-gather is balanced
    assert rep["phases"]["reduce"]["straggler"] == "1"
    assert rep["phases"]["reduce"]["wait_ms"] == pytest.approx(0.45)
    assert rep["phases"]["matvec"]["straggler"] is None
    # rendering carries the verdict lines an operator reads
    txt = fleet.format_fleet_report(rep)
    assert "straggler: p1" in txt and "skew_frac" in txt
    assert "clock offsets vs p0" in txt
    # the telemetry event validates against the schema contract
    rec = _CapturingRecorder()
    fleet.emit_fleet_report(rec, rep)
    assert validate_event(rec.events[0]) == []
    assert rec.gauges["fleet.skew_frac"] == rep["skew_frac"]


def test_fleet_report_degrades_by_name(tmp_path):
    # empty root: nothing to attribute
    rep = fleet.fleet_report(str(tmp_path / "nowhere"))
    assert rep["n_processes"] == 0
    assert rep["verdict"].startswith("degraded:")
    # single-process capture: a real artifact, but no cross-process skew
    _write_capture(str(tmp_path / "p0"), [("all-reduce.1", 0, 100)])
    rep = fleet.fleet_report(str(tmp_path))
    assert rep["n_processes"] == 1 and rep["skew_frac"] is None
    assert "single-process" in rep["verdict"]
    assert fleet.format_fleet_report(rep)          # renders, never raises
    # two processes with NO shared collective: alignment has no anchors
    _write_capture(str(tmp_path / "p1"), [("all-gather.9", 0, 100)])
    rep = fleet.fleet_report(str(tmp_path))
    assert rep["n_processes"] == 2
    assert "no matched collectives" in rep["verdict"]
    assert rep["skew_frac"] is None


def test_bench_detail_fields_never_fabricate(tmp_path):
    rep = fleet.fleet_report(_skewed_fleet_root(tmp_path))
    det = fleet.bench_detail_fields(rep, 0)
    assert det == {"skew_frac": rep["skew_frac"], "straggler_rank": 1}
    assert fleet.bench_detail_fields(rep, 1)["straggler_rank"] == 0
    # a process the report does not carry -> {}
    assert fleet.bench_detail_fields(rep, 7) == {}
    # an unmeasurable report -> {} (absent, not null — the ISSUE 15 rule)
    assert fleet.bench_detail_fields({"skew_frac": None}) == {}
    # and the stamped line validates against the bench schema
    line = {"schema": "pcg-tpu-bench/1", "metric": "dof_iter_per_s",
            "value": 1.0, "unit": "1/s", "vs_baseline": None,
            "detail": det}
    assert validate_bench_text(json.dumps(line)) == []


def test_trend_matches_legs_across_skew_stamped_rounds(tmp_path):
    """`pcg-tpu trend` must match a skew-stamped multi-controller line
    against an unstamped earlier round of the SAME leg: the ISSUE 16
    detail fields ride along without entering the matching identity."""
    from pcg_mpi_solver_tpu.obs import trend

    def line(value, extra_detail):
        d = {"model": "cube", "n_dof": 1000, "mode": "direct",
             "backend": "general", "pcg_variant": "classic",
             "precond": "jacobi", "nrhs": 1}
        d.update(extra_detail)
        return {"schema": "pcg-tpu-bench/1", "metric": "dof_iter_per_s",
                "value": value, "unit": "1/s", "vs_baseline": None,
                "detail": d}

    old = line(100.0, {})
    new = line(101.0, {"skew_frac": 0.37, "straggler_rank": 0})
    assert trend.leg_key(old) == trend.leg_key(new)
    a = str(tmp_path / "BENCH_r97.json")
    b = str(tmp_path / "BENCH_r98.json")
    json.dump(old, open(a, "w"))
    json.dump(new, open(b, "w"))
    rep = trend.trend_report([a, b])
    assert rep["flat"] == 1 and rep["regressed"] == 0
    assert rep["legs"][0]["rounds_seen"] == 2


def test_fleet_report_cli(tmp_path, capsys):
    from pcg_mpi_solver_tpu.cli import main

    root = _skewed_fleet_root(tmp_path)
    jpath = str(tmp_path / "fleet.json")
    tpath = str(tmp_path / "fleet.jsonl")
    main(["fleet-report", root, "--json", jpath,
          "--telemetry-out", tpath])
    out = capsys.readouterr().out
    assert "straggler: p1" in out and "verdict: ok" in out
    # the saved JSON round-trips through the loader
    rep = fleet.load_fleet_report(jpath)
    assert rep is not None and rep["straggler"] == "1"
    assert fleet.load_fleet_report(str(tmp_path / "ghost")) is None
    # the telemetry artifact carries a valid fleet_report event
    evs = [json.loads(ln) for ln in open(tpath)]
    assert any(e["kind"] == "fleet_report" for e in evs)
    # an empty root is a scripting failure: exit 2
    with pytest.raises(SystemExit) as ei:
        main(["fleet-report", str(tmp_path / "void")])
    assert ei.value.code == 2


# ----------------------------------------------------------------------
# REAL 2-process CPU capture: injected boundary delay -> named straggler
# ----------------------------------------------------------------------

_FLEET_CHILD = r"""
import os, sys
N_PROCS = 2
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=4")
os.environ["PCG_TPU_FAULT_SLEEP_S"] = "0.05"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from pcg_mpi_solver_tpu.parallel.distributed import (
    init_distributed, make_global_mesh)

pid = init_distributed(coordinator_address=sys.argv[1],
                       num_processes=N_PROCS, process_id=int(sys.argv[2]))
assert jax.process_count() == N_PROCS

from pcg_mpi_solver_tpu import RunConfig, SolverConfig
from pcg_mpi_solver_tpu.solver import Solver
from pcg_mpi_solver_tpu.resilience.faultinject import FaultPlan
from pcg_mpi_solver_tpu.obs.profview import capture_solve_profile

model = make_mh_test_model("general")
# small chunks => many host-side chunk boundaries for the delay to fire
cfg = RunConfig(solver=SolverConfig(tol=1e-8, max_iter=500,
                                    iters_per_dispatch=5))
s = Solver(model, cfg, mesh=make_global_mesh(), n_parts=8,
           backend="general")
if pid == 1:
    # rank 1 sleeps 50ms at EVERY chunk boundary (warm + traced solve
    # both consume boundary indices: cover plenty) — the deterministic
    # straggler every OTHER rank then waits for at its next collective
    s.fault_plan = FaultPlan(",".join(f"sleep@{i}" for i in range(400)))
cap = capture_solve_profile(s, sys.argv[3])
print(f"RESULT {pid} iters={cap['iters']} dir={cap['artifact']}",
      flush=True)
"""


@pytest.mark.skipif(os.environ.get("PCG_TPU_SKIP_MULTIPROC") == "1",
                    reason="multi-process test disabled")
def test_two_process_capture_names_delayed_rank_straggler(tmp_path,
                                                          capsys):
    """End to end on real gloo collectives: a 2-process CPU solve where
    rank 1 is artificially delayed at every chunk boundary
    (faultinject ``sleep``) must produce a fleet report that names rank
    1 the straggler, with the healthy rank carrying the matching wait."""
    from test_distributed import _run_multiproc

    root = str(tmp_path / "cap")
    results = _run_multiproc(tmp_path, _FLEET_CHILD, 2, [root])
    assert len(results) == 2
    # each process captured into its own p<idx>/ subdir
    assert os.path.isdir(os.path.join(root, "p0"))
    assert os.path.isdir(os.path.join(root, "p1"))

    rep = fleet.fleet_report(root)
    assert rep["n_processes"] == 2, rep["verdict"]
    assert rep["matched_collectives"] > 0, rep["verdict"]
    assert rep["skew_frac"] is not None and rep["skew_frac"] > 0
    # the delayed rank arrived last at every collective: THE straggler
    assert rep["straggler"] == "1", rep
    assert rep["processes"]["1"]["straggler_rank"] == 0
    # ... and the healthy rank is the one that paid the wait
    assert rep["processes"]["0"]["wait_ms"] > \
        rep["processes"]["1"]["wait_ms"]
    assert rep["processes"]["1"]["caused_wait_ms"] > \
        rep["processes"]["0"]["caused_wait_ms"]

    # the CLI reads the same capture back
    from pcg_mpi_solver_tpu.cli import main

    main(["fleet-report", root])
    out = capsys.readouterr().out
    assert "straggler: p1" in out


# ----------------------------------------------------------------------
# live run monitor: stall semantics, salvage, ETA
# ----------------------------------------------------------------------

def _ev(t, kind, **fields):
    d = {"schema": TELEMETRY_SCHEMA, "t": t, "kind": kind}
    d.update(fields)
    return json.dumps(d)


def test_watch_statuses_and_stall_needs_all_shards_silent(tmp_path):
    now = 1000.0
    base = str(tmp_path / "run.jsonl")
    # no shards on disk at all
    assert watch.watch_snapshot(base, now=now)["status"] == "empty"
    # one fresh shard: running
    (tmp_path / "run.p0.jsonl").write_text(
        _ev(now - 1.0, "note", msg="alive") + "\n")
    snap = watch.watch_snapshot(base, now=now, stall_after_s=5.0)
    assert snap["status"] == "running" and snap["n_shards"] == 1
    # a second, silent shard: NOT a stall — one slow host is skew, not a
    # wedged run
    (tmp_path / "run.p1.jsonl").write_text(
        _ev(now - 60.0, "note", msg="old") + "\n")
    snap = watch.watch_snapshot(base, now=now, stall_after_s=5.0)
    assert snap["status"] == "running"
    # ALL shards silent past the threshold: stall, detected within one
    # heartbeat-interval-sized threshold of the last record
    (tmp_path / "run.p0.jsonl").write_text(
        _ev(now - 6.0, "note", msg="stale") + "\n")
    snap = watch.watch_snapshot(base, now=now, stall_after_s=5.0)
    assert snap["status"] == "stalled"
    assert snap["silent_s"] == pytest.approx(6.0)
    txt = watch.format_watch(snap)
    assert "STALL" in txt and "STALLED" in txt
    rec = _CapturingRecorder()
    watch.emit_watch_events(rec, snap)
    kinds = [e["kind"] for e in rec.events]
    assert kinds == ["watch", "stall"]
    assert all(validate_event(e) == [] for e in rec.events)
    # done: a run_summary landed and nothing is in flight
    (tmp_path / "run.p0.jsonl").write_text(
        _ev(now - 6.0, "run_summary", counters={}, gauges={}) + "\n")
    (tmp_path / "run.p1.jsonl").write_text(
        _ev(now - 60.0, "run_summary", counters={}, gauges={}) + "\n")
    assert watch.watch_snapshot(base, now=now,
                                stall_after_s=5.0)["status"] == "done"


def test_watch_salvaged_heartbeat_defers_stall(tmp_path):
    """A final heartbeat cut mid-write is the run's last breath: the
    salvaged timestamp must keep the shard alive, not let the monitor
    flag a live run that was merely killed mid-write... of a line it
    wrote moments ago."""
    now = 1000.0
    p = tmp_path / "run.jsonl"
    cut = ('{"schema": "%s", "t": %s, "kind": "flight", '
           '"op": "heartbeat", "mono": 55.5, "se' % (TELEMETRY_SCHEMA,
                                                     now - 1.0))
    p.write_text(_ev(now - 30.0, "note", msg="old") + "\n" + cut)
    assert salvage_truncated_tail(str(p))["t"] == now - 1.0
    snap = watch.watch_snapshot(str(p), now=now, stall_after_s=5.0)
    assert snap["status"] == "running"
    assert snap["shards"][0]["salvaged_tail"]
    # without the salvaged tail the same stream would read stalled
    p.write_text(_ev(now - 30.0, "note", msg="old") + "\n")
    snap = watch.watch_snapshot(str(p), now=now, stall_after_s=5.0)
    assert snap["status"] == "stalled"


def test_watch_eta_cost_model_times_observed_rate(tmp_path):
    now = 1000.0
    p = tmp_path / "run.jsonl"
    lines = [
        _ev(now - 3.0, "cost_model", pcg_variant="classic",
            precond="jacobi", nrhs=1, backend="general", phases={},
            predicted_ms_per_iter=2.0),
        _ev(now - 2.0, "dispatch", name="cycle", wall_s=0.1, cold=True),
        _ev(now - 1.0, "resid_trace", step=1, n_recorded=4,
            truncated=False, normr=[1.0, 0.1, 0.01, 1e-3]),
    ]
    p.write_text("\n".join(lines) + "\n")
    snap = watch.watch_snapshot(str(p), now=now, stall_after_s=60.0,
                                tol=1e-8)
    # one decade per iteration observed; 5 decades left to tol; 2 ms/iter
    assert snap["rate_decades_per_iter"] == pytest.approx(-1.0)
    assert snap["last_relres"] == pytest.approx(1e-3)
    assert snap["eta_s"] == pytest.approx(0.01)
    assert snap["dispatches"] == {"cycle": 1}
    assert "ETA to tol" in watch.format_watch(snap)
    # remove the cost model: the ETA degrades to a NAMED reason
    p.write_text(lines[2] + "\n")
    snap = watch.watch_snapshot(str(p), now=now, stall_after_s=60.0)
    assert snap["eta_s"] is None
    assert "cost_model" in snap["eta_reason"]
    # steps-only stream: the rate falls back to relres over cumulative
    # iters
    p.write_text("\n".join([
        _ev(now - 2.0, "step", step=1, flag=0, relres=1e-2, iters=10,
            wall_s=0.1),
        _ev(now - 1.0, "step", step=2, flag=0, relres=1e-4, iters=10,
            wall_s=0.1)]) + "\n")
    snap = watch.watch_snapshot(str(p), now=now, stall_after_s=60.0)
    assert snap["rate_decades_per_iter"] == pytest.approx(-0.2)


def test_watch_cli_once_exit_codes(tmp_path, capsys):
    from pcg_mpi_solver_tpu.cli import main

    p = tmp_path / "run.jsonl"
    p.write_text(_ev(time.time(), "note", msg="alive") + "\n")
    # healthy snapshot: returns normally
    main(["watch", str(p), "--once"])
    assert "RUNNING" in capsys.readouterr().out
    # stalled snapshot: exit 3 (the scriptable probe)
    p.write_text(_ev(time.time() - 120.0, "note", msg="stale") + "\n")
    tout = str(tmp_path / "mon.jsonl")
    with pytest.raises(SystemExit) as ei:
        main(["watch", str(p), "--once", "--stall-after", "5",
              "--telemetry-out", tout])
    assert ei.value.code == 3
    evs = [json.loads(ln) for ln in open(tout)]
    assert [e["kind"] for e in evs if e["kind"] in ("watch", "stall")] \
        == ["watch", "stall"]


def test_stall_threshold_resolution(monkeypatch):
    assert watch.stall_threshold_s(7.5) == 7.5
    monkeypatch.setenv("PCG_TPU_FLIGHT_HEARTBEAT_S", "2.0")
    assert watch.stall_threshold_s() == pytest.approx(6.0)
    monkeypatch.setenv("PCG_TPU_FLIGHT_HEARTBEAT_S", "typo")
    assert watch.stall_threshold_s() == pytest.approx(
        watch.STALL_HEARTBEATS * 5.0)


# ----------------------------------------------------------------------
# telemetry-merge --align collectives over clock-skewed shards
# ----------------------------------------------------------------------

def test_merge_align_collectives_restores_true_order(tmp_path):
    """Two shards of one run whose host clocks disagree by 100.5s: the
    dispatch completions are the shared anchors, and alignment must
    interleave the events in TRUE order (raw-t ordering would sort every
    p1 event after every p0 event)."""
    p0 = tmp_path / "run.p0.jsonl"
    p1 = tmp_path / "run.p1.jsonl"
    p0.write_text("\n".join([
        _ev(10.0, "dispatch", name="cycle", wall_s=0.1, cold=True),
        _ev(15.0, "note", msg="mid0"),
        _ev(20.0, "dispatch", name="cycle", wall_s=0.1, cold=False),
    ]) + "\n")
    p1.write_text("\n".join([
        _ev(110.5, "dispatch", name="cycle", wall_s=0.1, cold=True),
        _ev(112.0, "note", msg="mid1"),
        _ev(120.5, "dispatch", name="cycle", wall_s=0.1, cold=False),
    ]) + "\n")
    out = str(tmp_path / "merged.jsonl")
    # without alignment: raw clocks, p1's note sorts last
    stats = merge_shards([str(p0), str(p1)], out)
    assert "align" not in stats
    msgs = [e["msg"] for e in map(json.loads, open(out))
            if e["kind"] == "note"]
    assert msgs == ["mid0", "mid1"]
    # with alignment: p1's offset (+100.5s) is recovered from the two
    # matched cycle completions and mid1 (true t=11.5) precedes mid0
    stats = merge_shards([str(p0), str(p1)], out, align="collectives")
    al = stats["align"]
    assert al["matched_anchors"] == 2
    assert al["offsets_s"]["run.p1.jsonl"] == pytest.approx(100.5)
    evs = [json.loads(ln) for ln in open(out)]
    msgs = [e["msg"] for e in evs if e["kind"] == "note"]
    assert msgs == ["mid1", "mid0"]
    # t_aligned stamped, raw t preserved
    mid1 = next(e for e in evs if e.get("msg") == "mid1")
    assert mid1["t"] == 112.0
    assert mid1["t_aligned"] == pytest.approx(11.5)


def test_dispatch_anchors_from_flight_and_telemetry():
    evs = [
        {"t": 1.0, "kind": "dispatch", "name": "cycle"},
        {"t": 2.0, "kind": "flight", "op": "end", "name": "dispatch:step"},
        {"t": 3.0, "kind": "dispatch", "name": "cycle"},
        {"t": 4.0, "kind": "flight", "op": "begin",
         "name": "dispatch:step"},            # begins are not completions
        {"t": 5.0, "kind": "note", "msg": "x"},
        {"kind": "dispatch", "name": "cycle"},  # no t: unusable
    ]
    a = dispatch_anchors(evs)
    assert a == {("cycle", 0): 1.0, ("dispatch:step", 0): 2.0,
                 ("cycle", 1): 3.0}


def test_merge_align_cli_prints_offsets(tmp_path, capsys):
    from pcg_mpi_solver_tpu.cli import main

    (tmp_path / "run.p0.jsonl").write_text(
        _ev(10.0, "dispatch", name="cycle", wall_s=0.1, cold=True) + "\n")
    (tmp_path / "run.p1.jsonl").write_text(
        _ev(110.0, "dispatch", name="cycle", wall_s=0.1, cold=True) + "\n")
    out = str(tmp_path / "m.jsonl")
    main(["telemetry-merge", str(tmp_path / "run.jsonl"), "--out", out,
          "--align", "collectives"])
    stdout = capsys.readouterr().out
    assert ">clock alignment (collectives): 1 matched anchor(s)" in stdout
    assert "+100.000000s" in stdout
    # no shared anchors: the mode degrades to raw-t ordering and says so
    (tmp_path / "run.p1.jsonl").write_text(
        _ev(110.0, "note", msg="no anchors here") + "\n")
    main(["telemetry-merge", str(tmp_path / "run.jsonl"), "--out", out,
          "--align", "collectives"])
    assert "no matched dispatch anchors" in capsys.readouterr().out


# ----------------------------------------------------------------------
# summary CLI: a truncated FINAL heartbeat still counts as the last one
# ----------------------------------------------------------------------

def test_summary_salvages_truncated_final_heartbeat(tmp_path, capsys):
    from pcg_mpi_solver_tpu.cli import main

    p = tmp_path / "run.jsonl"
    f = FlightRecorder(str(p), heartbeat_s=3600)
    f.begin("dispatch:cycle")
    f.close()
    # append the dead-tunnel signature: a heartbeat cut mid-write with a
    # NEWER timestamp than any complete record
    with open(p, "a", encoding="utf-8") as fh:
        fh.write('{"schema": "%s", "t": 9e9, "kind": "flight", '
                 '"op": "heartbeat", "mono": 9e8, "hos'
                 % TELEMETRY_SCHEMA)
    v = flight_verdict_path(str(p))
    assert v["verdict"] == "died"               # the begin never closed
    assert v["salvaged_tail"] and v["last_wall"] == 9e9
    assert v["last_mono"] == 9e8
    main(["summary", str(p)])
    out = capsys.readouterr().out
    assert "[salvaged from the truncated final line]" in out
    assert "t=9000000000.000" in out
    # a complete final line must NOT claim salvage
    f2 = FlightRecorder(str(tmp_path / "ok.jsonl"), heartbeat_s=3600)
    with f2.record("dispatch:fine"):
        pass
    f2.close()
    v2 = flight_verdict_path(str(tmp_path / "ok.jsonl"))
    assert "salvaged_tail" not in v2
    assert salvage_truncated_tail(str(tmp_path / "ok.jsonl")) is None


# ----------------------------------------------------------------------
# faultinject: the ``sleep`` straggler simulator
# ----------------------------------------------------------------------

def test_fault_sleep_mode_boundary_semantics(monkeypatch):
    from pcg_mpi_solver_tpu.resilience.faultinject import FaultPlan

    monkeypatch.setenv("PCG_TPU_FAULT_SLEEP_S", "0.01")
    plan = FaultPlan("sleep@0,sleep@2*2")
    assert plan.sleep_s == pytest.approx(0.01)
    carry = {"r": None}
    t0 = time.monotonic()
    out = plan.at_boundary(dict(carry))       # boundary 0: fires
    assert out == carry                       # a delay, not a poison
    plan.at_boundary(dict(carry))             # boundary 1: no fault
    plan.at_boundary(dict(carry))             # boundary 2: fires
    plan.at_boundary(dict(carry))             # boundary 3: *2 consumed?
    assert time.monotonic() - t0 >= 0.02
    fired = [(f["mode"], f["point"], f["at"]) for f in plan.fired]
    # boundary indices advance per call, so each @idx fires at most once
    # per pass; the *count budget covers re-visits (a recovery replay)
    assert fired == [("sleep", "boundary", 0), ("sleep", "boundary", 2)]
    assert plan.armed                          # one firing of @2 left
    # recorder attribution: mode/point/at ride the fault event
    rec = _CapturingRecorder()
    plan3 = FaultPlan("sleep@0", recorder=rec)
    plan3.at_boundary(dict(carry))
    assert rec.events[0]["mode"] == "sleep"
    assert validate_event(rec.events[0]) == []
    # a typo'd duration env falls back to the default, never raises
    monkeypatch.setenv("PCG_TPU_FAULT_SLEEP_S", "oops")
    assert FaultPlan("sleep@0").sleep_s == pytest.approx(0.25)


def test_fault_sleep_parse_rejects_bad_domains():
    from pcg_mpi_solver_tpu.resilience.faultinject import FaultPlan

    # sleep is a boundary-domain mode: step/column triggers are refused
    with pytest.raises(ValueError):
        FaultPlan("sleep@s:1")
    with pytest.raises(ValueError):
        FaultPlan("sleep@col:0")


# ----------------------------------------------------------------------
# analysis: doc-schema sync rule
# ----------------------------------------------------------------------

def test_doc_schema_sync_seeded_violation():
    from pcg_mpi_solver_tpu.analysis.rules_artifacts import (
        check_doc_schema_sync, documented_event_kinds)

    doc = ("| kind | fields |\n"
           "| --- | --- |\n"
           "| `step` | step, flag |\n"
           "| `dispatch` | name |\n")
    assert documented_event_kinds(doc) == {"step", "dispatch"}
    errs = check_doc_schema_sync(doc, kinds=("step", "dispatch", "stall"))
    assert len(errs) == 1 and "`stall`" in errs[0]
    assert check_doc_schema_sync(doc, kinds=("step",)) == []


def test_doc_schema_sync_clean_on_current_tree():
    """Every kind in EVENT_KINDS has a row in OBSERVABILITY.md's event
    table — the rule the fast lint gate now enforces."""
    from pcg_mpi_solver_tpu.analysis.rules_artifacts import (
        EVENT_TABLE_DOC, check_doc_schema_sync)

    with open(os.path.join(REPO, EVENT_TABLE_DOC), encoding="utf-8") as f:
        assert check_doc_schema_sync(f.read()) == []
