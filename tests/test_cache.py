"""Warm-path subsystem (cache/): content-addressed partition cache, AOT
step export, donated-carry dispatch.

The contract under test is the round-5 lesson (BENCH_r05.json: 58.5 s
partition, 400+ s compiles inside a 9-minute hardware window): the SECOND
solve of the same model/n_parts/backend with a warm cache dir must perform
ZERO partitioning work (parallel/partition.py BUILD_CALLS counters) and
ZERO jit tracing of the PCG step (the host-side ``trace.step`` counter
that runs only while jax traces ``_step``), while producing the same
answer.  Donation is a pure memory optimization: bit-identical on/off.
"""

import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

from pcg_mpi_solver_tpu.cache import keys as ckeys
from pcg_mpi_solver_tpu.cache import partition_cache as pcache
from pcg_mpi_solver_tpu.config import (RunConfig, SolverConfig,
                                       TimeHistoryConfig)
from pcg_mpi_solver_tpu.models.synthetic import make_cube_model
from pcg_mpi_solver_tpu.obs.metrics import MetricsRecorder
from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
from pcg_mpi_solver_tpu.parallel.partition import BUILD_CALLS
from pcg_mpi_solver_tpu.solver.driver import Solver

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def cache_dir(tmp_path):
    """A per-test cache dir + global-config hygiene: Solver construction
    with cache_dir points jax's persistent compilation cache INTO the
    tmp dir (cache/aot.py), which pytest eventually deletes — restore
    the process-global knob so later tests never write into a grave."""
    import jax

    before = jax.config.jax_compilation_cache_dir
    yield str(tmp_path / "warm")
    jax.config.update("jax_compilation_cache_dir", before)


def _cfg(*, cache_dir="", donate=True, mode="direct", ipd=-1, tol=1e-8):
    return RunConfig(
        solver=SolverConfig(tol=tol, max_iter=2000, precision_mode=mode,
                            iters_per_dispatch=ipd, donate_carry=donate),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]),
        cache_dir=cache_dir,
    )


def _solver(model, cfg, n_dev=1, recorder=None, **kw):
    return Solver(model, cfg, mesh=make_mesh(n_dev), n_parts=n_dev,
                  recorder=recorder, **kw)


# ----------------------------------------------------------------------
# Keys: content addressing + invalidation (jax-free layer)
# ----------------------------------------------------------------------

def test_partition_key_determinism_and_invalidation(monkeypatch):
    fp = "a" * 64
    base = ckeys.partition_cache_key(fp, n_parts=8, backend="general",
                                     dtype="float64")
    again = ckeys.partition_cache_key(fp, n_parts=8, backend="general",
                                      dtype="float64")
    assert base == again
    # every knob that shapes the partition arrays re-keys the entry
    assert ckeys.partition_cache_key(fp, n_parts=4, backend="general",
                                     dtype="float64") != base
    assert ckeys.partition_cache_key(fp, n_parts=8, backend="general",
                                     dtype="float32") != base
    assert ckeys.partition_cache_key(fp, n_parts=8, backend="hybrid",
                                     dtype="float64") != base
    assert ckeys.partition_cache_key("b" * 64, n_parts=8, backend="general",
                                     dtype="float64") != base
    assert ckeys.partition_cache_key(fp, n_parts=8, backend="general",
                                     dtype="float64", method="graph") != base
    # a code bump (package version or cache schema) invalidates everything
    monkeypatch.setattr(ckeys, "PACKAGE_VERSION", "99.99.dev0")
    assert ckeys.partition_cache_key(fp, n_parts=8, backend="general",
                                     dtype="float64") != base
    monkeypatch.undo()
    monkeypatch.setattr(ckeys, "CACHE_SCHEMA", ckeys.CACHE_SCHEMA + 1)
    assert ckeys.partition_cache_key(fp, n_parts=8, backend="general",
                                     dtype="float64") != base


def test_model_fingerprint_tracks_content():
    m1 = make_cube_model(3, 2, 2, heterogeneous=True)
    m2 = make_cube_model(3, 2, 2, heterogeneous=True)
    assert ckeys.model_fingerprint(m1) == ckeys.model_fingerprint(m2)
    m3 = make_cube_model(3, 2, 2, heterogeneous=True)
    m3.F = np.asarray(m3.F).copy()
    m3.F[0] += 1.0
    assert ckeys.model_fingerprint(m3) != ckeys.model_fingerprint(m1)


def test_cache_modules_import_jax_free():
    """The package __init__ must stay jax-free (compat-shim constraint,
    pcg_mpi_solver_tpu/__init__.py) and the cache key/stats layer is
    consulted before the accelerator env is configured — importing it
    must not drag jax in."""
    code = ("import sys; import pcg_mpi_solver_tpu.cache; "
            "sys.exit(1 if 'jax' in sys.modules else 0)")
    # strip the conftest's JAX_PLATFORMS=cpu: the package __init__
    # deliberately imports jax to PIN the backend when that env is set
    # (the wedged-tunnel guard) — irrelevant to the cache modules' own
    # import graph, which is what this test pins down.
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                       capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stderr


# ----------------------------------------------------------------------
# Partition cache store: hit/miss/corruption/stats
# ----------------------------------------------------------------------

def test_cached_partition_miss_then_hit(tmp_path):
    rec = MetricsRecorder()
    built = []

    def builder():
        built.append(1)
        return {"arr": np.arange(5)}

    d = str(tmp_path)
    out1 = pcache.cached_partition(d, "k" * 32, builder, recorder=rec)
    assert built == [1] and rec.counters["cache.partition.miss"] == 1
    out2 = pcache.cached_partition(d, "k" * 32, builder, recorder=rec)
    assert built == [1], "hit must not invoke the builder"
    assert rec.counters["cache.partition.hit"] == 1
    np.testing.assert_array_equal(out1["arr"], out2["arr"])


def test_corrupt_entry_is_a_miss(tmp_path):
    d = str(tmp_path)
    key = "c" * 32
    assert pcache.store_partition(d, key, [1, 2, 3])
    path = os.path.join(d, "partition", f"{key}.zpkl")
    with open(path, "wb") as f:
        f.write(b"not a zlib pickle")
    assert pcache.load_partition(d, key) is None
    assert not os.path.exists(path), "corrupt entry must be removed"


def test_cache_stats_and_format(tmp_path):
    d = str(tmp_path)
    pcache.store_partition(d, "s" * 32, np.zeros(16))
    stats = pcache.cache_stats(d)
    assert stats["partition"]["entries"] == 1
    assert stats["partition"]["bytes"] > 0
    assert stats["aot"]["entries"] == 0
    assert "partition" in pcache.format_stats(d)


# ----------------------------------------------------------------------
# End-to-end warm path: zero partition work, zero step tracing
# ----------------------------------------------------------------------

def test_second_solve_warm_zero_partition_zero_tracing(cache_dir):
    model = make_cube_model(4, 3, 3, heterogeneous=True)

    rec_cold = MetricsRecorder()
    s1 = _solver(model, _cfg(cache_dir=cache_dir), n_dev=8,
                 recorder=rec_cold)
    assert s1.setup_cache == "cold"
    assert rec_cold.counters["cache.partition.miss"] >= 1
    r1 = s1.step(1.0)
    assert r1.flag == 0
    u1 = np.asarray(s1.displacement_global())
    calls_after_cold = dict(BUILD_CALLS)

    rec_warm = MetricsRecorder()
    s2 = _solver(model, _cfg(cache_dir=cache_dir), n_dev=8,
                 recorder=rec_warm)
    # zero partitioning work: no builder ran anywhere in parallel/
    assert dict(BUILD_CALLS) == calls_after_cold
    assert rec_warm.counters["cache.partition.hit"] >= 1
    assert "cache.partition.miss" not in rec_warm.counters
    assert s2.setup_cache == "warm"
    # zero jit tracing of the PCG step: the AOT entry was deserialized
    # (trace.step increments only inside a live trace of _step)
    assert rec_warm.counters.get("trace.step", 0) == 0
    assert rec_warm.counters.get("cache.aot.hit", 0) == 1
    r2 = s2.step(1.0)
    assert rec_warm.counters.get("trace.step", 0) == 0
    assert r2.flag == 0 and r2.iters == r1.iters
    np.testing.assert_array_equal(np.asarray(s2.displacement_global()), u1)


def test_hybrid_warm_path_recovers_elem_part(cache_dir):
    """Hybrid+mixed needs TWO consistent partitions (level-grid + the
    f64-refresh general partition on the SAME element->part map).  A
    cache hit skips make_elem_part entirely — the driver recovers the
    map from the cached partition itself; warm must be zero-build and
    answer-identical to cold."""
    from pcg_mpi_solver_tpu.models.octree import make_octree_model

    model = make_octree_model(2, 2, 2, max_level=2, n_incl=2, seed=3,
                              load="traction", load_value=1.0)
    cfg = _cfg(cache_dir=cache_dir, mode="mixed")
    rec_cold = MetricsRecorder()
    s1 = Solver(model, cfg, mesh=make_mesh(4), n_parts=4,
                backend="hybrid", recorder=rec_cold)
    assert s1.f64_refresh in ("general", "bucketed")
    assert rec_cold.counters["cache.partition.miss"] >= 2
    r1 = s1.step(1.0)
    assert r1.flag == 0
    calls_after_cold = dict(BUILD_CALLS)

    rec_warm = MetricsRecorder()
    s2 = Solver(model, _cfg(cache_dir=cache_dir, mode="mixed"),
                mesh=make_mesh(4), n_parts=4, backend="hybrid",
                recorder=rec_warm)
    assert dict(BUILD_CALLS) == calls_after_cold
    assert rec_warm.counters["cache.partition.hit"] >= 2
    assert "cache.partition.miss" not in rec_warm.counters
    assert s2.setup_cache == "warm"
    r2 = s2.step(1.0)
    assert r2.flag == 0 and r2.iters == r1.iters
    assert np.array_equal(np.asarray(s2.displacement_global()),
                          np.asarray(s1.displacement_global()))


def test_version_bump_invalidates_on_disk_entries(cache_dir, monkeypatch):
    model = make_cube_model(3, 2, 2, heterogeneous=True)
    rec1 = MetricsRecorder()
    _solver(model, _cfg(cache_dir=cache_dir), recorder=rec1)
    assert rec1.counters["cache.partition.miss"] >= 1

    rec2 = MetricsRecorder()
    _solver(model, _cfg(cache_dir=cache_dir), recorder=rec2)
    assert rec2.counters["cache.partition.hit"] >= 1

    # a package-version bump re-keys every entry: back to a miss
    monkeypatch.setattr(ckeys, "PACKAGE_VERSION", "99.99.dev0")
    rec3 = MetricsRecorder()
    _solver(model, _cfg(cache_dir=cache_dir), recorder=rec3)
    assert rec3.counters["cache.partition.miss"] >= 1
    assert "cache.partition.hit" not in rec3.counters


def test_changed_n_parts_is_a_miss(cache_dir):
    model = make_cube_model(3, 2, 2, heterogeneous=True)
    rec1 = MetricsRecorder()
    _solver(model, _cfg(cache_dir=cache_dir), n_dev=1, recorder=rec1)
    rec2 = MetricsRecorder()
    _solver(model, _cfg(cache_dir=cache_dir), n_dev=8, recorder=rec2)
    assert rec2.counters["cache.partition.miss"] >= 1
    assert "cache.partition.hit" not in rec2.counters


def test_changed_dtype_is_a_miss(cache_dir):
    model = make_cube_model(3, 2, 2, heterogeneous=True)
    cfg32 = _cfg(cache_dir=cache_dir)
    cfg32.solver.dtype = "float32"
    rec1 = MetricsRecorder()
    _solver(model, _cfg(cache_dir=cache_dir), recorder=rec1)
    rec2 = MetricsRecorder()
    _solver(model, cfg32, recorder=rec2)
    assert rec2.counters["cache.partition.miss"] >= 1
    assert "cache.partition.hit" not in rec2.counters


# ----------------------------------------------------------------------
# AOT export roundtrip (CPU backend)
# ----------------------------------------------------------------------

def test_aot_cached_step_roundtrip(tmp_path):
    import jax
    import jax.numpy as jnp

    from pcg_mpi_solver_tpu.cache import aot

    fn = jax.jit(lambda x: x * 2.0 + 1.0)
    abstract = (jax.ShapeDtypeStruct((8,), jnp.float32),)
    rec = MetricsRecorder()
    d = str(tmp_path)
    exp_cold = aot.cached_step(d, "k1", fn, abstract, recorder=rec)
    assert exp_cold is not None
    assert rec.counters["cache.aot.miss"] == 1
    exp_warm = aot.cached_step(d, "k1", fn, abstract, recorder=rec)
    assert rec.counters["cache.aot.hit"] == 1
    x = jnp.arange(8, dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(jax.jit(exp_warm.call)(x)),
                                  np.asarray(fn(x)))


def test_aot_corrupt_entry_quarantined_and_rebuilt(tmp_path):
    """A corrupt/truncated serialized AOT step is a cache MISS, not a
    crash: the bad blob is quarantined (<entry>.corrupt — kept for
    toolchain-skew forensics, matching partition_cache's corrupt-entry
    handling) and the step is re-exported in place (ISSUE 3 satellite;
    regression for a truncated file from a killed writer / torn disk)."""
    import jax
    import jax.numpy as jnp

    from pcg_mpi_solver_tpu.cache import aot

    fn = jax.jit(lambda x: x * 3.0)
    abstract = (jax.ShapeDtypeStruct((8,), jnp.float32),)
    rec = MetricsRecorder()
    d = str(tmp_path)
    assert aot.cached_step(d, "kq", fn, abstract, recorder=rec) is not None
    entry = os.path.join(d, "aot", "kq.jaxexport")
    assert os.path.exists(entry)

    # truncate the entry to half its bytes (a killed writer's artifact)
    blob = open(entry, "rb").read()
    with open(entry, "wb") as f:
        f.write(blob[: len(blob) // 2])
    exp = aot.cached_step(d, "kq", fn, abstract, recorder=rec)
    assert exp is not None                      # rebuilt, not crashed
    assert rec.counters["cache.aot.corrupt"] == 1
    assert rec.counters["cache.aot.miss"] == 2  # the corrupt read = miss
    assert os.path.exists(entry + ".corrupt")   # quarantined for forensics
    assert os.path.exists(entry)                # fresh export in place
    x = jnp.arange(8, dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(jax.jit(exp.call)(x)),
                                  np.asarray(fn(x)))

    # a zero-byte entry (torn write) reads the same way
    with open(entry, "wb"):
        pass
    assert aot.cached_step(d, "kq", fn, abstract, recorder=rec) is not None
    assert rec.counters["cache.aot.corrupt"] == 2


def test_aot_quarantine_is_lru_evicted(tmp_path, monkeypatch):
    """Quarantined .corrupt blobs share the LRU discipline (own suffix):
    version bumps re-key entries, so per-key overwrite alone would let
    them grow a long-lived shared cache dir unboundedly."""
    import jax
    import jax.numpy as jnp

    from pcg_mpi_solver_tpu.cache import aot

    d = str(tmp_path)
    fn = jax.jit(lambda x: x * 3.0)
    abstract = (jax.ShapeDtypeStruct((8,), jnp.float32),)
    assert aot.cached_step(d, "kold", fn, abstract) is not None
    old = os.path.join(d, "aot", "kold.jaxexport")
    with open(old, "wb") as f:
        f.write(b"garbage")
    assert aot.load_step(d, "kold") is None     # -> kold.jaxexport.corrupt
    assert os.path.exists(old + ".corrupt")
    monkeypatch.setenv("PCG_TPU_CACHE_GB", str(1 / 2**30))  # ~1 byte cap
    assert aot.cached_step(d, "knew", fn, abstract) is not None
    assert not os.path.exists(old + ".corrupt")


def test_persistent_compilation_cache_not_wired_on_cpu(tmp_path):
    """Regression: on the jax 0.4.x CPU backend, entries written to the
    persistent compilation cache deserialize into executables that crash
    the process flakily on a LATER same-signature compile (reproduced on
    the 8-device virtual mesh), and the cache module is sticky across
    config restores — so enable must be a no-op on CPU.  The xla/ dir is
    still created (layout is uniform); only the config stays untouched."""
    import jax

    from pcg_mpi_solver_tpu.cache import aot

    before = jax.config.jax_compilation_cache_dir
    d = aot.enable_persistent_compilation_cache(str(tmp_path))
    assert os.path.isdir(d)
    assert jax.config.jax_compilation_cache_dir == before


def test_aot_store_failure_leaves_no_tmp(tmp_path):
    from pcg_mpi_solver_tpu.cache import aot

    class Unserializable:
        def serialize(self):
            raise RuntimeError("disk on fire")

    d = str(tmp_path)
    assert aot.store_step(d, "k" * 32, Unserializable()) is False
    leftovers = [fn for _r, _d, fns in os.walk(d) for fn in fns]
    assert leftovers == [], f"tmp residue: {leftovers}"


def test_aot_entries_lru_evicted(tmp_path, monkeypatch):
    """aot/ honors the same PCG_TPU_CACHE_GB cap as partition/ — code or
    version re-keys orphan old exports, which must not pile up on a
    shared warm dir."""
    import jax
    import jax.numpy as jnp

    from pcg_mpi_solver_tpu.cache import aot

    monkeypatch.setenv("PCG_TPU_CACHE_GB", str(4096 / 2**30))  # ~4 KB cap
    d = str(tmp_path)
    exported = aot.export_step(
        jax.jit(lambda x: x + 1),
        (jax.ShapeDtypeStruct((4,), jnp.float32),))
    for i in range(8):
        assert aot.store_step(d, f"key{i:02d}", exported)
    names = sorted(os.listdir(os.path.join(d, "aot")))
    assert len(names) < 8, "size cap never evicted"
    assert "key07.jaxexport" in names, "newest entry must survive"


# ----------------------------------------------------------------------
# Donated-carry dispatch: bit-identical, warning-free
# ----------------------------------------------------------------------

@pytest.mark.parametrize("mode", ["direct", "mixed"])
def test_donation_parity_chunked(mode):
    """Chunked dispatch (the donated resumable carry) with donation on
    must be BIT-identical to donation off — donation only changes buffer
    aliasing, never values."""
    model = make_cube_model(4, 3, 3, heterogeneous=True)
    s_off = _solver(model, _cfg(donate=False, mode=mode, ipd=20))
    s_on = _solver(model, _cfg(donate=True, mode=mode, ipd=20))
    r_off, r_on = s_off.step(1.0), s_on.step(1.0)
    assert r_on.flag == 0 and r_on.iters == r_off.iters
    assert np.array_equal(np.asarray(s_on.displacement_global()),
                          np.asarray(s_off.displacement_global()))


def test_donation_parity_one_shot_multidevice():
    """One-shot path on the 8-device virtual mesh: the donated un_prev
    must not change values, and the run must not raise donation-related
    XLA copy warnings (unusable-donation = the aliasing contract broke)."""
    model = make_cube_model(4, 3, 3, heterogeneous=True)
    s_off = _solver(model, _cfg(donate=False), n_dev=8)
    r_off = s_off.step(1.0)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        s_on = _solver(model, _cfg(donate=True), n_dev=8)
        r_on = s_on.step(1.0)
    donation_warnings = [w for w in caught
                         if "donat" in str(w.message).lower()]
    assert donation_warnings == []
    assert r_on.flag == 0 and r_on.iters == r_off.iters
    assert np.array_equal(np.asarray(s_on.displacement_global()),
                          np.asarray(s_off.displacement_global()))


@pytest.mark.parametrize("mode", ["direct", "mixed"])
def test_donation_parity_chunked_multidevice(mode):
    model = make_cube_model(5, 4, 4, heterogeneous=True)
    s_off = _solver(model, _cfg(donate=False, mode=mode, ipd=25), n_dev=8)
    s_on = _solver(model, _cfg(donate=True, mode=mode, ipd=25), n_dev=8)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        r_on = s_on.step(1.0)
    assert [w for w in caught if "donat" in str(w.message).lower()] == []
    r_off = s_off.step(1.0)
    assert r_on.flag == 0 and r_on.iters == r_off.iters
    assert np.array_equal(np.asarray(s_on.displacement_global()),
                          np.asarray(s_off.displacement_global()))


def test_failed_donating_step_leaves_solver_retryable():
    """A one-shot dispatch failure with donation on must not strand the
    solver on a deleted un buffer: step() restores a live zero state on
    the exception path, so a retry behaves like the pre-donation code."""
    model = make_cube_model(3, 2, 2, heterogeneous=True)
    s = _solver(model, _cfg(donate=True))
    good_fn = s._step_fn

    def boom(*a, **k):
        raise RuntimeError("injected dispatch failure")

    s._step_fn = boom
    with pytest.raises(RuntimeError, match="injected"):
        s.step(1.0)
    np.asarray(s.un)                    # state is live, not deleted
    s._step_fn = good_fn
    r = s.step(1.0)
    assert r.flag == 0


# ----------------------------------------------------------------------
# Warmup: pre-bake without solving
# ----------------------------------------------------------------------

def test_warmup_populates_caches_and_leaves_state(cache_dir):
    model = make_cube_model(4, 3, 3, heterogeneous=True)
    s = _solver(model, _cfg(cache_dir=cache_dir), n_dev=8)
    un_before = np.asarray(s.un)
    s.warmup()
    np.testing.assert_array_equal(np.asarray(s.un), un_before)
    stats = pcache.cache_stats(cache_dir)
    assert stats["partition"]["entries"] >= 1
    assert stats["aot"]["entries"] >= 1
    # a fresh solver is fully warm after warmup alone (no solve ran)
    rec = MetricsRecorder()
    s2 = _solver(model, _cfg(cache_dir=cache_dir), n_dev=8, recorder=rec)
    assert s2.setup_cache == "warm"
    assert rec.counters.get("trace.step", 0) == 0
    assert s2.step(1.0).flag == 0


def test_warmup_chunked_path(cache_dir):
    """Chunked engine warmup: every budget-loop program compiles (1-iter
    budget execution), and a later real solve on the same solver is
    unaffected — same answer as an un-warmed reference."""
    model = make_cube_model(4, 3, 3, heterogeneous=True)
    ref = _solver(model, _cfg(mode="mixed", ipd=20))
    r_ref = ref.step(1.0)
    rec = MetricsRecorder()
    s = _solver(model, _cfg(cache_dir=cache_dir, mode="mixed", ipd=20),
                recorder=rec)
    s.warmup()
    # warmup paid every compile under the run()-time dispatch names...
    cold_after_warmup = {k: v["cold_s"]
                         for k, v in rec.dispatch_stats().items()}
    assert {"start", "inner_start", "inner_cycle"} <= \
        cold_after_warmup.keys()
    r = s.step(1.0)
    # ...so the real solve's dispatches all book WARM (no new cold time)
    for name, st in rec.dispatch_stats().items():
        assert st["cold_s"] == cold_after_warmup.get(name), name
    assert r.flag == 0 and r.iters == r_ref.iters
    assert np.array_equal(np.asarray(s.displacement_global()),
                          np.asarray(ref.displacement_global()))
