"""Batched multi-RHS PCG (ISSUE 6): the blocked Krylov loop
(solver/pcg.pcg_many), the Solver.solve_many dispatch path, and the
plumbing it threads through — validate/, cache keys, snapshots,
telemetry, CLI.

The headline contracts:

* a blocked CLASSIC solve on CPU reproduces each column of the
  equivalent single-RHS solves BIT-IDENTICALLY (frozen converged
  columns included) — the per-column lockstep merge only reorders which
  trip a column's arithmetic runs on, never the arithmetic;
* the fused variant agrees per column to rounding (it is documented
  non-bit-exact even against the scalar reference);
* psum count independent of nrhs is proven in tests/test_collectives.py;
* the warm path does zero partition builds and zero step re-traces for
  repeated blocks of the same shape (BUILD_CALLS + trace.step, the PR-2
  contract extended to the blocked program);
* a killed blocked solve resumes bit-identically, and a cross-nrhs
  resume is rejected as a clear fingerprint mismatch naming ``nrhs``.
"""

import glob
import os
import shutil

import numpy as np
import pytest

from pcg_mpi_solver_tpu.config import (RunConfig, SolverConfig,
                                       TimeHistoryConfig)
from pcg_mpi_solver_tpu.models.synthetic import make_cube_model
from pcg_mpi_solver_tpu.obs.metrics import MetricsRecorder
from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
from pcg_mpi_solver_tpu.parallel.partition import BUILD_CALLS
from pcg_mpi_solver_tpu.resilience import FaultPlan, SimulatedKill
from pcg_mpi_solver_tpu.solver.driver import Solver
from pcg_mpi_solver_tpu.validate import PreflightError, check_rhs_block


class _Cap:
    """Metrics sink collecting events for assertions."""

    def __init__(self):
        self.events = []

    def emit(self, ev):
        self.events.append(ev)

    def close(self):
        pass


def _cfg(*, mode="direct", tol=1e-8, ipd=-1, cache_dir="", snap=0,
         variant="classic", scratch=""):
    cfg = RunConfig(
        solver=SolverConfig(tol=tol, max_iter=2000, precision_mode=mode,
                            iters_per_dispatch=ipd, pcg_variant=variant),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]),
        cache_dir=cache_dir, snapshot_every=snap,
    )
    if scratch:
        cfg.scratch_path = scratch
    return cfg


@pytest.fixture
def model():
    return make_cube_model(4, 3, 3, heterogeneous=True)


def _hard_load(model, seed=5):
    """A load case that converges SLOWER than the smooth traction F: a
    random field restricted to effective dofs (rough right-hand sides
    excite the high modes Jacobi damps worst)."""
    rng = np.random.default_rng(seed)
    f = np.zeros(model.n_dof)
    eff = np.asarray(model.dof_eff)
    f[eff] = rng.standard_normal(eff.size)
    return f


# ----------------------------------------------------------------------
# Column-for-column parity with single-RHS solves
# ----------------------------------------------------------------------

def test_classic_block_matches_single_rhs_bit_identical(model):
    """Width-3 classic block (easy, scaled, zero columns) == the three
    width-1 solves, bit for bit, per column."""
    s = Solver(model, _cfg(), mesh=make_mesh(2), n_parts=2,
               backend="general")
    F = np.asarray(model.F)
    cols = [F, 0.5 * F, np.zeros_like(F)]
    blk = s.solve_many(np.stack(cols, axis=-1))
    xb = np.asarray(blk.x)
    for j, col in enumerate(cols):
        single = s.solve_many(col)
        assert int(single.flags[0]) == int(blk.flags[j])
        assert int(single.iters[0]) == int(blk.iters[j])
        np.testing.assert_array_equal(np.asarray(single.x)[..., 0],
                                      xb[..., j])
    # zero column: flag 0, zero iterations, zero solution
    assert int(blk.flags[2]) == 0 and int(blk.iters[2]) == 0
    assert not xb[..., 2].any()


def test_fused_block_matches_single_rhs_to_rounding(model):
    """The fused variant is documented non-bit-exact; per column the
    blocked solve must still take the same iteration path (flags and
    iteration counts equal) and agree to rounding."""
    s = Solver(model, _cfg(variant="fused"), mesh=make_mesh(2), n_parts=2,
               backend="general")
    F = np.asarray(model.F)
    cols = [F, 0.25 * F]
    blk = s.solve_many(np.stack(cols, axis=-1))
    xb = np.asarray(blk.x)
    for j, col in enumerate(cols):
        single = s.solve_many(col)
        assert int(single.flags[0]) == int(blk.flags[j]) == 0
        assert int(single.iters[0]) == int(blk.iters[j])
        np.testing.assert_allclose(np.asarray(single.x)[..., 0],
                                   xb[..., j], rtol=1e-7, atol=1e-12)


def test_mixed_convergence_rates_freeze_converged_columns(model):
    """One easy + one hard RHS: the hard column keeps iterating after
    the easy one converged, and the frozen easy column is bit-identical
    to its solo solve — proof the mask really freezes it.  Easy = the
    image of a smooth ramp displacement (low-mode content: CG's
    residual polynomial kills it in fewer iterations); hard = the
    smooth-traction reference load."""
    from pcg_mpi_solver_tpu.solver.numpy_ref import NumpyRefSolver

    s = Solver(model, _cfg(tol=1e-10), mesh=make_mesh(2), n_parts=2,
               backend="general")
    eff_mask = np.zeros(model.n_dof)
    eff_mask[np.asarray(model.dof_eff)] = 1.0
    ramp = np.zeros(model.n_dof)
    ramp[0::3] = np.asarray(model.node_coords)[:, 0]
    easy = NumpyRefSolver(model).matvec(ramp * eff_mask) * eff_mask
    hard = np.asarray(model.F)
    blk = s.solve_many(np.stack([easy, hard], axis=-1))
    assert list(blk.flags) == [0, 0]
    assert int(blk.iters[1]) > int(blk.iters[0]), \
        "hard column should need more iterations than the easy one"
    solo = s.solve_many(easy)
    np.testing.assert_array_equal(np.asarray(solo.x)[..., 0],
                                  np.asarray(blk.x)[..., 0])


def test_mixed_precision_block_matches_width1(model):
    """Blocked mixed-precision refinement (pcg_mixed_many): per-column
    flags 0 at tol and column parity with the width-1 blocked solve."""
    s = Solver(model, _cfg(mode="mixed", tol=1e-9), mesh=make_mesh(2),
               n_parts=2, backend="general")
    F = np.asarray(model.F)
    hard = _hard_load(model)
    blk = s.solve_many(np.stack([F, hard], axis=-1))
    assert list(blk.flags) == [0, 0]
    assert float(blk.relres.max()) <= 1e-9
    solo = s.solve_many(F)
    np.testing.assert_array_equal(np.asarray(solo.x)[..., 0],
                                  np.asarray(blk.x)[..., 0])


def test_structured_backend_block(model):
    """The stencil backend's vmapped block axis: same per-column parity
    contract on the structured slab partition."""
    m = make_cube_model(4, 4, 4, heterogeneous=False)
    s = Solver(m, _cfg(), mesh=make_mesh(2), n_parts=2)
    assert s.backend == "structured"
    F = np.asarray(m.F)
    blk = s.solve_many(np.stack([F, 2.0 * F], axis=-1))
    assert list(blk.flags) == [0, 0]
    solo = s.solve_many(F)
    np.testing.assert_array_equal(np.asarray(solo.x)[..., 0],
                                  np.asarray(blk.x)[..., 0])
    xg = s.displacement_global_many(blk.x)
    assert xg.shape == (m.n_dof, 2)
    np.testing.assert_allclose(xg[:, 1], 2.0 * xg[:, 0], rtol=1e-6)


# ----------------------------------------------------------------------
# Chunked dispatch: kill-and-resume, cross-nrhs rejection
# ----------------------------------------------------------------------

def _chunked_solver(model, tmp_path, snap=1):
    return Solver(model, _cfg(ipd=20, snap=snap, scratch=str(tmp_path)),
                  mesh=make_mesh(2), n_parts=2, backend="general")


def _kill_after(solver, nrhs, n_dispatches):
    """Replace the blocked cycle program with one that raises after
    ``n_dispatches`` capped dispatches — the deterministic stand-in for
    a mid-solve kill/preemption."""
    progs = solver._ensure_many_programs(nrhs)
    real = progs["cycle"]
    count = {"n": 0}

    def bomb(*a):
        count["n"] += 1
        if count["n"] > n_dispatches:
            raise RuntimeError("simulated kill")
        return real(*a)

    progs["cycle"] = bomb


def test_chunked_block_kill_and_resume_bit_identical(model, tmp_path):
    F = np.asarray(model.F)
    fb = np.stack([F, _hard_load(model)], axis=-1)
    ref = _chunked_solver(model, tmp_path / "ref").solve_many(fb)
    assert list(ref.flags) == [0, 0]
    assert int(np.asarray(ref.iters).max()) > 20, \
        "solve must span several capped dispatches for the test to bite"

    s2 = _chunked_solver(model, tmp_path / "run")
    _kill_after(s2, 2, n_dispatches=2)
    with pytest.raises(RuntimeError, match="simulated kill"):
        s2.solve_many(fb)
    snaps = glob.glob(os.path.join(s2.config.checkpoint_path,
                                   "many_*.npz"))
    assert snaps, "the killed solve must leave its mid-solve snapshot"

    s3 = _chunked_solver(model, tmp_path / "run")
    res = s3.solve_many(fb, resume=True)
    assert list(res.flags) == [0, 0]
    np.testing.assert_array_equal(np.asarray(res.iters),
                                  np.asarray(ref.iters))
    np.testing.assert_array_equal(np.asarray(res.x), np.asarray(ref.x))
    # completion discards the snapshot: a later resume starts cold
    assert not glob.glob(os.path.join(s3.config.checkpoint_path,
                                      "many_*.npz"))


def test_cross_nrhs_resume_is_a_clear_fingerprint_mismatch(model,
                                                           tmp_path):
    F = np.asarray(model.F)
    fb2 = np.stack([F, 0.5 * F], axis=-1)
    s = _chunked_solver(model, tmp_path)
    _kill_after(s, 2, n_dispatches=2)
    with pytest.raises(RuntimeError):
        s.solve_many(fb2)

    s2 = _chunked_solver(model, tmp_path)
    fb3 = np.stack([F, 0.5 * F, 0.25 * F], axis=-1)
    with pytest.raises(ValueError, match="nrhs"):
        s2.solve_many(fb3, resume=True)


def test_same_width_different_rhs_resume_rejected(model, tmp_path):
    """A resumed blocked carry belongs to ONE rhs block: a same-width
    block of different load cases must mismatch on the rhs content hash
    (the scalar paths derive their rhs from the fingerprinted model;
    solve_many's rhs is a per-request input and is fingerprinted too)."""
    F = np.asarray(model.F)
    s = _chunked_solver(model, tmp_path)
    _kill_after(s, 2, n_dispatches=2)
    with pytest.raises(RuntimeError):
        s.solve_many(np.stack([F, 0.5 * F], axis=-1))

    s2 = _chunked_solver(model, tmp_path)
    with pytest.raises(ValueError, match="rhs_hash"):
        s2.solve_many(np.stack([F, 0.25 * F], axis=-1), resume=True)


# ----------------------------------------------------------------------
# Per-column resilience (ISSUE 9): recovery ladder, quarantine, fault
# isolation between columns
# ----------------------------------------------------------------------

def _res_solver(model, tmp_path, *, variant="classic", maxrec=2, snap=0,
                fault=None, cap=None, ipd=20, precond="jacobi"):
    cfg = _cfg(ipd=ipd, snap=snap, variant=variant,
               scratch=str(tmp_path))
    cfg.solver.max_recoveries = maxrec
    cfg.solver.precond = precond
    rec = MetricsRecorder(sinks=[cap]) if cap is not None else None
    s = Solver(model, cfg, mesh=make_mesh(2), n_parts=2,
               backend="general", recorder=rec)
    if fault:
        s.fault_plan = FaultPlan(fault, recorder=s.recorder)
    return s


@pytest.fixture(autouse=True)
def _fast_backoff(monkeypatch):
    monkeypatch.setenv("PCG_TPU_RETRY_BACKOFF_S", "0.01")


@pytest.mark.parametrize("variant", ["classic", "fused", "pipelined"])
def test_chunked_column_fault_chaos_matrix(model, tmp_path, variant):
    """Chaos matrix, chunked blocked path: each of {nan, inf, rho0}
    injected into ONE column engages that column's recovery ladder
    (restart from its min-residual iterate) while the block completes —
    and under classic the HEALTHY column's solution and iteration count
    are bit-identical to a fault-free block run (fault isolation).
    With the ladder disabled the same poison QUARANTINES the column
    (flag 5 + telemetry) and healthy-column isolation still holds.
    One solver runs every leg: the fault plan and the recovery budget
    are host-side state, so the compiled blocked programs are shared."""
    from pcg_mpi_solver_tpu.obs.schema import validate_event

    F = np.asarray(model.F)
    fb = np.stack([F, _hard_load(model)], axis=-1)
    # the bit-identity reference is only consumed by the classic legs
    # (fused is documented non-bit-exact) — skip its solve under fused
    ref = (_res_solver(model, tmp_path / "ref").solve_many(fb)
           if variant == "classic" else None)
    if ref is not None:
        assert list(ref.flags) == [0, 0] and ref.recoveries == 0

    cap = _Cap()
    s = _res_solver(model, tmp_path / "run", variant=variant, cap=cap)
    for mode in ("nan", "inf", "rho0"):
        n0 = len(cap.events)
        s.fault_plan = FaultPlan(f"{mode}@col:1", recorder=s.recorder)
        res = s.solve_many(fb)
        ev = cap.events[n0:]
        assert list(res.flags) == [0, 0], \
            f"{mode}: poisoned column must recover"
        assert res.recoveries >= 1 and res.quarantined == ()
        recs = [e for e in ev if e["kind"] == "recovery"]
        assert recs and all(e["rhs"] == 1 for e in recs), \
            "recovery events must name the poisoned column"
        fired = [e for e in ev if e["kind"] == "fault"]
        assert [(e["mode"], e["point"], e["at"]) for e in fired] == \
            [(mode, "col", 1)]
        if ref is not None:
            np.testing.assert_array_equal(np.asarray(res.x)[..., 0],
                                          np.asarray(ref.x)[..., 0])
            assert int(res.iters[0]) == int(ref.iters[0])

    # ladder disabled: quarantine isolation on the same programs
    s.config.solver.max_recoveries = 0
    n0 = len(cap.events)
    s.fault_plan = FaultPlan("nan@col:1", recorder=s.recorder)
    res = s.solve_many(fb)
    ev = cap.events[n0:]
    assert list(res.flags) == [0, 5] and res.quarantined == (1,)
    assert np.isfinite(res.relres[1]), \
        "a quarantined column must report its min-residual truth"
    q = [e for e in ev if e["kind"] == "rhs_quarantine"]
    assert len(q) == 1 and q[0]["rhs"] == 1 \
        and q[0]["trigger"] == "nan_carry"
    assert validate_event(q[0]) == []
    rhs_ev = {e["rhs"]: e for e in ev if e["kind"] == "rhs_solve"}
    assert rhs_ev[1]["quarantined"] and not rhs_ev[0]["quarantined"]
    if ref is not None:
        np.testing.assert_array_equal(np.asarray(res.x)[..., 0],
                                      np.asarray(ref.x)[..., 0])


def test_blocked_kill_and_resume_mid_recovery_bit_identical(model,
                                                            tmp_path):
    """Satellite 4(a): a blocked solve killed AFTER a per-column
    recovery resumes bit-identically — the recovery state (per-column
    flag, prec_sel) rides the snapshotted carry, so the resumed run
    reproduces the uninterrupted faulted run exactly and re-runs no
    ladder attempts."""
    F = np.asarray(model.F)
    fb = np.stack([F, _hard_load(model)], axis=-1)
    ref = _res_solver(model, tmp_path / "ref", snap=1,
                      fault="rho0@col:1").solve_many(fb)
    assert list(ref.flags) == [0, 0] and ref.recoveries >= 1

    s2 = _res_solver(model, tmp_path / "run", snap=1,
                     fault="rho0@col:1, kill@2")
    with pytest.raises(SimulatedKill):
        s2.solve_many(fb)
    assert glob.glob(os.path.join(s2.config.checkpoint_path,
                                  "many_*.npz"))

    cap = _Cap()
    s3 = _res_solver(model, tmp_path / "run", snap=1, cap=cap)
    res = s3.solve_many(fb, resume=True)
    assert list(res.flags) == [0, 0]
    # the recovery happened BEFORE the kill: the resumed run continues
    # the post-restart Krylov space without consuming new attempts
    assert res.recoveries == 0
    np.testing.assert_array_equal(np.asarray(res.iters),
                                  np.asarray(ref.iters))
    np.testing.assert_array_equal(np.asarray(res.x), np.asarray(ref.x))


def test_one_shot_retry_guard_and_unlandable_column_fault(model,
                                                          tmp_path):
    """One-shot blocked path (ipd=0), both legs on one solver: (a) an
    injected device-loss exception before the dispatch is retried by
    the guard (the program donates nothing, so re-dispatch is safe) and
    the block completes; (b) column faults fire at blocked chunk
    boundaries, of which the one-shot path has NONE — the plan stays
    armed and NOT fired (a chaos drill must never read 'exercised' off
    an injection that could not land), and the solve is untouched."""
    cap = _Cap()
    s = _res_solver(model, tmp_path, ipd=0, fault="exc@0", cap=cap)
    F = np.asarray(model.F)
    fb = np.stack([F, 0.5 * F], axis=-1)
    res = s.solve_many(fb)
    assert list(res.flags) == [0, 0]
    recs = [e for e in cap.events if e["kind"] == "recovery"]
    assert [e["action"] for e in recs] == ["redispatch"]
    assert [f["mode"] for f in s.fault_plan.fired] == ["exc"]

    s.fault_plan = FaultPlan("nan@col:1", recorder=s.recorder)
    res = s.solve_many(fb)
    assert list(res.flags) == [0, 0] and res.quarantined == ()
    assert s.fault_plan.fired == [] and s.fault_plan.col_armed


def test_many_snapshot_retention_and_latest(model, tmp_path,
                                            monkeypatch):
    """Satellite: PCG_TPU_SNAP_KEEP retention pruning and the
    corrupt-tolerant latest() pointer are PREFIX-scoped, so they govern
    the ``many_*`` namespace exactly like ``snap_*``/``step_*``."""
    from pcg_mpi_solver_tpu.utils.checkpoint import SnapshotStore

    monkeypatch.setenv("PCG_TPU_SNAP_KEEP", "2")
    s = _chunked_solver(model, tmp_path)
    store = SnapshotStore.for_many_solver(s, 2, rhs_hash="h")
    other = SnapshotStore.for_solver(s)     # snap_* neighbor namespace
    other.save(7, {"kind": "direct", "total": np.int64(1)})
    for t in (1, 2, 3, 4):
        store.save(t, {"kind": "many", "total": np.int64(t)})
    files = sorted(os.path.basename(p) for p in glob.glob(
        os.path.join(store.path, "many_*.npz")))
    assert files == ["many_000003.npz", "many_000004.npz"], \
        "retention must prune the many_* namespace to the newest K"
    # the neighbor namespace is untouched by many_* pruning
    assert glob.glob(os.path.join(store.path, "snap_*.npz"))
    assert store.latest() == 4
    # corrupt newest -> latest() falls back to the next valid snapshot
    with open(store._file(4), "wb") as f:
        f.write(b"torn")
    assert store.latest() == 3
    assert store.load(4) is None    # corrupt reads as absent, loudly-ish


def test_many_snapshot_fingerprint_tracks_fallback_wiring(model,
                                                          tmp_path):
    """A blocked carry whose ``prec_sel`` flipped a column to the
    fallback preconditioner must never resume into programs compiled
    WITHOUT the fallback operand (the selection would silently compile
    out): the many-snapshot fingerprint records the wiring, so such a
    resume mismatches loudly on ``many_fallback``."""
    from pcg_mpi_solver_tpu.utils.checkpoint import SnapshotStore

    s = _res_solver(model, tmp_path, precond="block3", maxrec=2)
    fp_on = SnapshotStore.for_many_solver(s, 2, rhs_hash="h").fingerprint
    assert fp_on["many_fallback"] is True
    s.config.solver.max_recoveries = 0      # ladder (and operand) off
    fp_off = SnapshotStore.for_many_solver(s, 2,
                                           rhs_hash="h").fingerprint
    assert fp_off["many_fallback"] is False
    # the mismatch names the field (same posture as nrhs/rhs_hash)
    store_on = SnapshotStore(s.config.checkpoint_path, fp_on,
                             prefix="many")
    store_on.save(1, {"kind": "many", "total": np.int64(0)})
    store_off = SnapshotStore(s.config.checkpoint_path, fp_off,
                              prefix="many")
    with pytest.raises(ValueError, match="many_fallback"):
        store_off.load(1)

@pytest.fixture
def cache_dir(tmp_path):
    import jax

    before = jax.config.jax_compilation_cache_dir
    yield str(tmp_path / "warm")
    jax.config.update("jax_compilation_cache_dir", before)


def test_solve_many_warm_zero_builds_zero_traces(model, cache_dir):
    F = np.asarray(model.F)
    fb = np.stack([F, 0.5 * F], axis=-1)

    rec_cold = MetricsRecorder()
    s1 = Solver(model, _cfg(cache_dir=cache_dir), mesh=make_mesh(2),
                n_parts=2, backend="general", recorder=rec_cold)
    r1 = s1.solve_many(fb)
    assert list(r1.flags) == [0, 0]
    assert rec_cold.counters.get("trace.solve_many", 0) == 1
    x1 = np.asarray(r1.x)
    calls_after_cold = dict(BUILD_CALLS)

    rec_warm = MetricsRecorder()
    s2 = Solver(model, _cfg(cache_dir=cache_dir), mesh=make_mesh(2),
                n_parts=2, backend="general", recorder=rec_warm)
    assert dict(BUILD_CALLS) == calls_after_cold, \
        "warm construction must do zero partition builds"
    assert s2.setup_cache == "warm"
    r2 = s2.solve_many(fb)
    # zero jit tracing of the blocked program: the AOT entry was
    # deserialized (the counters increment only inside a live trace)
    assert rec_warm.counters.get("trace.step", 0) == 0
    assert rec_warm.counters.get("trace.solve_many", 0) == 0
    assert rec_warm.counters.get("cache.aot.hit", 0) >= 1
    assert dict(BUILD_CALLS) == calls_after_cold
    np.testing.assert_array_equal(np.asarray(r2.x), x1)
    # a repeated same-shape block on the SAME solver is also trace-free
    s2.solve_many(fb)
    assert rec_warm.counters.get("trace.solve_many", 0) == 0


def test_step_cache_key_carries_nrhs():
    from pcg_mpi_solver_tpu.cache.keys import step_cache_key

    kw = dict(abstract="sig", mesh="m", backend="general",
              solver={"tol": 1e-8}, trace_len=0, glob_n_dof_eff=100,
              donate=False, jax_version="x")
    assert step_cache_key(nrhs=1, **kw) != step_cache_key(nrhs=8, **kw)
    assert step_cache_key(nrhs=8, **kw) == step_cache_key(nrhs=8, **kw)


# ----------------------------------------------------------------------
# Per-request validation (validate/): offending column index
# ----------------------------------------------------------------------

def test_check_rhs_block_names_offending_column():
    good = np.ones((30, 3))
    assert all(r.status in ("ok", "warn")
               for r in check_rhs_block(good, 30))
    bad = good.copy()
    bad[7, 2] = np.nan
    res = {r.name: r for r in check_rhs_block(bad, 30)}
    assert res["rhs_block_finite"].status == "fail"
    assert "rhs 2" in res["rhs_block_finite"].detail
    # shape contract per RHS
    assert check_rhs_block(np.ones((29, 3)), 30)[0].status == "fail"
    assert check_rhs_block(np.ones(30), 30)[0].status == "fail"
    # all-zero column: usable but flagged
    zero_col = good.copy()
    zero_col[:, 1] = 0
    res = {r.name: r for r in check_rhs_block(zero_col, 30)}
    assert res["rhs_block_zero"].status == "warn"
    assert "1" in res["rhs_block_zero"].detail


def test_solve_many_rejects_bad_column(model):
    s = Solver(model, _cfg(), mesh=make_mesh(2), n_parts=2,
               backend="general")
    fb = np.stack([np.asarray(model.F)] * 3, axis=-1)
    fb[11, 1] = np.inf
    with pytest.raises(PreflightError, match="rhs 1"):
        s.solve_many(fb)


# ----------------------------------------------------------------------
# Telemetry plumbing: per-RHS events, schema-valid
# ----------------------------------------------------------------------

def test_solve_many_emits_schema_valid_per_rhs_events(model):
    from pcg_mpi_solver_tpu.obs.schema import validate_event

    class Capture:
        def __init__(self):
            self.events = []

        def emit(self, ev):
            self.events.append(ev)

        def close(self):
            pass

    cap = Capture()
    rec = MetricsRecorder(sinks=[cap])
    s = Solver(model, _cfg(), mesh=make_mesh(2), n_parts=2,
               backend="general", recorder=rec)
    F = np.asarray(model.F)
    s.solve_many(np.stack([F, 0.5 * F], axis=-1))
    kinds = [e["kind"] for e in cap.events]
    assert "solve_many" in kinds
    rhs_events = [e for e in cap.events if e["kind"] == "rhs_solve"]
    assert [e["rhs"] for e in rhs_events] == [0, 1]
    for e in cap.events:
        assert validate_event(e) == [], e
    many = next(e for e in cap.events if e["kind"] == "solve_many")
    assert many["nrhs"] == 2 and many["flags"] == [0, 0]
    assert rec.gauges.get("many.nrhs") == 2


# ----------------------------------------------------------------------
# CLI front-end
# ----------------------------------------------------------------------

def test_cli_solve_many(tmp_path, capsys):
    from pcg_mpi_solver_tpu.cli import main
    from pcg_mpi_solver_tpu.models.mdf import write_mdf

    model = make_cube_model(4, 3, 3, load="traction", heterogeneous=True)
    src = tmp_path / "src"
    write_mdf(model, str(src))
    archive = shutil.make_archive(str(tmp_path / "cube"), "zip", src)
    scratch = str(tmp_path / "scratch")
    main(["ingest", archive, scratch])
    capsys.readouterr()

    main(["solve-many", scratch, "1", "--scales", "1.0,0.5,2.0",
          "--n-parts", "2", "--tol", "1e-8", "--precision", "direct"])
    out = capsys.readouterr().out
    assert ">rhs 0: flag=0" in out and ">rhs 2: flag=0" in out
    assert ">success!" in out
    u = np.load(os.path.join(scratch, "Results_Run1", "u_many.npy"))
    assert u.shape[1] == 3
    np.testing.assert_allclose(u[:, 2], 2.0 * u[:, 0], rtol=1e-6)

    # --rhs file path: a transposed block is accepted
    rhs = np.stack([np.asarray(model.F), 0.5 * np.asarray(model.F)])
    rhs_file = str(tmp_path / "loads.npy")
    np.save(rhs_file, rhs)
    main(["solve-many", scratch, "2", "--rhs", rhs_file,
          "--n-parts", "2", "--tol", "1e-8", "--precision", "direct"])
    out = capsys.readouterr().out
    assert ">rhs 1: flag=0" in out and ">success!" in out


def test_cli_solve_many_max_recoveries_bites(tmp_path, capsys,
                                             monkeypatch):
    """Satellite: --max-recoveries now rides blocked solves for REAL —
    with the ladder on, an injected per-column fault recovers to flag 0;
    with --max-recoveries 0 the same fault quarantines the column (flag
    5) — and the old '--max-recoveries does not yet apply' warning is
    gone."""
    import json

    from pcg_mpi_solver_tpu.cli import main
    from pcg_mpi_solver_tpu.models.mdf import write_mdf

    model = make_cube_model(4, 3, 3, load="traction", heterogeneous=True)
    src = tmp_path / "src"
    write_mdf(model, str(src))
    archive = shutil.make_archive(str(tmp_path / "cube"), "zip", src)
    scratch = str(tmp_path / "scratch")
    main(["ingest", archive, scratch])
    capsys.readouterr()

    # force the chunked/resumable blocked path below the auto-engage
    # size (settings-only override), so boundary faults can land
    settings = str(tmp_path / "settings.json")
    with open(settings, "w") as f:
        json.dump({"SolverParam": {"ItersPerDispatch": 20}}, f)
    monkeypatch.setenv("PCG_TPU_FAULTS", "rho0@col:1")
    monkeypatch.setenv("PCG_TPU_RETRY_BACKOFF_S", "0.01")

    common = ["solve-many", scratch, "--scales", "1.0,0.5",
              "--n-parts", "2", "--tol", "1e-8", "--precision",
              "direct", "--settings", settings]
    main([common[0], common[1], "r1"] + common[2:]
         + ["--max-recoveries", "2"])
    out = capsys.readouterr().out
    assert "does not yet apply" not in out
    assert ">rhs 1: flag=0" in out
    assert ">recoveries: 1" in out

    main([common[0], common[1], "r2"] + common[2:]
         + ["--max-recoveries", "0"])
    out = capsys.readouterr().out
    assert ">rhs 1: flag=5" in out and "[QUARANTINED]" in out
    assert ">quarantined columns: [1]" in out


# ----------------------------------------------------------------------
# bench plumbing: detail fields present + schema-valid
# ----------------------------------------------------------------------

def test_bench_detail_carries_nrhs_fields(monkeypatch):
    import json

    from pcg_mpi_solver_tpu import bench
    from pcg_mpi_solver_tpu.obs.schema import validate_bench_line

    monkeypatch.setenv("BENCH_NRHS", "4")
    model = make_cube_model(3, 3, 3)

    class R:
        flag, relres, iters, wall_s = 0, 1e-9, 10, 0.5

    line = bench._result_json(model, "cube", R, 10, 100.0, "note",
                              {"nrhs": 4})
    d = json.loads(line)
    assert validate_bench_line(d) == []
    assert d["detail"]["nrhs"] == 4
    assert d["detail"]["dof_iter_rhs_per_s"] == pytest.approx(
        4 * d["value"], rel=1e-6)
