"""Geometric multigrid V-cycle preconditioner (ISSUE 10, ops/mg.py).

The acceptance contracts, each as a tier-1 CPU test:

* the V-cycle is a FIXED symmetric PSD linear operator (dense M^-1 on a
  tiny model via one blocked apply; two applies bitwise identical) — so
  plain non-flexible PCG stays valid;
* precond="mg" converges in >= 5x fewer PCG iterations than "jacobi" at
  identical tolerance on the heterogeneous golden-class cube;
* the traced while-body collective histogram equals
  ``Ops.body_collective_budget(variant, precond="mg")`` at nrhs in
  {1, 8} for BOTH pcg variants (general) and for the structured slab
  (ppermute accounting), and the replicated coarse cycle — smoother
  included — contributes ZERO collectives;
* blocked ``pcg_many`` + mg: column bit-parity across block widths;
* the full resilience stack: kill-and-resume bit-identical, the ladder
  demotes mg -> scalar-Jacobi fallback without aborting, cross-precond
  resume is a NAMED fingerprint mismatch;
* preflight rejects un-coarsenable models with a named reason; the
  degenerate Chebyshev interval check warns.

Runtime discipline: solver builds dominate tier-1 wall on the 8-way
virtual CPU mesh, so the module shares builds through module-scoped
fixtures and uses 2-device meshes wherever the contract allows.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pcg_mpi_solver_tpu.config import RunConfig, SolverConfig, TimeHistoryConfig
from pcg_mpi_solver_tpu.models import make_cube_model
from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
from pcg_mpi_solver_tpu.resilience import FaultPlan
from pcg_mpi_solver_tpu.solver.driver import Solver


@pytest.fixture(autouse=True)
def _fast_backoff(monkeypatch):
    monkeypatch.setenv("PCG_TPU_RETRY_BACKOFF_S", "0.01")


@pytest.fixture(scope="module")
def model():
    # 8x8x8 h=0.5 heterogeneous: the golden-class cube (test_goldens.py
    # pins 6x5x5, whose odd dims cannot coarsen) at an even,
    # two-level-coarsenable size
    return make_cube_model(8, 8, 8, h=0.5, nu=0.3, heterogeneous=True,
                           seed=0)


@pytest.fixture(scope="module")
def model_small():
    return make_cube_model(8, 4, 4, h=0.5, nu=0.3, heterogeneous=True,
                           seed=0)


def _cfg(precond="mg", scratch=None, run_id="1", **sk):
    skw = dict(tol=1e-8, max_iter=2000, precond=precond)
    skw.update(sk)
    cfg = RunConfig(
        solver=SolverConfig(**skw),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0],
                                       export_flag=False))
    if scratch is not None:
        cfg.scratch_path = str(scratch)
        cfg.run_id = run_id
    return cfg


def _solve(model, precond, backend="general", n_dev=2, **sk):
    s = Solver(model, _cfg(precond, **sk), mesh=make_mesh(n_dev),
               n_parts=n_dev, backend=backend)
    return s, s.step(1.0)


@pytest.fixture(scope="module")
def general_mg(model):
    """The reference mg solve on the golden-class cube (shared by the
    iteration-regression and cross-backend tests)."""
    s, r = _solve(model, "mg", n_dev=4)
    return s, r, s.displacement_global()


@pytest.fixture(scope="module")
def small_mg(model_small):
    """Shared small mg solver + solve (variant/mixed parity, blocked
    solve_many reuse)."""
    s, r = _solve(model_small, "mg")
    return s, r, s.displacement_global()


@pytest.fixture(scope="module")
def small_jacobi(model_small):
    """Shared small jacobi solver (default-untouched + cross-precond
    fingerprint tests)."""
    s, r = _solve(model_small, "jacobi")
    return s, r


# ----------------------------------------------------------------------
# The headline: iteration count
# ----------------------------------------------------------------------

def test_mg_cuts_iterations_5x_vs_jacobi(model, general_mg):
    """precond='mg' must converge in >= 5x fewer PCG iterations than
    'jacobi' at identical tolerance, to the same solution (measured
    here: ~151 vs ~14)."""
    _sm, rm, um = general_mg
    sj, rj = _solve(model, "jacobi", n_dev=4)
    assert rj.flag == 0 and rm.flag == 0
    assert 5 * rm.iters <= rj.iters, (rm.iters, rj.iters)
    uj = sj.displacement_global()
    np.testing.assert_allclose(um, uj, rtol=1e-6,
                               atol=1e-7 * np.abs(uj).max())


def test_mg_structured_backend_matches_general(model, general_mg):
    _sg, rg, ug = general_mg
    ss, rs = _solve(model, "mg", backend="structured", n_dev=8)
    assert rs.flag == 0
    assert abs(rs.iters - rg.iters) <= 2
    np.testing.assert_allclose(ss.displacement_global(), ug, rtol=1e-6,
                               atol=1e-9)


def test_mg_fused_variant_and_mixed_mode(model_small, small_mg):
    _s0, r0, u0 = small_mg
    sf, rf = _solve(model_small, "mg", pcg_variant="fused")
    sm, rm = _solve(model_small, "mg", precision_mode="mixed")
    assert r0.flag == 0 and rf.flag == 0 and rm.flag == 0
    scale = np.abs(u0).max()
    assert np.abs(sf.displacement_global() - u0).max() / scale < 1e-6
    assert np.abs(sm.displacement_global() - u0).max() / scale < 1e-6


def test_mg_jacobi_default_untouched(small_jacobi):
    """precond='jacobi' must not see any of the mg plumbing: no mg data
    subtree, the plain array prec operand, the old collective budget,
    the 'n/a' fingerprint component."""
    from pcg_mpi_solver_tpu.utils.checkpoint import _fingerprint

    s, r = small_jacobi
    assert r.flag == 0
    assert "mg" not in s.data
    assert s._mg_meta is None
    assert s.ops.body_collective_budget("classic") == {"psum": 5}
    assert _fingerprint(s)["mg_shape"] == "n/a"


# ----------------------------------------------------------------------
# Fixed symmetric PSD operator
# ----------------------------------------------------------------------

def test_vcycle_operator_symmetric_psd_and_fixed():
    """Dense M^-1 (applied to every basis vector via ONE blocked apply)
    must be symmetric PSD, strictly positive on effective dofs, and
    FIXED — two applies to the same block bitwise identical (the
    non-flexible-CG validity contract)."""
    m2 = make_cube_model(2, 2, 2, h=1.0, nu=0.3)
    s = Solver(m2, _cfg("mg"), mesh=make_mesh(2), n_parts=2,
               backend="general")
    P = s._part_spec

    def apply_block(data, rb):
        m = s._make_prec(s.ops, data)
        return s.ops.apply_prec(m, rb, data=data)

    fn = jax.jit(jax.shard_map(apply_block, mesh=s.mesh,
                               in_specs=(s._specs, P), out_specs=P,
                               check_vma=False))
    n = m2.n_dof
    gid = np.asarray(s.pm.dof_gid)
    loc = np.eye(n)[np.clip(gid, 0, None), :] * (gid >= 0)[..., None]
    from pcg_mpi_solver_tpu.parallel.distributed import put_sharded

    rb = put_sharded(np.ascontiguousarray(loc), s.mesh, P)
    out1 = fn(s.data, rb)
    out2 = fn(s.data, rb)
    np.testing.assert_array_equal(np.asarray(out1),
                                  np.asarray(out2))  # fixed, bitwise
    M = s.displacement_global_many(out1)
    scale = np.abs(M).max()
    assert np.abs(M - M.T).max() / scale < 1e-12   # symmetric
    eigs = np.linalg.eigvalsh(0.5 * (M + M.T))
    assert eigs.min() >= -1e-12 * eigs.max()       # PSD
    eff = np.zeros(n, bool)
    eff[np.asarray(m2.dof_eff)] = True
    assert (np.diag(M)[eff] > 0).all()             # SPD on eff dofs
    assert np.abs(M[~eff]).max() == 0.0            # fixed dofs untouched


# ----------------------------------------------------------------------
# Static collective budgets (the acceptance matrix)
# ----------------------------------------------------------------------

@pytest.mark.parametrize("backend,variant",
                         [("general", "classic"), ("general", "fused"),
                          ("structured", "classic")])
def test_mg_body_collective_budget_proven(backend, variant):
    """The traced while-body collective histogram must EQUAL the
    declared mg budget at nrhs in {1, 8}: collective count independent
    of the block width, and every collective accounted to fine matvec
    assembly or THE restriction psum (the smoother contributes none)."""
    from pcg_mpi_solver_tpu.analysis import jaxpr_utils as ju
    from pcg_mpi_solver_tpu.analysis import programs as ap

    s = ap.build_solver(backend, nx=4, precond="mg", pcg_variant=variant)
    budget = s.ops.body_collective_budget(variant, precond="mg")
    for nrhs in (1, 8):
        jx = ap.step_jaxpr(s) if nrhs == 1 else ap.many_jaxpr(s, nrhs)
        hists = [h for h in ju.body_collective_histograms(jx) if h]
        assert len(hists) == 1, hists
        assert hists[0] == budget, (nrhs, hists[0], budget)
    # arithmetic of the declaration: base body + 2*degree matvec
    # assemblies + 1 restriction — nothing attributable to the smoother
    from pcg_mpi_solver_tpu.ops.matvec import (
        MG_RESTRICT_PSUMS, precond_cycle_cost)

    base = s.ops.body_collective_budget(variant, precond="jacobi")
    mv, ps = precond_cycle_cost("mg", s.ops.mg_degree)
    assert mv == 2 * s.ops.mg_degree and ps == MG_RESTRICT_PSUMS
    if backend == "general":
        assert budget["psum"] == base["psum"] + mv + ps
    else:
        assert budget["psum"] == base["psum"] + ps
        assert budget["ppermute"] == base["ppermute"] * (1 + mv)


def test_mg_coarse_cycle_is_collective_free():
    """The replicated coarse hierarchy — Chebyshev smoothers, level
    transfers, the coarsest sweep — must trace to ZERO collective
    primitives (the 'smoother contributes zero collectives' claim,
    statically)."""
    from pcg_mpi_solver_tpu.analysis.jaxpr_utils import count_primitive
    from pcg_mpi_solver_tpu.ops import mg as mgmod
    from pcg_mpi_solver_tpu.parallel.partition import partition_model

    m = make_cube_model(4, 4, 4)
    pm = partition_model(m, 2)
    setup = mgmod.build_mg_host(m, pm)
    tree = jax.tree.map(jnp.asarray, setup.tree)
    tree["lam"] = jnp.asarray([4.0] + setup.coarse_lams)
    n0 = tree["levels"][0]["idiag"].shape[0]

    def coarse(rc):
        return mgmod._coarse_vcycle(tree, 0, rc, 2)

    jx = jax.make_jaxpr(coarse)(jnp.zeros((n0, 3)))
    for prim in ("psum", "ppermute", "all_gather", "all_to_all"):
        assert count_primitive(jx.jaxpr, prim) == 0, prim


def test_unknown_precond_is_loud_keyerror():
    from pcg_mpi_solver_tpu.ops.matvec import Ops, precond_cycle_cost

    with pytest.raises(KeyError):
        precond_cycle_cost("frobnicate")
    ops = Ops(n_loc=8, n_iface=2)
    with pytest.raises(KeyError):
        ops.body_collective_budget("classic", precond="frobnicate")
    with pytest.raises(KeyError):
        ops.comm_estimate(precond="frobnicate")


# ----------------------------------------------------------------------
# Blocked multi-RHS
# ----------------------------------------------------------------------

def test_mg_pcg_many_column_bit_parity(model_small, small_mg):
    """A column of an nrhs=2 mg block must reproduce the same column of
    an nrhs=1 mg block bit-identically (block-width independence — the
    PR-6 contract extended to the V-cycle preconditioner)."""
    s = small_mg[0]
    F = np.asarray(model_small.F)
    fb = np.stack([F, 0.5 * F], axis=-1)
    res2 = s.solve_many(fb)
    assert list(res2.flags) == [0, 0]
    res1 = s.solve_many(F[:, None])
    u2 = s.displacement_global_many(res2.x)
    u1 = s.displacement_global_many(res1.x)
    np.testing.assert_array_equal(u2[:, 0], u1[:, 0])
    assert int(res2.iters[0]) == int(res1.iters[0])


def test_mg_pcg_many_chunked_with_column_fault(model_small):
    """Per-column resilience rides mg: a NaN-poisoned column recovers
    through its own ladder (rung 2 = the scalar-Jacobi inv_diag_fb)
    while the healthy column completes."""
    cfg = _cfg("mg", iters_per_dispatch=5, max_recoveries=2)
    s = Solver(model_small, cfg, mesh=make_mesh(2), n_parts=2,
               backend="general")
    F = np.asarray(model_small.F)
    fb = np.stack([F, 0.5 * F], axis=-1)
    s.fault_plan = FaultPlan("nan@col:1", recorder=s.recorder)
    res = s.solve_many(fb)
    assert list(res.flags) == [0, 0], (res.flags, res.quarantined)
    assert res.recoveries >= 1


# ----------------------------------------------------------------------
# Resilience: kill/resume, ladder demotion, cross-precond resume
# ----------------------------------------------------------------------

def test_mg_kill_and_resume_bit_identical(model_small, tmp_path):
    """An uninterrupted chunked mg solve vs kill-at-chunk-2 + resume
    must be bit-identical (the mg carry/prec state rides the snapshot
    like every other resumable leaf)."""
    from pcg_mpi_solver_tpu.resilience.faultinject import SimulatedKill

    def mk(run_id):
        cfg = _cfg("mg", scratch=tmp_path, run_id=run_id,
                   iters_per_dispatch=5)
        cfg.snapshot_every = 1
        return cfg

    sa = Solver(model_small, mk("a"), mesh=make_mesh(2), n_parts=2)
    sa.solve()
    cb = mk("b")
    sk = Solver(model_small, cb, mesh=make_mesh(2), n_parts=2)
    sk.fault_plan = FaultPlan("kill@2")
    with pytest.raises(SimulatedKill):
        sk.solve()
    sk2 = Solver(model_small, cb, mesh=make_mesh(2), n_parts=2)
    sk2.solve(resume=True)
    assert sk2.flags == sa.flags and sk2.iters == sa.iters
    np.testing.assert_array_equal(sk2.displacement_global(),
                                  sa.displacement_global())


def test_mg_cross_precond_resume_named_mismatch(small_jacobi, small_mg,
                                                tmp_path):
    """A snapshot written under jacobi must refuse to load under mg with
    a mismatch NAMING precond + mg_shape — never a pytree error deep in
    the dispatch (tested at the exact guard layer, SnapshotStore.load)."""
    from pcg_mpi_solver_tpu.utils.checkpoint import (
        SnapshotStore, _fingerprint)

    store_j = SnapshotStore(str(tmp_path), _fingerprint(small_jacobi[0]))
    store_j.save(1, {"kind": "direct", "total": np.int64(5)})
    store_m = SnapshotStore(str(tmp_path), _fingerprint(small_mg[0]))
    with pytest.raises(ValueError) as ei:
        store_m.load(1)
    assert "precond" in str(ei.value) and "mg_shape" in str(ei.value)


def test_mg_ladder_demotes_to_scalar_jacobi(model_small, tmp_path):
    """Two injected NaN carries must walk the ladder restart ->
    fallback_prec (the mg->scalar-Jacobi DEMOTION: the compiled cycle's
    fb switch, no recompilation, no abort) and still converge."""
    from pcg_mpi_solver_tpu.obs.metrics import MetricsRecorder

    class Cap:
        def __init__(self):
            self.events = []

        def emit(self, ev):
            self.events.append(ev)

        def close(self):
            pass

    cap = Cap()
    rec = MetricsRecorder(sinks=[cap])
    cfg = _cfg("mg", scratch=tmp_path, iters_per_dispatch=5,
               max_recoveries=2)
    s = Solver(model_small, cfg, mesh=make_mesh(2), n_parts=2,
               recorder=rec)
    s.fault_plan = FaultPlan("nan@1, nan@3", recorder=rec)
    r = s.step(1.0)
    acts = [e["action"] for e in cap.events if e["kind"] == "recovery"]
    assert acts == ["restart_minres", "fallback_prec"], acts
    assert r.flag == 0 and r.relres <= 1e-7
    # the demoted prec keeps the mg operand SHAPE with fb=1 (the cycle
    # program is reused, not recompiled)
    fb = s._fallback_prec()
    assert isinstance(fb, dict) and int(fb["fb"]) == 1


def test_mg_fallback_kind_and_ladder_rungs():
    from pcg_mpi_solver_tpu.ops.precond import fallback_kind
    from pcg_mpi_solver_tpu.resilience.recovery import RecoveryLadder

    assert fallback_kind("mg") == "jacobi"
    lad = RecoveryLadder(precond="mg", mixed=False, max_recoveries=3)
    assert lad.next_action("flag4") == "restart_minres"
    assert lad.next_action("flag4") == "fallback_prec"


# ----------------------------------------------------------------------
# Preflight / validate
# ----------------------------------------------------------------------

def test_preflight_rejects_uncoarsenable_lattice():
    from pcg_mpi_solver_tpu.validate import PreflightError

    m5 = make_cube_model(5, 5, 5)
    with pytest.raises(PreflightError, match="mg_hierarchy"):
        Solver(m5, _cfg("mg"), mesh=make_mesh(1), n_parts=1)


def test_preflight_rejects_overdeep_mg_levels(model_small):
    from pcg_mpi_solver_tpu.validate import PreflightError

    with pytest.raises(PreflightError, match="mg_levels"):
        Solver(model_small, _cfg("mg", mg_levels=5), mesh=make_mesh(1),
               n_parts=1)


def test_mg_rejected_on_hybrid_backend():
    from pcg_mpi_solver_tpu.models.octree import make_octree_model

    m = make_octree_model(2, 2, 2, max_level=2, n_incl=2, seed=3)
    with pytest.raises(ValueError, match="hybrid"):
        Solver(m, _cfg("mg"), mesh=make_mesh(2), n_parts=2,
               backend="hybrid")


def test_mg_octree_model_on_general_backend():
    """An octree model (graded leaves, transition types) builds its
    hierarchy from the octree lattice metadata and converges faster
    than jacobi on the general backend."""
    from pcg_mpi_solver_tpu.models.octree import make_octree_model

    m = make_octree_model(2, 2, 2, max_level=2, n_incl=2, seed=3,
                          load="traction", load_value=1.0)
    sj, rj = _solve(m, "jacobi", backend="general")
    sm, rm = _solve(m, "mg", backend="general")
    assert rj.flag == 0 and rm.flag == 0
    assert rm.iters < rj.iters, (rm.iters, rj.iters)
    uj, um = sj.displacement_global(), sm.displacement_global()
    np.testing.assert_allclose(um, uj, rtol=1e-4,
                               atol=1e-7 * np.abs(uj).max())


def test_check_mg_interval_degenerate_warns():
    from pcg_mpi_solver_tpu.validate import check_mg_interval

    assert check_mg_interval(1.0, 4.0).status == "ok"
    chk = check_mg_interval(1.0, 1.01)
    assert chk.status == "warn" and "degenerate" in chk.detail
    assert check_mg_interval(0.5, float("nan")).status == "warn"


def test_mg_setup_event_gauges_and_fingerprint(model_small):
    from pcg_mpi_solver_tpu.obs.metrics import MetricsRecorder
    from pcg_mpi_solver_tpu.obs.schema import validate_event
    from pcg_mpi_solver_tpu.utils.checkpoint import _fingerprint

    class Cap:
        def __init__(self):
            self.events = []

        def emit(self, ev):
            self.events.append(ev)

        def close(self):
            pass

    cap = Cap()
    rec = MetricsRecorder(sinks=[cap])
    s = Solver(model_small, _cfg("mg"), mesh=make_mesh(2), n_parts=2,
               recorder=rec)
    ev = [e for e in cap.events if e["kind"] == "mg_setup"]
    assert len(ev) == 1 and validate_event(ev[0]) == []
    assert ev[0]["levels"] == 2 and ev[0]["lam_fine"] > 0
    assert rec.gauges["precond"] == "mg"
    assert rec.gauges["mg.levels"] == 2
    # comm gauges are precond-aware and read the same declared table
    est = s.ops.comm_estimate(variant="classic", precond="mg")
    assert est["precond"] == "mg"
    assert est["psums_per_iter"] > s.ops.comm_estimate(
        variant="classic", precond="jacobi")["psums_per_iter"]
    # the snapshot fingerprint carries the structural mg shape
    fp = _fingerprint(s)
    assert fp["precond"] == "mg"
    levels, degree, dims = fp["mg_shape"]
    assert (levels, degree, dims) == (2, 2, [8, 4, 4])
    # the step event carries the time_to_tol_s time-to-solution field
    r = s.step(1.0)
    step_ev = [e for e in cap.events if e["kind"] == "step"][-1]
    assert step_ev["time_to_tol_s"] is not None and r.flag == 0


def test_mg_warm_cache_reuses_partition_and_lam(model_small, tmp_path):
    """With cache_dir set, the second construction serves both the
    partition AND the mg fine-level eigenvalue bound from the cache
    (the 'cached in the partition cache' satellite), bit-identically."""
    def mk():
        cfg = _cfg("mg")
        cfg.cache_dir = str(tmp_path / "cache")
        return cfg

    s1 = Solver(model_small, mk(), mesh=make_mesh(2), n_parts=2)
    r1 = s1.step(1.0)
    s2 = Solver(model_small, mk(), mesh=make_mesh(2), n_parts=2)
    r2 = s2.step(1.0)
    assert s2.setup_cache == "warm"
    hits = s2.recorder.counters.get("cache.partition.hit", 0)
    assert hits >= 2          # partition + mg lam entries
    assert r1.iters == r2.iters
    np.testing.assert_array_equal(s1.displacement_global(),
                                  s2.displacement_global())


def test_mg_aot_key_structural_component():
    """precond is a structural AOT-key component: jacobi/mg programs
    must never collide even with an empty solver dict."""
    from pcg_mpi_solver_tpu.cache.keys import step_cache_key

    kw = dict(abstract="a", mesh="m", backend="b", solver={},
              trace_len=0, glob_n_dof_eff=1, donate=True,
              jax_version="j", pcg_variant="classic", nrhs=1)
    assert step_cache_key(precond="jacobi", **kw) \
        != step_cache_key(precond="mg", **kw)


def test_cli_demo_with_mg(tmp_path, capsys):
    """`pcg-tpu demo --precond mg` end to end (the --precond plumbing)."""
    from pcg_mpi_solver_tpu.cli import main

    main(["demo", "--nx", "4", "--precond", "mg", "--precision",
          "direct", "--scratch", str(tmp_path / "scratch")])
    out = capsys.readouterr().out
    assert "flag=0" in out
