"""Dispatch-chunked solve path: correctness vs the one-shot path and the
max_iter budget clamp.

The chunked path (driver.py `_step_chunked`) is auto-engaged above ~4M dofs,
far beyond test scale, so these tests force it with an explicit
``iters_per_dispatch`` and check it against the one-shot solve on the same
model (same reference semantics: pcg_solver.py:356-598 in one dispatch vs
several)."""

import numpy as np
import pytest

from pcg_mpi_solver_tpu.config import RunConfig, SolverConfig, TimeHistoryConfig
from pcg_mpi_solver_tpu.models.synthetic import make_cube_model
from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
from pcg_mpi_solver_tpu.solver.driver import Solver


def _solver(model, *, iters_per_dispatch=0, precision_mode="direct",
            tol=1e-8, max_iter=2000, n_dev=1):
    cfg = RunConfig(
        solver=SolverConfig(tol=tol, max_iter=max_iter,
                            iters_per_dispatch=iters_per_dispatch,
                            precision_mode=precision_mode),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]),
    )
    return Solver(model, cfg, mesh=make_mesh(n_dev), n_parts=n_dev)


@pytest.mark.parametrize("precision_mode", ["direct", "mixed"])
def test_chunked_matches_one_shot(precision_mode):
    model = make_cube_model(4, 3, 3, h=0.5, nu=0.3, heterogeneous=True)
    ref = _solver(model, precision_mode=precision_mode)
    res_ref = ref.step(1.0)
    assert res_ref.flag == 0

    chunked = _solver(model, iters_per_dispatch=20,
                      precision_mode=precision_mode)
    assert chunked._dispatch_cap == 20
    res = chunked.step(1.0)
    assert res.flag == 0
    assert res.relres <= 1e-8
    # The Krylov carry makes chunked dispatches iteration-for-iteration
    # identical to the one-shot solve (mixed mode: f32 state carried across
    # dispatches within a refinement cycle).
    assert res.iters == res_ref.iters
    np.testing.assert_allclose(
        chunked.displacement_global(), ref.displacement_global(),
        rtol=1e-6, atol=1e-7 * np.abs(ref.displacement_global()).max())


@pytest.mark.parametrize("precision_mode", ["direct", "mixed"])
def test_chunked_respects_max_iter_budget(precision_mode):
    """Total iterations never exceed config.solver.max_iter: the last cycle's
    inner budget is clamped to the remainder (ADVICE round 1) — in mixed
    mode across the nested refinement-cycle/inner-dispatch loops too."""
    model = make_cube_model(5, 4, 4, heterogeneous=True)
    # A budget far below convergence, deliberately not a multiple of the cap.
    s = _solver(model, iters_per_dispatch=16, max_iter=37, tol=1e-12,
                precision_mode=precision_mode)
    res = s.step(1.0)
    assert res.flag != 0
    assert res.iters <= 37


def test_chunked_multidevice_spmd():
    model = make_cube_model(5, 4, 4, heterogeneous=True)
    ref = _solver(model, n_dev=1)
    chunked = _solver(model, iters_per_dispatch=25, n_dev=8)
    r0, r1 = ref.step(1.0), chunked.step(1.0)
    assert r1.flag == 0 and r1.relres <= 1e-8
    np.testing.assert_allclose(
        chunked.displacement_global(), ref.displacement_global(),
        rtol=1e-6, atol=1e-7 * np.abs(ref.displacement_global()).max())
