"""Fast lint (tier-1): every broad ``except`` in solver/, cache/ and
resilience/ re-raises, logs a metrics/warning event, or carries a
``# noqa: BLE001`` justification — via the same
tools/check_recovery_paths.py entry point CI and humans run (wired like
the telemetry-schema lint)."""

import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(REPO, "tools", "check_recovery_paths.py")

sys.path.insert(0, os.path.join(REPO, "tools"))
import check_recovery_paths as lint  # noqa: E402


def test_default_scope_is_clean():
    files = lint.iter_py_files(lint.DEFAULT_SCOPE)
    assert files, "expected solver/cache/resilience sources"
    errors = []
    for f in files:
        errors.extend(lint.check_file(f))
    assert errors == []


def test_tool_cli_exit_codes(tmp_path):
    ok = subprocess.run([sys.executable, TOOL], capture_output=True,
                        text=True, cwd=REPO)
    assert ok.returncode == 0, ok.stderr

    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        def f():
            try:
                g()
            except Exception:
                pass
    """))
    r = subprocess.run([sys.executable, TOOL, str(bad)],
                       capture_output=True, text=True)
    assert r.returncode == 1
    assert "broad `except`" in r.stderr


def test_lint_rules():
    ok_reraise = "try:\n    f()\nexcept Exception:\n    cleanup()\n    raise\n"
    ok_logged = ("try:\n    f()\nexcept Exception as e:\n"
                 "    rec.note(f'failed: {e}')\n")
    ok_warn = ("import warnings\ntry:\n    f()\nexcept Exception as e:\n"
               "    warnings.warn(str(e))\n")
    ok_noqa = ("try:\n    f()\n"
               "except Exception:  # noqa: BLE001\n    pass\n")
    ok_narrow = "try:\n    f()\nexcept OSError:\n    pass\n"
    bad_silent = "try:\n    f()\nexcept Exception:\n    pass\n"
    bad_bare = "try:\n    f()\nexcept:\n    x = 1\n"
    bad_base = "try:\n    f()\nexcept BaseException:\n    pass\n"
    for src in (ok_reraise, ok_logged, ok_warn, ok_noqa, ok_narrow):
        assert lint.check_source(src) == [], src
    for src in (bad_silent, bad_bare, bad_base):
        assert lint.check_source(src) != [], src
