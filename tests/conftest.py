"""Test configuration: multi-device SPMD on a virtual CPU mesh.

The reference has no automated tests (SURVEY.md §4); its rig is `mpiexec`
oversubscription.  The JAX-native substitute: force 8 virtual CPU devices so
every sharding/collective path runs as real SPMD without TPU hardware.
Must be set before jax initializes.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax

# The axon (tunneled TPU) sitecustomize force-registers its platform ahead of
# the env var; override back so tests really run 8-way CPU SPMD.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

assert len(jax.devices()) == 8, f"expected 8 virtual CPU devices, got {jax.devices()}"
