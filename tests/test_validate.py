"""Preflight subsystem (validate/): pathological models/configs are
rejected BEFORE any partition build or compile — asserted against
parallel/partition.BUILD_CALLS, the same warm-path work counters the
cache contract uses — under the fail/warn/off policy
(PCG_TPU_PREFLIGHT / RunConfig.preflight / --preflight)."""

import os
import shutil

import numpy as np
import pytest

from pcg_mpi_solver_tpu.config import RunConfig, SolverConfig
from pcg_mpi_solver_tpu.models.synthetic import make_cube_model
from pcg_mpi_solver_tpu.obs.metrics import MetricsRecorder
from pcg_mpi_solver_tpu.parallel import partition
from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
from pcg_mpi_solver_tpu.solver.driver import Solver
from pcg_mpi_solver_tpu.validate import (
    PreflightError, preflight_checks, resolve_policy, run_preflight)


class _Capture:
    def __init__(self):
        self.events = []

    def emit(self, ev):
        self.events.append(ev)

    def close(self):
        pass


@pytest.fixture(scope="module")
def model():
    return make_cube_model(4, 3, 3, heterogeneous=True)


def _nan_load_model():
    m = make_cube_model(3, 3, 3)
    m.F[5] = float("nan")
    return m


def _status(results, name):
    return {r.name: r for r in results}[name].status


# ----------------------------------------------------------------------
# Check taxonomy
# ----------------------------------------------------------------------

def test_healthy_model_passes_every_check(model):
    results = preflight_checks(model, RunConfig())
    assert results and all(r.status == "ok" for r in results), \
        [(r.name, r.status, r.detail) for r in results]


def test_nan_everywhere_is_caught():
    for field, check in (("F", "finite_loads"), ("Ud", "finite_loads"),
                         ("Vd", "finite_loads"), ("diag_M", "finite_mass"),
                         ("ck", "finite_scales")):
        m = make_cube_model(3, 3, 3)
        getattr(m, field)[2] = float("inf")
        assert _status(preflight_checks(m), check) == "fail", field
    m = make_cube_model(3, 3, 3)
    m.node_coords[0, 1] = float("nan")
    assert _status(preflight_checks(m), "finite_coords") == "fail"


def test_degenerate_elements_and_constraints():
    m = make_cube_model(3, 3, 3)
    m.level[4] = 0.0
    assert _status(preflight_checks(m), "element_volume") == "fail"
    m2 = make_cube_model(3, 3, 3)
    m2.ck[1] = -1.0
    assert _status(preflight_checks(m2), "element_volume") == "fail"
    m3 = make_cube_model(3, 3, 3)
    m3.fixed_dof = np.zeros(0, dtype=m3.fixed_dof.dtype)
    res = preflight_checks(m3)
    assert _status(res, "constraints") == "fail"
    assert "rigid body" in {r.name: r for r in res}["constraints"].detail


def test_connectivity_contract():
    m = make_cube_model(3, 3, 3)
    m.elem_dofs_flat[7] = m.n_dof + 3      # out-of-range dof id
    assert _status(preflight_checks(m), "connectivity") == "fail"


def test_config_cross_checks(model):
    # mixed tol below the refinement floor: warn, not fail
    cfg = RunConfig(solver=SolverConfig(precision_mode="mixed", tol=1e-15))
    assert _status(preflight_checks(model, cfg), "tol_floor") == "warn"
    # direct f32 below the f32 floor
    cfg = RunConfig(solver=SolverConfig(dtype="float32", tol=1e-9))
    assert _status(preflight_checks(model, cfg), "tol_floor") == "warn"
    # nonsense solver params are fail-class
    cfg = RunConfig(solver=SolverConfig(tol=-1.0))
    assert _status(preflight_checks(model, cfg), "solver_params") == "fail"
    # snapshot cadence beyond the schedule never fires
    cfg = RunConfig()
    cfg.snapshot_every = 50
    res = preflight_checks(model, cfg, context={"n_steps": 5})
    assert _status(res, "snapshot_cadence") == "warn"


def test_explicit_dt_margin(model):
    from pcg_mpi_solver_tpu.solver.dynamics import stable_dt

    bound = stable_dt(model, safety=1.0)
    ctx = {"kind": "dynamics", "dt": 2 * bound, "dt_source": "arg"}
    assert _status(preflight_checks(model, None, ctx),
                   "explicit_dt") == "fail"
    # a model-file dt placeholder only warns (legacy MDF bundles)
    ctx = {"kind": "dynamics", "dt": 2 * bound, "dt_source": "model"}
    assert _status(preflight_checks(model, None, ctx),
                   "explicit_dt") == "warn"
    ctx = {"kind": "dynamics", "dt": 0.5 * bound, "dt_source": "arg"}
    assert _status(preflight_checks(model, None, ctx),
                   "explicit_dt") == "ok"


# ----------------------------------------------------------------------
# Policy: fail / warn / off
# ----------------------------------------------------------------------

def test_policy_resolution(monkeypatch):
    monkeypatch.delenv("PCG_TPU_PREFLIGHT", raising=False)
    assert resolve_policy() == "fail"
    assert resolve_policy("warn") == "warn"
    monkeypatch.setenv("PCG_TPU_PREFLIGHT", "off")
    assert resolve_policy() == "off"
    assert resolve_policy("fail") == "fail"     # arg beats env
    monkeypatch.setenv("PCG_TPU_PREFLIGHT", "frobnicate")
    with pytest.raises(ValueError, match="policy"):
        resolve_policy()


def test_fail_policy_rejects_before_partition_build():
    """ISSUE 4 acceptance: a ModelData with NaN loads (or an
    unconstrained mesh) is rejected by preflight before any partition
    build or compile, asserted via parallel/partition.BUILD_CALLS."""
    before = dict(partition.BUILD_CALLS)
    with pytest.raises(PreflightError, match="finite_loads"):
        Solver(_nan_load_model(), RunConfig(), mesh=make_mesh(1),
               n_parts=1, backend="general")
    m = make_cube_model(3, 3, 3)
    m.fixed_dof = np.zeros(0, dtype=m.fixed_dof.dtype)
    with pytest.raises(PreflightError, match="constraints"):
        Solver(m, RunConfig(), mesh=make_mesh(1), n_parts=1,
               backend="general")
    assert partition.BUILD_CALLS == before


def test_time_drivers_reject_before_partition_build():
    from pcg_mpi_solver_tpu.solver.dynamics import DynamicsSolver
    from pcg_mpi_solver_tpu.solver.newmark import NewmarkSolver

    before = dict(partition.BUILD_CALLS)
    with pytest.raises(PreflightError):
        NewmarkSolver(_nan_load_model(), RunConfig(), mesh=make_mesh(1),
                      n_parts=1, dt=0.1)
    with pytest.raises(PreflightError):
        DynamicsSolver(_nan_load_model(), RunConfig(), mesh=make_mesh(1),
                       n_parts=1)
    assert partition.BUILD_CALLS == before


def test_warn_policy_proceeds_with_warning():
    cfg = RunConfig()
    cfg.preflight = "warn"
    before = partition.BUILD_CALLS["partition_model"]
    with pytest.warns(UserWarning, match="preflight rejected"):
        s = Solver(_nan_load_model(), cfg, mesh=make_mesh(1), n_parts=1,
                   backend="general")
    assert s.backend == "general"
    assert partition.BUILD_CALLS["partition_model"] == before + 1


def test_off_policy_skips_scans(model):
    cfg = RunConfig()
    cfg.preflight = "off"
    assert run_preflight(_nan_load_model(), cfg) == []
    cap = _Capture()
    run_preflight(model, cfg, recorder=MetricsRecorder(sinks=[cap]))
    assert cap.events == []         # off emits nothing, scans nothing


def test_env_policy_drives_constructors(model, monkeypatch):
    monkeypatch.setenv("PCG_TPU_PREFLIGHT", "off")
    s = Solver(_nan_load_model(), RunConfig(), mesh=make_mesh(1),
               n_parts=1, backend="general")     # no gate, no raise
    assert s.backend == "general"


# ----------------------------------------------------------------------
# Telemetry event
# ----------------------------------------------------------------------

def test_preflight_event_schema(model):
    from pcg_mpi_solver_tpu.obs.schema import validate_event

    cap = _Capture()
    run_preflight(model, RunConfig(),
                  recorder=MetricsRecorder(sinks=[cap]),
                  context={"kind": "quasi_static"})
    evs = [e for e in cap.events if e["kind"] == "preflight"]
    assert len(evs) == 1
    ev = evs[0]
    assert validate_event(ev) == []
    assert ev["policy"] == "fail" and ev["failed"] == 0
    assert {c["name"] for c in ev["checks"]} >= {
        "finite_loads", "constraints", "element_volume", "connectivity"}


def test_rejected_event_still_emitted():
    cap = _Capture()
    with pytest.raises(PreflightError):
        run_preflight(_nan_load_model(), RunConfig(),
                      recorder=MetricsRecorder(sinks=[cap]))
    ev = [e for e in cap.events if e["kind"] == "preflight"][0]
    assert ev["failed"] == 1        # the post-mortem survives the raise


# ----------------------------------------------------------------------
# CLI: validate subcommand + --preflight plumbing
# ----------------------------------------------------------------------

def test_cli_validate_subcommand(tmp_path, capsys):
    from pcg_mpi_solver_tpu.cli import main
    from pcg_mpi_solver_tpu.models.mdf import write_mdf

    model = make_cube_model(3, 3, 3, load="traction")
    src = tmp_path / "src"
    write_mdf(model, str(src))
    archive = shutil.make_archive(str(tmp_path / "cube"), "zip", src)
    scratch = str(tmp_path / "scratch")
    main(["ingest", archive, scratch])
    main(["validate", scratch])
    out = capsys.readouterr().out
    assert ">validate: all checks passed" in out

    # poison the scratch model: validate must exit non-zero
    bad = make_cube_model(3, 3, 3, load="traction")
    bad.F[0] = float("nan")
    src2 = tmp_path / "src2"
    write_mdf(bad, str(src2))
    archive2 = shutil.make_archive(str(tmp_path / "bad"), "zip", src2)
    scratch2 = str(tmp_path / "scratch2")
    main(["ingest", archive2, scratch2])
    with pytest.raises(SystemExit, match="failed check"):
        main(["validate", scratch2])
    out = capsys.readouterr().out
    assert "FAIL" in out and "finite_loads" in out


def test_cli_preflight_flag(tmp_path, capsys):
    """--preflight=off reaches the Solver: a NaN model solves far enough
    to fail later (or not at all for ingest-only paths) instead of being
    gated — here we just assert the flag lands in the RunConfig."""
    import argparse

    from pcg_mpi_solver_tpu.cli import _load_settings

    args = argparse.Namespace(preflight="warn", tol=None, max_iter=None,
                              precision=None, precond=None,
                              telemetry_out=None, trace_resid=None,
                              profile_spans=False, cache_dir=None)
    cfg = _load_settings(None, args)
    assert cfg.preflight == "warn"
