"""Unit coverage for bench.py's resilience machinery — the code that
stands between the round's one driver-captured perf artifact and
infrastructure weather.  Pure logic tests (no solver, no accelerator)."""

import os
from unittest import mock

import pytest

from pcg_mpi_solver_tpu import bench


def _clear_bench_env(monkeypatch):
    for k in list(os.environ):
        if k.startswith("BENCH_") or k.startswith("PCG_TPU_"):
            monkeypatch.delenv(k, raising=False)


def test_ladder_cube_default(monkeypatch):
    _clear_bench_env(monkeypatch)
    assert bench._ladder("cube", False) == [
        (150, 150, 150, 0, 0), (128, 128, 128, 0, 0), (96, 96, 96, 0, 0)]


def test_ladder_explicit_pin_wins(monkeypatch):
    _clear_bench_env(monkeypatch)
    monkeypatch.setenv("BENCH_LADDER", "100,50")
    monkeypatch.setenv("BENCH_NX", "64")
    assert bench._ladder("cube", False) == [(64, 64, 64, 0, 0)]


def test_ladder_tolerates_sloppy_spec(monkeypatch):
    """A trailing comma or spaces must not crash the artifact run."""
    _clear_bench_env(monkeypatch)
    monkeypatch.setenv("BENCH_LADDER", " 100 , 50 , ")
    assert bench._ladder("cube", False) == [
        (100, 100, 100, 0, 0), (50, 50, 50, 0, 0)]
    monkeypatch.setenv("BENCH_LADDER", ",,")
    with pytest.raises(ValueError, match="no sizes"):
        bench._ladder("cube", False)


def test_ladder_octree_pin_beats_ladder(monkeypatch):
    _clear_bench_env(monkeypatch)
    monkeypatch.setenv("BENCH_OT_LADDER", "14,8")
    monkeypatch.setenv("BENCH_OT_N", "10")
    monkeypatch.setenv("BENCH_OT_LEVEL", "3")
    assert bench._ladder("octree", False) == [(0, 0, 0, 10, 3)]


def test_ladder_cpu_fallback_is_small(monkeypatch):
    """CPU fallback must ignore flagship-size envs (a 150^3 CPU solve
    would blow the driver's wall budget — the exact failure the
    fallback exists to avoid)."""
    _clear_bench_env(monkeypatch)
    monkeypatch.setenv("BENCH_NX", "150")
    monkeypatch.setenv("BENCH_NY", "150")
    monkeypatch.setenv("BENCH_NZ", "150")
    assert bench._ladder("cube", True) == [(48, 48, 48, 0, 0)]
    monkeypatch.setenv("BENCH_OT_N", "22")
    assert bench._ladder("octree", True) == [(0, 0, 0, 6, 4)]


def test_matvec_form_pinned_on_stencil_ops(monkeypatch):
    """The form attribute lives on the stencil ops (pinned at their
    construction); the general Ops never carries one — the single rule
    bench reporting and checkpoint fingerprints both read."""
    _clear_bench_env(monkeypatch)
    from pcg_mpi_solver_tpu.ops.matvec import Ops
    from pcg_mpi_solver_tpu.parallel.hybrid import HybridOps
    from pcg_mpi_solver_tpu.parallel.structured import StructuredOps

    import dataclasses

    import pytest as _pytest

    from pcg_mpi_solver_tpu.models import make_cube_model
    from pcg_mpi_solver_tpu.parallel.structured import partition_structured

    base = dict(n_loc=3, n_iface=0)
    sp = partition_structured(make_cube_model(4, 2, 2), 1)
    # env fallback: the knob is resolved at construction...
    monkeypatch.setenv("PCG_TPU_MATVEC_FORM", "corner")
    ops = StructuredOps.from_partition(sp)
    assert ops.form == "corner"
    # ...and pinned: a later env flip does not move it
    monkeypatch.setenv("PCG_TPU_MATVEC_FORM", "gse")
    assert ops.form == "corner"
    # explicit pin beats the env
    assert StructuredOps.from_partition(sp, form="gse").form == "gse"
    assert HybridOps(**base, form="gse").form == "gse"
    # the general Ops never carries a form
    assert getattr(Ops(**base), "form", "n/a") == "n/a"
    # typo'd pins are rejected, not silently run as gse
    with _pytest.raises(ValueError, match="form"):
        StructuredOps.from_partition(sp, form="Corner")
    with _pytest.raises(ValueError, match="form"):
        dataclasses.replace(ops, form="croner")


def test_probe_retry_waits_out_timeouts(monkeypatch):
    """Transient tunnel timeouts are retried across the budget (the r02
    failure mode: one 180s attempt, artifact lost)."""
    _clear_bench_env(monkeypatch)
    monkeypatch.setenv("BENCH_PROBE_BUDGET_S", "10")
    calls = {"n": 0}

    def fake_probe(timeout_s=180.0):
        calls["n"] += 1
        if calls["n"] < 3:
            return False, "backend init did not complete within 180s"
        return True, "ok"

    with mock.patch("pcg_mpi_solver_tpu.utils.backend_probe.probe_backend",
                    fake_probe), \
            mock.patch("time.sleep", lambda s: None):
        ok, detail = bench._probe_with_retry()
    assert ok and calls["n"] == 3


def test_probe_retry_two_strikes_on_deterministic_failure(monkeypatch):
    """A missing/broken plugin must NOT burn the 45-minute budget."""
    _clear_bench_env(monkeypatch)
    monkeypatch.setenv("BENCH_PROBE_BUDGET_S", "10000")
    calls = {"n": 0}

    def fake_probe(timeout_s=180.0):
        calls["n"] += 1
        return False, ("backend init failed (rc=1):\n"
                       "ModuleNotFoundError: No module named 'axon'")

    with mock.patch("pcg_mpi_solver_tpu.utils.backend_probe.probe_backend",
                    fake_probe), \
            mock.patch("time.sleep", lambda s: None):
        ok, _ = bench._probe_with_retry()
    assert not ok and calls["n"] == 2


def test_result_json_marks_unconverged(monkeypatch):
    """time_to_tol_s must be null when the emitted solve has flag != 0."""
    import json
    import types

    model = types.SimpleNamespace(n_dof=1000)
    r1 = types.SimpleNamespace(flag=1, relres=1e-3, wall_s=2.0)
    line = bench._result_json(model, "cube", r1, 50, 235.0, "note", {})
    d = json.loads(line)
    assert d["detail"]["time_to_tol_s"] is None
    assert d["detail"]["solve_wall_s"] == 2.0
    r0 = types.SimpleNamespace(flag=0, relres=1e-8, wall_s=2.0)
    d0 = json.loads(bench._result_json(model, "cube", r0, 50, 235.0, "n", {}))
    assert d0["detail"]["time_to_tol_s"] == 2.0


def test_settle_compile_mechanics(monkeypatch):
    """settle_compile subprocess plumbing: success and failure paths.

    Uses stub executables instead of a real jax probe — on the bench
    host a real probe subprocess first-touches the tunneled TPU backend
    (JAX_PLATFORMS=cpu alone does NOT stop axon backend init; only an
    in-process jax.config.update can, see tests/conftest.py) and hangs
    the suite for the full timeout whenever the tunnel is wedged."""
    from pcg_mpi_solver_tpu.utils import backend_probe

    # force the no-live-backend branch: the pytest process has a live
    # (CPU) backend, which would route the probe in-process
    monkeypatch.setattr(backend_probe, "backend_live", lambda: False)
    monkeypatch.setattr(backend_probe.sys, "executable", "/bin/true")
    ok, detail = backend_probe.settle_compile(max_attempts=1)
    assert ok and "attempt 1" in detail, detail

    monkeypatch.setattr(backend_probe.sys, "executable", "/bin/false")
    ok, detail = backend_probe.settle_compile(max_attempts=1)
    assert not ok and "rc=1" in detail, detail


def test_settle_compile_live_backend_in_process():
    """With a live in-process backend (this pytest process, CPU-pinned)
    the probe must compile in-process — no subprocess that would contend
    with an exclusive device grant — and succeed on attempt 1."""
    from pcg_mpi_solver_tpu.utils import backend_probe

    assert backend_probe.backend_live()
    ok, detail = backend_probe.settle_compile(max_attempts=1, timeout_s=120)
    assert ok and "attempt 1" in detail, detail


def test_ladder_provisional_is_tiny_and_env_proof(monkeypatch):
    """The provisional rung must ignore flagship envs (its whole job is
    landing a line in minutes) and honor only BENCH_PROV_NX."""
    _clear_bench_env(monkeypatch)
    monkeypatch.setenv("BENCH_NX", "150")
    monkeypatch.setenv("BENCH_LADDER", "150,128")
    assert bench._ladder("cube", True, provisional=True) == [
        (24, 24, 24, 0, 0)]
    # even for an octree bench request the provisional stays a cube
    # ladder shape (main() forces BENCH_MODEL=cube for the subprocess)
    monkeypatch.setenv("BENCH_PROV_NX", "16")
    assert bench._ladder("cube", True, provisional=True) == [
        (16, 16, 16, 0, 0)]


def test_emitter_exactly_once(capsys):
    """Watchdog and main flow race to emit; exactly one line may win."""
    em = bench._Emitter("initial")
    em.offer("better")
    assert em.emit() is True          # prints the best offered line
    assert em.emit("late") is False   # second emit is refused
    out = capsys.readouterr().out
    assert out == "better\n"


def test_emitter_offer_after_emit_is_noop(capsys):
    em = bench._Emitter("a")
    assert em.emit("final")
    em.offer("late-offer")
    assert em.best == "a"          # a late offer must not mutate state
    assert capsys.readouterr().out == "final\n"


def test_emitter_rank_priority(capsys):
    """A provisional (rank 1) offer must never displace an accelerator
    (rank 2) line — the watchdog races the live-baseline upgrade and the
    TPU measurement has to win (r04 review finding)."""
    em = bench._Emitter("sentinel")
    em.offer("tpu-line", rank=2)
    em.offer("provisional", rank=1)   # late watchdog offer
    assert em.emit() is True
    assert capsys.readouterr().out == "tpu-line\n"
    # equal rank upgrades in place (measured-live replaces const)
    em2 = bench._Emitter("sentinel")
    em2.offer("const", rank=2)
    em2.offer("live", rank=2)
    assert em2.best == "live"


def test_error_line_is_parseable_sentinel():
    import json

    d = json.loads(bench._error_line("boom"))
    assert d["value"] == 0.0 and d["vs_baseline"] == 0.0
    assert "boom" in d["detail"]["error"]
    assert d["metric"] == "pcg_dof_iterations_per_second"


def test_sweep_stale_tmps(tmp_path):
    """Orphaned .tmp files older than an hour are removed on the read
    path; fresh ones (a concurrent writer) are left alone."""
    import os
    import time

    d = str(tmp_path)
    old = os.path.join(d, "model_dead.tmp")
    fresh = os.path.join(d, "model_live.tmp")
    for p in (old, fresh):
        with open(p, "wb") as f:
            f.write(b"x")
    os.utime(old, (time.time() - 7200,) * 2)
    bench._sweep_stale_tmps(d)
    assert sorted(os.listdir(d)) == ["model_live.tmp"]


class _FakeProv:
    def __init__(self, line='{"metric": "m", "value": 1.0}'):
        self._line = line
        self.killed = False

    def line(self, timeout_s=0.0):
        return self._line

    def kill(self):
        self.killed = True


_OFF = object()     # sentinel: no CPU-upgrade leg in this scenario


def _orchestrate(monkeypatch, capsys, probe_ok, run_result, tmp_path,
                 prov_line='{"metric": "m", "value": 1.0}', upgrade=_OFF):
    """Drive bench.main()'s orchestrator with the heavy pieces mocked.
    ``upgrade``: omitted disables the CPU-upgrade leg; otherwise the
    line (or None) the mocked upgrade subprocess yields."""
    _clear_bench_env(monkeypatch)
    monkeypatch.chdir(tmp_path)      # bench writes provisional files in cwd
    monkeypatch.setenv("BENCH_WALL_BUDGET_S", "3600")
    if upgrade is _OFF:
        monkeypatch.setenv("BENCH_CPU_UPGRADE", "0")
    prov = _FakeProv(prov_line)

    def fake_runs(env_extra=None, logname=None, provisional=True):
        return prov if provisional else _FakeProv(upgrade)

    monkeypatch.setattr(bench, "_ProvisionalRun", fake_runs)
    monkeypatch.setattr(bench, "_probe_with_retry",
                        lambda budget_s=None: (probe_ok, "mock"))
    if isinstance(run_result, Exception):
        def run(**kw):
            raise run_result
    else:
        def run(**kw):
            return run_result
    monkeypatch.setattr(
        bench, "_run_bench",
        lambda cpu_fallback, provisional=False, deadline=None, emitter=None:
        run())
    bench.main()
    return prov, capsys.readouterr().out.strip().splitlines()


def test_orchestrator_tpu_success(monkeypatch, capsys, tmp_path):
    """Probe ok + accelerator bench succeeds: ITS line is the one line on
    stdout; the provisional subprocess is reaped."""
    prov, out = _orchestrate(monkeypatch, capsys, True, '{"tpu": 1}',
                             tmp_path)
    assert out == ['{"tpu": 1}']
    assert prov.killed


def test_orchestrator_probe_dead_emits_provisional(monkeypatch, capsys,
                                                   tmp_path):
    prov, out = _orchestrate(monkeypatch, capsys, False, '{"tpu": 1}',
                             tmp_path)
    assert out == ['{"metric": "m", "value": 1.0}']


def test_orchestrator_bench_crash_emits_provisional(monkeypatch, capsys,
                                                    tmp_path):
    """Accelerator path dies AFTER a good probe (tunnel death mid-solve):
    the provisional line still lands, exit stays clean."""
    prov, out = _orchestrate(monkeypatch, capsys, True,
                             RuntimeError("tunnel died"), tmp_path)
    assert out == ['{"metric": "m", "value": 1.0}']


def test_orchestrator_everything_dead_emits_sentinel(monkeypatch, capsys,
                                                     tmp_path):
    """No provisional AND no accelerator: the labeled zero-value sentinel
    is still exactly one parseable line."""
    import json

    prov, out = _orchestrate(monkeypatch, capsys, False,
                             '{"tpu": 1}', tmp_path, prov_line=None)
    assert len(out) == 1
    d = json.loads(out[0])
    assert d["value"] == 0.0 and "error" in d["detail"]


def _tpu_line(v=20.0, value=7e8):
    import json

    return json.dumps({"metric": "pcg_dof_iterations_per_second",
                       "value": value, "unit": "dof*iter/s",
                       "vs_baseline": v,
                       "detail": {"platform": "tpu", "n_dof": 10328853}})


def test_salvage_roundtrip_and_relabeling(monkeypatch, tmp_path):
    """A live accelerator line written by one invocation is readable by a
    later one, re-labeled so it cannot pass as a live measurement; the
    best (by vs_baseline) fresh entry wins."""
    import json

    _clear_bench_env(monkeypatch)
    monkeypatch.chdir(tmp_path)
    bench._write_salvage(_tpu_line(v=5.0))
    bench._write_salvage(_tpu_line(v=21.9))
    bench._write_salvage(_tpu_line(v=12.0))
    got = json.loads(bench._read_salvage())
    assert got["vs_baseline"] == 21.9
    det = got["detail"]
    assert det["salvaged_from_earlier_session"] is True
    assert det["salvage_age_s"] >= 0 and "not measured live" \
        in det["salvage_note"]


def test_salvage_rejects_cpu_and_stale_lines(monkeypatch, tmp_path):
    """CPU fallback/provisional lines never enter the salvage file; aged
    entries and a disabled knob read as absent."""
    import json
    import time

    _clear_bench_env(monkeypatch)
    monkeypatch.chdir(tmp_path)
    cpu = json.dumps({"metric": "m", "value": 4e7, "vs_baseline": 1.18,
                      "detail": {"platform": "cpu (CPU PROVISIONAL)"}})
    bench._write_salvage(cpu)
    assert not (tmp_path / "bench_salvage.json").exists()
    bench._write_salvage(_tpu_line())
    assert bench._read_salvage() is not None
    monkeypatch.setenv("BENCH_SALVAGE", "0")     # hardware-queue posture
    assert bench._read_salvage() is None
    monkeypatch.delenv("BENCH_SALVAGE")
    # age out: rewrite the file with an old timestamp
    p = tmp_path / "bench_salvage.json"
    data = json.loads(p.read_text())
    data["lines"][0]["unix_time"] = time.time() - 100000
    p.write_text(json.dumps(data))
    assert bench._read_salvage() is None


def test_salvage_prefers_matching_config(monkeypatch, tmp_path):
    """A config-matching entry beats a higher-vs_baseline entry from a
    different benchmark config; with no match the best any-config line
    still salvages (self-describing beats CPU)."""
    import json

    _clear_bench_env(monkeypatch)
    monkeypatch.chdir(tmp_path)
    cube = json.dumps({"metric": "m", "value": 5e8, "vs_baseline": 10.0,
                       "detail": {"platform": "tpu", "model": "cube",
                                  "mode": "mixed", "dtype": "float32"}})
    octree = json.dumps({"metric": "m", "value": 7e8, "vs_baseline": 21.0,
                         "detail": {"platform": "tpu", "model": "octree",
                                    "mode": "mixed", "dtype": "float32"}})
    bench._write_salvage(cube)
    bench._write_salvage(octree)
    assert json.loads(bench._read_salvage())["vs_baseline"] == 10.0
    monkeypatch.setenv("BENCH_MODEL", "octree")
    assert json.loads(bench._read_salvage())["vs_baseline"] == 21.0
    monkeypatch.setenv("BENCH_MODEL", "sphere")    # no match at all
    assert json.loads(bench._read_salvage())["vs_baseline"] == 21.0


def test_orchestrator_probe_dead_salvage_beats_cpu(monkeypatch, capsys,
                                                   tmp_path):
    """Dead tunnel + a fresh salvage line: the salvaged TPU number is the
    round artifact (clearly re-labeled), not the CPU provisional, and the
    CPU upgrade leg is skipped entirely."""
    import json

    monkeypatch.chdir(tmp_path)
    bench._write_salvage(_tpu_line(v=21.9))
    prov, out = _orchestrate(monkeypatch, capsys, False, '{"tpu": 1}',
                             tmp_path, upgrade='{"metric": "up"}')
    d = json.loads(out[0])
    assert d["vs_baseline"] == 21.9
    assert d["detail"]["salvaged_from_earlier_session"] is True


def test_orchestrator_probe_dead_upgrade_beats_provisional(
        monkeypatch, capsys, tmp_path):
    """Dead tunnel, no salvage: the mid-size CPU upgrade line outranks
    the tiny provisional (VERDICT r04 weak #1)."""
    prov, out = _orchestrate(
        monkeypatch, capsys, False, '{"tpu": 1}', tmp_path,
        upgrade='{"metric": "upgraded", "value": 5.0}')
    assert out == ['{"metric": "upgraded", "value": 5.0}']


def test_orchestrator_probe_dead_upgrade_fails_keeps_provisional(
        monkeypatch, capsys, tmp_path):
    """Upgrade subprocess dies without a line: the provisional still
    lands (the liveness floor never regresses)."""
    prov, out = _orchestrate(monkeypatch, capsys, False, '{"tpu": 1}',
                             tmp_path, upgrade=None)
    assert out == ['{"metric": "m", "value": 1.0}']


def test_orchestrator_success_writes_salvage(monkeypatch, capsys,
                                             tmp_path):
    """A successful accelerator run records its line for later
    invocations; CPU-labeled lines are never recorded."""
    import json

    line = _tpu_line(v=20.5)
    prov, out = _orchestrate(monkeypatch, capsys, True, line, tmp_path)
    assert out == [line]
    data = json.loads((tmp_path / "bench_salvage.json").read_text())
    assert json.loads(data["lines"][0]["line"])["vs_baseline"] == 20.5


def test_model_cache_eviction(tmp_path):
    """LRU eviction keeps the cache under the cap, never deletes the
    just-written entry, and evicts oldest-mtime first."""
    import os
    import time

    from pcg_mpi_solver_tpu.bench import _evict_model_cache

    d = str(tmp_path)
    for i, sz in enumerate([100, 200, 300]):
        p = os.path.join(d, f"model_{i}.pkl")
        with open(p, "wb") as f:
            f.write(b"x" * sz)
        os.utime(p, (time.time() - 100 + i,) * 2)
    keep = os.path.join(d, "model_2.pkl")
    _evict_model_cache(d, keep=keep, cap_bytes=550)
    assert sorted(os.listdir(d)) == ["model_1.pkl", "model_2.pkl"]
    _evict_model_cache(d, keep=keep, cap_bytes=50)
    assert sorted(os.listdir(d)) == ["model_2.pkl"]


def _live_line(value=1.0):
    import json

    return json.dumps({"metric": "m", "value": value, "unit": "u",
                       "vs_baseline": 1.0,
                       "detail": {"platform": "tpu"}})


def test_offer_rank4_persists_salvage_immediately(monkeypatch, tmp_path):
    """A LIVE accelerator line must hit bench_salvage.json the moment it
    is offered: on 2026-08-01 the watchdog's os._exit(0) fired 2 s
    before the flagship step ended, emitting the TPU line to stdout but
    racing out main's end-of-run _write_salvage."""
    import json

    from pcg_mpi_solver_tpu import bench as b

    monkeypatch.chdir(tmp_path)
    em = b._Emitter("init")
    em.offer(_live_line(), rank=4)
    data = json.load(open(b._SALVAGE_PATH))
    assert len(data["lines"]) == 1


def test_emit_persists_salvage_and_dedups(monkeypatch, tmp_path):
    import json

    from pcg_mpi_solver_tpu import bench as b

    monkeypatch.chdir(tmp_path)
    em = b._Emitter("init")
    ln = _live_line(2.0)
    em.offer(ln, rank=4)        # first write
    assert em.emit(ln) is True  # emit-side write must dedup, not append
    data = json.load(open(b._SALVAGE_PATH))
    assert len(data["lines"]) == 1
    # a CPU-labeled line must never be persisted
    em2 = b._Emitter("init")
    em2.emit(json.dumps({"metric": "m", "value": 1.0, "unit": "u",
                         "vs_baseline": 0.1,
                         "detail": {"platform": "cpu (fallback)"}}))
    data = json.load(open(b._SALVAGE_PATH))
    assert len(data["lines"]) == 1


def test_warm_solve_offers_rank4_line(monkeypatch, tmp_path):
    """A converged warm solve on an accelerator must offer (and thus
    persist) a rank-4 line BEFORE the timed solve runs: on 2026-08-01
    the device died mid-timed-solve two minutes after a completed warm
    solve and the round artifact fell back to a CPU provisional."""
    import json

    from pcg_mpi_solver_tpu import bench as b

    monkeypatch.chdir(tmp_path)
    monkeypatch.setattr(b, "_accel_platform", lambda: "tpu")
    offers = []

    class Em:
        def offer(self, line, rank=1):
            offers.append((rank, line))

    b._solve_once("cube", 4, 4, 4, 0, 0, "auto", 8, 1e-7,
                  "mixed", "float32", emitter=Em())
    warm = [ln for r, ln in offers if r == 4]
    assert warm, "no rank-4 warm line offered"
    d = json.loads(warm[0])
    assert d["detail"]["flag"] == 0
    assert d["detail"]["timing"].startswith("warm")
    assert d["detail"]["platform"] == "tpu"
    # offer(rank=4) persists to salvage via the emitter-independent path
    # only when called through _Emitter; the fake Em does not — persist
    # here happens when bench's real _Emitter is used (covered by
    # test_offer_rank4_persists_salvage_immediately)


def test_failed_timed_solve_offers_salvage_line(monkeypatch, tmp_path):
    """A solver exception mid-measurement (the r05 device death) writes
    a salvage line carrying failed=true + the reason at accelerator
    rank, so the round artifact records both the warm number and WHY the
    timed leg is missing — instead of aborting with nothing."""
    import json
    import types

    from pcg_mpi_solver_tpu import bench as b

    monkeypatch.chdir(tmp_path)
    offers = []

    class Em:
        def offer(self, line, rank=1):
            offers.append((rank, line))

    model = types.SimpleNamespace(n_dof=10_328_853)
    r0 = types.SimpleNamespace(flag=0, relres=3.2e-8, wall_s=83.3,
                               iters=3334)
    extra = {"platform": "tpu", "mode": "mixed", "dtype": "float32"}
    line = b._offer_failed_salvage(Em(), model, "cube", r0, dict(extra),
                                   "timed solve died: XlaRuntimeError: "
                                   "UNAVAILABLE: socket closed")
    assert offers and offers[0][0] == 4
    d = json.loads(line)
    assert d["detail"]["failed"] is True
    assert "UNAVAILABLE" in d["detail"]["fail_reason"]
    assert d["detail"]["timing"].startswith("warm")
    assert d["value"] > 0
    # schema stays valid with the extra failure fields
    from pcg_mpi_solver_tpu.obs.schema import validate_bench_line

    assert validate_bench_line(d) == []

    # no emitter / unconverged warm solve / CPU platform: nothing offered
    assert b._offer_failed_salvage(None, model, "cube", r0, extra, "x") \
        is None
    bad = types.SimpleNamespace(flag=1, relres=1.0, wall_s=1.0, iters=5)
    assert b._offer_failed_salvage(Em(), model, "cube", bad, extra, "x") \
        is None
    cpu = dict(extra, platform="cpu (CPU FALLBACK)")
    assert b._offer_failed_salvage(Em(), model, "cube", r0, cpu, "x") \
        is None


def test_salvage_trims_by_value_not_recency(monkeypatch, tmp_path):
    """Write pressure from warm/const/final lines across a live wave must
    never evict the highest-vs_baseline entry (the line the round-end
    driver's salvage fallback exists to re-emit)."""
    import json

    from pcg_mpi_solver_tpu import bench as b

    monkeypatch.chdir(tmp_path)

    def line(v):
        return json.dumps({"metric": "m", "value": v * 1e6, "unit": "u",
                           "vs_baseline": v,
                           "detail": {"platform": "tpu", "tag": v}})

    b._write_salvage(line(21.9))            # the flagship line
    for v in [1.1, 1.2, 1.3, 1.4, 1.5, 1.6, 1.7, 1.8, 1.9, 2.0, 2.1]:
        b._write_salvage(line(v))
    data = json.load(open(b._SALVAGE_PATH))
    vs = [json.loads(e["line"])["vs_baseline"] for e in data["lines"]]
    assert len(vs) <= 8
    assert 21.9 in vs, f"flagship line evicted: {vs}"


def test_salvage_evicts_age_expired_before_value_trim(monkeypatch,
                                                      tmp_path):
    """ADVICE r05 #2: entries older than BENCH_SALVAGE_MAX_AGE_S are
    unusable by _read_salvage, so they must be evicted FIRST — a stale
    high-vs_baseline line must never permanently occupy a slot a fresh
    (usable) lower-value line needs."""
    import json
    import time

    from pcg_mpi_solver_tpu import bench as b

    monkeypatch.chdir(tmp_path)

    def line(v, tag):
        return json.dumps({"metric": "m", "value": v * 1e6, "unit": "u",
                           "vs_baseline": v,
                           "detail": {"platform": "tpu", "tag": tag}})

    # fill every slot with stale, unbeatably-high-value entries
    for i in range(8):
        b._write_salvage(line(100.0 + i, f"stale{i}"))
    data = json.load(open(b._SALVAGE_PATH))
    now = time.time()
    for e in data["lines"]:
        e["unix_time"] = now - 2 * 43200        # 2x the default max age
    with open(b._SALVAGE_PATH, "w") as f:
        json.dump(data, f)

    # a fresh, modest line must displace them all (they can never be
    # read again), not lose the value-based trim to them
    b._write_salvage(line(1.5, "fresh"))
    data = json.load(open(b._SALVAGE_PATH))
    tags = [json.loads(e["line"])["detail"]["tag"] for e in data["lines"]]
    assert tags == ["fresh"], tags
    got = json.loads(b._read_salvage())
    assert got["detail"]["tag"] == "fresh"


def test_emitter_explicit_line_persists_even_after_watchdog_emit(
        monkeypatch, tmp_path):
    """ADVICE r05 #1: when the watchdog emitted first (done=True), main's
    fresh measured-live emit(line) must STILL persist the line to the
    salvage file — the done check only suppresses the duplicate stdout
    print, never the persist."""
    import json

    from pcg_mpi_solver_tpu import bench as b

    monkeypatch.chdir(tmp_path)
    em = b._Emitter("init")
    assert em.emit() is True                # the watchdog won the race
    fresh = _live_line(3.0)
    assert em.emit(fresh) is False          # stdout stays single-line...
    data = json.load(open(b._SALVAGE_PATH))
    assert [e["line"] for e in data["lines"]] == [fresh]  # ...persisted
