"""Multi-tenant solve service (serve/, ISSUE 19): admission control,
backpressure, per-job fault isolation, and crash-durable exactly-once
execution.

The headline contracts:

* every admission-decision outcome — accept, reject, shed — produces a
  NAMED reason: a schema-versioned telemetry event, a journal record
  and a result file the submitter can read (never a silent drop);
* a poisoned tenant's RHS quarantines ALONE while its co-batched
  tenants finish with solutions bit-identical to the unpacked
  single-RHS reference (the PR 8 isolation promise at service scope);
* SIGKILLing the daemon mid-block and restarting over the same spool
  loses no job and solves none twice (results are written BEFORE the
  terminal journal record; replay completes from whichever survived);
* the ``@job:`` fault domain fires by absolute admission ordinal and a
  consumed fault never re-fires across a restart.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from pcg_mpi_solver_tpu.config import (RunConfig, SolverConfig,
                                       TimeHistoryConfig)
from pcg_mpi_solver_tpu.models.synthetic import make_cube_model
from pcg_mpi_solver_tpu.obs.metrics import MetricsRecorder
from pcg_mpi_solver_tpu.obs.schema import validate_bench_line, validate_event
from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
from pcg_mpi_solver_tpu.resilience import FaultPlan
from pcg_mpi_solver_tpu.resilience.faultinject import InjectedDispatchError
from pcg_mpi_solver_tpu.serve import jobs as sjobs
from pcg_mpi_solver_tpu.serve.admission import (
    REJECT_DEADLINE, REJECT_DRAINING, REJECT_QUEUE_FULL,
    SHED_PAST_DEADLINE, AdmissionController, price_admission)
from pcg_mpi_solver_tpu.serve.daemon import ServeDaemon
from pcg_mpi_solver_tpu.serve.journal import (
    JobJournal, TERMINAL_OPS, next_ordinal, read_journal, replay_jobs)
from pcg_mpi_solver_tpu.serve.packer import (
    normalize_widths, pack_block, pick_width)
from pcg_mpi_solver_tpu.solver.driver import Solver


class _Cap:
    """Metrics sink collecting events for assertions."""

    def __init__(self):
        self.events = []

    def emit(self, ev):
        self.events.append(ev)

    def close(self):
        pass

    def kinds(self, kind):
        return [e for e in self.events if e.get("kind") == kind]


class _StubJournal:
    """Records journal (op, job, fields) tuples without touching disk."""

    def __init__(self):
        self.records = []

    def record(self, op, job=None, **fields):
        self.records.append((op, job, fields))


def _cfg():
    return RunConfig(
        solver=SolverConfig(tol=1e-8, max_iter=2000,
                            precision_mode="direct",
                            iters_per_dispatch=-1, pcg_variant="classic"),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]),
    )


@pytest.fixture(scope="module")
def solver():
    """One warm solver shared by the service tests — model parameters
    match ``pcg-tpu serve --synthetic 4,3,3`` so the chaos test's
    restarted generation serves the same operator the killed CLI
    daemon did."""
    model = make_cube_model(4, 3, 3, E=30e9, nu=0.2, load="traction",
                            load_value=1e6, heterogeneous=True)
    return Solver(model, _cfg(), mesh=make_mesh(2), n_parts=2,
                  backend="general")


@pytest.fixture
def cap(solver):
    c = _Cap()
    solver.recorder.add_sink(c)
    yield c
    solver.recorder.remove_sink(c)


def _terminal_counts(journal_file):
    """{job: number of terminal journal records} over the whole journal
    — the exactly-once audit (every value must be exactly 1)."""
    events, _ = read_journal(journal_file)
    counts = {}
    for ev in events:
        if ev.get("op") in TERMINAL_OPS and isinstance(ev.get("job"), str):
            counts[ev["job"]] = counts.get(ev["job"], 0) + 1
    return counts


# ----------------------------------------------------------------------
# import-light contract: submission works from a login node
# ----------------------------------------------------------------------

def test_serve_protocol_modules_import_jax_free():
    """jobs/journal/packer/admission are the submission-side protocol —
    ``pcg-tpu submit``/``jobs`` must work from a login node without the
    accelerator environment, so their import graph stays jax-free."""
    code = ("import sys; "
            "import pcg_mpi_solver_tpu.serve.jobs; "
            "import pcg_mpi_solver_tpu.serve.journal; "
            "import pcg_mpi_solver_tpu.serve.packer; "
            "import pcg_mpi_solver_tpu.serve.admission; "
            "sys.exit(1 if 'jax' in sys.modules else 0)")
    # strip the conftest's JAX_PLATFORMS=cpu: the package __init__
    # deliberately imports jax to pin the backend when that env is set
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    r = subprocess.run([sys.executable, "-c", code],
                       cwd=os.path.dirname(os.path.dirname(
                           os.path.abspath(__file__))),
                       capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stderr


# ----------------------------------------------------------------------
# packer: standard widths, FIFO-by-ordinal packing
# ----------------------------------------------------------------------

def test_packer_widths_and_fifo_packing():
    assert normalize_widths((8, 2, 2)) == (1, 2, 8)
    assert normalize_widths(()) == (1,)          # 1 is always forced in
    assert normalize_widths((0, -3, 4)) == (1, 4)
    assert pick_width(0) == 0
    assert pick_width(1) == 1
    assert pick_width(3, (1, 2, 4)) == 2          # largest fit, not 4
    assert pick_width(100, (1, 2, 4, 8)) == 8
    queue = [{"job": f"j{o}", "ordinal": o} for o in (2, 0, 1)]
    block = pack_block(queue, (1, 2))
    assert [e["ordinal"] for e in block] == [0, 1]  # oldest first
    assert [e["ordinal"] for e in queue] == [2]     # popped off the queue
    assert pack_block([], (1, 2)) == []


# ----------------------------------------------------------------------
# jobs: spec validation + spool protocol
# ----------------------------------------------------------------------

def test_check_spec_names_every_rejection():
    assert sjobs.check_spec({"job": "a", "scale": 1.0,
                             "deadline_s": 60.0}) is None
    assert sjobs.check_spec({"job": "a", "rhs": "/x.npy"}) is None
    assert "not an object" in sjobs.check_spec([1, 2])
    assert "unknown key" in sjobs.check_spec({"job": "a", "scale": 1.0,
                                              "priority": 9})
    # exactly one of scale / rhs
    assert "exactly one" in sjobs.check_spec({"job": "a"})
    assert "exactly one" in sjobs.check_spec(
        {"job": "a", "scale": 1.0, "rhs": "/x.npy"})
    assert "deadline_s" in sjobs.check_spec(
        {"job": "a", "scale": 1.0, "deadline_s": -5})


def test_submit_and_list_incoming_deterministic_order(tmp_path):
    spool = str(tmp_path / "spool")
    # deliberately out-of-order submit times: the scan must sort by them
    jb = sjobs.submit(spool, {"job": "b", "scale": 2.0}, submit_t=1.0)
    ja = sjobs.submit(spool, {"job": "a", "scale": 1.0}, submit_t=0.0)
    jc = sjobs.submit(spool, {"scale": 3.0}, submit_t=2.0)  # id generated
    assert (ja, jb) == ("a", "b") and len(jc) == 12
    order = [spec["job"] for _, spec in sjobs.list_incoming(spool)]
    assert order == ["a", "b", jc]
    # a bad spec fails AT SUBMIT, not via a result file later
    with pytest.raises(ValueError, match="exactly one"):
        sjobs.submit(spool, {"job": "x"})
    # an unparseable incoming file is surfaced with spec=None, not skipped
    with open(os.path.join(sjobs.incoming_dir(spool), "torn.json"),
              "w") as f:
        f.write('{"job": "to')
    pairs = sjobs.list_incoming(spool)
    assert any(spec is None for _, spec in pairs)


def test_result_roundtrip(tmp_path):
    spool = str(tmp_path / "spool")
    assert sjobs.read_result(spool, "nope") is None
    sjobs.write_result(spool, "j1", {"ok": True, "verdict": "converged"})
    res = sjobs.read_result(spool, "j1")
    assert res["ok"] is True and res["job"] == "j1"


# ----------------------------------------------------------------------
# journal: durable records, replay folding, torn tails
# ----------------------------------------------------------------------

def test_journal_roundtrip_and_replay(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    j = JobJournal(path)
    j.record("admitted", "a", spec={"job": "a", "scale": 1.0},
             ordinal=0, deadline_t=100.0)
    j.record("admitted", "b", spec={"job": "b", "scale": 2.0},
             ordinal=1, deadline_t=200.0)
    j.record("packed", None, block=0, jobs=["a", "b"], width=2)
    j.record("dispatched", None, block=0, jobs=["a", "b"], width=2)
    j.record("done", "a", verdict="converged", block=0)
    j.record("rejected", "c", reason="queue_full")
    j.drain("test")
    j.close()

    events, truncated = read_journal(path)
    assert truncated == 0
    states = replay_jobs(events)
    assert states["a"]["terminal"] and states["a"]["verdict"] == "converged"
    assert states["a"]["ordinal"] == 0
    # b was packed+dispatched but never finished: non-terminal, spec kept
    assert not states["b"]["terminal"]
    assert states["b"]["spec"] == {"job": "b", "scale": 2.0}
    assert states["b"]["deadline_t"] == 200.0
    # c never got an ordinal (rejected at the door) but IS terminal
    assert states["c"]["terminal"] and states["c"]["ordinal"] is None
    # ordinals never reset across restarts
    assert next_ordinal(states) == 2
    assert next_ordinal({}) == 0


def test_journal_tolerates_torn_tail(tmp_path):
    """The exact artifact a SIGKILL leaves: a line cut mid-object is
    skipped and counted, and replay still folds the intact prefix."""
    path = str(tmp_path / "journal.jsonl")
    j = JobJournal(path)
    j.record("admitted", "a", spec={"job": "a", "scale": 1.0},
             ordinal=0, deadline_t=9.0)
    j.close()
    with open(path, "a") as f:
        f.write('{"kind": "flight", "op": "do')   # the kill, mid-write
    events, truncated = read_journal(path)
    assert truncated == 1
    assert replay_jobs(events)["a"]["ordinal"] == 0


# ----------------------------------------------------------------------
# admission: pricing, bounded queue, shedding — all with named reasons
# ----------------------------------------------------------------------

def test_price_admission():
    assert price_admission(None, 1000) is None    # degraded model: open
    assert price_admission(2.0, 500) == pytest.approx(1.0)


def _controller(cap, *, queue_max=2, ms=2.0, expected_iters=500,
                on_shed=None):
    rec = MetricsRecorder(sinks=[cap])
    jn = _StubJournal()
    ctl = AdmissionController(queue_max, pricer=lambda nrhs: ms,
                             journal=jn, recorder=rec,
                             expected_iters=expected_iters,
                             price_width=4, on_shed=on_shed)
    return ctl, jn


def test_admission_prices_and_rejects_infeasible_deadlines(cap):
    ctl, jn = _controller(cap)                    # predicted_s = 1.0
    verdict, reason = ctl.admit({"job": "slow", "scale": 1.0,
                                 "deadline_s": 0.5}, now=100.0)
    assert (verdict, reason) == ("rejected", REJECT_DEADLINE)
    verdict, entry = ctl.admit({"job": "ok", "scale": 1.0,
                                "deadline_s": 10.0}, now=100.0)
    assert verdict == "admitted" and entry["ordinal"] == 0
    assert entry["deadline_t"] == pytest.approx(110.0)
    # every decision journaled + evented, with the named reason
    assert [r[0] for r in jn.records] == ["rejected", "admitted"]
    (rej,) = cap.kinds("job_reject")
    assert rej["reason"] == REJECT_DEADLINE
    (adm,) = cap.kinds("job_admit")
    assert adm["ordinal"] == 0 and adm["predicted_s"] == pytest.approx(1.0)
    assert validate_event(rej) == [] and validate_event(adm) == []


def test_admission_degrades_open_without_a_cost_model(cap):
    ctl, _ = _controller(cap, ms=None)
    verdict, entry = ctl.admit({"job": "a", "scale": 1.0,
                                "deadline_s": 1e-9}, now=0.0)
    assert verdict == "admitted"                   # pricing never gates
    assert cap.kinds("job_admit")[0]["predicted_s"] is None


def test_admission_backpressure_sheds_then_rejects_full(cap):
    shed_hook = []
    ctl, jn = _controller(cap, queue_max=2,
                          on_shed=lambda e, r: shed_hook.append((e, r)))
    for i in range(2):
        v, _ = ctl.admit({"job": f"j{i}", "scale": 1.0,
                          "deadline_s": 5.0}, now=0.0)
        assert v == "admitted"
    # queue full, nothing past deadline yet -> the arrival is rejected
    v, reason = ctl.admit({"job": "j2", "scale": 1.0, "deadline_s": 50.0},
                          now=1.0)
    assert (v, reason) == ("rejected", REJECT_QUEUE_FULL)
    # later, the queued jobs' deadlines have passed: shed oldest first,
    # then the arrival fits
    v, entry = ctl.admit({"job": "j3", "scale": 1.0, "deadline_s": 50.0},
                         now=100.0)
    assert v == "admitted" and ctl.shed_count == 2
    assert [e["job"] for e, _ in shed_hook] == ["j0", "j1"]
    assert all(r == SHED_PAST_DEADLINE for _, r in shed_hook)
    sheds = cap.kinds("job_shed")
    assert [e["job"] for e in sheds] == ["j0", "j1"]
    assert all(validate_event(e) == [] for e in sheds)
    assert [r[0] for r in jn.records].count("shed") == 2
    # ordinals keep counting past shed jobs (absolute, never reused)
    assert entry["ordinal"] == 2


def test_admission_rejects_while_draining_and_requeue_keeps_ordinals(cap):
    ctl, jn = _controller(cap)
    ctl.requeue({"job": "old", "spec": {"job": "old", "scale": 1.0},
                 "ordinal": 7, "deadline_t": 50.0, "admit_t": 0.0})
    # replay re-enqueue: no second admitted record, numbering continues
    assert jn.records == [] and ctl._next_ordinal == 8
    v, entry = ctl.admit({"job": "new", "scale": 1.0, "deadline_s": 99.0},
                         now=0.0)
    assert v == "admitted" and entry["ordinal"] == 8
    ctl.draining = True
    v, reason = ctl.admit({"job": "late", "scale": 1.0,
                           "deadline_s": 99.0}, now=0.0)
    assert (v, reason) == ("rejected", REJECT_DRAINING)


# ----------------------------------------------------------------------
# @job: fault domain — absolute ordinals, replay pre-consumption
# ----------------------------------------------------------------------

def test_job_fault_domain_fires_by_absolute_ordinal(monkeypatch):
    monkeypatch.setenv("PCG_TPU_FAULT_SLEEP_S", "0.0")
    plan = FaultPlan("sleep@job:0,nan@job:2,exc@job:1")
    assert plan.job_armed
    assert plan.at_job(0) is None                  # sleep only delays
    assert plan.at_job(2) == "nan"                 # caller poisons col
    with pytest.raises(InjectedDispatchError, match="ordinal 1"):
        plan.at_job(1)
    # single-use: consumed faults never fire twice in one lifetime
    assert plan.at_job(1) is None and plan.at_job(2) is None
    assert [f["mode"] for f in plan.fired] == ["sleep", "nan", "exc"]
    assert not plan.job_armed                      # all consumed
    assert FaultPlan("").job_armed is False


def test_job_fault_replay_consume_never_refires():
    """A restarted daemon re-parses PCG_TPU_FAULTS into a fresh plan;
    replay pre-consumes ordinals the journal shows already passed the
    service boundary, so the fault fires at most once per journal."""
    plan = FaultPlan("exc@job:3")
    plan.replay_consume_job(3)
    assert plan.at_job(3) is None and plan.fired == []


def test_job_fault_spec_parse_errors():
    with pytest.raises(ValueError):
        FaultPlan("kill@job:0")                    # kill is not a job mode


# ----------------------------------------------------------------------
# schema: the new event kinds and bench detail fields
# ----------------------------------------------------------------------

def test_serve_event_kinds_are_schema_versioned():
    cap = _Cap()
    rec = MetricsRecorder(sinks=[cap])
    rec.event("job_admit", job="a", ordinal=0, predicted_s=0.1,
              deadline_s=60.0)
    rec.event("job_reject", job="b", reason=REJECT_QUEUE_FULL)
    rec.event("job_shed", job="c", reason=SHED_PAST_DEADLINE)
    rec.event("job_done", job="a", ok=True, verdict="converged")
    rec.event("job_quarantine", job="d", verdict="rhs_nonfinite")
    rec.event("serve_drain", reason="idle")
    assert all(validate_event(e) == [] for e in cap.events)
    # a job_done missing its verdict is a schema error, not a pass
    bad = dict(cap.events[3])
    del bad["verdict"]
    assert any("verdict" in e for e in validate_event(bad))


def test_serve_bench_detail_fields_numeric_or_null():
    line = {"schema": "pcg-tpu-bench/1", "metric": "serve_jobs_per_s",
            "value": 120.0, "unit": "jobs/s", "vs_baseline": 1.4,
            "detail": {"jobs_per_s": 120.0, "jobs_per_s_serial": 85.0,
                       "queue_depth_max": 12, "jobs_shed": 0}}
    assert validate_bench_line(line) == []
    line["detail"]["jobs_per_s"] = "fast"
    assert any("jobs_per_s" in e for e in validate_bench_line(line))


# ----------------------------------------------------------------------
# daemon end-to-end: fault isolation inside a packed block
# ----------------------------------------------------------------------

def test_daemon_serves_jobs_and_isolates_injected_failure(
        tmp_path, solver, cap):
    """Three tenants, one ``exc@job:1`` service-boundary fault: the
    faulted job fails with a named ``injected:`` verdict, the other
    two finish with solutions bit-identical to the unpacked single-RHS
    reference, and the daemon drains idle."""
    spool = str(tmp_path / "spool")
    scales = {"t0": 1.0, "t1": 0.5, "t2": 2.0}
    for i, (job, sc) in enumerate(sorted(scales.items())):
        sjobs.submit(spool, {"job": job, "scale": sc}, submit_t=float(i))
    d = ServeDaemon(solver, spool, queue_max=8, widths=(1, 2),
                    fault_plan=FaultPlan("exc@job:1"), poll_s=0.001)
    reason = d.run(idle_exit_s=0.0, install_signals=False)
    assert reason == "idle"
    assert (d.jobs_done, d.jobs_failed) == (2, 1)

    results = {j: sjobs.read_result(spool, j) for j in scales}
    assert results["t1"]["ok"] is False
    assert results["t1"]["verdict"].startswith("injected:")
    F = np.asarray(solver._model.F, dtype=np.float64)
    for job in ("t0", "t2"):
        assert results[job]["ok"] and results[job]["verdict"] == "converged"
        ref = solver.solve_many(F * scales[job])
        u_ref = np.asarray(solver.displacement_global_many(ref.x))[:, 0]
        np.testing.assert_array_equal(
            np.load(sjobs.solution_path(spool, job)), u_ref)

    # every outcome evented with a named verdict + the drain stamp
    done = {e["job"]: e for e in cap.kinds("job_done")}
    assert set(done) == set(scales)
    assert all(validate_event(e) == [] for e in done.values())
    (drain,) = cap.kinds("serve_drain")
    assert drain["reason"] == "idle" and validate_event(drain) == []
    # exactly one terminal journal record per job
    assert set(_terminal_counts(sjobs.journal_path(spool)).values()) == {1}


def test_nan_poison_quarantines_alone_in_packed_block(
        tmp_path, solver, cap):
    """``nan@job:0`` poisons the first tenant's RHS column inside a
    width-2 block: it quarantines ALONE (named verdict + event) and the
    co-batched tenant converges bit-identically to its unpacked
    reference — one tenant's poison never fails the block."""
    spool = str(tmp_path / "spool")
    sjobs.submit(spool, {"job": "bad", "scale": 1.0}, submit_t=0.0)
    sjobs.submit(spool, {"job": "good", "scale": 2.0}, submit_t=1.0)
    d = ServeDaemon(solver, spool, queue_max=8, widths=(1, 2),
                    fault_plan=FaultPlan("nan@job:0"), poll_s=0.001)
    d.run(idle_exit_s=0.0, install_signals=False)
    assert (d.jobs_done, d.jobs_failed) == (1, 1)

    bad = sjobs.read_result(spool, "bad")
    assert bad["ok"] is False and bad["verdict"] == "rhs_nonfinite"
    (q,) = cap.kinds("job_quarantine")
    assert q["job"] == "bad" and validate_event(q) == []

    good = sjobs.read_result(spool, "good")
    assert good["ok"] and good["verdict"] == "converged"
    F = np.asarray(solver._model.F, dtype=np.float64)
    ref = solver.solve_many(F * 2.0)
    u_ref = np.asarray(solver.displacement_global_many(ref.x))[:, 0]
    np.testing.assert_array_equal(
        np.load(sjobs.solution_path(spool, "good")), u_ref)


def test_daemon_rejects_bad_specs_and_rhs_failures_by_name(
        tmp_path, solver, cap):
    """Submission-protocol garbage never crashes the daemon: an
    unparseable file, an unknown-key spec and a wrong-length rhs all
    fail THEIR job with a named verdict while valid tenants solve."""
    spool = str(tmp_path / "spool")
    sjobs.ensure_spool(spool)
    inc = sjobs.incoming_dir(spool)
    with open(os.path.join(inc, "torn.json"), "w") as f:
        f.write('{"job": "to')                    # unparseable
    sjobs.write_json_atomic(os.path.join(inc, "oddkey.json"),
                            {"job": "oddkey", "scale": 1.0, "nice": True})
    rhs = tmp_path / "short.npy"
    np.save(rhs, np.ones(3))                      # wrong length for model
    sjobs.submit(spool, {"job": "shortrhs", "rhs": str(rhs)},
                 submit_t=0.0)
    sjobs.submit(spool, {"job": "fine", "scale": 1.0}, submit_t=1.0)

    d = ServeDaemon(solver, spool, queue_max=8, widths=(1, 2),
                    fault_plan=FaultPlan(""), poll_s=0.001)
    d.run(idle_exit_s=0.0, install_signals=False)

    assert sjobs.read_result(spool, "torn")["verdict"].startswith(
        "rejected: bad_spec")
    assert "unknown key" in sjobs.read_result(spool, "oddkey")["verdict"]
    short = sjobs.read_result(spool, "shortrhs")
    assert short["verdict"].startswith("rhs_load_failed:")
    assert sjobs.read_result(spool, "fine")["ok"] is True
    assert not os.listdir(inc)                    # every file consumed
    rejects = cap.kinds("job_reject")
    assert {e["job"] for e in rejects} == {"torn", "oddkey"}
    assert all(validate_event(e) == [] for e in rejects)


# ----------------------------------------------------------------------
# overload: shedding + named rejections at the daemon level
# ----------------------------------------------------------------------

def test_daemon_overload_sheds_with_named_verdicts(tmp_path, solver, cap):
    """Saturate a queue_max=2 daemon, let the queued deadlines lapse,
    and assert backpressure sheds them LOUDLY: journal record, event,
    and a result file the submitter can read — then the infeasible-
    deadline and draining rejections, each by name."""
    spool = str(tmp_path / "spool")
    t0 = 1000.0
    sjobs.submit(spool, {"job": "q0", "scale": 1.0, "deadline_s": 0.5},
                 submit_t=0.0)
    sjobs.submit(spool, {"job": "q1", "scale": 1.0, "deadline_s": 0.5},
                 submit_t=1.0)
    d = ServeDaemon(solver, spool, queue_max=2, widths=(1,),
                    fault_plan=FaultPlan(""), poll_s=0.001)
    assert d.poll_once(now=t0) == 2

    # the full queue + lapsed deadlines: both shed, the arrival admitted
    sjobs.submit(spool, {"job": "q2", "scale": 1.0, "deadline_s": 500.0},
                 submit_t=2.0)
    assert d.poll_once(now=t0 + 50.0) == 1
    assert d.admission.shed_count == 2
    for job in ("q0", "q1"):
        res = sjobs.read_result(spool, job)
        assert res["verdict"] == f"shed: {SHED_PAST_DEADLINE}"
    sheds = cap.kinds("job_shed")
    assert {e["job"] for e in sheds} == {"q0", "q1"}
    assert all(e["reason"] == SHED_PAST_DEADLINE for e in sheds)

    # infeasible deadline: priced at the door (CPU cost model is live)
    assert solver.predicted_ms_per_iter(1) is not None
    sjobs.submit(spool, {"job": "rush", "scale": 1.0, "deadline_s": 1e-9},
                 submit_t=3.0)
    d.poll_once(now=t0 + 51.0)
    assert sjobs.read_result(spool, "rush")["verdict"] == \
        f"rejected: {REJECT_DEADLINE}"

    # draining: new arrivals rejected by name, the queue still finishes
    d.request_drain()
    sjobs.submit(spool, {"job": "late", "scale": 1.0}, submit_t=4.0)
    d.poll_once(now=t0 + 52.0)
    assert sjobs.read_result(spool, "late")["verdict"] == \
        f"rejected: {REJECT_DRAINING}"
    reason = d.run(install_signals=False)
    assert reason == "sigterm"
    assert sjobs.read_result(spool, "q2")["ok"] is True
    # the whole episode: exactly one terminal record per job, none silent
    counts = _terminal_counts(sjobs.journal_path(spool))
    assert set(counts) == {"q0", "q1", "q2", "rush", "late"}
    assert set(counts.values()) == {1}


# ----------------------------------------------------------------------
# exactly-once: in-process crash-window replay
# ----------------------------------------------------------------------

def test_replay_completes_from_result_and_requeues_the_rest(
        tmp_path, solver, cap):
    """The narrowest crash window: the daemon died AFTER writing job
    a's result file but BEFORE its terminal journal record.  Replay
    completes `a` from the result (``replayed=true``) without
    re-solving, re-enqueues `b` with its ORIGINAL ordinal, and drops a
    duplicate re-submission of `a` on the floor."""
    spool = str(tmp_path / "spool")
    sjobs.submit(spool, {"job": "a", "scale": 1.0}, submit_t=0.0)
    sjobs.submit(spool, {"job": "b", "scale": 2.0}, submit_t=1.0)
    d1 = ServeDaemon(solver, spool, queue_max=8, widths=(1,),
                     fault_plan=FaultPlan(""), poll_s=0.001)
    d1.poll_once()
    # simulate the kill: result written, terminal record lost
    sjobs.write_result(spool, "a", {"ok": True, "verdict": "converged"})
    d1.journal._fl.close()                        # no drain, no bracket end

    # the duplicate re-submission a crashed client might retry
    sjobs.submit(spool, {"job": "a", "scale": 1.0}, submit_t=2.0)

    d2 = ServeDaemon(solver, spool, queue_max=8, widths=(1,),
                     fault_plan=FaultPlan(""), poll_s=0.001)
    # `a` completed from its surviving result — never re-queued
    assert d2.jobs_done == 1
    assert [e["job"] for e in d2.admission.queue] == ["b"]
    assert d2.admission.queue[0]["ordinal"] == 1   # original ordinal kept
    done = [e for e in cap.kinds("job_done") if e.get("replayed")]
    assert done and done[0]["job"] == "a"

    reason = d2.run(idle_exit_s=0.0, install_signals=False)
    assert reason == "idle" and d2.jobs_done == 2
    assert sjobs.read_result(spool, "b")["ok"] is True
    counts = _terminal_counts(sjobs.journal_path(spool))
    assert counts == {"a": 1, "b": 1}             # exactly once, each


def test_replay_fails_incomplete_admitted_record_by_name(tmp_path, solver):
    """A journal whose ``admitted`` record lost its spec (torn write)
    cannot re-enqueue that job — replay fails it with a named verdict
    instead of dropping it silently or crashing the daemon."""
    spool = str(tmp_path / "spool")
    sjobs.ensure_spool(spool)
    j = JobJournal(sjobs.journal_path(spool))
    j.record("admitted", "ghost")                 # no spec, no ordinal
    j._fl.close()
    d = ServeDaemon(solver, spool, queue_max=4, widths=(1,),
                    fault_plan=FaultPlan(""), poll_s=0.001)
    assert d.jobs_failed == 1 and d.admission.queue == []
    res = sjobs.read_result(spool, "ghost")
    assert res["verdict"].startswith("replay_unrecoverable")


# ----------------------------------------------------------------------
# chaos: SIGKILL the real daemon mid-block, restart, exactly once
# ----------------------------------------------------------------------

def test_sigkill_mid_block_restart_is_exactly_once(tmp_path, solver):
    """The acceptance chaos leg: a real ``pcg-tpu serve`` process is
    SIGKILLed inside a packed block (held open by ``sleep@job:0``), a
    fresh daemon generation restarts over the same spool, and every
    job finishes EXACTLY once — original ordinals, no re-fired fault,
    solutions matching the unpacked reference."""
    spool = str(tmp_path / "spool")
    sjobs.submit(spool, {"job": "k0", "scale": 1.0}, submit_t=0.0)
    sjobs.submit(spool, {"job": "k1", "scale": 2.0}, submit_t=1.0)

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PCG_TPU_FAULTS"] = "sleep@job:0"         # holds the block open
    env["PCG_TPU_FAULT_SLEEP_S"] = "600"
    proc = subprocess.Popen(
        [sys.executable, "-m", "pcg_mpi_solver_tpu.cli", "serve",
         "--spool", spool, "--synthetic", "4,3,3", "--widths", "1,2",
         "--poll-s", "0.01", "--n-parts", "2"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    journal_file = sjobs.journal_path(spool)
    try:
        deadline = time.monotonic() + 240.0
        packed = False
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                pytest.fail("serve daemon exited before packing: "
                            + (proc.communicate()[0] or "")[-2000:])
            if os.path.exists(journal_file):
                events, _ = read_journal(journal_file)
                if any(ev.get("op") == "packed" for ev in events):
                    packed = True
                    break
            time.sleep(0.2)
        assert packed, "daemon never journaled a packed block"
        os.kill(proc.pid, signal.SIGKILL)         # the chaos
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)

    # the kill left both jobs non-terminal and no results behind
    events, _ = read_journal(journal_file)
    assert not any(ev.get("op") in TERMINAL_OPS for ev in events)
    assert not any(ev.get("op") == "drain" for ev in events)
    assert sjobs.read_result(spool, "k0") is None

    # generation 2: same spool, same fault spec re-parsed — replay must
    # not re-fire ordinal 0's consumed... the journal shows it was never
    # dispatched, so the sleep WOULD re-fire; the restarted operator
    # runs with sleep_s=0 instead, proving restart liveness regardless
    os.environ["PCG_TPU_FAULT_SLEEP_S"] = "0.0"
    try:
        plan2 = FaultPlan("sleep@job:0")
    finally:
        os.environ.pop("PCG_TPU_FAULT_SLEEP_S", None)
    d2 = ServeDaemon(solver, spool, queue_max=8, widths=(1, 2),
                     fault_plan=plan2, poll_s=0.001)
    # replay re-enqueued both with their ORIGINAL ordinals
    assert [e["ordinal"] for e in d2.admission.queue] == [0, 1]
    reason = d2.run(idle_exit_s=0.0, install_signals=False)
    assert reason == "idle" and d2.jobs_done == 2 and d2.jobs_failed == 0

    F = np.asarray(solver._model.F, dtype=np.float64)
    for job, sc in (("k0", 1.0), ("k1", 2.0)):
        res = sjobs.read_result(spool, job)
        assert res["ok"] and res["verdict"] == "converged"
        ref = solver.solve_many(F * sc)
        u_ref = np.asarray(solver.displacement_global_many(ref.x))[:, 0]
        np.testing.assert_array_equal(
            np.load(sjobs.solution_path(spool, job)), u_ref)
    counts = _terminal_counts(journal_file)
    assert counts == {"k0": 1, "k1": 1}           # the exactly-once audit


# ----------------------------------------------------------------------
# watch: the serve journal is a first-class watch target
# ----------------------------------------------------------------------

def test_watch_folds_serve_journal_and_drain_means_done(tmp_path):
    from pcg_mpi_solver_tpu.obs.watch import format_watch, watch_snapshot

    path = str(tmp_path / "journal.jsonl")
    j = JobJournal(path)
    j.record("admitted", "a", spec={"job": "a", "scale": 1.0},
             ordinal=0, deadline_t=9.0)
    j.record("admitted", "b", spec={"job": "b", "scale": 2.0},
             ordinal=1, deadline_t=9.0)
    j.record("packed", None, block=0, jobs=["a", "b"], width=2)
    j.record("done", "a", verdict="converged", block=0)

    snap = watch_snapshot(path)
    srv = snap["serve"]
    assert srv["jobs"] == {"admitted": 2, "packed": 1, "done": 1}
    assert srv["in_flight"] == ["b"]               # a finished, b did not
    assert not srv["drained"]
    text = format_watch(snap)
    assert "serve jobs:" in text and "in-flight jobs: b" in text

    j.record("done", "b", verdict="converged", block=0)
    j.drain("idle", jobs_done=2)
    j.close()
    snap2 = watch_snapshot(path)
    # a gracefully drained journal is DONE — never a stall alarm
    assert snap2["serve"]["drained"] and snap2["status"] == "done"
    assert "serve drained (idle)" in format_watch(snap2)


def test_watch_ignores_non_serve_streams(tmp_path):
    from pcg_mpi_solver_tpu.obs.watch import watch_snapshot

    path = str(tmp_path / "run.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"kind": "step", "t": 0.0, "iter": 3,
                            "relres": 1e-3}) + "\n")
    assert watch_snapshot(path)["serve"] is None
